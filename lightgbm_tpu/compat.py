"""Version-portability shims for JAX APIs that moved between releases.

The training code targets the current public spellings
(``jax.shard_map`` with ``check_vma=``, ``jax.enable_x64``); older
jaxlibs (e.g. the 0.4.x line this container ships) only have the
``jax.experimental`` spellings (``shard_map`` with ``check_rep=``,
``experimental.enable_x64``).  Without the shim every mesh learner and
every f64-accumulating metric died with AttributeError on 0.4.x —
27 of the 30 seed tier-1 failures.

Imports of jax stay inside the functions: importing this module must
not trigger backend registration (bench.py probes backend liveness in
a subprocess BEFORE letting the axon plugin dial the TPU tunnel).
"""

from __future__ import annotations


def shard_map(f, **kwargs):
    """``jax.shard_map`` where available, else the experimental one.

    The replication-check kwarg was renamed ``check_rep`` ->
    ``check_vma`` across versions, on BOTH spellings' APIs (mid-range
    releases expose top-level ``jax.shard_map`` still taking
    ``check_rep``), so the translation is driven by the TypeError, not
    by which import resolved."""
    import jax

    native = getattr(jax, "shard_map", None)
    if native is None:
        from jax.experimental.shard_map import shard_map as native
    try:
        return native(f, **kwargs)
    except TypeError:
        flipped = dict(kwargs)
        if "check_vma" in flipped:
            flipped["check_rep"] = flipped.pop("check_vma")
        elif "check_rep" in flipped:
            flipped["check_vma"] = flipped.pop("check_rep")
        else:
            raise
        return native(f, **flipped)


def enable_x64(enabled: bool = True):
    """``jax.enable_x64`` where available, else the experimental
    context manager."""
    import jax

    try:
        return jax.enable_x64(enabled)
    except AttributeError:
        from jax.experimental import enable_x64 as _e64

        return _e64(enabled)
