"""lightgbm_tpu — a TPU-native gradient-boosted decision tree framework.

A from-scratch rebuild of early LightGBM's capabilities (histogram-based
leaf-wise GBDT/DART, binary/regression/multiclass/LambdaRank, bagging,
feature subsampling, early stopping, model text IO, distributed training)
designed for TPUs: binned uint8 feature matrices in HBM, fused histogram /
split-search kernels under jit, and XLA collectives over a device mesh in
place of socket/MPI allreduce.
"""

__version__ = "0.1.0"

from .config import Config  # noqa: F401
from .io import BinMapper, BinnedDataset, Metadata  # noqa: F401

__all__ = ["Config", "BinMapper", "BinnedDataset", "Metadata", "__version__"]
