"""lightgbm_tpu — a TPU-native gradient-boosted decision tree framework.

A from-scratch rebuild of early LightGBM's capabilities (histogram-based
leaf-wise GBDT/DART, binary/regression/multiclass/LambdaRank, bagging,
feature subsampling, early stopping, model text IO, distributed training)
designed for TPUs: binned uint8 feature matrices in HBM, fused histogram /
split-search kernels under jit, and XLA collectives over a device mesh in
place of socket/MPI allreduce.
"""

__version__ = "0.1.0"


_compile_cache_checked = False


def _enable_persistent_compile_cache() -> None:
    """Default-on persistent XLA compile cache for TPU backends
    (VERDICT r4 item 5): the 10M-row training loop carries ~10 Mosaic
    kernel compiles (~174 s cold on a v5e); caching them makes every
    process after the first start warm.  The reference has zero compile
    cost, so cold-start is pure regression against it.

    Called LAZILY from the first GBDT/Booster construction — by then
    the jax backend is being initialized anyway, so gating on
    ``jax.default_backend() == "tpu"`` neither dials a dead TPU tunnel
    at import nor enables the XLA:CPU cache (whose machine-feature
    keying risks SIGILL replay across heterogeneous hosts).  Opt out
    with ``LGBM_TPU_COMPILE_CACHE=0``; force on anywhere with
    ``LGBM_TPU_COMPILE_CACHE=/path``.  Never a requirement: any failure
    (read-only FS, old jax) leaves compiles uncached."""
    global _compile_cache_checked
    if _compile_cache_checked:
        return
    _compile_cache_checked = True
    import os

    loc = os.environ.get("LGBM_TPU_COMPILE_CACHE", "")
    if loc in ("0", "off", "none"):
        return
    try:
        import jax

        if not loc and jax.default_backend() != "tpu":
            return
        # never override a cache the user already configured (env var
        # or an explicit jax.config.update before importing us)
        if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
            return
        if getattr(jax.config, "jax_compilation_cache_dir", None):
            return
        if not loc:
            loc = os.path.join(
                os.environ.get(
                    "XDG_CACHE_HOME",
                    os.path.join(os.path.expanduser("~"), ".cache")),
                "lightgbm_tpu", "jaxcache")
        os.makedirs(loc, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", loc)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass


from .config import Config  # noqa: F401
from .io import BinMapper, BinnedDataset, Metadata  # noqa: F401
from .basic import Booster, Dataset, LightGBMError  # noqa: F401
from .callback import (  # noqa: F401
    EarlyStopException,
    early_stopping,
    print_evaluation,
    record_evaluation,
    reset_parameter,
)
from .engine import CVBooster, cv, train, train_many  # noqa: F401
from .sklearn import (  # noqa: F401
    LGBMClassifier,
    LGBMModel,
    LGBMRanker,
    LGBMRegressor,
)

__all__ = [
    "Config",
    "BinMapper",
    "BinnedDataset",
    "Metadata",
    "Dataset",
    "Booster",
    "LightGBMError",
    "train",
    "train_many",
    "cv",
    "CVBooster",
    "print_evaluation",
    "record_evaluation",
    "reset_parameter",
    "early_stopping",
    "EarlyStopException",
    "LGBMModel",
    "LGBMRegressor",
    "LGBMClassifier",
    "LGBMRanker",
    "__version__",
]
