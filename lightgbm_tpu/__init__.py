"""lightgbm_tpu — a TPU-native gradient-boosted decision tree framework.

A from-scratch rebuild of early LightGBM's capabilities (histogram-based
leaf-wise GBDT/DART, binary/regression/multiclass/LambdaRank, bagging,
feature subsampling, early stopping, model text IO, distributed training)
designed for TPUs: binned uint8 feature matrices in HBM, fused histogram /
split-search kernels under jit, and XLA collectives over a device mesh in
place of socket/MPI allreduce.
"""

__version__ = "0.1.0"

from .config import Config  # noqa: F401
from .io import BinMapper, BinnedDataset, Metadata  # noqa: F401
from .basic import Booster, Dataset, LightGBMError  # noqa: F401
from .callback import (  # noqa: F401
    EarlyStopException,
    early_stopping,
    print_evaluation,
    record_evaluation,
    reset_parameter,
)
from .engine import CVBooster, cv, train  # noqa: F401
from .sklearn import (  # noqa: F401
    LGBMClassifier,
    LGBMModel,
    LGBMRanker,
    LGBMRegressor,
)

__all__ = [
    "Config",
    "BinMapper",
    "BinnedDataset",
    "Metadata",
    "Dataset",
    "Booster",
    "LightGBMError",
    "train",
    "cv",
    "CVBooster",
    "print_evaluation",
    "record_evaluation",
    "reset_parameter",
    "early_stopping",
    "EarlyStopException",
    "LGBMModel",
    "LGBMRegressor",
    "LGBMClassifier",
    "LGBMRanker",
    "__version__",
]
