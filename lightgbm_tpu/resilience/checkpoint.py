"""Training checkpoints + resume with a BITWISE-identity contract.

A checkpoint captures every per-iteration mutable of a training run —
the grown trees (exact device arrays, not the text round-trip), the
float32 score buffers byte-for-byte, bagging/feature/drop RNG states,
the bagging mask, early-stopping bests, lagged-stop parked values —
so that ``kill at iteration k; resume`` produces a final model file
bitwise-identical to the uninterrupted run (tier-1 contract,
tests/test_resilience.py; chaos proof, tools/chaos.py).

Why exact arrays and not the model string: ``threshold_real`` is the
float32 cast of a float64 bin bound, and recovering the bin from the
cast (models/gbdt.py ``_rebind_tree``) tolerates text-format noise with
an epsilon SMALLER than a float32 ulp — correct for interop, not
guaranteed exact.  The model string still rides along (``model_str``)
as human-readable lineage and an interop escape hatch.

Format: one JSON file per checkpoint (``ckpt_00000010.json`` in
``<output_model>.ckpt/`` by default), arrays as zlib+base64 blobs, a
``sha256`` header over the canonical payload serialization, and a
lineage block (git sha, config fingerprint, previous checkpoint's
digest).  Writes go through :func:`~.atomic.atomic_write` — a
preemption mid-checkpoint leaves the previous checkpoint intact, never
half a file.  Resume validates checksum and config fingerprint and
refuses LOUDLY on mismatch: silently restarting over corruption is the
failure mode this module exists to kill.
"""

from __future__ import annotations

import base64
import dataclasses
import glob
import hashlib
import json
import os
import signal
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..log import Log
from ..obs import flightrec, telemetry
from ..obs.manifest import _git_info, config_fingerprint
from . import EXIT_PREEMPTED
from . import faults
from .atomic import atomic_write

SCHEMA = "lightgbm-tpu/checkpoint/v1"
_KEEP = 2  # checkpoints retained per run (newest + one fallback)


class CheckpointError(Exception):
    """A checkpoint could not be used.  Messages are actionable — they
    name the file, the mismatch, and the operator's options."""


class TrainingPreempted(Exception):
    """Raised out of the train loop after a SIGTERM/SIGINT-triggered
    checkpoint; cli.main converts it to :data:`EXIT_PREEMPTED`."""

    exit_code = EXIT_PREEMPTED

    def __init__(self, path: str, iteration: int) -> None:
        super().__init__(
            f"training preempted at iteration {iteration}; checkpoint "
            f"saved to {path} — re-run with resume=true to continue")
        self.path = path
        self.iteration = iteration


# ------------------------------------------------------------- array codec
def _enc(arr) -> dict:
    a = np.ascontiguousarray(np.asarray(arr))
    return {
        "dtype": a.dtype.str,
        "shape": list(a.shape),
        "z64": base64.b64encode(zlib.compress(a.tobytes(), 1)).decode(),
    }


def _dec(d: dict) -> np.ndarray:
    raw = zlib.decompress(base64.b64decode(d["z64"]))
    return np.frombuffer(raw, dtype=np.dtype(d["dtype"])).reshape(d["shape"])


def _enc_rng(rng: np.random.RandomState) -> dict:
    alg, keys, pos, has_gauss, cached = rng.get_state()
    return {"alg": alg, "keys": _enc(keys), "pos": int(pos),
            "has_gauss": int(has_gauss), "cached_gaussian": float(cached)}


def _dec_rng(d: dict) -> tuple:
    return (d["alg"], _dec(d["keys"]), d["pos"], d["has_gauss"],
            d["cached_gaussian"])


# ---------------------------------------------------------- fingerprinting
def training_fingerprint(cfg) -> Optional[str]:
    """Config fingerprint for checkpoint compatibility: the full config
    minus the resume switch itself (a resumed run flips ``resume`` and
    nothing else; everything else — data, trees, seeds, snapshot cadence
    — must match for the bitwise contract to hold)."""
    if cfg is None:
        return None
    d = dataclasses.asdict(cfg) if dataclasses.is_dataclass(cfg) else dict(vars(cfg))
    d.pop("resume", None)
    return config_fingerprint(d)


# ------------------------------------------------------------ state capture
def _capture_models(booster) -> List[dict]:
    """Stacked tree arrays, grouped by padding shape (one group per run
    of consecutive same-shape trees; normally exactly one group, more
    when an ``input_model`` with a different num_leaves was merged).
    Exact: no re-binning, no text round trip."""
    groups: List[dict] = []
    run: List = []
    run_shape = None
    from ..models.tree import Tree

    def flush():
        if run:
            groups.append({
                "count": len(run),
                "fields": {
                    name: _enc(np.stack([np.asarray(getattr(t, name))
                                         for t in run]))
                    for name in Tree._fields
                },
            })

    for t in booster.models:
        shape = t.leaf_value.shape
        if shape != run_shape and run:
            flush()
            run = []
        run_shape = shape
        run.append(t)
    flush()
    return groups


def _restore_models(groups: List[dict]) -> List:
    import jax.numpy as jnp

    from ..models.tree import Tree

    models: List = []
    for g in groups:
        fields = {name: _dec(d) for name, d in g["fields"].items()}
        for i in range(g["count"]):
            models.append(Tree(**{
                name: jnp.asarray(arr[i]) for name, arr in fields.items()
            }))
    return models


def save_checkpoint(path: str, booster, cfg, *, iteration: int,
                    best_score: Optional[Dict[tuple, float]] = None,
                    best_iter: Optional[Dict[tuple, int]] = None,
                    prev_sha: Optional[str] = None,
                    gang: Optional[dict] = None) -> str:
    """Serialize the full training state after ``iteration`` completed
    boosting iterations.  Reading the device buffers is a deliberate
    host sync (counted); the checkpoint cadence, not the tree loop,
    pays it.

    ``gang`` (optional) is the rank-topology block a gang member stamps
    into its manifest — ``{gang_id, rank, world_size, barrier_every,
    barrier_id, barrier}`` — so the gang supervisor can compute the last
    COORDINATED barrier (an iteration every live rank checkpointed)
    without trusting filenames alone, and so a resumed rank can refuse a
    checkpoint written under a different topology."""
    telemetry.host_sync()
    payload: Dict = {
        "schema": SCHEMA,
        "created_unix": round(time.time(), 3),
        "iteration": int(iteration),
        "config_fingerprint": training_fingerprint(cfg),
        "lineage": {
            "git": _git_info(),
            "entry": "cli.train",
            "data": getattr(cfg, "data", None),
            "output_model": getattr(cfg, "output_model", None),
            "prev_checkpoint_sha256": prev_sha,
        },
        "booster": {
            "name": booster.name,
            "iter_": int(booster.iter_),
            "num_init_iteration": int(booster.num_init_iteration),
            "num_class": int(booster.num_class),
            "objective": booster.objective_name(),
            "pending_stop": [int(v) for v in booster._pending_stop],
        },
        "models": _capture_models(booster),
        "model_str": base64.b64encode(zlib.compress(
            booster.save_model_to_string(-1).encode(), 1)).decode(),
        "scores": _enc(booster._scores),
        "valid_scores": [_enc(v) for v in
                         getattr(booster, "_valid_scores", [])],
        "bagging": {
            "mask_bits": _enc(np.packbits(
                np.asarray(booster._bag_mask) != 0)),
            "n": int(np.asarray(booster._bag_mask).shape[0]),
            "cnt": int(booster._bag_cnt),
        },
        "rng": {
            "bag": _enc_rng(booster._bag_rng),
            "feat": _enc_rng(booster._feat_rng),
        },
        "early_stop": {
            "best": [
                [int(di), str(name), float((best_score or {})[(di, name)]),
                 int((best_iter or {})[(di, name)])]
                for (di, name) in (best_score or {})
            ],
        },
        "telemetry": telemetry.get_telemetry().snapshot(),
    }
    if gang is not None:
        payload["gang"] = dict(gang)
    if hasattr(booster, "_drop_rng"):  # DART extras
        payload["dart"] = {
            "drop_rng": _enc_rng(booster._drop_rng),
            "tree_weight": [float(w) for w in booster.tree_weight],
            "sum_weight": float(booster.sum_weight),
        }
    if hasattr(booster, "_nf_guard") and booster._nf_guard is not None:
        payload["nonfinite"] = booster._nf_guard.state_dict()

    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    doc = {"schema": SCHEMA,
           "sha256": hashlib.sha256(blob.encode()).hexdigest(),
           "payload": payload}
    atomic_write(path, json.dumps(doc, sort_keys=True,
                                  separators=(",", ":")) + "\n")
    telemetry.count("checkpoints_written")
    return path


def load_checkpoint(path: str) -> dict:
    """Parse + validate one checkpoint file.  Raises
    :class:`CheckpointError` (loud, actionable) on any corruption."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError) as e:
        raise CheckpointError(
            f"checkpoint {path} is unreadable ({type(e).__name__}: "
            f"{str(e)[:120]}) — it was truncated or corrupted. Delete it "
            "to resume from the previous checkpoint, or restart without "
            "resume=true to train from scratch.") from e
    payload = doc.get("payload")
    if doc.get("schema") != SCHEMA or not isinstance(payload, dict):
        raise CheckpointError(
            f"checkpoint {path} has schema {doc.get('schema')!r}, "
            f"expected {SCHEMA!r} — it was written by an incompatible "
            "version; restart without resume=true.")
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    got = hashlib.sha256(blob.encode()).hexdigest()
    if got != doc.get("sha256"):
        raise CheckpointError(
            f"checkpoint {path} FAILED its content checksum "
            f"(sha256 {got[:16]}… != recorded "
            f"{str(doc.get('sha256'))[:16]}…) — the file was corrupted "
            "after writing. Delete it to fall back to the previous "
            "checkpoint, or restart without resume=true.")
    return payload


def validate_against_config(payload: dict, cfg, path: str = "") -> None:
    want = training_fingerprint(cfg)
    have = payload.get("config_fingerprint")
    if want != have:
        raise CheckpointError(
            f"checkpoint {path or '<payload>'} was written under config "
            f"fingerprint {have}, but this run's is {want} — resuming "
            "under a different configuration would NOT reproduce the "
            "uninterrupted run. Re-run with the original parameters "
            "(only the resume flag may differ), or restart without "
            "resume=true.")


def restore_training_state(booster, payload: dict,
                           best_score: Optional[Dict] = None,
                           best_iter: Optional[Dict] = None) -> int:
    """Install a checkpoint payload into a freshly-constructed booster
    (data already loaded, valid sets already attached).  Mirrors
    ``GBDT.restore_state`` field-for-field, from host bytes.  Returns
    the number of completed boosting iterations."""
    import jax.numpy as jnp

    b = payload["booster"]
    if b["num_class"] != booster.num_class:
        raise CheckpointError(
            f"checkpoint num_class={b['num_class']} != configured "
            f"{booster.num_class}")
    booster.models = _restore_models(payload["models"])
    booster.iter_ = int(b["iter_"])
    booster.num_init_iteration = int(b["num_init_iteration"])
    booster._pending_stop = [int(v) for v in b.get("pending_stop", [])]
    booster._scores = jnp.asarray(_dec(payload["scores"]))
    valid = [jnp.asarray(_dec(v)) for v in payload.get("valid_scores", [])]
    if valid:
        if len(getattr(booster, "_valid_scores", [])) != len(valid):
            raise CheckpointError(
                f"checkpoint carries {len(valid)} valid-set score "
                f"buffers, run has "
                f"{len(getattr(booster, '_valid_scores', []))} — the "
                "valid_data list must match the original run's")
        for i, v in enumerate(valid):
            booster._valid_scores[i] = v
    bag = payload["bagging"]
    mask = np.unpackbits(_dec(bag["mask_bits"]))[: bag["n"]]
    booster._bag_mask = jnp.asarray(mask.astype(np.float32))
    booster._bag_cnt = int(bag["cnt"])
    booster._bag_rng.set_state(_dec_rng(payload["rng"]["bag"]))
    booster._feat_rng.set_state(_dec_rng(payload["rng"]["feat"]))
    if "dart" in payload and hasattr(booster, "_drop_rng"):
        booster._drop_rng.set_state(_dec_rng(payload["dart"]["drop_rng"]))
        booster.tree_weight = list(payload["dart"]["tree_weight"])
        booster.sum_weight = float(payload["dart"]["sum_weight"])
    if "nonfinite" in payload and getattr(booster, "_nf_guard", None):
        booster._nf_guard.load_state_dict(payload["nonfinite"])
    if best_score is not None:
        for di, name, score, it in payload["early_stop"]["best"]:
            best_score[(int(di), name)] = float(score)
            if best_iter is not None:
                best_iter[(int(di), name)] = int(it)
    booster._model_version += 1
    telemetry.count("checkpoints_resumed")
    return int(payload["iteration"])


# ----------------------------------------------------------- dir handling
def checkpoint_dir(cfg) -> str:
    d = getattr(cfg, "snapshot_dir", "") or ""
    return d or (getattr(cfg, "output_model", "model.txt") + ".ckpt")


def checkpoint_file(directory: str, iteration: int) -> str:
    return os.path.join(directory, f"ckpt_{iteration:08d}.json")


def list_checkpoints(directory: str) -> List[str]:
    """Checkpoint paths, oldest first (iteration-numbered names sort)."""
    return sorted(glob.glob(os.path.join(directory, "ckpt_*.json")))


def latest_checkpoint(directory: str) -> Optional[str]:
    cks = list_checkpoints(directory)
    return cks[-1] if cks else None


def load_latest_for(cfg) -> Optional[Tuple[str, dict]]:
    """Resolve + validate the newest checkpoint for this run.  Returns
    ``(path, payload)``, or None when the run has no checkpoints at all
    (a preemption before the first snapshot: resuming from scratch IS
    the lossless continuation).  Corruption or a config mismatch raises
    — never silently restarts."""
    path = latest_checkpoint(checkpoint_dir(cfg))
    if path is None:
        return None
    payload = load_checkpoint(path)
    validate_against_config(payload, cfg, path)
    return path, payload


def prune_checkpoints(directory: str, keep: int = _KEEP) -> None:
    for stale in list_checkpoints(directory)[:-keep]:
        try:
            os.remove(stale)
        except OSError:
            pass


# -------------------------------------------------------- train-loop hook
class CheckpointManager:
    """The cli train loop's preemption guard: periodic snapshots
    (``snapshot_freq``), SIGTERM/SIGINT capture that lets the in-flight
    iteration finish, and the checkpoint-then-exit handshake.

    Use as a context manager around the train loop; handlers are
    restored on exit.  ``after_iteration(it)`` is the single hook the
    loop calls — it injects the ``kill_after_tree`` chaos fault, writes
    due snapshots, and raises :class:`TrainingPreempted` after a
    stop-signal checkpoint."""

    def __init__(self, cfg, booster, best_score: Dict, best_iter: Dict,
                 gang: Optional[dict] = None, heartbeat=None):
        self.cfg = cfg
        self.booster = booster
        self.best_score = best_score
        self.best_iter = best_iter
        self.freq = int(getattr(cfg, "snapshot_freq", 0) or 0)
        self.dir = checkpoint_dir(cfg)
        self.enabled = self.freq > 0
        # gang membership (resilience/gang.py): static topology stamped
        # into every checkpoint, plus a liveness beacon the supervisor's
        # heartbeat deadline watches
        self.gang = dict(gang) if gang else None
        self.heartbeat = heartbeat
        self._stop_signum: Optional[int] = None
        self._old_handlers: Dict[int, object] = {}
        self._last_sha: Optional[str] = None

    # -- signals
    def _on_signal(self, signum, frame) -> None:
        # handler body is minimal and re-entrant: set the flag; the
        # train loop checkpoints at the next iteration boundary (the
        # in-flight tree finishes — a half-grown tree is not a state
        # anyone can resume from)
        if self._stop_signum is not None:
            # SECOND signal: the operator means it (a long compile or a
            # minutes-long iteration is in flight) — restore the default
            # disposition and re-raise, aborting immediately without a
            # checkpoint.  Ctrl-C twice must never require SIGKILL.
            # No checkpoint on this path, so the flight recorder is the
            # ONLY record of how far the run got — dump before dying.
            Log.warning(
                f"second {signal.Signals(signum).name}: aborting "
                "immediately (no checkpoint)")
            flightrec.record("signal",
                             signal=signal.Signals(signum).name,
                             second=True)
            flightrec.dump(reason="second_signal")
            signal.signal(signum,
                          self._old_handlers.get(signum, signal.SIG_DFL))
            os.kill(os.getpid(), signum)
            return
        # invariant: signals are delivered on the MAIN thread between
        # bytecodes, and a single reference assignment is atomic under
        # the GIL — a lock here could self-deadlock the handler, and
        # the training loop only ever reads this flag once per round
        self._stop_signum = signum  # jaxlint: disable=shared-state-unlocked
        flightrec.record("signal", signal=signal.Signals(signum).name,
                         second=False)
        Log.warning(
            f"received {signal.Signals(signum).name}; finishing the "
            "in-flight iteration, then checkpointing and exiting "
            f"(exit status {EXIT_PREEMPTED}); send again to abort "
            "immediately")

    def __enter__(self) -> "CheckpointManager":
        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                # invariant: this write happens-before any delivery of
                # the handler being registered (signal.signal returns
                # only after installation), and _old_handlers is
                # read-only afterwards — no interleaving can observe a
                # partial dict
                self._old_handlers[sig] = signal.signal(  # jaxlint: disable=shared-state-unlocked
                    sig, self._on_signal)
        except ValueError:
            # not the main thread (embedded use): periodic snapshots
            # still work, signal capture does not
            self._old_handlers = {}
        return self

    def __exit__(self, *exc) -> None:
        for sig, old in self._old_handlers.items():
            signal.signal(sig, old)

    # -- the loop hook
    def after_iteration(self, it: int) -> None:
        completed = it + 1
        faults.maybe_kill(completed)  # chaos: may deliver SIGTERM here
        if self._stop_signum is not None:
            path = self.write(completed)
            raise TrainingPreempted(path or "<snapshots disabled>",
                                    completed)
        if self.enabled and completed % self.freq == 0:
            self.write(completed)
        if self.heartbeat is not None:
            # the beacon fires AFTER any due barrier commit: a
            # supervisor-observed heartbeat at K implies K's barrier
            # checkpoint is durable, so a gang rollback never regresses
            # past an iteration some rank already attested
            self.heartbeat(completed)
        # the hang fault fires AFTER any due checkpoint commits: a
        # wedged collective strikes between barriers, not instead of
        # one, so the gang supervisor's rollback lands on the barrier
        # this iteration just published
        faults.maybe_hang(completed)  # chaos: may stall (no heartbeat)

    def write(self, completed: int) -> Optional[str]:
        if not self.enabled and self._stop_signum is None:
            return None
        os.makedirs(self.dir, exist_ok=True)
        path = checkpoint_file(self.dir, completed)
        gang_block = None
        if self.gang is not None:
            gang_block = dict(self.gang)
            every = int(gang_block.get("barrier_every", 0) or self.freq or 1)
            gang_block["barrier_id"] = completed
            # barrier-aligned writes are the coordinated ones; a SIGTERM
            # checkpoint can land at any iteration and says so
            gang_block["barrier"] = (completed % every == 0)
        save_checkpoint(path, self.booster, self.cfg,
                        iteration=completed, best_score=self.best_score,
                        best_iter=self.best_iter, prev_sha=self._last_sha,
                        gang=gang_block)
        if faults.maybe_corrupt_checkpoint(path):
            Log.warning(f"FAULT corrupt_checkpoint: corrupted {path}")
        self._last_sha = _file_payload_sha(path)
        prune_checkpoints(self.dir)
        flightrec.record("checkpoint", path=path, iteration=completed)
        Log.info(f"Checkpoint written: {path} (iteration {completed})")
        return path


def _file_payload_sha(path: str) -> Optional[str]:
    try:
        with open(path) as fh:
            return json.load(fh).get("sha256")
    except Exception:
        return None
