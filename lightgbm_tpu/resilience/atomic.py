"""Crash-safe artifact writes: tmp file + fsync + rename (+ checksum).

Every result artifact this repo commits or serves from — model files,
run manifests, ``.bench/*.json``, COPYCHECK.json, prediction outputs —
used to be written with a bare ``open(path, "w")``.  A preemption
mid-write then leaves *half a file under the real name*: a truncated
model that silently loads fewer trees, half a JSON that benchdiff
chokes on.  ``atomic_write`` closes the hole:

1. write to ``<path>.tmp.<pid>`` in the SAME directory (rename must not
   cross filesystems),
2. flush + ``os.fsync`` the tmp file (a rename of un-synced data can
   still surface as an empty file after power loss),
3. ``os.replace`` onto the final name (atomic on POSIX),
4. best-effort fsync of the directory entry.

With ``checksum=True`` a ``<path>.sha256`` sidecar records the content
digest; :func:`verify_sidecar` turns "is this artifact intact?" into a
loud yes/no instead of a guess.  The jaxlint ``raw-artifact-write``
rule (analysis/ast_rules.py) keeps new writers from regressing to bare
``open``.

This module imports neither jax nor numpy (tools adopt it for free);
the only lightgbm_tpu dependency is the fault-injection hook.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
from typing import Any, Iterator, Optional

from . import faults


class ArtifactCorrupt(Exception):
    """An artifact failed its checksum/shape validation.  The message is
    actionable: it names the file, what mismatched, and what to do."""


class _DigestWriter:
    """File-handle proxy teeing every write through a running sha256
    (builtin file objects reject attribute assignment, so the tee is a
    wrapper, not a monkeypatch)."""

    def __init__(self, fh, digest) -> None:
        self._fh = fh
        self._digest = digest

    def write(self, data):
        self._digest.update(data.encode() if isinstance(data, str) else data)
        return self._fh.write(data)

    def writelines(self, lines):
        # must route through write(): proxying writelines straight to
        # the file would ship bytes the digest never saw, committing a
        # sidecar that flags the intact artifact as corrupt
        for line in lines:
            self.write(line)

    def __getattr__(self, name):
        return getattr(self._fh, name)


def sidecar_path(path: str) -> str:
    """Checksum sidecar location for an artifact: ``foo.txt`` ->
    ``foo.txt.sha256`` (self-pairing, survives renames of the pair)."""
    return path + ".sha256"


def _fsync_dir(path: str) -> None:
    """Best-effort directory-entry durability after a rename (not
    supported on some filesystems; never a reason to fail the write)."""
    dirname = os.path.dirname(os.path.abspath(path))
    try:
        fd = os.open(dirname, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


@contextlib.contextmanager
def atomic_writer(path: str, mode: str = "w",
                  checksum: bool = False) -> Iterator[Any]:
    """Context manager yielding a file handle whose contents only ever
    appear under ``path`` complete: commit (fsync + rename) on clean
    exit, tmp-file cleanup on exception.  ``mode`` is ``"w"`` or
    ``"wb"``.  The streaming counterpart of :func:`atomic_write`
    (cli.py's chunked prediction writer)."""
    if mode not in ("w", "wb"):
        raise ValueError(f"atomic_writer mode must be 'w' or 'wb', got {mode!r}")
    tmp = f"{path}.tmp.{os.getpid()}"
    digest = hashlib.sha256() if checksum else None

    fh = open(tmp, mode)  # jaxlint: disable=raw-artifact-write — this IS the atomic implementation
    try:
        yield fh if digest is None else _DigestWriter(fh, digest)
        fh.flush()
        os.fsync(fh.fileno())
        fh.close()
        faults.maybe_fail_write(path)  # LGBM_TPU_FAULT=fail_write_once:
        # injected BEFORE the rename — the destination must stay intact
        if digest is not None:
            # drop any stale sidecar BEFORE the artifact rename: a crash
            # between the rename and the new sidecar write must leave
            # "new artifact, no sidecar" (verify_sidecar -> None, valid)
            # — never "new artifact, OLD sidecar", which would flag an
            # intact file as corrupt
            with contextlib.suppress(OSError):
                os.remove(sidecar_path(path))
        os.replace(tmp, path)
        _fsync_dir(path)
        if digest is not None:
            _write_sidecar(path, digest.hexdigest())
    except BaseException:
        with contextlib.suppress(OSError):
            fh.close()
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise


def _write_sidecar(path: str, hexdigest: str) -> None:
    """The sidecar itself is written atomically (no fault hook: a
    sidecar-less artifact is valid; a half sidecar is not)."""
    tmp = f"{sidecar_path(path)}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:  # jaxlint: disable=raw-artifact-write — sidecar leg of the atomic implementation
        fh.write(hexdigest + "  " + os.path.basename(path) + "\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, sidecar_path(path))


def atomic_write(path: str, data, mode: str = "w",
                 checksum: bool = False) -> str:
    """Write ``data`` (str or bytes) to ``path`` atomically.  Returns
    ``path``.  See module docstring for the crash-safety contract."""
    if isinstance(data, bytes) and mode == "w":
        mode = "wb"
    with atomic_writer(path, mode, checksum=checksum) as fh:
        fh.write(data)
    return path


def atomic_write_json(path: str, obj: Any, indent: Optional[int] = 1,
                      sort_keys: bool = True, checksum: bool = False) -> str:
    """The ``json.dump`` replacement every artifact writer uses: one
    serialization, then the atomic commit."""
    return atomic_write(
        path, json.dumps(obj, indent=indent, sort_keys=sort_keys) + "\n",
        checksum=checksum)


def file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for block in iter(lambda: fh.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def verify_sidecar(path: str) -> Optional[str]:
    """Check ``path`` against its ``.sha256`` sidecar.

    Returns the verified hex digest, or None when no sidecar exists
    (not an error: checksums are opt-in per artifact).  Raises
    :class:`ArtifactCorrupt` on mismatch or a missing artifact."""
    sc = sidecar_path(path)
    if not os.path.exists(sc):
        return None
    with open(sc) as fh:
        expect = fh.read().split()[0].strip()
    if not os.path.exists(path):
        raise ArtifactCorrupt(
            f"{path}: sidecar {sc} exists but the artifact is missing — "
            "the write was interrupted before commit; regenerate the "
            "artifact or delete the stale sidecar")
    got = file_sha256(path)
    if got != expect:
        raise ArtifactCorrupt(
            f"{path}: content sha256 {got[:16]}… does not match sidecar "
            f"{expect[:16]}… — the artifact was truncated or modified "
            "after it was written; regenerate it (or delete both files "
            "if it is disposable)")
    return got
