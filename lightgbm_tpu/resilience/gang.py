"""Elastic multihost training: gang supervisor + coordinated barriers.

The training-side twin of serving/supervisor.py's ReplicaSupervisor.
A multihost data-parallel run is a GANG: every rank must advance
together, so one dead or hung rank costs the whole iteration — the
reference binary simply dies there (network.h:87-159 has no recovery
path).  This module makes rank loss a bounded, attributable event:

* :class:`GangSupervisor` — launches N rank processes with a readiness
  handshake (atomic ``rank_<slot>.ready.json`` files), watches per-rank
  HEARTBEAT files (one atomic write per boosting iteration), and on a
  rank death / stale heartbeat / fired collective deadline aborts the
  iteration, rolls EVERY survivor back to the last coordinated
  checkpoint barrier, and reforms the gang.
* **Coordinated checkpoint barrier** — ranks checkpoint on a shared
  deterministic cadence (``gang_barrier_every`` boosting iterations),
  so "an iteration every live rank has a checkpoint for" always exists.
  The barrier id IS the completed-iteration count; rollback = prune
  every rank's ``ckpt_%08d.json`` files beyond the last common id and
  relaunch with ``resume=true``.  Same world size -> the resumed final
  model is BITWISE identical to an uninterrupted run (the existing
  single-process resume contract, applied gang-wide; chaos proof:
  tools/chaos.py ``rank_kill_midtrain``).
* **Escalation ladder** (resilience/retry.py RecoveryEscalation) —
  stage 1 (in-rank transient retry) is unchanged; stage 2 restarts the
  gang at the same world size; stage 3 shrinks past a rank that died
  ``gang_rank_fail_limit`` times, under one jittered-backoff restart
  budget.  Budget exhausted -> RecoveryExhausted, flight-recorder dump,
  exit 1 — a crash-looping gang must page, not spin.
* **Shrink + reshard parity gate** — with ``gang_shard_data=true`` the
  supervisor row-shards the data file; a shrink reshards across the
  survivors and REFUSES to proceed unless the union of shards carries
  the same row multiset as the original dataset
  (:func:`histogram_fingerprint`): identical row multiset => every
  global (allreduced) feature histogram is identical, so training on
  the resharded world is statistically the same problem.  Resharded
  ranks restart boosting (their per-row score buffers no longer match
  their shard); without sharding (redundant mode) survivors resume
  from the barrier with zero lost iterations.
* **SIGTERM fan-out** — a SIGTERM to the supervisor is forwarded to
  EVERY rank child; each checkpoints and exits 75, then the supervisor
  itself exits 75 (resilience.EXIT_PREEMPTED).  Relaunching
  ``task=train_fleet`` with ``resume=true`` rolls to the last common
  barrier and continues.

Wire format and counter names are documented in docs/resilience.md and
docs/parallel_comm.md.  This module imports no jax directly: the
supervisor is host code; only the rank children pay for a device
runtime.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..analysis import lockcheck
from ..log import Log
from ..obs import flightrec, telemetry
from . import EXIT_PREEMPTED
from .atomic import atomic_write, atomic_write_json
from .retry import RecoveryEscalation, RecoveryExhausted

GANG_SCHEMA = "lightgbm-tpu/gang/v1"
ARTIFACT_SCHEMA = "lightgbm-tpu/train-fleet/v1"

_CKPT_RE = re.compile(r"ckpt_(\d{8})\.json$")


class GangParityError(RuntimeError):
    """A reshard lost or duplicated rows: the union of the proposed
    shards does not carry the original dataset's row multiset, so
    global histograms would silently change.  The shrink is refused."""


# --------------------------------------------------------------- rank files
def ready_file(gang_dir: str, slot: int) -> str:
    return os.path.join(gang_dir, f"rank_{slot}.ready.json")


def heartbeat_file(gang_dir: str, slot: int) -> str:
    return os.path.join(gang_dir, f"rank_{slot}.hb.json")


class RankBeacon:
    """The rank-side half of the supervision protocol, driven from the
    cli train path: one atomic ready-file write when the training loop
    is about to start, one atomic heartbeat write per completed
    iteration (CheckpointManager.after_iteration), and the rank-topology
    block every checkpoint carries."""

    def __init__(self, gang_dir: str, slot: int, rank: int, world: int,
                 gang_id: str, barrier_every: int) -> None:
        self.gang_dir = gang_dir
        self.slot = int(slot)
        self.rank = int(rank)
        self.world = int(world)
        self.gang_id = gang_id
        self.barrier_every = int(barrier_every)

    def ready(self) -> None:
        atomic_write_json(ready_file(self.gang_dir, self.slot), {
            "slot": self.slot, "rank": self.rank, "pid": os.getpid(),
            "t_unix": round(time.time(), 3)})

    def heartbeat(self, iteration: int) -> None:
        atomic_write_json(heartbeat_file(self.gang_dir, self.slot), {
            "slot": self.slot, "rank": self.rank,
            "iteration": int(iteration), "pid": os.getpid(),
            "t_unix": round(time.time(), 3)})

    def gang_block(self) -> dict:
        """Static topology stamped into every checkpoint manifest (the
        manager adds the per-write ``barrier_id``/``barrier``)."""
        return {"schema": GANG_SCHEMA, "gang_id": self.gang_id,
                "slot": self.slot, "rank": self.rank,
                "world_size": self.world,
                "barrier_every": self.barrier_every}


def beacon_from_env() -> Optional[RankBeacon]:
    """Build the beacon from the env the supervisor launched us with;
    None when this process is not a gang member."""
    gang_dir = os.environ.get("LGBM_TPU_GANG_DIR", "")
    if not gang_dir:
        return None
    slot = int(os.environ.get("LGBM_TPU_GANG_SLOT", "0") or 0)
    rank = int(os.environ.get("LGBM_TPU_PROCESS_ID", "0") or 0)
    world = int(os.environ.get("LGBM_TPU_NUM_PROCESSES", "1") or 1)
    gang_id = os.environ.get("LGBM_TPU_GANG_ID", "gang")
    every = int(os.environ.get("LGBM_TPU_GANG_BARRIER_EVERY", "1") or 1)
    return RankBeacon(gang_dir, slot, rank, world, gang_id, every)


# ------------------------------------------------------------ barrier math
def _ckpt_iterations(ckpt_dir: str) -> Dict[int, str]:
    """iteration -> path for every checkpoint file in ``ckpt_dir``."""
    out: Dict[int, str] = {}
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return out
    for name in names:
        m = _CKPT_RE.match(name)
        if m:
            out[int(m.group(1))] = os.path.join(ckpt_dir, name)
    return out


def last_common_barrier(ckpt_dirs: Sequence[str]) -> int:
    """The newest iteration EVERY rank has a checkpoint for (0 = none:
    the gang restarts from scratch, which is itself a valid barrier —
    a deterministic run from iteration 0 still hits the bitwise
    contract)."""
    common: Optional[set] = None
    for d in ckpt_dirs:
        its = set(_ckpt_iterations(d))
        common = its if common is None else (common & its)
    return max(common) if common else 0


def rollback_to_barrier(ckpt_dirs: Sequence[str], barrier: int) -> int:
    """Prune every checkpoint NEWER than ``barrier`` (uncoordinated
    progress: some rank advanced past the last common barrier before
    the abort).  Returns the number of files removed."""
    removed = 0
    for d in ckpt_dirs:
        for it, path in _ckpt_iterations(d).items():
            if it > barrier:
                try:
                    os.remove(path)
                    removed += 1
                except OSError:
                    pass
    return removed


# ---------------------------------------------------- reshard parity gate
def histogram_fingerprint(paths: Sequence[str]) -> str:
    """Order-independent fingerprint of the row MULTISET across
    ``paths``: sha256 over the sorted concatenation of data lines.
    Two datasets with equal fingerprints produce identical global
    feature histograms under ANY row partition — this is the parity
    gate a shrink-time reshard must pass (docs/parallel_comm.md)."""
    rows: List[bytes] = []
    for p in paths:
        with open(p, "rb") as fh:
            rows.extend(line.rstrip(b"\r\n") for line in fh
                        if line.strip())
    h = hashlib.sha256()
    for line in sorted(rows):
        h.update(line)
        h.update(b"\n")
    return h.hexdigest()


def shard_rows(data_path: str, out_dir: str,
               slots: Sequence[int]) -> Dict[int, str]:
    """Round-robin row shards of ``data_path`` for the active slots
    (``shard_r<slot>.csv`` under ``out_dir``), verified against the
    parity gate before anyone trains on them.  Returns slot -> path."""
    os.makedirs(out_dir, exist_ok=True)
    with open(data_path, "r") as fh:
        lines = [ln for ln in fh.read().splitlines() if ln.strip()]
    shards: Dict[int, List[str]] = {s: [] for s in slots}
    order = list(slots)
    for i, ln in enumerate(lines):
        shards[order[i % len(order)]].append(ln)
    paths: Dict[int, str] = {}
    for s in slots:
        path = os.path.join(out_dir, f"shard_r{s}.csv")
        atomic_write(path, "\n".join(shards[s]) + "\n")
        paths[s] = path
    want = histogram_fingerprint([data_path])
    got = histogram_fingerprint([paths[s] for s in slots])
    if want != got:
        raise GangParityError(
            f"reshard of {data_path} across slots {list(slots)} FAILED "
            f"the global-histogram parity gate (row-multiset sha256 "
            f"{got[:16]}… != source {want[:16]}…) — rows were lost or "
            "duplicated; refusing to train on it.")
    telemetry.count("lgbm_gang_parity_checks")
    return paths


# ------------------------------------------------------------ rank handles
class SubprocessRank:
    """One rank as a real ``python -m lightgbm_tpu task=train``
    subprocess.  stdout/stderr tee to ``<slot_dir>/log.txt``; kill() is
    SIGKILL (abrupt rank death), terminate() is SIGTERM (the rank
    checkpoints and exits 75)."""

    def __init__(self, slot: int, rank: int, argv: Sequence[str],
                 env: Dict[str, str], gang_dir: str, log_path: str) -> None:
        self.slot = int(slot)
        self.rank = int(rank)
        self.argv = list(argv)
        self.env = dict(env)
        self.gang_dir = gang_dir
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self._log_fh = None

    def start(self) -> None:
        env = dict(os.environ)
        env.update(self.env)
        self._log_fh = open(self.log_path, "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-u", "-m", "lightgbm_tpu", *self.argv],
            stdout=self._log_fh, stderr=subprocess.STDOUT, env=env)

    def wait_ready(self, timeout_s: float) -> bool:
        path = ready_file(self.gang_dir, self.slot)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if os.path.exists(path):
                return True
            if self.poll() is not None:
                return False
            time.sleep(0.05)
        return False

    def poll(self) -> Optional[int]:
        if self.proc is None:
            return None
        rc = self.proc.poll()
        if rc is not None and self._log_fh is not None:
            try:
                self._log_fh.close()
            except OSError:
                pass
            self._log_fh = None
        return rc

    def kill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()

    def terminate(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()

    def wait(self, timeout_s: float) -> Optional[int]:
        if self.proc is None:
            return None
        try:
            self.proc.wait(timeout_s)
        except subprocess.TimeoutExpired:
            pass
        return self.poll()


class RankKilled(Exception):
    """In-thread stand-in for SIGKILL (dryrun/chaos thread ranks)."""


class RankPreempted(Exception):
    """In-thread stand-in for the SIGTERM checkpoint-and-exit-75 path."""


class ThreadRankContext:
    """What a thread-rank job sees: identity, the handshake/heartbeat
    beacon, and the cooperative kill/preempt flags the job must poll
    between iterations (a thread cannot be SIGKILLed; polling at the
    iteration boundary is the same granularity the real train loop
    honors signals at)."""

    def __init__(self, slot: int, rank: int, world: int, gang_dir: str,
                 slot_dir: str, barrier_every: int, resume: bool,
                 data_path: str = "") -> None:
        self.slot = slot
        self.rank = rank
        self.world = world
        self.gang_dir = gang_dir
        self.slot_dir = slot_dir
        self.barrier_every = barrier_every
        self.resume = resume
        self.data_path = data_path
        self.killed = threading.Event()
        self.preempt = threading.Event()
        self._beacon = RankBeacon(gang_dir, slot, rank, world,
                                  "thread-gang", barrier_every)

    def ready(self) -> None:
        self._beacon.ready()

    def heartbeat(self, iteration: int) -> None:
        self._beacon.heartbeat(iteration)

    def check_signals(self) -> None:
        """Raise the pending simulated signal, kill winning over
        preempt (a SIGKILL outranks a SIGTERM)."""
        if self.killed.is_set():
            raise RankKilled()
        if self.preempt.is_set():
            raise RankPreempted()


class ThreadRank:
    """One rank as a daemon thread running ``fn(ctx)`` — the dryrun
    stand-in for SubprocessRank (tools/chaos.py supplies a deterministic
    stub training job).  Exit codes mirror the process contract:
    0 done, 75 preempted-after-checkpoint, -9 killed, 1 error."""

    def __init__(self, slot: int, rank: int, fn: Callable, ctx:
                 ThreadRankContext) -> None:
        self.slot = int(slot)
        self.rank = int(rank)
        self.fn = fn
        self.ctx = ctx
        self.gang_dir = ctx.gang_dir
        self._rc: Optional[int] = None
        self._thread: Optional[threading.Thread] = None
        self._lock = lockcheck.make_lock(f"gang.threadrank.{slot}")

    def _run(self) -> None:
        try:
            self.fn(self.ctx)
            rc = 0
        except RankKilled:
            rc = -9
        except RankPreempted:
            rc = 75
        except Exception as e:  # noqa: BLE001 — rank error -> exit 1
            Log.warning(f"thread rank {self.slot} error: "
                        f"{type(e).__name__}: {e}")
            rc = 1
        with self._lock:
            self._rc = rc

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"gang-rank-{self.slot}")
        self._thread.start()

    def wait_ready(self, timeout_s: float) -> bool:
        path = ready_file(self.gang_dir, self.slot)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if os.path.exists(path):
                return True
            if self.poll() is not None:
                return False
            time.sleep(0.01)
        return False

    def poll(self) -> Optional[int]:
        with self._lock:
            return self._rc

    def kill(self) -> None:
        self.ctx.killed.set()

    def terminate(self) -> None:
        self.ctx.preempt.set()

    def wait(self, timeout_s: float) -> Optional[int]:
        if self._thread is not None:
            self._thread.join(timeout_s)
        return self.poll()


# ------------------------------------------------------------- supervisor
class _RankSlot:
    """One supervised rank position.  ``slot_id`` is stable for the
    life of the gang (it names the rank's private dir, shard, and
    handshake files); ``rank`` is the dense 0..world-1 index the
    current formation assigns (re-numbered after a shrink so rank-file
    exchanges stay contiguous)."""

    __slots__ = ("slot_id", "rank", "handle", "failures", "done",
                 "last_hb_iter")

    def __init__(self, slot_id: int) -> None:
        self.slot_id = slot_id
        self.rank = slot_id
        self.handle = None
        self.failures = 0
        self.done = False
        self.last_hb_iter = 0


class GangSupervisor:
    """Owns the rank gang: formation (with rollback to the last common
    barrier), heartbeat/death monitoring, the recovery ladder, SIGTERM
    fan-out, and the train-fleet artifact metrics.

    ``factory(slot_id, rank, world, resume)`` builds a rank handle
    (SubprocessRank or ThreadRank).  ``ckpt_dir_for(slot_id)`` names a
    slot's checkpoint dir (for barrier math).  ``reshard(slot_ids)``
    (optional) re-partitions the data across the surviving slots after
    a shrink and returns whether survivors may resume (False = the
    shards changed under them, restart boosting from scratch)."""

    def __init__(self, factory: Callable, *, slots: Sequence[int],
                 gang_dir: str, ckpt_dir_for: Callable[[int], str],
                 barrier_every: int = 1,
                 restart_budget: int = 8, rank_fail_limit: int = 2,
                 min_ranks: int = 1,
                 backoff_base_s: float = 0.2, backoff_max_s: float = 5.0,
                 heartbeat_timeout_s: float = 60.0,
                 ready_timeout_s: float = 180.0,
                 poll_interval_s: float = 0.2,
                 reshard: Optional[Callable] = None,
                 chaos_kill_at: Optional[Dict[int, int]] = None,
                 seed: int = 0, sleep: Callable = time.sleep) -> None:
        self._factory = factory
        self._gang_dir = gang_dir
        self._ckpt_dir_for = ckpt_dir_for
        self._barrier_every = int(barrier_every)
        self._hb_timeout = float(heartbeat_timeout_s)
        self._ready_timeout = float(ready_timeout_s)
        self._poll_interval = float(poll_interval_s)
        self._reshard = reshard
        # slot -> (iteration, persistent): SIGKILL the slot once its
        # heartbeat reaches the iteration; persistent entries re-arm at
        # every gang formation (they model a host that keeps dying,
        # driving the shrink rung of the ladder)
        self._chaos_kill_at: Dict[int, tuple] = {}
        for k, v in (chaos_kill_at or {}).items():
            self._chaos_kill_at[int(k)] = (
                (int(v[0]), bool(v[1])) if isinstance(v, (tuple, list))
                else (int(v), False))
        self._chaos_fired: set = set()
        self._sleep = sleep
        self._esc = RecoveryEscalation(
            restart_budget=restart_budget, rank_fail_limit=rank_fail_limit,
            min_world=min_ranks, backoff_base_s=backoff_base_s,
            backoff_max_s=backoff_max_s, seed=seed)
        self._lock = lockcheck.make_lock("gang.state")
        self._slots: List[_RankSlot] = [_RankSlot(s) for s in slots]
        self._world_start = len(self._slots)
        # set from a signal handler: a single reference assignment is
        # atomic under the GIL and the run loop reads it once per poll
        self._preempt_signum: Optional[int] = None
        self.recoveries: List[dict] = []
        self.lost_iterations = 0
        self.restarts = 0
        self.shrinks = 0
        self.rank_deaths = 0
        self.rank_hangs = 0
        self.preempted = False
        self.budget_exhausted = False
        self.final_barrier = 0

    # -- public surface -------------------------------------------------
    def request_preempt(self, signum: int = signal.SIGTERM) -> None:
        """Signal-handler hook: ask the run loop to fan the preemption
        out to every rank (SIGTERM fan-out satellite — ALL ranks must
        checkpoint and exit 75, not just rank 0)."""
        self._preempt_signum = signum  # jaxlint: disable=shared-state-unlocked

    def chaos_kill(self, slot_id: int) -> None:
        """Abruptly kill one rank (chaos hook — drives the exact death
        path a preempted host produces)."""
        with self._lock:
            slot = self._slot_by_id(slot_id)
            if slot is not None and slot.handle is not None:
                telemetry.count("lgbm_gang_chaos_kills")
                flightrec.record("gang_chaos_kill", slot=slot_id)
                slot.handle.kill()

    def describe(self) -> dict:
        with self._lock:
            return {
                "world_size_start": self._world_start,
                "world_size": len(self._slots),
                "slots": [{"slot": s.slot_id, "rank": s.rank,
                           "failures": s.failures, "done": s.done,
                           "last_hb_iter": s.last_hb_iter}
                          for s in self._slots],
                "restarts": self.restarts, "shrinks": self.shrinks,
                "rank_deaths": self.rank_deaths,
                "rank_hangs": self.rank_hangs,
                "budget_spent": self._esc.spent,
                "budget_remaining": self._esc.remaining(),
                "recoveries": list(self.recoveries),
                "lost_iterations": self.lost_iterations,
                "preempted": self.preempted,
                "budget_exhausted": self.budget_exhausted,
                "final_barrier": self.final_barrier,
            }

    def run(self, resume: bool = False) -> int:
        """Supervise until every rank finishes (0), the operator
        preempts the fleet (75), or recovery is exhausted (1).  A rank
        that dies DURING formation re-enters the same recovery ladder
        as one that dies mid-iteration."""
        self._t_start = time.monotonic()
        pending: Optional[tuple] = ("__form__", resume)
        try:
            while True:
                if pending is not None:
                    kind = pending[0]
                    try:
                        if kind == "__form__":
                            self._form_gang(resume=pending[1], first=True)
                        else:
                            self._recover(*pending)
                        pending = None
                    except _FormationFailed as ff:
                        pending = (ff.slot_id, "rank_death", ff.rc)
                    continue
                if self._preempt_signum is not None:
                    return self._preempt_all()
                failed = self._poll_once()
                with self._lock:
                    if all(s.done for s in self._slots):
                        break
                if failed is not None:
                    pending = failed
                    continue
                self._sleep(self._poll_interval)
        except RecoveryExhausted as err:
            self.budget_exhausted = True
            telemetry.count("lgbm_gang_budget_exhausted")
            flightrec.record("gang_budget_exhausted", error=str(err)[:400])
            flightrec.dump(reason="gang_budget_exhausted")
            Log.warning(f"gang: {err}")
            self._kill_all()
            return 1
        self.final_barrier = last_common_barrier(
            [self._ckpt_dir_for(s.slot_id) for s in self._slots])
        Log.info(
            f"gang: all {len(self._slots)} ranks finished "
            f"(restarts={self.restarts}, shrinks={self.shrinks}, "
            f"lost_iterations={self.lost_iterations})")
        return 0

    def active_slot_ids(self) -> List[int]:
        with self._lock:
            return [s.slot_id for s in self._slots]

    def artifact_section(self) -> dict:
        """The metrics block of the train-fleet/v1 artifact
        (tools/benchdiff.py gates on it)."""
        wall = time.monotonic() - getattr(self, "_t_start", time.monotonic())
        mttrs = [r["mttr_s"] for r in self.recoveries if "mttr_s" in r]
        return {
            "world_size_start": self._world_start,
            "world_size_end": len(self._slots),
            "restarts": self.restarts,
            "shrinks": self.shrinks,
            "rank_deaths": self.rank_deaths,
            "rank_hangs": self.rank_hangs,
            "recoveries": len(self.recoveries),
            "recovery_timeline": list(self.recoveries),
            "mttr_s": round(sum(mttrs) / len(mttrs), 4) if mttrs else 0.0,
            "lost_iterations": self.lost_iterations,
            "budget_spent": self._esc.spent,
            "budget_exhausted": self.budget_exhausted,
            "preempted": self.preempted,
            "final_barrier": self.final_barrier,
            "wall_s": round(wall, 4),
        }

    # -- internals ------------------------------------------------------
    def _slot_by_id(self, slot_id: int) -> Optional[_RankSlot]:
        for s in self._slots:
            if s.slot_id == slot_id:
                return s
        return None

    def _clear_handshake(self, slot_id: int) -> None:
        for path in (ready_file(self._gang_dir, slot_id),
                     heartbeat_file(self._gang_dir, slot_id)):
            try:
                os.remove(path)
            except OSError:
                pass

    def _form_gang(self, resume: bool, first: bool = False) -> None:
        """(Re)launch every active rank from a COMMON state: roll all
        checkpoint dirs back to the last common barrier (or wipe them on
        a fresh start), clear the handshake files, start the handles,
        and wait for every ready file.  A rank that dies before ready
        re-enters the recovery ladder."""
        with self._lock:
            slots = list(self._slots)
        dirs = [self._ckpt_dir_for(s.slot_id) for s in slots]
        if resume:
            barrier = last_common_barrier(dirs)
            pruned = rollback_to_barrier(dirs, barrier)
            if pruned:
                telemetry.count("lgbm_gang_rollbacks")
                Log.info(f"gang: rolled back {pruned} checkpoint(s) "
                         f"beyond barrier {barrier}")
        else:
            barrier = 0
            rollback_to_barrier(dirs, 0)
        self._barrier = barrier
        # persistent chaos kills re-arm at every formation
        self._chaos_fired -= {s for s, (_, persist)
                              in self._chaos_kill_at.items() if persist}
        for i, slot in enumerate(slots):
            self._clear_handshake(slot.slot_id)
            slot.rank = i
            slot.done = False
            slot.last_hb_iter = barrier  # stale fronts would inflate lost
        telemetry.count("lgbm_gang_launches", len(slots))
        flightrec.record("gang_form", world=len(slots), barrier=barrier,
                         resume=bool(resume), first=bool(first))
        for slot in slots:
            handle = self._factory(slot.slot_id, slot.rank, len(slots),
                                   resume)
            with self._lock:
                slot.handle = handle
            handle.start()
        for slot in slots:
            if not slot.handle.wait_ready(self._ready_timeout):
                rc = slot.handle.poll()
                raise _FormationFailed(slot.slot_id, rc)
        Log.info(f"gang: formed with {len(slots)} rank(s) at barrier "
                 f"{barrier} (resume={resume})")

    def _heartbeat_age(self, slot: _RankSlot) -> Optional[float]:
        hb = heartbeat_file(self._gang_dir, slot.slot_id)
        try:
            with open(hb) as fh:
                slot.last_hb_iter = int(json.load(fh).get("iteration", 0))
        except (OSError, ValueError):
            pass
        for path in (hb, ready_file(self._gang_dir, slot.slot_id)):
            try:
                return time.time() - os.path.getmtime(path)
            except OSError:
                continue
        return None

    def _poll_once(self):
        """One monitor pass.  Returns ``(slot_id, cause, rc)`` on the
        first observed failure, else None.  Marks cleanly finished
        ranks done."""
        with self._lock:
            slots = list(self._slots)
        for slot in slots:
            if slot.done or slot.handle is None:
                continue
            rc = slot.handle.poll()
            if rc == 0:
                slot.done = True
                continue
            if rc is not None:
                # 75 without a supervisor-initiated preemption means an
                # outside actor SIGTERMed one rank: the gang treats any
                # unilateral exit as a death and recovers
                return (slot.slot_id, "rank_death", rc)
            age = self._heartbeat_age(slot)
            if self._hb_timeout > 0 and age is not None and \
                    age > self._hb_timeout:
                Log.warning(
                    f"gang: rank slot {slot.slot_id} heartbeat is "
                    f"{age:.1f}s stale (deadline {self._hb_timeout:.1f}s)"
                    " — declaring it hung and killing it")
                slot.handle.kill()
                slot.handle.wait(10.0)
                return (slot.slot_id, "rank_hang", None)
            target = self._chaos_kill_at.get(slot.slot_id)
            if target is not None and slot.slot_id not in \
                    self._chaos_fired and slot.last_hb_iter >= target[0]:
                self._chaos_fired.add(slot.slot_id)
                self.chaos_kill(slot.slot_id)
        return None

    def _kill_all(self) -> None:
        with self._lock:
            slots = list(self._slots)
        for slot in slots:
            if slot.handle is not None and not slot.done:
                slot.handle.kill()
        for slot in slots:
            if slot.handle is not None and not slot.done:
                slot.handle.wait(10.0)

    def _recover(self, slot_id: int, cause: str, rc) -> None:
        """Stages 2/3 of the ladder: abort the iteration (kill every
        survivor — their post-barrier progress is unjoinable anyway),
        roll back, back off, reform.  Raises RecoveryExhausted when the
        ladder is out of rungs."""
        t_detect = time.monotonic()
        slot = self._slot_by_id(slot_id)
        slot.failures += 1
        if cause == "rank_hang":
            self.rank_hangs += 1
            telemetry.count("lgbm_gang_rank_hangs")
        else:
            self.rank_deaths += 1
            telemetry.count("lgbm_gang_rank_deaths")
        hb_front = max([s.last_hb_iter for s in self._slots] + [0])
        flightrec.record("gang_abort", slot=slot_id, cause=cause,
                         rc=rc if rc is None else int(rc),
                         failures=slot.failures, world=len(self._slots),
                         hb_front=hb_front)
        self._kill_all()
        action, delay = self._esc.next_action(
            world=len(self._slots), rank_failures=slot.failures)
        resume = True
        if action == "shrink":
            with self._lock:
                self._slots = [s for s in self._slots
                               if s.slot_id != slot_id]
            self.shrinks += 1
            telemetry.count("lgbm_gang_shrinks")
            Log.warning(
                f"gang: slot {slot_id} died {slot.failures}x — shrinking "
                f"to {len(self._slots)} rank(s)")
            if self._reshard is not None:
                resume = bool(self._reshard(self.active_slot_ids()))
        else:
            self.restarts += 1
            telemetry.count("lgbm_gang_restarts")
        # the drain-tagged post-mortem: every abort leaves the full
        # event ring (who died, what the heartbeat front was, what the
        # ladder decided) next to the artifacts BEFORE the backoff wait
        flightrec.record("gang_recovery", action=action, slot=slot_id,
                         cause=cause, backoff_s=round(delay, 3),
                         budget_spent=self._esc.spent)
        flightrec.dump(reason=f"gang_abort_{cause}")
        self._sleep(delay)
        self._form_gang(resume=resume)
        barrier = self._barrier
        lost = max(0, hb_front - barrier)
        self.lost_iterations += lost
        telemetry.count_many({"lgbm_gang_lost_iterations": lost})
        mttr = time.monotonic() - t_detect
        self.recoveries.append({
            "t_rel_s": round(t_detect - self._t_start, 4),
            "cause": cause, "slot": slot_id, "action": action,
            "world_after": len(self._slots), "barrier": barrier,
            "lost_iterations": lost, "mttr_s": round(mttr, 4),
        })
        telemetry.record_value("lgbm_gang_mttr_s", mttr)
        Log.info(f"gang: recovered from {cause} of slot {slot_id} via "
                 f"{action} in {mttr:.2f}s (barrier {barrier}, "
                 f"{lost} lost iteration(s))")

    def _preempt_all(self) -> int:
        """SIGTERM fan-out: forward the preemption to EVERY rank child,
        wait for each to checkpoint and exit 75, then report 75
        ourselves.  A rank that ignores the signal is killed (and
        logged) — the fleet must release its hosts."""
        signum = self._preempt_signum or signal.SIGTERM
        self.preempted = True
        telemetry.count("lgbm_gang_preemptions")
        with self._lock:
            live = [s for s in self._slots
                    if not s.done and s.handle is not None]
        Log.warning(
            f"gang: forwarding {signal.Signals(signum).name} to "
            f"{len(live)} rank(s); each checkpoints and exits "
            f"{EXIT_PREEMPTED}")
        for slot in live:
            slot.handle.terminate()
        clean = 0
        for slot in live:
            rc = slot.handle.wait(self._ready_timeout)
            if rc == EXIT_PREEMPTED:
                clean += 1
            else:
                Log.warning(
                    f"gang: rank slot {slot.slot_id} exited {rc} "
                    f"(expected {EXIT_PREEMPTED}) during preemption")
                slot.handle.kill()
                slot.handle.wait(10.0)
        flightrec.record("gang_preempt", ranks=len(live), clean=clean,
                         signal=signal.Signals(signum).name)
        flightrec.dump(reason="gang_preempt")
        Log.info(f"gang: preempted; {clean}/{len(live)} rank(s) "
                 "checkpointed cleanly — relaunch with resume=true")
        return EXIT_PREEMPTED


class _FormationFailed(Exception):
    """A rank died (or never became ready) during gang formation —
    converted into the normal recovery path by the run loop."""

    def __init__(self, slot_id: int, rc) -> None:
        super().__init__(f"rank slot {slot_id} failed during formation "
                         f"(rc={rc})")
        self.slot_id = slot_id
        self.rc = rc


# -------------------------------------------------------- CLI entry point
def _passthrough_params(cfg) -> List[str]:
    """Re-emit the training parameters a rank child needs as
    ``key=value`` argv: every field that differs from the dataclass
    default, minus the ones the supervisor owns (task/data/output/
    checkpoint/gang/serving knobs)."""
    import dataclasses

    from ..config import Config

    skip = {"task", "data", "output_model", "snapshot_dir",
            "snapshot_freq", "resume", "train_ranks", "gang_dir",
            "gang_barrier_every", "gang_restart_budget",
            "gang_backoff_base_s", "gang_backoff_max_s",
            "gang_rank_fail_limit", "gang_min_ranks",
            "gang_heartbeat_timeout_s", "gang_ready_timeout_s",
            "gang_shard_data", "machine_list_file"}
    out: List[str] = []
    for f in dataclasses.fields(Config):
        if f.name in skip or f.name.startswith("serve_"):
            continue
        val = getattr(cfg, f.name)
        if f.default is not dataclasses.MISSING:
            if val == f.default:
                continue
        elif f.default_factory is not dataclasses.MISSING:  # type: ignore
            if val == f.default_factory():  # type: ignore
                continue
        if isinstance(val, bool):
            out.append(f"{f.name}={'true' if val else 'false'}")
        elif isinstance(val, (list, tuple)):
            if val:
                out.append(f"{f.name}={','.join(str(v) for v in val)}")
        else:
            out.append(f"{f.name}={val}")
    return out


def _chaos_kill_from_env() -> Dict[int, tuple]:
    """``LGBM_TPU_GANG_CHAOS_KILL="<slot>:<iteration>[:always][,...]"``
    — the supervisor SIGKILLs the slot once its heartbeat reaches the
    iteration; ``always`` re-arms the kill at every gang formation, the
    crash-looping host that drives the shrink rung (tools/chaos.py
    rank_kill_midtrain / elastic_shrink)."""
    spec = os.environ.get("LGBM_TPU_GANG_CHAOS_KILL", "")
    out: Dict[int, tuple] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        s, _, rest = part.partition(":")
        it, _, mode = rest.partition(":")
        out[int(s)] = (int(it or 1), mode == "always")
    return out


def _gang_fault_env() -> Dict[int, str]:
    """``LGBM_TPU_GANG_FAULT="<slot>:<fault-spec>"`` — inject an
    LGBM_TPU_FAULT into ONE rank child only (chaos rank_hang)."""
    spec = os.environ.get("LGBM_TPU_GANG_FAULT", "")
    out: Dict[int, str] = {}
    if spec:
        s, _, fault = spec.partition(":")
        out[int(s)] = fault
    return out


def train_fleet_from_config(cfg) -> int:
    """``task=train_fleet``: supervise ``train_ranks`` rank
    subprocesses through to a finished model at ``cfg.output_model``
    (rank 0's model, copied on success), with the full recovery ladder,
    SIGTERM fan-out, and a committed-shape train-fleet/v1 artifact at
    ``<gang_dir>/train_fleet.json``."""
    gang_dir = cfg.gang_dir or (cfg.output_model + ".gang")
    barrier_every = int(cfg.gang_barrier_every or cfg.snapshot_freq or 0)
    if barrier_every <= 0:
        raise ValueError(
            "task=train_fleet needs gang_barrier_every or snapshot_freq "
            "> 0 — a gang without checkpoint barriers cannot roll back")
    os.makedirs(gang_dir, exist_ok=True)
    flightrec.configure_dir(gang_dir)
    slots = list(range(int(cfg.train_ranks)))
    gang_id = f"gang-{os.getpid()}"
    obs_dir = os.path.join(gang_dir, "obs")
    os.makedirs(obs_dir, exist_ok=True)

    shard_map: Dict[int, str] = {}
    reshard = None
    if cfg.gang_shard_data:
        shard_map.update(shard_rows(cfg.data, gang_dir, slots))

        def reshard(active_ids: Sequence[int]) -> bool:
            shard_map.update(shard_rows(cfg.data, gang_dir, active_ids))
            # resharded rows invalidate the survivors' per-row score
            # buffers: boosting restarts from scratch on the new shards
            # (statistically identical — the parity gate just held)
            return False

    passthrough = _passthrough_params(cfg)

    def slot_dir(slot: int) -> str:
        return os.path.join(gang_dir, f"r{slot}")

    def ckpt_dir_for(slot: int) -> str:
        return os.path.join(slot_dir(slot), "ckpt")

    fault_by_slot = _gang_fault_env()

    def factory(slot: int, rank: int, world: int, resume: bool):
        sdir = slot_dir(slot)
        os.makedirs(ckpt_dir_for(slot), exist_ok=True)
        data = shard_map.get(slot, cfg.data)
        argv = ["task=train", f"data={data}",
                f"output_model={os.path.join(sdir, 'model.txt')}",
                f"snapshot_dir={ckpt_dir_for(slot)}",
                f"snapshot_freq={barrier_every}",
                f"resume={'true' if resume else 'false'}",
                *passthrough]
        env = {
            "LGBM_TPU_GANG_DIR": gang_dir,
            "LGBM_TPU_GANG_SLOT": str(slot),
            "LGBM_TPU_GANG_ID": gang_id,
            "LGBM_TPU_GANG_BARRIER_EVERY": str(barrier_every),
            "LGBM_TPU_PROCESS_ID": str(rank),
            "LGBM_TPU_NUM_PROCESSES": str(world),
            "LGBM_TPU_RANK_OBS_DIR": obs_dir,
            "LGBM_TPU_FLIGHTREC_DIR": gang_dir,
        }
        if slot in fault_by_slot:
            env["LGBM_TPU_FAULT"] = fault_by_slot[slot]
        return SubprocessRank(slot, rank, argv, env, gang_dir,
                              log_path=os.path.join(sdir, "log.txt"))

    sup = GangSupervisor(
        factory, slots=slots, gang_dir=gang_dir,
        ckpt_dir_for=ckpt_dir_for, barrier_every=barrier_every,
        restart_budget=cfg.gang_restart_budget,
        rank_fail_limit=cfg.gang_rank_fail_limit,
        min_ranks=cfg.gang_min_ranks,
        backoff_base_s=cfg.gang_backoff_base_s,
        backoff_max_s=cfg.gang_backoff_max_s,
        heartbeat_timeout_s=cfg.gang_heartbeat_timeout_s,
        ready_timeout_s=cfg.gang_ready_timeout_s,
        poll_interval_s=0.05,  # detection latency IS the MTTR floor
        chaos_kill_at=_chaos_kill_from_env(), reshard=reshard,
        seed=cfg.seed)

    old_handlers = {}
    try:
        for sig in (signal.SIGTERM, signal.SIGINT):
            old_handlers[sig] = signal.signal(
                sig, lambda signum, frame: sup.request_preempt(signum))
    except ValueError:
        old_handlers = {}  # not the main thread (tests)
    try:
        rc = sup.run(resume=bool(cfg.resume))
    finally:
        for sig, old in old_handlers.items():
            signal.signal(sig, old)

    if rc == 0:
        first = sup.active_slot_ids()[0]
        src = os.path.join(slot_dir(first), "model.txt")
        with open(src, "rb") as fh:
            atomic_write(cfg.output_model, fh.read(), mode="wb")
        Log.info(f"gang: saved rank {first}'s model to "
                 f"{cfg.output_model}")
    write_train_fleet_artifact(
        os.path.join(gang_dir, "train_fleet.json"), sup, cfg,
        barrier_every=barrier_every, rc=rc)
    return rc


def write_train_fleet_artifact(path: str, sup: GangSupervisor, cfg,
                               barrier_every: int, rc: int) -> str:
    """The ``lightgbm-tpu/train-fleet/v1`` artifact: recovery metrics a
    benchdiff gate can regress on (MTTR headline; failed_iterations>0
    and budget exhaustion are outright regressions)."""
    section = sup.artifact_section()
    target = int(getattr(cfg, "num_iterations", 0) or 0)
    section["target_iterations"] = target
    section["failed_iterations"] = (
        0 if rc in (0, EXIT_PREEMPTED)
        else max(0, target - sup.final_barrier))
    section["exit_code"] = int(rc)
    section["barriers_committed"] = (
        sup.final_barrier // max(1, barrier_every))
    tel = telemetry.get_telemetry().snapshot()
    counters = {k: v for k, v in tel.get("counters", {}).items()
                if k.startswith("lgbm_gang_")}
    doc = {
        "schema": ARTIFACT_SCHEMA,
        "created_unix": round(time.time(), 3),
        "shape": {
            "ranks": section["world_size_start"],
            "trees": target,
            "barrier_every": int(barrier_every),
            "shard_data": bool(getattr(cfg, "gang_shard_data", False)),
            "seed": int(getattr(cfg, "seed", 0) or 0),
        },
        "train_fleet": section,
        "counters": counters,
    }
    atomic_write_json(path, doc)
    try:
        # the manifest sibling (obs/manifest.py): rank snapshots carry
        # the gang stamp (obs/dist.py), making every recovery
        # attributable — "slot 2's third incarnation" has a name
        from ..obs import dist
        from ..obs.manifest import RunManifest, manifest_path

        snaps = []
        obs_dir = os.path.join(os.path.dirname(path), "obs")
        for name in sorted(os.listdir(obs_dir)):
            if name.startswith("rank_") and name.endswith(".json"):
                with open(os.path.join(obs_dir, name)) as fh:
                    snaps.append(json.load(fh))
        man = RunManifest.collect(
            "train_fleet", config=cfg, result=dict(section),
            ranks=dist.ranks_section(snaps) if snaps else [])
        man.write(manifest_path(path))
    except Exception as e:  # noqa: BLE001 — manifest is best-effort
        Log.warning(f"train-fleet manifest write failed: "
                    f"{type(e).__name__}: {e}")
    Log.info(f"gang: wrote train-fleet artifact to {path}")
    return path
