"""Bounded retry for transient device/collective failures + a deadline
that fails loudly instead of hanging.

Two production failure shapes this covers:

* **Transient errors** — a dropped TPU tunnel, a coordinator mid-restart,
  a collective hitting a preempted peer.  These surface as exceptions
  whose messages carry the runtime's status vocabulary (``UNAVAILABLE``,
  ``DEADLINE_EXCEEDED``, ``connection reset`` …).  :func:`retry_transient`
  retries exactly those, with exponential backoff and a telemetry
  counter, and re-raises everything else immediately — an OOM or a
  shape error must never be retried into a loop.
* **Hangs** — a multihost collective whose peer died before joining
  blocks FOREVER by default (jax's barrier has no library-level
  timeout).  :func:`call_with_deadline` runs the call on a worker
  thread and raises :class:`CollectiveDeadlineExceeded` when the clock
  runs out.  The worker thread cannot be killed (the underlying C++
  call is not interruptible), so the process should treat the exception
  as fatal-but-loud: log, checkpoint state if any, exit nonzero — the
  supervisor restarts it.  That is strictly better than a silent hang
  that holds fleet capacity until a human notices.

No jax import: the classifier works on message text, so the module
stays importable from tools.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Sequence, TypeVar

from ..log import Log
from ..obs import telemetry
from . import faults

T = TypeVar("T")

# status vocabulary of transient, retry-safe failures (XLA/gRPC wording)
TRANSIENT_MARKERS: Sequence[str] = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "connection reset",
    "Connection reset",
    "Socket closed",
    "failed to connect",
    "Broken pipe",
)


def _counter_label(label: str) -> str:
    """Human label -> counter-name segment ("config sync allgather
    (pre-dispatch)" -> "config_sync_allgather_pre-dispatch")."""
    return "_".join(label.replace("(", "").replace(")", "").split())


def is_transient(exc: BaseException) -> bool:
    msg = f"{type(exc).__name__}: {exc}"
    return any(marker in msg for marker in TRANSIENT_MARKERS)


def backoff_delay(attempt: int, *, base_s: float, max_s: float,
                  rng=None) -> float:
    """THE exponential-backoff schedule, shared by every retry loop in
    the tree (transient-collective retry here, replica restarts in
    serving/supervisor.py, gang restarts in resilience/gang.py,
    coordinator connects in parallel/multihost.py).  ``attempt`` is the
    zero-based failure count: attempt 0 waits ``base_s``.

    With ``rng`` (a ``random.Random``) the delay is jittered into
    ``[0.5x, 1.5x)`` — fleet restarts must not stampede the coordinator
    in lockstep.  Without it the schedule is deterministic, which the
    single-process retry paths prefer (reproducible test timings)."""
    delay = min(max_s, base_s * (2 ** max(0, attempt)))
    if rng is not None:
        delay *= 0.5 + rng.random()  # jitter in [0.5x, 1.5x)
    return delay


def retry_transient(fn: Callable[[], T], *, retries: int = 3,
                    base_delay_s: float = 0.5, max_delay_s: float = 8.0,
                    label: str = "") -> T:
    """Call ``fn``; on a transient failure (see :func:`is_transient`)
    retry up to ``retries`` times with exponential backoff.  Counts
    ``transient_retries`` in telemetry, plus the label-scoped
    ``transient_retries.<label>`` so a retry is attributable to the
    specific collective/site it guarded.  Non-transient exceptions and
    the final transient failure propagate unchanged."""
    attempt = 0
    while True:
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — classified below
            if not is_transient(e) or attempt >= retries:
                raise
            attempt += 1
            delay = backoff_delay(attempt - 1, base_s=base_delay_s,
                                  max_s=max_delay_s)
            # attribute the retry to the specific collective/site: the
            # bare global counter says "something retried somewhere",
            # which on an 8-rank run is no attribution at all
            adds = {"transient_retries": 1}
            if label:
                adds[f"transient_retries.{_counter_label(label)}"] = 1
            telemetry.count_many(adds)
            Log.warning(
                f"transient failure{f' in {label}' if label else ''} "
                f"(attempt {attempt}/{retries}, retrying in {delay:.1f}s): "
                f"{type(e).__name__}: {str(e)[:200]}")
            time.sleep(delay)


class CollectiveDeadlineExceeded(RuntimeError):
    """A guarded collective/device call outlived its deadline.  The call
    is still blocked on its (abandoned, daemon) worker thread — treat
    this as fatal-but-loud: the process must exit rather than issue
    further collectives into a wedged world."""


def call_with_deadline(fn: Callable[[], T], deadline_s: float,
                       what: str = "collective") -> T:
    """Run ``fn`` with a wall-clock deadline.  ``deadline_s <= 0``
    disables the guard (direct call).  On timeout raises
    :class:`CollectiveDeadlineExceeded` with an actionable message."""
    if deadline_s <= 0:
        return fn()
    result: list = []
    error: list = []

    def runner() -> None:
        try:
            result.append(fn())
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            error.append(e)

    t = threading.Thread(target=runner, daemon=True,
                         name=f"deadline:{what}")
    t.start()
    t.join(deadline_s)
    if t.is_alive():
        telemetry.count("collective_deadline_hits")
        raise CollectiveDeadlineExceeded(
            f"{what} did not complete within {deadline_s:.0f}s — a peer "
            "process likely died or was preempted before joining. The "
            "call is abandoned on a daemon thread; exit this process and "
            "re-launch the world (resume from the latest checkpoint). "
            "Raise collective_deadline_s (or set it to 0) if the "
            "deadline is simply too tight for this topology.")
    if error:
        raise error[0]
    return result[0]


class CollectiveFailed(RuntimeError):
    """A dispatched collective failed.  Deliberately NOT retried on this
    rank alone: peers that already completed the op have moved on, and a
    unilaterally re-issued collective would match the WRONG op (silent
    cross-rank desync — worse than the failure).  Recovery is
    world-level: exit, re-launch all ranks, resume from checkpoint."""


def guarded_collective(fn: Callable[[], T], *, deadline_s: float,
                       label: str, retries: int = 2) -> T:
    """The composition the multihost paths use: fault-injection point,
    retry of PRE-DISPATCH failures only, and a deadline on the
    collective itself.

    The retry scope is deliberately narrow: only failures raised before
    the collective dispatches (the chaos injection point; connection
    setup in callers that stage it there) are transient-retried.  A
    failure from the dispatched collective is wrapped in
    :class:`CollectiveFailed` and raised loudly — one rank retrying a
    matched collective while its peers have moved on desynchronizes the
    world."""
    retry_transient(faults.maybe_fail_collective, retries=retries,
                    label=f"{label} (pre-dispatch)")
    try:
        return call_with_deadline(fn, deadline_s, what=label)
    except CollectiveDeadlineExceeded:
        raise
    except BaseException as e:  # noqa: BLE001 — classified below
        if is_transient(e):
            raise CollectiveFailed(
                f"{label} failed after dispatch ({type(e).__name__}: "
                f"{str(e)[:200]}). Not retrying on this rank alone — "
                "re-issuing a matched collective unilaterally would "
                "desynchronize the world. Exit, re-launch all ranks "
                "together, and resume from the latest checkpoint.") from e
        raise


def collective_deadline_s(cfg=None, default: float = 0.0) -> float:
    """Resolve the configured collective deadline: the
    ``LGBM_TPU_COLLECTIVE_DEADLINE_S`` env var wins (operator override
    on a wedged fleet), else ``cfg.collective_deadline_s``, else
    ``default`` (0 = disabled)."""
    import os

    env = os.environ.get("LGBM_TPU_COLLECTIVE_DEADLINE_S", "")
    if env:
        return float(env)
    if cfg is not None:
        return float(getattr(cfg, "collective_deadline_s", default) or 0.0)
    return default


# --------------------------------------------------- escalation ladder
class RecoveryExhausted(RuntimeError):
    """Every recovery stage has been spent: the restart budget is gone
    (or shrinking would go below the minimum world size).  The caller
    must fail LOUDLY — dump the flight recorder and exit nonzero; a
    supervisor that silently keeps respawning a doomed gang burns fleet
    capacity without ever telling an operator."""


class RecoveryEscalation:
    """The three-stage recovery ladder for multihost training.

    Stage 1 — **retry** — lives inside the rank: pre-dispatch transient
    failures are retried in place by :func:`guarded_collective` /
    :func:`retry_transient`.  A failure that escapes a rank (process
    death, a fired collective deadline, a heartbeat stall) reaches this
    object, which decides between the remaining stages:

    Stage 2 — **restart** — abort the iteration, roll every survivor
    back to the last coordinated checkpoint barrier, and reform the gang
    at the SAME world size (bitwise-identical resume).  Each restart
    consumes one unit of ``restart_budget`` and waits a jittered
    exponential backoff (:func:`backoff_delay`).

    Stage 3 — **shrink** — when the same rank has died
    ``rank_fail_limit`` times in a row, stop paying for it: drop the
    rank, reshard the data (gated on global-histogram parity), and
    reform the gang one rank smaller.  Shrinking also consumes budget.

    When the budget is exhausted, or shrinking would drop the world
    below ``min_world``, :meth:`next_action` raises
    :class:`RecoveryExhausted`.

    Decisions are deterministic given ``seed`` (the jitter uses a
    private ``random.Random``), so chaos tests replay exactly."""

    def __init__(self, *, restart_budget: int = 8, rank_fail_limit: int = 2,
                 min_world: int = 1, backoff_base_s: float = 0.2,
                 backoff_max_s: float = 5.0, seed: int = 0) -> None:
        import random

        self.restart_budget = int(restart_budget)
        self.rank_fail_limit = int(rank_fail_limit)
        self.min_world = max(1, int(min_world))
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.spent = 0
        self._rng = random.Random(seed)

    def remaining(self) -> int:
        return max(0, self.restart_budget - self.spent)

    def next_action(self, *, world: int, rank_failures: int):
        """Classify the next recovery step after a rank failure.

        ``world`` is the current gang size; ``rank_failures`` is the
        consecutive-failure count of the slot that just died (including
        this failure).  Returns ``("restart", delay_s)`` or
        ``("shrink", delay_s)``; raises :class:`RecoveryExhausted` when
        the ladder has no rung left."""
        if self.spent >= self.restart_budget:
            raise RecoveryExhausted(
                f"restart budget exhausted ({self.spent}/"
                f"{self.restart_budget} recoveries spent) — refusing to "
                "respawn a gang that keeps dying. Inspect the flight "
                "recorder dump and the per-rank logs; raise "
                "gang_restart_budget only once the cause is understood.")
        want_shrink = rank_failures >= self.rank_fail_limit
        if want_shrink and world - 1 < self.min_world:
            raise RecoveryExhausted(
                f"rank died {rank_failures}x (limit {self.rank_fail_limit}) "
                f"but shrinking below gang_min_ranks={self.min_world} is "
                "not allowed — the world cannot hold the workload. "
                "Replace the bad host or lower gang_min_ranks.")
        self.spent += 1
        delay = backoff_delay(self.spent - 1, base_s=self.backoff_base_s,
                              max_s=self.backoff_max_s, rng=self._rng)
        return ("shrink" if want_shrink else "restart"), delay
