"""Deterministic fault injection: every recovery path gets a test.

``LGBM_TPU_FAULT`` holds a comma-separated list of fault specs; each
spec is ``kind`` or ``kind:param``.  The injection points live INSIDE
the production code paths they exercise, so a chaos run drives exactly
the code a real preemption would:

==========================  ====================================================
spec                        injection point
==========================  ====================================================
``kill_after_tree:K``       cli train loop raises SIGTERM to the process the
                            moment iteration K completes — the real
                            preemption signal through the real handler
``hang_after_tree:K[:S]``   cli train loop stalls for S seconds (default
                            3600 — "forever" at test scale) the moment
                            iteration K completes, without heartbeating —
                            the lab stand-in for a wedged collective /
                            dead NIC; the gang supervisor's heartbeat
                            deadline must detect and kill the rank
``corrupt_checkpoint``      every checkpoint write is followed by flipping
                            bytes mid-file — resume must refuse it loudly
``nan_grads:J``             gradient poisoning at boosting iteration J
                            (models/gbdt.py) — exercises the non-finite
                            guard policies
``fail_collective_once``    first guarded collective raises a fake
                            ``UNAVAILABLE`` — exercises retry_transient
``fail_write_once``         first atomic_write fails before its rename —
                            the destination must stay intact
``corrupt_model``           every serving hot-swap candidate is corrupted
                            mid-file before verification
                            (serving/hotswap.py) — the swap must be
                            refused and the old model keeps answering
``delay_collective:R:MS``   rank R sleeps MS milliseconds before EVERY
                            traced host collective (obs/dist.py) — the
                            lab straggler: peers' barrier-wait skew must
                            attribute to rank R (recurring, not
                            self-consuming)
``desync_step:R``           rank R perturbs its desync-sentinel
                            fingerprint ONCE — the sentinel on every
                            rank must detect and NAME rank R within one
                            iteration
``oom_dispatch``            the next train/serve dispatch raises a fake
                            ``RESOURCE_EXHAUSTED`` (self-consuming) —
                            exercises the OOM classifier + flight
                            recorder post-mortem (obs/memory.py)
==========================  ====================================================

The env var is read once at import (the repo-wide convention for
behavior knobs); tests inject in-process via :func:`set_fault` /
:func:`clear_faults`.  ``*_once`` faults self-consume.  No jax/numpy
imports — the gradient poisoner operates on whatever array type it is
handed via duck-typed ops.
"""

from __future__ import annotations

import os
import signal
from typing import Dict, Optional

_VALID = ("kill_after_tree", "hang_after_tree", "corrupt_checkpoint",
          "nan_grads", "fail_collective_once", "fail_write_once",
          "corrupt_model", "delay_collective", "desync_step",
          "oom_dispatch")


class InjectedFault(Exception):
    """Base for all injected failures — distinguishable from real ones
    in test assertions, indistinguishable in the recovery paths (which
    must not special-case it)."""


class InjectedWriteError(InjectedFault, OSError):
    pass


class InjectedCollectiveError(InjectedFault, RuntimeError):
    pass


class InjectedResourceExhausted(InjectedFault, RuntimeError):
    """Fake device OOM.  The message carries the literal
    ``RESOURCE_EXHAUSTED`` marker, matching what XlaRuntimeError puts
    in-text, so the classifier (obs/memory.is_oom_error) keys on the
    same evidence it would see from a real allocator failure."""


def _parse(spec: str) -> Dict[str, Optional[str]]:
    out: Dict[str, Optional[str]] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        kind, _, param = part.partition(":")
        if kind not in _VALID:
            raise ValueError(
                f"unknown LGBM_TPU_FAULT kind {kind!r} "
                f"(valid: {', '.join(_VALID)})")
        out[kind] = param or None
    return out


_FAULTS: Dict[str, Optional[str]] = _parse(os.environ.get("LGBM_TPU_FAULT", ""))
_CONSUMED: set = set()


def set_fault(spec: str) -> None:
    """Replace the active fault set in-process (tests/chaos dryrun)."""
    global _FAULTS
    _FAULTS = _parse(spec)
    _CONSUMED.clear()


def clear_faults() -> None:
    set_fault("")


def fault_active(kind: str) -> Optional[str]:
    """The fault's param ("" when parameterless) or None when inactive
    (or already consumed, for ``*_once`` kinds)."""
    if kind not in _FAULTS or kind in _CONSUMED:
        return None
    return _FAULTS[kind] or ""


def _consume(kind: str) -> None:
    _CONSUMED.add(kind)


def _note(kind: str, **fields) -> None:
    """Record the injection in the flight recorder (lazy import — this
    module must stay importable with nothing but the stdlib; a chaos
    post-mortem that does not show its own injected faults would send
    the reader chasing a phantom)."""
    try:
        from ..obs import flightrec

        flightrec.record("fault_injected", fault=kind, **fields)
    except Exception:  # noqa: BLE001 — never let observability break injection
        pass


# ------------------------------------------------------- injection points
def kill_after_tree() -> Optional[int]:
    """Iteration count after which the training loop should receive
    SIGTERM, or None."""
    p = fault_active("kill_after_tree")
    return int(p) if p else None


def maybe_kill(completed_iterations: int) -> None:
    """cli train-loop hook: raise the REAL preemption signal to this
    process once iteration K has completed (the handler then finishes
    bookkeeping and checkpoints, exactly as under a fleet preemption)."""
    k = kill_after_tree()
    if k is not None and completed_iterations == k:
        _consume("kill_after_tree")
        _note("kill_after_tree", iteration=completed_iterations)
        os.kill(os.getpid(), signal.SIGTERM)


def maybe_hang(completed_iterations: int) -> None:
    """cli train-loop hook: stall this rank for S seconds once iteration
    K has completed, WITHOUT heartbeating — from the gang supervisor's
    seat this is indistinguishable from a wedged collective, which is
    the point: the heartbeat deadline (not a human) must notice and
    SIGKILL the rank."""
    p = fault_active("hang_after_tree")
    if p is None:
        return
    k, _, secs = p.partition(":")
    if completed_iterations != int(k or 0):
        return
    _consume("hang_after_tree")
    stall_s = float(secs) if secs else 3600.0
    _note("hang_after_tree", iteration=completed_iterations,
          stall_s=stall_s)
    import time

    time.sleep(stall_s)


def maybe_fail_write(path: str) -> None:
    """atomic_write hook, fired after the tmp file is written but BEFORE
    the rename: the crash window the atomic protocol exists to survive."""
    if fault_active("fail_write_once") is not None:
        _consume("fail_write_once")
        _note("fail_write_once", path=path)
        raise InjectedWriteError(
            f"injected write failure before committing {path}")


def maybe_fail_collective() -> None:
    """Guarded-collective hook: one fake transient failure, in the
    vocabulary real collective stacks use (retry_transient keys on it)."""
    if fault_active("fail_collective_once") is not None:
        _consume("fail_collective_once")
        _note("fail_collective_once")
        raise InjectedCollectiveError(
            "UNAVAILABLE: injected transient collective failure")


def _overwrite_mid_file(path: str) -> None:
    """Overwrite bytes in the middle of ``path`` with ASCII filler.
    ASCII (not bit-flips) so a text format usually stays *parseable*
    and the corruption is caught by the content CHECKSUM — the deepest
    validation layer; when the filler happens to break the structure
    instead, the shallower unreadable-file error path is exercised."""
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.seek(size // 2)
        fh.write(b"A" * min(16, max(1, size // 2)))


def maybe_corrupt_checkpoint(path: str) -> bool:
    """Checkpoint-writer hook: corrupt the freshly committed file —
    either way the resume must refuse loudly.  Returns True when
    corruption was injected."""
    if fault_active("corrupt_checkpoint") is None:
        return False
    _overwrite_mid_file(path)
    _note("corrupt_checkpoint", path=path)
    return True


def _current_rank() -> int:
    """Lazy rank resolution — ONE implementation, in obs/dist.py
    (jax-if-already-imported -> launcher env -> 0; never imports jax,
    honoring this module's stdlib-only contract).  Guarded: a fault
    hook must degrade to rank 0, not raise."""
    try:
        from ..obs.dist import process_index

        return process_index()
    except Exception:  # noqa: BLE001
        return 0


def maybe_delay_collective(rank=None) -> None:
    """obs/dist.traced_collective hook: when the active fault names THIS
    rank, sleep the configured milliseconds before the barrier — every
    peer then observes the delay as barrier-wait time attributable to
    this rank.  Recurring (not ``_once``): a straggling chip straggles
    every collective, and one delayed site would vanish into noise."""
    p = fault_active("delay_collective")
    if p is None:
        return
    want_rank, _, ms = p.partition(":")
    try:
        want, delay_ms = int(want_rank), float(ms or 0)
    except ValueError:
        raise ValueError(
            f"delay_collective wants '<rank>:<ms>', got {p!r}") from None
    me = _current_rank() if rank is None else int(rank)
    if me != want or delay_ms <= 0:
        return
    import time

    _note("delay_collective", rank=me, delay_ms=delay_ms)
    time.sleep(delay_ms / 1000.0)


def maybe_desync_step(rank=None) -> bool:
    """Desync-sentinel hook (obs/dist.DesyncSentinel.local_row): when
    the active fault names THIS rank, consume it and return True — the
    sentinel then perturbs its fingerprint once, and every rank's next
    verify must detect and name this rank."""
    p = fault_active("desync_step")
    if p is None:
        return False
    try:
        want = int(p)
    except ValueError:
        raise ValueError(f"desync_step wants '<rank>', got {p!r}") from None
    me = _current_rank() if rank is None else int(rank)
    if me != want:
        return False
    _consume("desync_step")
    _note("desync_step", rank=me)
    return True


def maybe_oom_dispatch(where: str) -> None:
    """Train/serve dispatch hook (models/gbdt.py train_one_iter,
    serving/engine.py _dispatch_rows): one fake RESOURCE_EXHAUSTED at
    the next dispatch.  Self-consuming — a real OOM kills one dispatch;
    the interesting behavior is the post-mortem, not a crash loop."""
    if fault_active("oom_dispatch") is not None:
        _consume("oom_dispatch")
        _note("oom_dispatch", where=where)
        raise InjectedResourceExhausted(
            f"RESOURCE_EXHAUSTED: injected out-of-memory at {where} "
            "dispatch (allocator reported no free device memory)")


def maybe_corrupt_model(path: str) -> bool:
    """serving/hotswap.py hook, fired BEFORE sidecar verification:
    corrupt the hot-swap candidate model file so the checksum check is
    what refuses it (the lab analog of a truncated/partial model write
    reaching a serving replica).  Returns True when injected."""
    if fault_active("corrupt_model") is None or not os.path.exists(path):
        return False
    _overwrite_mid_file(path)
    _note("corrupt_model", path=path)
    return True


def poison_grads(grad, hess, iteration: int):
    """models/gbdt.py hook: at boosting iteration J, overwrite the first
    gradient lane of every class with NaN (and one hessian lane with
    +inf, so both operands are exercised).  Duck-typed: works on jax and
    numpy arrays alike."""
    p = fault_active("nan_grads")
    if p is None or iteration != int(p or 0):
        return grad, hess
    _consume("nan_grads")
    _note("nan_grads", iteration=iteration)
    grad = grad.at[..., 0].set(float("nan")) if hasattr(grad, "at") else _np_poison(grad, float("nan"))
    hess = hess.at[..., 0].set(float("inf")) if hasattr(hess, "at") else _np_poison(hess, float("inf"))
    return grad, hess


def _np_poison(arr, value):
    arr = arr.copy()
    arr[..., 0] = value
    return arr
