"""Fault tolerance for preemptible fleets.

Production TPU fleets preempt, OOM, and drop hosts; the reference CLI's
answer is "rerun the job".  This subsystem makes a training run
survivable instead:

* :mod:`~lightgbm_tpu.resilience.atomic` — crash-safe artifact writes
  (tmp file + fsync + rename, optional sha256 sidecar).  A SIGKILL
  mid-write must never leave half a model/manifest/bench JSON shadowing
  a real artifact.
* :mod:`~lightgbm_tpu.resilience.checkpoint` — exact training-state
  checkpoints + resume such that the resumed final model is BITWISE
  identical to an uninterrupted run (tier-1 contract,
  tests/test_resilience.py).
* :mod:`~lightgbm_tpu.resilience.guards` — non-finite gradient/leaf
  guards with ``raise | skip_tree | clip`` policies, checked at the
  library's existing deliberate sync points (never a new hot-path sync).
* :mod:`~lightgbm_tpu.resilience.retry` — bounded retry-with-backoff
  for transient device/collective failures and a collective deadline
  that fails loudly instead of hanging a preempted world.
* :mod:`~lightgbm_tpu.resilience.faults` — deterministic fault
  injection (``LGBM_TPU_FAULT``) so every recovery path above is
  exercised by tests (tools/chaos.py) rather than trusted.

This module and ``atomic``/``faults``/``retry`` import neither jax nor
numpy: tools (benchdiff, jaxlint) adopt atomic writes without paying a
jax import.  ``checkpoint``/``guards`` are imported lazily by their
users (cli.py, models/gbdt.py).
"""

from .atomic import (  # noqa: F401
    ArtifactCorrupt,
    atomic_write,
    atomic_write_json,
    atomic_writer,
    sidecar_path,
    verify_sidecar,
)
from .faults import (  # noqa: F401
    InjectedFault,
    clear_faults,
    fault_active,
    set_fault,
)
from .retry import (  # noqa: F401
    CollectiveDeadlineExceeded,
    RecoveryEscalation,
    RecoveryExhausted,
    backoff_delay,
    call_with_deadline,
    retry_transient,
)

EXIT_PREEMPTED = 75
"""CLI exit status for "training was preempted but checkpointed": the
sysexits EX_TEMPFAIL convention — a supervisor should re-launch with
``resume=true``.  Distinct from 0 (done) and 1 (error)."""
