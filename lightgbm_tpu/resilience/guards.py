"""Non-finite guards: graceful degradation instead of silent garbage.

A single NaN gradient (a poisoned row, an overflowing custom objective,
a bad init score) propagates through histogram sums into every split
gain and leaf value of the tree — and float32 training will neither
crash nor warn.  The guard watches the two places non-finites enter the
model (gradients/hessians before growing, leaf outputs after) under a
configurable policy (``Config.nonfinite_policy``):

* ``off`` (default) — zero checks, zero cost: the exact pre-existing
  behavior.
* ``raise`` — count non-finites on device (one tiny fused reduction per
  iteration, async), materialize the count at the iteration's existing
  deliberate sync point, and abort loudly (after rolling the poisoned
  iteration back) via :class:`NonFiniteError`.
* ``skip_tree`` — materialize the gradient check BEFORE growing (this
  policy buys certainty with one host sync per iteration — documented
  cost) and skip the iteration when poisoned; training continues on the
  next objective evaluation.
* ``clip`` — zero out non-finite gradient/hessian entries (the poisoned
  rows contribute nothing this iteration, like a per-row dropout) and
  sanitize non-finite leaf outputs to 0; counts accumulate on device
  and drain at checkpoints/teardown.

Everything is counted in telemetry (``nonfinite_grad_events``,
``nonfinite_values_clipped``, ``nonfinite_skipped_trees``) so a fleet
dashboard sees degradation the moment it starts.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from ..log import Log
from ..obs import flightrec, telemetry

POLICIES = ("off", "raise", "skip_tree", "clip")

# skip_tree escalation bound: a skip mutates nothing, so a DETERMINISTIC
# non-finite source would silently burn every remaining iteration —
# after this many consecutive skips the guard raises instead
MAX_CONSECUTIVE_SKIPS = 10


class NonFiniteError(RuntimeError):
    """Non-finite gradients/hessians/leaf outputs under policy=raise."""


@jax.jit
def _count_nonfinite(grad, hess):
    return (jnp.sum(~jnp.isfinite(grad)) + jnp.sum(~jnp.isfinite(hess))).astype(jnp.int32)


@jax.jit
def _clean_pair(grad, hess):
    """Zero non-finite entries (a poisoned row drops out of this
    iteration's tree) and report how many were cleaned."""
    bad_g = ~jnp.isfinite(grad)
    bad_h = ~jnp.isfinite(hess)
    n = (jnp.sum(bad_g) + jnp.sum(bad_h)).astype(jnp.int32)
    return (jnp.where(bad_g, 0.0, grad).astype(grad.dtype),
            jnp.where(bad_h, 0.0, hess).astype(hess.dtype), n)


@jax.jit
def _count_nonfinite_leaves(leaf_value):
    return jnp.sum(~jnp.isfinite(leaf_value)).astype(jnp.int32)


@jax.jit
def _clean_leaves(leaf_value):
    bad = ~jnp.isfinite(leaf_value)
    return jnp.where(bad, 0.0, leaf_value), jnp.sum(bad).astype(jnp.int32)


class NonFiniteGuard:
    """Per-booster guard state; one instance per GBDT when the policy is
    not ``off`` (models/gbdt.py constructs it)."""

    def __init__(self, policy: str) -> None:
        if policy not in POLICIES:
            raise ValueError(
                f"Unknown nonfinite_policy: {policy!r} "
                f"(valid: {', '.join(POLICIES)})")
        self.policy = policy
        # parked per-iteration device counts (policy=raise) — drained at
        # the iteration's existing sync point, never a new hot-path sync
        self._pending: List[jax.Array] = []
        self._clipped_total = 0  # host mirror, survives checkpointing
        self._consecutive_skips = 0

    # ------------------------------------------------------------- grads
    def check_gradients(self, grad, hess):
        """Returns ``(grad, hess, skip_iteration)``."""
        if self.policy == "clip":
            grad, hess, n = _clean_pair(grad, hess)
            self._pending.append(n)
            self._drain_clip(limit=64)
            return grad, hess, False
        n = _count_nonfinite(grad, hess)
        if self.policy == "skip_tree":
            telemetry.host_sync()
            if int(n) > 0:
                telemetry.count("nonfinite_grad_events")
                telemetry.count("nonfinite_skipped_trees")
                self._consecutive_skips += 1
                flightrec.record("guard_trip", policy="skip_tree",
                                 nonfinite=int(n),
                                 consecutive=self._consecutive_skips)
                if self._consecutive_skips >= MAX_CONSECUTIVE_SKIPS:
                    # a skip changes no state, so deterministic NaN
                    # sources (inf init_score, a broken objective) would
                    # otherwise burn EVERY remaining iteration and exit
                    # 0 as if training succeeded — escalate instead
                    raise NonFiniteError(
                        f"{self._consecutive_skips} consecutive boosting "
                        "iterations skipped for non-finite gradients "
                        "(nonfinite_policy=skip_tree): the source is "
                        "persistent, not transient — skipping cannot "
                        "converge. Fix the objective/data, or use "
                        "nonfinite_policy=clip.")
                Log.warning(
                    f"non-finite gradients/hessians ({int(n)} values); "
                    "policy=skip_tree: skipping this boosting iteration")
                return grad, hess, True
            self._consecutive_skips = 0
            return grad, hess, False
        # policy == "raise": park the async count; raise_if_poisoned()
        # materializes it at the iteration's end-of-iteration sync
        self._pending.append(n)
        return grad, hess, False

    # ------------------------------------------------------------ leaves
    def check_tree(self, tree):
        """Leaf-output guard, applied before the tree's score update.
        Returns ``(tree, handled)``.  Never drops a tree — the caller's
        models list must stay iteration-major K-aligned — so skip_tree
        degrades to zeroing the poisoned leaves here (gradients are the
        skip_tree policy's skip point; a non-finite leaf with finite
        gradients is the rare lambda/hessian-edge case)."""
        if self.policy in ("clip", "skip_tree"):
            cleaned, n = _clean_leaves(tree.leaf_value)
            if self.policy == "skip_tree":
                telemetry.host_sync()
                if int(n) > 0:
                    telemetry.count("nonfinite_leaf_values", int(n))
                    telemetry.count("nonfinite_grad_events")
                    Log.warning(
                        f"zeroed {int(n)} non-finite leaf outputs "
                        "(nonfinite_policy=skip_tree)")
                    return tree._replace(leaf_value=cleaned), True
                return tree, False
            self._pending.append(n)
            return tree._replace(leaf_value=cleaned), True
        n = _count_nonfinite_leaves(tree.leaf_value)
        self._pending.append(n)
        return tree, False

    # ----------------------------------------------------------- drains
    def raise_if_poisoned(self, booster=None, snap=None) -> None:
        """policy=raise drain: materialize parked counts (the caller
        sits at a deliberate sync point already).  Restores the
        booster to the pre-iteration ``snap`` (GBDT.snapshot_state)
        first: a subtract-style rollback cannot work here — the NaN
        already added into the score buffers would survive the
        subtraction (NaN - NaN = NaN) and poison every later gradient.
        A caller that catches the error therefore holds a genuinely
        clean pre-iteration state."""
        if self.policy != "raise" or not self._pending:
            return
        telemetry.host_sync()
        counts = [int(v) for v in jax.device_get(self._pending)]
        self._pending.clear()
        bad = sum(counts)
        if bad:
            telemetry.count("nonfinite_grad_events")
            flightrec.record("guard_trip", policy="raise",
                             nonfinite=int(bad))
            if booster is not None and snap is not None:
                booster.restore_state(snap)
            raise NonFiniteError(
                f"{bad} non-finite gradient/hessian/leaf values this "
                "iteration (nonfinite_policy=raise). The booster was "
                "restored to its exact pre-iteration state. Check the "
                "input data (strict_data=true surfaces bad rows at load "
                "time) or train with nonfinite_policy=skip_tree|clip to "
                "degrade gracefully instead.")

    def _drain_clip(self, limit: int = 0) -> None:
        if self.policy != "clip" or len(self._pending) <= limit:
            return
        telemetry.host_sync()
        n = sum(int(v) for v in jax.device_get(self._pending))
        self._pending.clear()
        if n:
            self._clipped_total += n
            telemetry.count("nonfinite_values_clipped", n)
            telemetry.count("nonfinite_grad_events")
            Log.warning(
                f"clipped {n} non-finite gradient/hessian/leaf values "
                "(nonfinite_policy=clip)")

    def finalize(self) -> None:
        """End-of-training / checkpoint drain for the lazy policies."""
        self._drain_clip()
        # raise-policy leftovers are materialized WITHOUT raising a
        # booster rollback (training is over; the caller gets the error)
        if self.policy == "raise" and self._pending:
            self.raise_if_poisoned(None)

    # ------------------------------------------------------ checkpointing
    def state_dict(self) -> dict:
        self._drain_clip()
        return {"policy": self.policy,
                "clipped_total": int(self._clipped_total)}

    def load_state_dict(self, d: dict) -> None:
        self._clipped_total = int(d.get("clipped_total", 0))


def make_guard(policy: str) -> Optional[NonFiniteGuard]:
    return None if policy in (None, "", "off") else NonFiniteGuard(policy)
