"""Objective functions: per-row gradients/hessians, jitted.

Each objective re-expresses its reference counterpart
(src/objective/*.hpp) as a vectorized function
``(scores, label, weights) -> (grad, hess)`` suitable for jit/shard_map.
Scores are class-major ``[num_class, n]`` for multiclass (matching the
reference's ``curr_class * num_data_`` offsets, gbdt.cpp:226-244) and
``[n]`` otherwise.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


class ObjectiveFunction:
    """Base: mirrors ObjectiveFunction (objective_function.h:13-49)."""

    name = "none"
    num_class = 1
    # sigmoid parameter used by prediction transform (-1 = no transform)
    sigmoid = -1.0

    def init(self, metadata, num_data: int) -> None:
        self.label = jnp.asarray(metadata.label, jnp.float32)
        self.weights = (
            None
            if metadata.weights is None
            else jnp.asarray(metadata.weights, jnp.float32)
        )
        self.num_data = num_data

    def get_gradients(self, scores: jax.Array):
        raise NotImplementedError


class RegressionL2(ObjectiveFunction):
    """L2 regression: g = score - label, h = 1 (x weight)
    (regression_objective.hpp:24-39)."""

    name = "regression"

    def get_gradients(self, scores):
        return _l2_grads(scores, self.label, self.weights)


@jax.jit
def _l2_grads(score, label, weights):
    g = score - label
    h = jnp.ones_like(score)
    if weights is not None:
        g, h = g * weights, h * weights
    return g, h


class BinaryLogloss(ObjectiveFunction):
    """Binary logloss on labels {0,1} -> {-1,+1}
    (binary_objective.hpp:62-88): response = -2*l*sig / (1 + exp(2*l*sig*s));
    hess = |r| * (2*sig - |r|).  Supports is_unbalance and scale_pos_weight
    class weights (binary_objective.hpp:40-59)."""

    name = "binary"

    def __init__(self, config):
        if config.sigmoid <= 0:
            raise ValueError("sigmoid parameter must be > 0")
        self.sigmoid = float(config.sigmoid)
        self.is_unbalance = bool(config.is_unbalance)
        self.scale_pos_weight = float(config.scale_pos_weight)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.asarray(metadata.label)
        cnt_pos = int((lab == 1).sum())
        cnt_neg = int(num_data - cnt_pos)
        if cnt_pos == 0 or cnt_neg == 0:
            raise ValueError("Training data only contains one class")
        w_neg, w_pos = 1.0, 1.0
        if self.is_unbalance:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.scale_pos_weight
        self._label_weight = (float(w_neg), float(w_pos))

    def get_gradients(self, scores):
        return _binary_grads(
            scores,
            self.label,
            self.weights,
            jnp.float32(self.sigmoid),
            jnp.float32(self._label_weight[0]),
            jnp.float32(self._label_weight[1]),
        )


@jax.jit
def _binary_grads(score, label, weights, sigmoid, w_neg, w_pos):
    is_pos = label > 0
    sign = jnp.where(is_pos, 1.0, -1.0)
    lw = jnp.where(is_pos, w_pos, w_neg)
    response = -2.0 * sign * sigmoid / (1.0 + jnp.exp(2.0 * sign * sigmoid * score))
    abs_r = jnp.abs(response)
    g = response * lw
    h = abs_r * (2.0 * sigmoid - abs_r) * lw
    if weights is not None:
        g, h = g * weights, h * weights
    return g, h


class MulticlassSoftmax(ObjectiveFunction):
    """Softmax multiclass (multiclass_objective.hpp:13-94): scores are
    [K, n]; g = p - 1{y=k}, h = 2 p (1-p)."""

    name = "multiclass"

    def __init__(self, config):
        self.num_class = int(config.num_class)
        if self.num_class <= 1:
            raise ValueError("multiclass objective needs num_class > 1")

    def get_gradients(self, scores):
        return _multiclass_grads(scores, self.label, self.weights)


@jax.jit
def _multiclass_grads(scores, label, weights):
    # scores [K, n]
    p = jax.nn.softmax(scores, axis=0)
    onehot = (label[None, :] == jnp.arange(scores.shape[0])[:, None]).astype(
        jnp.float32
    )
    g = p - onehot
    h = 2.0 * p * (1.0 - p)
    if weights is not None:
        g, h = g * weights[None, :], h * weights[None, :]
    return g, h


def create_objective(config, metadata=None, num_data: Optional[int] = None):
    """Factory (objective_function.cpp:9-20).  lambdarank lives in
    objectives_rank.py to keep the NDCG machinery together."""
    name = config.objective
    if name in ("regression", "regression_l2", "mean_squared_error", "mse", "l2"):
        obj = RegressionL2()
    elif name == "binary":
        obj = BinaryLogloss(config)
    elif name in ("multiclass", "softmax"):
        obj = MulticlassSoftmax(config)
    elif name == "lambdarank":
        from .objectives_rank import LambdarankNDCG

        obj = LambdarankNDCG(config)
    else:
        raise ValueError(f"Unknown objective: {name!r}")
    if metadata is not None:
        obj.init(metadata, num_data if num_data is not None else len(metadata.label))
    return obj
