"""Serial (single-device) leaf-wise tree learner, fully jittable.

TPU-native re-design of SerialTreeLearner
(src/treelearner/serial_tree_learner.cpp:116-150): the same best-first
growth — repeatedly split the leaf with the globally best gain until the
``num_leaves`` budget or no positive gain remains — expressed as a
fixed-shape ``lax.fori_loop``:

* the row partition is a PERSISTENT leaf-sorted permutation ``order``
  plus per-leaf ``(begin, count)`` ranges — the reference's
  DataPartition (data_partition.hpp:91-139) re-cast for static shapes.
  Each split touches only the parent leaf's contiguous range via
  capacity-tiered ``dynamic_slice`` (a ``lax.cond`` chain picks the
  smallest static capacity that fits), so per-split work is
  O(|parent|), not O(n): the whole tree costs O(n * depth) partition
  work like the reference, instead of O(n * num_leaves).
* per split, only the SMALLER child's histogram is built from data —
  its rows are one contiguous ``dynamic_slice`` of ``order`` (the
  ordered-gradients gather, serial_tree_learner.cpp:259-315); the
  larger child is parent - smaller (the Subtract trick,
  feature_histogram.hpp:97-106).  Histograms for every live leaf stay
  resident in HBM (``hists[L, F, B, 3]``) — the LRU HistogramPool
  (feature_histogram.hpp:337-481) is unnecessary at TPU memory sizes.
* leaf numbering matches the reference exactly (left child keeps the
  parent's leaf index, right child gets the next fresh index,
  tree.cpp:78-89), so trees are comparable node-for-node.
* every store in the split step is MASKED on the split-fired predicate
  (rather than branching with ``lax.cond``, whose pass-through branch
  forced XLA to copy the histogram buffer each iteration), so all state
  updates stay in place and an exhausted tree simply no-ops its
  remaining steps.

The data-parallel learner wraps this same step with psum'd histograms
(parallel/data_parallel.py); determinism of argmax tie-breaks keeps
parallel == serial trees (split_info.hpp:98-103 semantics).
"""

from __future__ import annotations

import functools
import os as _os
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

# Read ONCE at import (like ops.record.TILE): grow_tree reads this at
# trace time but the jit cache keys only on static args, so a mid-process
# env flip would silently not apply to already-traced shapes (ADVICE r3).
_KERN_ENV = _os.environ.get("LGBM_TPU_SEARCH_KERNEL", "pallas") != "jnp"
_FUSE_HIST_ENV = _os.environ.get("LGBM_TPU_FUSE_HIST", "1") != "0"
# direct in-kernel placement (ops/record.py place_runs): replaces the
# XLA scan-of-DUS + roll/merge chain and the full-record tier-cond copy.
# Chip-validated by tools/tpu_parity_check.py (1M: 0.473 -> 0.399
# s/tree); interpret mode uses the bit-identical XLA fallback.
_DIRECT_PLACE_ENV = _os.environ.get("LGBM_TPU_DIRECT_PLACE", "1") != "0"
# geometric step between hist/partition tier capacities (see
# _hist_tiers); read ONCE at import like every other kernel knob — a
# trace-time read bakes the value per trace while the jit cache keys
# only on static args, so a mid-process env flip silently applied to
# SOME shapes and not others (jaxlint env-read-at-trace)
_TIER_SPACING_ENV = max(
    2, int(_os.environ.get("LGBM_TPU_TIER_SPACING", "2")))

from ..models.tree import Tree
from ..obs import telemetry
from ..ops.histogram import histogram_by_leaf, histogram_feature_major
from ..ops.split import (
    SplitResult, find_best_split, find_best_split_leaves, K_MIN_SCORE)


# leaf_count/internal_count ride the histogram count channel, which is
# float32 under the default hist_dtype: integers are exact in float32
# only up to 2**24, so a single leaf holding more than ~16.7M rows
# would silently round its count (and the min_data_in_leaf comparisons
# on it).  Row count bounds every leaf count, so the envelope is
# checked once per reset_training_data against n (ADVICE r5).
F32_COUNT_EXACT_ROWS = 1 << 24


def check_count_envelope(num_rows: int, hist_dtype: str) -> None:
    """Reject datasets whose row count can overflow the float32
    integer-exact range in the count channel."""
    if hist_dtype == "float32" and num_rows > F32_COUNT_EXACT_ROWS:
        raise ValueError(
            f"num_data={num_rows} exceeds the float32 integer-exact "
            f"envelope ({F32_COUNT_EXACT_ROWS} = 2**24) for the "
            "histogram count channel: leaf_count/internal_count could "
            "round silently.  Set hist_dtype=float64 (the reference's "
            "double accumulation) for datasets this large.")


class TreeLearnerParams(NamedTuple):
    """Scalar tree-growth constraints (TreeConfig, config.h:165-190)."""

    min_data_in_leaf: jax.Array
    min_sum_hessian_in_leaf: jax.Array
    lambda_l1: jax.Array
    lambda_l2: jax.Array
    min_gain_to_split: jax.Array
    max_depth: jax.Array  # <= 0 means unlimited

    @staticmethod
    def from_config(cfg) -> "TreeLearnerParams":
        return TreeLearnerParams(
            min_data_in_leaf=jnp.float32(cfg.min_data_in_leaf),
            min_sum_hessian_in_leaf=jnp.float32(cfg.min_sum_hessian_in_leaf),
            lambda_l1=jnp.float32(cfg.lambda_l1),
            lambda_l2=jnp.float32(cfg.lambda_l2),
            min_gain_to_split=jnp.float32(cfg.min_gain_to_split),
            max_depth=jnp.int32(cfg.max_depth),
        )


class _GrowState(NamedTuple):
    """Loop carry of the best-first growth.  All per-leaf scalar state is
    PACKED into a few [rows, L] matrices so one split updates two
    matrix COLUMNS instead of ~60 individual [L] arrays — the round-5
    profile at the 100k/63-leaf shape showed HALF the device time was
    per-op launch gaps from the unpacked representation's ~100 tiny
    dynamic-slice/DUS/select ops per split."""

    order: jax.Array  # [n + max_cap] leaf-sorted row permutation (pad = n)
    pos_mat: jax.Array  # [3, L] i32 rows: (leaf_begin, pos_cnt, gate_cnt)
    hists: jax.Array  # [L, F, B, 3] resident, or [P, F, B, 3] pooled
    slot_of: jax.Array  # [L] int32 pool slot per leaf, -1 = evicted ([0] off)
    slot_leaf: jax.Array  # [P] int32 leaf occupying each slot, -1 = free
    slot_last: jax.Array  # [P] int32 last-use step per slot, -1 = free
    best_mat: jax.Array  # [16, L] acc_dt — see _B* row constants
    tree_i: jax.Array  # [5, L] i32 node table: feat, thr, dtype, lch, rch
    tree_f: jax.Array  # [3, L] f32 node table: gain, int_value, int_count
    nleaves: jax.Array  # scalar int32 used-leaf count


# best_mat row indices.  Rows 0-10 are EXACTLY the Pallas search
# kernels' packed [2, 16] result layout (ops/pallas_search._unpack), so
# a kernel result row drops into a best_mat column unchanged; rows
# 11-14 carry the per-leaf half of the Tree so the same two column
# writes cover split state AND leaf bookkeeping.  Feature/threshold/
# counts ride as floats — exact to 2^24, the same envelope the f32
# kernel result already imposes.
_BG, _BF, _BT = 0, 1, 2
_BLSG, _BLSH, _BLC = 3, 4, 5
_BRSG, _BRSH, _BRC = 6, 7, 8
_BLO, _BRO = 9, 10
_BLV, _BLCNT, _BLPAR, _BLDEP = 11, 12, 13, 14
_BROWS = 16


def _sr_row(sr: SplitResult, dt):
    """SplitResult -> kernel-result row layout [11(, L)]."""
    return jnp.stack([
        sr.gain.astype(dt), sr.feature.astype(dt), sr.threshold.astype(dt),
        sr.left_sum_grad.astype(dt), sr.left_sum_hess.astype(dt),
        sr.left_count.astype(dt),
        sr.right_sum_grad.astype(dt), sr.right_sum_hess.astype(dt),
        sr.right_count.astype(dt),
        sr.left_output.astype(dt), sr.right_output.astype(dt),
    ])


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _hist_tiers(n: int):
    """Static slice capacities for the smaller-child histogram: fractions
    of n, lane-aligned, ascending.  Includes a full-n tier: under row
    sharding the LOCAL count of the globally-smaller child can approach
    n_local (global balance says nothing about one shard's split), so
    ceil(n/2) is not a guaranteed fit there.

    LGBM_TPU_TIER_SPACING (read ONCE at import, see _TIER_SPACING_ENV;
    default 2) sets the geometric step between capacities: 2 wastes
    <2x gather work per split but instantiates ~9 tier bodies (one
    Mosaic kernel compile each on TPU); 4 halves the tier count for
    <4x gather waste.  Measured XLA:CPU compile at n=1M, L=255, B=255
    (segment hist): spacing=2 (9 tiers) 9.5s, spacing=4 (5 tiers)
    13.8s — tier count is NOT the compile bottleneck off-TPU; the knob
    exists for the Mosaic per-kernel compile path."""
    step = _TIER_SPACING_ENV
    caps = {max(512, _round_up(n, 128))}
    frac = 2
    while frac <= 256:  # step=2 reproduces the original 2,4,...,256 set
        caps.add(max(512, _round_up(-(-n // frac), 128)))
        frac *= step
    return tuple(sorted(caps))


def _part_tiers(n: int):
    """Capacities for the parent-range partition slice (the root split
    spans every row; _hist_tiers already tops out at full n)."""
    return _hist_tiers(n)


def _tier_chain(caps, gate_cnt, branch_fn):
    """Run ``branch_fn(cap)`` for the smallest static cap >= gate_cnt.
    ``caps`` must be ascending with its largest entry a guaranteed fit."""
    fn = lambda _: branch_fn(caps[-1])  # noqa: E731 — guaranteed fallback
    for cap in sorted(caps[:-1], reverse=True):
        def tiered(_, cap=cap, nxt=fn):
            return jax.lax.cond(
                gate_cnt <= cap, lambda __: branch_fn(cap), nxt, None
            )

        fn = tiered
    return fn(None)


def _go_i32(fv, thr, is_cat):
    """Left-going decision as i32 WITHOUT a bool intermediate: [cap]-ish
    pred tensors bounce between bit layouts on this stack (round-3
    measured ~80-100 ms/tree of pure copies at 1M rows)."""
    isc = is_cat.astype(jnp.int32)
    return isc * (fv == thr).astype(jnp.int32) + (1 - isc) * (
        fv <= thr).astype(jnp.int32)


def _partition_branch(order, bins_T, f, thr, is_cat, begin, pcnt, do_split, cap):
    """Stably partition the parent's [begin, begin+pcnt) range of
    ``order`` by the split decision (DataPartition::Split,
    data_partition.hpp:91-139): left-going rows keep their relative
    order at the front, right-going rows follow.  Positions past pcnt
    (other leaves' rows inside the static cap window) are written back
    unchanged.  Returns (order, nleft)."""
    n = bins_T.shape[1]
    rows_p = jax.lax.dynamic_slice(order, (begin,), (cap,))
    validp = jnp.arange(cap, dtype=jnp.int32) < pcnt
    rows_c = jnp.minimum(rows_p, n - 1)
    frow = jax.lax.dynamic_index_in_dim(bins_T, f, axis=0, keepdims=False)
    vals = frow[rows_c].astype(jnp.int32)
    go = jnp.where(is_cat, vals == thr, vals <= thr) & validp
    # dtype pinned: under jax_enable_x64 (hist_dtype=float64) a plain sum
    # promotes to int64 and the int32 leaf_begin/pos_cnt scatters become
    # unsafe casts
    nleft = jnp.sum(go, dtype=jnp.int32)
    lpos = jnp.cumsum(go.astype(jnp.int32)) - 1
    rpos = nleft + jnp.cumsum((validp & ~go).astype(jnp.int32)) - 1
    # invalid positions get DISTINCT out-of-bounds indices (cap + j):
    # unique_indices promises every index distinct, and mode="drop"
    # discards all of them
    newpos = jnp.where(
        go,
        lpos,
        jnp.where(validp, rpos, cap + jnp.arange(cap, dtype=jnp.int32)),
    )
    buf = rows_p.at[newpos].set(rows_p, mode="drop", unique_indices=True)
    out = jnp.where(do_split, buf, rows_p)
    return jax.lax.dynamic_update_slice(order, out, (begin,)), nleft


def _child_hist_branch(hist_fn, order, bins_T, grad, hess, bag_mask,
                       begin_s, cnt_s, cap):
    """Histogram of one child from its contiguous ``order`` range: slice
    the row ids, gather bins/grad/hess, mask rows past cnt_s and
    unbagged rows, and run the histogram kernel over the capped buffer
    only (the ordered-gradients gather, serial_tree_learner.cpp:283-315)."""
    n = grad.shape[0]
    rows = jax.lax.dynamic_slice(order, (begin_s,), (cap,))
    valid = jnp.arange(cap, dtype=jnp.int32) < cnt_s
    rows_c = jnp.minimum(rows, n - 1)
    sub = jnp.take(bins_T, rows_c, axis=1)
    m = valid.astype(grad.dtype) * bag_mask[rows_c]
    return hist_fn(sub, grad[rows_c], hess[rows_c], m)


def default_search_fn(
    hist, sum_grad, sum_hess, count, can_split,
    feature_mask, num_bins_per_feature, is_categorical, params,
):
    """Local split search over the full feature set (the serial learner's
    FindBestThresholds).  Parallel learners substitute variants that search
    a feature shard and combine across the mesh."""
    return find_best_split(
        hist,
        sum_grad,
        sum_hess,
        count,
        feature_mask,
        num_bins_per_feature,
        is_categorical,
        params.min_data_in_leaf,
        params.min_sum_hessian_in_leaf,
        params.lambda_l1,
        params.lambda_l2,
        params.min_gain_to_split,
        can_split,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_bins", "max_leaves", "hist_fn", "reduce_fn", "search_fn",
        "reduce_max_fn", "child_counts_fn", "search2_fn", "hist_pool",
        "init_hist_fn", "init_search_fn", "hist_fn_raw", "record_mode",
        "choice_by_mask_counts",
    ),
)
def grow_tree(
    bins_T: jax.Array,  # [F, n] feature-major binned matrix
    grad: jax.Array,  # [n]
    hess: jax.Array,  # [n]
    bag_mask: jax.Array,  # [n] 0/1 bagging mask
    feature_mask: jax.Array,  # [F] bool, feature_fraction sample
    num_bins_per_feature: jax.Array,  # [F] int32
    is_categorical: jax.Array,  # [F] bool
    params: TreeLearnerParams,
    num_bins: int,
    max_leaves: int,
    hist_fn=None,
    reduce_fn=None,
    search_fn=None,
    reduce_max_fn=None,
    child_counts_fn=None,
    search2_fn=None,
    hist_pool: int = 0,
    init_tree=None,
    init_leaf_id=None,
    init_hist_fn=None,
    init_search_fn=None,
    hist_fn_raw=None,
    record_mode: bool = False,
    choice_by_mask_counts: bool = False,
) -> Tuple[Tree, jax.Array]:
    """Grow one tree; returns (tree, final leaf_id per row).

    ``hist_fn(bins_T, grad, hess, mask) -> [F, B, 3]`` abstracts histogram
    construction so the data-parallel learner can psum across the mesh;
    default is the local kernel.  ``reduce_fn`` (cross-device sum) is
    applied to the root (Σg, Σh, count) scalars — the analog of the
    data-parallel learner's tree-start allreduce
    (data_parallel_tree_learner.cpp:97-125).

    Per-split cross-device traffic is concentrated in two hooks so a
    parallel learner pays the minimum collective count per split:

    * ``child_counts_fn(nleft, nright) -> (sum_l, sum_r, max_l, max_r)``
      reduces the two children's LOCAL positional counts once — the sums
      pick the globally smaller child (whose histogram partials the mesh
      reduces), the maxes feed the static-capacity tier gates of BOTH
      later splits of these leaves (stored in ``pos_mat`` row 2, so no
      per-split pmax is needed at consume time).  Default: local values
      through ``reduce_fn``/``reduce_max_fn`` when given, else identity.
    * ``search2_fn(h_left, h_right, lsg, lsh, lc, rsg, rsh, rc, can,
      feature_mask, nbpf, is_cat, params) -> (SplitResult, SplitResult)``
      searches BOTH children in one go so a sharded-search learner can
      combine the two results in a single all_gather.  Default: two
      ``search_fn`` calls.

    ``init_tree``/``init_leaf_id`` resume best-first growth from an
    existing partial tree (the hybrid growth mode, learners/hybrid.py):
    the persistent partition is rebuilt from the row->leaf map, per-leaf
    histograms come from one fused pass (``init_hist_fn``, the depthwise
    level kernel), and the loop continues numbering nodes from
    ``init_tree.num_leaves - 1``.  Sharded learners resume too:
    ``init_search_fn`` searches the fused histogram's feature shard and
    combines, ``reduce_max_fn`` lifts the rebuilt positional counts to
    cross-shard tier gates.  Exclusive with ``hist_pool``.

    ``hist_pool`` bounds histogram HBM: when ``2 <= hist_pool <
    max_leaves`` only that many leaf histograms stay resident
    (``[P, F, B, 3]``) under an LRU policy, and a split whose parent was
    evicted RECOMPUTES the parent histogram from the leaf's contiguous
    ``order`` range — the reference's HistogramPool
    (feature_histogram.hpp:337-481, serial_tree_learner.cpp:25-32)
    re-cast for static shapes.  ``0`` (default) keeps every leaf
    resident.
    """
    # Python here runs once per TRACE, so this counts grow-program
    # retraces exactly (obs: a timed loop whose grow_traces counter
    # moves is re-tracing — the same hazard the bench warm-up gate and
    # the steady-loop recompile test watch from the compile side)
    telemetry.count("grow_traces")
    F, n = bins_T.shape
    L = max_leaves
    h_tiers = _hist_tiers(n)
    p_tiers = _part_tiers(n)
    order_pad = max(p_tiers + h_tiers)

    if hist_fn is None:
        hist_fn = functools.partial(histogram_feature_major, num_bins=num_bins)
    # ---- opt mode: the whole split step stays in the histogram
    # kernel's NATIVE [Fp, 4, Bp] layout (raw hist kernel -> subtract ->
    # raw search kernel), eliminating the per-split layout-churn fusions
    # the round-3 profile showed radiating from the [F, B, 3] transpose
    # (~0.5 ms/split).  Only the default serial hook set qualifies;
    # parallel learners and the hybrid resume keep the canonical layout.
    _kern_env = _KERN_ENV
    _interp = jax.default_backend() != "tpu"
    opt = (
        hist_fn_raw is not None
        and search_fn is None
        and search2_fn is None
        and init_tree is None
        and grad.dtype == jnp.float32
        # the raw layout REQUIRES the raw search kernel, so the
        # LGBM_TPU_SEARCH_KERNEL=jnp escape hatch disables opt wholesale
        and _kern_env
    )
    # fused split step (subtract + search + in-place buffer update in
    # one launch) — unpooled only: the left child reuses the parent's
    # buffer row
    opt_fused = opt and not (0 < hist_pool < max_leaves)
    if choice_by_mask_counts and opt:
        # the raw-layout fused kernels pick the small child positionally
        # INSIDE the launch; callers that set a base row mask (cv
        # bin-once) are gated to the canonical path before reaching here
        raise NotImplementedError(
            "choice_by_mask_counts requires the canonical (non-raw-"
            "kernel) grow path"
        )
    # ``record_mode``: PARALLEL learners (search hooks present) opt into
    # the leaf-sorted packed-record partition — the round-3/4 fast path
    # was previously serial-only, leaving every distributed run on the
    # per-index-gather partition (VERDICT r4 item 1; the reference's
    # parallel learners inherit the serial hot loop,
    # parallel_tree_learner.h:46-90).  Histograms of a child's window
    # still flow through ``hist_fn`` (which reduce-scatters across the
    # mesh) and searches through the hooks; only the partition and the
    # contiguous-window child access change.
    rec_hooks = (
        record_mode
        and not opt
        and grad.dtype == jnp.float32
        and init_tree is None
        and not (0 < hist_pool < max_leaves)
    )
    rec = opt_fused or rec_hooks
    fuse_hist = False  # set below when the record path qualifies
    if search_fn is None:
        search_fn = default_search_fn
        if search2_fn is None:
            use_kernel = jax.default_backend() == "tpu" and _kern_env

            def search2_fn(hl, hr, lsg, lsh, lc, rsg, rsh, rc, can,
                           fmask, nbpf, is_cat, prm):
                # TPU: the whole two-child search is ONE Pallas launch
                # (ops/pallas_search.py) — the round-3 profile showed
                # the jnp search compiling to ~60 small fusions per
                # split (~1.6 ms, 4x the histogram kernel), all per-op
                # overhead no jnp restructuring removes.  The jnp path
                # stays the reference implementation (CPU, float64).
                if opt:
                    from ..ops.pallas_search import search2_pallas_raw

                    return search2_pallas_raw(
                        jnp.stack([hl, hr]),
                        lsg, lsh, lc, rsg, rsh, rc, can,
                        fmask, nbpf, is_cat,
                        prm.min_data_in_leaf,
                        prm.min_sum_hessian_in_leaf,
                        prm.lambda_l1, prm.lambda_l2,
                        prm.min_gain_to_split,
                        interpret=_interp,
                    )
                if use_kernel and hl.dtype == jnp.float32:
                    from ..ops.pallas_search import search2_pallas

                    return search2_pallas(
                        hl, hr, lsg, lsh, lc, rsg, rsh, rc, can,
                        fmask, nbpf, is_cat,
                        prm.min_data_in_leaf,
                        prm.min_sum_hessian_in_leaf,
                        prm.lambda_l1, prm.lambda_l2,
                        prm.min_gain_to_split,
                    )
                res = find_best_split_leaves(
                    jnp.stack([hl, hr]),
                    jnp.stack([lsg, rsg]),
                    jnp.stack([lsh, rsh]),
                    jnp.stack([lc, rc]),
                    fmask, nbpf, is_cat,
                    prm.min_data_in_leaf, prm.min_sum_hessian_in_leaf,
                    prm.lambda_l1, prm.lambda_l2, prm.min_gain_to_split,
                    jnp.stack([can, can]),
                )
                return (
                    SplitResult(*[a[0] for a in res]),
                    SplitResult(*[a[1] for a in res]),
                )
    if opt:
        # every in-loop histogram (children + pooled parent recompute)
        # is built in the raw layout
        hist_fn = hist_fn_raw
    if rec:
        # record mode: the loop state carries the leaf-sorted PACKED
        # RECORD [W, n_pad] (ops/record.py) instead of the row
        # permutation — every per-split access becomes a contiguous
        # slice and the partition runs as the MXU block-compaction
        # kernel.  The round-3 profile showed the order-based path's
        # per-index gathers/scatters costing ~0.4 s/tree at 1M rows.
        from ..ops.record import (
            TILE as _REC_TILE,
            bins_per_word, build_record, extract_feature, num_words,
            partition_window, place_runs, rec_height,
            split_step_window, unpack_window,
        )

        k_pack = bins_per_word(bins_T.dtype)
        Wrec = rec_height(F, k_pack)
        _row_id_row = num_words(F, k_pack) + 3
        _leaf_row = num_words(F, k_pack) + 4
        bin_dt = bins_T.dtype
        h_tiers = tuple(sorted({_round_up(c, _REC_TILE) for c in h_tiers}))
        p_tiers = tuple(sorted({_round_up(c, _REC_TILE) for c in p_tiers}))
        order_pad = max(p_tiers + h_tiers)
    if opt_fused:
        from ..ops.pallas_histogram import FGROUP as _FGROUP
        from ..ops.pallas_search import (
            _pack_meta as _search_pack_meta,
            _pack_scal as _search_pack_scal,
        )
        # mega split-step kernel (ops/record.py split_step_window):
        # compaction + LEFT-child histogram + both searches + in-place
        # buffer updates in ONE launch, dropping the separate
        # smaller-child histogram launch and its whole h_tier cond
        # chain.  Gated on the hist block fitting comfortably in VMEM
        # next to the routing matrices.
        _Bp = _round_up(num_bins, 128)
        _Fp = _round_up(F, _FGROUP)
        # LGBM_TPU_FUSE_HIST=0 is the A/B escape hatch (read at import
        # like the other kernel knobs — see _KERN_ENV)
        # VMEM gate, routing-dependent.  onehot: at Fp=248/Bp=256 (a
        # one-hot categorical bench shape) the mega kernel's scoped
        # VMEM measured 16.16M against the 16M limit — the hist block
        # must stay well clear of the ~12MB routing matrices + search
        # temporaries, so cap it at 512KB (Fp*Bp*16B); wider shapes
        # take the 2-kernel path.  prefix: the routing matrices are
        # gone (the compress network's temporaries are [W+1, TILE]
        # rows, ~KBs), so the gate loosens to 4MB and shapes like
        # Fp=248/Bp=256 (1.0MB) keep the one-launch split step.
        from ..ops.record import ROUTING as _REC_ROUTING

        _vmem_cap = (1 << 22) if _REC_ROUTING == "prefix" else (1 << 19)
        fuse_hist = _FUSE_HIST_ENV and _Fp * _Bp * 16 <= _vmem_cap
        direct_place = fuse_hist and _DIRECT_PLACE_ENV
        if fuse_hist:
            # constant per tree: the search kernel's [Fp, 4] meta block
            _mega_meta = _search_pack_meta(
                feature_mask, num_bins_per_feature, is_categorical, _Fp)
    if child_counts_fn is None:
        _sum = (lambda x: x) if reduce_fn is None else reduce_fn
        _max = (lambda x: x) if reduce_max_fn is None else reduce_max_fn

        def child_counts_fn(nl, nr):
            return _sum(nl), _sum(nr), _max(nl), _max(nr)

    def best_for(hist, sg, sh, c, depth_child):
        can = (params.max_depth <= 0) | (depth_child < params.max_depth)
        return search_fn(
            hist, sg, sh, c, can,
            feature_mask, num_bins_per_feature, is_categorical, params,
        )

    def best2_for(hl, hr, lsg, lsh, lc, rsg, rsh, rc, depth_child):
        can = (params.max_depth <= 0) | (depth_child < params.max_depth)
        if search2_fn is not None:
            return search2_fn(
                hl, hr, lsg, lsh, lc, rsg, rsh, rc, can,
                feature_mask, num_bins_per_feature, is_categorical, params,
            )
        return (
            search_fn(hl, lsg, lsh, lc, can,
                      feature_mask, num_bins_per_feature, is_categorical,
                      params),
            search_fn(hr, rsg, rsh, rc, can,
                      feature_mask, num_bins_per_feature, is_categorical,
                      params),
        )

    if init_tree is None:
        # ---- root (BeforeTrain / LeafSplits::Init, leaf_splits.hpp:51-92)
        hist0 = hist_fn(bins_T, grad, hess, bag_mask)
        # root Σg/Σh via a ONE-segment segment-sum, not jnp.sum: scatter
        # accumulates per row in order, so a masked-out row adds an exact
        # ±0.0 that never perturbs the accumulator.  jnp.sum's reduction
        # tree regroups with n, making the root sums depend on how many
        # DEAD rows ride along — which would break the base-row-mask
        # parity contract (cv bin-once trains fold boosters on the full
        # matrix and pins their metrics bitwise to subset-trained ones)
        # and the batched forest grower's stacked-vs-loop parity pin.
        # cnt0 stays jnp.sum: counts are exact small integers in any
        # grouping.
        gh0 = jax.ops.segment_sum(
            jnp.stack([grad * bag_mask, hess * bag_mask], axis=-1),
            jnp.zeros(grad.shape[0], jnp.int32),
            num_segments=1,
        )[0]
        sum_g0, sum_h0 = gh0[0], gh0[1]
        cnt0 = jnp.sum(bag_mask)
        if reduce_fn is not None:
            # one stacked collective for the tree-start allreduce
            s = reduce_fn(jnp.stack([sum_g0, sum_h0, cnt0]))
            sum_g0, sum_h0, cnt0 = s[0], s[1], s[2]
        # hist0's feature extent may be a shard of F (feature-parallel
        # learner); accumulation dtype follows grad/hess — float64 when
        # Config.hist_dtype asks for the reference's double accumulation
        # (include/LightGBM/bin.h:21-22)
        acc_dt = hist0.dtype
    else:
        acc_dt = jnp.promote_types(grad.dtype, jnp.float32)
    pooled = 0 < hist_pool < L
    P = max(hist_pool, 2) if pooled else L
    if init_tree is not None:
        assert not pooled, "init_tree resume is unpooled"
        K0 = init_tree.num_leaves.astype(jnp.int32)
        lid = init_leaf_id.astype(jnp.int32)
        # leaf-sorted permutation + contiguous per-leaf ranges from the
        # row->leaf map (stable: preserves row order within a leaf);
        # under row sharding these are LOCAL ranges, while the fused
        # histogram/search below see GLOBAL stats through the hooks
        order0 = jnp.argsort(lid, stable=True).astype(jnp.int32)
        counts = jnp.zeros(L, jnp.int32).at[lid].add(1)
        begin0 = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)]
        )
        gate0 = counts if reduce_max_fn is None else reduce_max_fn(counts)
        # every live leaf's histogram in ONE fused pass, through the same
        # level-histogram kernel the depthwise phase used (the Pallas MXU
        # sorted kernel on TPU; init_hist_fn has the depthwise hist_fn
        # signature)
        if init_hist_fn is None:
            fused = histogram_by_leaf(
                bins_T, lid, grad, hess, bag_mask,
                num_bins=num_bins, num_leaves=L,
            ).astype(acc_dt)
        else:
            fused = init_hist_fn(
                bins_T, lid, grad, hess, bag_mask, L
            ).astype(acc_dt)
        leaf_tot = jnp.sum(fused[:, 0, :, :], axis=1)  # [L, 3]
        live = jnp.arange(L, dtype=jnp.int32) < K0
        can0 = live & (
            (params.max_depth <= 0)
            | (init_tree.leaf_depth < params.max_depth)
        )
        if init_search_fn is not None:
            # sharded-search learners search their feature shard of the
            # fused histogram and combine winners in one collective
            best0 = init_search_fn(
                fused, leaf_tot[:, 0], leaf_tot[:, 1], leaf_tot[:, 2],
                can0, feature_mask, num_bins_per_feature, is_categorical,
                params,
            )
        else:
            best0 = find_best_split_leaves(
                fused, leaf_tot[:, 0], leaf_tot[:, 1], leaf_tot[:, 2],
                feature_mask, num_bins_per_feature, is_categorical,
                params.min_data_in_leaf, params.min_sum_hessian_in_leaf,
                params.lambda_l1, params.lambda_l2, params.min_gain_to_split,
                can0,
            )
        _pad1 = lambda a: jnp.concatenate(  # noqa: E731
            [a, jnp.zeros(1, a.dtype)])
        state = _GrowState(
            order=jnp.concatenate(
                [order0, jnp.full(order_pad, n, jnp.int32)]
            ),
            pos_mat=jnp.stack([begin0, counts, gate0]),
            hists=fused,
            slot_of=jnp.zeros(0, jnp.int32),
            slot_leaf=jnp.zeros(0, jnp.int32),
            slot_last=jnp.zeros(0, jnp.int32),
            best_mat=jnp.concatenate([
                _sr_row(best0, acc_dt),
                init_tree.leaf_value[None].astype(acc_dt),
                init_tree.leaf_count[None].astype(acc_dt),
                init_tree.leaf_parent[None].astype(acc_dt),
                init_tree.leaf_depth[None].astype(acc_dt),
                jnp.zeros((_BROWS - 15, L), acc_dt),
            ]),
            tree_i=jnp.stack([
                _pad1(init_tree.split_feature),
                _pad1(init_tree.threshold_bin),
                _pad1(init_tree.decision_type),
                _pad1(init_tree.left_child),
                _pad1(init_tree.right_child),
            ]),
            tree_f=jnp.stack([
                _pad1(init_tree.split_gain),
                _pad1(init_tree.internal_value),
                _pad1(init_tree.internal_count),
            ]),
            nleaves=K0,
        )
        start_step = K0 - 1
    else:
        root_best = best_for(
            # raw-layout root histogram -> canonical view for the
            # (once-per-tree) jnp root search
            hist0[:F, :3, :num_bins].transpose(0, 2, 1) if opt else hist0,
            sum_g0, sum_h0, cnt0, jnp.int32(0),
        )
        best_mat0 = (
            jnp.zeros((_BROWS, L), acc_dt)
            .at[_BG].set(K_MIN_SCORE)
            .at[_BF].set(-1.0)
            .at[_BLPAR].set(-1.0)  # empty_tree's leaf_parent = -1
        )
        best_mat0 = jax.lax.dynamic_update_slice(
            best_mat0, _sr_row(root_best, acc_dt)[:, None], (0, 0))
        state = _GrowState(
            # record mode: the "order" leaf carries the [W, n_pad]
            # packed record; otherwise the flat row permutation
            order=build_record(
                bins_T, grad, hess, bag_mask,
                _round_up(n, _REC_TILE) + order_pad,
            )
            if rec
            else jnp.concatenate(
                [
                    jnp.arange(n, dtype=jnp.int32),
                    jnp.full(order_pad, n, jnp.int32),
                ]
            ),
            # root gate: every shard's padded local row count is the
            # same n (rows: leaf_begin, pos_cnt, gate_cnt)
            pos_mat=jnp.zeros((3, L), jnp.int32)
            .at[1, 0].set(n).at[2, 0].set(n),
            hists=jnp.zeros((P,) + hist0.shape, acc_dt).at[0].set(hist0),
            slot_of=(jnp.full(L, -1, jnp.int32).at[0].set(0) if pooled
                     else jnp.zeros(0, jnp.int32)),
            slot_leaf=(jnp.full(P, -1, jnp.int32).at[0].set(0) if pooled
                       else jnp.zeros(0, jnp.int32)),
            slot_last=(jnp.full(P, -1, jnp.int32).at[0].set(0) if pooled
                       else jnp.zeros(0, jnp.int32)),
            best_mat=best_mat0,
            tree_i=jnp.zeros((5, L), jnp.int32).at[0].set(-1),
            tree_f=jnp.zeros((3, L), jnp.float32),
            nleaves=jnp.int32(1),
        )
        start_step = 0

    def split_branch(state, step, best_leaf, do_split):
        """One split step with MASKED writes: when ``do_split`` is false
        every store preserves the old value, so the state round-trips
        unchanged.  An earlier version wrapped this in lax.cond with an
        identity branch; XLA's copy insertion then duplicated the whole
        [L, F, B, 3] histogram buffer every iteration (O(L^2*F*B) traffic
        per tree), which dominated the run time.  Masked straight-line
        writes keep every buffer update in place."""
        node = step
        new_leaf = step + 1

        # ---- ALL per-leaf scalar reads come from four column slices
        # (parent + prospective-new-leaf columns of the two packed
        # matrices) instead of ~40 individual [L]-array gathers.
        z0 = jnp.int32(0)
        bcol = jax.lax.dynamic_slice(
            state.best_mat, (z0, best_leaf), (_BROWS, 1))[:, 0]
        bcolN = jax.lax.dynamic_slice(
            state.best_mat, (z0, new_leaf), (_BROWS, 1))[:, 0]
        pcol = jax.lax.dynamic_slice(
            state.pos_mat, (z0, best_leaf), (3, 1))[:, 0]
        pcolN = jax.lax.dynamic_slice(
            state.pos_mat, (z0, new_leaf), (3, 1))[:, 0]

        f = bcol[_BF].astype(jnp.int32)
        thr = bcol[_BT].astype(jnp.int32)
        is_cat = is_categorical[jnp.maximum(f, 0)]
        lsg, lsh, lc = bcol[_BLSG], bcol[_BLSH], bcol[_BLC]
        rsg, rsh, rc = bcol[_BRSG], bcol[_BRSH], bcol[_BRC]
        depth_child = bcol[_BLDEP].astype(jnp.int32) + 1

        # ---- partition the parent's range in place (DataPartition::Split).
        # The tier gate (cross-shard max of the parent's positional count)
        # was stored at the split that CREATED this leaf — no collective
        # here.
        begin = pcol[0]
        pcnt = pcol[1]
        gate = pcol[2]
        mega_res = None
        if opt_fused and fuse_hist:
            # MEGA split step: compaction + left-child histogram + both
            # searches + in-place hists-row updates, ONE launch (the
            # round-4 profile showed the loop bound by per-split
            # dispatch, not op work).  depth gate + per-split scalars
            # for the in-kernel search:
            can_k = (params.max_depth <= 0) | (
                depth_child < params.max_depth)
            scal_f = _search_pack_scal(
                can_k.astype(jnp.float32),
                lsg, lsh, lc, rsg, rsh, rc,
                params.min_data_in_leaf, params.min_sum_hessian_in_leaf,
                params.lambda_l1, params.lambda_l2,
                params.min_gain_to_split,
            )

            def _mega_rec(cap):
                # the decision AND the tile counts live in the kernel
                # (_tile_go + the cnt output): no XLA-side read of the
                # record at all, so the aliased placement updates it in
                # place across the tier conds (the materialized window
                # + go vector previously forced a full-record copy per
                # split — ~1 s/tree at 10M rows)
                out = split_step_window(
                    state.hists, state.order, begin, pcnt,
                    do_split, f, thr, is_cat, best_leaf, new_leaf,
                    scal_f, _mega_meta, F=F, cap=cap, k=k_pack,
                    fgroup=_FGROUP, return_comp=direct_place,
                    interpret=_interp,
                )
                if not direct_place:
                    return out
                mh, comp, nl, res, cl, cr, rec_pass = out
                rec2 = place_runs(
                    rec_pass, comp, None, begin, pcnt, nl, do_split,
                    best_leaf, new_leaf, cap=cap, leaf_row=_leaf_row,
                    interpret=_interp, counts=(cl, cr),
                )
                return mh, rec2, nl, res

            mega_hists, order, nleft, mega_res = _tier_chain(
                p_tiers, gate, _mega_rec
            )
        elif rec:

            def _part_rec(cap):
                fv = extract_feature(state.order, f, begin, cap, k_pack)
                go = _go_i32(fv, thr, is_cat)
                return partition_window(
                    state.order, go, begin, pcnt, do_split, cap,
                    left_leaf=best_leaf, right_leaf=new_leaf,
                    leaf_row=_leaf_row, direct=_DIRECT_PLACE_ENV,
                    interpret=_interp,
                )

            order, nleft = _tier_chain(p_tiers, gate, _part_rec)
        else:
            order, nleft = _tier_chain(
                p_tiers,
                gate,
                lambda cap: _partition_branch(
                    state.order, bins_T, f, thr, is_cat, begin, pcnt,
                    do_split, cap
                ),
            )
        nright = pcnt - nleft

        # ---- smaller-child histogram from its contiguous range; sibling
        # by subtraction.  "Smaller" is by POSITIONAL count (the work the
        # gather actually does) — reduced across row shards: every shard
        # must pick the SAME child (the cross-shard reduction inside the
        # hist branch sums one child's partials), even though local counts
        # differ.  ONE child_counts_fn call yields both the global sums
        # (child choice) and the cross-shard maxes (tier gates for this
        # split's histogram AND both children's later partitions).
        nleft_g, nright_g, nleft_gate, nright_gate = child_counts_fn(
            nleft, nright
        )
        if choice_by_mask_counts:
            # base-row-mask mode (cv bin-once, gbdt.set_base_row_mask):
            # pick the small child by the split's MASKED counts instead.
            # A fold booster trained on the full matrix with the fold
            # mask sees positional counts inflated by held-out rows,
            # which could flip this choice vs. the subset-trained run —
            # and the direct-vs-subtracted child histograms differ in
            # final ulps.  lc/rc are the mask-weighted counts from the
            # split search, exactly the subset run's positional counts
            # (its mask is all-ones), so the choice — hence every
            # histogram — matches the subset run bitwise.  Window sizes
            # below stay positional: held-out rows still occupy slots.
            small_is_left = lc <= rc
        else:
            small_is_left = nleft_g <= nright_g
        cnt_s = jnp.where(small_is_left, nleft, nright)
        cnt_s_gate = jnp.where(small_is_left, nleft_gate, nright_gate)
        begin_s = jnp.where(small_is_left, begin, begin + nleft)
        if opt_fused and fuse_hist:
            # mega path: histogram, subtract, search AND buffer update
            # all happened inside split_step_window already
            pass
        elif rec:
            # record mode: the child's rows are a CONTIGUOUS slice of
            # the leaf-sorted record — unpack (vector shifts) + kernel,
            # no indexed access at all.  Under hooks, hist_fn carries
            # the cross-mesh reduce-scatter.
            def _hist_rec(cap):
                win = jax.lax.dynamic_slice(
                    order, (0, begin_s), (Wrec, cap))
                bins_w, g_w, h_w, m_w = unpack_window(
                    win, F, k_pack, bin_dt)
                m_w = m_w * (
                    jnp.arange(cap, dtype=jnp.int32) < cnt_s
                ).astype(m_w.dtype)
                return hist_fn(bins_w, g_w, h_w, m_w)

            h_small = _tier_chain(h_tiers, cnt_s_gate, _hist_rec)
        else:
            h_small = _tier_chain(
                h_tiers,
                cnt_s_gate,
                lambda cap: _child_hist_branch(
                    hist_fn, order, bins_T, grad, hess, bag_mask,
                    begin_s, cnt_s, cap,
                ),
            )
        if pooled:
            # ---- HistogramPool residency (feature_histogram.hpp:337-481):
            # the parent's histogram may have been LRU-evicted since the
            # split that computed it; recompute it from the leaf's
            # contiguous order range then (same O(|parent|) gather as a
            # child histogram — the range holds exactly the parent's rows,
            # partition order does not change the histogram).  The
            # residency flag is uniform across shards (slot state is
            # deterministic), so collectives inside the cond are safe.
            ps = state.slot_of[best_leaf]
            resident = ps >= 0
            h_parent = jax.lax.cond(
                resident,
                lambda _: state.hists[jnp.maximum(ps, 0)],
                lambda _: _tier_chain(
                    h_tiers,
                    gate,
                    lambda cap: _child_hist_branch(
                        hist_fn, order, bins_T, grad, hess, bag_mask,
                        begin, pcnt, cap,
                    ),
                ).astype(acc_dt),
                None,
            )
            # LRU slot choice: overwrite the parent's slot for the left
            # child when resident; otherwise the least-recently-used slot
            # (free slots carry last-use -1 and win argmin).  The right
            # child takes the LRU slot excluding s1.
            s1 = jnp.where(
                resident, ps, jnp.argmin(state.slot_last).astype(jnp.int32)
            )
            idxP = jnp.arange(P, dtype=jnp.int32)
            s2 = jnp.argmin(
                jnp.where(idxP == s1, jnp.int32(2**30), state.slot_last)
            ).astype(jnp.int32)
            h_prev_new = state.hists[s2]
        else:
            h_parent = None if opt_fused else state.hists[best_leaf]
            h_prev_new = None if opt_fused else state.hists[new_leaf]
        if mega_res is not None:
            # mega path: results come straight out of split_step_window
            # ALREADY in the best_mat row layout — no unpack/repack
            hists = mega_hists
            rowL = mega_res[0, :11].astype(bcol.dtype)
            rowR = mega_res[1, :11].astype(bcol.dtype)
        elif opt_fused:
            # ---- ONE launch: subtract + child routing + both searches
            # + in-place buffer row updates (ops/pallas_search.py
            # _fused_kernel).  No [F, B]-sized intermediate exists as an
            # XLA value, so there is nothing to relayout and no barrier
            # is needed — the aliased custom-call IS the buffer update.
            from ..ops.pallas_search import search2_update_pallas

            can = (params.max_depth <= 0) | (depth_child < params.max_depth)
            hists, best_l_new, best_r_new = search2_update_pallas(
                state.hists, h_small, best_leaf, new_leaf,
                do_split,
                small_is_left,
                lsg, lsh, lc, rsg, rsh, rc, can,
                feature_mask, num_bins_per_feature, is_categorical,
                params.min_data_in_leaf, params.min_sum_hessian_in_leaf,
                params.lambda_l1, params.lambda_l2,
                params.min_gain_to_split,
                interpret=_interp,
            )
            rowL = _sr_row(best_l_new, bcol.dtype)
            rowR = _sr_row(best_r_new, bcol.dtype)
        else:
            h_large = h_parent - h_small
            h_left = jnp.where(small_is_left, h_small, h_large)
            h_right = jnp.where(small_is_left, h_large, h_small)

            # ---- child best splits (FindBestThresholds on the two new
            # leaves) — computed BEFORE the buffer update so that every
            # read of state.hists is finished by then (see barrier below)
            best_l_new, best_r_new = best2_for(
                h_left, h_right, lsg, lsh, lc, rsg, rsh, rc, depth_child
            )

            # ---- in-place buffer update.  Everything derived from reads
            # of state.hists (the stacked new rows and the child
            # searches) goes through ONE optimization_barrier together
            # with the buffer itself: after the barrier the buffer has no
            # other live readers, so XLA's copy insertion lets the
            # two-row scatter update it in place.  (Without this, the
            # compiled while body copied the full [L, F, B, 3] buffer
            # twice per split — measured in the HLO.)
            if pooled:
                # preserve the slots' old contents when the step no-ops
                new_rows = jnp.stack(
                    [
                        jnp.where(do_split, h_left, state.hists[s1]),
                        jnp.where(do_split, h_right, h_prev_new),
                    ]
                )
                rows_idx = jnp.stack([s1, s2])
            else:
                new_rows = jnp.stack(
                    [
                        jnp.where(do_split, h_left, h_parent),
                        jnp.where(do_split, h_right, h_prev_new),
                    ]
                )
                rows_idx = jnp.stack([best_leaf, new_leaf])
            new_rows, best_l_new, best_r_new, hists_in = (
                jax.lax.optimization_barrier(
                    (new_rows, best_l_new, best_r_new, state.hists)
                )
            )
            hists = hists_in.at[rows_idx].set(new_rows, unique_indices=True)
            rowL = _sr_row(best_l_new, bcol.dtype)
            rowR = _sr_row(best_r_new, bcol.dtype)

        if pooled:
            # residency bookkeeping, all masked on do_split: evicted
            # occupants lose their slot, then the two children claim
            # s1/s2 (ORDER MATTERS: the parent may be its own evictee)
            def mi(arr, i, val):
                return arr.at[i].set(
                    jnp.where(do_split, val, arr[i]).astype(arr.dtype)
                )

            e1, e2 = state.slot_leaf[s1], state.slot_leaf[s2]
            slot_of = state.slot_of
            slot_of = mi(slot_of, jnp.maximum(e1, 0),
                         jnp.where(e1 >= 0, -1, slot_of[jnp.maximum(e1, 0)]))
            slot_of = mi(slot_of, jnp.maximum(e2, 0),
                         jnp.where(e2 >= 0, -1, slot_of[jnp.maximum(e2, 0)]))
            slot_of = mi(mi(slot_of, best_leaf, s1), new_leaf, s2)
            slot_leaf = mi(mi(state.slot_leaf, s1, best_leaf), s2, new_leaf)
            slot_last = mi(mi(state.slot_last, s1, step), s2, step)
        else:
            slot_of = state.slot_of
            slot_leaf = state.slot_leaf
            slot_last = state.slot_last

        # ---- packed column updates: per-leaf split state + the leaf
        # half of the tree ride best_mat (two column writes); partition
        # ranges ride pos_mat (two column writes); the node half of the
        # tree rides tree_i/tree_f (three column read-modify-writes).
        dt = bcol.dtype
        node_f = node.astype(dt)
        dep_f = depth_child.astype(dt)
        zero = jnp.zeros((), dt)
        tailL = jnp.stack([bcol[_BLO], lc, node_f, dep_f, zero])
        tailR = jnp.stack([bcol[_BRO], rc, node_f, dep_f, zero])
        colL = jnp.where(do_split, jnp.concatenate([rowL, tailL]), bcol)
        colR = jnp.where(do_split, jnp.concatenate([rowR, tailR]), bcolN)
        best_mat = jax.lax.dynamic_update_slice(
            state.best_mat, colL[:, None], (z0, best_leaf))
        best_mat = jax.lax.dynamic_update_slice(
            best_mat, colR[:, None], (z0, new_leaf))

        pcL = jnp.where(do_split, jnp.stack([begin, nleft, nleft_gate]), pcol)
        pcR = jnp.where(
            do_split, jnp.stack([begin + nleft, nright, nright_gate]), pcolN)
        pos_mat = jax.lax.dynamic_update_slice(
            state.pos_mat, pcL[:, None], (z0, best_leaf))
        pos_mat = jax.lax.dynamic_update_slice(
            pos_mat, pcR[:, None], (z0, new_leaf))

        # ---- tree bookkeeping (Tree::Split, tree.cpp:52-96): fix up the
        # parent's child pointer (the split leaf keeps its node id ~leaf
        # until it becomes internal node ``node``), then write the new
        # node's column.  pidx < node always, so the two writes never
        # collide.
        parent = bcol[_BLPAR].astype(jnp.int32)
        has_parent = parent >= 0
        pidx = jnp.maximum(parent, 0)
        colP = jax.lax.dynamic_slice(state.tree_i, (z0, pidx), (5, 1))[:, 0]
        was_left = colP[3] == ~best_leaf
        colP = colP.at[3].set(
            jnp.where(do_split & has_parent & was_left, node, colP[3]))
        colP = colP.at[4].set(
            jnp.where(do_split & has_parent & ~was_left, node, colP[4]))
        tree_i = jax.lax.dynamic_update_slice(
            state.tree_i, colP[:, None], (z0, pidx))
        colNd = jax.lax.dynamic_slice(tree_i, (z0, node), (5, 1))[:, 0]
        colNd = jnp.where(
            do_split,
            jnp.stack(
                [f, thr, is_cat.astype(jnp.int32), ~best_leaf, ~new_leaf]),
            colNd,
        )
        tree_i = jax.lax.dynamic_update_slice(
            tree_i, colNd[:, None], (z0, node))

        colTf = jax.lax.dynamic_slice(state.tree_f, (z0, node), (3, 1))[:, 0]
        colTf = jnp.where(
            do_split,
            # cast explicitly: under hist_dtype=float64 the split stats
            # are f64 while tree buffers stay f32
            jnp.stack([bcol[_BG], bcol[_BLV], lc + rc]).astype(jnp.float32),
            colTf,
        )
        tree_f = jax.lax.dynamic_update_slice(
            state.tree_f, colTf[:, None], (z0, node))

        return _GrowState(
            order=order,
            pos_mat=pos_mat,
            hists=hists,
            slot_of=slot_of,
            slot_leaf=slot_leaf,
            slot_last=slot_last,
            best_mat=best_mat,
            tree_i=tree_i,
            tree_f=tree_f,
            nleaves=state.nleaves + do_split.astype(jnp.int32),
        )

    def body(step, state):
        gain_row = state.best_mat[_BG]
        best_leaf = jnp.argmax(gain_row).astype(jnp.int32)
        do_split = gain_row[best_leaf] > 0.0
        return split_branch(state, jnp.int32(step), best_leaf, do_split)

    state = jax.lax.fori_loop(start_step, L - 1, body, state)

    # ---- unpack the Tree pytree from the packed node/leaf tables (one
    # set of static row slices per TREE, replacing the ~30 per-SPLIT
    # masked stores of the unpacked representation)
    li = L - 1
    tree = Tree(
        num_leaves=state.nleaves,
        split_feature=state.tree_i[0, :li],
        split_feature_real=(
            init_tree.split_feature_real if init_tree is not None
            else jnp.full(li, -1, jnp.int32)),
        threshold_bin=state.tree_i[1, :li],
        threshold_real=(
            init_tree.threshold_real if init_tree is not None
            else jnp.zeros(li, jnp.float32)),
        decision_type=state.tree_i[2, :li],
        left_child=state.tree_i[3, :li],
        right_child=state.tree_i[4, :li],
        split_gain=state.tree_f[0, :li],
        internal_value=state.tree_f[1, :li],
        internal_count=state.tree_f[2, :li],
        leaf_value=state.best_mat[_BLV].astype(jnp.float32),
        leaf_count=state.best_mat[_BLCNT].astype(jnp.float32),
        leaf_parent=state.best_mat[_BLPAR].astype(jnp.int32),
        leaf_depth=state.best_mat[_BLDEP].astype(jnp.int32),
    )

    # ---- per-row leaf assignment from the final ranges: leaves own
    # disjoint contiguous [begin, begin+count) spans of ``order``, so the
    # leaf of a position is a searchsorted over the (few) sorted begins,
    # then one unique-index scatter maps positions back to rows.
    if rec:
        # record mode: the partition stamped every position's leaf id
        # into the record's leaf-id row — one contiguous read replaces
        # the searchsorted over leaf ranges (~75 ms/tree of
        # binary-search gathers in the round-4 profile)
        leaf_of_pos = state.order[_leaf_row, :n]
        rows = jnp.minimum(state.order[_row_id_row, :n], n - 1)
    else:
        idxL = jnp.arange(L, dtype=jnp.int32)
        valid_leaf = (idxL < tree.num_leaves) & (state.pos_mat[1] > 0)
        key = jnp.where(
            valid_leaf, state.pos_mat[0], jnp.int32(n + order_pad))
        perm = jnp.argsort(key).astype(jnp.int32)
        sb = key[perm]
        leaf_of_pos = perm[
            jnp.searchsorted(
                sb, jnp.arange(n, dtype=jnp.int32), side="right") - 1
        ]
        rows = jnp.minimum(state.order[:n], n - 1)
    leaf_id = (
        jnp.zeros(n, jnp.int32).at[rows].set(leaf_of_pos, unique_indices=True)
    )
    return tree, leaf_id
