"""Serial (single-device) leaf-wise tree learner, fully jittable.

TPU-native re-design of SerialTreeLearner
(src/treelearner/serial_tree_learner.cpp:116-150): the same best-first
growth — repeatedly split the leaf with the globally best gain until the
``num_leaves`` budget or no positive gain remains — expressed as a
fixed-shape ``lax.fori_loop``:

* per split step, only the SMALLER child's histogram is built from data
  (one masked scatter pass over all rows); the larger child is parent -
  smaller (the Subtract trick, feature_histogram.hpp:97-106 and
  serial_tree_learner.cpp:259-281).  Histograms for every live leaf stay
  resident in HBM (``hists[L, F, B, 3]``) — the LRU HistogramPool
  (feature_histogram.hpp:337-481) is unnecessary at TPU memory sizes.
* the leaf partition is an int32 ``leaf_id`` row vector updated by a
  vectorized compare (replaces DataPartition::Split, data_partition.hpp:91).
  Left child keeps the parent's leaf index, right child gets the next
  fresh index — the reference's exact leaf numbering (tree.cpp:78-89),
  so trees are comparable node-for-node.
* the heavy branch runs under ``lax.cond`` so exhausted trees cost
  nothing per remaining step.

The data-parallel learner wraps this same step with psum'd histograms
(learners/data_parallel.py); determinism of argmax tie-breaks keeps
parallel == serial trees (split_info.hpp:98-103 semantics).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..models.tree import Tree, empty_tree
from ..ops.histogram import histogram_feature_major
from ..ops.split import SplitResult, find_best_split, K_MIN_SCORE


class TreeLearnerParams(NamedTuple):
    """Scalar tree-growth constraints (TreeConfig, config.h:165-190)."""

    min_data_in_leaf: jax.Array
    min_sum_hessian_in_leaf: jax.Array
    lambda_l1: jax.Array
    lambda_l2: jax.Array
    min_gain_to_split: jax.Array
    max_depth: jax.Array  # <= 0 means unlimited

    @staticmethod
    def from_config(cfg) -> "TreeLearnerParams":
        return TreeLearnerParams(
            min_data_in_leaf=jnp.float32(cfg.min_data_in_leaf),
            min_sum_hessian_in_leaf=jnp.float32(cfg.min_sum_hessian_in_leaf),
            lambda_l1=jnp.float32(cfg.lambda_l1),
            lambda_l2=jnp.float32(cfg.lambda_l2),
            min_gain_to_split=jnp.float32(cfg.min_gain_to_split),
            max_depth=jnp.int32(cfg.max_depth),
        )


class _GrowState(NamedTuple):
    leaf_id: jax.Array  # [n]
    hists: jax.Array  # [L, F, B, 3]
    sum_g: jax.Array  # [L]
    sum_h: jax.Array  # [L]
    cnt: jax.Array  # [L]
    best: SplitResult  # arrays of [L]
    tree: Tree


def _empty_best(L: int) -> SplitResult:
    z = jnp.zeros(L, jnp.float32)
    return SplitResult(
        gain=jnp.full(L, K_MIN_SCORE, jnp.float32),
        feature=jnp.full(L, -1, jnp.int32),
        threshold=jnp.zeros(L, jnp.int32),
        left_sum_grad=z,
        left_sum_hess=z,
        left_count=z,
        right_sum_grad=z,
        right_sum_hess=z,
        right_count=z,
        left_output=z,
        right_output=z,
    )


def _set_best(best: SplitResult, i, new: SplitResult) -> SplitResult:
    return SplitResult(*[b.at[i].set(n) for b, n in zip(best, new)])


def default_search_fn(
    hist, sum_grad, sum_hess, count, can_split,
    feature_mask, num_bins_per_feature, is_categorical, params,
):
    """Local split search over the full feature set (the serial learner's
    FindBestThresholds).  Parallel learners substitute variants that search
    a feature shard and combine across the mesh."""
    return find_best_split(
        hist,
        sum_grad,
        sum_hess,
        count,
        feature_mask,
        num_bins_per_feature,
        is_categorical,
        params.min_data_in_leaf,
        params.min_sum_hessian_in_leaf,
        params.lambda_l1,
        params.lambda_l2,
        params.min_gain_to_split,
        can_split,
    )


@functools.partial(
    jax.jit,
    static_argnames=("num_bins", "max_leaves", "hist_fn", "reduce_fn", "search_fn"),
)
def grow_tree(
    bins_T: jax.Array,  # [F, n] feature-major binned matrix
    grad: jax.Array,  # [n]
    hess: jax.Array,  # [n]
    bag_mask: jax.Array,  # [n] 0/1 bagging mask
    feature_mask: jax.Array,  # [F] bool, feature_fraction sample
    num_bins_per_feature: jax.Array,  # [F] int32
    is_categorical: jax.Array,  # [F] bool
    params: TreeLearnerParams,
    num_bins: int,
    max_leaves: int,
    hist_fn=None,
    reduce_fn=None,
    search_fn=None,
) -> Tuple[Tree, jax.Array]:
    """Grow one tree; returns (tree, final leaf_id per row).

    ``hist_fn(bins_T, grad, hess, mask) -> [F, B, 3]`` abstracts histogram
    construction so the data-parallel learner can psum across the mesh;
    default is the local kernel.  ``reduce_fn`` (cross-device sum) is
    applied to the root (Σg, Σh, count) scalars — the analog of the
    data-parallel learner's tree-start allreduce
    (data_parallel_tree_learner.cpp:97-125).
    """
    F, n = bins_T.shape
    L = max_leaves

    if hist_fn is None:
        hist_fn = functools.partial(histogram_feature_major, num_bins=num_bins)
    if search_fn is None:
        search_fn = default_search_fn

    def best_for(hist, sg, sh, c, depth_child):
        can = (params.max_depth <= 0) | (depth_child < params.max_depth)
        return search_fn(
            hist, sg, sh, c, can,
            feature_mask, num_bins_per_feature, is_categorical, params,
        )

    # ---- root (BeforeTrain / LeafSplits::Init, leaf_splits.hpp:51-92)
    hist0 = hist_fn(bins_T, grad, hess, bag_mask)
    sum_g0 = jnp.sum(grad * bag_mask)
    sum_h0 = jnp.sum(hess * bag_mask)
    cnt0 = jnp.sum(bag_mask)
    if reduce_fn is not None:
        sum_g0, sum_h0, cnt0 = reduce_fn(sum_g0), reduce_fn(sum_h0), reduce_fn(cnt0)

    # hist0's feature extent may be a shard of F (feature-parallel learner)
    state = _GrowState(
        leaf_id=jnp.zeros(n, jnp.int32),
        hists=jnp.zeros((L,) + hist0.shape, jnp.float32).at[0].set(hist0),
        sum_g=jnp.zeros(L, jnp.float32).at[0].set(sum_g0),
        sum_h=jnp.zeros(L, jnp.float32).at[0].set(sum_h0),
        cnt=jnp.zeros(L, jnp.float32).at[0].set(cnt0),
        best=_set_best(
            _empty_best(L), 0, best_for(hist0, sum_g0, sum_h0, cnt0, jnp.int32(0))
        ),
        tree=empty_tree(L),
    )

    def split_branch(args):
        state, step, best_leaf = args
        t = state.tree
        node = step
        new_leaf = step + 1

        f = state.best.feature[best_leaf]
        thr = state.best.threshold[best_leaf]
        is_cat = is_categorical[f]

        # ---- partition (DataPartition::Split, data_partition.hpp:91-139)
        vals = bins_T[f].astype(jnp.int32)
        go_left = jnp.where(is_cat, vals == thr, vals <= thr)
        in_leaf = state.leaf_id == best_leaf
        leaf_id = jnp.where(in_leaf & ~go_left, new_leaf, state.leaf_id)

        lsg = state.best.left_sum_grad[best_leaf]
        lsh = state.best.left_sum_hess[best_leaf]
        lc = state.best.left_count[best_leaf]
        rsg = state.best.right_sum_grad[best_leaf]
        rsh = state.best.right_sum_hess[best_leaf]
        rc = state.best.right_count[best_leaf]

        # ---- smaller-child histogram from data; sibling by subtraction
        smaller_is_left = lc <= rc
        target = jnp.where(smaller_is_left, best_leaf, new_leaf)
        mask_small = bag_mask * (leaf_id == target)
        h_small = hist_fn(bins_T, grad, hess, mask_small)
        h_parent = state.hists[best_leaf]
        h_large = h_parent - h_small
        h_left = jnp.where(smaller_is_left, h_small, h_large)
        h_right = jnp.where(smaller_is_left, h_large, h_small)
        hists = state.hists.at[best_leaf].set(h_left).at[new_leaf].set(h_right)

        # ---- tree bookkeeping (Tree::Split, tree.cpp:52-96)
        parent = t.leaf_parent[best_leaf]
        has_parent = parent >= 0
        pidx = jnp.maximum(parent, 0)
        was_left = t.left_child[pidx] == ~best_leaf
        left_child = t.left_child.at[pidx].set(
            jnp.where(has_parent & was_left, node, t.left_child[pidx])
        )
        right_child = t.right_child.at[pidx].set(
            jnp.where(has_parent & ~was_left, node, t.right_child[pidx])
        )
        left_child = left_child.at[node].set(~best_leaf)
        right_child = right_child.at[node].set(~new_leaf)

        depth_child = t.leaf_depth[best_leaf] + 1
        tree = t._replace(
            num_leaves=t.num_leaves + 1,
            split_feature=t.split_feature.at[node].set(f),
            threshold_bin=t.threshold_bin.at[node].set(thr),
            decision_type=t.decision_type.at[node].set(is_cat.astype(jnp.int32)),
            left_child=left_child,
            right_child=right_child,
            split_gain=t.split_gain.at[node].set(state.best.gain[best_leaf]),
            internal_value=t.internal_value.at[node].set(t.leaf_value[best_leaf]),
            internal_count=t.internal_count.at[node].set(lc + rc),
            leaf_value=t.leaf_value.at[best_leaf]
            .set(state.best.left_output[best_leaf])
            .at[new_leaf]
            .set(state.best.right_output[best_leaf]),
            leaf_count=t.leaf_count.at[best_leaf].set(lc).at[new_leaf].set(rc),
            leaf_parent=t.leaf_parent.at[best_leaf].set(node).at[new_leaf].set(node),
            leaf_depth=t.leaf_depth.at[best_leaf]
            .set(depth_child)
            .at[new_leaf]
            .set(depth_child),
        )

        # ---- child best splits (FindBestThresholds on the two new leaves)
        best_l = best_for(h_left, lsg, lsh, lc, depth_child)
        best_r = best_for(h_right, rsg, rsh, rc, depth_child)
        best = _set_best(_set_best(state.best, best_leaf, best_l), new_leaf, best_r)

        return _GrowState(
            leaf_id=leaf_id,
            hists=hists,
            sum_g=state.sum_g.at[best_leaf].set(lsg).at[new_leaf].set(rsg),
            sum_h=state.sum_h.at[best_leaf].set(lsh).at[new_leaf].set(rsh),
            cnt=state.cnt.at[best_leaf].set(lc).at[new_leaf].set(rc),
            best=best,
            tree=tree,
        )

    def body(step, state):
        best_leaf = jnp.argmax(state.best.gain).astype(jnp.int32)
        do_split = state.best.gain[best_leaf] > 0.0
        return jax.lax.cond(
            do_split,
            split_branch,
            lambda args: args[0],
            (state, jnp.int32(step), best_leaf),
        )

    state = jax.lax.fori_loop(0, L - 1, body, state)
    return state.tree, state.leaf_id
