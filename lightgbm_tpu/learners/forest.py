"""Forest-level batched dispatch: one launch advances B independent trees.

Every small-shape loss (the categorical config-3 gap, ROADMAP item 2)
is the same ~0.45 ms/split dispatch floor that only 10M-row shapes
amortize.  This module amortizes it STRUCTURALLY: stack B independent
tree-growth problems (per-tree grad/hess, bagging masks, feature
samples, per-model scalar knobs) into a leading batch axis so ONE
traced program — one dispatch per call — grows B trees instead of B
programs growing one tree each.

The B-sources routed through here (models/gbdt.py, engine.py):

* multiclass per-class trees within one boosting iteration (the K-loop
  in GBDT._train_one_iter_impl shares grad/hess batches already);
* ``engine.cv()`` folds — with the shared-binning path every fold
  trains on the SAME binned matrix under a per-fold row mask, so fold
  problems differ only in batched operands;
* ``engine.train_many()`` — N independent small models sharing one
  binned dataset (per-model configs restricted to shape-compatible
  knobs; the scalar knobs ride the batched ``TreeLearnerParams`` lanes).

Two implementations, chosen on measured evidence (docs/forest_batching.md):

* ``impl="batched"`` (default) — an EXPLICIT batched grow loop.  The
  sequential learner's strength — O(|parent|) per-split work via the
  leaf-sorted ``order`` permutation and capacity-tiered windows — is
  exactly what pessimizes under vmap: per-lane window offsets turn the
  contiguous dynamic-slices into per-element gathers/scatters, and the
  tier ``lax.cond`` chains into execute-every-branch selects.  The
  batched loop therefore drops the permutation entirely and carries a
  direct row->leaf map ``leaf_id[B, n]``: the partition is a masked
  elementwise update, the smaller child's histogram is a full-data
  masked segment-sum, and per-leaf bookkeeping is two column writes on
  [B, rows, L] tables.  Per-split work is O(n) per lane — the right
  trade at the small shapes forest batching exists for (the sequential
  windows bottom out at the 512-row tier floor anyway, so for n at or
  below ~512 the batched loop does no more histogram work per lane
  than the sequential one).
* ``impl="vmap"`` — ``jax.vmap`` over the UNMODIFIED sequential grow
  program.  Kept as the reference lowering and A/B foil: on the CPU
  container it is parity-exact but ~1x (no win) at the 512-row tier
  floor and up to ~5x SLOWER once multiple capacity tiers exist,
  because every tier branch executes under batched predicates.

Parity contract (tier-1 pinned in tests/test_forest_batching.py):
batching changes scheduling, never math — every lane's tree is
byte-identical to the tree ``grow_tree`` grows for that lane's inputs
alone.  For the explicit loop this holds because (a) the stable
partition keeps within-leaf rows in ascending row order, so the
full-data masked histogram accumulates the same nonzero contributions
in the same order as the sequential window gather (masked rows add
exact zeros, which cannot perturb an accumulator), and (b) the split
search / leaf-value math is the same ``find_best_split*`` program,
vmapped — reductions stay per-lane over the same axes.

Kernel note: the batched path always uses the jnp reference search and
segment-sum histograms.  Whether vmap pessimizes the Pallas
search/histogram kernels is a ``tools/kernel_ab.py`` question for the
next chip window — the eligibility gate in models/gbdt.py falls back
to the sequential learner whenever a kernel path is selected.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..models.tree import Tree
from ..obs import telemetry
from ..ops.split import K_MIN_SCORE, find_best_split, find_best_split_leaves
from .serial import (
    _BF, _BG, _BLC, _BLDEP, _BLO, _BLPAR, _BLSG, _BLSH, _BLV, _BLCNT,
    _BRC, _BRO, _BROWS, _BRSG, _BRSH, _BT,
    TreeLearnerParams, _sr_row, grow_tree,
)

# jax 0.4.x ships no batching rule for optimization_barrier (the grow
# loop's in-place-update fence, serial.py split_branch).  The barrier
# is identity on every operand, so batched dims pass through unchanged
# — vmap of the fence is the fence of the vmapped operands.  Without
# this, vmapping grow_tree raises NotImplementedError.
from jax._src.interpreters import batching as _batching
from jax._src.lax import lax as _lax_internal

_optbar_p = getattr(_lax_internal, "optimization_barrier_p", None)
if _optbar_p is not None and _optbar_p not in _batching.primitive_batchers:
    def _optbar_batcher(args, dims):
        return _optbar_p.bind(*args), dims

    _batching.primitive_batchers[_optbar_p] = _optbar_batcher

# batch every per-tree operand; share the binned matrix and the
# per-feature metadata across lanes.  TreeLearnerParams is batched
# per-FIELD ([B] scalars) so train_many can give each model its own
# regularization/depth knobs without retracing.
_IN_AXES = (
    None,  # bins_T        [F, n]    shared
    0,     # grad          [B, n]
    0,     # hess          [B, n]
    0,     # bag_mask      [B, n]
    0,     # feature_mask  [B, F]
    None,  # num_bins_per_feature [F] shared
    None,  # is_categorical       [F] shared
    TreeLearnerParams(0, 0, 0, 0, 0, 0),  # per-lane scalar knobs
)

# the two-child search, one lane per tree: hist [B, 2, F, nb, 3],
# leaf totals [B, 2], per-lane feature masks and scalar knobs
_search2_lanes = jax.vmap(
    find_best_split_leaves,
    in_axes=(0, 0, 0, 0, 0, None, None, 0, 0, 0, 0, 0, 0),
)
# the root search: one leaf per lane
_search_root = jax.vmap(
    find_best_split,
    in_axes=(0, 0, 0, 0, 0, None, None, 0, 0, 0, 0, 0, 0),
)


def _batched_hist(bins_i32, grad, hess, mask, num_bins: int):
    """hist[B, F, num_bins, 3] — per-lane full-data masked histogram,
    the exact per-lane op sequence of ops.histogram_feature_major so
    each lane's result is bitwise the sequential kernel's."""
    gm = grad * mask
    hm = hess * mask
    stats = jnp.stack([gm, hm, mask], axis=-1)  # [B, n, 3]

    def lane(st):
        def per_feature(b_row):
            return jax.ops.segment_sum(st, b_row, num_segments=num_bins)

        return jax.vmap(per_feature)(bins_i32)

    return jax.vmap(lane)(stats)


class _ForestState(NamedTuple):
    hists: jax.Array    # [B, L, F, nb, 3]
    best_mat: jax.Array  # [B, 16, L]
    tree_i: jax.Array   # [B, 5, L]
    tree_f: jax.Array   # [B, 3, L]
    leaf_id: jax.Array  # [B, n] direct row->leaf map (no order permutation)
    nleaves: jax.Array  # [B]


@functools.lru_cache(maxsize=None)
def make_grow_forest(num_bins: int, max_leaves: int, impl: str = "batched",
                     choice_by_mask_counts: bool = False):
    """The batched grower for a (num_bins, max_leaves) shape family.

    Returns a jitted callable
    ``(bins_T, grad[B,n], hess[B,n], bag_mask[B,n], feature_mask[B,F],
    nbpf, is_cat, params[B-per-field]) -> (Tree[B,...], leaf_id[B,n])``.

    Cached per (num_bins, max_leaves, impl) so every caller — the
    multiclass K-loop, cv folds, train_many — shares ONE jit cache: a
    given (B, n, F) shape traces once process-wide, which is what the
    tier-1 ``grow_traces`` pin asserts.
    """
    if impl == "vmap":
        core = functools.partial(
            # the UNJITTED grow core: vmap of the jitted wrapper would
            # nest jit-under-vmap and re-trace per outer call; the
            # single outer jit below owns caching and the trace-time
            # telemetry count inside the core fires once per trace.
            grow_tree.__wrapped__,
            num_bins=num_bins,
            max_leaves=max_leaves,
            choice_by_mask_counts=choice_by_mask_counts,
        )
        batched = jax.vmap(core, in_axes=_IN_AXES)

        def grow_forest_vmap(bins_T, grad, hess, bag_mask, feature_mask,
                             num_bins_per_feature, is_categorical,
                             params: TreeLearnerParams):
            return batched(bins_T, grad, hess, bag_mask, feature_mask,
                           num_bins_per_feature, is_categorical, params)

        return jax.jit(grow_forest_vmap)
    if impl != "batched":
        raise ValueError(f"unknown forest impl: {impl!r}")

    L = max_leaves

    def grow_forest(bins_T, grad, hess, bag_mask, feature_mask,
                    num_bins_per_feature, is_categorical,
                    params: TreeLearnerParams) -> Tuple[Tree, jax.Array]:
        telemetry.count("grow_traces")  # trace-time: once per (B, shape)
        B, n = grad.shape
        dt = grad.dtype
        bT = bins_T.astype(jnp.int32)
        lanes = jnp.arange(B, dtype=jnp.int32)

        # ---- root (mirrors serial.py's BeforeTrain block, one lane each)
        hist0 = _batched_hist(bT, grad, hess, bag_mask, num_bins)
        # per-lane ONE-segment segment-sums, mirroring serial.py's root:
        # scatter order makes the sums invariant to interleaved zero-mask
        # rows, which the parity pins (stacked-vs-loop, cv bin-once)
        # depend on; jnp.sum's shape-dependent reduction tree is not
        gh0 = jax.vmap(
            lambda x: jax.ops.segment_sum(
                x, jnp.zeros(x.shape[0], jnp.int32), num_segments=1)[0]
        )(jnp.stack([grad * bag_mask, hess * bag_mask], axis=-1))
        sum_g0, sum_h0 = gh0[:, 0], gh0[:, 1]
        cnt0 = jnp.sum(bag_mask, axis=1)
        can0 = (params.max_depth <= 0) | (0 < params.max_depth)
        root_best = _search_root(
            hist0, sum_g0, sum_h0, cnt0,
            feature_mask, num_bins_per_feature, is_categorical,
            params.min_data_in_leaf, params.min_sum_hessian_in_leaf,
            params.lambda_l1, params.lambda_l2, params.min_gain_to_split,
            can0,
        )
        bm = (
            jnp.zeros((B, _BROWS, L), dt)
            .at[:, _BG].set(K_MIN_SCORE)
            .at[:, _BF].set(-1.0)
            .at[:, _BLPAR].set(-1.0)
        )
        bm = bm.at[:, :11, 0].set(_sr_row(root_best, dt).T)
        state = _ForestState(
            hists=jnp.zeros((B, L) + hist0.shape[1:], dt).at[:, 0].set(hist0),
            best_mat=bm,
            tree_i=jnp.zeros((B, 5, L), jnp.int32).at[:, 0].set(-1),
            tree_f=jnp.zeros((B, 3, L), jnp.float32),
            leaf_id=jnp.zeros((B, n), jnp.int32),
            nleaves=jnp.ones(B, jnp.int32),
        )

        def body(step, st: _ForestState) -> _ForestState:
            node = jnp.int32(step)
            new_leaf = node + 1
            gain_row = st.best_mat[:, _BG, :]  # [B, L]
            best_leaf = jnp.argmax(gain_row, axis=1).astype(jnp.int32)
            do_split = jnp.take_along_axis(
                gain_row, best_leaf[:, None], axis=1)[:, 0] > 0.0

            bcol = jnp.take_along_axis(
                st.best_mat, best_leaf[:, None, None], axis=2)[:, :, 0]
            bcolN = jax.lax.dynamic_index_in_dim(
                st.best_mat, new_leaf, axis=2, keepdims=False)
            f = bcol[:, _BF].astype(jnp.int32)
            thr = bcol[:, _BT].astype(jnp.int32)
            isc = is_categorical[jnp.maximum(f, 0)]
            lsg, lsh, lc = bcol[:, _BLSG], bcol[:, _BLSH], bcol[:, _BLC]
            rsg, rsh, rc = bcol[:, _BRSG], bcol[:, _BRSH], bcol[:, _BRC]
            depth_child = bcol[:, _BLDEP].astype(jnp.int32) + 1

            # ---- partition: a masked elementwise update of the direct
            # row->leaf map — the batched replacement for the sequential
            # order-permutation scatter (left child keeps the parent's
            # leaf index, right child takes the fresh one, tree.cpp:78-89)
            vals = bT[jnp.maximum(f, 0)]  # [B, n] per-lane feature rows
            in_leaf = st.leaf_id == best_leaf[:, None]
            dec = jnp.where(
                isc[:, None], vals == thr[:, None], vals <= thr[:, None])
            go = dec & in_leaf
            go_r = in_leaf & ~dec
            nleft = jnp.sum(go, axis=1, dtype=jnp.int32)
            pcnt = jnp.sum(in_leaf, axis=1, dtype=jnp.int32)
            nright = pcnt - nleft
            leaf_id = jnp.where(
                go_r & do_split[:, None], new_leaf, st.leaf_id)

            # ---- smaller child's histogram as a full-data masked
            # segment-sum (bitwise the sequential window gather: same
            # nonzero contributions in the same ascending-row order);
            # sibling by subtraction (feature_histogram.hpp:97-106)
            if choice_by_mask_counts:
                # base-row-mask mode: masked counts, matching the
                # subset-trained run's positional choice (serial.py
                # carries the full argument at its small_is_left)
                small_is_left = lc <= rc
            else:
                small_is_left = nleft <= nright
            child = jnp.where(small_is_left[:, None], go, go_r)
            h_small = _batched_hist(
                bT, grad, hess, bag_mask * child.astype(dt), num_bins)
            h_parent = jnp.take_along_axis(
                st.hists, best_leaf[:, None, None, None, None],
                axis=1)[:, 0]
            h_prev_new = jax.lax.dynamic_index_in_dim(
                st.hists, new_leaf, axis=1, keepdims=False)
            h_large = h_parent - h_small
            sl = small_is_left[:, None, None, None]
            h_left = jnp.where(sl, h_small, h_large)
            h_right = jnp.where(sl, h_large, h_small)

            # ---- both children's best splits, one batched search
            can = (params.max_depth <= 0) | (depth_child < params.max_depth)
            res = _search2_lanes(
                jnp.stack([h_left, h_right], axis=1),
                jnp.stack([lsg, rsg], axis=1),
                jnp.stack([lsh, rsh], axis=1),
                jnp.stack([lc, rc], axis=1),
                feature_mask, num_bins_per_feature, is_categorical,
                params.min_data_in_leaf, params.min_sum_hessian_in_leaf,
                params.lambda_l1, params.lambda_l2,
                params.min_gain_to_split,
                jnp.stack([can, can], axis=1),
            )
            rowL = _sr_row(type(res)(*[a[:, 0] for a in res]), dt).T
            rowR = _sr_row(type(res)(*[a[:, 1] for a in res]), dt).T

            # ---- in-place hists update behind the same barrier idiom
            # as the sequential loop: after it the buffer has no other
            # live readers, so the two row writes stay in place
            dsm = do_split[:, None, None, None]
            new_l = jnp.where(dsm, h_left, h_parent)
            new_r = jnp.where(dsm, h_right, h_prev_new)
            new_l, new_r, rowL, rowR, hists_in = jax.lax.optimization_barrier(
                (new_l, new_r, rowL, rowR, st.hists))
            hists = hists_in.at[lanes, best_leaf].set(
                new_l, unique_indices=True)
            hists = hists.at[:, new_leaf].set(new_r)

            # ---- packed column updates (two columns per table)
            node_f = jnp.broadcast_to(node.astype(dt), lc.shape)
            dep_f = depth_child.astype(dt)
            zero = jnp.zeros_like(lc)
            tailL = jnp.stack([bcol[:, _BLO], lc, node_f, dep_f, zero], 1)
            tailR = jnp.stack([bcol[:, _BRO], rc, node_f, dep_f, zero], 1)
            colL = jnp.where(do_split[:, None],
                             jnp.concatenate([rowL, tailL], axis=1), bcol)
            colR = jnp.where(do_split[:, None],
                             jnp.concatenate([rowR, tailR], axis=1), bcolN)
            best_mat = st.best_mat.at[lanes, :, best_leaf].set(
                colL, unique_indices=True)
            best_mat = best_mat.at[:, :, new_leaf].set(colR)

            # ---- tree bookkeeping (Tree::Split, tree.cpp:52-96)
            parent = bcol[:, _BLPAR].astype(jnp.int32)
            has_parent = parent >= 0
            pidx = jnp.maximum(parent, 0)
            colP = jnp.take_along_axis(
                st.tree_i, pidx[:, None, None], axis=2)[:, :, 0]
            was_left = colP[:, 3] == ~best_leaf
            colP = colP.at[:, 3].set(jnp.where(
                do_split & has_parent & was_left, node, colP[:, 3]))
            colP = colP.at[:, 4].set(jnp.where(
                do_split & has_parent & ~was_left, node, colP[:, 4]))
            tree_i = st.tree_i.at[lanes, :, pidx].set(
                colP, unique_indices=True)
            colNd = jax.lax.dynamic_index_in_dim(
                tree_i, node, axis=2, keepdims=False)
            colNd = jnp.where(
                do_split[:, None],
                jnp.stack([
                    f, thr, isc.astype(jnp.int32), ~best_leaf,
                    jnp.broadcast_to(~new_leaf, f.shape)], axis=1),
                colNd,
            )
            tree_i = tree_i.at[:, :, node].set(colNd)

            colTf = jax.lax.dynamic_index_in_dim(
                st.tree_f, node, axis=2, keepdims=False)
            colTf = jnp.where(
                do_split[:, None],
                jnp.stack([bcol[:, _BG], bcol[:, _BLV], lc + rc],
                          axis=1).astype(jnp.float32),
                colTf,
            )
            tree_f = st.tree_f.at[:, :, node].set(colTf)

            return _ForestState(
                hists=hists,
                best_mat=best_mat,
                tree_i=tree_i,
                tree_f=tree_f,
                leaf_id=leaf_id,
                nleaves=st.nleaves + do_split.astype(jnp.int32),
            )

        state = jax.lax.fori_loop(0, L - 1, body, state)

        li = L - 1
        B_ = state.tree_i.shape[0]
        tree = Tree(
            num_leaves=state.nleaves,
            split_feature=state.tree_i[:, 0, :li],
            split_feature_real=jnp.full((B_, li), -1, jnp.int32),
            threshold_bin=state.tree_i[:, 1, :li],
            threshold_real=jnp.zeros((B_, li), jnp.float32),
            decision_type=state.tree_i[:, 2, :li],
            left_child=state.tree_i[:, 3, :li],
            right_child=state.tree_i[:, 4, :li],
            split_gain=state.tree_f[:, 0, :li],
            internal_value=state.tree_f[:, 1, :li],
            internal_count=state.tree_f[:, 2, :li],
            leaf_value=state.best_mat[:, _BLV].astype(jnp.float32),
            leaf_count=state.best_mat[:, _BLCNT].astype(jnp.float32),
            leaf_parent=state.best_mat[:, _BLPAR].astype(jnp.int32),
            leaf_depth=state.best_mat[:, _BLDEP].astype(jnp.int32),
        )
        return tree, state.leaf_id

    return jax.jit(grow_forest)


def stack_learner_params(params_list) -> TreeLearnerParams:
    """[B] TreeLearnerParams -> one TreeLearnerParams of [B] arrays
    (the batched-lane layout ``make_grow_forest`` expects)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def unstack_tree(trees: Tree, i: int) -> Tree:
    """Lane ``i`` of a batched Tree pytree as a plain per-tree pytree
    (the shape the post-grow step and the models list consume)."""
    return jax.tree.map(lambda a: a[i], trees)


def grow_forest_trees(bins_T, grads, hesses, bag_masks, feature_masks,
                      num_bins_per_feature, is_categorical, params_list,
                      *, num_bins: int, max_leaves: int,
                      impl: str = "batched"):
    """Convenience one-shot: stack per-lane operands, run the batched
    grower, count the dispatch.  ``grads``/``hesses``/``bag_masks``/
    ``feature_masks`` are sequences of per-lane arrays (or already
    stacked [B, ...] arrays); ``params_list`` a sequence of
    TreeLearnerParams (or one batched TreeLearnerParams)."""
    stk = lambda v: v if isinstance(v, jax.Array) else jnp.stack(list(v))  # noqa: E731
    params = (params_list if isinstance(params_list, TreeLearnerParams)
              and getattr(params_list.max_depth, "ndim", 0) == 1
              else stack_learner_params(list(params_list)))
    gf = make_grow_forest(num_bins, max_leaves, impl)
    trees, leaf_ids = gf(
        bins_T, stk(grads), stk(hesses), stk(bag_masks),
        stk(feature_masks), num_bins_per_feature, is_categorical, params,
    )
    telemetry.count("forest_dispatches")
    telemetry.count("forest_batched_trees", int(leaf_ids.shape[0]))
    return trees, leaf_ids
