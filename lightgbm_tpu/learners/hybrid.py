"""Hybrid growth: depthwise levels, then best-first refinement.

The depthwise learner's accuracy loss comes from ONE place: when a
level proposes more splits than the remaining ``num_leaves`` budget, it
truncates by current gain instead of descending best-first
(learners/depthwise.py budget selection).  Hybrid growth removes that
case: phase 1 grows level-synchronously only while the frontier stays
within ``max_leaves // 4`` leaves (``stop_before_budget=4``; the final
level can at most double that, so the handoff happens with <= ~L/2
leaves, every split has positive gain, and at least half the budget
remains for refinement), then phase 2
resumes EXACT best-first growth from the partial tree (grow_tree
``init_tree``), spending the remaining budget one highest-gain leaf at
a time.  Measured at 60k rows / 63 leaves / 20 trees: leafwise AUC
0.88274, hybrid(4) 0.88271, hybrid(2) 0.88081, depthwise 0.86897.

Cost model: phase 1 does one fused histogram pass per level (~log2(L/2)
passes); phase 2 does ~L/2 smaller-child passes over leaves that are
already small.  Accuracy matches leaf-wise growth to within noise
(pinned in tests/test_hybrid.py), while keeping most of depthwise's
level-fused speed on TPU (the mode exists for exactly that trade,
VERDICT r2 item 9 / docs/Parameters-tuning.md:9).
"""

from __future__ import annotations

import functools

import jax

from .depthwise import grow_tree_depthwise
from .serial import grow_tree

# Phase-1 handoff: grow levels while the frontier stays within
# max_leaves // HYBRID_STOP_FACTOR (4 measured leafwise-parity AUC;
# 2 trails by ~0.002 — module docstring).  The sharded hybrid in
# parallel/data_parallel.py MUST use the same factor or serial and
# data-parallel hybrid trees diverge structurally.
HYBRID_STOP_FACTOR = 4


@functools.partial(
    jax.jit,
    static_argnames=("num_bins", "max_leaves", "hist_fn", "level_hist_fn"),
)
def grow_tree_hybrid(
    bins_T,
    grad,
    hess,
    bag_mask,
    feature_mask,
    num_bins_per_feature,
    is_categorical,
    params,
    num_bins: int,
    max_leaves: int,
    hist_fn=None,
    level_hist_fn=None,
):
    """Grow one tree: depthwise to max_leaves//4, best-first the rest."""
    tree1, leaf1 = grow_tree_depthwise(
        bins_T, grad, hess, bag_mask, feature_mask, num_bins_per_feature,
        is_categorical, params,
        num_bins=num_bins, max_leaves=max_leaves,
        hist_fn=level_hist_fn, stop_before_budget=HYBRID_STOP_FACTOR,
    )
    return grow_tree(
        bins_T, grad, hess, bag_mask, feature_mask, num_bins_per_feature,
        is_categorical, params,
        num_bins=num_bins, max_leaves=max_leaves,
        hist_fn=hist_fn,
        init_tree=tree1, init_leaf_id=leaf1, init_hist_fn=level_hist_fn,
    )
