from .serial import grow_tree, TreeLearnerParams
from .depthwise import grow_tree_depthwise

__all__ = ["grow_tree", "grow_tree_depthwise", "TreeLearnerParams"]
