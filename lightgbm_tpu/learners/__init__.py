from .serial import grow_tree, TreeLearnerParams

__all__ = ["grow_tree", "TreeLearnerParams"]
