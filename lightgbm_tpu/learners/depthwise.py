"""Depthwise (level-synchronous) tree learner — the fast TPU growth mode.

The reference grows strictly best-first, one leaf at a time
(serial_tree_learner.cpp:116-150), which on TPU costs one full histogram
pass over the data PER SPLIT.  This learner grows a whole level per
iteration: ONE fused histogram pass builds ``hist[L, F, B, 3]`` for every
live leaf simultaneously (ops/histogram.histogram_by_leaf — the segment
keys fuse leaf x bin), one vmapped split search scores every leaf, and one
vectorized partition pass routes every row.  A tree of depth D costs D
passes instead of num_leaves-1 — ~30x fewer at 255 leaves.

LightGBM's ``num_leaves`` budget (its defining hyperparameter,
docs/Parameters-tuning.md:9) is preserved: when a level proposes more
splits than the remaining budget, only the highest-gain splits are taken
(gain-descending, leaf-index tie-break), which is exactly the order the
best-first learner would have chosen among that frontier.  Trees are
therefore not always node-identical to leaf-wise trees (a best-first
learner may descend one subtree before finishing the level), but every
split still clears the same gain/min_data/min_hessian constraints and
accuracy tracks the leaf-wise learner closely; leafwise stays the
default/compat mode (config.tree_growth).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..models.tree import Tree, empty_tree
from ..ops.histogram import histogram_by_leaf
from ..ops.split import SplitResult, find_best_split_leaves, K_MIN_SCORE
from .serial import TreeLearnerParams


class _LevelState(NamedTuple):
    leaf_id: jax.Array  # [n] row -> leaf
    tree: Tree
    num_leaves: jax.Array  # scalar int32
    depth: jax.Array  # scalar int32, current level
    keep_going: jax.Array  # scalar bool


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_bins", "max_leaves", "hist_fn", "reduce_fn", "search_leaves_fn",
        "stop_before_budget",
    ),
)
def grow_tree_depthwise(
    bins_T: jax.Array,  # [F, n]
    grad: jax.Array,
    hess: jax.Array,
    bag_mask: jax.Array,
    feature_mask: jax.Array,
    num_bins_per_feature: jax.Array,
    is_categorical: jax.Array,
    params: TreeLearnerParams,
    num_bins: int,
    max_leaves: int,
    hist_fn=None,
    reduce_fn=None,
    search_leaves_fn=None,
    stop_before_budget: int = 0,
) -> Tuple[Tree, jax.Array]:
    """Grow one tree level-by-level; returns (tree, final leaf_id).

    ``hist_fn(bins_T, leaf_id, grad, hess, mask, num_leaves) -> [L, F, B, 3]``
    abstracts the fused histogram so the data-parallel learner can reduce
    the level histogram across the mesh (its feature extent may be a
    shard); ``search_leaves_fn(hist, sum_g, sum_h, cnt, can_split, fmask,
    nbpf, is_cat, params) -> SplitResult[L]`` abstracts the per-leaf split
    search so a sharded-search learner can search its feature shard and
    combine winners in one collective.  ``reduce_fn`` is unused here (root
    stats come from the reduced histogram) but accepted for signature
    parity with the leaf-wise grower.
    """
    F, n = bins_T.shape
    L = max_leaves

    if hist_fn is None:
        def hist_fn(bt, lid, g, h, m, num_leaves):
            return histogram_by_leaf(
                bt, lid, g, h, m, num_bins=num_bins, num_leaves=num_leaves
            )

    if search_leaves_fn is None:
        def search_leaves_fn(hist, sg, sh, c, can, fm, nb, ic, prm):
            return find_best_split_leaves(
                hist, sg, sh, c, fm, nb, ic,
                prm.min_data_in_leaf, prm.min_sum_hessian_in_leaf,
                prm.lambda_l1, prm.lambda_l2, prm.min_gain_to_split, can,
            )

    max_levels = jnp.where(
        params.max_depth > 0, params.max_depth, jnp.int32(L - 1)
    )

    def level_body(state: _LevelState) -> _LevelState:
        t = state.tree
        # ---- one fused histogram pass for every live leaf
        hist = hist_fn(bins_T, state.leaf_id, grad, hess, bag_mask, L)
        # per-leaf totals from any feature's bins (all features see every
        # row, so feature 0's bin sums are the leaf sums)
        leaf_tot = jnp.sum(hist[:, 0, :, :], axis=1)  # [L, 3]
        sum_g, sum_h, cnt = leaf_tot[:, 0], leaf_tot[:, 1], leaf_tot[:, 2]

        live = jnp.arange(L, dtype=jnp.int32) < state.num_leaves
        depth_ok = (params.max_depth <= 0) | (t.leaf_depth < params.max_depth)
        can_split = live & depth_ok

        best: SplitResult = search_leaves_fn(
            hist, sum_g, sum_h, cnt,
            can_split,
            feature_mask, num_bins_per_feature, is_categorical, params,
        )

        # ---- budget selection: top-gain splits, at most L - num_leaves
        gains = jnp.where(best.gain > 0.0, best.gain, K_MIN_SCORE)
        order = jnp.argsort(-gains)  # stable: leaf-index tie-break
        rank = jnp.zeros(L, jnp.int32).at[order].set(
            jnp.arange(L, dtype=jnp.int32)
        )
        budget = L - state.num_leaves
        selected = (gains > K_MIN_SCORE) & (rank < budget)
        n_sel = jnp.sum(selected.astype(jnp.int32))

        # ---- sequential node numbering in gain order (matches the order
        # best-first would take these splits): i-th selected split gets
        # node = num_leaves-1+i, its right child leaf = num_leaves+i
        sel_in_order = selected[order]  # [L] bool, order[i] = leaf
        slot = jnp.cumsum(sel_in_order.astype(jnp.int32)) - 1  # per order pos
        slot_of_leaf = jnp.zeros(L, jnp.int32).at[order].set(slot)
        node_of_leaf = jnp.where(
            selected, state.num_leaves - 1 + slot_of_leaf, -1
        )
        new_leaf_of = jnp.where(selected, state.num_leaves + slot_of_leaf, -1)

        # ---- tree bookkeeping, fully vectorized over selected leaves.
        # Unselected lanes are routed to an out-of-range index: JAX's
        # default scatter mode DROPS out-of-bounds updates, giving a clean
        # masked scatter with no read-modify-write races on shared slots.
        leaves = jnp.arange(L, dtype=jnp.int32)
        node_idx = jnp.where(selected, node_of_leaf, L - 1)  # L-1 OOB: len L-1

        def scatter(arr, values):
            return arr.at[node_idx].set(values)

        split_feature = scatter(t.split_feature, best.feature)
        threshold_bin = scatter(t.threshold_bin, best.threshold)
        decision_type = scatter(
            t.decision_type, is_categorical[best.feature].astype(jnp.int32)
        )
        split_gain = scatter(t.split_gain, best.gain)
        internal_value = scatter(t.internal_value, t.leaf_value[leaves])
        internal_count = scatter(
            t.internal_count, best.left_count + best.right_count
        )
        left_child = scatter(t.left_child, ~leaves)
        right_child = scatter(t.right_child, ~new_leaf_of)

        # parent hookup: the split leaf's old parent node now points at the
        # new internal node (Tree::Split, tree.cpp:78-89).  Two sibling
        # leaves splitting in the same level target the same parent node on
        # different sides, so each side is its own drop-mode scatter.
        parent = t.leaf_parent[leaves]  # [L]
        has_parent = selected & (parent >= 0)
        pidx = jnp.maximum(parent, 0)
        was_left = t.left_child[pidx] == ~leaves
        left_child = left_child.at[
            jnp.where(has_parent & was_left, pidx, L - 1)
        ].set(node_of_leaf)
        right_child = right_child.at[
            jnp.where(has_parent & ~was_left, pidx, L - 1)
        ].set(node_of_leaf)

        depth_child = t.leaf_depth[leaves] + 1
        leaf_sel = selected

        def set_leaf(arr, left_vals, right_vals):
            # leaf arrays have length L, so L itself is the drop index
            arr = arr.at[jnp.where(leaf_sel, leaves, L)].set(left_vals)
            return arr.at[jnp.where(leaf_sel, new_leaf_of, L)].set(right_vals)

        leaf_value = set_leaf(t.leaf_value, best.left_output, best.right_output)
        leaf_count = set_leaf(t.leaf_count, best.left_count, best.right_count)
        leaf_parent = set_leaf(t.leaf_parent, node_of_leaf, node_of_leaf)
        leaf_depth = set_leaf(t.leaf_depth, depth_child, depth_child)

        tree = t._replace(
            num_leaves=state.num_leaves + n_sel,
            split_feature=split_feature,
            threshold_bin=threshold_bin,
            decision_type=decision_type,
            left_child=left_child,
            right_child=right_child,
            split_gain=split_gain,
            internal_value=internal_value,
            internal_count=internal_count,
            leaf_value=leaf_value,
            leaf_count=leaf_count,
            leaf_parent=leaf_parent,
            leaf_depth=leaf_depth,
        )

        # ---- one partition pass for the whole level
        lid = state.leaf_id
        f_row = best.feature[lid]  # [n]
        v_row = bins_T[jnp.maximum(f_row, 0), jnp.arange(n)].astype(jnp.int32)
        thr_row = best.threshold[lid]
        cat_row = is_categorical[jnp.maximum(f_row, 0)]
        go_left = jnp.where(cat_row, v_row == thr_row, v_row <= thr_row)
        sel_row = selected[lid]
        leaf_id = jnp.where(sel_row & ~go_left, new_leaf_of[lid], lid)

        keep_going = (
            (n_sel > 0)
            & (state.num_leaves + n_sel < L)
            & (state.depth + 1 < max_levels)
        )
        if stop_before_budget:
            # hybrid phase 1 (learners/hybrid.py): hand over to the
            # best-first phase while num_leaves is still <= L/factor, so
            # no level is ever truncated by the top-gain budget selection
            # AND the refinement phase keeps enough budget to spend
            # best-first (factor=4 measured leafwise-parity AUC; factor=2
            # — the largest no-truncation-possible cap — still trailed by
            # ~0.002 because forcing a full weak frontier level spends
            # budget best-first would have used deeper)
            keep_going = keep_going & (
                stop_before_budget * (state.num_leaves + n_sel) <= L
            )
        return _LevelState(
            leaf_id=leaf_id,
            tree=tree,
            num_leaves=state.num_leaves + n_sel,
            depth=state.depth + 1,
            keep_going=keep_going,
        )

    init = _LevelState(
        leaf_id=jnp.zeros(n, jnp.int32),
        tree=empty_tree(L),
        num_leaves=jnp.int32(1),
        depth=jnp.int32(0),
        keep_going=jnp.bool_(True),
    )
    final = jax.lax.while_loop(lambda s: s.keep_going, level_body, init)
    tree = final.tree._replace(num_leaves=final.num_leaves)
    return tree, final.leaf_id
