"""LambdaRank-NDCG objective, TPU-native.

Re-expresses LambdarankNDCG (src/objective/rank_objective.hpp:19-227) as a
padded, vmapped pairwise computation, replacing the reference's per-query
OpenMP loop (rank_objective.hpp:68-74) and its O(cnt^2) nested pair loops
(rank_objective.hpp:109-156) with dense [C,Q,Q] tensor ops.  Queries are
BUCKETED by power-of-two length and each bucket is padded only to its own
bound and processed in fixed-size chunks (``lax.map``): real query-length
distributions (MSLR-style: median ~100, max >1000) would waste ~(Qmax/Q)^2
pair work per query under a single global pad, while bucketing bounds the
waste per query at <4x and keeps every shape static for XLA.  The 1M-entry
sigmoid lookup table (rank_objective.hpp:179-192) is replaced by the exact
sigmoid — table lookup is a CPU trick; the VPU evaluates exp directly.

Per pair (high=rank i, low=rank j, label_high > label_low):
  delta_ndcg = (gain[lh]-gain[ll]) * |disc_i - disc_j| * inv_max_dcg
               [/ (0.01 + |s_h - s_l|) when best != worst score]
  p        = 2 / (1 + exp(2*sigma*(s_h - s_l)))
  lambda_h += -delta_ndcg * p        lambda_l -= -delta_ndcg * p
  hess_{h,l} += 2 * delta_ndcg * p * (2 - p)
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .dcg import label_gains_from_config, max_dcg_at_k, position_discounts
from .objectives import ObjectiveFunction


class LambdarankNDCG(ObjectiveFunction):
    name = "lambdarank"

    def __init__(self, config):
        if config.sigmoid <= 0:
            raise ValueError("sigmoid parameter must be > 0")
        self.sigmoid = float(config.sigmoid)
        self.optimize_pos_at = int(config.max_position)
        self._gains_np = label_gains_from_config(config.label_gain)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError("Lambdarank tasks require query information")
        qb = np.asarray(metadata.query_boundaries)
        label_np = np.asarray(metadata.label)
        nq = len(qb) - 1
        sizes = qb[1:] - qb[:-1]
        inv_max_dcg = np.zeros(nq, np.float64)
        for q in range(nq):
            m = max_dcg_at_k(
                self.optimize_pos_at, label_np[qb[q] : qb[q + 1]], self._gains_np
            )
            inv_max_dcg[q] = 1.0 / m if m > 0 else 0.0
        self._gains = jnp.asarray(self._gains_np, jnp.float32)

        # bucket queries by next-power-of-two length (min 16): each
        # bucket pads to its own bound, so pair work tracks the actual
        # length distribution instead of the global max
        bucket_of = np.maximum(
            16, 1 << np.ceil(np.log2(np.maximum(sizes, 1))).astype(np.int64)
        )
        self._buckets = []
        for Qb in sorted(set(int(b) for b in bucket_of)):
            qsel = np.flatnonzero(bucket_of == Qb)
            bq = len(qsel)
            pad_idx = np.full((bq, Qb), num_data, np.int32)
            for i, q in enumerate(qsel):
                c = int(sizes[q])
                pad_idx[i, :c] = np.arange(qb[q], qb[q + 1])
            valid = pad_idx < num_data
            labels_padded = np.where(
                valid, label_np[np.minimum(pad_idx, num_data - 1)], 0
            ).astype(np.int32)
            # chunk queries to bound the [C, Q, Q] pair tensors to ~64MB
            chunk = max(1, min(bq, (1 << 24) // max(Qb * Qb, 1)))
            self._buckets.append((
                jnp.asarray(pad_idx),
                jnp.asarray(valid),
                jnp.asarray(labels_padded),
                jnp.asarray(inv_max_dcg[qsel], jnp.float32),
                jnp.asarray(position_discounts(Qb), jnp.float32),
                chunk,
            ))

    def get_gradients(self, scores):
        grad = jnp.zeros(self.num_data, jnp.float32)
        hess = jnp.zeros(self.num_data, jnp.float32)
        for pad_idx, valid, labels, imd, discounts, chunk in self._buckets:
            g, h = _lambdarank_grads(
                scores, pad_idx, valid, labels, imd, self._gains, discounts,
                jnp.float32(self.sigmoid), None, self.num_data, chunk,
            )
            grad, hess = grad + g, hess + h
        if self.weights is not None:
            grad, hess = grad * self.weights, hess * self.weights
        return grad, hess


@functools.partial(jax.jit, static_argnames=("num_data", "chunk"))
def _lambdarank_grads(
    scores,
    pad_idx,
    valid,
    labels,
    inv_max_dcg,
    gains,
    discounts,
    sigmoid,
    weights,
    num_data: int,
    chunk: int,
):
    nq, Q = pad_idx.shape
    # pad scores with a sentinel slot at index n
    s_ext = jnp.concatenate([scores, jnp.zeros(1, scores.dtype)])

    nchunks = -(-nq // chunk)
    pad_q = nchunks * chunk - nq
    if pad_q:
        pad_idx = jnp.concatenate(
            [pad_idx, jnp.full((pad_q, Q), num_data, pad_idx.dtype)]
        )
        valid = jnp.concatenate([valid, jnp.zeros((pad_q, Q), bool)])
        labels = jnp.concatenate([labels, jnp.zeros((pad_q, Q), labels.dtype)])
        inv_max_dcg = jnp.concatenate([inv_max_dcg, jnp.zeros(pad_q, inv_max_dcg.dtype)])

    def one_chunk(args):
        idx, vld, lab, imd = args
        s = jnp.where(vld, s_ext[idx], -jnp.inf)  # [C, Q]
        order = jnp.argsort(-s, axis=1, stable=True)  # rank -> slot
        s_r = jnp.take_along_axis(s, order, axis=1)
        l_r = jnp.take_along_axis(lab, order, axis=1)
        v_r = jnp.take_along_axis(vld, order, axis=1)
        cnt = vld.sum(axis=1)
        best = s_r[:, 0]
        worst = jnp.take_along_axis(
            s_r, jnp.maximum(cnt - 1, 0)[:, None], axis=1
        )[:, 0]
        regularize = (best != worst)[:, None, None]

        g_r = gains[jnp.clip(l_r, 0, gains.shape[0] - 1)]
        D = s_r[:, :, None] - s_r[:, None, :]  # s_high - s_low
        cond = (
            (l_r[:, :, None] > l_r[:, None, :])
            & v_r[:, :, None]
            & v_r[:, None, :]
        )
        dcg_gap = g_r[:, :, None] - g_r[:, None, :]
        pd = jnp.abs(discounts[None, :, None] - discounts[None, None, :])
        dn = dcg_gap * pd * imd[:, None, None]
        dn = jnp.where(regularize, dn / (0.01 + jnp.abs(D)), dn)
        p = 2.0 / (1.0 + jnp.exp(jnp.clip(2.0 * sigmoid * D, -88.0, 88.0)))
        lam = jnp.where(cond, -dn * p, 0.0)
        hes = jnp.where(cond, 2.0 * dn * p * (2.0 - p), 0.0)
        lam_r = lam.sum(axis=2) - lam.sum(axis=1)  # high gets +, low gets -
        hes_r = hes.sum(axis=2) + hes.sum(axis=1)
        # unsort back to slot order
        C = idx.shape[0]
        unsort = jnp.argsort(order, axis=1, stable=True)
        lam_s = jnp.take_along_axis(lam_r, unsort, axis=1)
        hes_s = jnp.take_along_axis(hes_r, unsort, axis=1)
        return lam_s, hes_s

    idx_c = pad_idx.reshape(nchunks, chunk, Q)
    vld_c = valid.reshape(nchunks, chunk, Q)
    lab_c = labels.reshape(nchunks, chunk, Q)
    imd_c = inv_max_dcg.reshape(nchunks, chunk)
    lam, hes = jax.lax.map(one_chunk, (idx_c, vld_c, lab_c, imd_c))

    flat_idx = pad_idx.reshape(-1)
    grad = jnp.zeros(num_data + 1, jnp.float32).at[flat_idx].add(lam.reshape(-1))[
        :num_data
    ]
    hess = jnp.zeros(num_data + 1, jnp.float32).at[flat_idx].add(hes.reshape(-1))[
        :num_data
    ]
    if weights is not None:
        grad, hess = grad * weights, hess * weights
    return grad, hess
