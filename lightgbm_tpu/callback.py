"""Training callbacks (reference python-package/lightgbm/callback.py).

Same protocol: each callback receives a ``CallbackEnv`` namedtuple per
iteration; ``before_iteration`` callbacks run before ``Booster.update``.
``early_stopping`` raises :class:`EarlyStopException` and stamps
``booster.best_iteration`` (callback.py:126-192).
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List


class EarlyStopException(Exception):
    """Raised to stop training early (callback.py:9-14)."""

    def __init__(self, best_iteration: int):
        super().__init__()
        self.best_iteration = best_iteration


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"],
)


def _format_eval_result(value, show_stdv: bool = True) -> str:
    """callback.py:22-37."""
    if len(value) == 4:
        return f"{value[0]}'s {value[1]}:{value[2]:.6g}"
    if len(value) == 5:  # cv: (name, metric, mean, bigger_is_better, std)
        if show_stdv:
            return f"{value[0]}'s {value[1]}:{value[2]:.6g}+{value[4]:.6g}"
        return f"{value[0]}'s {value[1]}:{value[2]:.6g}"
    raise ValueError("Wrong metric value")


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    """Print metrics every ``period`` iterations (callback.py:40-62)."""

    def callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list and (
            (env.iteration + 1) % period == 0
        ):
            result = "\t".join(
                _format_eval_result(x, show_stdv) for x in env.evaluation_result_list
            )
            print(f"[{env.iteration + 1}]\t{result}")

    callback.order = 10
    return callback


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]) -> Callable:
    """Fill a dict with the eval history (callback.py:65-98)."""
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result has to be a dictionary")
    eval_result.clear()

    def init(env: CallbackEnv) -> None:
        for data_name, eval_name, _, *_rest in env.evaluation_result_list:
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])

    def callback(env: CallbackEnv) -> None:
        if not eval_result:
            init(env)
        for data_name, eval_name, result, *_rest in env.evaluation_result_list:
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])
            eval_result[data_name][eval_name].append(result)

    callback.order = 20
    return callback


def reset_parameter(**kwargs: Any) -> Callable:
    """Reset parameters per iteration; each value is a list (one entry per
    iteration) or a function iteration -> value (callback.py:101-123)."""

    def callback(env: CallbackEnv) -> None:
        new_parameters = {}
        for key, value in kwargs.items():
            if key in ("num_class", "boosting_type", "metric"):
                raise RuntimeError(f"cannot reset {key} during training")
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"Length of list {key!r} has to equal to 'num_boost_round'."
                    )
                new_parameters[key] = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_parameters[key] = value(env.iteration - env.begin_iteration)
            else:
                raise ValueError("Only list and callable values are supported.")
        env.model.reset_parameter(new_parameters)
        env.params.update(new_parameters)

    callback.before_iteration = True
    callback.order = 10
    return callback


def early_stopping(stopping_rounds: int, verbose: bool = True) -> Callable:
    """Stop training when no valid metric improves in ``stopping_rounds``
    rounds (callback.py:126-192).  Sets ``model.best_iteration`` (1-based,
    like the reference's ``best_iteration``)."""
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[list] = []
    cmp_op: List[Callable[[float, float], bool]] = []

    def init(env: CallbackEnv) -> None:
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one dataset and eval metric "
                "is required for evaluation"
            )
        if verbose:
            print(
                f"Training until validation scores don't improve for "
                f"{stopping_rounds} rounds."
            )
        for _ in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            bigger_is_better = _[3]
            if bigger_is_better:
                best_score.append(float("-inf"))
                cmp_op.append(lambda a, b: a > b)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda a, b: a < b)

    def callback(env: CallbackEnv) -> None:
        if not best_score:
            init(env)
        for i, (data_name, eval_name, score, *_rest) in enumerate(
            env.evaluation_result_list
        ):
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            # never early-stop on the training metric (callback.py:171).
            # engine.train renames the train set to the user's valid_names
            # entry, so compare against the model's train_data_name rather
            # than the literal default.
            elif data_name == getattr(env.model, "train_data_name", "training"):
                continue
            elif env.iteration - best_iter[i] >= stopping_rounds:
                if env.model is not None:
                    env.model.best_iteration = best_iter[i] + 1
                if verbose:
                    print(f"Early stopping, best iteration is:")
                    print(
                        f"[{best_iter[i] + 1}]\t"
                        + "\t".join(
                            _format_eval_result(x) for x in best_score_list[i]
                        )
                    )
                raise EarlyStopException(best_iter[i])

    callback.order = 30
    return callback
