"""ctypes bindings for the native data-loading runtime.

The reference reaches its C++ core through ctypes (python-package/
lightgbm/basic.py:30-40 loading lib_lightgbm.so); we do the same for the
host-side ingest library (src/native/lgbm_native.cpp) that accelerates
text parsing and the value->bin encode.  The library is built on demand
with g++ (cached next to the package); every entry point has a pure
Python fallback, so the framework works without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Tuple

import numpy as np

from .analysis import lockcheck
from .log import Log

_LIB_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "lib")
_LIB_PATH = os.path.join(_LIB_DIR, "liblgbm_native.so")
_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "src", "native", "lgbm_native.cpp",
)
_lock = lockcheck.make_lock("native.load")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    if not os.path.exists(_SRC):
        return False
    os.makedirs(_LIB_DIR, exist_ok=True)
    # the Makefile is the single source of truth for compile flags
    makefile_dir = os.path.dirname(_SRC)
    if os.path.exists(os.path.join(makefile_dir, "Makefile")):
        cmd = ["make", "-C", makefile_dir, "--always-make"]
    else:
        cmd = ["g++", "-O3", "-std=c++17", "-Wall", "-fPIC", "-fopenmp",
               "-shared", "-o", _LIB_PATH, _SRC]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    if proc.returncode != 0 or not os.path.exists(_LIB_PATH):
        Log.warning(f"native build failed, using python IO: {proc.stderr[:500]}")
        return False
    return True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("LIGHTGBM_TPU_NO_NATIVE"):
            return None
        if not os.path.exists(_LIB_PATH) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_LIB_PATH)
        ):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            Log.warning(f"native lib load failed, using python IO: {e}")
            return None
        lib.lgbm_parse_delimited.restype = ctypes.c_int
        lib.lgbm_parse_delimited.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long),
        ]
        lib.lgbm_parse_libsvm.restype = ctypes.c_int
        lib.lgbm_parse_libsvm.argtypes = [
            ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long),
        ]
        lib.lgbm_detect_format.restype = ctypes.c_int
        lib.lgbm_detect_format.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.lgbm_value_to_bin.restype = None
        lib.lgbm_value_to_bin.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_long, ctypes.c_long,
            ctypes.POINTER(ctypes.c_long), ctypes.c_long,
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_long),
            ctypes.c_void_p, ctypes.c_int,
        ]
        lib.lgbm_free.restype = None
        lib.lgbm_free.argtypes = [ctypes.c_void_p]
        lib.lgbm_chunk_open.restype = ctypes.c_void_p
        lib.lgbm_chunk_open.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_long),
        ]
        lib.lgbm_chunk_next.restype = ctypes.c_long
        lib.lgbm_chunk_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_double), ctypes.c_long,
        ]
        lib.lgbm_chunk_close.restype = None
        lib.lgbm_chunk_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def detect_format(path: str, skip_header: bool) -> Optional[str]:
    lib = _load()
    if lib is None:
        return None
    code = lib.lgbm_detect_format(path.encode(), int(skip_header))
    return {1: "csv", 2: "tsv", 3: "libsvm"}.get(code)


def parse_file(path: str, fmt: str, skip_header: bool) -> Optional[np.ndarray]:
    """Parse with the native runtime; None -> caller falls back to Python."""
    lib = _load()
    if lib is None:
        return None
    data_p = ctypes.POINTER(ctypes.c_double)()
    rows = ctypes.c_long()
    cols = ctypes.c_long()
    if fmt == "libsvm":
        rc = lib.lgbm_parse_libsvm(
            path.encode(), int(skip_header),
            ctypes.byref(data_p), ctypes.byref(rows), ctypes.byref(cols),
        )
    else:
        rc = lib.lgbm_parse_delimited(
            path.encode(), 1 if fmt == "csv" else 2, int(skip_header),
            ctypes.byref(data_p), ctypes.byref(rows), ctypes.byref(cols),
        )
    if rc != 0:
        return None
    n, f = rows.value, cols.value
    try:
        out = np.ctypeslib.as_array(data_p, shape=(n, f)).copy()
    finally:
        lib.lgbm_free(data_p)
    return out


def parse_file_chunks(path: str, fmt: str, skip_header: bool,
                      chunk_rows: int):
    """Streaming chunk parse (the native half of two-round loading,
    text_reader.h:144-288 semantics).  Yields row-major float64 chunks.
    Returns None when unavailable so the caller uses the pandas reader;
    raises ValueError on malformed rows mid-stream (matching the strict
    whole-file native parser's fallback-to-python contract is impossible
    once chunks have been handed out)."""
    lib = _load()
    if lib is None or fmt == "libsvm":
        return None
    cols = ctypes.c_long()
    handle = lib.lgbm_chunk_open(path.encode(), 1 if fmt == "csv" else 2,
                                 int(skip_header), ctypes.byref(cols))
    if not handle:
        return None
    if cols.value <= 0:  # empty file
        lib.lgbm_chunk_close(handle)
        return iter(())

    def gen():
        try:
            while True:
                buf = np.empty((chunk_rows, cols.value), np.float64)
                got = lib.lgbm_chunk_next(
                    handle,
                    buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                    chunk_rows,
                )
                if got < 0:
                    raise ValueError(f"malformed data row in {path}")
                if got == 0:
                    return
                yield buf[:got]
        finally:
            lib.lgbm_chunk_close(handle)

    return gen()


def value_to_bin_numerical(
    X: np.ndarray,
    col_idx: np.ndarray,
    bounds_list: List[np.ndarray],
    out: np.ndarray,
) -> bool:
    """Batch value->bin encode for numerical features into ``out``
    (row-major [n, n_used] u8/u16 slice-compatible array).  Returns False
    when the native path is unavailable (caller uses numpy)."""
    lib = _load()
    if lib is None:
        return False
    if out.dtype == np.uint8:
        is_u16 = 0
    elif out.dtype == np.uint16:
        is_u16 = 1
    else:
        return False
    if not (X.flags.c_contiguous and out.flags.c_contiguous):
        return False
    X = np.ascontiguousarray(X, np.float64)
    col_idx = np.ascontiguousarray(col_idx, np.int64)
    offsets = np.zeros(len(bounds_list) + 1, np.int64)
    for i, b in enumerate(bounds_list):
        offsets[i + 1] = offsets[i] + len(b)
    bounds = (
        np.concatenate(bounds_list).astype(np.float64)
        if bounds_list
        else np.zeros(0, np.float64)
    )
    bounds = np.ascontiguousarray(bounds)
    lib.lgbm_value_to_bin(
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        X.shape[0], X.shape[1],
        col_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        len(col_idx),
        bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        out.ctypes.data_as(ctypes.c_void_p),
        is_u16,
    )
    return True
