"""Histogram construction — the framework's hottest kernel.

TPU-native replacement for the reference's per-feature gather-accumulate
loops (DenseBin::ConstructHistogram, src/io/dense_bin.hpp:39-104, and the
ordered sparse variant).  Instead of pointer-chasing over row indices, we
build `hist[F, B, 3]` (sum_grad, sum_hess, count — bin.h:18-28) for ALL
features in one vectorized scatter-add, with row masking standing in for
the reference's leaf-index partitions (DataPartition).

Two implementations:
* ``histogram_feature_major`` — `jax.ops.segment_sum` over a [F, n]
  feature-major bin matrix (vmapped scatter).  Works everywhere.
* a Pallas VMEM-accumulation kernel (ops/pallas_histogram.py) is selected
  automatically for large inputs on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..obs.device_time import phase_scope


@functools.partial(jax.jit, static_argnames=("num_bins",))
@phase_scope("histogram")
def histogram_feature_major(
    bins_T: jax.Array,  # [F, n] integer bins, feature-major
    grad: jax.Array,  # [n]
    hess: jax.Array,  # [n]
    mask: jax.Array,  # [n] 0/1 row mask (bagging x leaf membership)
    num_bins: int,
) -> jax.Array:
    """Returns hist[F, num_bins, 3] with (sum_grad, sum_hess, count)."""
    gm = grad * mask
    hm = hess * mask
    stats = jnp.stack([gm, hm, mask], axis=-1)  # [n, 3]

    def per_feature(b_row):
        return jax.ops.segment_sum(stats, b_row, num_segments=num_bins)

    return jax.vmap(per_feature)(bins_T.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("num_bins", "num_leaves"))
@phase_scope("histogram")
def histogram_by_leaf(
    bins_T: jax.Array,  # [F, n]
    leaf_id: jax.Array,  # [n] current leaf per row
    grad: jax.Array,
    hess: jax.Array,
    mask: jax.Array,
    num_bins: int,
    num_leaves: int,
) -> jax.Array:
    """Level-wise variant: hist[L, F, B, 3] for all leaves in one pass.

    Used by the depthwise grower and the data-parallel learner, where one
    fused pass per level replaces the reference's per-leaf histogram
    construction + LRU HistogramPool (feature_histogram.hpp:337-481).
    """
    gm = grad * mask
    hm = hess * mask
    stats = jnp.stack([gm, hm, mask], axis=-1)  # [n, 3]
    keys = leaf_id.astype(jnp.int32) * num_bins + bins_T.astype(jnp.int32)  # [F, n]

    def per_feature(k_row):
        return jax.ops.segment_sum(stats, k_row, num_segments=num_leaves * num_bins)

    out = jax.vmap(per_feature)(keys)  # [F, L*B, 3]
    return out.reshape(bins_T.shape[0], num_leaves, num_bins, 3).transpose(1, 0, 2, 3)


def select_single_hist_fn(num_bins: int, use_pallas: bool):
    """ONE place choosing the per-row-set histogram implementation
    (signature: bins_T, grad, hess, mask -> [F, B, 3]): the single-leaf
    MXU kernel when requested, segment_sum otherwise.  Shared by the
    serial learner wiring and every parallel maker."""
    if use_pallas:
        from .pallas_histogram import make_single_hist_fn

        return make_single_hist_fn(num_bins)
    return functools.partial(histogram_feature_major, num_bins=num_bins)
