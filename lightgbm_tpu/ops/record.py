"""Leaf-sorted packed training record: the TPU-native DataPartition.

Round-3 on-chip profiling (tools/profile_split.py, BASELINE.md) showed
the leaf-wise split loop bound by per-index gather/scatter work on
[n]-sized arrays (~30 ns/element): the partition's feature-row gather
and order scatter plus the smaller-child bins/grad/hess takes total
~42M indexed elements per 1M-row 255-leaf tree — almost the whole
measured s/tree — while contiguous streams run ~40x faster.  The
reference's DataPartition (data_partition.hpp:91-139) leans on CPU
caches to make indices()-indirected histogram reads cheap; the TPU
analog keeps the DATA ITSELF physically leaf-ordered so every per-split
access is a contiguous slice.

Storage: one i32 record matrix [W, n_pad] whose word-rows are

    rows 0..Wb-1 : binned features, packed k per word (k=4 for u8
                   bins, k=2 for u16; little-endian within the word)
    row  Wb      : gradient  (f32 bitcast)
    row  Wb+1    : hessian   (f32 bitcast)
    row  Wb+2    : bagging mask (f32 bitcast)
    row  Wb+3    : original row id (int32; n past the valid prefix)

Split-step primitives:

 *  ``extract_feature`` — split-feature bin values of a leaf's
    contiguous range: dynamic word-row + contiguous slice + shift.
 *  ``partition_window`` — stable partition of a leaf's range by the
    split decision.  Per-tile stable compaction runs in a Pallas
    kernel: destination positions via strict-triangular MXU dots (no
    cumsum lowering), a one-hot routing matrix applied to the four i32
    byte planes (bytes and 0/1 flags are exact in bf16, f32
    accumulation — the dots are EXACT at default MXU precision), and
    in-order sliced async DMA placing each tile's left/right runs at
    their global offsets — later tiles overwrite earlier garbage tails
    because TPU grids execute sequentially.  Zero per-element
    descriptors anywhere.
 *  ``unpack_window`` — a child's contiguous [W, cap] slice back to
    (bins, grad, hess, mask) for the histogram kernels: vectorized
    shifts, no indexed access.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import os as _os

# partition tile width; larger tiles halve the placement-scan step count
# at quadratically more (cheap) MXU routing work per tile
TILE = int(_os.environ.get("LGBM_TPU_REC_TILE", "512"))
if TILE <= 0 or TILE % 128 != 0:
    raise ValueError(
        f"LGBM_TPU_REC_TILE must be a positive multiple of 128 (Mosaic "
        f"lane alignment; the compaction kernel's DMA offsets and the "
        f"cap%TILE assert both require it), got {TILE}"
    )


def round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def bins_per_word(bin_dtype) -> int:
    return 4 if jnp.dtype(bin_dtype).itemsize == 1 else 2


def num_words(F: int, k: int) -> int:
    return -(-F // k)


def rec_height(F: int, k: int) -> int:
    """Record row count: packed words + 4 stat rows, padded to a
    sublane-tile multiple of 8 — Mosaic DMA slices must be 8-aligned in
    the sublane dimension, so the pad rows ride along for free instead
    of a per-split pad/unpad pass."""
    return round_up(num_words(F, k) + 4, 8)


def pack_bins(bins_T: jax.Array, n_pad: int) -> jax.Array:
    """[F, n] u8/u16 -> [Wb, n_pad] i32, k features per word."""
    F, n = bins_T.shape
    k = bins_per_word(bins_T.dtype)
    shift = 32 // k
    Wb = num_words(F, k)
    x = bins_T.astype(jnp.int32)
    if n_pad > n:
        x = jnp.pad(x, ((0, 0), (0, n_pad - n)))
    if F % k:
        x = jnp.pad(x, ((0, Wb * k - F), (0, 0)))
    x = x.reshape(Wb, k, n_pad)
    out = x[:, 0, :]
    for j in range(1, k):
        out = out | (x[:, j, :] << (shift * j))
    return out


def build_record(
    bins_T: jax.Array,  # [F, n] u8/u16
    grad: jax.Array,  # [n] f32
    hess: jax.Array,  # [n] f32
    bag_mask: jax.Array,  # [n]
    n_pad: int,
) -> jax.Array:
    """Assemble the per-tree record in identity order: one contiguous
    O(n*W) pass."""
    n = grad.shape[0]

    def stat_row(v):
        v = v.astype(jnp.float32)
        if n_pad > n:
            v = jnp.pad(v, (0, n_pad - n))
        return jax.lax.bitcast_convert_type(v, jnp.int32)[None]

    F = bins_T.shape[0]
    k = bins_per_word(bins_T.dtype)
    pad_rows = rec_height(F, k) - num_words(F, k) - 4
    return jnp.concatenate([
        pack_bins(bins_T, n_pad),
        stat_row(grad),
        stat_row(hess),
        stat_row(bag_mask),
        jnp.pad(jnp.arange(n, dtype=jnp.int32), (0, n_pad - n),
                constant_values=n)[None],
        jnp.zeros((pad_rows, n_pad), jnp.int32),
    ])


def extract_feature(
    rec: jax.Array, f: jax.Array, begin: jax.Array, cap: int, k: int
) -> jax.Array:
    """Split-feature bin values of window [begin, begin+cap): dynamic
    word-row index + contiguous slice + shift.  ``f`` may be -1 on a
    no-op step — clamped; the result is masked upstream."""
    shift = 32 // k
    f = jnp.maximum(f, 0)
    word = jax.lax.dynamic_index_in_dim(rec, f // k, axis=0, keepdims=False)
    win = jax.lax.dynamic_slice(word, (begin,), (cap,))
    return jax.lax.shift_right_logical(win, (f % k) * shift) & (
        (1 << shift) - 1)


def unpack_window(win: jax.Array, F: int, k: int, bin_dtype):
    """[W, cap] record slice -> (bins [F, cap], grad, hess, mask)."""
    Wb = num_words(F, k)
    shift = 32 // k
    words = win[:Wb]
    parts = [((words >> (shift * j)) & ((1 << shift) - 1)) for j in range(k)]
    bins = jnp.stack(parts, axis=1).reshape(Wb * k, -1)[:F].astype(bin_dtype)
    g = jax.lax.bitcast_convert_type(win[Wb], jnp.float32)
    h = jax.lax.bitcast_convert_type(win[Wb + 1], jnp.float32)
    m = jax.lax.bitcast_convert_type(win[Wb + 2], jnp.float32)
    return bins, g, h, m


def _compact_kernel(win_ref, gcol_ref, out_ref, *, W):
    """One grid step = one [W, T] tile: MXU one-hot stable compaction.

    win_ref  [W, T] i32    : this tile of the record window
    gcol_ref [T, 1] i32    : go flags (1 = left, valid only)
    out_ref  [1, W, 2T] i32: lefts compacted to [0, T), everything else
                             to [T, 2T), original order inside each

    Placement at the (unaligned) global run offsets happens in an XLA
    dynamic-update-slice scan outside — Mosaic DMA slices must be
    128-lane aligned, which arbitrary compaction offsets are not.
    """
    T = TILE
    g = gcol_ref[...].astype(jnp.float32)  # [T, 1]

    # strict-lower triangular: Lt[t, b] = 1.0 iff b < t; positions via
    # MXU dots (inputs 0/1 -> exact at any precision, f32 accumulation)
    t_i = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)
    b_i = jax.lax.broadcasted_iota(jnp.int32, (T, T), 1)
    lt = (b_i < t_i).astype(jnp.float32)
    lpos = jax.lax.dot_general(
        lt, g, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)  # [T, 1] lefts before t
    rpos = jax.lax.dot_general(
        lt, 1.0 - g, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    pos = jnp.where(g > 0, lpos, rpos + T).astype(jnp.int32)  # [T, 1]

    hot = (pos == jax.lax.broadcasted_iota(jnp.int32, (T, 2 * T), 1)
           ).astype(jnp.float32)  # [T, 2T] routing matrix
    tile = win_ref[...]  # [W, T] i32
    comp = jnp.zeros((W, 2 * T), jnp.int32)
    for b in range(4):
        byte = ((tile >> (8 * b)) & 0xFF).astype(jnp.float32)
        m = jax.lax.dot_general(
            byte, hot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [W, 2T]
        comp = comp | (m.astype(jnp.int32) << (8 * b))
    out_ref[0] = comp


@functools.partial(jax.jit, static_argnames=("cap", "interpret"))
def partition_window(
    rec: jax.Array,  # [W, n_pad] i32
    go: jax.Array,  # [cap] bool: left-going (valid rows only)
    begin: jax.Array,
    pcnt: jax.Array,
    do_split: jax.Array,
    cap: int,
    interpret: bool = False,
):
    """Stably partition window [begin, begin+cap) of ``rec``: the
    parent's rows [0, pcnt) become left-rows ++ right-rows (original
    order within each), positions [pcnt, cap) — other leaves' rows
    inside the static tier window, or the n_pad tail — are preserved
    exactly.  Returns (rec', nleft).  DataPartition::Split
    (data_partition.hpp:91-139) re-designed for the TPU memory system.
    """
    W = rec.shape[0]
    T = TILE
    assert cap % T == 0, (cap, T)
    nt = cap // T

    win = jax.lax.dynamic_slice(rec, (0, begin), (W, cap))
    iota = jnp.arange(cap, dtype=jnp.int32)
    valid = iota < pcnt
    # i32 from the start: pred (1-bit) arrays at [cap, 1]-ish shapes
    # bounce between bit layouts (measured ~80 ms/tree of copies)
    gov = (go & valid).astype(jnp.int32)
    nleft = jnp.sum(gov, dtype=jnp.int32)

    kt = gov.reshape(nt, T)
    cl = jnp.sum(kt, axis=1, dtype=jnp.int32)
    # rights per tile INCLUDE the invalid tail: invalids are a SUFFIX of
    # the window, so within any tile valid rights precede invalids and
    # each right-run's valid prefix lands at the right global offset;
    # the garbage beyond total-valid-rights is cut by the final selects
    cr = jnp.sum(valid.reshape(nt, T).astype(jnp.int32) - kt,
                 axis=1, dtype=jnp.int32)
    loff = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(cl)])[:-1]
    roff = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(cr)])[:-1]

    comp = pl.pallas_call(
        functools.partial(_compact_kernel, W=W),
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((W, T), lambda i: (0, i)),
            pl.BlockSpec((T, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, W, 2 * T), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nt, W, 2 * T), jnp.int32),
        interpret=interpret,
    )(win, gov.reshape(cap, 1))

    # in-order placement: sequential DUS writes let each tile's garbage
    # tail be overwritten by the next tile's run
    def place(carry, x):
        lbuf, rbuf = carry
        c, lo, ro = x
        lbuf = jax.lax.dynamic_update_slice(lbuf, c[:, :T], (0, lo))
        rbuf = jax.lax.dynamic_update_slice(rbuf, c[:, T:], (0, ro))
        return (lbuf, rbuf), None

    buf0 = jnp.zeros((W, cap + T), jnp.int32)
    (lbuf, rbuf), _ = jax.lax.scan(
        place, (buf0, buf0), (comp, loff, roff))

    # merge: [0, nleft) from the left runs, [nleft, pcnt) from the right
    # runs shifted to start at nleft (dynamic roll = two contiguous
    # slices), everything else keeps its original value.  Selects are
    # ARITHMETIC on i32 masks: [cap, 1]-shaped pred tensors bounce
    # between bit layouts on this stack (~100 ms/tree of copies)
    rolled = jnp.roll(rbuf, nleft, axis=1)[:, :cap]
    is_left = (iota < nleft).astype(jnp.int32)[None, :]
    merged = lbuf[:, :cap] * is_left + rolled * (1 - is_left)
    keep = (valid.astype(jnp.int32) * do_split.astype(jnp.int32))[None, :]
    out = merged * keep + win * (1 - keep)
    return jax.lax.dynamic_update_slice(rec, out, (0, begin)), nleft
