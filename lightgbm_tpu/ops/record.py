"""Leaf-sorted packed training record: the TPU-native DataPartition.

Round-3 on-chip profiling (tools/profile_split.py, BASELINE.md) showed
the leaf-wise split loop bound by per-index gather/scatter work on
[n]-sized arrays (~30 ns/element): the partition's feature-row gather
and order scatter plus the smaller-child bins/grad/hess takes total
~42M indexed elements per 1M-row 255-leaf tree — almost the whole
measured s/tree — while contiguous streams run ~40x faster.  The
reference's DataPartition (data_partition.hpp:91-139) leans on CPU
caches to make indices()-indirected histogram reads cheap; the TPU
analog keeps the DATA ITSELF physically leaf-ordered so every per-split
access is a contiguous slice.

Storage: one i32 record matrix [W, n_pad] whose word-rows are

    rows 0..Wb-1 : binned features, packed k per word (k=4 for u8
                   bins, k=2 for u16; little-endian within the word)
    row  Wb      : gradient  (f32 bitcast)
    row  Wb+1    : hessian   (f32 bitcast)
    row  Wb+2    : bagging mask (f32 bitcast)
    row  Wb+3    : original row id (int32; n past the valid prefix)

Split-step primitives:

 *  ``extract_feature`` — split-feature bin values of a leaf's
    contiguous range: dynamic word-row + contiguous slice + shift.
 *  ``partition_window`` — stable partition of a leaf's range by the
    split decision.  Per-tile stable compaction runs in a Pallas
    kernel under one of TWO routing strategies (``LGBM_TPU_REC_ROUTING``,
    read once at import; kernels also take an explicit ``routing=``
    static arg so tools/kernel_ab.py can A/B both in one process):

    - ``prefix`` (DEFAULT): per-tile prefix-sum routing.  A lane
      cumsum over the go bitmask yields each column's destination
      offset directly — left rows land at ``cumsum(go)-1``, right rows
      at ``cumsum(1-go)-1`` in the right half — and the columns move
      through an LSB-first staged-shift compress network (Hacker's
      Delight 7-4), ``2*ceil(log2(TILE))`` roll+select steps on the
      VPU: O(TILE*log TILE) work per tile, O(n*log TILE) per level.
    - ``onehot``: the round-3 design this replaced.  Destination
      positions via strict-triangular MXU dots (no cumsum lowering), a
      one-hot routing matrix applied to the four i32 byte planes
      (bytes and 0/1 flags are exact in bf16, f32 accumulation — the
      dots are EXACT at default MXU precision): O(TILE^2) MXU work per
      tile, O(n*TILE) per level — ~85% of device FLOPs at 10M rows
      moved rows instead of binning them (PR 10 phase attribution).
      Kept selectable as the chip-validated fallback and A/B baseline.

    Both routings produce BITWISE-IDENTICAL final partitions (pinned
    by tests/test_partition_routing.py and tools/kernel_ab.py): the
    runs' garbage tails differ, but every consumer masks or overwrites
    garbage lanes by the run counts.  Placement is in-order sliced
    async DMA landing each tile's left/right runs at their global
    offsets — later tiles overwrite earlier garbage tails because TPU
    grids execute sequentially.  Zero per-element descriptors anywhere.
 *  ``unpack_window`` — a child's contiguous [W, cap] slice back to
    (bins, grad, hess, mask) for the histogram kernels: vectorized
    shifts, no indexed access.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..obs.device_time import phase_scope

import os as _os

# partition tile width; larger tiles halve the placement-scan step
# count at more routing work per tile — quadratically more MXU dots
# under onehot routing, one extra compress stage per doubling under
# prefix routing (see ROUTING below)
TILE = int(_os.environ.get("LGBM_TPU_REC_TILE", "512"))
if TILE <= 0 or TILE % 128 != 0:
    raise ValueError(
        f"LGBM_TPU_REC_TILE must be a positive multiple of 128 (Mosaic "
        f"lane alignment; the compaction kernel's DMA offsets and the "
        f"cap%TILE assert both require it), got {TILE}"
    )
# place_runs step-table chunk per launch: a [8, steps] i32 SMEM prefetch
# block is 32B/step (SMEM pads the minor dim to 128 lanes per ROW, hence
# the transpose), and the 1MB SMEM budget caps one launch at ~16k steps
# — the 10M top tier has ~78k.  Read at IMPORT like the other kernel
# knobs (ADVICE r4): place_runs reads it at trace time, so a mid-process
# flip would silently not apply to already-traced caps.
PLACE_CHUNK = int(_os.environ.get("LGBM_TPU_PLACE_CHUNK", "16384"))
if PLACE_CHUNK <= 0:
    raise ValueError(
        f"LGBM_TPU_PLACE_CHUNK must be positive, got {PLACE_CHUNK}")
# partition compaction routing strategy (module docstring): "prefix" =
# lane-cumsum destination offsets + staged-shift compress network
# (O(TILE*log TILE)/tile), "onehot" = the [TILE, 2*TILE] MXU routing
# dots (O(TILE^2)/tile, the round-3 design, kept as A/B baseline and
# chip-validated fallback).  Read ONCE at import like the other kernel
# knobs (ADVICE r4): the kernels read it at trace time, and jit caches
# key only on shapes/static args, so a mid-process env flip would
# silently half-apply.  The kernels' explicit ``routing=`` static arg
# is the in-process override for A/B tooling.
ROUTING = _os.environ.get("LGBM_TPU_REC_ROUTING", "prefix")
if ROUTING not in ("onehot", "prefix"):
    raise ValueError(
        f"LGBM_TPU_REC_ROUTING must be 'onehot' or 'prefix', "
        f"got {ROUTING!r}")


def round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def bins_per_word(bin_dtype) -> int:
    return 4 if jnp.dtype(bin_dtype).itemsize == 1 else 2


def num_words(F: int, k: int) -> int:
    return -(-F // k)


def rec_height(F: int, k: int) -> int:
    """Record row count: packed words + 5 stat rows (grad, hess, mask,
    row id, leaf id), padded to a sublane-tile multiple of 8 — Mosaic
    DMA slices must be 8-aligned in the sublane dimension, so the pad
    rows ride along for free instead of a per-split pad/unpad pass.

    The LEAF-ID row rides the partition: each split stamps the two
    child ids over the parent's window, so end-of-tree leaf assignment
    is a contiguous row read instead of a searchsorted over the leaf
    ranges (profiled ~75 ms/tree of binary-search gathers at 1M)."""
    return round_up(num_words(F, k) + 5, 8)


def pack_bins(bins_T: jax.Array, n_pad: int) -> jax.Array:
    """[F, n] u8/u16 -> [Wb, n_pad] i32, k features per word."""
    F, n = bins_T.shape
    k = bins_per_word(bins_T.dtype)
    shift = 32 // k
    Wb = num_words(F, k)
    x = bins_T.astype(jnp.int32)
    if n_pad > n:
        x = jnp.pad(x, ((0, 0), (0, n_pad - n)))
    if F % k:
        x = jnp.pad(x, ((0, Wb * k - F), (0, 0)))
    x = x.reshape(Wb, k, n_pad)
    out = x[:, 0, :]
    for j in range(1, k):
        out = out | (x[:, j, :] << (shift * j))
    return out


@phase_scope("partition")
def build_record(
    bins_T: jax.Array,  # [F, n] u8/u16
    grad: jax.Array,  # [n] f32
    hess: jax.Array,  # [n] f32
    bag_mask: jax.Array,  # [n]
    n_pad: int,
) -> jax.Array:
    """Assemble the per-tree record in identity order: one contiguous
    O(n*W) pass."""
    n = grad.shape[0]

    def stat_row(v):
        v = v.astype(jnp.float32)
        if n_pad > n:
            v = jnp.pad(v, (0, n_pad - n))
        return jax.lax.bitcast_convert_type(v, jnp.int32)[None]

    F = bins_T.shape[0]
    k = bins_per_word(bins_T.dtype)
    pad_rows = rec_height(F, k) - num_words(F, k) - 5
    return jnp.concatenate([
        pack_bins(bins_T, n_pad),
        stat_row(grad),
        stat_row(hess),
        stat_row(bag_mask),
        jnp.pad(jnp.arange(n, dtype=jnp.int32), (0, n_pad - n),
                constant_values=n)[None],
        # leaf-id row: every row starts in the root leaf (0)
        jnp.zeros((1 + pad_rows, n_pad), jnp.int32),
    ])


def extract_feature(
    rec: jax.Array, f: jax.Array, begin: jax.Array, cap: int, k: int
) -> jax.Array:
    """Split-feature bin values of window [begin, begin+cap): dynamic
    word-row index + contiguous slice + shift.  ``f`` may be -1 on a
    no-op step — clamped; the result is masked upstream."""
    shift = 32 // k
    f = jnp.maximum(f, 0)
    word = jax.lax.dynamic_index_in_dim(rec, f // k, axis=0, keepdims=False)
    win = jax.lax.dynamic_slice(word, (begin,), (cap,))
    return jax.lax.shift_right_logical(win, (f % k) * shift) & (
        (1 << shift) - 1)


def unpack_window(win: jax.Array, F: int, k: int, bin_dtype):
    """[W, cap] record slice -> (bins [F, cap], grad, hess, mask)."""
    Wb = num_words(F, k)
    shift = 32 // k
    words = win[:Wb]
    parts = [((words >> (shift * j)) & ((1 << shift) - 1)) for j in range(k)]
    bins = jnp.stack(parts, axis=1).reshape(Wb * k, -1)[:F].astype(bin_dtype)
    g = jax.lax.bitcast_convert_type(win[Wb], jnp.float32)
    h = jax.lax.bitcast_convert_type(win[Wb + 1], jnp.float32)
    m = jax.lax.bitcast_convert_type(win[Wb + 2], jnp.float32)
    return bins, g, h, m


def _tile_go(tile, scal_i_ref, i, *, F, k):
    """Left-going flags of one [W, T] record tile, recomputed IN-KERNEL
    from the split scalars — the [cap, 1] go-column operand this
    replaces cost a layout copy per split per tier on the XLA side
    (profiled ~300 ms/tree at 10M rows: {0,1:T(1,128)} ->
    {1,0:T(8,128)} relayouts of every tier's column).

    Returns [1, T] f32: 1.0 = left AND valid (rows past pcnt are 0).
    scal_i layout: (.., .., .., .., f, thr, is_cat, pcnt) — indices 4-7.
    """
    T = TILE
    f = scal_i_ref[4]
    thr = scal_i_ref[5]
    is_cat = scal_i_ref[6]
    pcnt = scal_i_ref[7]
    shift = 32 // k
    mask_v = (1 << shift) - 1
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
    valid = ((i * T + lane) < pcnt).astype(jnp.int32)
    fw = f // k
    fs = (f % k) * shift
    # static compare-select row pick: Mosaic has no dynamic_slice
    # lowering, and a dynamically-indexed sublane load is the failure
    # class the histogram kernel's FGROUP loop dodges
    frow = jnp.zeros((1, T), jnp.int32)
    for w in range(num_words(F, k)):
        frow = frow + jnp.where(fw == w, tile[w: w + 1, :], 0)
    fv = jax.lax.shift_right_logical(frow, fs) & mask_v
    # ARITHMETIC select: an i1-on-i1 arith.select fails legalization
    go = is_cat * (fv == thr).astype(jnp.int32) + (1 - is_cat) * (
        fv <= thr).astype(jnp.int32)
    return (go * valid).astype(jnp.float32)


def _resolve_routing(routing):
    """None -> the import default; anything else must be a known
    strategy (an unrecognized string silently meaning 'onehot' would
    make A/B tooling lie)."""
    routing = routing or ROUTING
    if routing not in ("onehot", "prefix"):
        raise ValueError(
            f"routing must be 'onehot' or 'prefix', got {routing!r}")
    return routing


def _lane_cumsum(g):
    """Inclusive prefix sum along the LANE axis of a [1, T] i32 row:
    ceil(log2(T)) Hillis-Steele roll+mask stages.  Mosaic has no
    reliable cumsum lowering on the lane axis; ``pltpu.roll`` plus an
    iota mask (arithmetic, no i1 select) is the portable scan — and it
    runs identically under interpret mode, so CPU parity tests exercise
    the same math the chip does."""
    T = g.shape[-1]
    lane = jax.lax.broadcasted_iota(jnp.int32, g.shape, 1)
    c = g
    step = 1
    while step < T:
        # lane t accumulates lane t-step; the iota mask zeroes the
        # wrapped lanes (< step), so the circular roll acts as a shift.
        # jnp.where (i1 pred, i32 operands — the _tile_go row-pick
        # pattern) instead of a cast-and-multiply: a select lowers with
        # no convert op, keeping the hlo_audit convert budget tight
        c = c + jnp.where(lane >= step, pltpu.roll(c, step, axis=1), 0)
        step *= 2
    return c


def _compress_half(tile, live, shift, nbits):
    """Stable left-compaction of the ``live`` columns of one [R, T]
    tile: column t moves LEFT by ``shift[t]`` lanes (its lane minus its
    prefix-sum destination), applied as LSB-first staged moves of 2^j
    lanes — the Hacker's Delight 7-4 'compress' network.  Monotone
    zero-count shifts make the stages conflict-free: a live column with
    bit j still pending sits at lane >= 2^j (its destination is >= 0),
    so no live column ever wraps or lands on another live column.

    The shift row rides the tile (one extra sublane) so it moves WITH
    its column; ``live`` [1, T] i32 gates every move — vacated lanes
    carry stale values but a dead live flag, and dead lanes can never
    move or be kept.  Returns [R, T] with the live columns compacted to
    [0, count) in original order and GARBAGE beyond — every consumer
    masks or overwrites garbage lanes via the run counts (same contract
    as the one-hot path's zero lanes, which were equally meaningless).
    """
    R = tile.shape[0]
    T = tile.shape[-1]
    work = jnp.concatenate([tile, shift], axis=0)  # [R+1, T]
    for j in range(nbits):
        step = 1 << j
        # left-rotate by ``step``: lane t sees lane t+step (pltpu.roll
        # shifts toward higher lanes, so rotate by T-step)
        r_work = pltpu.roll(work, T - step, axis=1)
        r_live = pltpu.roll(live, T - step, axis=1)
        move_in = r_live * ((r_work[R: R + 1, :] >> j) & 1)  # [1, T]
        stay = live * (1 - ((work[R: R + 1, :] >> j) & 1))
        # arithmetic select (move_in is exact 0/1); stay and move_in
        # are disjoint on live lanes by the conflict-freedom argument
        work = move_in * r_work + (1 - move_in) * work
        live = jnp.maximum(move_in, stay)
    return work[:R]


def _prefix_compact_body(tile, g, W):
    """Prefix-sum routing (the ``routing="prefix"`` default): the
    O(TILE*log TILE) replacement for the one-hot MXU compaction below.
    A lane cumsum of the go row yields destination offsets directly —
    lefts land at ``cumsum(go)-1``, everything else (the invalid tail
    included, exactly like the one-hot path) at ``cumsum(1-go)-1`` in
    the right half — and the columns move through two compress
    networks (2*ceil(log2(T)) roll+select stages) instead of [T, 2T]
    routing dots.  The i32 words move untouched (no bf16 byte-plane
    round trip), so routed content is exact by construction.

    tile [W, T] i32, g [1, T] 0/1 row (f32 or i32; 1 = left AND valid)
    -> [W, 2T]: lefts compacted to [0, T), everything else to [T, 2T),
    original order inside each, garbage lanes beyond each run.
    """
    T = tile.shape[-1]
    gi = g.astype(jnp.int32)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
    csum = _lane_cumsum(gi)  # inclusive left count per lane
    nbits = (T - 1).bit_length()
    # go column at lane t: dest = csum[t]-1, shift = t - csum[t] + 1
    # (= non-go count strictly below t); non-go column: dest =
    # t - csum[t], shift = csum[t] (= go count strictly below t)
    left = _compress_half(tile, gi, lane - csum + 1, nbits)
    right = _compress_half(tile, 1 - gi, csum, nbits)
    return jnp.concatenate([left, right], axis=1)


def _compact_body(tile, g, W, routing=None):
    """Shared stable-compaction math (used by both the plain and the
    fused kernel): route tile columns so lefts land in [0, T) and
    everything else in [T, 2T), original order inside each.

    ``routing`` (static; None = module default ROUTING) picks the
    prefix-sum network (above) or the one-hot MXU dots (below).

    tile [W, T] i32, g [1, T] f32 ROW (1.0 = left, valid only) ->
    [W, 2T].  The row form contracts directly on the lane axis — no
    [1,T]->[T,1] in-kernel relayout and no column operand from XLA.
    """
    if _resolve_routing(routing) == "prefix":
        return _prefix_compact_body(tile, g, W)
    T = TILE
    # strict-lower triangular: Lt[t, b] = 1.0 iff b < t; positions via
    # MXU dots (inputs 0/1 -> exact at any precision, f32 accumulation)
    t_i = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)
    b_i = jax.lax.broadcasted_iota(jnp.int32, (T, T), 1)
    lt = (b_i < t_i).astype(jnp.float32)
    lte = (b_i <= t_i).astype(jnp.float32)
    # position dots stay f32: their FLOPs are negligible (T-wide
    # outputs) and Mosaic rejects bf16 dots with unit minor dims
    # ('vector.broadcast' element-type verification, seen on-chip)
    contract_lane = (((1,), (1,)), ((), ()))
    lpos = jax.lax.dot_general(
        lt, g, contract_lane,
        preferred_element_type=jnp.float32)  # [T, 1] lefts before t
    # inclusive count recovers the column-form flag without a relayout:
    # g_col[t] = lefts(<=t) - lefts(<t) in {0.0, 1.0}
    lpos_inc = jax.lax.dot_general(
        lte, g, contract_lane, preferred_element_type=jnp.float32)
    g_col = lpos_inc - lpos  # [T, 1]
    rpos = jax.lax.dot_general(
        lt, 1.0 - g, contract_lane,
        preferred_element_type=jnp.float32)
    # arithmetic select (g_col is exact 0/1 f32); the +T right-half
    # offset is applied in INT after the cast — written as rpos + T it
    # gets folded into the dot's accumulator init, which Mosaic rejects
    # ("only neutral accumulator supported for float reduction")
    pos = (g_col * lpos + (1.0 - g_col) * rpos).astype(jnp.int32)
    pos = pos + (1 - g_col.astype(jnp.int32)) * T

    return _route_bytes(tile, pos, W)


def _route_bytes(tile, pos, W):
    """Apply the one-hot routing matrix built from ``pos`` [T, 1] to the
    four i32 byte planes.  The BYTE routing dots carry ~all the
    compaction FLOPs (O(n*T) per level): bf16 inputs + f32 accumulation
    are EXACT here — bytes are integers < 256 (8 mantissa bits suffice)
    and the one-hot gives each output cell exactly one nonzero addend —
    while cutting the MXU pass count 3x vs f32's bf16x3 decomposition
    (these dots profiled ~1.2 s/tree of device time at 10M rows)."""
    T = TILE
    hot = (pos == jax.lax.broadcasted_iota(jnp.int32, (T, 2 * T), 1)
           ).astype(jnp.bfloat16)  # [T, 2T] routing matrix
    comp = jnp.zeros((W, 2 * T), jnp.int32)
    for b in range(4):
        byte = ((tile >> (8 * b)) & 0xFF).astype(jnp.bfloat16)
        m = jax.lax.dot_general(
            byte, hot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [W, 2T]
        comp = comp | (m.astype(jnp.int32) << (8 * b))
    return comp


def _compact_body_col(tile, g, W):
    """Column-operand variant of the ONE-HOT _compact_body (g [T, 1]
    f32): used by partition_window's ``routing="onehot"`` kernel, whose
    go flags arrive as an explicit vector (a [nt, T] row-block operand
    is not a legal Mosaic block shape — sublane dim 1 — while the
    [cap, 1] column's (T, 1) block is).  The prefix path has no column
    variant: its compress network runs on the lane axis, so
    partition_window ships the go row sublane-aligned instead (see
    _compact_kernel_prefix)."""
    T = TILE
    t_i = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)
    b_i = jax.lax.broadcasted_iota(jnp.int32, (T, T), 1)
    lt = (b_i < t_i).astype(jnp.float32)
    lpos = jax.lax.dot_general(
        lt, g, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)  # [T, 1] lefts before t
    rpos = jax.lax.dot_general(
        lt, 1.0 - g, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    pos = jnp.where(g > 0, lpos, rpos + T).astype(jnp.int32)  # [T, 1]
    return _route_bytes(tile, pos, W)


def _compact_kernel(win_ref, gcol_ref, out_ref, *, W):
    """One grid step = one [W, T] tile: MXU one-hot stable compaction
    (partition_window, ``routing="onehot"``).

    win_ref  [W, T] i32    : this tile of the record window
    gcol_ref [T, 1] i32    : go flags (1 = left, valid only)
    out_ref  [1, W, 2T] i32: lefts compacted to [0, T), everything else
                             to [T, 2T), original order inside each

    Placement at the (unaligned) global run offsets happens in an XLA
    dynamic-update-slice scan outside — Mosaic DMA slices must be
    128-lane aligned, which arbitrary compaction offsets are not.
    """
    out_ref[0] = _compact_body_col(
        win_ref[...], gcol_ref[...].astype(jnp.float32), W)


def _compact_kernel_prefix(win_ref, grow_ref, out_ref, *, W):
    """One grid step = one [W, T] tile: prefix-sum stable compaction
    (partition_window, ``routing="prefix"``).  Same grid and output
    contract as _compact_kernel, but the go flags arrive as ROW 0 of a
    sublane-aligned [8, T] operand — the compress network runs on the
    lane axis, and a bare [1, cap] row block (sublane dim 1) is not
    Mosaic-legal while the one-hot path's [cap, 1] column would need an
    in-kernel relayout to reach the lanes."""
    out_ref[0] = _prefix_compact_body(win_ref[...], grow_ref[0:1, :], W)



def _hist_tile_body(tile, scal_i_ref, hacc_set, *, W, F, k, Bp,
                    govf, fgroup=8):
    """Shared left-child histogram accumulation over one [W, T] record
    tile (used by _split_step_kernel via _split_tile).  The split
    decision ``govf`` is the SAME [1, T] row the compaction used
    (_tile_go); stats stack on sublanes; the one-hot is born transposed
    against a sublane iota and contracts the shared lane axis on the
    MXU — no relayouts.

    ``hacc_set(fi, contrib)`` accumulates [4, Bp] into feature row fi.
    scal_i layout: (.., .., .., .., f, thr, is_cat, pcnt) — indices 4-7.
    """
    T = TILE
    shift = 32 // k
    mask_v = (1 << shift) - 1

    Wb = num_words(F, k)
    grow = jax.lax.bitcast_convert_type(tile[Wb: Wb + 1, :], jnp.float32)
    hrow = jax.lax.bitcast_convert_type(
        tile[Wb + 1: Wb + 2, :], jnp.float32)
    mrow = jax.lax.bitcast_convert_type(
        tile[Wb + 2: Wb + 3, :], jnp.float32)
    mw = mrow * govf  # bagging mask restricted to the left child
    stats4 = jnp.concatenate(
        [grow * mw, hrow * mw, mw, jnp.zeros_like(mw)], axis=0)

    iota_s = jax.lax.broadcasted_iota(jnp.int32, (Bp, T), 0)
    # caller-sized histogram block: the padded-feature fill below must
    # cover exactly the caller's round_up(F, fgroup) rows (ADVICE r4 —
    # a literal 8 here would leave rows [round_up(F,8), Fp) zero and
    # break parent-minus-left subtraction consistency for fgroup != 8)
    Fp = round_up(F, fgroup)
    for fi in range(F):
        w_idx, sh = fi // k, (fi % k) * shift
        row = jax.lax.shift_right_logical(
            tile[w_idx: w_idx + 1, :], sh) & mask_v
        onehot = (row == iota_s).astype(jnp.float32)
        contrib = jax.lax.dot_general(
            stats4, onehot, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        hacc_set(fi, contrib)
    if Fp > F:
        # padded features: bin-0 totals, matching _prep_single_leaf's
        # zero-padded feature rows (subtract consistency with the
        # buffer's existing rows)
        zrow = jnp.zeros((1, T), jnp.int32)
        onehot0 = (zrow == iota_s).astype(jnp.float32)
        contrib0 = jax.lax.dot_general(
            stats4, onehot0, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        for fi in range(F, Fp):
            hacc_set(fi, contrib0)


# NOTE on lineage: the round-4 fused compact+hist kernel pair
# (_compact_hist_kernel / partition_hist_window) was deleted in round 5
# — split_step_window superseded it (ADVICE r4).  Through round 6 every
# surviving compaction path routed via the one-hot MXU dots; round 7
# added the prefix-sum routing above and made it the default, keeping
# one-hot selectable (LGBM_TPU_REC_ROUTING / the kernels' ``routing=``
# static arg) as the A/B baseline and chip-validated fallback.  See the
# module docstring for the two strategies' cost model.


def _run_offsets(cl, cr):
    """Exclusive per-tile start offsets of the left/right runs within
    their halves, from the per-tile left/right counts [nt].  ONE
    definition of the offset convention — place_runs, split_step_window
    and partition_window all consume it, so the three (previously
    duplicated) constructions cannot drift apart."""
    loff = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(cl)])[:-1]
    roff = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(cr)])[:-1]
    return loff, roff


def _xla_place(rec, win, comp, loff, roff, nleft, iota, valid, do_split,
               begin, cap, leaf_row=-1, left_leaf=None, right_leaf=None):
    """Reference XLA placement: scan-of-DUS run packing + roll/merge +
    optional leaf-id stamping + window write-back.  Shared by
    partition_window, split_step_window, and
    place_runs' interpret fallback — the hardware path (ops.record
    place_runs kernel) is parity-checked against THIS implementation."""
    T = TILE
    W = rec.shape[0]

    def place(carry, x):
        lbuf, rbuf = carry
        c, lo, ro = x
        lbuf = jax.lax.dynamic_update_slice(lbuf, c[:, :T], (0, lo))
        rbuf = jax.lax.dynamic_update_slice(rbuf, c[:, T:], (0, ro))
        return (lbuf, rbuf), None

    buf0 = jnp.zeros((W, cap + T), jnp.int32)
    (lbuf, rbuf), _ = jax.lax.scan(place, (buf0, buf0), (comp, loff, roff))

    rolled = jnp.roll(rbuf, nleft, axis=1)[:, :cap]
    is_left = (iota < nleft).astype(jnp.int32)[None, :]
    merged = lbuf[:, :cap] * is_left + rolled * (1 - is_left)
    keep = (valid * do_split.astype(jnp.int32))[None, :]
    out = merged * keep + win * (1 - keep)
    if leaf_row >= 0 and left_leaf is not None:
        # after the roll, [0, nleft) is the left child, [nleft, pcnt)
        # the right — stamp the child ids over the kept range
        leafvals = (is_left[0] * left_leaf.astype(jnp.int32)
                    + (1 - is_left[0]) * right_leaf.astype(jnp.int32))
        out = out.at[leaf_row].set(
            keep[0] * leafvals + (1 - keep[0]) * out[leaf_row])
    return jax.lax.dynamic_update_slice(rec, out, (0, begin))


def _write_window_kernel(scal_ref, prev_ref, cur_ref, rec_in_ref,
                         rec_out_ref, *, nt):
    """One grid step rewrites ONE T-lane block of the record that the
    window [begin, begin+cap) touches: the window content is rotated
    into block alignment (pltpu.roll by begin%T, dynamic) and merged
    with the block's OLD content outside the window bounds.  Everything
    uses supported constructs — dynamic BLOCK index maps, roll, and
    arithmetic selects; no manual DMA (Mosaic rejects dynamically
    lane-sliced HBM DMAs outright, aligned or not — probed on chip).

    scal [3]: (begin // T, begin % T, last content block — the r == 0
    surplus step clamps onto it, see write_window)
    prev/cur: window blocks i-1 and i (the rotated block straddles two)
    rec_in/rec_out: the SAME aliased record block at begin//T + i
    """
    T = TILE
    i = pl.program_id(0)
    r = scal_ref[1]

    # A no-op grid step happens only when r == 0 (the window spans
    # exactly nt blocks and step nt is surplus).  Its block index is
    # CLAMPED onto the last content block; writing there would clobber
    # the previous step's output with stale input (the aliased input
    # block is not re-fetched on a same-index revisit), so skip.
    @pl.when(i * T - r < nt * T)
    def _():
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
        # source index into the window for lane t: i*T + t - r; valid
        # (= inside the window) iff 0 <= idx < cap == nt*T
        idx = i * T + lane - r
        new_mask = ((idx >= 0) & (idx < nt * T)).astype(jnp.int32)
        both = jnp.concatenate([prev_ref[...], cur_ref[...]], axis=1)
        shifted = pltpu.roll(both, r, axis=1)[:, T:]
        old = rec_in_ref[...]
        rec_out_ref[...] = shifted * new_mask + old * (1 - new_mask)


# opt-in escape hatch (on by default once chip-validated by
# tools/tpu_parity_check.py check_writeback)
ALIASED_WRITEBACK = _os.environ.get("LGBM_TPU_ALIASED_WRITEBACK", "1") != "0"


@phase_scope("partition")
def write_window(rec, out_win, begin, cap: int, interpret: bool = False):
    """rec[:, begin:begin+cap] = out_win, with rec aliased in place so
    the record threads tier-cond boundaries copy-free (the round-4
    profile showed the plain dynamic-update-slice write-back forcing a
    full-record copy, ~95 ms/tree at 1M, while the aliased histogram
    buffer threaded the same conds copy-free).

    Interpret mode (CPU tests) uses the semantically identical
    dynamic-update-slice — the interpreter maps aliased outputs onto
    read-only numpy views."""
    if interpret or not ALIASED_WRITEBACK:
        return jax.lax.dynamic_update_slice(rec, out_win, (0, begin))
    W, n_pad = rec.shape
    T = TILE
    nt = cap // T
    nb = nt + 1  # the rotated window straddles up to nt+1 blocks
    scal = jnp.stack([
        (begin // T).astype(jnp.int32),
        (begin % T).astype(jnp.int32),
        # last CONTENT block: the surplus step (r == 0 only) clamps
        # here, revisiting a written block (and skipping its write)
        # instead of touching a pristine one
        ((begin + cap - 1) // T).astype(jnp.int32),
    ])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((W, T), lambda i, s: (0, jnp.maximum(i - 1, 0))),
            pl.BlockSpec((W, T), lambda i, s: (0, jnp.minimum(i, nt - 1))),
            pl.BlockSpec(
                (W, T),
                lambda i, s: (0, jnp.minimum(s[0] + i, s[2]))),
        ],
        out_specs=pl.BlockSpec(
            (W, T), lambda i, s: (0, jnp.minimum(s[0] + i, s[2]))),
    )
    return pl.pallas_call(
        functools.partial(_write_window_kernel, nt=nt),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(rec.shape, rec.dtype),
        input_output_aliases={3: 0},  # rec (incl. the prefetch arg)
        interpret=interpret,
    )(scal, out_win, out_win, rec)


def _split_tile(tile, scal_i_ref, j, comp_ref, cnt_ref, hacc_ref, *,
                W, F, k, Bp, fgroup, routing=None):
    """Per-tile work of the split step: ONE in-kernel go computation
    (no [cap, 1] column operand from XLA — see _tile_go) shared by the
    compaction (prefix or one-hot, per ``routing``), the per-tile
    left-count output, and the left-child histogram accumulation.
    ``j`` is the tile ordinal (validity)."""
    govf = _tile_go(tile, scal_i_ref, j, F=F, k=k)
    comp_ref[0] = _compact_body(tile, govf, W, routing=routing)
    cnt_ref[...] = jnp.zeros((1, 128), jnp.int32) + jnp.sum(
        govf).astype(jnp.int32)

    def hacc_set(fi, contrib):
        hacc_ref[fi] = hacc_ref[fi] + contrib

    _hist_tile_body(tile, scal_i_ref, hacc_set, W=W, F=F, k=k,
                    Bp=Bp, fgroup=fgroup, govf=govf)


def _split_step_kernel(
    scal_i_ref, scal_f_ref, *refs,
    W, F, k, Bp, nt, fgroup=8, direct_read=False, routing=None,
):
    """The WHOLE split step in one launch: per-tile MXU compaction +
    left-child histogram accumulation (steps 0..nt-1), then subtract +
    two-child search + in-place histogram-buffer row updates (steps nt
    and nt+1) — the union of the tile compaction and
    pallas_search._fused_kernel, eliminating one ~0.35 ms launch floor
    plus the [Fp, 4, Bp] h_small round trip through HBM per split.

    scal_i [10]: (parent_slot, left_slot, new_slot, do_split, f, thr,
                 is_cat, pcnt, begin//T, begin%T)
    scal_f [16]: pallas_search._pack_scal layout
    win_ref    : the [W, T] window tile (non-direct mode).  With
                 ``direct_read`` the RECORD itself is the (single,
                 ALIASED) data operand: each step fetches one T-aligned
                 block and writes it back unchanged through the aliased
                 output, and the unaligned window tile i-1 is
                 roll-merged from the PREVIOUS block (VMEM scratch) and
                 the current one — the grid gains one pipeline step.
                 The single-mention aliased pass-through is what lets
                 XLA chain the record in place through place_runs: any
                 second read of the record (a window slice, a go
                 vector, a sibling block view) made copy-insertion
                 clone the full record every split (~1-2 s/tree at 10M
                 rows, measured both ways).
    hrow_ref   : hists row — parent slot until the search step, new
                 slot on the last
    hists_out  : left row at the search step, right row on the last
    cnt_ref    : [1, 128] i32 per tile — lane 0 carries this tile's
                 LEFT count, so the XLA side derives cl/cr/nleft with
                 no go vector (and no record read) at all
    hacc_ref   : VMEM scratch — left-child histogram accumulator, then
                 the right-child stash between the last two steps
    """
    from .pallas_search import K_EPSILON, _child_search, _tail_of, _tri

    if direct_read:
        (rec_ref, hrow_ref, meta_ref, hists_out_ref,
         comp_ref, res_ref, cnt_ref, rec_out_ref, hacc_ref,
         prev_ref) = refs
    else:
        (win_ref, hrow_ref, meta_ref, hists_out_ref, comp_ref,
         res_ref, cnt_ref, hacc_ref) = refs

    T = TILE
    i = pl.program_id(0)
    do_split = scal_i_ref[3] > 0
    off = 1 if direct_read else 0  # pipeline offset of the tile steps
    search_step = nt + off
    last_step = nt + 1 + off

    @pl.when(i == 0)
    def _():
        hacc_ref[...] = jnp.zeros_like(hacc_ref)

    if direct_read:
        @pl.when(i <= nt)
        def _():
            # fetch block b0+i and write it back unchanged through the
            # aliased output; tile j = i-1 is merged from LAST step's
            # stashed block (prev) and this fetch BEFORE re-stashing
            cur = rec_ref[...]
            rec_out_ref[...] = cur

            @pl.when(i >= 1)
            def _():
                hists_out_ref[0] = hrow_ref[0]
                r = scal_i_ref[9]
                # tile lanes [0, T-r) from prev[:, r:], lanes [T-r, T)
                # from cur[:, :r): both the same right-rotation by
                # (T - r) % T (dynamic shifts are the one dynamic-lane
                # primitive Mosaic supports)
                prev = prev_ref[...]
                sh = jax.lax.rem(T - r, T)
                ra = pltpu.roll(prev, sh, 1)
                rb = pltpu.roll(cur, sh, 1)
                lane = jax.lax.broadcasted_iota(jnp.int32, (W, T), 1)
                m = (lane < (T - r)).astype(jnp.int32)
                tile = ra * m + rb * (1 - m)
                _split_tile(tile, scal_i_ref, i - 1, comp_ref, cnt_ref,
                            hacc_ref, W=W, F=F, k=k, Bp=Bp,
                            fgroup=fgroup, routing=routing)

            prev_ref[...] = cur
    else:
        @pl.when(i < nt)
        def _():
            # the output block aliases the PARENT row during tile steps
            # (si[1] == si[0]); pass the parent through so any
            # intermediate writeback (interpret mode flushes every
            # step) is an identity write, never garbage over a row the
            # search still needs
            hists_out_ref[0] = hrow_ref[0]
            _split_tile(win_ref[...], scal_i_ref, i, comp_ref, cnt_ref,
                        hacc_ref, W=W, F=F, k=k, Bp=Bp, fgroup=fgroup,
                        routing=routing)

    @pl.when(i >= nt + off)
    def _():
        # tail steps revisit tile nt-1's count block: identity rewrite
        # so interpret mode never flushes it unwritten
        cnt_ref[...] = cnt_ref[...]
        if direct_read:
            rec_out_ref[...] = rec_ref[...]

    @pl.when(i == search_step)
    def _():
        parent = hrow_ref[0]  # [Fp, 4, Bp]
        h_left = hacc_ref[...]
        h_right = parent - h_left
        hists_out_ref[0] = jnp.where(do_split, h_left, parent)
        hacc_ref[...] = h_right  # stash for the final step

        B = Bp
        tri = _tri(B)
        for cc in range(2):
            side = (h_left, h_right)[cc]
            hg, hh, hc = side[:, 0, :], side[:, 1, :], side[:, 2, :]
            _child_search(
                cc, hg, hh, hc,
                _tail_of(hg, tri), _tail_of(hh, tri) + K_EPSILON,
                _tail_of(hc, tri),
                scal_f_ref, meta_ref, res_ref, hacc_ref.shape[0], B,
            )

    @pl.when(i == last_step)
    def _():
        hists_out_ref[0] = jnp.where(do_split, hacc_ref[...], hrow_ref[0])


def _place_kernel(sp_ref, comp_ref, rec_in_ref, rec_out_ref, *,
                  W, leaf_row):
    """Placement-only kernel: stream the compacted left/right runs into
    the ALIASED record at their (arbitrary, unaligned) destinations —
    replacing the XLA scan-of-DUS + roll/merge chain AND the full-record
    copy its dynamic-update-slice forced at the tier-cond boundary.

    Step table sp [4*nt, 8] i32 (see _place_table): per step one run
    half lands in one T-lane rec block; block indices are monotone, so
    each block is flushed exactly once after its last write.  On an
    index advance the merge base is the freshly fetched block; on a
    revisit it is the still-resident out block.  Child leaf ids are
    stamped into the record's leaf-id row as part of the same write.
    """
    T = TILE
    i = pl.program_id(0)
    # the table is stored TRANSPOSED [8, steps]: a [steps, 8] SMEM
    # prefetch array pads its minor dim to 128 lanes (16x the bytes);
    # huge tiers additionally CHUNK the table across multiple launches
    # to stay inside the 1MB SMEM budget (see place_runs)
    en = sp_ref[6, i] > 0

    def _merge(base):
        half = sp_ref[1, i] & 1
        comp = comp_ref[0]  # [W, 2T]
        content = comp[:, :T] * (1 - half) + comp[:, T:] * half
        rolled = pltpu.roll(content, sp_ref[2, i], axis=1)
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
        mask = ((lane >= sp_ref[3, i]) & (lane < sp_ref[4, i])
                ).astype(jnp.int32)
        rowsel = (jax.lax.broadcasted_iota(jnp.int32, (W, 1), 0)
                  == leaf_row).astype(jnp.int32)
        stamped = rowsel * sp_ref[7, i] + (1 - rowsel) * rolled
        return mask * stamped + (1 - mask) * base

    @pl.when(en & (sp_ref[5, i] > 0))
    def _():
        rec_out_ref[...] = _merge(rec_in_ref[...])

    @pl.when(en & (sp_ref[5, i] == 0))
    def _():
        rec_out_ref[...] = _merge(rec_out_ref[...])

    @pl.when((i == 0) & jnp.logical_not(en))
    def _():
        # a fully disabled table (no-op split) must still write the
        # parked block once or the grid-end flush emits garbage
        rec_out_ref[...] = rec_in_ref[...]


def _place_table(begin, pcnt, nleft, cl, cr, loff, roff,
                 left_leaf, right_leaf, do_split, nt):
    """[4*nt, 8] i32 placement step table (columns documented on
    _place_kernel).  Lefts stream to [begin, begin+nleft), rights to
    [begin+nleft, begin+pcnt); each tile's run may straddle two blocks
    (lower + upper step).  Block indices are forward-filled monotone."""
    T = TILE

    def run_rows(gbase, counts, offs, half_flag, leaf_val):
        g = gbase + offs
        b = g // T
        s_ = g % T
        end = s_ + counts
        spill = end - T
        has_lo = (counts > 0).astype(jnp.int32)
        has_up = (spill > 0).astype(jnp.int32)
        j2 = jnp.arange(nt, dtype=jnp.int32) * 2 + half_flag
        zeros = jnp.zeros_like(b)
        lower = jnp.stack([
            b, j2, s_, s_, jnp.minimum(end, T), zeros, has_lo,
            jnp.full_like(b, leaf_val)], axis=1)
        upper = jnp.stack([
            b + has_up, j2, s_, zeros, jnp.maximum(spill, 0), zeros,
            has_up, jnp.full_like(b, leaf_val)], axis=1)
        return jnp.stack([lower, upper], axis=1).reshape(2 * nt, 8)

    rowsL = run_rows(begin, cl, loff, 0, left_leaf)
    rowsR = run_rows(begin + nleft, cr, roff, 1, right_leaf)
    rows = jnp.concatenate([rowsL, rowsR])
    enable = rows[:, 6] * do_split.astype(jnp.int32)
    park = (begin // T).astype(jnp.int32)
    idx_seq = jnp.where(enable > 0, rows[:, 0], -1)
    idx_ff = jax.lax.cummax(
        jnp.concatenate([park[None], idx_seq])[None], axis=1)[0][1:]
    adv = (jnp.concatenate([park[None], idx_ff])[:-1] != idx_ff
           ).astype(jnp.int32)
    # (each launch's first enabled row is forced to adv=1 in place_runs'
    # chunk loop — chunk 0 covers the park-index case)
    rows = rows.at[:, 0].set(idx_ff)
    rows = rows.at[:, 5].set(adv)
    rows = rows.at[:, 6].set(enable)
    return rows


@functools.partial(
    jax.jit, static_argnames=("cap", "leaf_row", "interpret"),
    donate_argnums=(0,),
)
@phase_scope("partition")
def place_runs(
    rec,  # [W, n_pad] i32 — DONATED, aliased in place
    comp,  # [nt, W, 2T] i32 — the split kernel's compacted tiles
    go,  # [cap] i32 decision column, or None when ``counts`` is given
    begin, pcnt, nleft, do_split,
    left_leaf, right_leaf,
    cap: int,
    leaf_row: int,
    interpret: bool = False,
    counts=None,  # (cl [nt], cr [nt]) from the split kernel's cnt out
):
    """Scatter the compacted runs into the record in ONE aliased launch.
    Interpret mode falls back to the (bit-identical, slower) XLA
    scan-of-DUS placement so CPU tests stay meaningful; hardware parity
    of the kernel path is pinned by tools/tpu_parity_check.py."""
    W, n_pad = rec.shape
    T = TILE
    nt = cap // T
    iota = jnp.arange(cap, dtype=jnp.int32)
    valid = (iota < pcnt).astype(jnp.int32)
    if counts is not None:
        cl, cr = counts
    else:
        gov = jnp.asarray(go).astype(jnp.int32) * valid
        kt = gov.reshape(nt, T)
        cl = jnp.sum(kt, axis=1, dtype=jnp.int32)
        cr = jnp.sum(valid.reshape(nt, T) - kt, axis=1, dtype=jnp.int32)
    loff, roff = _run_offsets(cl, cr)

    if interpret:
        # reference placement (the XLA path the kernel replaces)
        win = jax.lax.dynamic_slice(rec, (0, begin), (W, cap))
        return _xla_place(
            rec, win, comp, loff, roff, nleft, iota, valid, do_split,
            begin, cap, leaf_row=leaf_row, left_leaf=left_leaf,
            right_leaf=right_leaf)

    rows = _place_table(begin, pcnt, nleft, cl, cr, loff, roff,
                        left_leaf, right_leaf, do_split, nt)
    CHUNK = PLACE_CHUNK
    total = 4 * nt
    n_chunks = -(-total // CHUNK)
    for c in range(n_chunks):
        lo = c * CHUNK
        sl = rows[lo: lo + CHUNK]
        en_c = sl[:, 6]
        # each launch's first enabled row must merge from the freshly
        # fetched block: the previous launch's writes are flushed to
        # HBM at ITS grid end, not resident in this launch's windows
        first_c = ((jnp.cumsum(en_c) == 1) & (en_c > 0)).astype(jnp.int32)
        sl = sl.at[:, 5].set(jnp.maximum(sl[:, 5], first_c))
        steps = sl.shape[0]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(steps,),
            in_specs=[
                pl.BlockSpec(
                    (1, W, 2 * T),
                    lambda i, sp: (sp[1, i] >> 1, 0, 0)),
                pl.BlockSpec((W, T), lambda i, sp: (0, sp[0, i])),
            ],
            out_specs=pl.BlockSpec((W, T), lambda i, sp: (0, sp[0, i])),
        )
        rec = pl.pallas_call(
            functools.partial(_place_kernel, W=W, leaf_row=leaf_row),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((W, n_pad), jnp.int32),
            input_output_aliases={2: 0},  # rec (incl. the prefetch arg)
            interpret=interpret,
        )(sl.T, comp, rec)
    return rec


@functools.partial(
    jax.jit,
    static_argnames=("F", "cap", "k", "fgroup", "return_comp",
                     "interpret", "routing"),
    donate_argnums=(0,),
)
@phase_scope("split_step")
def split_step_window(
    hists,  # [P, Fp, 4, Bp] f32 — DONATED, rows updated in place
    rec,  # [W, n_pad] i32
    begin, pcnt, do_split,
    f, thr, is_cat,  # split decision scalars
    parent_slot, new_slot,  # hists rows (left child reuses parent's)
    scal_f,  # [16] f32 — pallas_search._pack_scal layout
    meta,  # [Fp, 4] — pallas_search._pack_meta
    F: int, cap: int, k: int,
    fgroup: int = 8,
    return_comp: bool = False,
    interpret: bool = False,
    routing: str | None = None,  # compaction routing (None = ROUTING)
):
    """One-launch split step over window [begin, begin+cap): compaction
    + left-child histogram + subtract + two-child search + in-place
    hists-row updates.  Returns (hists', rec', nleft, res[2, 16]) — or,
    with ``return_comp``, (hists', comp, nleft, res, cl, cr, rec_pass)
    where ``rec_pass`` is the kernel's aliased record pass-through that
    MUST feed place_runs (feeding the original ``rec`` reintroduces
    the full-record copy this chain eliminates).

    The split decision AND the per-tile left counts live entirely in
    the kernel (_tile_go + the cnt output): the XLA side touches the
    record only through the kernel's block reads (on hardware, two
    T-aligned blocks roll-merged per tile — no materialized window
    slice), which is what lets the aliased placement (place_runs)
    update the record in place across the tier-cond chain instead of
    paying a full-record copy per split.

    The child leaf ids are stamped into the record's leaf-id row (see
    rec_height).  With ``return_comp`` the XLA placement (scan-of-DUS +
    roll/merge) is SKIPPED and the raw compacted tiles come back for
    ops.record.place_runs — the aliased placement kernel that replaces
    that whole chain.
    """
    W, n_pad = rec.shape
    T = TILE
    assert cap % T == 0, (cap, T)
    assert n_pad % T == 0, (n_pad, T)
    nt = cap // T
    nblocks = n_pad // T
    P, Fp, _, Bp = hists.shape

    i32 = functools.partial(jnp.asarray, dtype=jnp.int32)
    b0 = i32(begin) // T
    roff_in = i32(begin) % T
    scal_i = jnp.stack([
        i32(parent_slot), i32(parent_slot), i32(new_slot), i32(do_split),
        jnp.maximum(i32(f), 0), i32(thr), i32(is_cat), i32(pcnt),
        b0, roff_in])

    direct_read = not interpret
    off = 1 if direct_read else 0  # pipeline offset (see the kernel)
    # block walk of the single aliased record view: b0, b0+1, ..,
    # b0+nt (clamped), parked on the last block for the tail steps
    def _rec_idx(i, si, sf):
        return (0, jnp.minimum(si[8] + jnp.minimum(i, nt), nblocks - 1))

    def _tile_idx(i):  # comp/cnt block for the tile processed at step i
        return jnp.clip(i - off, 0, nt - 1)

    if direct_read:
        data_in = [rec]
        data_specs = [pl.BlockSpec((W, T), _rec_idx)]
    else:
        # interpret fallback: materialized window slice (pltpu.roll
        # paths are hardware-only; CPU tests keep the reference DS)
        data_in = [jax.lax.dynamic_slice(rec, (0, begin), (W, cap))]
        data_specs = [
            pl.BlockSpec(
                (W, T), lambda i, si, sf: (0, jnp.minimum(i, nt - 1))),
        ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nt + 2 + off,),
        in_specs=data_specs + [
            pl.BlockSpec(
                (1, Fp, 4, Bp),
                lambda i, si, sf: (jnp.where(i <= nt + off, si[0], si[2]),
                                   0, 0, 0)),
            pl.BlockSpec((Fp, 4), lambda i, si, sf: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, Fp, 4, Bp),
                lambda i, si, sf: (jnp.where(i <= nt + off, si[1], si[2]),
                                   0, 0, 0)),
            pl.BlockSpec((1, W, 2 * T),
                         lambda i, si, sf: (_tile_idx(i), 0, 0)),
            pl.BlockSpec((2, 16), lambda i, si, sf: (0, 0)),
            # counts ride the LANE axis: a (1, 128) block on [1, nt*128]
            # is Mosaic-legal (major dim == array dim), a [nt, 128]
            # row-per-tile layout is not (sublane dim 1)
            pl.BlockSpec((1, 128),
                         lambda i, si, sf: (0, _tile_idx(i))),
        ] + ([
            # aliased identity pass-through of the record (same block
            # walk as the input view): the output VALUE feeds
            # place_runs so every link of the record chain is
            # single-use — see the kernel docstring's copy note
            pl.BlockSpec((W, T), _rec_idx),
        ] if direct_read else []),
        scratch_shapes=[pltpu.VMEM((Fp, 4, Bp), jnp.float32)] + (
            [pltpu.VMEM((W, T), jnp.int32)] if direct_read else []),
    )
    hists_idx = 2 + len(data_in)  # incl. the 2 prefetch args
    out_shape = [
        jax.ShapeDtypeStruct((P, Fp, 4, Bp), jnp.float32),
        jax.ShapeDtypeStruct((nt, W, 2 * T), jnp.int32),
        jax.ShapeDtypeStruct((2, 16), jnp.float32),
        jax.ShapeDtypeStruct((1, nt * 128), jnp.int32),
    ]
    aliases = {hists_idx: 0}
    if direct_read:
        out_shape.append(jax.ShapeDtypeStruct((W, n_pad), jnp.int32))
        aliases[2] = 4  # recA -> rec pass-through
    outs = pl.pallas_call(
        functools.partial(
            _split_step_kernel, W=W, F=F, k=k, Bp=Bp, nt=nt,
            fgroup=fgroup, direct_read=direct_read, routing=routing),
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
    )(scal_i, scal_f, *data_in, hists, meta)
    if direct_read:
        hists_new, comp, res, cnt, rec_pass = outs
    else:
        hists_new, comp, res, cnt = outs
        rec_pass = rec

    # tile counts from the KERNEL: cl from the cnt output, per-tile
    # valid counts from pcnt alone — no go vector, no record read
    cl = cnt.reshape(nt, 128)[:, 0]
    vt = jnp.clip(pcnt - jnp.arange(nt, dtype=jnp.int32) * T, 0, T)
    cr = vt - cl
    nleft = jnp.sum(cl, dtype=jnp.int32)

    if return_comp:
        return hists_new, comp, nleft, res, cl, cr, rec_pass

    loff, roff = _run_offsets(cl, cr)
    iota = jnp.arange(cap, dtype=jnp.int32)
    valid = (iota < pcnt).astype(jnp.int32)
    win = (data_in[0] if not direct_read
           else jax.lax.dynamic_slice(rec_pass, (0, begin), (W, cap)))
    rec2 = _xla_place(
        rec_pass, win, comp, loff, roff, nleft, iota, valid, do_split,
        begin, cap, leaf_row=num_words(F, k) + 4,
        left_leaf=parent_slot, right_leaf=new_slot)
    return hists_new, rec2, nleft, res


@functools.partial(
    jax.jit, static_argnames=("cap", "leaf_row", "direct", "interpret",
                              "routing"))
@phase_scope("partition")
def partition_window(
    rec: jax.Array,  # [W, n_pad] i32 (aliased in-kernel when direct)
    go: jax.Array,  # [cap] i32: left-going (valid rows only)
    begin: jax.Array,
    pcnt: jax.Array,
    do_split: jax.Array,
    cap: int,
    left_leaf: jax.Array | None = None,
    right_leaf: jax.Array | None = None,
    leaf_row: int = -1,  # record row to stamp child leaf ids into
    direct: bool = False,  # aliased in-kernel placement (place_runs)
    interpret: bool = False,
    routing: str | None = None,  # compaction routing (None = ROUTING)
):
    """Stably partition window [begin, begin+cap) of ``rec``: the
    parent's rows [0, pcnt) become left-rows ++ right-rows (original
    order within each), positions [pcnt, cap) — other leaves' rows
    inside the static tier window, or the n_pad tail — are preserved
    exactly.  Returns (rec', nleft).  DataPartition::Split
    (data_partition.hpp:91-139) re-designed for the TPU memory system.
    With ``leaf_row`` >= 0 the child leaf ids are stamped over the
    parent's kept range (see rec_height's leaf-id row).  ``routing``
    picks the compaction strategy (module docstring); both produce
    bitwise-identical results (tests/test_partition_routing.py).
    """
    W = rec.shape[0]
    T = TILE
    assert cap % T == 0, (cap, T)
    nt = cap // T

    win = jax.lax.dynamic_slice(rec, (0, begin), (W, cap))
    iota = jnp.arange(cap, dtype=jnp.int32)
    # i32 from the start: pred (1-bit) arrays at [cap, 1]-ish shapes
    # bounce between bit layouts (measured ~80-100 ms/tree of copies;
    # callers pass go as i32 via serial._go_i32)
    valid = (iota < pcnt).astype(jnp.int32)
    gov = jnp.asarray(go).astype(jnp.int32) * valid
    nleft = jnp.sum(gov, dtype=jnp.int32)

    kt = gov.reshape(nt, T)
    cl = jnp.sum(kt, axis=1, dtype=jnp.int32)
    # rights per tile INCLUDE the invalid tail: invalids are a SUFFIX of
    # the window, so within any tile valid rights precede invalids and
    # each right-run's valid prefix lands at the right global offset;
    # the garbage beyond total-valid-rights is cut by the final selects
    cr = jnp.sum(valid.reshape(nt, T) - kt, axis=1, dtype=jnp.int32)
    loff, roff = _run_offsets(cl, cr)

    if _resolve_routing(routing) == "prefix":
        # go flags ride ROW 0 of a sublane-aligned [8, cap] operand
        # (see _compact_kernel_prefix); rows 1-7 are zero padding
        gov8 = jnp.pad(gov[None], ((0, 7), (0, 0)))
        comp = pl.pallas_call(
            functools.partial(_compact_kernel_prefix, W=W),
            grid=(nt,),
            in_specs=[
                pl.BlockSpec((W, T), lambda i: (0, i)),
                pl.BlockSpec((8, T), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((1, W, 2 * T), lambda i: (i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((nt, W, 2 * T), jnp.int32),
            interpret=interpret,
        )(win, gov8)
    else:
        comp = pl.pallas_call(
            functools.partial(_compact_kernel, W=W),
            grid=(nt,),
            in_specs=[
                pl.BlockSpec((W, T), lambda i: (0, i)),
                pl.BlockSpec((T, 1), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((1, W, 2 * T), lambda i: (i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((nt, W, 2 * T), jnp.int32),
            interpret=interpret,
        )(win, gov.reshape(cap, 1))

    if direct and not interpret:
        # aliased in-kernel placement: no scan-of-DUS and no copy of
        # the record through downstream cond boundaries (place_runs
        # itself falls back to _xla_place under interpret)
        rec2 = place_runs(
            rec, comp, gov, begin, pcnt, nleft, do_split,
            left_leaf, right_leaf, cap=cap, leaf_row=leaf_row,
            interpret=interpret)
        return rec2, nleft

    rec2 = _xla_place(
        rec, win, comp, loff, roff, nleft, iota, valid, do_split, begin,
        cap, leaf_row=leaf_row, left_leaf=left_leaf,
        right_leaf=right_leaf)
    return rec2, nleft
