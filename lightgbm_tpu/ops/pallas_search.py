"""Pallas TPU kernel for the per-split best-threshold search.

The round-3 on-chip profile (tools/profile_split.py, BASELINE.md) showed
the leaf-wise loop bound by PER-OP overhead, not data volume: the jnp
split search compiles to ~60 small [F, B]-shaped fusions per split
(~1.6 ms), 4x the histogram kernel itself, and no jnp-level
restructuring escapes the per-op cost (batching the two children into
[2, F, B] ops left the steady state unchanged at ~0.95 s/tree).  This
kernel runs the ENTIRE two-child search — suffix sums, gain grid,
validity masking, deterministic (feature asc, bin desc) winner
selection, and winner-stat extraction — as ONE launch.

Design notes:

* Mosaic wants (sublane, lane) register shapes, so the kernel works in
  STRICTLY rank-2 arrays: the two children's [F, B, 3] histograms are
  pre-flattened to one [6F, B] operand (child-major, then stat, then
  feature), the two children unroll as Python iterations, scalars stay
  [1, 1] slices, and feature metadata arrives pre-transposed as [F, 4].
* Suffix sums ride the MXU: tail[t] = sum_{b>t} h[b] is one dot with
  the strict upper-triangular ones matrix at precision=HIGHEST
  (f32-accurate bf16 passes) — no reliance on a Mosaic cumsum lowering.
* The deterministic tie-break reproduces ops/split.py exactly under
  exact float equality: per feature the LARGEST threshold among
  equal-gain maxima, across features the SMALLEST feature index
  (split_info.hpp:98-103 semantics).
* Outputs are a [2, 16] f32 row pair (gain, feature, threshold, six
  stats, two leaf outputs); the host-side wrapper casts feature and
  threshold back to int32 and rebuilds the two SplitResults.

The jnp path in ops/split.py remains the reference implementation (and
the CPU / float64 path); tests pin this kernel against it in interpret
mode, including crafted exact ties.  Reference scan being replaced:
FeatureHistogram::FindBestThreshold* (feature_histogram.hpp:116-246).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .split import SplitResult, K_EPSILON

NEG = -3.4e38  # "no split" sentinel (python float on purpose: a jnp
# scalar would be a captured constant inside the kernel)
BIG = 2**30


def _tri(B):
    """Strict upper-triangular ones: tri[b, t] = 1.0 iff b > t."""
    bi = jax.lax.broadcasted_iota(jnp.int32, (B, B), 0)
    ti = jax.lax.broadcasted_iota(jnp.int32, (B, B), 1)
    return (bi > ti).astype(jnp.float32)


def _tail_of(x, tri):
    """Exclusive suffix sums along bins: tail[., t] = sum_{b>t} x[., b]
    via one MXU dot at HIGHEST precision (f32-accurate)."""
    return jax.lax.dot_general(
        x, tri, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


def _pack_meta(feature_mask, num_bins_per_feature, is_categorical, Fp):
    """[F] feature metadata -> the kernels' [Fp, 4] i32 operand (padded
    features get feature_mask 0 and never validate)."""
    F = feature_mask.shape[0]
    meta = jnp.stack([
        feature_mask.astype(jnp.int32),
        num_bins_per_feature.astype(jnp.int32),
        is_categorical.astype(jnp.int32),
        jnp.zeros(F, jnp.int32),
    ], axis=1)
    if Fp != F:
        meta = jnp.pad(meta, ((0, Fp - F), (0, 0)))
    return meta


def _child_search(c, hg, hh, hc, tg, th, tc, scal_ref, meta_ref, out_ref,
                  F, B):
    """One child's full search given its stat planes [F, B] and their
    exclusive suffix sums; writes the child's [1, 16] result row.

    Mosaic-friendly shapes only: [F, B] / [F, 1] vectors, TRUE scalars
    from the SMEM-prefetched ``scal_ref`` (scalar splats broadcast
    freely; [1,1]->[F,B] tensor broadcasts do not on this stack), and
    scalar full-array reduces for the winner selection.
    """
    fmask = meta_ref[:, 0:1] > 0  # [F, 1]
    nb = meta_ref[:, 1:2]  # [F, 1]
    iscat = meta_ref[:, 2:3] > 0  # [F, 1]
    bins = jax.lax.broadcasted_iota(jnp.int32, (F, B), 1)
    # pure logical ops, not where-on-bools: Mosaic cannot truncate the
    # i8 select result back to i1
    in_range = ((iscat & (bins < nb)) | (~iscat & (bins < nb - 1))) & fmask
    fi = jax.lax.broadcasted_iota(jnp.int32, (F, 1), 0)
    lane16 = jax.lax.broadcasted_iota(jnp.int32, (1, 16), 1)

    min_data = scal_ref[8]
    min_hess = scal_ref[9]
    l1 = scal_ref[10]
    l2 = scal_ref[11]
    min_gain = scal_ref[12]

    def leaf_gain(sg, sh):
        reg = jnp.maximum(jnp.abs(sg) - l1, 0.0)
        return reg * reg / (sh + l2)

    can = scal_ref[4 * c + 0] > 0.0  # scalar bool
    sg_t = scal_ref[4 * c + 1]
    sh_t = scal_ref[4 * c + 2]
    cnt_t = scal_ref[4 * c + 3]

    left_g = jnp.where(iscat, hg, sg_t - tg)
    left_h = jnp.where(iscat, hh, sh_t - th)
    left_c = jnp.where(iscat, hc, cnt_t - tc)
    right_g = jnp.where(iscat, sg_t - hg, tg)
    right_h = jnp.where(iscat, sh_t - hh, th)
    right_c = jnp.where(iscat, cnt_t - hc, tc)

    gain_shift = leaf_gain(sg_t, sh_t)  # scalar
    gains = leaf_gain(left_g, left_h) + leaf_gain(right_g, right_h)
    valid = (
        in_range
        & (left_c >= min_data) & (right_c >= min_data)
        & (left_h >= min_hess) & (right_h >= min_hess)
        & (gains >= gain_shift + min_gain)
        & can
    )
    score = jnp.where(valid, gains, NEG)  # [F, B]

    # deterministic winner: global max; largest t per feature among
    # maxima; smallest such feature
    maxg = jnp.max(score)  # scalar
    at_max = (score == maxg) & valid
    tbest = jnp.max(jnp.where(at_max, bins, -1), axis=1,
                    keepdims=True)  # [F, 1]
    fbest = jnp.min(jnp.where(tbest >= 0, fi, BIG))  # scalar
    thr = jnp.max(jnp.where(fi == fbest, tbest, -1))  # scalar

    sel = (fi == fbest) & (bins == thr)  # [F, B]

    def pick(x):
        return jnp.sum(jnp.where(sel, x, 0.0))  # scalar

    lg, lh, lc = pick(left_g), pick(left_h), pick(left_c)
    rg, rh, rc = pick(right_g), pick(right_h), pick(right_c)

    def leaf_out(sg, sh):
        reg = jnp.maximum(jnp.abs(sg) - l1, 0.0)
        return -jnp.sign(sg) * reg / (sh + l2)

    ok = maxg > NEG  # scalar bool
    vals = [
        jnp.where(ok, maxg - gain_shift, -jnp.inf),
        jnp.where(ok, fbest, -1).astype(jnp.float32),
        jnp.where(ok, thr, 0).astype(jnp.float32),
        lg, lh, lc, rg, rh, rc,
        leaf_out(lg, lh), leaf_out(rg, rh),
    ]
    # assemble the [1, 16] row with lane selects (scalar splats are
    # the one broadcast form this Mosaic supports everywhere)
    row = jnp.zeros((1, 16), jnp.float32)
    for j, v in enumerate(vals):
        row = jnp.where(lane16 == j, v, row)
    out_ref[c:c + 1, :] = row


def _search2_kernel(scal_ref, hist_ref, meta_ref, out_ref, *, F, B):
    """One grid step: both children end-to-end.

    scal_ref [16]    f32 SMEM  (canL, lsg, lsh, lc, canR, rsg, rsh, rc,
                                min_data, min_hess, l1, l2, min_gain)
    hist_ref [6F, B] f32       child-major [c, s, f] rows: g, h, count
    meta_ref [F, 4]  i32       (feature_mask, nbpf, is_categorical, pad)
    out_ref  [2, 16] f32
    """
    h = hist_ref[...]  # [6F, B]
    # tail[row, t] = sum_{b > t} h[row, b] for ALL six (child, stat) rows
    tail = _tail_of(h, _tri(B))  # [6F, B]
    for c in range(2):
        base = c * 3 * F
        _child_search(
            c,
            h[base:base + F], h[base + F:base + 2 * F],
            h[base + 2 * F:base + 3 * F],
            tail[base:base + F],
            tail[base + F:base + 2 * F] + K_EPSILON,  # kEpsilon seed
            tail[base + 2 * F:base + 3 * F],
            scal_ref, meta_ref, out_ref, F, B,
        )


def _search2_kernel_raw(scal_ref, hist_ref, meta_ref, out_ref, *, F, B):
    """Raw-layout variant: hist_ref [2, F, 4, B] is the histogram
    buffer's KERNEL-NATIVE layout (ops/pallas_histogram raw path), so
    the split step never converts layouts.  Stat planes come from
    static rank-4 indexing (supported by this Mosaic); everything else
    is the shared per-child search."""
    h = hist_ref[...]  # [2, F, 4, B]
    tri = _tri(B)

    for c in range(2):
        hg, hh, hc = h[c, :, 0, :], h[c, :, 1, :], h[c, :, 2, :]
        _child_search(
            c, hg, hh, hc,
            _tail_of(hg, tri), _tail_of(hh, tri) + K_EPSILON,
            _tail_of(hc, tri),
            scal_ref, meta_ref, out_ref, F, B,
        )


@functools.partial(jax.jit, static_argnames=("interpret",))
def search2_pallas(
    h_left, h_right,  # [F, B, 3] f32
    lsg, lsh, lc, rsg, rsh, rc,  # scalars
    can,  # scalar bool (shared by both children: same depth)
    feature_mask, num_bins_per_feature, is_categorical,  # [F]
    min_data_in_leaf, min_sum_hessian_in_leaf,
    lambda_l1, lambda_l2, min_gain_to_split,
    interpret: bool = False,
):
    """Both children's best splits in one kernel launch; returns two
    scalar SplitResults matching ops/split.find_best_split bit-for-bit
    up to the suffix-sum accumulation order (MXU triangular dot vs
    sequential cumsum — identical under exact arithmetic)."""
    if h_left.dtype != jnp.float32 or h_right.dtype != jnp.float32:
        # a silent astype here would hide precision loss from a future
        # float64 hist_dtype caller; the f64 parity mode must stay on
        # the jnp search path (serial.py routes on hl.dtype)
        raise TypeError(
            f"search2_pallas requires float32 histograms, got "
            f"{h_left.dtype}/{h_right.dtype}"
        )
    F, B, _ = h_left.shape
    hist = (
        jnp.stack([h_left, h_right])  # [2, F, B, 3]
        .transpose(0, 3, 1, 2)  # [2, 3, F, B] child-major, stat, feature
        .reshape(6 * F, B)
        .astype(jnp.float32)
    )
    meta = _pack_meta(
        feature_mask, num_bins_per_feature, is_categorical, F)
    scal = _pack_scal(
        jnp.asarray(can, jnp.float32), lsg, lsh, lc, rsg, rsh, rc,
        min_data_in_leaf, min_sum_hessian_in_leaf,
        lambda_l1, lambda_l2, min_gain_to_split)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[
            pl.BlockSpec((6 * F, B), lambda i, s: (0, 0)),
            pl.BlockSpec((F, 4), lambda i, s: (0, 0)),
        ],
        out_specs=pl.BlockSpec((2, 16), lambda i, s: (0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_search2_kernel, F=F, B=B),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((2, 16), jnp.float32),
        interpret=interpret,
    )(scal, hist, meta)

    return _unpack(out, 0), _unpack(out, 1)


def _unpack(out, i):
    row = out[i]
    return SplitResult(
        gain=row[0],
        feature=row[1].astype(jnp.int32),
        threshold=row[2].astype(jnp.int32),
        left_sum_grad=row[3],
        left_sum_hess=row[4],
        left_count=row[5],
        right_sum_grad=row[6],
        right_sum_hess=row[7],
        right_count=row[8],
        left_output=row[9],
        right_output=row[10],
    )


def _pack_scal(canf, lsg, lsh, lc, rsg, rsh, rc,
               min_data, min_hess, l1, l2, min_gain):
    f32 = functools.partial(jnp.asarray, dtype=jnp.float32)
    return jnp.stack([
        canf, f32(lsg), f32(lsh), f32(lc),
        canf, f32(rsg), f32(rsh), f32(rc),
        f32(min_data), f32(min_hess), f32(l1), f32(l2), f32(min_gain),
        f32(0), f32(0), f32(0),
    ])  # [16] SMEM scalar-prefetch


def _fused_kernel(scal_i_ref, scal_f_ref, hrow_ref, hsmall_ref, meta_ref,
                  hists_out_ref, res_ref, scratch_ref, *, F, B):
    """Fused subtract + child-select + search + histogram-buffer update.

    Two sequential grid steps over ONE aliased histogram buffer:

      step 0: hrow_ref = the PARENT row (index map reads slot si[0]).
        Compute h_large = parent - h_small, route small/large to
        left/right, run the full two-child search (res_ref), write the
        left child's row in place of the parent (slot si[1]), stash the
        right child's row in VMEM scratch.
      step 1: hrow_ref = the OLD row of the new leaf's slot (si[2]).
        Write where(do_split, stashed right row, old row).

    The parent slot is never the new slot (si[1] == si[0] != si[2] in
    unpooled mode), so step 1's input prefetch cannot race step 0's
    writeback.  With input_output_aliasing the buffer is updated in
    place and NO [F, B]-sized histogram intermediate ever exists as an
    XLA value — the round-3 profile showed those intermediates' layout
    churn costing ~0.5 ms/split.

    scal_i [8] i32 SMEM: (parent_slot, left_slot, new_slot, do_split,
                          small_is_left, 0, 0, 0)
    scal_f [16] f32 SMEM: as _pack_scal
    """
    c = pl.program_id(0)
    do_split = scal_i_ref[3] > 0
    small_left = scal_i_ref[4] > 0

    @pl.when(c == 0)
    def _():
        parent = hrow_ref[0]  # [F, 4, B]
        hs = hsmall_ref[...]
        h_large = parent - hs
        # where on f32 tensors with a scalar pred: splat-select
        h_left = jnp.where(small_left, hs, h_large)
        h_right = jnp.where(small_left, h_large, hs)
        hists_out_ref[0] = jnp.where(do_split, h_left, parent)
        scratch_ref[...] = h_right

        tri = _tri(B)
        for cc in range(2):
            side = (h_left, h_right)[cc]
            hg, hh, hc = side[:, 0, :], side[:, 1, :], side[:, 2, :]
            _child_search(
                cc, hg, hh, hc,
                _tail_of(hg, tri), _tail_of(hh, tri) + K_EPSILON,
                _tail_of(hc, tri),
                scal_f_ref, meta_ref, res_ref, F, B,
            )

    @pl.when(c == 1)
    def _():
        hists_out_ref[0] = jnp.where(do_split, scratch_ref[...],
                                     hrow_ref[0])


@functools.partial(jax.jit, static_argnames=("interpret",), donate_argnums=(0,))
def search2_update_pallas(
    hists,  # [P, Fp, 4, Bp] f32 — DONATED, updated in place
    h_small,  # [Fp, 4, Bp] f32 — the smaller child's histogram
    parent_slot, new_slot,  # i32 row indices (parent/left reuse parent_slot)
    do_split, small_is_left,  # scalar bools
    lsg, lsh, lc, rsg, rsh, rc,  # scalars (left/right child totals)
    can,
    feature_mask, num_bins_per_feature, is_categorical,  # [F] (unpadded)
    min_data_in_leaf, min_sum_hessian_in_leaf,
    lambda_l1, lambda_l2, min_gain_to_split,
    interpret: bool = False,
):
    """One launch: subtract trick + child routing + two-child search +
    in-place histogram-buffer row updates.  Returns (hists, resL, resR).
    Unpooled layout only: the left child reuses the parent's slot."""
    P, Fp, _, Bp = hists.shape
    F = feature_mask.shape[0]
    meta = _pack_meta(
        feature_mask, num_bins_per_feature, is_categorical, Fp)
    scal_f = _pack_scal(
        jnp.asarray(can, jnp.float32), lsg, lsh, lc, rsg, rsh, rc,
        min_data_in_leaf, min_sum_hessian_in_leaf,
        lambda_l1, lambda_l2, min_gain_to_split)
    i32 = functools.partial(jnp.asarray, dtype=jnp.int32)
    scal_i = jnp.stack([
        i32(parent_slot), i32(parent_slot), i32(new_slot),
        i32(do_split), i32(small_is_left), i32(0), i32(0), i32(0)])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(2,),
        in_specs=[
            # step 0 reads the parent's row, step 1 the new slot's row
            pl.BlockSpec(
                (1, Fp, 4, Bp),
                lambda i, si, sf: (jnp.where(i == 0, si[0], si[2]),
                                   0, 0, 0)),
            pl.BlockSpec((Fp, 4, Bp), lambda i, si, sf: (0, 0, 0)),
            pl.BlockSpec((Fp, 4), lambda i, si, sf: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, Fp, 4, Bp),
                lambda i, si, sf: (jnp.where(i == 0, si[1], si[2]),
                                   0, 0, 0)),
            pl.BlockSpec((2, 16), lambda i, si, sf: (0, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((Fp, 4, Bp), jnp.float32)],
    )
    hists_new, out = pl.pallas_call(
        functools.partial(_fused_kernel, F=Fp, B=Bp),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((P, Fp, 4, Bp), jnp.float32),
            jax.ShapeDtypeStruct((2, 16), jnp.float32),
        ],
        input_output_aliases={2: 0},  # hists (after the 2 prefetch args)
        interpret=interpret,
    )(scal_i, scal_f, hists, h_small, meta)
    return hists_new, _unpack(out, 0), _unpack(out, 1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def search2_pallas_raw(
    h2,  # [2, Fp, 4, Bp] f32 — the raw-layout histogram rows
    lsg, lsh, lc, rsg, rsh, rc,  # scalars
    can,  # scalar bool
    feature_mask, num_bins_per_feature, is_categorical,  # [F] (unpadded)
    min_data_in_leaf, min_sum_hessian_in_leaf,
    lambda_l1, lambda_l2, min_gain_to_split,
    interpret: bool = False,
):
    """search2_pallas over kernel-native [2, Fp, 4, Bp] histogram rows:
    no layout conversion anywhere between the histogram kernel, the
    subtract trick, and this search.  Padded features are masked out
    via the padded feature_mask; padded bins exceed nbpf and never
    validate."""
    _, Fp, _, Bp = h2.shape
    F = feature_mask.shape[0]
    meta = _pack_meta(
        feature_mask, num_bins_per_feature, is_categorical, Fp)
    scal = _pack_scal(
        jnp.asarray(can, jnp.float32), lsg, lsh, lc, rsg, rsh, rc,
        min_data_in_leaf, min_sum_hessian_in_leaf,
        lambda_l1, lambda_l2, min_gain_to_split)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[
            pl.BlockSpec((2, Fp, 4, Bp), lambda i, s: (0, 0, 0, 0)),
            pl.BlockSpec((Fp, 4), lambda i, s: (0, 0)),
        ],
        out_specs=pl.BlockSpec((2, 16), lambda i, s: (0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_search2_kernel_raw, F=Fp, B=Bp),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((2, 16), jnp.float32),
        interpret=interpret,
    )(scal, h2.astype(jnp.float32), meta)
    return _unpack(out, 0), _unpack(out, 1)
