from .histogram import histogram_feature_major, histogram_by_leaf
from .split import find_best_split, SplitResult

__all__ = [
    "histogram_feature_major",
    "histogram_by_leaf",
    "find_best_split",
    "SplitResult",
]
