"""Gather-free ensemble prediction as MXU matmuls.

The canonical per-row root-to-leaf walk (models/tree.py
predict_leaf_raw; reference Tree::Predict, tree.h:226-238,
predictor.hpp:82-155) costs one indexed feature gather per row per
level per tree — the exact HBM access pattern (~30 ns/element) whose
elimination from TRAINING was the round-3/4 headline.  Round-4
measured the walk at 104.9 s for 1M rows x 100 trees on a v5e-1
against the reference's 17.0 s threaded file predictor.

This module re-states prediction as three dense per-tree ops with NO
indexed access at all:

1. ``vals = X @ Sel`` — the per-node split-feature values via a
   one-hot selection matmul ``[n, F] @ [F, L-1]``.  One-hot selection
   is EXACT on the MXU: bf16x3/bf16x6 decomposition represents each
   f32 addend exactly and 0-products vanish, so ``vals[i, j]`` is
   bitwise ``X[i, feat[j]]``.
2. ``go = cmp(vals, thr)`` — elementwise; numerical ``<=``,
   categorical ``==`` on int casts (tree.h:116-122 routing).
3. ``match = go @ M + base`` — the signed path-incidence matmul
   ``[n, L-1] @ [L-1, L]``.  ``M[a, l]`` is +1 when node ``a`` is an
   ancestor of leaf ``l`` with ``l`` in its LEFT subtree, -1 for
   RIGHT, else 0; ``base[l]`` counts the -1 entries.  ``match[i, l]``
   then counts the ancestors of ``l`` whose decision row ``i``
   satisfies, so ``match == depth[l]`` picks exactly the leaf the walk
   would reach.  All operands are 0/±1 and depths are < 2^8, exact in
   bf16 inputs with f32 MXU accumulation.

Leaf values follow as ``hit @ leaf_value`` and leaf indices as
``argmax(hit)`` — every step a large, static-shape, fusable dense op.

NaN caveat: the walk routes NaN feature values right (NaN <= t is
false).  A NaN would poison the selection matmul (0 * NaN = NaN), so
X is sanitized NaN -> FLT_MAX first, which routes right everywhere a
finite threshold is used.  (Sole divergence: a NaN in a CATEGORICAL
feature walks as category 0 in the gather path — int cast of NaN —
and as INT_MAX here; the reference snapshot predates missing-value
handling entirely, so neither behavior is load-bearing.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..obs.device_time import phase_scope

_FLT_MAX = jnp.float32(3.4028235e38)


def _sanitize(X):
    """NaN AND +/-inf would poison the selection matmul (0 * inf = NaN
    contaminates every non-selecting node).  Clamp to +/-FLT_MAX
    sign-preserving: with finite thresholds, +/-FLT_MAX routes exactly
    like +/-inf does in the walk path."""
    return jnp.nan_to_num(X, nan=_FLT_MAX, posinf=_FLT_MAX,
                          neginf=-_FLT_MAX)


@jax.jit
@phase_scope("predict")
def build_path_tables(stacked):
    """Per-tree path-incidence tables from a stacked Tree pytree
    (leading axis [T], or [n_iter, K] — mirrored in the outputs):
    ``(M [.., L-1, L] bf16, base [.., L] f32, depth [.., L] i32,
    valid [.., L] bool)``.

    Relies on the construction invariant that an internal node's
    internal children carry LARGER node indices (node ids are assigned
    in split order, tree.cpp:52-96; both our grower and reference
    model files satisfy it), so one ascending pass propagates each
    node's signed ancestor vector to its children.
    """

    def per_tree(num_leaves, left_child, right_child, leaf_parent):
        Lm1 = left_child.shape[0]
        L = Lm1 + 1

        def body(j, pd):
            P, D = pd
            rowj = P[j]
            dj = D[j]
            cl = left_child[j]
            cr = right_child[j]
            ok = j < num_leaves - 1  # unused nodes carry zeroed children
            okl = ok & (cl >= 0)
            okr = ok & (cr >= 0)
            # dump writes for leaf/invalid children into the spare row
            il = jnp.where(okl, cl, Lm1)
            ir = jnp.where(okr, cr, Lm1)
            P = P.at[il].set(jnp.where(okl, rowj.at[j].set(1.0), P[il]))
            D = D.at[il].set(jnp.where(okl, dj + 1, D[il]))
            P = P.at[ir].set(jnp.where(okr, rowj.at[j].set(-1.0), P[ir]))
            D = D.at[ir].set(jnp.where(okr, dj + 1, D[ir]))
            return P, D

        P0 = jnp.zeros((Lm1 + 1, Lm1), jnp.float32)
        D0 = jnp.zeros(Lm1 + 1, jnp.int32)
        P, D = jax.lax.fori_loop(0, Lm1, body, (P0, D0))

        leaves = jnp.arange(L, dtype=jnp.int32)
        has_p = leaf_parent >= 0
        pidx = jnp.maximum(leaf_parent, 0)
        is_left = left_child[pidx] == ~leaves
        sign = jnp.where(is_left, 1.0, -1.0).astype(jnp.float32)
        own = sign[:, None] * (
            pidx[:, None] == jnp.arange(Lm1, dtype=jnp.int32)[None, :]
        ).astype(jnp.float32)
        cols = jnp.where(has_p[:, None], P[pidx] + own, 0.0)  # [L, L-1]
        depth = jnp.where(has_p, D[pidx] + 1, 0)
        base = jnp.sum((cols == -1.0).astype(jnp.float32), axis=1)
        valid = leaves < num_leaves
        return cols.T.astype(jnp.bfloat16), base, depth, valid

    lead = stacked.num_leaves.shape  # (T,) or (n_iter, K)
    nd = len(lead)
    args = (stacked.num_leaves, stacked.left_child, stacked.right_child,
            stacked.leaf_parent)
    flat = [a.reshape((-1,) + a.shape[nd:]) for a in args]
    out = jax.vmap(per_tree)(*flat)
    return tuple(o.reshape(lead + o.shape[1:]) for o in out)


def _tree_hit(X, feat, thr, is_cat, M, base, depth, valid):
    """[n, L] bool: which (valid) leaf each row lands in, for one tree."""
    F = X.shape[1]
    sel = (
        (jnp.maximum(feat, 0)[None, :] == jnp.arange(F, dtype=jnp.int32)[:, None])
        & (feat >= 0)[None, :]
    ).astype(jnp.float32)
    vals = jax.lax.dot_general(
        X, sel, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )  # [n, L-1], exact copies of the selected feature values
    go = jnp.where(
        is_cat[None, :],
        vals.astype(jnp.int32) == thr.astype(jnp.int32),
        vals <= thr[None, :],
    ).astype(jnp.bfloat16)
    match = jax.lax.dot_general(
        go, M, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + base[None, :]
    return (match.astype(jnp.int32) == depth[None, :]) & valid[None, :]


@jax.jit
@phase_scope("predict")
def ensemble_sum_matmul(tables, stacked, X):
    """Σ over trees of per-row outputs on RAW features; ``stacked`` and
    each table carry leading axes [n_iter, K]; returns [K, n].  Same
    contract as models/tree.py ensemble_sum_raw, per-tree outputs
    bitwise identical (one-hot selection and 0/1-weighted leaf-value
    sums are exact)."""
    K, n = stacked.leaf_value.shape[1], X.shape[0]
    X = _sanitize(X)

    def step(acc, xs):
        t, (M, base, depth, valid) = xs
        def one(feat, thr, dt, lv, M, base, depth, valid):
            hit = _tree_hit(X, feat, thr, dt == 1, M, base, depth, valid)
            return jnp.sum(hit.astype(jnp.float32) * lv[None, :], axis=1)
        out = jax.vmap(one)(
            t.split_feature_real, t.threshold_real, t.decision_type,
            t.leaf_value, M, base, depth, valid,
        )
        return acc + out, None

    acc, _ = jax.lax.scan(
        step, jnp.zeros((K, n), jnp.float32), (stacked, tables))
    return acc


@jax.jit
@phase_scope("predict")
def ensemble_leaves_matmul(tables, stacked, X):
    """Per-tree leaf indices on raw features (flat leading axis [T]) ->
    [T, n] int32 — contract of models/tree.py ensemble_leaves_raw."""
    X = _sanitize(X)

    def step(_, xs):
        t, (M, base, depth, valid) = xs
        hit = _tree_hit(
            X, t.split_feature_real, t.threshold_real,
            t.decision_type == 1, M, base, depth, valid,
        )
        return None, jnp.argmax(hit, axis=1).astype(jnp.int32)

    _, leaves = jax.lax.scan(step, None, (stacked, tables))
    return leaves
