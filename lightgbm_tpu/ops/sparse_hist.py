"""O(nnz) sparse histogram construction (level/depthwise growth).

The reference histograms high-sparsity features in O(nnz) via
OrderedSparseBin's leaf-grouped (row, bin) pair scans
(src/io/ordered_sparse_bin.hpp:79-92); the dense path is O(n * F)
regardless of sparsity.  TPU-native equivalent over the binned CSR
storage (io/sparse.py SparseBins):

  * every STORED entry (row, feature, bin) scatter-adds its row's
    (g*m, h*m, m) into hist[leaf(row), feature, bin] — one
    ``segment_sum`` over nnz keys;
  * every ABSENT entry sits in its feature's DEFAULT bin (the bin of
    raw 0.0, bin.h:150-160): its mass is reconstructed per
    (leaf, feature) as  leaf_totals[leaf] - stored_sums[leaf, feature]
    and added at ``default_bins[feature]`` — O(L * F), no per-entry
    work.

Total: O(nnz + n + L*F*B) instead of O(n*F) — the asymptotic win the
reference's sparse path exists for, without per-row pointer chasing.
The split ROUTING still reads the dense binned matrix (one feature row
per split, O(n) — independent of F), so this module only replaces the
histogram construction, which is where the O(n*F) lived.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def entry_rows(indptr: np.ndarray) -> np.ndarray:
    """Row index of every stored CSR entry: expand ``indptr`` once at
    dataset build (host-side, O(nnz))."""
    counts = np.diff(indptr).astype(np.int64)
    return np.repeat(np.arange(len(counts), dtype=np.int32), counts)


@functools.partial(
    jax.jit,
    static_argnames=("num_leaves", "num_features", "num_bins"),
)
def sparse_histogram_by_leaf(
    erow: jax.Array,  # [nnz] i32 row of each stored entry
    ecol: jax.Array,  # [nnz] i32 inner feature of each stored entry
    ebin: jax.Array,  # [nnz] bin of each stored entry (u8/u16)
    default_bins: jax.Array,  # [F] i32 bin of raw 0.0 per feature
    leaf_id: jax.Array,  # [n] i32 leaf per row
    grad: jax.Array,  # [n]
    hess: jax.Array,  # [n]
    mask: jax.Array,  # [n] bagging mask
    num_leaves: int,
    num_features: int,
    num_bins: int,
) -> jax.Array:
    """hist[L, F, B, 3] in O(nnz + n + L*F*B) — same result as the dense
    histogram_by_leaf on the densified matrix (pinned by tests)."""
    L, F, B = num_leaves, num_features, num_bins
    gm = (grad * mask).astype(jnp.float32)
    hm = (hess * mask).astype(jnp.float32)
    mm = mask.astype(jnp.float32)
    row_stats = jnp.stack([gm, hm, mm], axis=-1)  # [n, 3]

    # ---- stored entries: one segment_sum over nnz
    el = leaf_id[erow]  # [nnz]
    keys = (el * F + ecol.astype(jnp.int32)) * B + ebin.astype(jnp.int32)
    stored = jax.ops.segment_sum(
        row_stats[erow], keys, num_segments=L * F * B
    ).reshape(L, F, B, 3)

    # ---- absent entries: per-(leaf, feature) remainder at the default bin
    leaf_tot = jax.ops.segment_sum(
        row_stats, leaf_id, num_segments=L
    )  # [L, 3]
    stored_lf = stored.sum(axis=2)  # [L, F, 3]
    remainder = leaf_tot[:, None, :] - stored_lf  # [L, F, 3]
    hist = stored.reshape(L * F, B, 3)
    idx = jnp.broadcast_to(
        default_bins.astype(jnp.int32)[None, :], (L, F)
    ).reshape(L * F)
    hist = hist.at[jnp.arange(L * F), idx].add(remainder.reshape(L * F, 3))
    return hist.reshape(L, F, B, 3)


def make_sparse_hist_fn(sparse_bins, num_bins: int):
    """Depthwise-grower ``hist_fn`` closure over device-resident CSR
    arrays (signature: bins_T, leaf_id, grad, hess, mask, num_leaves —
    the dense bins_T argument is ignored).  Used when the dataset was
    ingested sparse and density is below Config.sparse_hist_density."""
    erow = jnp.asarray(entry_rows(np.asarray(sparse_bins.indptr)))
    ecol = jnp.asarray(sparse_bins.col)
    ebin = jnp.asarray(sparse_bins.bin)
    dbins = jnp.asarray(sparse_bins.default_bins, jnp.int32)
    F = int(sparse_bins.shape[1])

    def hist_fn(bins_T, leaf_id, grad, hess, mask, num_leaves):
        return sparse_histogram_by_leaf(
            erow, ecol, ebin, dbins, leaf_id, grad, hess, mask,
            num_leaves=num_leaves, num_features=F, num_bins=num_bins,
        )

    return hist_fn
