"""Vectorized best-split search over feature histograms.

Replaces the reference's per-feature threshold scans
(FeatureHistogram::FindBestThresholdForNumerical,
src/treelearner/feature_histogram.hpp:116-181, and
FindBestThresholdForCategorical, feature_histogram.hpp:187-246) with one
masked reduction over the whole [F, B] candidate grid:

* numerical: right-side sums via reverse cumulative sums over the bin
  axis; left = leaf totals - right (exactly the reference's accumulation
  order, including the kEpsilon seed on the right hessian).
* categorical: one-vs-rest — "left" is the single bin == threshold.
* gain/leaf-output formulas with L1/L2 regularization mirror
  GetLeafSplitGain / CalculateSplittedLeafOutput
  (feature_histogram.hpp:290-313).
* determinism: the reference scans thresholds HIGH->LOW with strict
  improvement (feature_histogram.hpp:129,154), so equal-gain ties keep
  the LARGEST threshold within a feature; across features the smaller
  feature index wins (SplitInfo::operator>, split_info.hpp:98-103).  We
  reproduce this by argmax-ing over (feature asc, bin desc) order.
  Matters for raw-space routing when bins between tied thresholds are
  empty — verified against the reference binary on binary.train.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

K_EPSILON = 1e-15
K_MIN_SCORE = -jnp.inf


class SplitResult(NamedTuple):
    """Scalar split decision for one leaf (SplitInfo, split_info.hpp:17-44)."""

    gain: jax.Array  # improvement over the un-split leaf (minus gain_shift)
    feature: jax.Array  # inner feature index (int32), -1 if no split
    threshold: jax.Array  # bin threshold (int32); left is bin <= t (== for cat)
    left_sum_grad: jax.Array
    left_sum_hess: jax.Array
    left_count: jax.Array
    right_sum_grad: jax.Array
    right_sum_hess: jax.Array
    right_count: jax.Array
    left_output: jax.Array
    right_output: jax.Array


def _leaf_split_gain(sum_grad, sum_hess, l1, l2):
    """GetLeafSplitGain (feature_histogram.hpp:290-298)."""
    reg = jnp.maximum(jnp.abs(sum_grad) - l1, 0.0)
    return reg * reg / (sum_hess + l2)


def _leaf_output(sum_grad, sum_hess, l1, l2):
    """CalculateSplittedLeafOutput (feature_histogram.hpp:306-313)."""
    reg = jnp.maximum(jnp.abs(sum_grad) - l1, 0.0)
    return -jnp.sign(sum_grad) * reg / (sum_hess + l2)


@functools.partial(jax.jit, static_argnames=())
def find_best_split(
    hist: jax.Array,  # [F, B, 3] (sum_grad, sum_hess, count) for one leaf
    sum_grad: jax.Array,  # scalar leaf totals (bookkept, not re-summed)
    sum_hess: jax.Array,
    num_data: jax.Array,  # scalar bagged row count in leaf
    feature_mask: jax.Array,  # [F] bool: usable this tree (feature_fraction)
    num_bins_per_feature: jax.Array,  # [F] int32
    is_categorical: jax.Array,  # [F] bool
    min_data_in_leaf: jax.Array,
    min_sum_hessian_in_leaf: jax.Array,
    lambda_l1: jax.Array,
    lambda_l2: jax.Array,
    min_gain_to_split: jax.Array,
    can_split: jax.Array,  # scalar bool (depth / leaf-size gating)
) -> SplitResult:
    F, B, _ = hist.shape
    hg, hh, hc = hist[..., 0], hist[..., 1], hist[..., 2]
    bins = jnp.arange(B, dtype=jnp.int32)

    # ---- right-side sums for numerical threshold t: bins > t
    # reverse cumsum: rsum[t] = sum_{b >= t+1} h[b]
    def rev_tail(x):  # [F, B] -> tail sums excluding bin t itself
        c = jnp.cumsum(x[:, ::-1], axis=1)[:, ::-1]  # inclusive suffix sums
        return jnp.concatenate([c[:, 1:], jnp.zeros((F, 1), x.dtype)], axis=1)

    num_right_g = rev_tail(hg)
    num_right_h = rev_tail(hh) + K_EPSILON  # matches kEpsilon seed (l.123)
    num_right_c = rev_tail(hc)

    # ---- categorical one-vs-rest: "left" = the single bin t
    cat_left_g, cat_left_h, cat_left_c = hg, hh, hc

    is_cat = is_categorical[:, None]
    left_g = jnp.where(is_cat, cat_left_g, sum_grad - num_right_g)
    left_h = jnp.where(is_cat, cat_left_h, sum_hess - num_right_h)
    left_c = jnp.where(is_cat, cat_left_c, num_data - num_right_c)
    right_g = jnp.where(is_cat, sum_grad - cat_left_g, num_right_g)
    right_h = jnp.where(is_cat, sum_hess - cat_left_h, num_right_h)
    right_c = jnp.where(is_cat, num_data - cat_left_c, num_right_c)

    # ---- validity (feature_histogram.hpp:133-142, 199-208)
    nb = num_bins_per_feature[:, None]
    in_range = jnp.where(is_cat, bins[None, :] < nb, bins[None, :] < nb - 1)
    valid = (
        in_range
        & feature_mask[:, None]
        & (left_c >= min_data_in_leaf)
        & (right_c >= min_data_in_leaf)
        & (left_h >= min_sum_hessian_in_leaf)
        & (right_h >= min_sum_hessian_in_leaf)
    )

    gain_shift = _leaf_split_gain(sum_grad, sum_hess, lambda_l1, lambda_l2)
    min_gain_shift = gain_shift + min_gain_to_split
    gains = _leaf_split_gain(left_g, left_h, lambda_l1, lambda_l2) + _leaf_split_gain(
        right_g, right_h, lambda_l1, lambda_l2
    )
    valid = valid & (gains >= min_gain_shift) & can_split
    gains = jnp.where(valid, gains, K_MIN_SCORE)

    # argmax over (feature asc, bin desc): reverse the bin axis so the
    # first maximum is the smallest feature with the LARGEST threshold
    flat = gains[:, ::-1].reshape(-1)
    best = jnp.argmax(flat)
    best_gain_raw = flat[best]
    feat = (best // B).astype(jnp.int32)
    thr = (B - 1 - best % B).astype(jnp.int32)
    splittable = best_gain_raw > K_MIN_SCORE

    lg = left_g[feat, thr]
    lh = left_h[feat, thr]
    lc = left_c[feat, thr]
    rg = right_g[feat, thr]
    rh = right_h[feat, thr]
    rc = right_c[feat, thr]
    return SplitResult(
        gain=jnp.where(splittable, best_gain_raw - gain_shift, K_MIN_SCORE),
        feature=jnp.where(splittable, feat, -1),
        threshold=jnp.where(splittable, thr, 0),
        left_sum_grad=lg,
        left_sum_hess=lh,
        left_count=lc,
        right_sum_grad=rg,
        right_sum_hess=rh,
        right_count=rc,
        left_output=_leaf_output(lg, lh, lambda_l1, lambda_l2),
        right_output=_leaf_output(rg, rh, lambda_l1, lambda_l2),
    )


# vectorized over leaves (depthwise grower / batched candidate evaluation)
find_best_split_leaves = jax.vmap(
    find_best_split,
    in_axes=(0, 0, 0, 0, None, None, None, None, None, None, None, None, 0),
)
