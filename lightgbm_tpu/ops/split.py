"""Vectorized best-split search over feature histograms.

Replaces the reference's per-feature threshold scans
(FeatureHistogram::FindBestThresholdForNumerical,
src/treelearner/feature_histogram.hpp:116-181, and
FindBestThresholdForCategorical, feature_histogram.hpp:187-246) with one
masked reduction over the whole [F, B] candidate grid:

* numerical: right-side sums via reverse cumulative sums over the bin
  axis; left = leaf totals - right (exactly the reference's accumulation
  order, including the kEpsilon seed on the right hessian).
* categorical: one-vs-rest — "left" is the single bin == threshold.
* gain/leaf-output formulas with L1/L2 regularization mirror
  GetLeafSplitGain / CalculateSplittedLeafOutput
  (feature_histogram.hpp:290-313).
* determinism: the reference scans thresholds HIGH->LOW with strict
  improvement (feature_histogram.hpp:129,154), so equal-gain ties keep
  the LARGEST threshold within a feature; across features the smaller
  feature index wins (SplitInfo::operator>, split_info.hpp:98-103).  We
  reproduce this by argmax-ing over (feature asc, bin desc) order.
  Matters for raw-space routing when bins between tied thresholds are
  empty — verified against the reference binary on binary.train.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..obs.device_time import phase_scope

K_EPSILON = 1e-15
K_MIN_SCORE = -jnp.inf


class SplitResult(NamedTuple):
    """Scalar split decision for one leaf (SplitInfo, split_info.hpp:17-44)."""

    gain: jax.Array  # improvement over the un-split leaf (minus gain_shift)
    feature: jax.Array  # inner feature index (int32), -1 if no split
    threshold: jax.Array  # bin threshold (int32); left is bin <= t (== for cat)
    left_sum_grad: jax.Array
    left_sum_hess: jax.Array
    left_count: jax.Array
    right_sum_grad: jax.Array
    right_sum_hess: jax.Array
    right_count: jax.Array
    left_output: jax.Array
    right_output: jax.Array


def _leaf_split_gain(sum_grad, sum_hess, l1, l2):
    """GetLeafSplitGain (feature_histogram.hpp:290-298)."""
    reg = jnp.maximum(jnp.abs(sum_grad) - l1, 0.0)
    return reg * reg / (sum_hess + l2)


def _leaf_output(sum_grad, sum_hess, l1, l2):
    """CalculateSplittedLeafOutput (feature_histogram.hpp:306-313)."""
    reg = jnp.maximum(jnp.abs(sum_grad) - l1, 0.0)
    return -jnp.sign(sum_grad) * reg / (sum_hess + l2)


@functools.partial(jax.jit, static_argnames=())
@phase_scope("split-search")
def find_best_split(
    hist: jax.Array,  # [F, B, 3] (sum_grad, sum_hess, count) for one leaf
    sum_grad: jax.Array,  # scalar leaf totals (bookkept, not re-summed)
    sum_hess: jax.Array,
    num_data: jax.Array,  # scalar bagged row count in leaf
    feature_mask: jax.Array,  # [F] bool: usable this tree (feature_fraction)
    num_bins_per_feature: jax.Array,  # [F] int32
    is_categorical: jax.Array,  # [F] bool
    min_data_in_leaf: jax.Array,
    min_sum_hessian_in_leaf: jax.Array,
    lambda_l1: jax.Array,
    lambda_l2: jax.Array,
    min_gain_to_split: jax.Array,
    can_split: jax.Array,  # scalar bool (depth / leaf-size gating)
) -> SplitResult:
    F, B, _ = hist.shape
    dt = hist.dtype
    bins = jnp.arange(B, dtype=jnp.int32)

    # The body is written to compile to FEW LARGE ops rather than many
    # small ones: one suffix cumsum over the whole [F, B, 3] tensor (all
    # three stats at once), stat-keeping wheres on [F, B, 3], and ONE
    # dynamic-slice extracting all six winner stats.  The round-3 TPU
    # profile (tools/profile_split.py) showed the previous per-stat
    # formulation spending ~1.6 ms/split on ~60 tiny-op fusions — 4x the
    # histogram kernel itself.  Math, dtype and tie-break order are
    # unchanged bit-for-bit.

    # ---- right-side sums for numerical threshold t: bins > t
    # suffix[t] = sum_{b >= t+1} hist[b]; kEpsilon seeds the right
    # hessian (feature_histogram.hpp:123)
    suf = jnp.cumsum(hist[:, ::-1, :], axis=1)[:, ::-1, :]
    tail = jnp.concatenate([suf[:, 1:], jnp.zeros((F, 1, 3), dt)], axis=1)
    tail = tail + jnp.asarray([0.0, K_EPSILON, 0.0], dt)

    tot = jnp.stack([
        jnp.asarray(sum_grad, dt),
        jnp.asarray(sum_hess, dt),
        jnp.asarray(num_data, dt),
    ])  # [3]

    # ---- categorical one-vs-rest: "left" is the single bin t
    is_cat3 = is_categorical[:, None, None]
    left = jnp.where(is_cat3, hist, tot - tail)  # [F, B, 3]
    right = jnp.where(is_cat3, tot - hist, tail)

    left_h, left_c = left[..., 1], left[..., 2]
    right_h, right_c = right[..., 1], right[..., 2]

    # ---- validity (feature_histogram.hpp:133-142, 199-208)
    is_cat = is_categorical[:, None]
    nb = num_bins_per_feature[:, None]
    in_range = jnp.where(is_cat, bins[None, :] < nb, bins[None, :] < nb - 1)
    valid = (
        in_range
        & feature_mask[:, None]
        & (left_c >= min_data_in_leaf)
        & (right_c >= min_data_in_leaf)
        & (left_h >= min_sum_hessian_in_leaf)
        & (right_h >= min_sum_hessian_in_leaf)
    )

    gain_shift = _leaf_split_gain(sum_grad, sum_hess, lambda_l1, lambda_l2)
    min_gain_shift = gain_shift + min_gain_to_split
    gains = _leaf_split_gain(
        left[..., 0], left_h, lambda_l1, lambda_l2
    ) + _leaf_split_gain(right[..., 0], right_h, lambda_l1, lambda_l2)
    valid = valid & (gains >= min_gain_shift) & can_split
    gains = jnp.where(valid, gains, K_MIN_SCORE)

    # argmax over (feature asc, bin desc): reverse the bin axis so the
    # first maximum is the smallest feature with the LARGEST threshold
    flat = gains[:, ::-1].reshape(-1)
    best = jnp.argmax(flat)
    best_gain_raw = flat[best]
    feat = (best // B).astype(jnp.int32)
    thr = (B - 1 - best % B).astype(jnp.int32)
    splittable = best_gain_raw > K_MIN_SCORE

    # all six winner stats in one dynamic-slice of the stacked tensor
    lr = jnp.stack([left, right])  # [2, F, B, 3]
    pick = jax.lax.dynamic_slice(
        lr, (jnp.int32(0), feat, thr, jnp.int32(0)), (2, 1, 1, 3)
    ).reshape(2, 3)
    lg, lh, lc = pick[0, 0], pick[0, 1], pick[0, 2]
    rg, rh, rc = pick[1, 0], pick[1, 1], pick[1, 2]
    return SplitResult(
        gain=jnp.where(splittable, best_gain_raw - gain_shift, K_MIN_SCORE),
        feature=jnp.where(splittable, feat, -1),
        threshold=jnp.where(splittable, thr, 0),
        left_sum_grad=lg,
        left_sum_hess=lh,
        left_count=lc,
        right_sum_grad=rg,
        right_sum_hess=rh,
        right_count=rc,
        left_output=_leaf_output(lg, lh, lambda_l1, lambda_l2),
        right_output=_leaf_output(rg, rh, lambda_l1, lambda_l2),
    )


# vectorized over leaves (depthwise grower / batched candidate evaluation)
find_best_split_leaves = jax.vmap(
    find_best_split,
    in_axes=(0, 0, 0, 0, None, None, None, None, None, None, None, None, 0),
)
