"""Pallas TPU histogram kernel — MXU one-hot matmuls over leaf-sorted rows.

The reference's hot loop is a scalar gather-accumulate per row
(DenseBin::ConstructHistogram, src/io/dense_bin.hpp:39-104).  TPUs have
no fast scatter, so `jax.ops.segment_sum` (ops/histogram.py) lowers to a
scatter-add that serializes badly at 10M rows x 64k leaf-bin segments.
This module reformulates the histogram as dense MXU work:

1. rows are re-ordered so each leaf's rows are contiguous (the same idea
   as the reference's DataPartition, data_partition.hpp:91-139), with
   each leaf padded to a multiple of the chunk size C so that
2. every C-row chunk belongs to exactly ONE leaf, and its histogram is a
   one-hot matmul on the MXU — no scatter at all, and
3. chunks of the same leaf are consecutive in the grid, so the Pallas
   output block (indexed by a scalar-prefetched ``leaf_of_chunk`` map)
   stays resident in VMEM and accumulates across chunk visits.

Total work is O(n x F x B) MACs per tree LEVEL — independent of the
number of leaves — plus one stable sort of the leaf ids.

Two kernel variants (LGBM_TPU_HIST_KERNEL env selects; default "v1"
until bsub has real-chip timings; pass ``variant=`` explicitly when
benchmarking — the env var is only read at TRACE time, so flipping it
between calls of identical shapes hits the jit cache and is ignored):

* ``bsub`` — the one-hot is built TRANSPOSED (``[B, C]``) by comparing a
  ``[1, C]`` feature row against a SUBLANE iota, then
  ``onehot[B, C] @ stats[C, 4] -> [B, 4]``.  The feature row stays in
  the lane dimension end to end — no relayout.
* ``v1`` — the historical form: each feature row is reshaped to
  ``[C, 1]`` (a lane->sublane relayout, one per feature per chunk —
  measured to dominate kernel time) and ``stats^T[4, C] @ onehot[C, B]
  -> [4, B]``.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..obs.device_time import phase_scope

DEFAULT_CHUNK = 1024
FGROUP = 8  # feature rows per kernel loop step (int8 sublane-pack aligned)
# bsub feature-group block height: the [C, 4] stats block is re-fetched
# once per (feature-group, chunk) grid step, so wider groups amortize
# that HBM traffic, while narrower groups waste less padding when F is
# just past a multiple.  At 16 the (1, 16, B=256, 4->128 lanes)
# accumulator block is ~2.1MB of VMEM — ample headroom, but 16 already
# makes stats traffic (32B/row at F<=32) comparable to the bins traffic.
FGROUP_BSUB = 16
_VARIANTS = ("v1", "bsub")


# read ONCE at import (jaxlint env-read-at-trace): _kernel_variant is
# called from inside jitted histogram fns, where an environ read bakes
# per trace while the jit cache keys only on static args
_VARIANT_ENV = os.environ.get("LGBM_TPU_HIST_KERNEL", "v1")


def _kernel_variant(variant: str | None = None) -> str:
    # default stays on the chip-proven v1 until bsub has a real Mosaic
    # compile + timing on TPU hardware (tunnel down at authoring time)
    v = variant or _VARIANT_ENV
    if v not in _VARIANTS:
        raise ValueError(
            f"unknown histogram kernel variant {v!r}; expected one of {_VARIANTS}"
        )
    return v


def _hist_kernel_v1(leaf_of_chunk, bins_ref, stats_ref, out_ref, *, num_f, num_b, chunk):
    """One grid step = one C-row chunk of a single leaf.

    bins_ref:  [F, C] uint8 (this chunk's bins, feature-major)
    stats_ref: [C, 4] f32   (g*m, h*m, m, 0)
    out_ref:   [1, F, 4, B] f32 block at row ``leaf_of_chunk[c]`` —
               revisited (and therefore VMEM-resident) across all chunks
               of the same leaf.
    """
    c = pl.program_id(0)
    prev = leaf_of_chunk[jnp.maximum(c - 1, 0)]
    is_first = (c == 0) | (leaf_of_chunk[c] != prev)

    @pl.when(is_first)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    stats = stats_ref[...]  # [C, 4]
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (chunk, num_b), 1)

    # int8 VMEM rows are 4-packed per sublane, so a dynamically-indexed
    # SINGLE-row vector.load cannot be proven aligned by Mosaic ("index
    # in dimension 0 is a multiple of 4").  Instead the loop walks the
    # feature axis in groups of FGROUP rows — the dynamic start g*FGROUP
    # is provably aligned — and slices rows statically within the group,
    # keeping compiled code size O(FGROUP), not O(num_f).
    num_groups = num_f // FGROUP  # caller pads F to a FGROUP multiple

    def group_body(g, _):
        blk = bins_ref[pl.ds(g * FGROUP, FGROUP), :].astype(jnp.int32)
        for i in range(FGROUP):
            row = blk[i, :].reshape(chunk, 1)
            onehot = (row == iota_b).astype(jnp.float32)  # [C, B]
            contrib = jax.lax.dot_general(
                stats, onehot, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [4, B]
            out_ref[0, g * FGROUP + i] = out_ref[0, g * FGROUP + i] + contrib
        return 0

    jax.lax.fori_loop(0, num_groups, group_body, 0)


def _hist_kernel_bsub(leaf_of_chunk, bins_ref, stats_ref, out_ref, *, num_b, chunk):
    """Relayout-free variant: one grid step = one C-row chunk of one leaf
    x one FGROUP-wide feature group (grid (F_groups, n_chunks), chunk
    MINOR so the accumulation block stays VMEM-resident across a leaf's
    chunks).

    bins_ref:  [FGROUP_BSUB, C] uint8 (feature-major; C in LANES)
    stats_ref: [C, 4] f32
    out_ref:   [1, FGROUP_BSUB, B, 4] f32 block at (leaf_of_chunk[c], fg) —
               bounded VMEM whatever the full feature count is (the
               minor 4 pads to 128 lanes, so a full-F block would be
               F x B x 128 floats).

    The [1, C] feature row broadcasts across SUBLANES against a [B, C]
    sublane iota, so the one-hot is born transposed and the row never
    leaves the lane dimension; ``onehot[B, C] @ stats[C, 4]`` contracts
    the shared lane axis on the MXU.
    """
    c = pl.program_id(1)
    prev = leaf_of_chunk[jnp.maximum(c - 1, 0)]
    is_first = (c == 0) | (leaf_of_chunk[c] != prev)

    @pl.when(is_first)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    stats = stats_ref[...]  # [C, 4]
    iota_s = jax.lax.broadcasted_iota(jnp.int32, (num_b, chunk), 0)
    blk = bins_ref[...].astype(jnp.int32)  # [FGROUP_BSUB, C]
    for i in range(FGROUP_BSUB):
        row = blk[i : i + 1, :]  # [1, C] — stays in lanes
        onehot = (row == iota_s).astype(jnp.float32)  # [B, C]
        contrib = jax.lax.dot_general(
            onehot, stats, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [B, 4]
        out_ref[0, i] = out_ref[0, i] + contrib


def _pad_pow(b: int) -> int:
    """Bin axis padded up to a lane multiple (128).  Must never round
    DOWN: max_bin > 256 is legal (uint16 bins), and a capped pad would
    silently drop rows whose bin >= cap from the histogram."""
    return ((b + 127) // 128) * 128


def _hist_pallas_call(
    leaf_of_chunk, bins_buf, stats_buf, out_leaves, Fp, B, C, n_chunks,
    interpret, variant=None, raw=False,
):
    """Shared pallas_call scaffolding for both kernels: one grid step per
    C-row chunk, output block indexed by the scalar-prefetched
    chunk->leaf map.  Returns hist[out_leaves, Fp, B, 4] in the
    CANONICAL bin-major layout whichever kernel variant ran — or, with
    ``raw=True`` (v1 only), the kernel's NATIVE [out_leaves, Fp, 4, B]
    layout with no relayout at all: the round-3 profile showed the
    per-split transpose to the canonical layout radiating ~0.5 ms/split
    of layout-churn fusions through the whole split step."""
    if raw:
        assert _kernel_variant(variant) == "v1", "raw layout is v1-only"
    if _kernel_variant(variant) == "v1":
        kernel = functools.partial(_hist_kernel_v1, num_f=Fp, num_b=B, chunk=C)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_chunks,),
            in_specs=[
                pl.BlockSpec((Fp, C), lambda c, leaf_ref: (0, c)),
                pl.BlockSpec((C, 4), lambda c, leaf_ref: (c, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, Fp, 4, B), lambda c, leaf_ref: (leaf_ref[c], 0, 0, 0)
            ),
        )
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((out_leaves, Fp, 4, B), jnp.float32),
            interpret=interpret,
        )(leaf_of_chunk, bins_buf, stats_buf)
        if raw:
            return out  # [L, Fp, 4, B] kernel-native
        return out.transpose(0, 1, 3, 2)  # -> [L, Fp, B, 4]

    # bsub: feature groups ride the OUTER grid axis (chunk minor), so the
    # (leaf, fg) accumulation block stays VMEM-resident across a leaf's
    # consecutive chunks and VMEM is bounded at FGROUP_BSUB x B x 128
    # floats regardless of the feature count
    kernel = functools.partial(_hist_kernel_bsub, num_b=B, chunk=C)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Fp // FGROUP_BSUB, n_chunks),
        in_specs=[
            pl.BlockSpec((FGROUP_BSUB, C), lambda fg, c, leaf_ref: (fg, c)),
            pl.BlockSpec((C, 4), lambda fg, c, leaf_ref: (c, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, FGROUP_BSUB, B, 4),
            lambda fg, c, leaf_ref: (leaf_ref[c], fg, 0, 0),
        ),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((out_leaves, Fp, B, 4), jnp.float32),
        interpret=interpret,
    )(leaf_of_chunk, bins_buf, stats_buf)


@functools.partial(
    jax.jit,
    static_argnames=("num_bins", "num_leaves", "chunk", "interpret", "variant"),
)
@phase_scope("histogram")
def histogram_by_leaf_sorted(
    bins_T: jax.Array,  # [F, n] uint8/uint16 binned matrix, feature-major
    leaf_id: jax.Array,  # [n] int32 leaf per row
    grad: jax.Array,  # [n]
    hess: jax.Array,  # [n]
    mask: jax.Array,  # [n] 0/1
    num_bins: int,
    num_leaves: int,
    chunk: int = DEFAULT_CHUNK,
    interpret: bool = False,
    variant: str | None = None,
) -> jax.Array:
    """Drop-in equivalent of ops.histogram.histogram_by_leaf:
    returns hist[num_leaves, F, num_bins, 3] = (sum_grad, sum_hess, count).
    """
    F, n = bins_T.shape
    L = num_leaves
    C = chunk
    B = _pad_pow(num_bins)
    fg = FGROUP if _kernel_variant(variant) == "v1" else FGROUP_BSUB
    Fp = ((F + fg - 1) // fg) * fg  # pad to the selected kernel's grouping
    if Fp != F:
        bins_T = jnp.pad(bins_T, ((0, Fp - F), (0, 0)))

    # ---- leaf-sorted order + per-leaf chunk-padded layout
    leaf_id = leaf_id.astype(jnp.int32)
    counts = jnp.bincount(leaf_id, length=L)  # [L]
    # every leaf gets >= 1 chunk so empty leaves still zero-init their
    # output row (their chunk carries all-zero stats)
    chunks_per_leaf = jnp.maximum((counts + C - 1) // C, 1)
    chunk_start = jnp.concatenate(
        [jnp.zeros(1, counts.dtype), jnp.cumsum(chunks_per_leaf)]
    )  # [L+1] exclusive chunk offsets
    n_chunks = (n + C - 1) // C + L  # static capacity (each leaf <=1 partial)
    n_pad = n_chunks * C

    order = jnp.argsort(leaf_id, stable=True)  # [n]
    leaf_sorted = leaf_id[order]
    row_start = jnp.concatenate(
        [jnp.zeros(1, counts.dtype), jnp.cumsum(counts)]
    )
    rank = jnp.arange(n) - row_start[leaf_sorted]  # position within leaf
    dest = (chunk_start[leaf_sorted] * C + rank).astype(jnp.int32)  # [n]

    # invert dest into a gather map: a [n_pad] 1-D scatter of int32, then
    # row GATHERS for the big buffers — far cheaper on TPU than scattering
    # the whole [Fp, n_pad] matrix (pad slots read OOB -> fill 0)
    src = jnp.full((n_pad,), n, jnp.int32).at[dest].set(
        order.astype(jnp.int32)
    )
    bins_buf = jnp.take(bins_T, src, axis=1, mode="fill", fill_value=0)
    gm = grad * mask
    hm = hess * mask
    stats = jnp.stack([gm, hm, mask, jnp.zeros_like(mask)], axis=-1)  # [n, 4]
    stats_buf = jnp.take(
        stats.astype(jnp.float32), src, axis=0, mode="fill", fill_value=0.0
    )

    # chunk -> leaf map; trailing unused chunks land on the dummy row L
    cidx = jnp.arange(n_chunks, dtype=chunk_start.dtype)
    leaf_of_chunk = jnp.clip(
        jnp.searchsorted(chunk_start, cidx, side="right") - 1, 0, L
    ).astype(jnp.int32)
    leaf_of_chunk = jnp.where(cidx < chunk_start[L], leaf_of_chunk, L)

    out = _hist_pallas_call(
        leaf_of_chunk, bins_buf, stats_buf, L + 1, Fp, B, C, n_chunks,
        interpret, variant,
    )  # [L+1, Fp, B, 4]
    return out[:L, :F, :num_bins, :3]


@functools.partial(
    jax.jit, static_argnames=("num_bins", "chunk", "interpret", "variant")
)
@phase_scope("histogram")
def histogram_single_leaf(
    bins_T: jax.Array,  # [F, cap] binned rows of ONE leaf (masked)
    grad: jax.Array,  # [cap]
    hess: jax.Array,  # [cap]
    mask: jax.Array,  # [cap] 0/1 validity
    num_bins: int,
    chunk: int = 512,
    interpret: bool = False,
    variant: str | None = None,
) -> jax.Array:
    """hist[F, num_bins, 3] for a single row set — the leaf-wise
    learner's per-split histogram (DenseBin::ConstructHistogram over the
    smaller child's gathered rows, dense_bin.hpp:39-104).  Same one-hot
    MXU matmul as the sorted kernel but with a trivial chunk->leaf map:
    every chunk accumulates into the one output block, so no sort, no
    scatter — just O(cap x B x F) dense MACs.
    """
    F, cap = bins_T.shape
    fg = FGROUP if _kernel_variant(variant) == "v1" else FGROUP_BSUB
    bins_T, stats, n_chunks, Fp, B, C = _prep_single_leaf(
        bins_T, grad, hess, mask, num_bins, chunk, fg)
    out = _hist_pallas_call(
        jnp.zeros(n_chunks, jnp.int32), bins_T, stats, 1, Fp, B, C,
        n_chunks, interpret, variant,
    )  # [1, Fp, B, 4]
    return out[0, :F, :num_bins, :3]


def _prep_single_leaf(bins_T, grad, hess, mask, num_bins, chunk, fg):
    """Shared single-leaf padding/stat prep: lane-aligned chunk width
    (an unaligned int8 block is the Mosaic failure class the FGROUP
    loop exists to avoid), features padded to the kernel grouping, and
    the (g*m, h*m, m, 0) stat stack."""
    F, cap = bins_T.shape
    C = max(128, (chunk // 128) * 128)
    B = _pad_pow(num_bins)
    Fp = ((F + fg - 1) // fg) * fg
    if Fp != F:
        bins_T = jnp.pad(bins_T, ((0, Fp - F), (0, 0)))
    pad = (-cap) % C
    if pad:
        bins_T = jnp.pad(bins_T, ((0, 0), (0, pad)))
        grad = jnp.pad(grad, (0, pad))
        hess = jnp.pad(hess, (0, pad))
        mask = jnp.pad(mask, (0, pad))
    gm = grad * mask
    hm = hess * mask
    stats = jnp.stack(
        [gm, hm, mask, jnp.zeros_like(mask)], axis=-1
    ).astype(jnp.float32)
    return bins_T, stats, (cap + pad) // C, Fp, B, C


@functools.partial(
    jax.jit, static_argnames=("num_bins", "chunk", "interpret")
)
@phase_scope("histogram")
def histogram_single_leaf_raw(
    bins_T: jax.Array,  # [F, cap] binned rows of ONE leaf (masked)
    grad: jax.Array,  # [cap]
    hess: jax.Array,  # [cap]
    mask: jax.Array,  # [cap] 0/1 validity
    num_bins: int,
    chunk: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """histogram_single_leaf in the KERNEL-NATIVE [Fp, 4, Bp] layout
    (stat rows g/h/count/zero, bins in lanes, features padded to the
    v1 grouping) — zero post-processing, so the whole split step can
    stay in one layout (see _hist_pallas_call raw)."""
    bins_T, stats, n_chunks, Fp, B, C = _prep_single_leaf(
        bins_T, grad, hess, mask, num_bins, chunk, FGROUP)
    out = _hist_pallas_call(
        jnp.zeros(n_chunks, jnp.int32), bins_T, stats, 1, Fp, B, C,
        n_chunks, interpret, variant="v1", raw=True,
    )  # [1, Fp, 4, B]
    return out[0]


@functools.lru_cache(maxsize=None)
def make_single_hist_fn_raw(num_bins: int, chunk: int = 512):
    """hist_fn for the leaf-wise grower's RAW-layout path (signature:
    bins_T, grad, hess, mask -> [Fp, 4, Bp])."""
    interpret = jax.default_backend() != "tpu"

    def hist_fn(bins_T, grad, hess, mask):
        return histogram_single_leaf_raw(
            bins_T, grad, hess, mask,
            num_bins=num_bins, chunk=chunk, interpret=interpret,
        )

    return hist_fn


@functools.lru_cache(maxsize=None)
def make_single_hist_fn(num_bins: int, chunk: int = 512):
    """hist_fn for the leaf-wise grower (signature: bins_T, grad, hess,
    mask -> [F, B, 3]) backed by the single-leaf MXU kernel.  Cached per
    config so repeated boosters reuse the jit cache (see
    make_sorted_hist_fn)."""
    interpret = jax.default_backend() != "tpu"

    def hist_fn(bins_T, grad, hess, mask):
        return histogram_single_leaf(
            bins_T, grad, hess, mask,
            num_bins=num_bins, chunk=chunk, interpret=interpret,
        )

    return hist_fn


@functools.lru_cache(maxsize=None)
def make_sorted_hist_fn(num_bins: int, chunk: int = DEFAULT_CHUNK):
    """hist_fn for the depthwise grower (signature: bins_T, leaf_id, grad,
    hess, mask, num_leaves -> [L, F, B, 3]) backed by the Pallas kernel.
    Interpret mode is selected off-TPU so tests run anywhere.

    Cached per (num_bins, chunk): the grower jits with hist_fn as a
    static argument, so returning the SAME closure across boosters (cv
    folds, repeated train calls) is what keeps the jit cache warm."""
    interpret = jax.default_backend() != "tpu"

    def hist_fn(bins_T, leaf_id, grad, hess, mask, num_leaves):
        return histogram_by_leaf_sorted(
            bins_T, leaf_id, grad, hess, mask,
            num_bins=num_bins, num_leaves=num_leaves,
            chunk=chunk, interpret=interpret,
        )

    return hist_fn
