"""Evaluation metrics (src/metric/*.hpp re-expressed, host-side numpy).

All metrics expose ``eval(scores) -> float`` plus ``bigger_is_better``
(factor_to_bigger_better, metric.h:31) which drives early-stopping
direction.  Scores are raw (pre-transform) model outputs, class-major
[K, n] for multiclass — the transforms (sigmoid/softmax) are applied
inside the metric exactly like the reference.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

_EPS = 1e-15


class Metric:
    name = "none"
    bigger_is_better = False

    def init(self, metadata, num_data: int) -> None:
        self.label = np.asarray(metadata.label, np.float64)
        self.weights = (
            None if metadata.weights is None else np.asarray(metadata.weights, np.float64)
        )
        self.sum_weights = (
            float(num_data) if self.weights is None else float(self.weights.sum())
        )
        self.num_data = num_data
        self.metadata = metadata

    def _avg(self, loss: np.ndarray) -> float:
        if self.weights is not None:
            return float((loss * self.weights).sum() / self.sum_weights)
        return float(loss.sum() / self.sum_weights)

    def eval(self, scores: np.ndarray) -> float:
        raise NotImplementedError


class L2Metric(Metric):
    """Reports RMSE (AverageLoss takes sqrt, regression_metric.hpp:98-101)."""

    name = "l2"

    def eval(self, scores):
        scores = np.asarray(scores, np.float64).reshape(-1)
        return float(np.sqrt(self._avg((scores - self.label) ** 2)))


class L1Metric(Metric):
    name = "l1"

    def eval(self, scores):
        scores = np.asarray(scores, np.float64).reshape(-1)
        return self._avg(np.abs(scores - self.label))


class BinaryLoglossMetric(Metric):
    """prob = sigmoid(2*sig*score); loss = -log p_y
    (binary_metric.hpp:44-98)."""

    name = "binary_logloss"

    def __init__(self, config):
        self.sigmoid = float(config.sigmoid)

    def eval(self, scores):
        scores = np.asarray(scores, np.float64).reshape(-1)
        prob = 1.0 / (1.0 + np.exp(-2.0 * self.sigmoid * scores))
        prob = np.clip(prob, _EPS, 1.0 - _EPS)
        loss = np.where(self.label > 0, -np.log(prob), -np.log(1.0 - prob))
        return self._avg(loss)


class BinaryErrorMetric(Metric):
    """Misclassification rate at prob 0.5 (binary_metric.hpp:105-140)."""

    name = "binary_error"

    def __init__(self, config):
        self.sigmoid = float(config.sigmoid)

    def eval(self, scores):
        scores = np.asarray(scores, np.float64).reshape(-1)
        pred_pos = scores > 0
        err = (pred_pos != (self.label > 0)).astype(np.float64)
        return self._avg(err)


class AUCMetric(Metric):
    """Weighted ROC AUC via a single sort sweep with tie handling
    (binary_metric.hpp:181-238)."""

    name = "auc"
    bigger_is_better = True

    def eval(self, scores):
        scores = np.asarray(scores, np.float64).reshape(-1)
        w = self.weights if self.weights is not None else np.ones_like(self.label)
        pos = (self.label > 0).astype(np.float64) * w
        neg = (self.label <= 0).astype(np.float64) * w
        order = np.argsort(-scores, kind="mergesort")
        s, p, ng = scores[order], pos[order], neg[order]
        # group ties: average rank treatment == trapezoid on grouped counts
        boundaries = np.nonzero(np.diff(s))[0]
        group_id = np.zeros(len(s), np.int64)
        group_id[1:] = np.cumsum(np.diff(s) != 0)
        npos = np.bincount(group_id, weights=p)
        nneg = np.bincount(group_id, weights=ng)
        cum_neg_before = np.concatenate([[0.0], np.cumsum(nneg)[:-1]])
        # each positive beats all negatives ranked below; ties count half
        auc_sum = (npos * (cum_neg_before + nneg * 0.5)).sum()
        total_pos, total_neg = npos.sum(), nneg.sum()
        if total_pos == 0 or total_neg == 0:
            return 1.0
        return float(1.0 - auc_sum / (total_pos * total_neg))


class MultiLoglossMetric(Metric):
    """Softmax logloss (multiclass_metric.hpp)."""

    name = "multi_logloss"

    def eval(self, scores):
        scores = np.asarray(scores, np.float64)  # [K, n]
        z = scores - scores.max(axis=0, keepdims=True)
        logp = z - np.log(np.exp(z).sum(axis=0, keepdims=True))
        idx = self.label.astype(np.int64)
        loss = -logp[idx, np.arange(scores.shape[1])]
        return self._avg(loss)


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, scores):
        scores = np.asarray(scores, np.float64)
        pred = scores.argmax(axis=0)
        err = (pred != self.label.astype(np.int64)).astype(np.float64)
        return self._avg(err)


def create_metrics(config, metadata=None, num_data: Optional[int] = None) -> List[Metric]:
    """Factory (metric.cpp:9-28); unknown names raise."""
    out: List[Metric] = []
    names = config.metric or _default_metric(config.objective)
    for name in names:
        name = name.strip()
        if name in ("l2", "mse", "mean_squared_error", "regression"):
            m: Metric = L2Metric()
        elif name in ("l1", "mae", "mean_absolute_error"):
            m = L1Metric()
        elif name == "binary_logloss":
            m = BinaryLoglossMetric(config)
        elif name == "binary_error":
            m = BinaryErrorMetric(config)
        elif name == "auc":
            m = AUCMetric()
        elif name == "multi_logloss":
            m = MultiLoglossMetric()
        elif name == "multi_error":
            m = MultiErrorMetric()
        elif name in ("ndcg", "ndcg@"):
            from .metrics_rank import NDCGMetric

            m = NDCGMetric(config)
        elif name in ("", "none", "null"):
            continue
        else:
            raise ValueError(f"Unknown metric: {name!r}")
        if metadata is not None:
            m.init(metadata, num_data if num_data is not None else len(metadata.label))
        out.append(m)
    return out


def _default_metric(objective: str) -> List[str]:
    return {
        "regression": ["l2"],
        "binary": ["binary_logloss"],
        "multiclass": ["multi_logloss"],
        "lambdarank": ["ndcg"],
    }.get(objective, ["l2"])
