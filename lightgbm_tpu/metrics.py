"""Evaluation metrics (src/metric/*.hpp re-expressed).

All metrics expose ``eval(scores) -> float`` plus ``bigger_is_better``
(factor_to_bigger_better, metric.h:31) which drives early-stopping
direction.  Scores are raw (pre-transform) model outputs, class-major
[K, n] for multiclass — the transforms (sigmoid/softmax) are applied
inside the metric exactly like the reference.

Two evaluation paths: ``eval`` (host numpy, the reference-parity
implementation) and, where implemented, ``eval_jax`` (device-resident:
scores never leave HBM, only the scalar comes back — the reference has
no analog because its scores already live in host memory; here a per-
iteration eval of a 10M-row score vector would otherwise pay a 40MB
device->host copy plus a host sort for AUC).  NDCG keeps host-only eval.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from .compat import enable_x64

# jaxlint: disable-file=f64-literal-in-traced — the eval_jax reductions
# deliberately accumulate in f64 under the enable_x64 context installed
# by eval_jax_jit (f32 cumsums drift in the 4th AUC decimal at ~10M
# rows; with >2^24 unit-weight rows the increments vanish entirely).

_EPS = 1e-15


class Metric:
    name = "none"
    bigger_is_better = False
    eval_jax = None  # device path; subclasses override where supported

    def init(self, metadata, num_data: int) -> None:
        self.label = np.asarray(metadata.label, np.float64)
        self.weights = (
            None if metadata.weights is None else np.asarray(metadata.weights, np.float64)
        )
        self.sum_weights = (
            float(num_data) if self.weights is None else float(self.weights.sum())
        )
        self.num_data = num_data
        self.metadata = metadata
        self._dev = None  # lazy (label, weights) device arrays
        self._jfn = None  # lazy jitted eval_jax

    def eval_jax_jit(self, scores):
        """Jitted device eval; traces once per score shape.  Runs under
        enable_x64 so the reductions inside eval_jax accumulate in f64
        like the host/reference path (f32 cumsums visibly drift in the
        4th AUC decimal at ~10M rows; with >2^24 unit-weight rows the
        increments drop below f32 spacing entirely)."""
        import jax

        with enable_x64(True):
            if self._jfn is None:
                self._jfn = jax.jit(self.eval_jax)
            return self._jfn(scores)

    def _dev_arrays(self):
        if self._dev is None:
            import jax.numpy as jnp

            lab = jnp.asarray(self.label, jnp.float32)
            w = (
                jnp.ones_like(lab)
                if self.weights is None
                else jnp.asarray(self.weights, jnp.float32)
            )
            self._dev = (lab, w)
        return self._dev

    def _avg(self, loss: np.ndarray) -> float:
        if self.weights is not None:
            return float((loss * self.weights).sum() / self.sum_weights)
        return float(loss.sum() / self.sum_weights)

    def eval(self, scores: np.ndarray) -> float:
        raise NotImplementedError


class L2Metric(Metric):
    """Reports RMSE (AverageLoss takes sqrt, regression_metric.hpp:98-101)."""

    name = "l2"

    def eval(self, scores):
        scores = np.asarray(scores, np.float64).reshape(-1)
        return float(np.sqrt(self._avg((scores - self.label) ** 2)))

    def eval_jax(self, scores):
        import jax.numpy as jnp

        lab, w = self._dev_arrays()
        s = scores.reshape(-1)
        sq = ((s - lab) ** 2 * w).astype(jnp.float64)
        return jnp.sqrt(jnp.sum(sq) / self.sum_weights)


class L1Metric(Metric):
    name = "l1"

    def eval(self, scores):
        scores = np.asarray(scores, np.float64).reshape(-1)
        return self._avg(np.abs(scores - self.label))

    def eval_jax(self, scores):
        import jax.numpy as jnp

        lab, w = self._dev_arrays()
        l1 = (jnp.abs(scores.reshape(-1) - lab) * w).astype(jnp.float64)
        return jnp.sum(l1) / self.sum_weights


class BinaryLoglossMetric(Metric):
    """prob = sigmoid(2*sig*score); loss = -log p_y
    (binary_metric.hpp:44-98)."""

    name = "binary_logloss"

    def __init__(self, config):
        self.sigmoid = float(config.sigmoid)

    def eval(self, scores):
        scores = np.asarray(scores, np.float64).reshape(-1)
        prob = 1.0 / (1.0 + np.exp(-2.0 * self.sigmoid * scores))
        prob = np.clip(prob, _EPS, 1.0 - _EPS)
        loss = np.where(self.label > 0, -np.log(prob), -np.log(1.0 - prob))
        return self._avg(loss)

    def eval_jax(self, scores):
        import jax.numpy as jnp

        lab, w = self._dev_arrays()
        s = scores.reshape(-1)
        prob = jnp.clip(
            1.0 / (1.0 + jnp.exp(-2.0 * self.sigmoid * s)), 1e-7, 1 - 1e-7
        )
        loss = jnp.where(lab > 0, -jnp.log(prob), -jnp.log(1.0 - prob))
        return jnp.sum((loss * w).astype(jnp.float64)) / self.sum_weights


class BinaryErrorMetric(Metric):
    """Misclassification rate at prob 0.5 (binary_metric.hpp:105-140)."""

    name = "binary_error"

    def __init__(self, config):
        self.sigmoid = float(config.sigmoid)

    def eval(self, scores):
        scores = np.asarray(scores, np.float64).reshape(-1)
        pred_pos = scores > 0
        err = (pred_pos != (self.label > 0)).astype(np.float64)
        return self._avg(err)

    def eval_jax(self, scores):
        import jax.numpy as jnp

        lab, w = self._dev_arrays()
        err = ((scores.reshape(-1) > 0) != (lab > 0)).astype(jnp.float32)
        return jnp.sum((err * w).astype(jnp.float64)) / self.sum_weights


class AUCMetric(Metric):
    """Weighted ROC AUC via a single sort sweep with tie handling
    (binary_metric.hpp:181-238)."""

    name = "auc"
    bigger_is_better = True

    def eval(self, scores):
        scores = np.asarray(scores, np.float64).reshape(-1)
        w = self.weights if self.weights is not None else np.ones_like(self.label)
        pos = (self.label > 0).astype(np.float64) * w
        neg = (self.label <= 0).astype(np.float64) * w
        order = np.argsort(-scores, kind="mergesort")
        s, p, ng = scores[order], pos[order], neg[order]
        # group ties: average rank treatment == trapezoid on grouped counts
        boundaries = np.nonzero(np.diff(s))[0]
        group_id = np.zeros(len(s), np.int64)
        group_id[1:] = np.cumsum(np.diff(s) != 0)
        npos = np.bincount(group_id, weights=p)
        nneg = np.bincount(group_id, weights=ng)
        cum_neg_before = np.concatenate([[0.0], np.cumsum(nneg)[:-1]])
        # each positive beats all negatives ranked below; ties count half
        auc_sum = (npos * (cum_neg_before + nneg * 0.5)).sum()
        total_pos, total_neg = npos.sum(), nneg.sum()
        if total_pos == 0 or total_neg == 0:
            return 1.0
        return float(1.0 - auc_sum / (total_pos * total_neg))

    def eval_jax(self, scores):
        """Device AUC: sort + tie-grouped segment sums, no host copy.
        Same grouped-tie math as ``eval`` with groups keyed by sorted
        position via cumsum (bincount -> segment_sum)."""
        import jax.numpy as jnp

        lab, w = self._dev_arrays()
        s = scores.reshape(-1)
        order = jnp.argsort(-s, stable=True)
        ss = s[order]
        p = jnp.where(lab > 0, w, 0.0)[order].astype(jnp.float64)
        ng = jnp.where(lab <= 0, w, 0.0)[order].astype(jnp.float64)
        new_group = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), (jnp.diff(ss) != 0).astype(jnp.int32)]
        )
        gid = jnp.cumsum(new_group)
        n = s.shape[0]
        import jax

        npos = jax.ops.segment_sum(p, gid, num_segments=n)
        nneg = jax.ops.segment_sum(ng, gid, num_segments=n)
        cum_neg_before = jnp.concatenate(
            [jnp.zeros(1, nneg.dtype), jnp.cumsum(nneg)[:-1]]
        )
        auc_sum = jnp.sum(npos * (cum_neg_before + nneg * 0.5))
        total_pos, total_neg = jnp.sum(npos), jnp.sum(nneg)
        denom = total_pos * total_neg
        return jnp.where(denom > 0, 1.0 - auc_sum / denom, 1.0)


class MultiLoglossMetric(Metric):
    """Softmax logloss (multiclass_metric.hpp)."""

    name = "multi_logloss"

    def eval(self, scores):
        scores = np.asarray(scores, np.float64)  # [K, n]
        z = scores - scores.max(axis=0, keepdims=True)
        logp = z - np.log(np.exp(z).sum(axis=0, keepdims=True))
        idx = self.label.astype(np.int64)
        loss = -logp[idx, np.arange(scores.shape[1])]
        return self._avg(loss)

    def eval_jax(self, scores):
        import jax.numpy as jnp

        lab, w = self._dev_arrays()
        z = scores - scores.max(axis=0, keepdims=True)
        logp = z - jnp.log(jnp.exp(z).sum(axis=0, keepdims=True))
        idx = lab.astype(jnp.int32)
        loss = -logp[idx, jnp.arange(scores.shape[1])]
        return jnp.sum((loss * w).astype(jnp.float64)) / self.sum_weights


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, scores):
        scores = np.asarray(scores, np.float64)
        pred = scores.argmax(axis=0)
        err = (pred != self.label.astype(np.int64)).astype(np.float64)
        return self._avg(err)

    def eval_jax(self, scores):
        import jax.numpy as jnp

        lab, w = self._dev_arrays()
        err = (scores.argmax(axis=0) != lab.astype(jnp.int32)).astype(
            jnp.float32
        )
        return jnp.sum((err * w).astype(jnp.float64)) / self.sum_weights


def create_metrics(config, metadata=None, num_data: Optional[int] = None) -> List[Metric]:
    """Factory (metric.cpp:9-28); unknown names raise."""
    out: List[Metric] = []
    names = config.metric or _default_metric(config.objective)
    for name in names:
        name = name.strip()
        if name in ("l2", "mse", "mean_squared_error", "regression"):
            m: Metric = L2Metric()
        elif name in ("l1", "mae", "mean_absolute_error"):
            m = L1Metric()
        elif name == "binary_logloss":
            m = BinaryLoglossMetric(config)
        elif name == "binary_error":
            m = BinaryErrorMetric(config)
        elif name == "auc":
            m = AUCMetric()
        elif name == "multi_logloss":
            m = MultiLoglossMetric()
        elif name == "multi_error":
            m = MultiErrorMetric()
        elif name in ("ndcg", "ndcg@"):
            from .metrics_rank import NDCGMetric

            m = NDCGMetric(config)
        elif name in ("", "none", "null"):
            continue
        else:
            raise ValueError(f"Unknown metric: {name!r}")
        if metadata is not None:
            m.init(metadata, num_data if num_data is not None else len(metadata.label))
        out.append(m)
    return out


def _default_metric(objective: str) -> List[str]:
    return {
        "regression": ["l2"],
        "binary": ["binary_logloss"],
        "multiclass": ["multi_logloss"],
        "lambdarank": ["ndcg"],
    }.get(objective, ["l2"])
