"""Python side of the C API shim (src/capi/lgbm_capi.c).

Implements the reference's ``LGBM_*`` semantics (include/LightGBM/
c_api.h:60-607, src/c_api.cpp) over the in-process framework: handles
are integer ids in a registry, caller buffers are read/written through
ctypes from the raw addresses the C layer forwards.  The embedded
interpreter holds the GIL for the duration of each call, which
serializes mutations exactly like the reference Booster's mutex
(c_api.cpp:231).

Set ``LGBM_CAPI_PLATFORM`` (e.g. ``cpu``) before first use to pin the
JAX platform — an embedded host usually wants explicit control.
"""

from __future__ import annotations

import ctypes
import os
from typing import Any, Dict, List

import numpy as np

if os.environ.get("LGBM_CAPI_PLATFORM"):
    import jax

    jax.config.update("jax_platforms", os.environ["LGBM_CAPI_PLATFORM"])
else:
    # No explicit platform: probe the default backend with a timeout so a
    # dead TPU tunnel degrades to CPU instead of hanging the host process
    # on its first LGBM_* call (see lightgbm_tpu.backend).  NOTE: this can
    # stall the first LGBM_* call for up to ~45s while the probe subprocess
    # dials the backend; embedded hosts that want a fast, deterministic
    # startup should set LGBM_CAPI_PLATFORM explicitly.  In hosts where
    # sys.executable is not a python interpreter the probe is skipped and
    # the default backend is trusted (lightgbm_tpu/backend.py).
    from .backend import pin_cpu_if_default_dead

    pin_cpu_if_default_dead(timeout_s=45.0)

from .basic import Booster, Dataset, LightGBMError  # noqa: E402
from .config import Config, key_alias_transform  # noqa: E402

# c_api.h:32-39
_DTYPE_F32, _DTYPE_F64, _DTYPE_I32, _DTYPE_I64 = 0, 1, 2, 3
_PREDICT_NORMAL, _PREDICT_RAW, _PREDICT_LEAF = 0, 1, 2

_NP_OF_DTYPE = {
    _DTYPE_F32: np.float32,
    _DTYPE_F64: np.float64,
    _DTYPE_I32: np.int32,
    _DTYPE_I64: np.int64,
}

_registry: Dict[int, Any] = {}
_next_id = [1]
# per-handle keep-alive store for LGBM_DatasetGetField out pointers
_field_cache: Dict[int, Dict[str, np.ndarray]] = {}


def _register(obj: Any) -> int:
    h = _next_id[0]
    _next_id[0] += 1
    _registry[h] = obj
    return h


def _get(handle: int):
    try:
        return _registry[handle]
    except KeyError:
        raise LightGBMError(f"invalid handle {handle}") from None


def _write_i64(addr: int, value: int) -> None:
    ctypes.c_int64.from_address(addr).value = int(value)


def _write_i32(addr: int, value: int) -> None:
    ctypes.c_int32.from_address(addr).value = int(value)


def _write_ptr(addr: int, value: int) -> None:
    ctypes.c_void_p.from_address(addr).value = int(value)


def _read_array(addr: int, count: int, dtype) -> np.ndarray:
    n = int(count)
    buf = (ctypes.c_char * (n * np.dtype(dtype).itemsize)).from_address(addr)
    return np.frombuffer(buf, dtype=dtype, count=n).copy()


def _write_array(addr: int, arr: np.ndarray) -> None:
    arr = np.ascontiguousarray(arr)
    ctypes.memmove(addr, arr.ctypes.data, arr.nbytes)


def _params_dict(parameters: str) -> Dict[str, str]:
    """The CLI's key=value string form (Str2Map, c_api.cpp:36)."""
    out: Dict[str, str] = {}
    for tok in (parameters or "").split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k] = v
    return key_alias_transform(out)


def _write_string_array(addr: int, names) -> None:
    """Write strings into a caller-allocated char*[] (the reference's
    GetEvalNames/GetFeatureNames out convention)."""
    ptrs = _read_array(addr, len(names), np.int64)
    for p, name in zip(ptrs, names):
        raw = name.encode() + b"\0"
        ctypes.memmove(int(p), raw, len(raw))


def _read_sparse_csr(ptr_addr, ptr_type, indices_addr, data_addr, data_type,
                     nptr, nelem, other_dim, order):
    """Rebuild a scipy matrix from caller CSR/CSC buffers; returns CSR."""
    import scipy.sparse as sp

    ptr = _read_array(ptr_addr, nptr, _NP_OF_DTYPE[ptr_type]).astype(np.int64)
    indices = _read_array(indices_addr, nelem, np.int32)
    values = _read_array(data_addr, nelem, _NP_OF_DTYPE[data_type]).astype(
        np.float64
    )
    if order == "csr":
        m = sp.csr_matrix((values, indices, ptr),
                          shape=(int(nptr) - 1, int(other_dim)))
        return m
    m = sp.csc_matrix((values, indices, ptr),
                      shape=(int(other_dim), int(nptr) - 1))
    return m.tocsr()


def free_handle(handle: int) -> None:
    _registry.pop(handle, None)
    _field_cache.pop(handle, None)


# ------------------------------------------------------------------ dataset
def dataset_create_from_file(filename, parameters, reference, out_addr):
    ref = _get(reference) if reference else None
    ds = Dataset(filename, reference=ref, params=_params_dict(parameters))
    ds.construct()
    _write_ptr(out_addr, _register(ds))


def dataset_create_from_mat(data_addr, data_type, nrow, ncol, is_row_major,
                            parameters, reference, out_addr):
    X = _read_array(data_addr, nrow * ncol, _NP_OF_DTYPE[data_type])
    X = X.reshape((nrow, ncol) if is_row_major else (ncol, nrow))
    if not is_row_major:
        X = X.T
    ref = _get(reference) if reference else None
    # the reference constructs label-less in-memory datasets; labels
    # arrive via LGBM_DatasetSetField before training (c_api.cpp:292-340)
    ds = Dataset(np.asarray(X, np.float64),
                 label=np.zeros(nrow, np.float32),
                 reference=ref, params=_params_dict(parameters))
    ds.construct()
    _write_ptr(out_addr, _register(ds))


def dataset_create_from_csr(indptr_addr, indptr_type, indices_addr, data_addr,
                            data_type, nindptr, nelem, num_col, parameters,
                            reference, out_addr):
    csr = _read_sparse_csr(indptr_addr, indptr_type, indices_addr, data_addr,
                           data_type, nindptr, nelem, num_col, "csr")
    ref = _get(reference) if reference else None
    ds = Dataset(csr, label=np.zeros(csr.shape[0], np.float32),
                 reference=ref, params=_params_dict(parameters))
    ds.construct()
    _write_ptr(out_addr, _register(ds))


def dataset_set_field(handle, field_name, data_addr, num_element, dtype):
    ds: Dataset = _get(handle)
    arr = _read_array(data_addr, num_element, _NP_OF_DTYPE[dtype])
    ds.set_field(field_name, arr)
    _field_cache.pop(handle, None)


def dataset_get_field(handle, field_name, out_len_addr, out_ptr_addr,
                      out_type_addr):
    ds: Dataset = _get(handle)
    val = ds.get_field(field_name)
    if val is None:
        raise LightGBMError(f"field {field_name} is empty")
    if field_name in ("group", "query"):
        # the reference C API returns query BOUNDARIES (len num_queries+1,
        # dataset.cpp GetIntField -> query_boundaries_), not per-query sizes;
        # its python wrapper diffs the boundaries back into sizes.  Internally
        # we store sizes, so convert on the way out.
        sizes = np.ascontiguousarray(val, dtype=np.int64)
        arr = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int32)
        out_type = _DTYPE_I32
    else:
        arr = np.ascontiguousarray(val, dtype=np.float32)
        out_type = _DTYPE_F32
    # the returned pointer must outlive the call (the reference hands out
    # internal vector storage, c_api.cpp); cache per handle+field
    _field_cache.setdefault(handle, {})[field_name] = arr
    _write_i64(out_len_addr, arr.shape[0])
    _write_ptr(out_ptr_addr, arr.ctypes.data)
    _write_i32(out_type_addr, out_type)


def dataset_get_num_data(handle, out_addr):
    _write_i64(out_addr, _get(handle).num_data())


def dataset_get_num_feature(handle, out_addr):
    _write_i64(out_addr, _get(handle).num_feature())


def dataset_save_binary(handle, filename):
    _get(handle).save_binary(filename)


# ------------------------------------------------------------------ booster
def booster_create(train_data, parameters, out_addr):
    ds: Dataset = _get(train_data)
    bst = Booster(params=_params_dict(parameters), train_set=ds)
    _write_ptr(out_addr, _register(bst))


def booster_create_from_modelfile(filename, out_num_iter_addr, out_addr):
    bst = Booster(model_file=filename)
    _write_i64(out_num_iter_addr,
               bst.num_trees() // max(1, bst._gbdt.num_class))
    _write_ptr(out_addr, _register(bst))


def booster_add_valid_data(handle, valid_data):
    bst: Booster = _get(handle)
    bst.add_valid(_get(valid_data), name=f"valid_{len(bst.name_valid_sets)}")


def booster_update_one_iter(handle, is_finished_addr):
    finished = _get(handle).update()
    _write_i32(is_finished_addr, 1 if finished else 0)


def booster_rollback_one_iter(handle):
    _get(handle).rollback_one_iter()


def booster_get_current_iteration(handle, out_addr):
    _write_i64(out_addr, _get(handle).current_iteration)


def booster_get_num_classes(handle, out_addr):
    _write_i64(out_addr, _get(handle)._gbdt.num_class)


def _eval_names(bst: Booster) -> List[str]:
    """Metric names WITHOUT evaluating (the reference reads its metric
    objects, c_api.cpp GetEvalNames); empty for model-file-loaded
    boosters, which carry no training metrics."""
    names: List[str] = []
    for m in getattr(bst._gbdt, "train_metrics", None) or []:
        if hasattr(m, "eval_multi"):
            names.extend(f"{m.name}@{k}" for k in m.eval_at)
        else:
            names.append(m.name)
    return names


def booster_get_eval_counts(handle, out_addr):
    _write_i64(out_addr, len(_eval_names(_get(handle))))


def booster_get_eval_names(handle, out_len_addr, out_strs_addr):
    names = _eval_names(_get(handle))
    _write_i64(out_len_addr, len(names))
    _write_string_array(out_strs_addr, names)


def booster_get_eval(handle, data_idx, out_len_addr, out_results_addr):
    vals = [t[2] for t in _get(handle).eval(int(data_idx), "")]
    arr = np.asarray(vals, np.float64)
    _write_i64(out_len_addr, arr.shape[0])
    _write_array(out_results_addr, arr)


def booster_predict_for_mat(handle, data_addr, data_type, nrow, ncol,
                            is_row_major, predict_type, num_iteration,
                            out_len_addr, out_result_addr):
    bst: Booster = _get(handle)
    X = _read_array(data_addr, nrow * ncol, _NP_OF_DTYPE[data_type])
    X = X.reshape((nrow, ncol) if is_row_major else (ncol, nrow))
    if not is_row_major:
        X = X.T
    X = np.asarray(X, np.float64)
    if predict_type == _PREDICT_LEAF:
        res = bst.predict(X, pred_leaf=True, num_iteration=num_iteration)
    elif predict_type == _PREDICT_RAW:
        res = bst.predict(X, raw_score=True, num_iteration=num_iteration)
    else:
        res = bst.predict(X, num_iteration=num_iteration)
    arr = np.ascontiguousarray(res, np.float64).reshape(-1)
    _write_i64(out_len_addr, arr.shape[0])
    _write_array(out_result_addr, arr)


def booster_predict_for_file(handle, data_filename, data_has_header,
                             predict_type, num_iteration, result_filename):
    bst: Booster = _get(handle)
    pred = bst.predict(
        data_filename,
        raw_score=predict_type == _PREDICT_RAW,
        pred_leaf=predict_type == _PREDICT_LEAF,
        num_iteration=num_iteration,
        data_has_header=bool(data_has_header),
    )
    arr = np.asarray(pred)
    from .resilience.atomic import atomic_writer

    with atomic_writer(result_filename) as fh:
        if arr.ndim == 1:
            fh.write("\n".join(repr(float(v)) for v in arr) + "\n")
        else:
            for row in arr:
                fh.write("\t".join(repr(float(v)) for v in row) + "\n")


def booster_save_model(handle, num_iteration, filename):
    _get(handle).save_model(filename, num_iteration=num_iteration)


def dataset_create_from_csc(col_ptr_addr, col_ptr_type, indices_addr,
                            data_addr, data_type, ncol_ptr, nelem, num_row,
                            parameters, reference, out_addr):
    csr = _read_sparse_csr(col_ptr_addr, col_ptr_type, indices_addr,
                           data_addr, data_type, ncol_ptr, nelem, num_row,
                           "csc")
    ref = _get(reference) if reference else None
    ds = Dataset(csr, label=np.zeros(int(num_row), np.float32),
                 reference=ref, params=_params_dict(parameters))
    ds.construct()
    _write_ptr(out_addr, _register(ds))


def dataset_get_subset(handle, indices_addr, num_indices, parameters,
                       out_addr):
    ds: Dataset = _get(handle)
    idx = _read_array(indices_addr, num_indices, np.int32)
    sub = ds.subset(idx, params=_params_dict(parameters) or None)
    _write_ptr(out_addr, _register(sub))


def dataset_set_feature_names(handle, names_addr, num_names):
    ds: Dataset = _get(handle)
    ptrs = _read_array(names_addr, num_names, np.int64)
    names = [ctypes.c_char_p(int(p)).value.decode() for p in ptrs]
    ds.set_feature_name(names)


def dataset_get_feature_names(handle, names_addr, out_num_addr):
    ds: Dataset = _get(handle)
    names = ds.construct().feature_names
    _write_i64(out_num_addr, len(names))
    _write_string_array(names_addr, names)


def booster_merge(handle, other_handle):
    _get(handle)._gbdt.merge_from(_get(other_handle)._gbdt)


def booster_reset_training_data(handle, train_data):
    _get(handle)._reset_train_data(_get(train_data))


def booster_reset_parameter(handle, parameters):
    _get(handle).reset_parameter(_params_dict(parameters))


def booster_update_one_iter_custom(handle, grad_addr, hess_addr,
                                   is_finished_addr):
    bst: Booster = _get(handle)
    n = bst._gbdt.num_data * bst._gbdt.num_class
    grad = _read_array(grad_addr, n, np.float32)
    hess = _read_array(hess_addr, n, np.float32)
    finished = bst._gbdt.train_one_iter(grad, hess)
    _write_i32(is_finished_addr, 1 if finished else 0)


def booster_get_num_predict(handle, data_idx, out_len_addr):
    gb = _get(handle)._gbdt
    n = gb.num_data if data_idx == 0 else gb.valid_sets[data_idx - 1].num_data
    _write_i64(out_len_addr, int(n) * gb.num_class)


def booster_get_predict(handle, data_idx, out_len_addr, out_result_addr):
    """Objective-transformed inner predictions in ROW-major
    [num_data, num_class] (GBDT::GetPredictAt, gbdt.cpp:388-426)."""
    gb = _get(handle)._gbdt
    scores = np.asarray(gb.predict_at(int(data_idx)))  # [K, n] raw
    if gb.sigmoid > 0 and gb.num_class == 1 and gb.objective_name() == "binary":
        out = 1.0 / (1.0 + np.exp(-2.0 * gb.sigmoid * scores[0]))
    elif gb.num_class > 1:
        z = scores - scores.max(axis=0, keepdims=True)
        e = np.exp(z)
        out = (e / e.sum(axis=0, keepdims=True)).T
    else:
        out = scores[0]
    arr = np.ascontiguousarray(out, np.float64).reshape(-1)
    _write_i64(out_len_addr, arr.shape[0])
    _write_array(out_result_addr, arr)


def booster_calc_num_predict(handle, num_row, predict_type, num_iteration,
                             out_len_addr):
    gb = _get(handle)._gbdt
    K = gb.num_class
    if predict_type == _PREDICT_LEAF:
        total_iter = gb.num_trees // max(1, K)
        n_iter = total_iter if num_iteration <= 0 else min(
            int(num_iteration), total_iter
        )
        per_row = n_iter * K
    else:
        per_row = K
    _write_i64(out_len_addr, int(num_row) * per_row)


def _predict_sparse(handle, csr, predict_type, num_iteration, out_len_addr,
                    out_result_addr):
    bst: Booster = _get(handle)
    if predict_type == _PREDICT_LEAF:
        res = bst.predict(csr, pred_leaf=True, num_iteration=num_iteration)
    elif predict_type == _PREDICT_RAW:
        res = bst.predict(csr, raw_score=True, num_iteration=num_iteration)
    else:
        res = bst.predict(csr, num_iteration=num_iteration)
    arr = np.ascontiguousarray(res, np.float64).reshape(-1)
    _write_i64(out_len_addr, arr.shape[0])
    _write_array(out_result_addr, arr)


def booster_predict_for_csr(handle, indptr_addr, indptr_type, indices_addr,
                            data_addr, data_type, nindptr, nelem, num_col,
                            predict_type, num_iteration, out_len_addr,
                            out_result_addr):
    csr = _read_sparse_csr(indptr_addr, indptr_type, indices_addr, data_addr,
                           data_type, nindptr, nelem, num_col, "csr")
    _predict_sparse(handle, csr, predict_type, num_iteration, out_len_addr,
                    out_result_addr)


def booster_predict_for_csc(handle, col_ptr_addr, col_ptr_type, indices_addr,
                            data_addr, data_type, ncol_ptr, nelem, num_row,
                            predict_type, num_iteration, out_len_addr,
                            out_result_addr):
    csr = _read_sparse_csr(col_ptr_addr, col_ptr_type, indices_addr,
                           data_addr, data_type, ncol_ptr, nelem, num_row,
                           "csc")
    _predict_sparse(handle, csr, predict_type, num_iteration, out_len_addr,
                    out_result_addr)


def booster_dump_model(handle, num_iteration, buffer_len, out_len_addr,
                       out_str_addr):
    import json

    txt = json.dumps(_get(handle).dump_model(num_iteration=num_iteration))
    raw = txt.encode() + b"\0"
    _write_i64(out_len_addr, len(raw))
    if buffer_len >= len(raw):
        ctypes.memmove(out_str_addr, raw, len(raw))


def booster_get_leaf_value(handle, tree_idx, leaf_idx, out_val_addr):
    gb = _get(handle)._gbdt
    val = float(np.asarray(gb.models[tree_idx].leaf_value)[leaf_idx])
    ctypes.c_double.from_address(out_val_addr).value = val


def booster_set_leaf_value(handle, tree_idx, leaf_idx, val):
    import jax.numpy as jnp

    gb = _get(handle)._gbdt
    tree = gb.models[tree_idx]
    gb.models[tree_idx] = tree._replace(
        leaf_value=jnp.asarray(tree.leaf_value).at[int(leaf_idx)].set(
            jnp.float32(val)
        )
    )
    gb._model_version += 1
