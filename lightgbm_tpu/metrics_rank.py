"""NDCG@k metric (src/metric/rank_metric.hpp:16-165)."""

from __future__ import annotations

from typing import List

import numpy as np

from .dcg import dcg_at_k, label_gains_from_config, max_dcg_at_k
from .metrics import Metric


class NDCGMetric(Metric):
    """Per-query NDCG averaged with query weights; all-negative queries
    count as 1 (rank_metric.hpp:96-100).  Reports one value per eval_at
    position via ``eval_multi``; ``eval`` returns the first position
    (used for early stopping like the reference's metric vector head)."""

    name = "ndcg"
    bigger_is_better = True

    def __init__(self, config):
        self.eval_at = list(config.ndcg_eval_at) or [1, 2, 3, 4, 5]
        self.gains = label_gains_from_config(config.label_gain)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError("NDCG metric requires query information")
        self.qb = np.asarray(metadata.query_boundaries)
        self.query_weights = metadata.query_weights
        nq = len(self.qb) - 1
        self.sum_query_weights = (
            float(nq) if self.query_weights is None else float(self.query_weights.sum())
        )
        # cache per-query ideal DCG at each eval position
        self.max_dcgs = np.zeros((nq, len(self.eval_at)))
        for q in range(nq):
            lab = self.label[self.qb[q] : self.qb[q + 1]]
            for ki, k in enumerate(self.eval_at):
                self.max_dcgs[q, ki] = max_dcg_at_k(k, lab, self.gains)

    def eval_multi(self, scores) -> List[float]:
        scores = np.asarray(scores, np.float64).reshape(-1)
        nq = len(self.qb) - 1
        acc = np.zeros(len(self.eval_at))
        for q in range(nq):
            beg, end = self.qb[q], self.qb[q + 1]
            lab = self.label[beg:end]
            order = np.argsort(-scores[beg:end], kind="stable")
            w = 1.0 if self.query_weights is None else self.query_weights[q]
            for ki, k in enumerate(self.eval_at):
                if self.max_dcgs[q, ki] <= 0:
                    acc[ki] += w  # no positive labels -> NDCG := 1
                else:
                    acc[ki] += (
                        w * dcg_at_k(k, lab[order], self.gains) / self.max_dcgs[q, ki]
                    )
        return [float(a / self.sum_query_weights) for a in acc]

    def eval(self, scores) -> float:
        return self.eval_multi(scores)[0]
