"""NDCG@k metric (src/metric/rank_metric.hpp:16-165)."""

from __future__ import annotations

from typing import List

import numpy as np

from .dcg import (
    build_padded_query_layout,
    dcg_at_k,
    label_gains_from_config,
    max_dcg_at_k,
    position_discounts,
)
from .metrics import Metric


class NDCGMetric(Metric):
    """Per-query NDCG averaged with query weights; all-negative queries
    count as 1 (rank_metric.hpp:96-100).  Reports one value per eval_at
    position via ``eval_multi``; ``eval`` returns the first position
    (used for early stopping like the reference's metric vector head)."""

    name = "ndcg"
    bigger_is_better = True

    def __init__(self, config):
        self.eval_at = list(config.ndcg_eval_at) or [1, 2, 3, 4, 5]
        self.gains = label_gains_from_config(config.label_gain)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError("NDCG metric requires query information")
        self.qb = np.asarray(metadata.query_boundaries)
        self.query_weights = metadata.query_weights
        nq = len(self.qb) - 1
        self.sum_query_weights = (
            float(nq) if self.query_weights is None else float(self.query_weights.sum())
        )
        # cache per-query ideal DCG at each eval position
        self.max_dcgs = np.zeros((nq, len(self.eval_at)))
        for q in range(nq):
            lab = self.label[self.qb[q] : self.qb[q + 1]]
            for ki, k in enumerate(self.eval_at):
                self.max_dcgs[q, ki] = max_dcg_at_k(k, lab, self.gains)
        # padded [nq, Q] layout for the vectorized eval (shared with the
        # lambdarank objective): padding cells point at the sentinel slot
        # n, whose score sorts last and whose gain is 0, so they never
        # contribute to any DCG@k.  Guard against skewed group sizes —
        # one giant query among many small ones makes nq*Q explode — by
        # falling back to the per-query loop when padding inflates the
        # work more than ~8x over the O(n) loop.
        lens = np.diff(self.qb)
        Q = int(lens.max()) if nq else 1
        # decide BEFORE allocating: the guard would be pointless if the
        # nq x Q matrix it protects against already existed
        self._use_padded = nq == 0 or nq * Q <= 8 * max(num_data, 1)
        if not self._use_padded:
            return
        pad_idx, _ = build_padded_query_layout(self.qb, num_data)
        self._pad_idx = pad_idx
        valid = pad_idx < num_data
        lab_idx = np.minimum(
            self.label[np.minimum(pad_idx, num_data - 1)].astype(np.int64),
            len(self.gains) - 1,
        )
        self._gain_padded = np.where(valid, self.gains[lab_idx], 0.0)
        self._discounts = position_discounts(pad_idx.shape[1])

    def _eval_multi_loop(self, scores) -> List[float]:
        """O(n) per-query fallback for heavily skewed query sizes."""
        acc = np.zeros(len(self.eval_at))
        nq = len(self.qb) - 1
        for q in range(nq):
            beg, end = self.qb[q], self.qb[q + 1]
            lab = self.label[beg:end]
            order = np.argsort(-scores[beg:end], kind="stable")
            w = 1.0 if self.query_weights is None else self.query_weights[q]
            for ki, k in enumerate(self.eval_at):
                if self.max_dcgs[q, ki] <= 0:
                    acc[ki] += w  # no positive labels -> NDCG := 1
                else:
                    acc[ki] += (
                        w * dcg_at_k(k, lab[order], self.gains) / self.max_dcgs[q, ki]
                    )
        return [float(a / self.sum_query_weights) for a in acc]

    def eval_multi(self, scores) -> List[float]:
        """Vectorized over queries: one padded argsort + gather replaces
        the per-query python loop (rank_metric.hpp's per-thread
        accumulators collapse into matrix ops)."""
        scores = np.asarray(scores, np.float64).reshape(-1)
        if not self._use_padded:
            return self._eval_multi_loop(scores)
        nq, Q = self._pad_idx.shape
        sp = np.concatenate([scores, [-np.inf]])  # sentinel slot n;
        # every pad cell maps there via the min(), so no extra masking
        qs = sp[np.minimum(self._pad_idx, len(scores))]
        order = np.argsort(-qs, axis=1, kind="stable")
        g = np.take_along_axis(self._gain_padded, order, axis=1)  # [nq, Q]
        gd = g * self._discounts[None, :]
        cum = np.cumsum(gd, axis=1)  # cum[:, k-1] = DCG@k
        w = (
            np.ones(nq)
            if self.query_weights is None
            else np.asarray(self.query_weights, np.float64)
        )
        out = []
        for ki, k in enumerate(self.eval_at):
            dcg = cum[:, min(k, Q) - 1] if Q else np.zeros(nq)
            maxd = self.max_dcgs[:, ki]
            ndcg = np.where(maxd > 0, dcg / np.maximum(maxd, 1e-300), 1.0)
            out.append(float((ndcg * w).sum() / self.sum_query_weights))
        return out

    def eval(self, scores) -> float:
        return self.eval_multi(scores)[0]
