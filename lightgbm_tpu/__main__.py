"""``python -m lightgbm_tpu config=train.conf`` == the reference's
``lightgbm`` CLI binary (src/main.cpp)."""

import sys

from .cli import main

sys.exit(main())
