"""Training and cross-validation entry points.

Mirrors the reference engine.py: ``train()`` (engine.py:12-194) translates
keyword conveniences into callbacks and runs the boosting loop; ``cv()``
(engine.py:197-399) runs k-fold (stratified when classifying) CV with
mean/std aggregation.
"""

from __future__ import annotations

import collections
import copy
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from . import callback
from .basic import Booster, Dataset, LightGBMError
from .config import Config, key_alias_transform


def train(
    params: Dict[str, Any],
    train_set: Dataset,
    num_boost_round: int = 100,
    valid_sets: Optional[List[Dataset]] = None,
    valid_names: Optional[List[str]] = None,
    fobj: Optional[Callable] = None,
    feval: Optional[Callable] = None,
    init_model=None,
    feature_name: Optional[List[str]] = None,
    categorical_feature: Optional[List[int]] = None,
    early_stopping_rounds: Optional[int] = None,
    evals_result: Optional[dict] = None,
    verbose_eval=True,
    learning_rates=None,
    callbacks: Optional[List[Callable]] = None,
) -> Booster:
    """Train a booster (reference engine.py:12-194)."""
    params = key_alias_transform(dict(params))
    if fobj is not None:
        params["objective"] = "none"
    if feature_name is not None:
        train_set.feature_name = feature_name
    if categorical_feature is not None:
        train_set.categorical_feature = list(categorical_feature)
    if isinstance(init_model, str):
        params["input_model"] = init_model
    elif isinstance(init_model, Booster):
        params["input_model"] = ""

    # merge dataset params so max_bin etc. flow through
    merged = dict(train_set.params or {})
    merged.update(params)
    train_set.params = merged

    booster = Booster(params=merged, train_set=train_set)
    if isinstance(init_model, Booster):
        booster._gbdt.merge_from(init_model._gbdt, prepend=True)
    init_iteration = booster._gbdt.num_init_iteration

    valid_sets = valid_sets or []
    valid_names = valid_names or []
    is_valid_contain_train = False
    train_data_name = "training"
    for i, vs in enumerate(valid_sets):
        name = valid_names[i] if i < len(valid_names) else f"valid_{i}"
        if vs is train_set:
            is_valid_contain_train = True
            train_data_name = name
            booster.train_data_name = name
            continue
        if vs.reference is None:
            vs.reference = train_set
        booster.add_valid(vs, name)

    # dedupe while preserving insertion order: callbacks sharing an order
    # value (user callbacks default to 0) must run in registration order
    # like the reference's list, not in hash order
    cbs = list(dict.fromkeys(callbacks or []))
    if verbose_eval is True:
        cbs.append(callback.print_evaluation())
    elif isinstance(verbose_eval, int) and verbose_eval:
        cbs.append(callback.print_evaluation(verbose_eval))
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.append(callback.early_stopping(early_stopping_rounds, verbose=bool(verbose_eval)))
    if learning_rates is not None:
        cbs.append(callback.reset_parameter(learning_rate=learning_rates))
    if evals_result is not None:
        cbs.append(callback.record_evaluation(evals_result))

    callbacks_before = [cb for cb in cbs if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in cbs if not getattr(cb, "before_iteration", False)]
    callbacks_before.sort(key=lambda cb: getattr(cb, "order", 0))
    callbacks_after.sort(key=lambda cb: getattr(cb, "order", 0))

    for i in range(init_iteration, init_iteration + num_boost_round):
        for cb in callbacks_before:
            cb(callback.CallbackEnv(
                model=booster, params=params, iteration=i,
                begin_iteration=init_iteration,
                end_iteration=init_iteration + num_boost_round,
                evaluation_result_list=None,
            ))
        is_finished = booster.update(fobj=fobj)

        evaluation_result_list = []
        if valid_sets or is_valid_contain_train:
            if is_valid_contain_train:
                evaluation_result_list.extend(booster.eval_train(feval))
            evaluation_result_list.extend(booster.eval_valid(feval))
        try:
            for cb in callbacks_after:
                cb(callback.CallbackEnv(
                    model=booster, params=params, iteration=i,
                    begin_iteration=init_iteration,
                    end_iteration=init_iteration + num_boost_round,
                    evaluation_result_list=evaluation_result_list,
                ))
        except callback.EarlyStopException:
            break
        if is_finished:
            break
    # drain the lagged stop check when the loop ended by round count
    # (no-op unless LGBM_TPU_STOP_LAG is set)
    booster.finish_lagged_stop()
    # lagged-stop rollback may have popped trees the early-stopping
    # callback already scored; best_iteration must never point past the
    # surviving model (ADVICE r3: gbdt.py rollback interaction).  When
    # the clamp fires, the callback-recorded best_score belongs to a
    # popped tree — drop it so consumers never pair the surviving
    # iteration with a rolled-back metric (ADVICE r4).
    if booster.best_iteration > booster.current_iteration:
        booster.best_iteration = booster.current_iteration
        if getattr(booster, "best_score", None):
            booster.best_score = {}
    if booster.best_iteration <= 0:
        booster.best_iteration = -1
    return booster


class CVBooster:
    """Auxiliary container keeping all fold boosters (engine.py:197-230)."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        if name.startswith("_"):  # never fabricate dunder/private protocol hooks
            raise AttributeError(name)

        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]

        return handler_function


def _make_n_folds(full_data: Dataset, nfold: int, params: Dict[str, Any],
                  seed: int, stratified: bool, shuffle: bool):
    """engine.py:233-263: fold index generation (query-granular for ranking,
    stratified for classification when asked)."""
    inner = full_data.construct()
    num_data = full_data.num_data()
    qb = inner.metadata.query_boundaries
    rng = np.random.RandomState(seed)
    folds = []
    if qb is not None:
        qb = np.asarray(qb)
        nq = len(qb) - 1
        perm = rng.permutation(nq) if shuffle else np.arange(nq)
        for k in range(nfold):
            test_q = perm[k::nfold]
            mask = np.zeros(num_data, bool)
            for q in test_q:
                mask[qb[q]:qb[q + 1]] = True
            folds.append((np.nonzero(~mask)[0], np.nonzero(mask)[0]))
    elif stratified:
        label = np.asarray(full_data.get_label())
        idx_by_class = [np.nonzero(label == c)[0] for c in np.unique(label)]
        test_sets = [[] for _ in range(nfold)]
        for idx in idx_by_class:
            perm = rng.permutation(idx) if shuffle else idx
            for k in range(nfold):
                test_sets[k].append(perm[k::nfold])
        for k in range(nfold):
            test_idx = np.sort(np.concatenate(test_sets[k]))
            mask = np.zeros(num_data, bool)
            mask[test_idx] = True
            folds.append((np.nonzero(~mask)[0], test_idx))
    else:
        perm = rng.permutation(num_data) if shuffle else np.arange(num_data)
        for k in range(nfold):
            test_idx = np.sort(perm[k::nfold])
            mask = np.zeros(num_data, bool)
            mask[test_idx] = True
            folds.append((np.nonzero(~mask)[0], test_idx))
    return folds


def _cv_can_share_bins(params, inner, fpreproc, fobj) -> bool:
    """May cv() train every fold on the shared full binned matrix with a
    base row mask instead of per-fold subsets?  Requires that NOTHING in
    the training pipeline looks at global (unmasked) data statistics:

    * fpreproc/fobj — arbitrary user code sees the dataset shape
    * query grouping — fold masks are query-granular, and the ranking
      objectives normalize per query over the raw row layout
    * bagging ANDs with the base mask fine, but the draw itself is over
      all n rows — a subset-trained fold draws over n_train rows with
      the same seed, so the realized masks diverge
    * is_unbalance / scale_pos_weight derive class weights from the
      WHOLE label vector at objective init
    * dart rescales against drop-set predictions whose normalization
      constants are global

    Everything else is per-row math, where masked rows are exact no-ops
    (set_base_row_mask's parity contract).
    """
    if fpreproc is not None or fobj is not None:
        return False
    if inner.metadata.query_boundaries is not None:
        return False
    try:
        probe = Config.from_dict(dict(params))
    except Exception:
        return False
    return (
        probe.boosting_type == "gbdt"
        and (probe.bagging_fraction >= 1.0 or probe.bagging_freq <= 0)
        and not probe.is_unbalance
        and probe.scale_pos_weight == 1.0
    )


def train_many(
    params_list: List[Dict[str, Any]],
    train_set: Dataset,
    num_boost_round: int = 100,
) -> List[Booster]:
    """Train N independent models on ONE shared binned dataset, batched
    so that each boosting round advances every model's trees in a single
    forest dispatch (models/gbdt.py train_forest_round) — the
    multi-tenant "B small models sharing one chip" product shape.

    ``params_list`` holds one param dict per model.  Binning comes from
    ``train_set`` (bin once); per-model params may vary freely across
    the lane-compatible knobs (learning_rate, lambda_l1/l2,
    min_data_in_leaf, min_sum_hessian_in_leaf, min_gain_to_split,
    max_depth, feature_fraction, bagging, seeds, objective — even
    num_class), but ``num_leaves`` and ``max_bin`` fix the traced
    program shape and must match across models (ValueError otherwise).

    Models whose configs cannot batch (forest_batching=off, non-serial
    learner, f64 histograms, histogram pool, or auto-gated row count)
    fall back to sequential per-model rounds — same results, no shared
    dispatch.  Returns the boosters in input order.
    """
    from .models.gbdt import train_forest_round

    if not params_list:
        return []
    # merge dataset params before binning, exactly as train() does, so
    # max_bin etc. reach the binner; the binning-relevant knobs must
    # agree across models anyway (the _num_bins check below), so the
    # first model's params speak for the sweep
    merged = dict(train_set.params or {})
    merged.update(key_alias_transform(dict(params_list[0])))
    train_set.params = merged
    train_set.construct()
    boosters = []
    for p in params_list:
        tparams = key_alias_transform(dict(p))
        boosters.append(Booster(params=tparams, train_set=train_set))
    gb = [b._gbdt for b in boosters]
    ref = gb[0]
    for g in gb[1:]:
        if g.max_leaves != ref.max_leaves or g._num_bins != ref._num_bins:
            raise ValueError(
                "train_many: num_leaves and max_bin must match across "
                "models (they fix the traced program shape); vary "
                "learning-rate/regularization/sampling knobs per model "
                "instead"
            )
    batched = all(g._forest_eligible() for g in gb)
    done = [False] * len(gb)
    for _ in range(num_boost_round):
        idx = [i for i, d in enumerate(done) if not d]
        if not idx:
            break
        if batched:
            stops = train_forest_round([gb[i] for i in idx])
            for i, stop in zip(idx, stops):
                done[i] = bool(stop)
        else:
            for i in idx:
                done[i] = bool(boosters[i].update())
    for b in boosters:
        b.finish_lagged_stop()
    return boosters


def _agg_cv_result(raw_results):
    """Mean/std across folds (engine.py:266-280)."""
    cvmap = collections.OrderedDict()
    metric_type = {}
    for one_result in raw_results:
        for one_line in one_result:
            key = f"{one_line[0]} {one_line[1]}"
            metric_type[key] = one_line[3]
            cvmap.setdefault(key, [])
            cvmap[key].append(one_line[2])
    return [
        ("cv_agg", k, float(np.mean(v)), metric_type[k], float(np.std(v)))
        for k, v in cvmap.items()
    ]


def cv(
    params: Dict[str, Any],
    train_set: Dataset,
    num_boost_round: int = 10,
    nfold: int = 5,
    stratified: bool = False,
    shuffle: bool = True,
    metrics: Optional[List[str]] = None,
    fobj: Optional[Callable] = None,
    feval: Optional[Callable] = None,
    init_model=None,
    feature_name=None,
    categorical_feature=None,
    early_stopping_rounds: Optional[int] = None,
    fpreproc: Optional[Callable] = None,
    verbose_eval=None,
    show_stdv: bool = True,
    seed: int = 0,
    callbacks: Optional[List[Callable]] = None,
) -> Dict[str, List[float]]:
    """K-fold cross validation (engine.py:283-399).  Returns the eval
    history dict {"<name>-mean": [...], "<name>-stdv": [...]}."""
    params = key_alias_transform(dict(params))
    if fobj is not None:
        params["objective"] = "none"
    if metrics:
        params["metric"] = metrics
    if isinstance(init_model, str):
        params["input_model"] = init_model
    if feature_name is not None:
        train_set.feature_name = feature_name
    if categorical_feature is not None:
        train_set.categorical_feature = list(categorical_feature)

    full_data = train_set
    inner = full_data.construct()
    folds = _make_n_folds(full_data, nfold, params, seed, stratified, shuffle)

    share_bins = _cv_can_share_bins(params, inner, fpreproc, fobj)
    cvfolds = CVBooster()
    shared_all = True
    for train_idx, test_idx in folds:
        te = full_data.subset(np.sort(test_idx))
        tparams = dict(params)
        bst = None
        if share_bins:
            # bin-once path: every fold booster trains on the SHARED
            # full binned matrix with the fold's train rows as a base
            # row mask — no per-fold binned copy, no per-fold device
            # transfer, ONE grow-program shape for all folds (so the
            # fold loop below can batch through train_forest_round).
            # Trees/metrics are bitwise the subset-trained ones
            # (gbdt.set_base_row_mask explains the contract); the
            # set_base_row_mask guard rejects non-canonical growers,
            # falling back to the subset path.
            cand = Booster(params=tparams, train_set=full_data)
            mask = np.zeros(full_data.num_data(), np.float32)
            mask[np.sort(train_idx)] = 1.0
            try:
                cand._gbdt.set_base_row_mask(mask)
                bst = cand
            except (ValueError, AttributeError):
                bst = None
        if bst is None:
            shared_all = False
            tr = full_data.subset(np.sort(train_idx))
            if fpreproc is not None:
                tr, te, tparams = fpreproc(tr, te, tparams.copy())
            tr.params.update(tparams)
            bst = Booster(params=tparams, train_set=tr)
        bst.add_valid(te, "valid")
        cvfolds.append(bst)

    # fold-level forest batching: with the bin-once path active on every
    # fold the per-iteration grow work is shape-identical across folds —
    # ONE batched dispatch advances all nfold trees (models/gbdt.py
    # train_forest_round)
    batch_folds = (
        share_bins and shared_all and fobj is None
        and all(b._gbdt._forest_eligible() for b in cvfolds.boosters)
    )
    if batch_folds:
        from .models.gbdt import train_forest_round

    results = collections.defaultdict(list)
    cbs = list(dict.fromkeys(callbacks or []))  # ordered dedupe, see train()
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.append(callback.early_stopping(early_stopping_rounds, verbose=False))
    if verbose_eval is True:
        cbs.append(callback.print_evaluation(show_stdv=show_stdv))
    elif isinstance(verbose_eval, int) and verbose_eval:
        cbs.append(callback.print_evaluation(verbose_eval, show_stdv))
    callbacks_before = [cb for cb in cbs if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in cbs if not getattr(cb, "before_iteration", False)]
    callbacks_before.sort(key=lambda cb: getattr(cb, "order", 0))
    callbacks_after.sort(key=lambda cb: getattr(cb, "order", 0))

    for i in range(num_boost_round):
        for cb in callbacks_before:
            for bst in cvfolds.boosters:
                cb(callback.CallbackEnv(
                    model=bst, params=params, iteration=i, begin_iteration=0,
                    end_iteration=num_boost_round, evaluation_result_list=None,
                ))
        fold_results = []
        if batch_folds:
            train_forest_round([b._gbdt for b in cvfolds.boosters])
            for bst in cvfolds.boosters:
                fold_results.append(bst.eval_valid(feval))
        else:
            for bst in cvfolds.boosters:
                bst.update(fobj=fobj)
                fold_results.append(bst.eval_valid(feval))
        res = _agg_cv_result(fold_results)
        for _, key, mean, _, std in res:
            results[key + "-mean"].append(mean)
            results[key + "-stdv"].append(std)
        try:
            for cb in callbacks_after:
                cb(callback.CallbackEnv(
                    model=cvfolds, params=params, iteration=i, begin_iteration=0,
                    end_iteration=num_boost_round, evaluation_result_list=res,
                ))
        except callback.EarlyStopException as e:
            cvfolds.best_iteration = e.best_iteration + 1
            for key in list(results):
                results[key] = results[key][: e.best_iteration + 1]
            break
    return dict(results)
