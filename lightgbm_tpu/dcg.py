"""DCG/NDCG shared machinery (DCGCalculator, src/metric/dcg_calculator.cpp).

Default label gains 2^i - 1 and position discounts 1/log2(2+i)
(dcg_calculator.cpp:13-32, kMaxPosition=10000).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

K_MAX_POSITION = 10000
_MAX_LABEL = 31


def default_label_gains() -> np.ndarray:
    return (2.0 ** np.arange(_MAX_LABEL) - 1.0).astype(np.float64)


def label_gains_from_config(label_gain: Sequence[float]) -> np.ndarray:
    if label_gain:
        return np.asarray(label_gain, np.float64)
    return default_label_gains()


def position_discounts(n: int) -> np.ndarray:
    """discount[i] = 1 / log2(2 + i) (dcg_calculator.cpp:25-28)."""
    return 1.0 / np.log2(2.0 + np.arange(n, dtype=np.float64))


def build_padded_query_layout(qb: np.ndarray, num_data: int):
    """Padded [nq, Q] row-index matrix shared by the lambdarank objective
    and the NDCG metric: row q holds that query's row indices, padding
    cells point at the sentinel slot ``num_data``.  Returns
    (pad_idx int64[nq, Q], lens int64[nq])."""
    qb = np.asarray(qb)
    lens = np.diff(qb)
    nq = len(lens)
    Q = int(lens.max()) if nq else 1
    # int32 is enough for row indices and halves the peak footprint
    # (callers needing int64 can cast the small result)
    pad_idx = np.full((nq, Q), num_data, np.int32)
    for q in range(nq):
        pad_idx[q, : lens[q]] = np.arange(qb[q], qb[q + 1])
    return pad_idx, lens


def max_dcg_at_k(k: int, labels: np.ndarray, gains: np.ndarray) -> float:
    """CalMaxDCGAtK (dcg_calculator.cpp:34-56): ideal DCG using labels
    sorted descending."""
    labels = np.asarray(labels)
    k = min(int(k), len(labels))
    top = np.sort(labels.astype(np.int64))[::-1][:k]
    disc = position_discounts(k)
    return float((gains[top] * disc).sum())


def dcg_at_k(k: int, labels_in_score_order: np.ndarray, gains: np.ndarray) -> float:
    labels = np.asarray(labels_in_score_order).astype(np.int64)
    k = min(int(k), len(labels))
    disc = position_discounts(k)
    return float((gains[labels[:k]] * disc).sum())
