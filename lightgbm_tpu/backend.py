"""JAX backend liveness probe shared by the driver entry points.

The axon TPU plugin reaches the chip through a tunnel; when that tunnel
dies, the first jax op HANGS rather than raising (reproduced live:
``jax.devices()`` blocks forever, and even ``JAX_PLATFORMS=cpu`` as an
environment variable does not stop the plugin's registration from
dialing).  Harness entry points that must always terminate (bench.py,
__graft_entry__.entry) therefore probe the default backend in a
THROWAWAY subprocess first: it either proves the backend usable (also
warming the tunnel) or times out, letting the parent pin the CPU
platform via ``jax.config`` — the only pinning that prevents the dial.
"""

from __future__ import annotations

import os
import subprocess
import sys

_PROBE_CODE = (
    "import jax, jax.numpy as jnp;"
    "x = jnp.ones((8, 8)); (x @ x).block_until_ready();"
    "print('alive', jax.devices()[0].platform)"
)


def _probe_interpreter() -> str | None:
    """Path to a real python interpreter for the probe subprocess, or None.

    In an embedded host (the plain-C path that src/capi/lgbm_capi.c
    advertises) ``sys.executable`` is the host binary or empty; spawning
    it with ``-c`` would re-execute the host program with arbitrary side
    effects, or fail and wrongly pin CPU on a healthy TPU.
    """
    exe = sys.executable
    if exe and os.path.basename(exe).lower().startswith("python"):
        return exe
    return None


def default_backend_alive(timeout_s: float = 240.0, log=None) -> bool:
    """True iff the default JAX backend completes a tiny computation in a
    subprocess within ``timeout_s``.

    When no safe probe interpreter exists (embedded host), returns True
    without probing: trusting the default backend is better than silently
    pinning CPU, and such hosts can set LGBM_CAPI_PLATFORM for control.
    """
    exe = _probe_interpreter()
    if exe is None:
        if log is not None:
            log("backend probe skipped: sys.executable is not a python "
                "interpreter (embedded host); trusting default backend")
        return True
    try:
        p = subprocess.run(
            [exe, "-c", _PROBE_CODE], timeout=timeout_s,
            capture_output=True, text=True,
        )
        ok = p.returncode == 0 and "alive" in p.stdout
        if not ok and log is not None:
            log(f"backend probe rc={p.returncode}: {p.stderr[-200:]}")
        return ok
    except Exception as e:
        if log is not None:
            log(f"backend probe failed: {type(e).__name__}: {str(e)[:200]}")
        return False


def pin_cpu_if_default_dead(timeout_s: float = 240.0, log=None) -> None:
    """Pin the CPU platform when the default backend is unresponsive.
    Must run BEFORE any jax op in the calling process."""
    if not default_backend_alive(timeout_s, log=log):
        import jax

        jax.config.update("jax_platforms", "cpu")


def require_tpu_or_row(platform: str, **row) -> bool:
    """Fail-fast contract for the measurement harnesses under
    tools/tpu_watch.sh: when ``BENCH_REQUIRE_TPU`` is set and the
    resolved backend is not the TPU, print the one-line JSON row the
    watcher's free-retry check recognizes (``platform`` + ``error``,
    plus any caller fields) and return False so the caller exits without
    burning hours on a CPU-fallback measurement.  Returns True when the
    run may proceed."""
    import json

    if platform == "tpu" or os.environ.get("BENCH_REQUIRE_TPU", "0") == "0":
        return True
    print(json.dumps({**row, "platform": platform,
                      "error": "BENCH_REQUIRE_TPU: backend is not tpu"}),
          flush=True)
    return False
