"""Command-line application: ``python -m lightgbm_tpu config=train.conf``.

Mirrors the reference Application (src/application/application.cpp,
src/main.cpp): ``key=value`` argv merged over a config file (argv wins,
application.cpp:46-104), then Train (application.cpp:187-239) — data
load, boosting/objective construction, per-iteration timing log, metric
output every ``metric_freq``, early stopping, model save — or Predict
(application.cpp:242-256) via the batch :class:`Predictor`.

Reference ``examples/*/train.conf`` files parse and run unchanged.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, List, Optional

from .config import (Config, key_alias_transform, parse_config_file,
                     parse_line_params)
from .io.dataset import BinnedDataset
from .log import Log
from .models.dart import create_boosting
from .models.gbdt import GBDT
from .obs import RunManifest, flightrec, manifest_path, telemetry
from .objectives import create_objective
from .resilience import EXIT_PREEMPTED
from .serving.batch import DEFAULT_CHUNK_ROWS, DEFAULT_STREAM_THRESHOLD


def load_parameters(argv: List[str]) -> Dict[str, str]:
    """argv ``key=value`` pairs + optional config file; argv wins
    (application.cpp:46-104).  Bare ``--flag`` tokens are accepted as
    ``flag=true`` (``python -m lightgbm_tpu ... --resume``)."""
    argv = [a[2:] + "=true" if a.startswith("--") and "=" not in a
            else a.lstrip("-") for a in argv]
    # Canonicalize alias keys BEFORE merging so argv wins across aliases
    # too (argv ``valid=`` must override a conf-file ``valid_data=``),
    # matching the reference's alias transform + priority merge
    # (config.cpp Config::KV2Map / KeyAliasTransform).
    params = key_alias_transform(parse_line_params(argv))
    conf_path = params.pop("config_file", "")  # 'config' canonicalizes here
    if conf_path:
        file_params = key_alias_transform(parse_config_file(conf_path))
        for k, v in file_params.items():
            params.setdefault(k, v)
    params.pop("config_file", None)
    return params


class Predictor:
    """Batch file prediction -> result file (src/application/predictor.hpp:
    24-155): parse input rows, run normal/raw/leaf-index prediction,
    write one line per row (tab-separated for multi-output).

    The heavy lifting lives in serving/batch.py: large CSV/TSV inputs
    stream through an overlapped parse -> predict -> write pipeline
    (reader thread prefetches the next chunk while the device runs the
    current one; a writer thread formats/writes under the crash-safe
    ``atomic_writer``).  ``overlap=False`` restores the old strictly
    sequential behavior; both are byte-identical."""

    # the single source of truth for both knobs is serving/batch.py;
    # these are instance-overridable mirrors, not independent copies
    stream_threshold = DEFAULT_STREAM_THRESHOLD
    chunk_rows = DEFAULT_CHUNK_ROWS
    overlap = True

    def __init__(self, booster, is_raw_score: bool, is_predict_leaf_index: bool):
        self.booster = booster
        self.is_raw_score = is_raw_score
        self.is_leaf = is_predict_leaf_index

    def predict_file(self, data_path: str, result_path: str, has_header: bool = False,
                     num_iteration: int = -1) -> dict:
        from .serving.batch import pipelined_predict_file

        return pipelined_predict_file(
            self.booster, data_path, result_path, has_header=has_header,
            num_iteration=num_iteration, raw_score=self.is_raw_score,
            pred_leaf=self.is_leaf,
            stream_threshold=self.stream_threshold,
            chunk_rows=self.chunk_rows, overlap=self.overlap,
        )

    def _predict_chunks(self, data_path, has_header, num_iteration):
        """The parity seam (tests pin streamed == one-shot bytes):
        prediction arrays chunk by chunk via the shared stream."""
        from .serving.batch import predict_chunk_stream

        yield from predict_chunk_stream(
            self.booster, data_path, has_header=has_header,
            num_iteration=num_iteration, raw_score=self.is_raw_score,
            pred_leaf=self.is_leaf,
            stream_threshold=self.stream_threshold,
            chunk_rows=self.chunk_rows,
        )


def _output_metrics(gbdt: GBDT, iter_num: int, names: List[str],
                    is_training_metric: bool) -> List[tuple]:
    """OutputMetric (gbdt.cpp:299-356): print + return (set_idx, metric,
    value, bigger_is_better) rows for early-stopping bookkeeping."""
    rows = []
    sets = []
    if is_training_metric:
        sets.append((0, "training"))
    sets.extend((i + 1, names[i]) for i in range(len(names)))
    for data_idx, name in sets:
        metrics = gbdt.train_metrics if data_idx == 0 else gbdt.valid_metrics[data_idx - 1]
        # device-resident eval where supported (scores stay in HBM); the
        # host copy is pulled lazily, only if some metric needs it
        plain = [m for m in metrics if not hasattr(m, "eval_multi")]
        dev_vals = (
            gbdt.eval_at(data_idx, only={m.name for m in plain})
            if plain else {}
        )
        s = None
        for m in metrics:
            if hasattr(m, "eval_multi"):
                # print every position, but early stopping judges a
                # multi-position metric only by its LAST position, like
                # the reference (gbdt.cpp OutputMetric: test_scores.back())
                if s is None:
                    scores = gbdt.predict_at(data_idx)
                    s = scores if gbdt.num_class > 1 else scores[0]
                values = m.eval_multi(s)
                for k, v in zip(m.eval_at, values):
                    Log.info(f"Iteration: {iter_num}, {name} {m.name}@{k} : {v:g}")
                if data_idx > 0 and len(values):
                    rows.append((data_idx, m.name, values[-1], m.bigger_is_better))
            else:
                v = dev_vals[m.name]
                Log.info(f"Iteration: {iter_num}, {name} {m.name} : {v:g}")
                if data_idx > 0:
                    rows.append((data_idx, m.name, v, m.bigger_is_better))
    return rows


def run_train(cfg: Config) -> GBDT:
    """InitTrain + Train (application.cpp:187-239)."""
    # install the backend-compile listener BEFORE the first jax trace so
    # the run manifest's compile count covers the whole run (the
    # listener only sees events fired after registration)
    from .analysis.recompile import compile_counter

    compile_counter()
    # a preempted/poisoned run dumps its flight recorder next to the
    # model it was training (LGBM_TPU_FLIGHTREC_DIR overrides)
    flightrec.configure_dir(
        os.path.dirname(os.path.abspath(cfg.output_model)))
    if cfg.is_parallel and cfg.num_machines > 1:
        # Network::Init analog (application.cpp:190): attach this process
        # to the multi-host JAX runtime before any data loads, so the
        # per-rank ingest partition and mapper allgather see the world
        from .parallel.multihost import (initialize_from_config,
                                         sync_config_across_processes)

        initialize_from_config(cfg)
        # GlobalSyncUpByMin analog (application.cpp:110-127, 190-198):
        # reconcile seeds/fractions, verify structural params match
        sync_config_across_processes(cfg)
    t0 = time.perf_counter()
    train = BinnedDataset.from_file(cfg.data, cfg)
    Log.info(
        f"Finish loading data, use {time.perf_counter() - t0:.6f} seconds"
    )
    objective = create_objective(cfg, train.metadata, train.num_data)
    booster = create_boosting(cfg, train, objective)

    valid_names: List[str] = []
    for path in cfg.valid_data:
        vset = BinnedDataset.from_file(path, cfg, reference=train)
        name = os.path.basename(path)
        booster.add_valid_dataset(vset, name)
        valid_names.append(name)

    if cfg.input_model:
        from .basic import Booster

        init = Booster(model_file=cfg.input_model)
        booster.merge_from(init._gbdt, prepend=True)
        Log.info(
            f"Continued training from {cfg.input_model} "
            f"({init._gbdt.num_trees} trees)"
        )

    # early-stopping state per (valid set, metric) (gbdt.cpp:336-347)
    best_score: Dict[tuple, float] = {}
    best_iter: Dict[tuple, int] = {}
    best_model_iter = 0

    # checkpoint resume (resilience/checkpoint.py): restore the EXACT
    # training state — trees, score buffers, RNGs, bagging mask, early-
    # stop bests — so the final model is bitwise-identical to an
    # uninterrupted run.  Validation (checksum, config fingerprint) is
    # loud; only "no checkpoint exists yet" silently starts fresh (a
    # preemption before the first snapshot loses nothing).
    from .resilience import checkpoint as ckpt

    start_iter = 0
    if cfg.resume:
        found = ckpt.load_latest_for(cfg)
        if found is not None:
            ck_path, payload = found
            start_iter = ckpt.restore_training_state(
                booster, payload, best_score, best_iter)
            Log.info(
                f"Resumed from {ck_path}: {booster.num_trees} trees, "
                f"continuing at iteration {start_iter + 1}")
        else:
            Log.warning(
                "resume=true but no checkpoint found in "
                f"{ckpt.checkpoint_dir(cfg)}; starting fresh")

    profiler_ctx = None
    if cfg.profile:
        # TPU-native replacement for the reference's per-iteration
        # wall-clock logging (application.cpp:228-235): a full
        # jax.profiler trace with per-kernel XLA cost breakdown
        import jax

        jax.profiler.start_trace(cfg.profile_dir)
        profiler_ctx = cfg.profile_dir

    # gang membership (resilience/gang.py): when a GangSupervisor
    # launched us, announce readiness just before the loop starts,
    # heartbeat every completed iteration, and stamp the rank topology
    # + barrier ids into every checkpoint manifest
    from .resilience.gang import beacon_from_env

    beacon = beacon_from_env()
    gang_block = None
    heartbeat = None
    if beacon is not None:
        gang_block = beacon.gang_block()
        heartbeat = beacon.heartbeat
        beacon.ready()
        if start_iter:
            beacon.heartbeat(start_iter)

    start = time.perf_counter()
    stop_iter = None
    try:
        with ckpt.CheckpointManager(cfg, booster, best_score, best_iter,
                                    gang=gang_block,
                                    heartbeat=heartbeat) as ckmgr:
            stop_iter = _train_loop(cfg, booster, valid_names, best_score,
                                    best_iter, start, start_iter, ckmgr)
    finally:
        if profiler_ctx is not None:
            import jax

            jax.profiler.stop_trace()
            Log.info(f"Saved profiler trace to {profiler_ctx}")
    # drain the non-finite guard's lazy counters BEFORE the model save
    # and manifest snapshot, so nonfinite_values_clipped is accurate in
    # both (short clip-policy runs would otherwise report 0)
    booster.finalize_guards()
    stop_early = stop_iter is not None
    if stop_early:
        best_model_iter = stop_iter + 1

    # slice counts iterations from the model start, so prepended
    # init-model trees are part of the budget (gbdt.cpp:589-592)
    num_iteration = (
        booster.num_init_iteration + best_model_iter if stop_early else -1
    )
    booster.save_model_to_file(cfg.output_model, num_iteration)
    Log.info(f"Finished training, saved model to {cfg.output_model}")
    _write_train_manifest(cfg, booster, time.perf_counter() - start,
                          profiler_ctx)
    return booster


def _write_train_manifest(cfg: Config, booster: GBDT, train_s: float,
                          profile_dir: Optional[str]) -> None:
    """RunManifest next to the saved model (``<output_model>.manifest
    .json``): every CLI training run leaves the same self-describing
    evidence as a bench run.  When ``profile=true`` captured a trace,
    the grow-loop phase breakdown is bucketed out of it; otherwise
    phases stay empty (host timers cannot see inside the jitted loop).
    Best-effort: a manifest failure must not fail a finished training
    run.

    Multi-rank runs (obs/dist.py): every rank publishes its telemetry
    snapshot into the exchange dir (``LGBM_TPU_RANK_OBS_DIR`` or a
    ``<output_model>.manifest.json.rankobs`` sibling), rank 0 gathers,
    merges, and writes the ONE manifest carrying a ``ranks[]`` section
    plus the merged counters/skew — non-zero ranks write no manifest
    (today's every-rank-writes-the-same-path race becomes the per-rank
    snapshot files instead)."""
    try:
        phases = {}
        if profile_dir:
            from .obs.device_time import phase_breakdown_from_trace

            phases = phase_breakdown_from_trace(profile_dir)
        ranks: list = []
        extra: dict = {}
        from .obs import dist
        from .resilience.gang import beacon_from_env

        beacon = beacon_from_env()
        if beacon is not None:
            # gang ranks are independent single-process jax worlds
            # (redundant data-parallel mode), so the >1-world exchange
            # below never triggers for them: publish the gang-stamped
            # snapshot under the formation rank so the supervisor's
            # train-fleet manifest carries every rank's telemetry
            # (resilience/gang.py write_train_fleet_artifact)
            dist.write_rank_snapshot(
                os.environ.get("LGBM_TPU_RANK_OBS_DIR") or
                dist.exchange_dir_for(manifest_path(cfg.output_model)),
                dist.rank_snapshot(rank=beacon.rank, world=beacon.world))

        if dist.process_count() > 1:
            xdir = dist.exchange_dir_for(manifest_path(cfg.output_model))
            dist.write_rank_snapshot(xdir)
            if dist.process_index() != 0:
                Log.info(
                    f"rank {dist.process_index()}: published telemetry "
                    f"snapshot to {xdir}; rank 0 writes the merged "
                    "manifest")
                telemetry.emit_if_json()
                return
            try:
                snaps = dist.gather_rank_snapshots(
                    xdir, dist.process_count(), timeout_s=120.0)
                ranks = dist.ranks_section(snaps)
                extra["distributed"] = dist.merged_manifest_extra(
                    dist.merge_snapshots(snaps))
            except Exception as e:  # noqa: BLE001 — degrade, don't lose
                # a peer that died before publishing must not cost the
                # finished run its manifest: fall back to rank 0's own
                # process-local view, with the failure ON the record
                Log.warning(
                    f"rank-snapshot gather failed ({type(e).__name__}: "
                    f"{str(e)[:200]}); writing a single-rank manifest")
                ranks = []
                extra["distributed"] = {
                    "gather_error": f"{type(e).__name__}: {str(e)[:300]}"}
        try:
            from .obs import memory as obs_memory

            mem_section = obs_memory.manifest_memory_section()
        except Exception:
            mem_section = {}
        manifest = RunManifest.collect(
            "cli.train", config=cfg,
            result={"num_trees": booster.num_trees,
                    "train_wall_s": round(train_s, 3),
                    "output_model": cfg.output_model},
            phases=phases,
            per_tree_reservoir="tree_dispatch_s",
            ranks=ranks,
            extra=extra,
            memory=mem_section,
        )
        path = manifest.write(manifest_path(cfg.output_model))
        Log.info(f"Wrote run manifest to {path}")
        if cfg.verbose >= 2:
            # structured telemetry tail (docs/observability.md): one
            # debug line a tool can parse out of the CLI log
            Log.debug("telemetry " + json.dumps(
                telemetry.get_telemetry().snapshot(), sort_keys=True))
        telemetry.emit_if_json()
    except Exception as e:
        Log.warning(f"run manifest write failed: {type(e).__name__}: {e}")


def _train_loop(cfg: Config, booster: GBDT, valid_names: List[str],
                best_score: Dict, best_iter: Dict, start: float,
                start_iter: int = 0, ckmgr=None):
    """The iteration loop (application.cpp:223-239); returns the best
    0-based iteration when early stopping fired, else None.

    Early stopping matches the reference (gbdt.cpp:336-349): it fires as
    soon as ANY (valid set, metric) pair has gone early_stopping_round
    iterations without improving, and the model is truncated to THAT
    pair's best iteration — not the max over all pairs.

    ``ckmgr.after_iteration`` runs once per completed iteration: it
    writes due snapshots and, after a SIGTERM/SIGINT, checkpoints and
    raises TrainingPreempted (the in-flight iteration always finishes
    first — a half-grown tree is not a resumable state)."""
    for it in range(start_iter, cfg.num_iterations):
        finished = booster.train_one_iter()
        Log.info(
            f"{time.perf_counter() - start:.6f} seconds elapsed, "
            f"finished iteration {it + 1}"
        )
        if cfg.metric_freq > 0 and (it + 1) % cfg.metric_freq == 0:
            rows = _output_metrics(booster, it + 1, valid_names, cfg.is_training_metric)
            if cfg.early_stopping_round > 0:
                for data_idx, mname, v, bigger in rows:
                    key = (data_idx, mname)
                    better = (
                        key not in best_score
                        or (v > best_score[key] if bigger else v < best_score[key])
                    )
                    if better:
                        best_score[key], best_iter[key] = v, it
                    elif it - best_iter[key] >= cfg.early_stopping_round:
                        Log.info(
                            f"Early stopping at iteration {it + 1}, the best "
                            f"iteration round is {best_iter[key] + 1}"
                        )
                        return best_iter[key]
        if finished:
            Log.info("Stopped training because there are no more leaves "
                     "that meet the split requirements.")
            break
        # AFTER the metric/early-stop bookkeeping: a checkpoint at
        # iteration k must carry k's best-score updates or a resumed
        # run's early stopping would diverge from the uninterrupted one
        if ckmgr is not None:
            ckmgr.after_iteration(it)
    # drain the lagged stop check when the loop ended by iteration count
    # (no-op unless LGBM_TPU_STOP_LAG is set)
    booster.finish_lagged_stop()
    return None


def run_train_many(cfg: Config, params: Dict[str, str]) -> None:
    """``task=train_many``: N independent models, one shared binned
    dataset, every boosting round advanced as ONE batched forest
    dispatch (engine.train_many; docs/forest_batching.md).  Model i
    trains with master seed ``seed + i`` — a seed-ensemble sweep — and
    saves to ``<output_model>.<i>``."""
    from .analysis.recompile import compile_counter
    from .basic import Dataset
    from .engine import train_many

    compile_counter()
    if cfg.num_models < 1:
        Log.fatal("num_models must be >= 1 for task=train_many")
    base = {
        k: v for k, v in params.items()
        if k not in ("task", "num_models", "data", "output_model")
    }
    plist = []
    for i in range(cfg.num_models):
        p = dict(base)
        p["seed"] = cfg.seed + i
        plist.append(p)
    t0 = time.perf_counter()
    ds = Dataset(cfg.data, params=dict(base))
    boosters = train_many(plist, ds, num_boost_round=cfg.num_iterations)
    Log.info(
        f"Finished training {len(boosters)} models in "
        f"{time.perf_counter() - t0:.6f} seconds"
    )
    for i, bst in enumerate(boosters):
        path = f"{cfg.output_model}.{i}"
        bst.save_model(path)
        Log.info(f"Saved model {i} ({bst.num_trees()} trees) to {path}")


def run_predict(cfg: Config) -> None:
    """Application::Predict (application.cpp:242-256)."""
    from .basic import Booster

    if not cfg.input_model:
        Log.fatal("input_model should not be empty for prediction task")
    booster = Booster(model_file=cfg.input_model)
    t0 = time.perf_counter()
    stats = Predictor(
        booster, cfg.is_predict_raw_score, cfg.is_predict_leaf_index
    ).predict_file(
        cfg.data, cfg.output_result, cfg.has_header,
        num_iteration=cfg.num_iteration_predict,
    )
    Log.info(
        f"Finish prediction, use {time.perf_counter() - t0:.6f} seconds; "
        f"saved to {cfg.output_result}"
    )
    if cfg.verbose >= 2:
        Log.debug("predict pipeline " + json.dumps(stats, sort_keys=True))


def run_serve(cfg: Config) -> int:
    """``task=serve``: the online micro-batched inference service
    (serving/server.py; docs/serving.md) — a persistent on-device
    ensemble behind shape-bucketed dispatch with checksum-verified
    hot-swap, serving until SIGINT/SIGTERM, then draining gracefully
    and exiting 75 (the supervisor-relaunch contract)."""
    from .serving import serve_from_config

    if not cfg.input_model:
        Log.fatal("input_model should not be empty for serve task")
    return int(serve_from_config(cfg, block=True) or 0)


def run_serve_fleet(cfg: Config) -> int:
    """``task=serve_fleet``: the replica supervisor
    (serving/supervisor.py; docs/serving.md) — N ``task=serve``
    subprocesses behind one round-robin front end, health-checked,
    restarted on crash/preemption with jittered backoff, scaled between
    ``serve_replicas`` and ``serve_max_replicas`` off the queue-depth
    gauge."""
    from .serving.supervisor import serve_fleet_from_config

    if not cfg.input_model:
        Log.fatal("input_model should not be empty for serve_fleet task")
    return int(serve_fleet_from_config(cfg) or 0)


def main(argv: Optional[List[str]] = None) -> int:
    """main.cpp:4-22."""
    # honor JAX_PLATFORMS before the first jax op: the axon TPU plugin
    # ignores the bare env var and dials the TPU tunnel anyway, so a
    # CPU-pinned CLI run (tests, CI) must pin via jax.config
    plat = os.environ.get("JAX_PLATFORMS", "")
    if plat and "axon" not in plat:
        import jax

        jax.config.update("jax_platforms", plat)
    argv = sys.argv[1:] if argv is None else list(argv)
    from .resilience.checkpoint import TrainingPreempted

    try:
        params = load_parameters(argv)
        cfg = Config.from_dict(params)
        Log.reset_log_level(cfg.verbose)
        if cfg.task == "train":
            run_train(cfg)
        elif cfg.task == "train_many":
            run_train_many(cfg, params)
        elif cfg.task in ("predict", "prediction", "test"):
            run_predict(cfg)
        elif cfg.task == "serve":
            return run_serve(cfg)
        elif cfg.task == "serve_fleet":
            return run_serve_fleet(cfg)
        elif cfg.task == "train_fleet":
            # elastic gang training (resilience/gang.py): supervise
            # train_ranks rank subprocesses with coordinated checkpoint
            # barriers and the restart/shrink recovery ladder.  The
            # supervisor imports no jax — only the children pay for a
            # device runtime.
            from .resilience.gang import train_fleet_from_config

            return train_fleet_from_config(cfg)
        else:
            Log.fatal(f"Unknown task: {cfg.task!r}")
    except TrainingPreempted as ex:
        # distinct exit status (sysexits EX_TEMPFAIL): the supervisor
        # re-launches with resume=true and loses nothing.  The flight
        # recorder dumps LAST so its tail is the preemption itself —
        # checkpoint path, iteration, signal — next to the model.
        print(f"Preempted:\n{ex}", file=sys.stderr)
        flightrec.record("preempted", iteration=ex.iteration,
                         checkpoint=ex.path)
        flightrec.dump(reason="preempted")
        return EXIT_PREEMPTED
    except Exception as ex:
        from .resilience.guards import NonFiniteError

        if isinstance(ex, NonFiniteError):
            # the guard already recorded its trip at the raise site;
            # the dump's tail names the escalation that killed the run
            flightrec.record("nonfinite_abort", error=str(ex)[:400])
            flightrec.dump(reason="nonfinite")
        print(f"Met Exceptions:\n{ex}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
