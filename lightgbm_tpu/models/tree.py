"""Decision tree model as a fixed-shape array pytree.

The reference's flat-array ``Tree`` (include/LightGBM/tree.h:18-198,
src/io/tree.cpp) is already array-oriented; we keep its exact layout —
internal nodes 0..L-2, leaves addressed as ``~leaf`` in child pointers
(tree.cpp:78-79) — but store every field as a fixed-size jax array so a
whole ensemble stacks into one pytree and prediction is a vectorized
gather loop instead of per-row pointer chasing (tree.h:226-238).

``num_leaves`` is the *used* leaf count; arrays are padded to the
``max_leaves`` training budget so shapes stay static under jit.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class Tree(NamedTuple):
    num_leaves: jax.Array  # scalar int32: used leaves (1 = stump)
    # internal nodes [max_leaves-1]
    split_feature: jax.Array  # inner feature index
    split_feature_real: jax.Array  # original column index (model IO)
    threshold_bin: jax.Array  # bin-space threshold
    threshold_real: jax.Array  # raw-value threshold (filled at finalize)
    decision_type: jax.Array  # 0 numerical (<=), 1 categorical (==)
    left_child: jax.Array  # node idx or ~leaf
    right_child: jax.Array
    split_gain: jax.Array
    internal_value: jax.Array
    internal_count: jax.Array
    # leaves [max_leaves]
    leaf_value: jax.Array
    leaf_count: jax.Array
    leaf_parent: jax.Array
    leaf_depth: jax.Array

    @property
    def max_leaves(self) -> int:
        return self.leaf_value.shape[-1]

    def shrink(self, rate) -> "Tree":
        """Tree::Shrinkage (tree.h:103-107): scale outputs in place."""
        return self._replace(
            leaf_value=self.leaf_value * rate,
            internal_value=self.internal_value * rate,
        )


def empty_tree(max_leaves: int) -> Tree:
    li = max_leaves - 1
    return Tree(
        num_leaves=jnp.int32(1),
        split_feature=jnp.full(li, -1, jnp.int32),
        split_feature_real=jnp.full(li, -1, jnp.int32),
        threshold_bin=jnp.zeros(li, jnp.int32),
        threshold_real=jnp.zeros(li, jnp.float32),
        decision_type=jnp.zeros(li, jnp.int32),
        left_child=jnp.zeros(li, jnp.int32),
        right_child=jnp.zeros(li, jnp.int32),
        split_gain=jnp.zeros(li, jnp.float32),
        internal_value=jnp.zeros(li, jnp.float32),
        internal_count=jnp.zeros(li, jnp.float32),
        leaf_value=jnp.zeros(max_leaves, jnp.float32),
        leaf_count=jnp.zeros(max_leaves, jnp.float32),
        leaf_parent=jnp.full(max_leaves, -1, jnp.int32),
        leaf_depth=jnp.zeros(max_leaves, jnp.int32),
    )


@jax.jit
def predict_leaf_binned(tree: Tree, X_bin: jax.Array) -> jax.Array:
    """Vectorized root-to-leaf walk over BINNED features -> leaf index.

    Equivalent to Tree::GetLeaf over bin iterators (tree.cpp:98-122).
    All rows walk in lockstep for at most max_leaves-1 steps; rows that
    reached a leaf stop updating (their node stays negative).
    """
    n = X_bin.shape[0]
    max_steps = tree.leaf_value.shape[-1] - 1

    # node >= 0: internal; node < 0: ~leaf
    start = jnp.where(tree.num_leaves > 1, 0, ~0)
    node = jnp.full((n,), start, jnp.int32)

    def body(state):
        node, _ = state
        active = node >= 0
        idx = jnp.maximum(node, 0)
        f = tree.split_feature[idx]
        t = tree.threshold_bin[idx]
        is_cat = tree.decision_type[idx] == 1
        v = jnp.take_along_axis(
            X_bin, f[:, None].astype(jnp.int32), axis=1
        )[:, 0].astype(jnp.int32)
        go_left = jnp.where(is_cat, v == t, v <= t)
        nxt = jnp.where(go_left, tree.left_child[idx], tree.right_child[idx])
        node = jnp.where(active, nxt, node)
        return node, jnp.any(node >= 0)

    def cond(state):
        return state[1]

    node, _ = jax.lax.while_loop(cond, body, (node, tree.num_leaves > 1))
    return ~node  # leaf index


@jax.jit
def predict_binned(tree: Tree, X_bin: jax.Array) -> jax.Array:
    """Per-row tree output on binned features."""
    leaves = predict_leaf_binned(tree, X_bin)
    return tree.leaf_value[leaves]


@jax.jit
def predict_leaf_raw(tree: Tree, X: jax.Array) -> jax.Array:
    """Root-to-leaf walk over RAW feature values (Tree::Predict,
    tree.h:226-238): numerical goes left when value <= threshold_real,
    categorical when int(value) == threshold_real."""
    n = X.shape[0]
    start = jnp.where(tree.num_leaves > 1, 0, ~0)
    node = jnp.full((n,), start, jnp.int32)

    def body(state):
        node, _ = state
        active = node >= 0
        idx = jnp.maximum(node, 0)
        f = tree.split_feature_real[idx]
        t = tree.threshold_real[idx]
        is_cat = tree.decision_type[idx] == 1
        v = jnp.take_along_axis(X, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
        go_left = jnp.where(is_cat, v.astype(jnp.int32) == t.astype(jnp.int32), v <= t)
        nxt = jnp.where(go_left, tree.left_child[idx], tree.right_child[idx])
        node = jnp.where(active, nxt, node)
        return node, jnp.any(node >= 0)

    node, _ = jax.lax.while_loop(lambda s: s[1], body, (node, tree.num_leaves > 1))
    return ~node


@jax.jit
def predict_raw(tree: Tree, X: jax.Array) -> jax.Array:
    return tree.leaf_value[predict_leaf_raw(tree, X)]


# ------------------------------------------------------------- ensembles
def pad_tree(tree: Tree, max_leaves: int) -> Tree:
    """Pad a tree's arrays to a larger leaf budget (no-op when equal) so
    trees from models with different ``num_leaves`` can stack."""
    cur = tree.max_leaves
    if cur == max_leaves:
        return tree
    dl = max_leaves - cur

    def pad(x, extra):
        return jnp.pad(x, (0, extra))

    return tree._replace(
        split_feature=pad(tree.split_feature, dl),
        split_feature_real=pad(tree.split_feature_real, dl),
        threshold_bin=pad(tree.threshold_bin, dl),
        threshold_real=pad(tree.threshold_real, dl),
        decision_type=pad(tree.decision_type, dl),
        left_child=pad(tree.left_child, dl),
        right_child=pad(tree.right_child, dl),
        split_gain=pad(tree.split_gain, dl),
        internal_value=pad(tree.internal_value, dl),
        internal_count=pad(tree.internal_count, dl),
        leaf_value=pad(tree.leaf_value, dl),
        leaf_count=pad(tree.leaf_count, dl),
        leaf_parent=pad(tree.leaf_parent, dl),
        leaf_depth=pad(tree.leaf_depth, dl),
    )


def stack_trees(trees) -> Tree:
    """Stack per-tree pytrees into one batched Tree (leading axis =
    tree) — the ensemble-as-one-pytree layout this module's docstring
    promises.  Replaces the reference's per-tree prediction loop
    (gbdt.cpp:388-426) with a single device program."""
    max_l = max(t.max_leaves for t in trees)
    trees = [pad_tree(t, max_l) for t in trees]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


@jax.jit
def ensemble_sum_raw(stacked: Tree, X: jax.Array) -> jax.Array:
    """Σ over trees of per-row outputs on RAW features.

    ``stacked`` has leading axes [n_iter, K]; returns [K, n].  A
    lax.scan over iterations (each step vmaps the K per-class trees)
    keeps memory at O(K * n) while compiling to ONE dispatch for the
    whole ensemble — vs. the reference's per-tree threaded row loop
    (predictor.hpp:82, tree.cpp:98-122)."""
    K, n = stacked.leaf_value.shape[1], X.shape[0]

    def step(acc, trees_k):
        out = jax.vmap(lambda t: predict_raw(t, X))(trees_k)
        return acc + out, None

    acc, _ = jax.lax.scan(step, jnp.zeros((K, n), jnp.float32), stacked)
    return acc


@jax.jit
def ensemble_sum_binned(stacked: Tree, X_bin: jax.Array) -> jax.Array:
    """Σ over trees on BINNED features; stacked axes [n_iter, K] -> [K, n]."""
    K, n = stacked.leaf_value.shape[1], X_bin.shape[0]

    def step(acc, trees_k):
        out = jax.vmap(lambda t: predict_binned(t, X_bin))(trees_k)
        return acc + out, None

    acc, _ = jax.lax.scan(step, jnp.zeros((K, n), jnp.float32), stacked)
    return acc


@jax.jit
def ensemble_leaves_raw(stacked: Tree, X: jax.Array) -> jax.Array:
    """Per-tree leaf indices on raw features: stacked leading axis [T]
    -> [T, n] (PredictLeafIndex, gbdt.cpp:647-655)."""
    return jax.vmap(lambda t: predict_leaf_raw(t, X))(stacked)


# ---------------------------------------------------------------- host side
def pack_threshold_bounds(bin_thresholds: list, real_feature_indices):
    """Host-side, once per dataset: the per-feature bin upper-bound lists
    as one padded [F, Bmax] f32 matrix (+inf replaced by float32 max,
    matching finalize_thresholds) plus the real-feature index vector —
    the operands of finalize_thresholds_device."""
    F = len(bin_thresholds)
    bmax = max((len(b) for b in bin_thresholds), default=1)
    mat = np.full((max(F, 1), max(bmax, 1)), np.finfo(np.float32).max,
                  np.float32)
    for f, bounds in enumerate(bin_thresholds):
        for b, v in enumerate(bounds):
            mat[f, b] = (
                np.float32(v) if np.isfinite(v)
                else np.finfo(np.float32).max
            )
        # clip semantics of the host path: bins past the list reuse the
        # last bound
        mat[f, len(bounds):] = mat[f, max(len(bounds) - 1, 0)]
    return (
        jnp.asarray(mat),
        jnp.asarray(np.asarray(real_feature_indices, np.int32)),
    )


def finalize_thresholds_device(tree: Tree, bounds_mat, real_feat) -> Tree:
    """finalize_thresholds as pure device ops — the host version's
    np.asarray/int() force a full device sync per built tree, which
    drains the dispatch pipeline (round-3 profiling; ~0.3 s/tree over
    the axon tunnel at 1M rows).  Same outputs: real thresholds from
    the bin upper bounds, real feature ids, -1/0 on non-split nodes."""
    sf = tree.split_feature
    is_split = sf >= 0
    fc = jnp.maximum(sf, 0)
    tb = jnp.clip(tree.threshold_bin, 0, bounds_mat.shape[1] - 1)
    tr = jnp.where(is_split, bounds_mat[fc, tb], 0.0).astype(jnp.float32)
    sfr = jnp.where(is_split, real_feat[fc], -1).astype(jnp.int32)
    return tree._replace(threshold_real=tr, split_feature_real=sfr)


def finalize_thresholds(tree: Tree, bin_thresholds: list, real_feature_indices: np.ndarray) -> Tree:
    """Fill threshold_real / split_feature_real from bin mappers (host-side,
    once per built tree).  For numerical features the real threshold is the
    bin's upper bound (matching how the reference stores thresholds for raw
    prediction, serial_tree_learner.cpp Split -> BinToValue); categorical
    thresholds are the category id."""
    sf = np.asarray(tree.split_feature)
    tb = np.asarray(tree.threshold_bin)
    nl = int(tree.num_leaves)
    tr = np.zeros_like(np.asarray(tree.threshold_real))
    sfr = np.full_like(sf, -1)
    for i in range(nl - 1):
        f = int(sf[i])
        if f >= 0:
            bounds = bin_thresholds[f]
            b = min(int(tb[i]), len(bounds) - 1)
            v = bounds[b]
            # +inf upper bound (last bin) can't be a numerical threshold;
            # it never appears because t <= num_bin-2 for numerical splits
            tr[i] = np.float32(v if np.isfinite(v) else np.finfo(np.float32).max)
            sfr[i] = real_feature_indices[f]
    return tree._replace(
        threshold_real=jnp.asarray(tr), split_feature_real=jnp.asarray(sfr)
    )
