"""DART: Dropouts meet Multiple Additive Regression Trees.

Re-expresses the reference DART (src/boosting/dart.hpp:17-196): per
iteration a random subset of past trees is dropped from the training
score before gradients are computed, the new tree is trained with
shrinkage lr/(1+k) (or lr/(lr+k) in xgboost_dart_mode), and the dropped
trees are renormalized to k/(k+1) (resp. k/(k+lr)) of their weight —
the exact Shrinkage(-1) / Shrinkage(1/(k+1)) / Shrinkage(-k) score
algebra of dart.hpp:144-183 collapsed into direct array updates.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..config import Config
from .gbdt import GBDT
from .tree import predict_binned


class DART(GBDT):
    name = "dart"

    def __init__(self, config: Config, train_set=None, objective=None):
        super().__init__(config, train_set, objective)
        self._drop_rng = np.random.RandomState(config.drop_seed)
        self.tree_weight: List[float] = []
        self.sum_weight = 0.0

    def _select_drops(self) -> List[int]:
        """DroppingTrees (dart.hpp:89-133)."""
        cfg = self.config
        if self._drop_rng.rand() < cfg.skip_drop:
            return []
        drop_rate = cfg.drop_rate
        drops = []
        if not cfg.uniform_drop:
            if self.sum_weight <= 0:
                return []
            inv_avg = len(self.tree_weight) / self.sum_weight
            if cfg.max_drop > 0:
                drop_rate = min(drop_rate, cfg.max_drop * inv_avg / self.sum_weight)
            for i in range(self.iter_):
                if self._drop_rng.rand() < drop_rate * self.tree_weight[i] * inv_avg:
                    drops.append(i)
        else:
            if cfg.max_drop > 0 and self.iter_ > 0:
                drop_rate = min(drop_rate, cfg.max_drop / float(self.iter_))
            for i in range(self.iter_):
                if self._drop_rng.rand() < drop_rate:
                    drops.append(i)
        return drops

    def train_one_iter(self, grad=None, hess=None) -> bool:
        cfg = self.config
        K = self.num_class
        drops = self._select_drops()
        k = float(len(drops))

        # subtract dropped trees from the training score (dart.hpp:117-123)
        for i in drops:
            for c in range(K):
                tree = self.models[i * K + c]
                self._scores = self._scores.at[c].add(
                    -predict_binned(tree, self._bins_T.T)
                )

        # shrinkage for the new tree (dart.hpp:124-132)
        if not cfg.xgboost_dart_mode:
            shrinkage = cfg.learning_rate / (1.0 + k)
        else:
            shrinkage = (
                cfg.learning_rate
                if not drops
                else cfg.learning_rate / (cfg.learning_rate + k)
            )
        saved_lr, self.learning_rate = self.learning_rate, shrinkage
        try:
            stop = super().train_one_iter(grad, hess)
        finally:
            self.learning_rate = saved_lr

        # renormalize dropped trees (Normalize, dart.hpp:144-183)
        # kept fraction of each dropped tree's weight; valid scores (which
        # still hold the full tree) are adjusted by (keep - 1)
        if not cfg.xgboost_dart_mode:
            keep = k / (k + 1.0)
        else:
            keep = k / (k + cfg.learning_rate)
        for i in drops:
            for c in range(K):
                idx = i * K + c
                tree = self.models[idx]
                delta = predict_binned(tree, self._bins_T.T)
                # train score gets the renormalized tree back
                self._scores = self._scores.at[c].add(keep * delta)
                # valid scores still hold the full tree; adjust by (keep-1)
                for vi in range(len(self.valid_sets)):
                    self._valid_scores[vi] = self._valid_scores[vi].at[c].add(
                        (keep - 1.0) * predict_binned(tree, self._valid_bins[vi])
                    )
                self.models[idx] = tree.shrink(keep)
            if not cfg.uniform_drop and self.tree_weight:
                denom = (k + 1.0) if not cfg.xgboost_dart_mode else (k + cfg.learning_rate)
                self.sum_weight -= self.tree_weight[i] * (1.0 / denom)
                self.tree_weight[i] *= keep
        if not cfg.uniform_drop:
            self.tree_weight.append(shrinkage)
            self.sum_weight += shrinkage
        return stop


def create_boosting(config: Config, train_set=None, objective=None) -> GBDT:
    """Boosting factory (src/boosting/boosting.cpp:30-66)."""
    if config.boosting_type == "dart":
        return DART(config, train_set, objective)
    return GBDT(config, train_set, objective)
