"""GBDT boosting driver.

TPU-native re-design of the reference GBDT (src/boosting/gbdt.{h,cpp}):
the binned matrix lives on device feature-major; each boosting iteration
computes objective gradients (jitted), optionally re-samples a bagging
mask, grows one tree per class with the serial (or parallel) learner,
applies shrinkage, and updates train/valid scores entirely on device —
train scores via the final leaf partition (no traversal, mirroring
score_updater.hpp:59-61), valid scores via vectorized traversal of the
bin-aligned valid matrix.

Model save/load uses the reference's text format byte-for-byte
(gbdt.cpp:479-592, tree.cpp:124-151) so models interoperate.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..compat import enable_x64
from ..config import Config
from ..io.dataset import BinnedDataset
from ..obs import memory as obs_memory
from ..obs import telemetry
from ..resilience import faults
from ..resilience.atomic import atomic_write
from ..obs.device_time import phase_scope
from ..learners.serial import (
    TreeLearnerParams, check_count_envelope, grow_tree)
from ..metrics import Metric, create_metrics
from ..objectives import ObjectiveFunction, create_objective
from .tree import (
    Tree,
    empty_tree,
    finalize_thresholds,
    finalize_thresholds_device,
    ensemble_leaves_raw,
    ensemble_sum_binned,
    ensemble_sum_raw,
    pack_threshold_bounds,
    predict_binned,
    predict_raw,
    stack_trees,
    predict_leaf_raw,
)

# Batch-prediction backend (read ONCE at import, like the kernel knobs):
# "auto" = the matmul path (ops/predict_matmul.py) on TPU, the
# vectorized walk elsewhere (the dense path-incidence matmuls would run
# at scalar speed on the CPU fallback); "1"/"0" force.
_PREDICT_MM = os.environ.get("LGBM_TPU_PREDICT_MATMUL", "auto")
# rows per matmul-predict dispatch: bounds the [rows, L]-shaped dense
# intermediates (~2.5KB/row/tree-step at L=255) well inside HBM
_ROW_CHUNK = int(os.environ.get("LGBM_TPU_PREDICT_ROW_CHUNK", str(1 << 20)))

# forest_batching="auto" row ceiling: the explicit batched grow loop
# (learners/forest.py) does O(n) work per split per lane while the
# sequential windows tier down, so its win inverts as n grows — the
# CPU-container sweep (docs/forest_batching.md) crosses between 2k rows
# (1.45x faster) and 4k (0.64x).  Chip re-evaluation rides
# forest_batching="on" or this env knob.
_FOREST_AUTO_MAX_ROWS = int(os.environ.get("LGBM_TPU_FOREST_MAX_ROWS",
                                           "2048"))


def _use_matmul_predict() -> bool:
    if _PREDICT_MM == "auto":
        return jax.default_backend() == "tpu"
    return _PREDICT_MM != "0"


def raw_score_output(out: np.ndarray, num_class: int) -> np.ndarray:
    """[K, n] raw scores -> the public raw-score shape ([n] or [n, K])."""
    return out[0] if num_class == 1 else out.T


def transform_scores(out: np.ndarray, num_class: int, sigmoid: float,
                     objective_name: str) -> np.ndarray:
    """GBDT::Predict's host-side f64 output transform (gbdt.cpp:
    631-645), factored out so the serving engine applies bitwise the
    SAME transform as the offline predictor (serving/engine.py)."""
    if sigmoid > 0 and num_class == 1 and objective_name == "binary":
        return 1.0 / (1.0 + np.exp(-2.0 * sigmoid * out[0]))
    if num_class > 1:
        z = out - out.max(axis=0, keepdims=True)
        e = np.exp(z)
        return (e / e.sum(axis=0, keepdims=True)).T
    return out[0]


@functools.partial(jax.jit, donate_argnums=(1,))
@phase_scope("leaf-update")
def _post_grow_step(tree, scores, k, leaf_id, rate, bounds_mat, real_feat):
    """Shrinkage + score update + device-side threshold finalization in
    one dispatch (gbdt.cpp:229-247's post-train steps)."""
    tree = tree.shrink(rate)
    scores = scores.at[k].add(tree.leaf_value[leaf_id])
    tree = finalize_thresholds_device(tree, bounds_mat, real_feat)
    return tree, scores


class GBDT:
    """Gradient Boosting Decision Trees (gbdt.h:17)."""

    name = "gbdt"

    def __init__(
        self,
        config: Config,
        train_set: Optional[BinnedDataset] = None,
        objective: Optional[ObjectiveFunction] = None,
    ):
        from .. import _enable_persistent_compile_cache

        _enable_persistent_compile_cache()  # lazy, TPU-gated, once
        self.config = config
        self.num_class = int(config.num_class)
        self.learning_rate = float(config.learning_rate)
        self.max_leaves = config.num_leaves_
        self.models: List[Tree] = []  # flat, iter-major: tree i*K+k
        self.iter_ = 0
        self.num_init_iteration = 0
        self.label_idx = 0
        self.max_feature_idx = -1
        self.feature_names: List[str] = []
        self.sigmoid = float(config.sigmoid)
        self.objective = objective
        self.train_set: Optional[BinnedDataset] = None
        self.valid_sets: List[BinnedDataset] = []
        self.valid_names: List[str] = []
        self.train_metrics: List[Metric] = []
        self.valid_metrics: List[List[Metric]] = []
        self.best_iteration = -1
        self._bag_rng = np.random.RandomState(config.bagging_seed)
        # lagged stop check (see train_one_iter); 0 = eager reference
        # semantics
        self._stop_lag = int(os.environ.get("LGBM_TPU_STOP_LAG", "0"))
        self._pending_stop: List[jax.Array] = []
        self._feat_rng = np.random.RandomState(config.feature_fraction_seed)
        # reference-parity double accumulation for histograms
        # (include/LightGBM/bin.h:21-22); see Config.hist_dtype.  f64 is
        # enabled per-trace via the jax.enable_x64 context in
        # train_one_iter, never by flipping the process-global flag.
        self._use_f64_hist = config.hist_dtype == "float64"
        # non-finite gradient/leaf guard (resilience/guards.py); None
        # under the default policy "off" — zero cost, zero behavior drift
        if getattr(config, "nonfinite_policy", "off") != "off":
            from ..resilience.guards import make_guard

            self._nf_guard = make_guard(config.nonfinite_policy)
        else:
            self._nf_guard = None
        self._model_version = 0
        if train_set is not None:
            self.reset_training_data(train_set, objective)

    # ------------------------------------------------------------------ setup
    def reset_training_data(
        self, train_set: BinnedDataset, objective: Optional[ObjectiveFunction]
    ) -> None:
        """GBDT::ResetTrainingData (gbdt.cpp:49-122)."""
        self.train_set = train_set
        self.objective = objective
        n = train_set.num_data
        check_count_envelope(n, self.config.hist_dtype)
        self.num_data = n
        self.max_feature_idx = train_set.num_total_features - 1
        self.feature_names = list(train_set.feature_names)
        if self.objective is not None and self.objective.name == "binary":
            self.sigmoid = self.objective.sigmoid

        # device copy cached ON the dataset: cv folds / train_many models
        # constructed over the same BinnedDataset share one upload
        self._bins_T = train_set.dense_bins_T_device()
        self._num_bins = max(int(train_set.max_num_bin), 2)
        self._nbpf = jnp.asarray(train_set.num_bins_per_feature)
        self._is_cat = jnp.asarray(train_set.is_categorical)
        self._learner_params = TreeLearnerParams.from_config(self.config)
        self._real_feat = train_set.real_feature_indices
        self._bin_thresholds = train_set.bin_thresholds_real()
        self._bounds_mat, self._real_feat_dev = pack_threshold_bounds(
            self._bin_thresholds, self._real_feat)
        self._grow = self._create_tree_learner()

        K = self.num_class
        init = train_set.metadata.init_score
        if init is not None:
            scores = np.asarray(init, np.float32).reshape(K, n) if K > 1 else np.asarray(
                init, np.float32
            ).reshape(1, n)
        else:
            scores = np.zeros((K, n), np.float32)
        self._scores = jnp.asarray(scores)
        self._bag_mask = jnp.ones(n, jnp.float32)
        self._bag_cnt = n
        # memory-census owner tags (obs/memory.py).  Getters resolve
        # the CURRENT attributes at census time, so the per-iteration
        # reassignment of _scores stays covered; the registry keeps
        # only a weakref to this booster, so dropping the booster
        # frees everything (the leak-detector contract).
        for tok in (getattr(self, "_mem_tokens", None) or ()):
            obs_memory.unregister_owner(tok)
        self._mem_tokens = (
            obs_memory.register_owner(
                "dataset", self,
                lambda b: (b._bins_T, b._nbpf, b._is_cat,
                           b._bounds_mat, b._real_feat_dev)),
            obs_memory.register_owner(
                "scores", self,
                lambda b: (b._scores, b._bag_mask,
                           getattr(b, "_valid_scores", []),
                           getattr(b, "_valid_bins", []))),
        )
        self.train_metrics = create_metrics(
            self.config, train_set.metadata, n
        )
        obs_memory.phase_boundary("binning")
        # rollback support: keep per-iteration train score deltas off-device?
        # cheaper: recompute on rollback from stored trees (rare path).

    def _create_tree_learner(self):
        """TreeLearner::CreateTreeLearner (tree_learner.cpp:8-20): map
        config.tree_learner to a grow callable.  All parallel variants run
        SPMD over the local device mesh — the reference's `num_machines`
        world (network.cpp:20-38) is the mesh's row axis."""
        tl = self.config.tree_learner
        if (self.config.tree_growth == "hybrid"
                and tl in ("feature", "voting", "grid")
                and len(jax.devices()) > 1 and jax.process_count() == 1):
            from ..log import Log

            Log.warning(
                "tree_growth=hybrid runs on serial and data-parallel "
                f"learners; tree_learner={tl} uses leaf-wise growth "
                "(same accuracy, no fused level phase)"
            )
        if jax.process_count() > 1:
            # true multi-host world (Network::Init analog already ran,
            # parallel/multihost.py): rows are the per-process ingest
            # partition, collectives cross hosts over the global mesh.
            # This check precedes the serial branch — a "serial" learner
            # on per-process partitions would silently train on a
            # fraction of the data.
            from ..log import Log
            from ..parallel import data_mesh
            from ..parallel.multihost import make_multihost_data_parallel_grower

            if tl != "data":
                Log.warning(
                    f"tree_learner={tl} runs data-parallel across "
                    "processes (feature/voting sharding stays intra-host)"
                )
            from ..resilience.retry import collective_deadline_s

            return make_multihost_data_parallel_grower(
                data_mesh(),  # all global devices
                num_bins=self._num_bins,
                max_leaves=self.max_leaves,
                growth=self.config.tree_growth,
                sorted_hist=self._use_pallas_hist(),
                hist_pool=self._hist_pool_slots(),
                # the config's collective deadline guards the sentinel's
                # per-iteration allgather too (a preempted peer must
                # fail the world loudly, not hang it)
                collective_deadline=collective_deadline_s(self.config),
            )
        if tl == "serial" or len(jax.devices()) == 1:
            if self.config.tree_growth == "depthwise":
                from ..learners.depthwise import grow_tree_depthwise

                return functools.partial(
                    grow_tree_depthwise,
                    num_bins=self._num_bins,
                    max_leaves=self.max_leaves,
                    hist_fn=self._depthwise_hist_fn(),
                )
            if self.config.tree_growth == "hybrid":
                from ..learners.hybrid import grow_tree_hybrid

                return functools.partial(
                    grow_tree_hybrid,
                    num_bins=self._num_bins,
                    max_leaves=self.max_leaves,
                    hist_fn=self._leafwise_hist_fn(),
                    level_hist_fn=self._depthwise_hist_fn(),
                )
            return functools.partial(
                grow_tree,
                num_bins=self._num_bins,
                max_leaves=self.max_leaves,
                hist_fn=self._leafwise_hist_fn(),
                hist_pool=self._hist_pool_slots(),
                hist_fn_raw=self._leafwise_hist_fn_raw(),
            )
        from ..parallel import (
            data_mesh,
            make_data_parallel_grower,
            make_feature_parallel_grower,
            make_voting_parallel_grower,
        )

        nd = len(jax.devices())
        if self.config.num_machines > 1:
            nd = min(nd, self.config.num_machines)
        mesh = data_mesh(num_devices=nd)
        if tl == "feature":
            return make_feature_parallel_grower(
                mesh, num_bins=self._num_bins, max_leaves=self.max_leaves,
                sorted_hist=self._use_pallas_hist(),
                hist_pool=self._hist_pool_slots(),
            )
        if tl == "grid":
            from ..log import Log
            from ..parallel import grid_mesh, make_grid_parallel_grower

            c = max(1, min(int(self.config.grid_feature_shards), nd))
            r = max(1, nd // c)
            if r * c < nd:
                Log.warning(
                    f"grid mesh ({r}x{c}) uses {r * c} of {nd} devices; "
                    "pick grid_feature_shards dividing the device count"
                )
            return make_grid_parallel_grower(
                grid_mesh((r, c)), num_bins=self._num_bins,
                max_leaves=self.max_leaves,
                sorted_hist=self._use_pallas_hist(),
                hist_pool=self._hist_pool_slots(),
            )
        if tl == "voting":
            return make_voting_parallel_grower(
                mesh,
                num_bins=self._num_bins,
                max_leaves=self.max_leaves,
                top_k=self.config.top_k,
                sorted_hist=self._use_pallas_hist(),
                hist_pool=self._hist_pool_slots(),
            )
        return make_data_parallel_grower(
            mesh,
            num_bins=self._num_bins,
            max_leaves=self.max_leaves,
            growth=self.config.tree_growth,
            sorted_hist=self._use_pallas_hist(),
            hist_pool=self._hist_pool_slots(),
        )

    def _hist_pool_slots(self) -> int:
        """config.histogram_pool_size (MB) -> LRU slot count, the
        reference's sizing rule (serial_tree_learner.cpp:25-37): 0 means
        keep all num_leaves histograms resident.  Applies to every
        leaf-wise learner (serial and all mesh variants); depth-wise
        growth builds transient per-level histograms instead of a
        resident per-leaf buffer, so the bound is moot there."""
        mb = float(self.config.histogram_pool_size)
        if mb <= 0:
            return 0
        if self.config.tree_growth in ("depthwise", "hybrid"):
            from ..log import Log

            Log.warning(
                f"histogram_pool_size is ignored for tree_growth="
                f"{self.config.tree_growth} (depthwise levels build "
                "transient histograms; the hybrid resume runs unpooled)"
            )
            return 0
        itemsize = 8 if self._use_f64_hist else 4
        F = int(self._bins_T.shape[0])
        if self._leafwise_hist_fn_raw() is not None:
            # raw-layout residency: each slot is the PADDED kernel-native
            # [Fp, 4, Bp] buffer, not F*num_bins*3.  (Parallel learners
            # keep the canonical layout; sizing them by the larger raw
            # slot just errs on the safe side of the MB bound.)
            from ..ops.pallas_histogram import FGROUP, _pad_pow

            Fp = ((F + FGROUP - 1) // FGROUP) * FGROUP
            per_leaf = Fp * 4 * _pad_pow(self._num_bins) * itemsize
        else:
            per_leaf = F * self._num_bins * 3 * itemsize
        slots = int(mb * 1024 * 1024 / max(per_leaf, 1))
        return max(2, min(slots, self.max_leaves))

    def _use_matmul_hist(self) -> bool:
        impl = self.config.hist_impl
        return impl == "matmul" or (
            impl == "auto" and jax.default_backend() == "tpu"
        )

    def _use_pallas_hist(self) -> bool:
        """ONE eligibility rule for the f32 Pallas MXU histogram kernels:
        requested (or auto-on-TPU) and not overridden by the f64
        reference-parity accumulation mode."""
        return self._use_matmul_hist() and not self._use_f64_hist

    def _leafwise_hist_fn(self):
        """Histogram implementation for leaf-wise growth: the single-leaf
        MXU matmul kernel on TPU (the gathered smaller-child buffer is
        one leaf's rows, so no sort is needed), segment_sum elsewhere.
        The f64 reference-parity accumulation keeps segment_sum — the
        Pallas kernel is f32."""
        if self._use_pallas_hist():
            from ..ops.histogram import select_single_hist_fn

            return select_single_hist_fn(self._num_bins, True)
        return None  # grower's default segment_sum path

    def _leafwise_hist_fn_raw(self):
        """Raw-layout ([Fp, 4, Bp]) single-leaf kernel for the serial
        leaf-wise opt path: the split step then never leaves the
        histogram kernel's native layout (grow_tree ``opt`` mode).
        v1-variant TPU only; LGBM_TPU_OPT_HISTS=0 disables."""
        from ..ops.pallas_histogram import _kernel_variant

        if (
            self._use_pallas_hist()
            and jax.default_backend() == "tpu"
            and _kernel_variant() == "v1"
            and os.environ.get("LGBM_TPU_OPT_HISTS", "1") != "0"
        ):
            from ..ops.pallas_histogram import make_single_hist_fn_raw

            return make_single_hist_fn_raw(
                self._num_bins,
                chunk=int(os.environ.get("LGBM_TPU_HIST_CHUNK", "512")),
            )
        return None

    def _depthwise_hist_fn(self):
        """Histogram implementation for depthwise growth (config.hist_impl):
        the leaf-sorted MXU matmul kernel on TPU, segment_sum elsewhere.
        f64 reference-parity accumulation keeps segment_sum — the Pallas
        kernels are f32 (same gate as _leafwise_hist_fn).

        Sparse-ingested datasets below Config.sparse_hist_density use
        the O(nnz) CSR histogram (ops/sparse_hist.py) instead of any
        O(n*F) dense pass — the reference's OrderedSparseBin role
        (ordered_sparse_bin.hpp:79-92)."""
        ds = self.train_set
        if (ds is not None and ds.is_sparse
                and self.config.hist_dtype != "float64"):
            nnz = ds.X_bin.nnz
            density = nnz / max(1, ds.num_data * ds.num_features)
            if density <= self.config.sparse_hist_density:
                from ..ops.sparse_hist import make_sparse_hist_fn

                return make_sparse_hist_fn(ds.X_bin, self._num_bins)
        if self._use_pallas_hist():
            from ..ops.pallas_histogram import make_sorted_hist_fn

            return make_sorted_hist_fn(self._num_bins)
        return None  # grower's default segment_sum path

    def add_valid_dataset(self, valid_set: BinnedDataset, name: str) -> None:
        """GBDT::AddValidDataset (gbdt.cpp:124-140)."""
        assert self.train_set is not None and self.train_set.check_align(valid_set)
        self.valid_sets.append(valid_set)
        self.valid_names.append(name)
        self.valid_metrics.append(
            create_metrics(self.config, valid_set.metadata, valid_set.num_data)
        )
        K = self.num_class
        vb = jnp.asarray(valid_set.dense_bins())
        init = valid_set.metadata.init_score
        if init is not None:
            vs = np.asarray(init, np.float32).reshape(K, valid_set.num_data)
        else:
            vs = np.zeros((K, valid_set.num_data), np.float32)
        if not hasattr(self, "_valid_bins"):
            self._valid_bins, self._valid_scores = [], []
        self._valid_bins.append(vb)
        self._valid_scores.append(jnp.asarray(vs))
        # replay existing model onto the new valid set (continued training)
        if self.models:
            n_iter = len(self.models) // K
            stacked = self._stacked_models(n_iter * K, grouped=True)
            step = self._iter_chunk(valid_set.num_data)
            acc = self._valid_scores[-1]
            for lo in range(0, n_iter, step):  # watchdog bound, see
                # _iter_chunk
                part = jax.tree.map(lambda a: a[lo:lo + step], stacked)
                acc = acc + ensemble_sum_binned(part, vb)
            self._valid_scores[-1] = acc

    # ---------------------------------------------------------------- bagging
    def set_base_row_mask(self, mask) -> None:
        """Persistent row mask ANDed under any bagging draw — how cv()
        trains each fold on the SHARED full binned matrix: the fold's
        held-out rows never enter histograms/counts, so the grown trees
        are bitwise the subset-trained ones (same nonzero contributions
        in the same row order; engine.cv, docs/forest_batching.md).

        Requires the canonical serial leaf-wise grower: the child-choice
        criterion switches to masked counts (choice_by_mask_counts in
        learners/serial.py explains why positional counts would break
        the subset-parity contract)."""
        if getattr(self._grow, "func", None) is not grow_tree:
            raise ValueError(
                "set_base_row_mask requires the serial leaf-wise tree "
                "learner (canonical path)"
            )
        m = jnp.asarray(mask, jnp.float32)
        self._base_row_mask = m
        self._bag_mask = self._bag_mask * m
        self._bag_cnt = int(jnp.sum(self._bag_mask))
        self._grow = functools.partial(
            self._grow, choice_by_mask_counts=True)

    def _update_bagging(self) -> None:
        """GBDT::Bagging (gbdt.cpp:157-208): every bagging_freq iterations
        draw floor(n * bagging_fraction) rows (query-granular for ranking)."""
        cfg = self.config
        if cfg.bagging_fraction >= 1.0 or cfg.bagging_freq <= 0:
            return
        if self.iter_ % cfg.bagging_freq != 0:
            return
        n = self.num_data
        meta = self.train_set.metadata
        if meta.query_boundaries is not None:
            qb = np.asarray(meta.query_boundaries)
            nq = len(qb) - 1
            take = int(nq * cfg.bagging_fraction)
            qs = self._bag_rng.choice(nq, size=take, replace=False)
            mask = np.zeros(n, np.float32)
            for q in qs:
                mask[qb[q] : qb[q + 1]] = 1.0
        else:
            take = int(n * cfg.bagging_fraction)
            idx = self._bag_rng.choice(n, size=take, replace=False)
            mask = np.zeros(n, np.float32)
            mask[idx] = 1.0
        base = getattr(self, "_base_row_mask", None)
        if base is not None:
            mask = mask * np.asarray(base)
        self._bag_mask = jnp.asarray(mask)
        self._bag_cnt = int(mask.sum())

    def _sample_features(self) -> jax.Array:
        """Per-tree feature_fraction sample (serial_tree_learner.cpp:160-165)."""
        F = self.train_set.num_features
        frac = float(self.config.feature_fraction)
        if frac >= 1.0:
            return jnp.ones(F, bool)
        take = max(1, int(F * frac))
        idx = self._feat_rng.choice(F, size=take, replace=False)
        mask = np.zeros(F, bool)
        mask[idx] = True
        return jnp.asarray(mask)

    # ------------------------------------------------------------------ train
    def train_one_iter(
        self,
        grad: Optional[np.ndarray] = None,
        hess: Optional[np.ndarray] = None,
    ) -> bool:
        """One boosting iteration (gbdt.cpp:217-252).  Returns True when no
        tree could be grown (training should stop).

        Telemetry: counts the iteration and records its host wall into
        the ``tree_dispatch_s`` reservoir.  That is DISPATCH time —
        under async dispatch the call returns before the chip finishes,
        so per-tree p50/p99 from this reservoir measure how fast the
        host can feed the device, not device time (the distinction the
        jaxlint ``wallclock-without-sync`` rule exists to protect).
        Synced per-tree times come from the bench harness's own timed
        loop; device phase attribution from obs.device_time traces."""
        t0 = time.perf_counter()
        try:
            # chaos hook (LGBM_TPU_FAULT=oom_dispatch): fake
            # RESOURCE_EXHAUSTED through the same classifier a real one hits
            faults.maybe_oom_dispatch("train")
            return self._train_one_iter_impl(grad, hess)
        except Exception as e:
            # OOM post-mortem (obs/memory.py): flight-recorder dump with
            # the last census + the analytic model's prediction for this
            # shape; non-OOM errors pass through untouched
            obs_memory.classify_dispatch_error(
                e, "train.dispatch", shape=self._memmodel_params(),
                predict_params=self._memmodel_params())
            raise
        finally:
            telemetry.count("train_iters")
            telemetry.record_value(
                "tree_dispatch_s", time.perf_counter() - t0)
            obs_memory.phase_boundary("train")

    def _memmodel_params(self) -> Optional[dict]:
        """This booster's shape in obs/memmodel.predict vocabulary
        (attached to OOM post-mortems so the dump carries the expected
        footprint beside the measured census)."""
        if getattr(self, "_bins_T", None) is None:
            return None
        try:
            return {
                "rows": int(self.num_data),
                "features": int(self._bins_T.shape[0]),
                "bins": int(self._num_bins),
                "leaves": int(self.max_leaves),
                "num_class": int(self.num_class),
                "world": int(jax.process_count()),
                "routing": ("order" if self.config.tree_learner == "serial"
                            else "prefix"),
                "hist_prec": ("float64" if self._use_f64_hist
                              else "float32"),
            }
        except Exception:
            return None

    # -------------------------------------------- forest-batched dispatch
    def _forest_eligible(self) -> bool:
        """May this booster's trees grow through the batched forest path
        (learners/forest.py)?  Mirrors the canonical serial branch of
        _create_tree_learner: single-process leaf-wise growth with the
        segment-sum histograms and jnp search — the op set the explicit
        batched loop reproduces bitwise.  Kernel paths (Pallas hist /
        raw-layout opt mode), f64 accumulation, pooled histograms, and
        parallel learners fall back to the sequential grower; whether
        vmap pessimizes those kernels is a tools/kernel_ab.py question
        for the next chip window (docs/forest_batching.md)."""
        cfg = self.config
        knob = getattr(cfg, "forest_batching", "auto")
        if knob == "off":
            return False
        if not (cfg.tree_learner == "serial" or len(jax.devices()) == 1):
            return False
        if jax.process_count() > 1 or cfg.tree_growth != "leafwise":
            return False
        if self._use_f64_hist or self._hist_pool_slots():
            return False
        if (self._leafwise_hist_fn() is not None
                or self._leafwise_hist_fn_raw() is not None):
            return False
        if knob == "on":
            return True
        # auto: the batched loop's per-split work is O(n) per lane while
        # the sequential windows tier down — measured CPU crossover sits
        # between 2k rows (1.45x) and 4k rows (0.64x); docs carry the
        # sweep.  forest_batching="on" overrides for chip re-evaluation.
        return self.num_data <= _FOREST_AUTO_MAX_ROWS

    def _grow_forest_batched(self, grads, hesses, bag_masks, fmasks,
                             params_lanes):
        """One batched dispatch growing ``B = len(fmasks)`` trees.
        Operands are [B, ...] stacks (grad/hess/bag per lane, feature
        mask per lane, TreeLearnerParams with [B] fields).  Returns the
        batched Tree pytree + leaf_id[B, n]."""
        from ..learners import forest

        gf = forest.make_grow_forest(
            self._num_bins, self.max_leaves,
            choice_by_mask_counts=(
                getattr(self, "_base_row_mask", None) is not None),
        )
        trees, leaf_ids = gf(
            self._bins_T, grads, hesses, bag_masks, fmasks,
            self._nbpf, self._is_cat, params_lanes,
        )
        telemetry.count("forest_dispatches")
        telemetry.count("forest_batched_trees", int(leaf_ids.shape[0]))
        return trees, leaf_ids

    def _forest_begin_iter(self, grad=None, hess=None):
        """First half of a boosting iteration, up to (not including) the
        tree growth: lagged-stop drain, objective gradients, non-finite
        guard, bagging, per-class feature samples.  Returns "stop",
        "skip", or (grad[K, n], hess[K, n], fmasks, nf_snap).  Factored
        out of _train_one_iter_impl so train_forest_round can stack the
        grow work of MANY boosters into one dispatch between identical
        begin/finish halves."""
        K = self.num_class
        # lagged stop check, consume side: BEFORE growing anything this
        # iteration, materialize parked num_leaves values that are now
        # ``lag`` iterations old (computed long ago — the int() does not
        # stall the pipeline).  On terminal detection, roll back every
        # iteration AFTER the terminal stump — the popped entries map
        # one-to-one onto the trees grown after it and nothing from the
        # current call has run yet — leaving the model IDENTICAL to the
        # eager check's (gbdt.cpp:217-252 stops right at the stump).
        while self._pending_stop and len(self._pending_stop) >= max(
            self._stop_lag, 1
        ):
            old = self._pending_stop.pop(0)
            telemetry.host_sync()  # lagged, so ~free — but still a sync
            if int(old) <= 1:
                for _ in range(len(self._pending_stop)):
                    self.rollback_one_iter()
                self._pending_stop.clear()
                return "stop"
        if grad is None or hess is None:
            scores = self._scores if K > 1 else self._scores[0]
            grad, hess = self.objective.get_gradients(scores)
            if K == 1:
                grad, hess = grad[None, :], hess[None, :]
        else:
            grad = jnp.asarray(grad, jnp.float32).reshape(K, self.num_data)
            hess = jnp.asarray(hess, jnp.float32).reshape(K, self.num_data)

        # chaos hook (LGBM_TPU_FAULT=nan_grads:J): deterministic gradient
        # poisoning, so the guard below is exercised by tests, not trusted
        grad, hess = faults.poison_grads(grad, hess, self.iter_)
        nf_snap = None
        if self._nf_guard is not None:
            if self._nf_guard.policy == "raise":
                # pre-iteration snapshot: the only rollback that works
                # once NaN reaches the score buffers is an exact restore
                # (see NonFiniteGuard.raise_if_poisoned).  One async
                # device copy of the score buffers per iteration — the
                # opt-in policy's cost, never the default path's.
                nf_snap = self.snapshot_state()
            grad, hess, skip_iter = self._nf_guard.check_gradients(grad, hess)
            if skip_iter:
                return "skip"

        self._update_bagging()
        # per-class feature samples drawn in k-order BEFORE any growth:
        # same _feat_rng consumption sequence as the sequential k-loop
        # (nothing else draws between them), so stacked == loop trees
        fmasks = [self._sample_features() for _ in range(K)]
        return grad, hess, fmasks, nf_snap

    def _forest_finish_tree(self, k: int, tree, leaf_id) -> bool:
        """Second half, per grown tree: lagged-stop bookkeeping,
        non-finite leaf guard, shrinkage + score/threshold dispatch,
        valid-score updates, model append.  Returns could_split."""
        K = self.num_class
        if self._stop_lag <= 0 or K != 1:
            could_split = int(tree.num_leaves) > 1
        else:
            # lagged stop check (LGBM_TPU_STOP_LAG): int(num_leaves)
            # every iteration blocks the host on the WHOLE tree
            # computation, draining the dispatch pipeline and
            # exposing the axon-tunnel RTT (~0.3 s/tree measured at
            # 1M rows).  Park the device scalar and start its host
            # copy; the NEXT call materializes values that are
            # ``lag`` iterations old (see _forest_begin_iter) and
            # rolls back to the exact eager-mode state on terminal
            # detection.
            nl = tree.num_leaves
            try:
                nl.copy_to_host_async()
            except Exception:
                pass
            self._pending_stop.append(nl)
            could_split = True
        if self._nf_guard is not None:
            # leaf-output guard (clip/count); never drops a tree —
            # the models list must stay iter-major K-aligned
            tree, _ = self._nf_guard.check_tree(tree)
        # shrink + score apply + threshold finalization as ONE
        # dispatch (each eager jnp op is its own round trip over the
        # axon tunnel; the host-side finalize_thresholds even forced
        # a full device sync per tree)
        tree, self._scores = _post_grow_step(
            tree, self._scores, jnp.int32(k),
            leaf_id, jnp.float32(self.learning_rate),
            self._bounds_mat, self._real_feat_dev,
        )
        for vi in range(len(self.valid_sets)):
            self._valid_scores[vi] = self._valid_scores[vi].at[k].add(
                predict_binned(tree, self._valid_bins[vi])
            )
        self.models.append(tree)
        return could_split

    def _forest_finish_iter(self, grown, nf_snap) -> bool:
        """Close an iteration whose K trees were grown elsewhere (the
        batched dispatch).  ``grown`` is [(tree, leaf_id)] in class
        order.  Returns True when training should stop."""
        could_split_any = False
        for k, (tree, leaf_id) in enumerate(grown):
            if self._forest_finish_tree(k, tree, leaf_id):
                could_split_any = True
        self.iter_ += 1
        self._model_version += 1
        if self._nf_guard is not None:
            self._nf_guard.raise_if_poisoned(self, nf_snap)
        return not could_split_any

    def _train_one_iter_impl(
        self,
        grad: Optional[np.ndarray] = None,
        hess: Optional[np.ndarray] = None,
    ) -> bool:
        K = self.num_class
        pre = self._forest_begin_iter(grad, hess)
        if pre == "stop":
            return True
        if pre == "skip":
            return False
        grad, hess, fmasks, nf_snap = pre

        if K > 1 and self._forest_eligible():
            # multiclass: the K per-class trees of ONE iteration share
            # grad/hess batches and the bagging mask already — grow all
            # K in one batched dispatch (ROADMAP item 2), bitwise the
            # sequential k-loop's trees (tier-1 pins this)
            from ..learners import forest

            params_lanes = jax.tree.map(
                lambda x: jnp.broadcast_to(x, (K,)), self._learner_params)
            trees_b, lids = self._grow_forest_batched(
                grad, hess,
                jnp.broadcast_to(self._bag_mask, (K, self.num_data)),
                jnp.stack(fmasks), params_lanes,
            )
            grown = [(forest.unstack_tree(trees_b, k), lids[k])
                     for k in range(K)]
            return self._forest_finish_iter(grown, nf_snap)

        could_split_any = False
        for k in range(K):
            fmask = fmasks[k]
            if self._use_f64_hist:
                with enable_x64(True):
                    gk = grad[k].astype(jnp.float64)
                    hk = hess[k].astype(jnp.float64)
                    tree, leaf_id = self._grow(
                        self._bins_T, gk, hk, self._bag_mask, fmask,
                        self._nbpf, self._is_cat, self._learner_params,
                    )
                    tree = jax.tree.map(
                        lambda a: a.astype(jnp.float32)
                        if a.dtype == jnp.float64 else a,
                        tree,
                    )
            else:
                tree, leaf_id = self._grow(
                    self._bins_T,
                    grad[k],
                    hess[k],
                    self._bag_mask,
                    fmask,
                    self._nbpf,
                    self._is_cat,
                    self._learner_params,
                )
            if self._forest_finish_tree(k, tree, leaf_id):
                could_split_any = True
        self.iter_ += 1
        self._model_version += 1
        if self._nf_guard is not None:
            # policy=raise drains its parked device counts here — the
            # iteration's end, where the eager stop check already synced
            self._nf_guard.raise_if_poisoned(self, nf_snap)
        return not could_split_any

    def finish_lagged_stop(self) -> None:
        """Drain the lagged stop check's parked values after the LAST
        train_one_iter call.  When training ends by iteration count, the
        parked num_leaves of the final ``lag`` iterations were never
        materialized; a terminal stump among them means later iterations
        must be rolled back to restore the eager-mode model.  No-op
        without LGBM_TPU_STOP_LAG."""
        while self._pending_stop:
            old = self._pending_stop.pop(0)
            telemetry.host_sync()
            if int(old) <= 1:
                for _ in range(len(self._pending_stop)):
                    self.rollback_one_iter()
                self._pending_stop.clear()
                break

    def finalize_guards(self) -> None:
        """End-of-training drain of the non-finite guard's lazily
        accumulated counts (policy=clip batches device fetches; without
        this drain a short run would report zero clipped values and the
        degradation would be invisible).  Under policy=raise a pending
        poisoned final iteration surfaces here as NonFiniteError."""
        if self._nf_guard is not None:
            self._nf_guard.finalize()

    def snapshot_state(self) -> tuple:
        """Capture every per-iteration mutable of the training state
        for an EXACT rewind (restore_state).  Unlike rollback_one_iter
        — whose (s + d) - d float32 round trip leaves ulp residue in
        the scores — restore is bit-exact: the score buffers are device
        COPIES (a bare reference would be donated into the next
        _post_grow_step and deleted).  Used by bench.py to discard
        warm-up trees so the timed model is byte-identical to a fresh
        one.  Keep this field list in sync with train_one_iter's state
        mutations."""
        return (
            jnp.array(self._scores),
            len(self.models),
            self.iter_,
            self._bag_rng.get_state(),
            self._feat_rng.get_state(),
            self._bag_mask,  # immutable and never donated: ref is safe
            self._bag_cnt,
            [jnp.array(v) for v in getattr(self, "_valid_scores", [])],
            # parked lagged-stop scalars (LGBM_TPU_STOP_LAG): device
            # scalars, never donated — the shallow copy suffices
            list(self._pending_stop),
        )

    def restore_state(self, snap: tuple) -> None:
        """Rewind to a snapshot_state() capture (see its contract).
        Restores COPIES of the score buffers so the snapshot stays
        reusable — installing the captured array itself would let the
        next _post_grow_step donate-and-delete it, making a second
        restore crash on a deleted buffer."""
        (scores, n_models, it, bag_state, feat_state, bag_mask,
         bag_cnt, valid_scores, pending_stop) = snap
        self._scores = jnp.array(scores)
        del self.models[n_models:]
        self.iter_ = it
        self._bag_rng.set_state(bag_state)
        self._feat_rng.set_state(feat_state)
        self._bag_mask = bag_mask
        self._bag_cnt = bag_cnt
        for i, v in enumerate(valid_scores):
            self._valid_scores[i] = jnp.array(v)
        self._pending_stop[:] = pending_stop
        self._model_version += 1

    def rollback_one_iter(self) -> None:
        """GBDT::RollbackOneIter (gbdt.cpp:254-271): subtract the last
        iteration's trees from all scores and pop them."""
        if self.iter_ <= 0:
            return
        K = self.num_class
        last = self.models[-K:]
        # any rollback invalidates the parked lagged-stop values: their
        # indices no longer line up with self.models (the detection path
        # clears this anyway; external callers get a fresh start —
        # a still-terminal state is simply re-detected a lag later)
        self._pending_stop.clear()
        for k, tree in enumerate(last):
            # negative shrinkage = subtraction
            delta = predict_binned(tree, self._bins_T.T)
            self._scores = self._scores.at[k].add(-delta)
            for vi in range(len(self.valid_sets)):
                self._valid_scores[vi] = self._valid_scores[vi].at[k].add(
                    -predict_binned(tree, self._valid_bins[vi])
                )
        del self.models[-K:]
        self.iter_ -= 1
        self._model_version += 1

    # ------------------------------------------------------------------- eval
    def eval_at(self, data_idx: int, only=None) -> Dict[str, float]:
        """Metric evaluation: data_idx 0 = train, 1.. = valid sets
        (GBDT::GetPredictAt semantics, gbdt.cpp:388-426).  ``only``
        restricts to a set of metric names (callers that handle
        multi-position metrics themselves skip them here)."""
        if data_idx == 0:
            scores, metrics = self._scores, self.train_metrics
        else:
            scores = self._valid_scores[data_idx - 1]
            metrics = self.valid_metrics[data_idx - 1]
        dev = scores if self.num_class > 1 else scores[0]
        out: Dict[str, float] = {}
        if only is not None:
            metrics = [m for m in metrics if m.name in only]
        # ALL device-path metric evals dispatch first (scores stay in
        # HBM, each returns an async device scalar), host-path metrics
        # run next behind ONE score materialization, and a single
        # device_get drains the pending scalars last — the previous
        # per-metric float() paid one pipeline-draining sync per metric
        # per iteration (jaxlint host-sync-in-loop; the same stall
        # class the lagged stop check measured at ~0.3 s/tree over the
        # TPU tunnel), and materializing host scores BEFORE dispatching
        # would re-serialize the same pipeline
        pending: Dict[str, object] = {}
        host_metrics: List[Metric] = []
        for m in metrics:
            out[m.name] = float("nan")  # placeholder keeps dict order
            if m.eval_jax is not None:
                pending[m.name] = m.eval_jax_jit(dev)
            else:
                host_metrics.append(m)
        if host_metrics:
            telemetry.host_sync()
            host = np.asarray(dev)
            for m in host_metrics:
                out[m.name] = m.eval(host)
        if pending:
            telemetry.host_sync()
            for name, val in zip(pending,
                                 jax.device_get(list(pending.values()))):
                out[name] = float(val)
        return out

    def predict_at(self, data_idx: int) -> np.ndarray:
        scores = self._scores if data_idx == 0 else self._valid_scores[data_idx - 1]
        return np.asarray(scores)

    # ---------------------------------------------------------------- predict
    def _versioned_cache(self, attr: str, key, build):
        """Model-version-keyed memo shared by the stack and table
        caches: one copy of the invalidation protocol (the explicit
        _model_version counter, bumped by every mutation of
        ``self.models``)."""
        version = getattr(self, "_model_version", 0)
        cache = getattr(self, attr, None)
        if cache is None or cache[0] != version:
            cache = (version, {})
            setattr(self, attr, cache)
        if key not in cache[1]:
            cache[1][key] = build()
        return cache[1][key]

    def _stacked_models(self, n_trees: int, grouped: bool):
        """Stack the first ``n_trees`` trees into one batched Tree pytree
        (leading axis [T], or [T//K, K] when ``grouped``)."""

        def build():
            stacked = stack_trees(self.models[:n_trees])
            if grouped:
                K = self.num_class
                stacked = jax.tree.map(
                    lambda a: a.reshape((n_trees // K, K) + a.shape[1:]),
                    stacked,
                )
            return stacked

        return self._versioned_cache("_stack_cache", (n_trees, grouped), build)

    def _stacked_tables(self, n_trees: int, grouped: bool):
        """Path-incidence tables (ops/predict_matmul.py) for the stacked
        model — cached next to the stack under the same version key."""

        def build():
            from ..ops.predict_matmul import build_path_tables

            return build_path_tables(self._stacked_models(n_trees, grouped))

        return self._versioned_cache("_table_cache", (n_trees, grouped), build)

    def _iter_chunk(self, n_rows: int) -> int:
        """Boosting iterations per prediction dispatch: the ensemble walk
        does O(rows * TREES * depth) indexed gathers in one device
        program, and a single program running for minutes TRIPS THE TPU
        WORKER WATCHDOG (measured: 1M rows x 100 trees crashes the
        worker; 1M x 10 and 100k x 100 are fine).  Bound rows*TREES per
        dispatch — each iteration is num_class trees — and accumulate
        the chunks' partial sums on device."""
        return max(1, 16_000_000 // max(n_rows * self.num_class, 1))

    def _raw_scores(self, X: np.ndarray, num_iteration: int = -1) -> np.ndarray:
        """Whole-ensemble prediction in tree-chunked device programs
        (stacked-tree scan, models/tree.py ensemble_sum_raw) — replaces
        the reference's per-tree per-row traversal loop
        (gbdt.cpp:388-426)."""
        K = self.num_class
        n_iter = len(self.models) // K
        if num_iteration > 0:
            n_iter = min(n_iter, num_iteration)
        X = jnp.asarray(np.ascontiguousarray(X, np.float32))
        if n_iter == 0:
            return np.zeros((K, X.shape[0]), np.float64)
        stacked = self._stacked_models(n_iter * K, grouped=True)
        if _use_matmul_predict():
            from ..ops.predict_matmul import ensemble_sum_matmul

            tables = self._stacked_tables(n_iter * K, grouped=True)
            # tree-chunking: no long per-row serial walk, so each
            # dispatch carries ~10x the walk path's rows*trees budget
            # without nearing the TPU worker watchdog.  ROW-chunking
            # bounds the per-tree dense intermediates (vals/go/match are
            # [rows, L]-shaped, ~2.5KB/row at L=255 — 10M rows would
            # OOM a 16GB chip without it).
            step = max(1, 10 * self._iter_chunk(min(X.shape[0], _ROW_CHUNK)))
            parts = []
            for rlo in range(0, X.shape[0], _ROW_CHUNK):
                Xc = X[rlo:rlo + _ROW_CHUNK]
                acc = None
                for lo in range(0, n_iter, step):
                    part = jax.tree.map(lambda a: a[lo:lo + step], stacked)
                    tpart = jax.tree.map(lambda a: a[lo:lo + step], tables)
                    out = ensemble_sum_matmul(tpart, part, Xc)
                    acc = out if acc is None else acc + out
                # per-chunk materialization IS the product here (the
                # chunking exists to bound device memory)
                parts.append(np.asarray(acc, np.float64))  # jaxlint: disable=host-sync-in-loop
            return np.concatenate(parts, axis=1)
        step = self._iter_chunk(X.shape[0])
        acc = None
        for lo in range(0, n_iter, step):
            part = jax.tree.map(lambda a: a[lo:lo + step], stacked)
            out = ensemble_sum_raw(part, X)
            acc = out if acc is None else acc + out
        return np.asarray(acc, np.float64)

    def predict_raw_score(self, X, num_iteration: int = -1) -> np.ndarray:
        return raw_score_output(self._raw_scores(X, num_iteration),
                                self.num_class)

    def predict(self, X, num_iteration: int = -1) -> np.ndarray:
        """With transform (GBDT::Predict, gbdt.cpp:631-645)."""
        return transform_scores(self._raw_scores(X, num_iteration),
                                self.num_class, self.sigmoid,
                                self.objective_name())

    def predict_leaf_index(self, X, num_iteration: int = -1) -> np.ndarray:
        K = self.num_class
        n_iter = len(self.models) // K
        if num_iteration > 0:
            n_iter = min(n_iter, num_iteration)
        X = jnp.asarray(np.ascontiguousarray(X, np.float32))
        if n_iter == 0:
            return np.zeros((X.shape[0], 0), np.int32)
        stacked = self._stacked_models(n_iter * K, grouped=False)
        # flat tree-major stack: _iter_chunk already accounts for K
        step = max(K, self._iter_chunk(X.shape[0]) * K)
        if _use_matmul_predict():
            from ..ops.predict_matmul import ensemble_leaves_matmul

            tables = self._stacked_tables(n_iter * K, grouped=False)
            step *= 10  # no serial walk per dispatch; see _raw_scores
            parts = []
            for rlo in range(0, X.shape[0], _ROW_CHUNK):
                Xc = X[rlo:rlo + _ROW_CHUNK]
                outs = []
                for lo in range(0, n_iter * K, step):
                    part = jax.tree.map(lambda a: a[lo:lo + step], stacked)
                    tpart = jax.tree.map(lambda a: a[lo:lo + step], tables)
                    # chunked materialization bounds device memory
                    outs.append(np.asarray(  # jaxlint: disable=host-sync-in-loop
                        ensemble_leaves_matmul(tpart, part, Xc)))
                parts.append(np.concatenate(outs, axis=0))
            return np.concatenate(parts, axis=1).T
        outs = []
        for lo in range(0, n_iter * K, step):
            part = jax.tree.map(lambda a: a[lo:lo + step], stacked)
            # chunked materialization bounds device memory
            outs.append(np.asarray(ensemble_leaves_raw(part, X)))  # jaxlint: disable=host-sync-in-loop
        return np.concatenate(outs, axis=0).T

    def objective_name(self) -> str:
        if self.objective is not None:
            return self.objective.name
        return getattr(self, "_loaded_objective", "")

    # ------------------------------------------------------------- model text
    def feature_importance(self) -> Dict[str, int]:
        """Split-count importance keyed by name (gbdt.cpp:594-619)."""
        imp = self.feature_importance_array("split")
        names = self.feature_names or [
            f"Column_{i}" for i in range(self.max_feature_idx + 1)
        ]
        return {names[i]: int(imp[i]) for i in range(len(imp)) if imp[i] > 0}

    def _lagged_terminal_drop(self) -> int:
        """Number of TRAILING trees a finish_lagged_stop() drain would
        roll back, computed WITHOUT mutating state: the parked values are
        synced (a save reads host arrays anyway) but nothing is popped —
        a mid-training checkpoint must not yank trees out from under the
        running train loop (ADVICE r3 / review r4)."""
        for i, old in enumerate(self._pending_stop):
            if int(old) <= 1:
                return (len(self._pending_stop) - 1 - i) * self.num_class
        return 0

    def save_model_to_string(self, num_iteration: int = -1) -> str:
        """Reference text format (gbdt.cpp:479-521).  With a lagged stop
        check (LGBM_TPU_STOP_LAG) active, trees a future drain would roll
        back are excluded from the STRING only — in-memory state is not
        touched, so checkpoint-every-iteration callbacks stay safe."""
        out = [self.name]
        out.append(f"num_class={self.num_class}")
        out.append(f"label_index={self.label_idx}")
        out.append(f"max_feature_idx={self.max_feature_idx}")
        if self.objective_name():
            out.append(f"objective={self.objective_name()}")
        out.append(f"sigmoid={_fmt(self.sigmoid)}")
        names = self.feature_names or [
            f"Column_{i}" for i in range(self.max_feature_idx + 1)
        ]
        out.append("feature_names=" + " ".join(names))
        out.append("")
        num_used = len(self.models) - self._lagged_terminal_drop()
        if num_iteration > 0:
            num_used = min(num_iteration * self.num_class, num_used)
        for i in range(num_used):
            out.append(f"Tree={i}")
            out.append(_tree_to_string(self.models[i]))
        out.append("")
        out.append("feature importances:")
        pairs = sorted(self.feature_importance().items(), key=lambda kv: -kv[1])
        for name, cnt in pairs:
            out.append(f"{name}={cnt}")
        return "\n".join(out) + "\n"

    def save_model_to_file(self, filename: str, num_iteration: int = -1) -> None:
        # atomic + checksummed: a preemption mid-save must never leave a
        # truncated model (which would silently LOAD, with fewer trees)
        # under the real name; the .sha256 sidecar makes "is this model
        # intact?" checkable (resilience/atomic.py)
        atomic_write(filename, self.save_model_to_string(num_iteration),
                     checksum=True)

    def load_model_from_string(self, model_str: str) -> None:
        """gbdt.cpp:523-592."""
        lines = model_str.splitlines()
        kv = {}
        tree_blocks: List[List[str]] = []
        i = 0
        while i < len(lines):
            line = lines[i].strip()
            if line.startswith("Tree="):
                i += 1
                block = []
                while i < len(lines) and not lines[i].startswith("Tree=") and not lines[
                    i
                ].startswith("feature importances"):
                    block.append(lines[i])
                    i += 1
                tree_blocks.append(block)
                continue
            if "=" in line:
                k, v = line.split("=", 1)
                kv.setdefault(k.strip(), v.strip())
            i += 1
        self.num_class = int(kv.get("num_class", 1))
        self.label_idx = int(kv.get("label_index", 0))
        self.max_feature_idx = int(kv.get("max_feature_idx", -1))
        self.sigmoid = float(kv.get("sigmoid", -1.0))
        self._loaded_objective = kv.get("objective", "")
        self.feature_names = kv.get("feature_names", "").split()
        self.models = [_tree_from_lines(b) for b in tree_blocks]
        self._model_version = getattr(self, "_model_version", 0) + 1
        self.num_init_iteration = len(self.models) // max(self.num_class, 1)
        self.iter_ = 0

    def merge_from(self, other: "GBDT", prepend: bool = False) -> None:
        """GBDT::MergeFrom (gbdt.h:44-61): concatenate another model's
        trees.  ``prepend=True`` puts the other model first (continued
        training from ``input_model``, gbdt.cpp:589-592) and replays its
        predictions into the current train/valid scores."""
        if other.num_class != self.num_class:
            raise ValueError("cannot merge models with different num_class")
        K = self.num_class
        incoming = list(other.models)
        if self.train_set is not None:
            # re-bind foreign trees into THIS dataset's bin space so every
            # stored model is safe for predict_binned (valid-set replay in
            # add_valid_dataset, score updates here)
            incoming = [self._rebind_tree(t) for t in incoming]
        if prepend:
            self.models = incoming + self.models
            self._model_version += 1
            self.num_init_iteration = len(incoming) // K
            # replay other's trees into live scores (init_score seeding,
            # application.cpp:110-115)
            if self.train_set is not None and incoming:
                stacked = stack_trees(incoming)
                stacked = jax.tree.map(
                    lambda a: a.reshape((len(incoming) // K, K) + a.shape[1:]),
                    stacked,
                )
                self._scores = self._scores + ensemble_sum_binned(
                    stacked, self._bins_T.T
                )
                for vi in range(len(self.valid_sets)):
                    self._valid_scores[vi] = self._valid_scores[vi] + (
                        ensemble_sum_binned(stacked, self._valid_bins[vi])
                    )
        else:
            self.models = self.models + incoming
            self._model_version += 1
        self.iter_ = len(self.models) // K - self.num_init_iteration

    def _rebind_tree(self, tree: Tree) -> Tree:
        """Map a tree from another model into THIS dataset's bin space.

        The tree's own bin-space fields are never trusted — they belong to
        whatever dataset the tree was trained on.  Only threshold_real /
        split_feature_real (the raw-value decision program the reference
        also uses for loaded models, tree.h:226-238) are consulted.
        """
        nl = int(tree.num_leaves)
        if nl <= 1:
            return tree
        sf = np.asarray(tree.split_feature_real)
        tr = np.asarray(tree.threshold_real)
        dt = np.asarray(tree.decision_type)
        num_bins = self._num_bins
        tb = np.zeros(tree.threshold_bin.shape, np.int32)
        sf_inner = np.zeros(sf.shape, np.int32)
        dt2 = dt.copy()
        for i in range(nl - 1):
            f_real = int(sf[i])
            if f_real < 0:
                continue
            inner = int(self.train_set.used_feature_map[f_real])
            if inner < 0:
                # feature is trivial (constant) here: we cannot evaluate
                # const <=/== threshold without the raw value, so force a
                # deterministic all-left route via an impossible-to-fail
                # numerical compare (bin <= num_bins)
                sf_inner[i] = 0
                tb[i] = num_bins
                dt2[i] = 0
                continue
            sf_inner[i] = inner
            mapper = self.train_set.bin_mappers[inner]
            if dt[i] == 1:  # categorical: threshold is the category id
                tb[i] = mapper.category_to_bin.get(int(tr[i]), num_bins)
            else:
                # threshold_real == bounds[threshold_bin]; recover the bin
                # as the first bound >= t (tolerating text-format fp noise)
                bounds = self._bin_thresholds[inner]
                eps = abs(tr[i]) * 1e-9 + 1e-12
                tb[i] = min(int(np.searchsorted(bounds, tr[i] - eps)), len(bounds) - 1)
        return tree._replace(
            split_feature=jnp.asarray(sf_inner),
            threshold_bin=jnp.asarray(tb),
            decision_type=jnp.asarray(dt2),
        )

    # ------------------------------------------------------------ JSON dump
    def dump_model(self, num_iteration: int = -1) -> Dict:
        """GBDT::DumpModel (gbdt.cpp:438-477): JSON-style dict."""
        names = self.feature_names or [
            f"Column_{i}" for i in range(self.max_feature_idx + 1)
        ]
        # same non-mutating guarantee as save_model_to_string
        num_used = len(self.models) - self._lagged_terminal_drop()
        if num_iteration > 0:
            num_used = min(num_iteration * self.num_class, num_used)
        return {
            "name": self.name,
            "num_class": self.num_class,
            "label_index": self.label_idx,
            "max_feature_idx": self.max_feature_idx,
            "objective": self.objective_name(),
            "sigmoid": self.sigmoid,
            "feature_names": names,
            "tree_info": [
                _tree_to_json(self.models[i], i) for i in range(num_used)
            ],
        }

    def feature_importance_array(self, importance_type: str = "split") -> np.ndarray:
        """Importances as an array over all original columns."""
        imp = np.zeros(self.max_feature_idx + 1, np.float64)
        # cold path (model save/dump), inherently host-side per tree
        for tree in self.models:
            nl = int(tree.num_leaves)
            sfr = np.asarray(tree.split_feature_real)[: nl - 1]  # jaxlint: disable=host-sync-in-loop
            gains = np.asarray(tree.split_gain)[: nl - 1]  # jaxlint: disable=host-sync-in-loop
            for j, f in enumerate(sfr):
                if f >= 0:
                    imp[f] += gains[j] if importance_type == "gain" else 1
        return imp

    @property
    def num_trees(self) -> int:
        return len(self.models)

    @property
    def current_iteration(self) -> int:
        return len(self.models) // max(self.num_class, 1)


def train_forest_round(gbdts: List["GBDT"]) -> List[bool]:
    """Advance every booster in ``gbdts`` one boosting iteration,
    growing ALL their trees (sum of num_class lanes) in ONE batched
    dispatch (learners/forest.py).  This is the cross-model B-source:
    engine.train_many's N independent small models and engine.cv's
    folds share a binned dataset, so their per-iteration grow work is
    shape-identical and stacks along the lane axis.

    Requirements (raise ValueError otherwise — the callers validate
    configs upfront and fall back to per-booster sequential training):
    every booster _forest_eligible() under its own knob, same binned
    matrix object (dense_bins_T_device cache), same num_bins and
    max_leaves.  Per-lane TreeLearnerParams may differ (lambda_l1/l2,
    min_data_in_leaf, ... ride the stacked params lanes).

    Returns a per-booster "should stop" flag, aligned with ``gbdts``.
    Boosters whose begin-half says "stop"/"skip" simply contribute no
    lanes; a shrinking active set retraces once per distinct lane
    count (cached in make_grow_forest's lru table).
    """
    from ..learners import forest

    if not gbdts:
        return []
    ref = gbdts[0]
    for b in gbdts:
        if not b._forest_eligible():
            raise ValueError(
                "train_forest_round: booster not forest-eligible "
                "(forest_batching=off, kernel/f64/pooled-histogram path, "
                "or parallel learner)"
            )
        if b._bins_T is not ref._bins_T:
            raise ValueError(
                "train_forest_round: boosters must share one binned "
                "dataset (same Dataset object, bin once)"
            )
        if (b._num_bins != ref._num_bins
                or b.max_leaves != ref.max_leaves):
            raise ValueError(
                "train_forest_round: max_bin and num_leaves must match "
                "across boosters (they fix the traced program shape)"
            )
        if ((getattr(b, "_base_row_mask", None) is None)
                != (getattr(ref, "_base_row_mask", None) is None)):
            raise ValueError(
                "train_forest_round: base row masks (cv fold mode) must "
                "be set on all boosters or none (the child-choice "
                "criterion is static per traced program)"
            )

    stops: List[bool] = [False] * len(gbdts)
    active: List[int] = []  # indices into gbdts with grow work
    pres = []
    for i, b in enumerate(gbdts):
        pre = b._forest_begin_iter()
        if pre == "stop":
            stops[i] = True
        elif pre == "skip":
            stops[i] = False
        else:
            active.append(i)
            pres.append(pre)
    if not active:
        return stops

    grads, hesses, bags, fmasks, plist = [], [], [], [], []
    lane_of = []  # (booster index, class k) per lane
    for i, (grad, hess, fms, _snap) in zip(active, pres):
        b = gbdts[i]
        for k in range(b.num_class):
            grads.append(grad[k])
            hesses.append(hess[k])
            bags.append(b._bag_mask)
            fmasks.append(fms[k])
            plist.append(b._learner_params)
            lane_of.append((i, k))

    gf = forest.make_grow_forest(
        ref._num_bins, ref.max_leaves,
        choice_by_mask_counts=(
            getattr(ref, "_base_row_mask", None) is not None),
    )
    trees_b, lids = gf(
        ref._bins_T, jnp.stack(grads), jnp.stack(hesses),
        jnp.stack(bags), jnp.stack(fmasks), ref._nbpf, ref._is_cat,
        forest.stack_learner_params(plist),
    )
    telemetry.count("forest_dispatches")
    telemetry.count("forest_batched_trees", len(lane_of))

    # distribute lanes back booster-major (lane_of is already grouped)
    per_booster: Dict[int, list] = {}
    for lane, (i, _k) in enumerate(lane_of):
        per_booster.setdefault(i, []).append(
            (forest.unstack_tree(trees_b, lane), lids[lane])
        )
    for pos, i in enumerate(active):
        nf_snap = pres[pos][3]
        stops[i] = gbdts[i]._forest_finish_iter(per_booster[i], nf_snap)
    return stops


def _fmt(x) -> str:
    """Compact float formatting matching C++ default ostream behavior."""
    x = float(x)
    if x == int(x) and abs(x) < 1e15:
        return str(int(x))
    return repr(x)


def _arr_str(a, n, fmt=str) -> str:
    return " ".join(fmt(v) for v in np.asarray(a)[:n])


def _tree_to_string(tree: Tree) -> str:
    """Tree::ToString (tree.cpp:124-151)."""
    nl = int(tree.num_leaves)
    ni = max(nl - 1, 0)
    f = lambda v: _fmt(float(v))
    out = [f"num_leaves={nl}"]
    out.append("split_feature=" + _arr_str(tree.split_feature_real, ni))
    out.append("split_gain=" + _arr_str(tree.split_gain, ni, f))
    out.append("threshold=" + _arr_str(tree.threshold_real, ni, f))
    out.append("decision_type=" + _arr_str(tree.decision_type, ni))
    out.append("left_child=" + _arr_str(tree.left_child, ni))
    out.append("right_child=" + _arr_str(tree.right_child, ni))
    out.append("leaf_parent=" + _arr_str(tree.leaf_parent, nl))
    out.append("leaf_value=" + _arr_str(tree.leaf_value, nl, f))
    out.append("leaf_count=" + _arr_str(tree.leaf_count, nl, lambda v: str(int(float(v)))))
    out.append("internal_value=" + _arr_str(tree.internal_value, ni, f))
    out.append(
        "internal_count=" + _arr_str(tree.internal_count, ni, lambda v: str(int(float(v))))
    )
    out.append("")
    return "\n".join(out)


def _tree_to_json(tree: Tree, index: int) -> Dict:
    """Tree::ToJSON (tree.cpp:153-191): recursive node dict."""
    nl = int(tree.num_leaves)
    sf = np.asarray(tree.split_feature_real)
    sg = np.asarray(tree.split_gain)
    tr = np.asarray(tree.threshold_real)
    dt = np.asarray(tree.decision_type)
    lc = np.asarray(tree.left_child)
    rc = np.asarray(tree.right_child)
    iv = np.asarray(tree.internal_value)
    ic = np.asarray(tree.internal_count)
    lv = np.asarray(tree.leaf_value)
    lcnt = np.asarray(tree.leaf_count)
    lp = np.asarray(tree.leaf_parent)

    def leaf_node(leaf: int) -> Dict:
        return {
            "leaf_index": int(leaf),
            "leaf_parent": int(lp[leaf]),
            "leaf_value": float(lv[leaf]),
            "leaf_count": int(lcnt[leaf]),
        }

    # children are always created after their parent (tree.cpp:52-96), so a
    # reverse sweep builds every child dict before its parent — no recursion
    built: Dict[int, Dict] = {}
    for i in range(nl - 2, -1, -1):
        li, ri = int(lc[i]), int(rc[i])
        built[i] = {
            "split_index": int(i),
            "split_feature": int(sf[i]),
            "split_gain": float(sg[i]),
            "threshold": float(tr[i]),
            "decision_type": "==" if dt[i] == 1 else "<=",
            "internal_value": float(iv[i]),
            "internal_count": int(ic[i]),
            "left_child": built[li] if li >= 0 else leaf_node(~li),
            "right_child": built[ri] if ri >= 0 else leaf_node(~ri),
        }

    return {
        "tree_index": index,
        "num_leaves": nl,
        "tree_structure": built[0] if nl > 1 else leaf_node(0),
    }


def _tree_from_lines(lines: List[str]) -> Tree:
    """Tree::Tree(const string&) (tree.cpp:193-231).  Bin-space fields are
    unavailable in the text format; loaded trees predict on raw values."""
    kv = {}
    for line in lines:
        if "=" in line:
            k, v = line.split("=", 1)
            if k.strip() and v.strip():
                kv[k.strip()] = v.strip()
    nl = int(kv["num_leaves"])
    max_leaves = max(nl, 2)
    t = empty_tree(max_leaves)

    def parse(key, n, dtype):
        if n == 0 or key not in kv:
            return np.zeros(n, dtype)
        vals = np.array(kv[key].split()[:n], dtype=np.float64)
        return vals.astype(dtype)

    ni = nl - 1
    pad_i = max_leaves - 1 - ni
    pad_l = max_leaves - nl

    def padded(key, n, pad, dtype, fill=0):
        v = parse(key, n, dtype)
        if pad > 0:
            v = np.concatenate([v, np.full(pad, fill, dtype)])
        return jnp.asarray(v)

    return t._replace(
        num_leaves=jnp.int32(nl),
        split_feature=padded("split_feature", ni, pad_i, np.int32),
        split_feature_real=padded("split_feature", ni, pad_i, np.int32),
        threshold_real=padded("threshold", ni, pad_i, np.float32),
        decision_type=padded("decision_type", ni, pad_i, np.int32),
        left_child=padded("left_child", ni, pad_i, np.int32),
        right_child=padded("right_child", ni, pad_i, np.int32),
        split_gain=padded("split_gain", ni, pad_i, np.float32),
        internal_value=padded("internal_value", ni, pad_i, np.float32),
        internal_count=padded("internal_count", ni, pad_i, np.float32),
        leaf_value=padded("leaf_value", nl, pad_l, np.float32),
        leaf_count=padded("leaf_count", nl, pad_l, np.float32),
        leaf_parent=padded("leaf_parent", nl, pad_l, np.int32, -1),
    )
