from .tree import (
    Tree,
    empty_tree,
    predict_binned,
    predict_leaf_binned,
    predict_leaf_raw,
    predict_raw,
    finalize_thresholds,
)

__all__ = [
    "Tree",
    "empty_tree",
    "predict_binned",
    "predict_leaf_binned",
    "predict_leaf_raw",
    "predict_raw",
    "finalize_thresholds",
]
