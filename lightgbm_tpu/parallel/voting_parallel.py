"""Voting-parallel (PV-Tree) learner: data parallel with top-k voting.

TPU-native re-design of VotingParallelTreeLearner
(src/treelearner/voting_parallel_tree_learner.cpp): rows are sharded as
in the data-parallel learner, but instead of reducing histograms for ALL
features, each device (a) searches its LOCAL histograms with constraints
scaled by 1/num_shards (voting_parallel_tree_learner.cpp:52-54),
(b) proposes its local top-2k features (ArrayArgs::MaxK,
voting_parallel_tree_learner.cpp:229-232), (c) a global vote weighted by
local data counts picks <=2*top_k features
(voting_parallel_tree_learner.cpp:137-166), and (d) only the winners'
histograms are summed across the mesh
(voting_parallel_tree_learner.cpp:260-265) — one small `psum` instead of
a full-width reduce-scatter, cutting per-level comm from O(F*B) to
O(top_k*B).  The final search over the reduced histograms runs
identically on every device, subsuming the SplitInfo allreduce.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..compat import shard_map
from ..learners.serial import grow_tree
from ..ops.histogram import histogram_feature_major
from ..ops.split import find_best_split
from .mesh import ROW_AXIS, row_padded_grower


def make_voting_parallel_grower(
    mesh, num_bins: int, max_leaves: int, top_k: int, axis: str = ROW_AXIS,
    sorted_hist: bool = False, hist_pool: int = 0,
):
    num_shards = mesh.shape[axis]
    from ..ops.histogram import select_single_hist_fn

    hist_local = select_single_hist_fn(num_bins, sorted_hist)

    def shard_body(bins_T, grad, hess, bag_mask, fmask, nbpf, is_cat, params):
        F = bins_T.shape[0]
        k2 = min(2 * top_k, F)

        def search_fn(hist, sg, sh, c, can, fm, nb, ic, prm):
            # local leaf totals: any feature's bins sum to the local totals
            lsg = jnp.sum(hist[0, :, 0])
            lsh = jnp.sum(hist[0, :, 1])
            lc = jnp.sum(hist[0, :, 2])
            scale = 1.0 / num_shards

            # (a) per-feature LOCAL best gains (FindBestThresholds on the
            # local histogram with 1/num_machines-scaled constraints)
            def one_feature(h, fmk, nbf, icf):
                return find_best_split(
                    h[None], lsg, lsh, lc,
                    fmk[None], nbf[None], icf[None],
                    prm.min_data_in_leaf * scale,
                    prm.min_sum_hessian_in_leaf * scale,
                    prm.lambda_l1, prm.lambda_l2,
                    prm.min_gain_to_split, can,
                ).gain

            local_gain = jax.vmap(one_feature)(hist, fm, nb, ic)  # [F]

            # (b) local proposal + (c) count-weighted global vote
            _, top_idx = jax.lax.top_k(local_gain, k2)
            proposal = jnp.zeros(F, jnp.float32).at[top_idx].set(1.0)
            votes = jax.lax.psum(proposal * lc, axis)
            _, selected = jax.lax.top_k(votes, k2)
            selected = jnp.sort(selected)  # ascending: smaller-feature tie-break

            # (d) reduce only the winners' histograms, search globally
            sel_hist = jax.lax.psum(hist[selected], axis)
            r = find_best_split(
                sel_hist, sg, sh, c,
                fm[selected], nb[selected], ic[selected],
                prm.min_data_in_leaf, prm.min_sum_hessian_in_leaf,
                prm.lambda_l1, prm.lambda_l2, prm.min_gain_to_split, can,
            )
            return r._replace(
                feature=jnp.where(r.feature >= 0, selected[r.feature], -1)
            )

        return grow_tree(
            bins_T, grad, hess, bag_mask, fmask, nbpf, is_cat, params,
            num_bins=num_bins, max_leaves=max_leaves,
            hist_fn=hist_local,
            reduce_fn=lambda x: jax.lax.psum(x, axis),
            search_fn=search_fn,
            reduce_max_fn=lambda x: jax.lax.pmax(x, axis),
            hist_pool=hist_pool,
            record_mode=True,
        )

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(None, axis), P(axis), P(axis), P(axis), P(), P(), P(), P()),
        out_specs=(P(), P(axis)),
        check_vma=False,
    )
    return row_padded_grower(sharded, num_shards)
