"""SplitInfo exchange shared by the sharded-search learners.

The reference ships one fixed-size SplitInfo byte buffer through
Network::Allreduce with a deterministic MaxReducer (split_info.hpp:58-104,
feature_parallel_tree_learner.cpp:64-77).  The mesh analog: pack the
11-field SplitResult into ONE float matrix (a pytree all_gather would
emit 11 collectives, one per leaf array), all_gather it, and reduce with
the reference's ordering — max gain, ties broken toward the smaller
feature index.  feature/threshold values are < 2^24, exactly
representable in f32 for transport.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.split import SplitResult

# Plain Python int (weakly typed in jnp ops): a module-level jnp constant
# would initialize the default JAX backend at import time, which hangs
# when a TPU plugin (axon) claims the platform before the caller pins it.
_INT_MAX = 2**31 - 1

_F_FEATURE = SplitResult._fields.index("feature")
_F_THRESH = SplitResult._fields.index("threshold")


def pack_split(r: SplitResult) -> jax.Array:
    """[..., 11] float transport form (int fields cast, exact)."""
    ft = r.gain.dtype
    return jnp.stack([jnp.asarray(f).astype(ft) for f in r], axis=-1)


def unpack_split(a: jax.Array) -> SplitResult:
    fields = [a[..., i] for i in range(len(SplitResult._fields))]
    fields[_F_FEATURE] = fields[_F_FEATURE].astype(jnp.int32)
    fields[_F_THRESH] = fields[_F_THRESH].astype(jnp.int32)
    return SplitResult(*fields)


def combine_gathered_split_infos(g: SplitResult) -> SplitResult:
    """Reduce an all_gathered SplitResult (leading device axis, arbitrary
    trailing batch axes) with the reference's deterministic ordering
    (split_info.hpp:98-103)."""
    feats = jnp.where(g.feature < 0, _INT_MAX, g.feature)
    tied = g.gain == jnp.max(g.gain, axis=0, keepdims=True)
    winner = jnp.argmin(jnp.where(tied, feats, _INT_MAX), axis=0)
    return SplitResult(
        *[jnp.take_along_axis(f, winner[None], axis=0)[0] for f in g]
    )


def gather_and_combine(r: SplitResult, axis: str,
                       site: str = None) -> SplitResult:
    """One packed all_gather over ``axis`` + deterministic max.

    ``site`` opts into the trace-time collective census (obs/dist.py):
    callers on an audited path name their site so the per-op
    collectives-per-split contract stays checkable."""
    g = jax.lax.all_gather(pack_split(r), axis)  # [D, 11]
    if site:
        from ..obs.dist import record_collective_site

        record_collective_site(site, "all-gather",
                               g.size * g.dtype.itemsize)
    return combine_gathered_split_infos(unpack_split(g))
