"""Grid-parallel tree learner: rows x split-search over a 2-D mesh.

A TPU-native extension beyond the reference's three 1-D modes
(src/treelearner/parallel_tree_learner.h): on an (R x C) device mesh,
rows shard over the ``row`` axis (each row shard replicated across the
``feature`` axis) and the split SEARCH shards over the ``feature`` axis.
Per split, each device

1. builds the local histogram for its FEATURE SLICE over its ROW SHARD
   (n/R rows x F/C features of work — the 2-D scaling product),
2. ``psum``s over the row axis (the data-parallel reduce,
   data_parallel_tree_learner.cpp:127-157 semantics),
3. searches its feature slice and combines one SplitInfo per slice over
   the feature axis with the reference's deterministic max (larger
   gain, smaller feature on ties — split_info.hpp:98-103), exactly the
   feature-parallel combine (feature_parallel_tree_learner.cpp:64-77).

Because every device stores full-F bins for its row shard, the winning
split partitions locally with the global feature id, and the grown tree
is replicated — the same invariants as the 1-D learners, composed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..compat import shard_map
from ..learners.serial import grow_tree
from ..ops.split import find_best_split
from .split_comm import gather_and_combine
from .mesh import FEATURE_AXIS, ROW_AXIS, row_padded_grower


def grid_mesh(shape, devices=None) -> Mesh:
    """An (R, C) mesh with axes (row, feature)."""
    if devices is None:
        devices = jax.devices()
    r, c = shape
    return Mesh(
        np.asarray(devices[: r * c]).reshape(r, c), (ROW_AXIS, FEATURE_AXIS)
    )


def make_grid_parallel_grower(mesh: Mesh, num_bins: int, max_leaves: int,
                              sorted_hist: bool = False,
                              hist_pool: int = 0):
    """grow(bins_T, grad, hess, bag_mask, feature_mask, nbpf, is_cat,
    params) -> (tree, leaf_id) over a 2-D (row, feature) mesh."""
    from ..ops.histogram import select_single_hist_fn

    num_fshards = mesh.shape[FEATURE_AXIS]
    local_hist = select_single_hist_fn(num_bins, sorted_hist)

    def shard_body(bins_T, grad, hess, bag_mask, fmask, nbpf, is_cat, params):
        F = bins_T.shape[0]
        Fs = -(-F // num_fshards)
        pad = Fs * num_fshards - F
        fstart = jax.lax.axis_index(FEATURE_AXIS) * Fs

        def fslice(a, fill=0):
            return jax.lax.dynamic_slice_in_dim(
                jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1),
                        constant_values=fill),
                fstart, Fs, axis=0,
            )

        def hist_fn(bins_arg, g, h, m):
            # local feature slice of the (possibly gathered) matrix, then
            # the data-parallel reduce over the row axis
            h_local = local_hist(fslice(bins_arg), g, h, m)
            return jax.lax.psum(h_local, ROW_AXIS)

        def search_fn(hist, sg, sh, c, can, _fm, _nb, _ic, prm):
            r = find_best_split(
                hist, sg, sh, c,
                fslice(fmask), fslice(nbpf, fill=1), fslice(is_cat),
                prm.min_data_in_leaf, prm.min_sum_hessian_in_leaf,
                prm.lambda_l1, prm.lambda_l2, prm.min_gain_to_split, can,
            )
            r = r._replace(
                feature=jnp.where(r.feature >= 0, r.feature + fstart, -1)
            )
            return gather_and_combine(r, FEATURE_AXIS)

        return grow_tree(
            bins_T, grad, hess, bag_mask, fmask, nbpf, is_cat, params,
            num_bins=num_bins, max_leaves=max_leaves,
            hist_fn=hist_fn,
            search_fn=search_fn,
            reduce_fn=lambda x: jax.lax.psum(x, ROW_AXIS),
            reduce_max_fn=lambda x: jax.lax.pmax(x, ROW_AXIS),
            hist_pool=hist_pool,
            record_mode=True,
        )

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(None, ROW_AXIS), P(ROW_AXIS), P(ROW_AXIS), P(ROW_AXIS),
                  P(), P(), P(), P()),
        out_specs=(P(), P(ROW_AXIS)),
        check_vma=False,
    )
    return row_padded_grower(sharded, mesh.shape[ROW_AXIS])
