"""Distributed training over a TPU device mesh.

TPU-native replacement for the reference's entire network layer
(src/network/: Bruck allgather, recursive-halving reduce-scatter, socket
and MPI linkers — network.cpp:40-185) and its parallel tree learners
(src/treelearner/parallel_tree_learner.h).  Sockets, topology maps, and
byte-level reducers collapse into XLA collectives (`psum`,
`psum_scatter`, `all_gather`, argmax reductions) over a
`jax.sharding.Mesh`, executing on ICI within a slice and DCN across
hosts with no framework code changes.
"""

from .mesh import data_mesh, default_device_count  # noqa: F401
from .data_parallel import make_data_parallel_grower  # noqa: F401
from .feature_parallel import make_feature_parallel_grower  # noqa: F401
from .voting_parallel import make_voting_parallel_grower  # noqa: F401
from .grid_parallel import grid_mesh, make_grid_parallel_grower  # noqa: F401

__all__ = [
    "data_mesh",
    "default_device_count",
    "make_data_parallel_grower",
    "make_feature_parallel_grower",
    "make_voting_parallel_grower",
    "grid_mesh",
    "make_grid_parallel_grower",
]
