"""Multi-host training: jax.distributed wiring + cross-process growers.

TPU-native replacement for the reference's Network::Init cluster
bootstrap (src/application/application.cpp:187-198) and its TCP/MPI
linker mesh (src/network/linkers_socket.cpp:20-61): one
``jax.distributed.initialize`` call attaches this process to the JAX
coordination service, after which ``jax.devices()`` spans every host and
the same XLA collectives (psum over the row axis) that power the
single-host data-parallel learner run over DCN/ICI across machines —
no sockets, no Bruck/recursive-halving topologies, no retry loops.

Process bootstrap accepts either

* the standard coordinator env/args (``LGBM_TPU_COORDINATOR``,
  ``LGBM_TPU_NUM_PROCESSES``, ``LGBM_TPU_PROCESS_ID``), or
* the reference's ``machine_list_file`` ("ip port" lines,
  linkers_socket.cpp:73-109): the first line is the coordinator and this
  process's rank is the position of a local interface address in the
  list (linkers_socket.cpp:31-44), overridable by env.
"""

from __future__ import annotations

import os
import socket
from typing import List, Optional, Tuple

import jax
import numpy as np

from ..log import Log
from .data_parallel import data_parallel_sharded
from .mesh import ROW_AXIS


def _parse_machine_list(path: str) -> List[Tuple[str, int]]:
    machines: List[Tuple[str, int]] = []
    with open(path) as fh:
        for line in fh:
            parts = line.split()
            if len(parts) >= 2:
                machines.append((parts[0], int(parts[1])))
    return machines


def _local_addresses() -> set:
    """Best-effort local interface addresses (GetLocalIpList,
    socket_wrapper.hpp:157-197)."""
    addrs = {"127.0.0.1", "localhost", "0.0.0.0"}
    try:
        hostname = socket.gethostname()
        addrs.add(hostname)
        for info in socket.getaddrinfo(hostname, None):
            addrs.add(info[4][0])
    except OSError:
        pass
    return addrs


def _already_distributed() -> bool:
    """Whether jax.distributed.initialize already ran in this process.

    Checked WITHOUT jax.process_count(): that call initializes the XLA
    backend as a side effect, after which jax.distributed.initialize
    refuses to run ("must be called before any JAX calls") — probing via
    process_count would permanently break the machine_list_file bootstrap
    it is guarding."""
    try:
        from jax._src import distributed

        return distributed.global_state.client is not None
    except Exception:
        return False


def initialize_from_config(cfg=None) -> bool:
    """Attach to (or bootstrap) the multi-process JAX runtime when the
    config/env asks for more than one machine.  Returns True when this
    process is part of a >1-process world.  Idempotent."""
    if _already_distributed():
        return jax.process_count() > 1

    coord = os.environ.get("LGBM_TPU_COORDINATOR", "")
    nproc = int(os.environ.get("LGBM_TPU_NUM_PROCESSES", "0") or 0)
    pid = int(os.environ.get("LGBM_TPU_PROCESS_ID", "-1") or -1)

    mlist = getattr(cfg, "machine_list_file", "") if cfg is not None else ""
    want = getattr(cfg, "num_machines", 1) if cfg is not None else nproc
    if not coord and mlist and want > 1:
        machines = _parse_machine_list(mlist)
        if len(machines) < want:
            Log.fatal(
                f"machine_list_file lists {len(machines)} machines, "
                f"num_machines={want}"
            )
        coord = f"{machines[0][0]}:{machines[0][1]}"
        nproc = want
        if pid < 0:
            local = _local_addresses()
            ranks = [i for i, (ip, _) in enumerate(machines) if ip in local]
            if len(ranks) == 1:
                pid = ranks[0]
            else:
                Log.fatal(
                    "cannot determine this machine's rank from "
                    f"machine_list_file (matches: {ranks}); set "
                    "LGBM_TPU_PROCESS_ID"
                )

    if coord and nproc > 1 and 0 <= pid < nproc:
        Log.info(
            f"Initializing distributed runtime: coordinator={coord}, "
            f"num_processes={nproc}, process_id={pid}"
        )
        # failure handling mirrors the reference's socket bootstrap: a
        # bounded retry loop (20 x 10s connect retries,
        # linkers_socket.cpp:182-197) under the config's time_out budget
        # (minutes, config.h:227).  jax.distributed's own
        # initialization_timeout covers the coordinator barrier.
        import time as _time

        timeout_s = 60 * int(getattr(cfg, "time_out", 120) or 120)
        attempts = 20
        deadline = _time.monotonic() + timeout_s
        for attempt in range(1, attempts + 1):
            try:
                jax.distributed.initialize(
                    coordinator_address=coord,
                    num_processes=nproc,
                    process_id=pid,
                    initialization_timeout=max(
                        10, min(timeout_s // attempts,
                                int(deadline - _time.monotonic()) or 1),
                    ),
                )
                break
            except Exception as e:  # noqa: BLE001 — retry any init failure
                try:  # a failed initialize leaves jax's global client set;
                    # without a shutdown every retry would instantly raise
                    # "should only be called once"
                    jax.distributed.shutdown()
                except Exception:
                    pass
                if attempt == attempts or _time.monotonic() >= deadline:
                    Log.fatal(
                        f"distributed init failed (attempt {attempt}/"
                        f"{attempts}, time_out={timeout_s // 60}min): "
                        f"{type(e).__name__}: {e}"
                    )
                Log.warning(
                    f"distributed init attempt {attempt}/{attempts} failed "
                    f"({type(e).__name__}); retrying"
                )
                # pace fast-failing errors (bad DNS, port still held by a
                # restarting coordinator) like the reference's 10s-spaced
                # connect retries, without overshooting the deadline
                _time.sleep(min(10.0, max(0.0, deadline - _time.monotonic())))
        return jax.process_count() > 1
    return False


def describe_topology() -> dict:
    """This process's rank-topology block, for checkpoint manifests and
    rank telemetry (obs/dist.py): who am I, how wide is the world, and
    which devices are local.  Resolution mirrors obs/dist.py — the live
    jax runtime when one is attached, else the launcher env
    (``LGBM_TPU_PROCESS_ID``/``LGBM_TPU_NUM_PROCESSES``), so a gang
    supervisor's CPU-only rank children report the same shape a real
    multihost world would."""
    topo = {
        "process_id": int(os.environ.get("LGBM_TPU_PROCESS_ID", "0") or 0),
        "num_processes": int(
            os.environ.get("LGBM_TPU_NUM_PROCESSES", "1") or 1),
        "local_devices": 0,
        "global_devices": 0,
        "platform": "",
    }
    # only query the live runtime when a backend already exists — the
    # probe must never initialize XLA as a side effect (that would
    # break the machine_list_file bootstrap _already_distributed guards)
    backend_live = False
    try:
        from jax._src import xla_bridge

        backend_live = bool(xla_bridge._backends)
    except Exception:  # noqa: BLE001 — private API moved; stay on env
        backend_live = _already_distributed()
    if backend_live:
        try:
            topo["process_id"] = jax.process_index()
            topo["num_processes"] = jax.process_count()
            topo["local_devices"] = jax.local_device_count()
            topo["global_devices"] = jax.device_count()
            topo["platform"] = jax.devices()[0].platform
        except Exception:  # noqa: BLE001 — env fallback already filled in
            pass
    gang_dir = os.environ.get("LGBM_TPU_GANG_DIR", "")
    if gang_dir:
        topo["gang_id"] = os.environ.get("LGBM_TPU_GANG_ID", "gang")
        topo["gang_slot"] = int(
            os.environ.get("LGBM_TPU_GANG_SLOT", "0") or 0)
    return topo


def sync_config_across_processes(cfg) -> None:
    """Cross-process config agreement — the reference's GlobalSyncUpByMin
    (application.cpp:110-127, 190-198, 259-270): randomized-behavior
    seeds/fractions take the MIN across ranks so every machine samples
    identically, and the load-bearing training params are fingerprinted
    and verified equal (the reference trusts operators to ship the same
    conf file; we fail fast instead of silently training a mixed world).
    No-op single-process.  Mutates ``cfg`` in place."""
    if jax.process_count() <= 1 or cfg is None:
        return
    from jax.experimental import multihost_utils

    # Exchange VALUES losslessly: under the default x64-disabled mode,
    # process_allgather downcasts f64->f32 / i64->i32 on the way through
    # the device, which would corrupt seeds >= 2^24 and add f32 drift to
    # fractions even when every rank already agrees.  Seeds ride as
    # int32 (config ints); fractions ride as their f64 BIT PATTERN in
    # two int32 lanes and are reassembled host-side before the min.
    seed_names = ("data_random_seed", "feature_fraction_seed", "bagging_seed")
    frac_names = ("feature_fraction", "bagging_fraction")
    seeds = np.asarray(
        [int(getattr(cfg, k, 0)) for k in seed_names], np.int32
    )
    fracs = np.asarray(
        [float(getattr(cfg, k, 1.0)) for k in frac_names], np.float64
    )
    payload = np.concatenate([seeds, fracs.view(np.int32)])  # [3 + 4] i32
    # traced + guarded collective (obs/dist.py over resilience/retry.py):
    # a peer that died before joining this allgather would otherwise hang
    # EVERY rank forever — collective_deadline_s (or
    # LGBM_TPU_COLLECTIVE_DEADLINE_S) bounds the wait and fails loudly,
    # transient UNAVAILABLE errors retry with backoff attributed to this
    # site (and the fail_collective_once chaos fault injects here).  The
    # tracing wrapper splits barrier wait (straggler time) from the
    # transfer and feeds the per-op collective counters.
    from ..obs import dist
    from ..resilience.retry import collective_deadline_s

    world = jax.process_count()
    gathered = dist.traced_collective(
        lambda: multihost_utils.process_allgather(payload),
        op="all-gather", label="config_sync",
        payload_bytes=int(payload.size) * 4 * world,
        barrier_fn=lambda: multihost_utils.sync_global_devices(
            "lgbm_config_sync"),
        deadline_s=collective_deadline_s(cfg))  # [P, 7] i32
    gathered = np.ascontiguousarray(np.asarray(gathered))
    seed_min = gathered[:, :3].min(axis=0)
    frac_all = gathered[:, 3:].view(np.float64)  # [P, 2]
    frac_min = frac_all.min(axis=0)
    for k, v in zip(seed_names, seed_min):
        if hasattr(cfg, k):
            setattr(cfg, k, int(v))
    for k, v in zip(frac_names, frac_min):
        if hasattr(cfg, k):
            setattr(cfg, k, float(v))

    # structural params must MATCH, not reconcile: a rank training with a
    # different tree shape would diverge at the first collective
    import zlib

    fp_src = "|".join(
        f"{k}={getattr(cfg, k, None)}" for k in (
            "objective", "num_iterations", "learning_rate", "num_leaves_",
            "max_bin", "min_data_in_leaf", "min_sum_hessian_in_leaf",
            "lambda_l1", "lambda_l2", "max_depth", "tree_learner",
            "tree_growth", "boosting_type", "num_class",
        )
    )
    # crc32 is uint32; mask to int31 so the int32 transport is lossless
    fp = np.asarray([zlib.crc32(fp_src.encode()) & 0x7FFFFFFF], np.int32)
    fps = np.asarray(dist.traced_collective(
        lambda: multihost_utils.process_allgather(fp),
        op="all-gather", label="config_fingerprint",
        payload_bytes=4 * world,
        deadline_s=collective_deadline_s(cfg))).ravel()
    if len(set(int(x) for x in fps)) > 1:
        Log.fatal(
            "training config differs across processes "
            f"(fingerprints {sorted(set(int(x) for x in fps))}); every "
            "rank must run with identical structural parameters"
        )


def make_multihost_data_parallel_grower(
    mesh, num_bins: int, max_leaves: int, axis: str = ROW_AXIS,
    growth: str = "leafwise", sorted_hist: bool = False,
    hist_pool: int = 0, record: bool = True,
    collective_deadline: Optional[float] = None,
):
    """Data-parallel grower across processes: each process feeds its
    LOCAL row partition (the per-rank ingest split, io/distributed.py);
    the shard-mapped growth program runs SPMD over the global mesh with
    psum collectives crossing hosts.

    Contract (mirrors the reference's balanced per-rank partition,
    dataset_loader.cpp:500-605): every process must pass the same number
    of LOCAL rows, padded here to a multiple of the local device count
    with bag_mask-0 rows.  Returns the (replicated) tree as host numpy
    and this process's local leaf partition.

    Observability (obs/dist.py): each call times its dispatch and its
    host fetch as ``dist.grow.dispatch`` / ``dist.grow.fetch`` spans
    (host-wall — the fetch span ends AFTER the np.asarray sync, so it
    is real device+transfer time; the dispatch span is trace+enqueue
    wall), and — in a >1-process world — piggybacks a desync sentinel
    on the fetch sync point: a cheap int32[3] fingerprint allgather of
    (step, crc32 of the grown tree's bytes).  Ranks whose trees diverge
    are NAMED within the iteration (`DesyncError`) instead of shipping
    bitwise-divergent models.  ``LGBM_TPU_DESYNC_CHECK=0`` disables,
    ``=N`` checks every N trees.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..obs import dist, telemetry
    from ..resilience.retry import collective_deadline_s

    # caller passes the config's deadline (gbdt does); None falls back
    # to the env override alone
    sentinel = dist.DesyncSentinel(
        deadline_s=collective_deadline_s(None)
        if collective_deadline is None else collective_deadline)
    step_box = [0]  # grow() calls on this rank (the boosting iteration)
    cfg_crc_box = [None]  # config half of the sentinel fingerprint

    sharded = jax.jit(
        data_parallel_sharded(
            mesh, num_bins, max_leaves, axis=axis, growth=growth,
            sorted_hist=sorted_hist, hist_pool=hist_pool, record=record,
        )
    )
    col_s = NamedSharding(mesh, P(None, axis))
    row_s = NamedSharding(mesh, P(axis))

    def grow(bins_T, grad, hess, bag_mask, fmask, nbpf, is_cat, params):
        with telemetry.span("dist.grow.dispatch"):
            bins_T = np.asarray(bins_T)
            grad = np.asarray(grad)
            hess = np.asarray(hess)
            bag_mask = np.asarray(bag_mask)
            n_local = bins_T.shape[1]
            pad = (-n_local) % jax.local_device_count()
            if pad:
                bins_T = np.pad(bins_T, ((0, 0), (0, pad)))
                grad = np.pad(grad, (0, pad))
                hess = np.pad(hess, (0, pad))
                bag_mask = np.pad(bag_mask, (0, pad))  # invisible rows

            mk = jax.make_array_from_process_local_data
            g_bins = mk(col_s, bins_T)
            g_grad = mk(row_s, grad)
            g_hess = mk(row_s, hess)
            g_bag = mk(row_s, bag_mask)
            # replicated small inputs go in as host numpy (identical on
            # every process; jit replicates them without communication)
            tree, leaf_id = sharded(
                g_bins, g_grad, g_hess, g_bag,
                np.asarray(fmask), np.asarray(nbpf), np.asarray(is_cat),
                jax.tree.map(np.asarray, params),
            )
        with telemetry.span("dist.grow.fetch"):
            # tree is replicated -> each process holds a full copy; the
            # np.asarray here is the per-iteration sync point the desync
            # sentinel piggybacks on
            tree = jax.tree.map(
                lambda a: np.asarray(a.addressable_data(0)), tree)
            # leaf_id is row-sharded; stitch this process's shards in order
            shards = sorted(
                leaf_id.addressable_shards,
                key=lambda s: s.index[0].start or 0
            )
            local = np.concatenate(
                [np.asarray(s.data) for s in shards])[:n_local]
        step_box[0] += 1
        if sentinel.should_check(step_box[0]):
            # fingerprint = (structural params crc, crc32 over every
            # tree field's bytes): bitwise tree divergence (the thing
            # the serial-equality dryrun pins offline) AND a rank
            # training under different params are both caught HERE,
            # named, within one iteration
            if cfg_crc_box[0] is None:
                cfg_crc_box[0] = dist.config_crc(
                    jax.tree.map(lambda a: np.asarray(a).tolist(), params))
            fp = dist.state_fingerprint(
                step_box[0], cfg_crc_box[0],
                *(np.asarray(f).tobytes() for f in tree))
            sentinel.verify(step_box[0], fp)
        return tree, local

    return grow
