"""Data-parallel tree learner: rows sharded over the mesh.

TPU-native re-design of DataParallelTreeLearner
(src/treelearner/data_parallel_tree_learner.cpp):

* rows are sharded over the mesh's row axis — the analog of the
  per-machine row partition at load (dataset_loader.cpp:500-605);
* each shard builds local histograms for ALL features, then a single
  `psum_scatter` over the FEATURE axis hands every device its feature
  shard of the GLOBAL histogram — the same reduce-scatter-of-histogram-
  blocks pattern as the reference's recursive-halving ReduceScatter
  (data_parallel_tree_learner.cpp:127-157, network.cpp:99-185), at half
  an allreduce's comm volume.  Each device searches only its own shard
  and the winners meet in an all_gather + deterministic max — the
  reference's Allreduce(SplitInfo, MaxReducer)
  (data_parallel_tree_learner.cpp:192-227);
* the root (Σg, Σh, n) allreduce at tree start
  (data_parallel_tree_learner.cpp:97-125) is the `reduce_fn` psum hook;
* the leaf partition stays fully local to each shard (leaf ids are
  global indices), mirroring the local DataPartition with global leaf
  counts (data_parallel_tree_learner.cpp:229-235).

Per-SPLIT collective budget of the leaf-wise learner (the reference pays
one reduce-scatter + one SplitInfo allreduce per LEVEL):

1. one all_gather of the two children's local positional counts [2]
   (child choice by global sum + tier gates by cross-shard max — both
   derived locally from the gathered vector);
2. one psum_scatter of the smaller child's [F, B, 3] histogram partials;
3. one all_gather of the two children's per-shard best SplitInfos
   (stacked — a single collective for both searches).

Per-device histogram residency shrinks to ``[L, F/D, B, 3]`` — the mesh
is also a histogram-memory shard (cf. HistogramPool,
feature_histogram.hpp:337-481).

Determinism: psum_scatter sums the same D partials as psum (reduction
order may differ from serial by association only), and the SplitInfo
combine reproduces split_info.hpp:98-103 tie-breaks, so parallel trees
match serial trees up to float reduction order.
"""

from __future__ import annotations

import functools
import os as _os

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..compat import shard_map
from ..learners.depthwise import grow_tree_depthwise
from ..learners.hybrid import HYBRID_STOP_FACTOR
from ..learners.serial import grow_tree
from ..obs import telemetry
from ..obs.dist import record_collective_site
from ..ops.histogram import histogram_by_leaf, histogram_feature_major
from ..ops.split import SplitResult, find_best_split
from .mesh import ROW_AXIS, row_padded_grower
from .split_comm import (combine_gathered_split_infos, gather_and_combine,
                         pack_split, unpack_split)


def data_parallel_sharded(
    mesh, num_bins: int, max_leaves: int, axis: str = ROW_AXIS,
    growth: str = "leafwise", sorted_hist: bool = False,
    hist_pool: int = 0, record: bool = True,
):
    """The raw shard-mapped grow fn over ``mesh`` (rows sharded on
    ``axis``).  Callers are responsible for row padding / global-array
    plumbing: use :func:`make_data_parallel_grower` single-host and
    multihost.make_multihost_data_parallel_grower across processes."""
    from ..ops.histogram import select_single_hist_fn

    num_shards = mesh.shape[axis]

    # per-shard kernels: leaf-wise per-split histogram over the gathered
    # smaller child, and the depthwise per-level leaf-sorted variant
    hist_local = select_single_hist_fn(num_bins, sorted_hist)
    if sorted_hist:
        from ..ops.pallas_histogram import make_sorted_hist_fn

        local_level_hist = make_sorted_hist_fn(num_bins)
    else:
        def local_level_hist(bins_T, leaf_id, grad, hess, mask, num_leaves):
            return histogram_by_leaf(
                bins_T, leaf_id, grad, hess, mask,
                num_bins=num_bins, num_leaves=num_leaves,
            )

    def reduce_sum(x):
        return jax.lax.psum(x, axis)

    def shard_body(bins_T, grad, hess, bag_mask, fmask, nbpf, is_cat, params):
        # trace-time retrace counter (obs; see serial.grow_tree)
        telemetry.count("dp_grow_traces")
        F = bins_T.shape[0]
        Fs = -(-F // num_shards)  # feature-shard width of the scattered hist
        pad = Fs * num_shards - F
        fmask_p = jnp.pad(fmask, (0, pad))  # padding: unusable features
        nbpf_p = jnp.pad(nbpf, (0, pad), constant_values=1)
        iscat_p = jnp.pad(is_cat, (0, pad))
        start = jax.lax.axis_index(axis) * Fs

        def local(a):
            return jax.lax.dynamic_slice_in_dim(a, start, Fs, axis=0)

        def offset_feature(r):
            return r._replace(
                feature=jnp.where(r.feature >= 0, r.feature + start, -1)
            )

        if growth in ("depthwise", "hybrid"):
            from ..ops.split import find_best_split_leaves

            def level_hist_scatter(bt, lid, g, h, m, num_leaves):
                # one reduce-scatter per LEVEL of [L, F, B, 3] feature
                # blocks — the reference's per-level ReduceScatter
                # (data_parallel_tree_learner.cpp:127-157) at half an
                # allreduce's bytes; each device keeps [L, F/D, B, 3]
                hl = local_level_hist(bt, lid, g, h, m, num_leaves)
                hl = jnp.pad(hl, ((0, 0), (0, pad), (0, 0), (0, 0)))
                out = jax.lax.psum_scatter(hl, axis, scatter_dimension=1,
                                           tiled=True)
                # trace-time site census (obs/dist.py): op identity +
                # result bytes, once per retrace — the per-op half of
                # the collectives-per-split contract
                record_collective_site(
                    "dp.level_hist_reduce_scatter", "reduce-scatter",
                    out.size * out.dtype.itemsize)
                return out

            def search_leaves_fn(hist, sg, sh, c, can, _fm, _nb, _ic, prm):
                # per-leaf shard search + ONE packed [D, L, 11] combine
                # (the SplitInfo allreduce,
                # data_parallel_tree_learner.cpp:192-227)
                r = find_best_split_leaves(
                    hist, sg, sh, c,
                    local(fmask_p), local(nbpf_p), local(iscat_p),
                    prm.min_data_in_leaf, prm.min_sum_hessian_in_leaf,
                    prm.lambda_l1, prm.lambda_l2, prm.min_gain_to_split,
                    can,
                )
                r = offset_feature(r)
                g2 = jax.lax.all_gather(pack_split(r), axis)  # [D, L, 11]
                record_collective_site(
                    "dp.split_allgather_leaves", "all-gather",
                    g2.size * g2.dtype.itemsize)
                return combine_gathered_split_infos(unpack_split(g2))

            if growth == "depthwise":
                return grow_tree_depthwise(
                    bins_T, grad, hess, bag_mask, fmask, nbpf, is_cat,
                    params,
                    num_bins=num_bins, max_leaves=max_leaves,
                    hist_fn=level_hist_scatter,
                    search_leaves_fn=search_leaves_fn,
                )
        def hist_scatter(bins_arg, g, h, m):
            # local full-feature partials -> reduce-scatter feature blocks:
            # this device leaves owning the GLOBAL histogram of features
            # [start, start+Fs) only (data_parallel_tree_learner.cpp:
            # 127-157)
            hp = hist_local(bins_arg, g, h, m)
            hp = jnp.pad(hp, ((0, pad), (0, 0), (0, 0)))
            out = jax.lax.psum_scatter(hp, axis, scatter_dimension=0,
                                       tiled=True)
            record_collective_site("dp.hist_reduce_scatter",
                                   "reduce-scatter",
                                   out.size * out.dtype.itemsize)
            return out

        def search_local(hist, sg, sh, c, can, prm):
            r = find_best_split(
                hist, sg, sh, c,
                local(fmask_p), local(nbpf_p), local(iscat_p),
                prm.min_data_in_leaf, prm.min_sum_hessian_in_leaf,
                prm.lambda_l1, prm.lambda_l2, prm.min_gain_to_split, can,
            )
            return offset_feature(r)

        def search_fn(hist, sg, sh, c, can, _fm, _nb, _ic, prm):
            # root search: one shard-best SplitInfo per device, one
            # (packed) all_gather + deterministic max
            return gather_and_combine(
                search_local(hist, sg, sh, c, can, prm), axis,
                site="dp.root_split_allgather",
            )

        # the per-split shard search: ONE Pallas launch on TPU (the
        # jnp search compiles to ~60 small fusions, ~1.6 ms/split —
        # round-3 profile), the jnp reference path elsewhere/under f64.
        # The knob is serial.py's import-time _KERN_ENV so a mid-process
        # env flip can't leave DP and serial searches in different modes.
        from ..learners.serial import _KERN_ENV

        use_kernel_search = jax.default_backend() == "tpu" and _KERN_ENV

        def search2_fn(hl, hr, lsg, lsh, lc, rsg, rsh, rc, can,
                       _fm, _nb, _ic, prm):
            # both children's shard-bests ride ONE packed all_gather
            if use_kernel_search and hl.dtype == jnp.float32:
                from ..ops.pallas_search import search2_pallas

                rl, rr = search2_pallas(
                    hl, hr, lsg, lsh, lc, rsg, rsh, rc, can,
                    local(fmask_p), local(nbpf_p), local(iscat_p),
                    prm.min_data_in_leaf, prm.min_sum_hessian_in_leaf,
                    prm.lambda_l1, prm.lambda_l2, prm.min_gain_to_split,
                )
                rl, rr = offset_feature(rl), offset_feature(rr)
            else:
                rl = search_local(hl, lsg, lsh, lc, can, prm)
                rr = search_local(hr, rsg, rsh, rc, can, prm)
            both = jnp.stack([pack_split(rl), pack_split(rr)])  # [2, 11]
            g = jax.lax.all_gather(both, axis)  # [D, 2, 11]
            record_collective_site("dp.split_allgather", "all-gather",
                                   g.size * g.dtype.itemsize)
            w = combine_gathered_split_infos(unpack_split(g))
            return (SplitResult(*[f[0] for f in w]),
                    SplitResult(*[f[1] for f in w]))

        def child_counts_fn(nl, nr):
            # ONE collective for the per-split scalar plumbing: gather the
            # two local counts, then global sums (smaller-child choice)
            # and cross-shard maxes (tier gates) are local reductions
            g = jax.lax.all_gather(jnp.stack([nl, nr]), axis)  # [D, 2]
            record_collective_site("dp.child_counts_allgather",
                                   "all-gather",
                                   g.size * g.dtype.itemsize)
            s = jnp.sum(g, axis=0)
            m = jnp.max(g, axis=0)
            return s[0], s[1], m[0], m[1]

        if growth == "hybrid":
            # sharded hybrid: depthwise phase with the per-level
            # reduce-scatter, then the best-first phase resumes with the
            # same sharded hooks (learners/hybrid.py semantics)
            tree1, leaf1 = grow_tree_depthwise(
                bins_T, grad, hess, bag_mask, fmask, nbpf, is_cat, params,
                num_bins=num_bins, max_leaves=max_leaves,
                hist_fn=level_hist_scatter,
                search_leaves_fn=search_leaves_fn,
                stop_before_budget=HYBRID_STOP_FACTOR,
            )
            return grow_tree(
                bins_T, grad, hess, bag_mask, fmask, nbpf, is_cat, params,
                num_bins=num_bins, max_leaves=max_leaves,
                hist_fn=hist_scatter,
                reduce_fn=reduce_sum,
                search_fn=search_fn,
                search2_fn=search2_fn,
                child_counts_fn=child_counts_fn,
                init_tree=tree1,
                init_leaf_id=leaf1,
                init_hist_fn=level_hist_scatter,
                init_search_fn=search_leaves_fn,
                reduce_max_fn=lambda c: jax.lax.pmax(c, axis),
            )

        return grow_tree(
            bins_T,
            grad,
            hess,
            bag_mask,
            fmask,
            nbpf,
            is_cat,
            params,
            num_bins=num_bins,
            max_leaves=max_leaves,
            hist_fn=hist_scatter,
            reduce_fn=reduce_sum,
            search_fn=search_fn,
            search2_fn=search2_fn,
            child_counts_fn=child_counts_fn,
            hist_pool=hist_pool,
            # the packed-record partition (VERDICT r4 item 1): the
            # parallel learner runs the serial fast path's leaf-sorted
            # record locally; only histogram blocks and SplitInfos
            # cross the mesh
            record_mode=record,
        )

    return shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(None, axis), P(axis), P(axis), P(axis), P(), P(), P(), P()),
        out_specs=(P(), P(axis)),
        check_vma=False,
    )


def make_data_parallel_grower(
    mesh, num_bins: int, max_leaves: int, axis: str = ROW_AXIS,
    growth: str = "leafwise", sorted_hist: bool = False,
    hist_pool: int = 0, record: bool = True,
):
    """Build a grow(bins_T, grad, hess, bag_mask, feature_mask,
    num_bins_per_feature, is_categorical, params) -> (tree, leaf_id)
    callable running the serial growth algorithm SPMD over ``mesh``.

    ``growth="depthwise"`` runs the level-synchronous learner instead:
    per LEVEL, one psum_scatter of [L, F, B, 3] feature blocks + one
    packed SplitInfo all_gather (two collectives per level at half an
    allreduce's histogram bytes — the reference's per-level
    reduce-scatter + SplitInfo allreduce pattern)."""
    sharded = data_parallel_sharded(
        mesh, num_bins, max_leaves, axis=axis, growth=growth,
        sorted_hist=sorted_hist, hist_pool=hist_pool, record=record,
    )
    return row_padded_grower(sharded, mesh.shape[axis])
