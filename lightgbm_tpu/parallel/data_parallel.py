"""Data-parallel tree learner: rows sharded over the mesh.

TPU-native re-design of DataParallelTreeLearner
(src/treelearner/data_parallel_tree_learner.cpp):

* rows are sharded over the mesh's row axis — the analog of the
  per-machine row partition at load (dataset_loader.cpp:500-605);
* each shard builds local histograms for ALL features, then a single
  `psum` replaces the reference's recursive-halving ReduceScatter +
  Bruck Allgather of histogram blocks (data_parallel_tree_learner.cpp:
  127-157, network.cpp:99-185).  Because every device then holds the
  GLOBAL histogram, the best-split argmax is computed redundantly but
  identically on all shards, which also subsumes the reference's
  Allreduce(SplitInfo, MaxReducer) step (data_parallel_tree_learner.cpp:
  192-227) — no candidate exchange is needed at all;
* the root (Σg, Σh, n) allreduce at tree start
  (data_parallel_tree_learner.cpp:97-125) is the `reduce_fn` psum hook;
* the leaf partition stays fully local to each shard (leaf ids are
  global indices), mirroring the local DataPartition with global leaf
  counts (data_parallel_tree_learner.cpp:229-235).

Because psum delivers bit-identical sums on every participant, parallel
trees match serial trees up to float reduction order — the reference's
parallel==serial invariant (split_info.hpp:98-103 tie-break) holds
structurally by construction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..learners.depthwise import grow_tree_depthwise
from ..learners.serial import grow_tree
from ..ops.histogram import histogram_by_leaf, histogram_feature_major
from .mesh import ROW_AXIS, row_padded_grower


def data_parallel_sharded(
    mesh, num_bins: int, max_leaves: int, axis: str = ROW_AXIS,
    growth: str = "leafwise", sorted_hist: bool = False,
):
    """The raw shard-mapped grow fn over ``mesh`` (rows sharded on
    ``axis``).  Callers are responsible for row padding / global-array
    plumbing: use :func:`make_data_parallel_grower` single-host and
    multihost.make_multihost_data_parallel_grower across processes."""
    from ..ops.histogram import select_single_hist_fn

    # per-shard kernels: leaf-wise per-split histogram over the gathered
    # smaller child, and the depthwise per-level leaf-sorted variant
    hist_local = select_single_hist_fn(num_bins, sorted_hist)
    if sorted_hist:
        from ..ops.pallas_histogram import make_sorted_hist_fn

        local_level_hist = make_sorted_hist_fn(num_bins)
    else:
        def local_level_hist(bins_T, leaf_id, grad, hess, mask, num_leaves):
            return histogram_by_leaf(
                bins_T, leaf_id, grad, hess, mask,
                num_bins=num_bins, num_leaves=num_leaves,
            )

    def hist_psum(bins_T, grad, hess, mask):
        return jax.lax.psum(hist_local(bins_T, grad, hess, mask), axis)

    def level_hist_psum(bins_T, leaf_id, grad, hess, mask, num_leaves):
        return jax.lax.psum(
            local_level_hist(bins_T, leaf_id, grad, hess, mask, num_leaves),
            axis,
        )

    def reduce_sum(x):
        return jax.lax.psum(x, axis)

    def reduce_max(x):
        # tier-gate uniformity: local leaf sizes differ per row shard, but
        # the static slice capacity (a lax.cond branch containing psums)
        # must be chosen identically everywhere
        return jax.lax.pmax(x, axis)

    def shard_body(bins_T, grad, hess, bag_mask, fmask, nbpf, is_cat, params):
        if growth == "depthwise":
            return grow_tree_depthwise(
                bins_T, grad, hess, bag_mask, fmask, nbpf, is_cat, params,
                num_bins=num_bins, max_leaves=max_leaves,
                hist_fn=level_hist_psum,
            )
        return grow_tree(
            bins_T,
            grad,
            hess,
            bag_mask,
            fmask,
            nbpf,
            is_cat,
            params,
            num_bins=num_bins,
            max_leaves=max_leaves,
            hist_fn=hist_psum,
            reduce_fn=reduce_sum,
            reduce_max_fn=reduce_max,
        )

    return jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(None, axis), P(axis), P(axis), P(axis), P(), P(), P(), P()),
        out_specs=(P(), P(axis)),
        check_vma=False,
    )


def make_data_parallel_grower(
    mesh, num_bins: int, max_leaves: int, axis: str = ROW_AXIS,
    growth: str = "leafwise", sorted_hist: bool = False,
):
    """Build a grow(bins_T, grad, hess, bag_mask, feature_mask,
    num_bins_per_feature, is_categorical, params) -> (tree, leaf_id)
    callable running the serial growth algorithm SPMD over ``mesh``.

    ``growth="depthwise"`` runs the level-synchronous learner instead:
    the per-level fused histogram is psum'd once per LEVEL (one collective
    per level instead of one per split — even less comm than the
    reference's per-level reduce-scatter)."""
    sharded = data_parallel_sharded(
        mesh, num_bins, max_leaves, axis=axis, growth=growth,
        sorted_hist=sorted_hist,
    )
    return row_padded_grower(sharded, mesh.shape[axis])
