"""Device-mesh helpers.

The reference bootstraps its cluster from a machine-list file + TCP
handshakes (src/network/linkers_socket.cpp:20-61) or MPI_COMM_WORLD.
On TPU the runtime already knows the topology: a 1-D mesh over all
addressable devices is the analog of `num_machines` ranks, and rank
assignment / connection retry logic disappears.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

ROW_AXIS = "row"  # data-parallel axis (rows sharded)
FEATURE_AXIS = "feature"  # feature-parallel axis (split search sharded)


def default_device_count() -> int:
    return len(jax.devices())


def data_mesh(
    num_devices: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_name: str = ROW_AXIS,
) -> Mesh:
    """A 1-D mesh whose single axis shards the row dimension — the
    mesh-shaped analog of the reference's `num_machines` world
    (network.cpp:20-38)."""
    if devices is None:
        devices = jax.devices()
        if num_devices is not None:
            devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def row_padded_grower(sharded_fn, num_shards: int):
    """Wrap a shard-mapped grow fn with row padding so n need not divide
    the mesh evenly.  Padded rows carry bag_mask 0, making them invisible
    to histograms and sums; the leaf partition is trimmed on return."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def grow(bins_T, grad, hess, bag_mask, feature_mask, nbpf, is_cat, params):
        n = bins_T.shape[1]
        pad = (-n) % num_shards
        if pad:
            bins_T = jnp.pad(bins_T, ((0, 0), (0, pad)))
            grad = jnp.pad(grad, (0, pad))
            hess = jnp.pad(hess, (0, pad))
            bag_mask = jnp.pad(bag_mask, (0, pad))
        tree, leaf_id = sharded_fn(
            bins_T, grad, hess, bag_mask, feature_mask, nbpf, is_cat, params
        )
        return tree, leaf_id[:n]

    return grow
