"""Feature-parallel tree learner: data replicated, split search sharded.

TPU-native re-design of FeatureParallelTreeLearner
(src/treelearner/feature_parallel_tree_learner.cpp): every device holds
ALL rows, but builds histograms and searches thresholds only for its
feature shard (the greedy bin-balanced assignment of
feature_parallel_tree_learner.cpp:29-42 becomes a plain contiguous shard
— bins are uniform-width tensors here, so there is nothing to balance).
The global best split is ONE packed `all_gather` of each device's best
SplitInfo + the reference's deterministic max (larger gain, ties to the
smaller feature index — SplitInfo::MaxReducer / operator>,
split_info.hpp:78-104), replacing Network::Allreduce over byte buffers
(feature_parallel_tree_learner.cpp:64-77) — see parallel/split_comm.py.
Every device then performs the identical split locally — no split
broadcast is needed because data is replicated, exactly as in the
reference.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..compat import shard_map
from ..learners.serial import grow_tree
from ..ops.histogram import histogram_feature_major
from ..ops.split import SplitResult, find_best_split
from .split_comm import gather_and_combine


def make_feature_parallel_grower(mesh, num_bins: int, max_leaves: int,
                                 sorted_hist: bool = False,
                                 hist_pool: int = 0):
    axis = mesh.axis_names[0]
    num_shards = mesh.shape[axis]
    from ..ops.histogram import select_single_hist_fn

    local_hist = select_single_hist_fn(num_bins, sorted_hist)

    def shard_body(bins_T, grad, hess, bag_mask, fmask, nbpf, is_cat, params):
        F = bins_T.shape[0]
        Fs = -(-F // num_shards)  # shard width (feature axis, padded)
        pad = Fs * num_shards - F
        bins_p = jnp.pad(bins_T, ((0, pad), (0, 0)))
        fmask_p = jnp.pad(fmask, (0, pad))  # padding: unusable features
        nbpf_p = jnp.pad(nbpf, (0, pad), constant_values=1)
        iscat_p = jnp.pad(is_cat, (0, pad))
        start = jax.lax.axis_index(axis) * Fs

        def local(a):
            return jax.lax.dynamic_slice_in_dim(a, start, Fs, axis=0)

        def hist_fn(bins_arg, g, h, m):
            # local-shard histogram: the per-device share of the search
            # work.  Pad + slice the PASSED matrix (not the closed-over
            # full one): grow_tree may hand us a gathered smaller-child
            # row buffer whose row count differs from n.
            bp = jnp.pad(bins_arg, ((0, pad), (0, 0)))
            return local_hist(local(bp), g, h, m)

        def search_fn(hist, sg, sh, c, can, _fm, _nb, _ic, prm):
            r = find_best_split(
                hist, sg, sh, c,
                local(fmask_p), local(nbpf_p), local(iscat_p),
                prm.min_data_in_leaf, prm.min_sum_hessian_in_leaf,
                prm.lambda_l1, prm.lambda_l2, prm.min_gain_to_split, can,
            )
            r = r._replace(
                feature=jnp.where(r.feature >= 0, r.feature + start, -1)
            )
            return gather_and_combine(r, axis)

        return grow_tree(
            bins_T, grad, hess, bag_mask, fmask, nbpf, is_cat, params,
            num_bins=num_bins, max_leaves=max_leaves,
            hist_fn=hist_fn, search_fn=search_fn, hist_pool=hist_pool,
            record_mode=True,
        )

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), P(), P(), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )

    def grow(bins_T, grad, hess, bag_mask, feature_mask, nbpf, is_cat, params):
        # NOTE: the winning split's partition runs on the full replicated
        # matrix, so grow_tree indexes bins_T with GLOBAL feature ids and
        # the returned tree/leaf partition is replicated on every device.
        return sharded(bins_T, grad, hess, bag_mask, feature_mask, nbpf, is_cat, params)

    return jax.jit(grow)
