"""Always-on runtime telemetry: spans, counters, per-tree reservoirs.

The round-5 regression (BENCH_r05 vs_baseline 0.71) shipped unnoticed
because no training run records where its time goes.  This module is
the runtime half of the fix (jaxlint is the static half): every process
carries a near-zero-overhead telemetry singleton that any entry point
can snapshot into a :class:`~lightgbm_tpu.obs.manifest.RunManifest`.

Design constraints, in order:

* **Near-zero overhead on the hot path.**  A span is two
  ``time.perf_counter()`` calls and two dict operations; a counter is
  one uncontended-lock acquisition and one dict add (the lock arrived
  with the multi-threaded serving tier — see the :class:`Telemetry`
  docstring).  Nothing here touches a device array, forces a sync, or
  allocates per-iteration beyond a float append.  The bound is itself
  an acceptance criterion (``tools/telemetry_overhead.py``, ≤2% at the
  100k driver-like shape, artifact in ``.bench/``).
* **Honesty about async dispatch.**  Host-side span times measure
  *dispatch* wall time, not device time — ``train_one_iter`` returns
  before the chip finishes (the same hazard the jaxlint
  ``wallclock-without-sync`` rule flags).  Spans are therefore labeled
  host-wall; phase-attributed *device* time comes from the profiler
  trace (:mod:`lightgbm_tpu.obs.device_time`), never from host timers.
* **No jax import at module import.**  Tools (benchdiff, jaxlint) read
  telemetry data structures without paying a jax import; the compile
  counter bridges to :mod:`lightgbm_tpu.analysis.recompile` lazily.

Counters maintained by the library itself:

* ``backend_compiles`` — XLA backend compiles (snapshot-time bridge to
  ``analysis/recompile.py``'s process-wide listener; cache hits are 0).
* ``grow_traces`` / ``dp_grow_traces`` — retraces of the serial /
  data-parallel grow program (incremented at Python trace time inside
  the traced body, so each retrace counts exactly once).
* ``host_syncs`` — deliberate device->host materialization points the
  library performs (eval fetches, lagged-stop drains, bench syncs).
* ``collective_ops`` / ``collective_bytes`` — cross-device collectives
  in compiled parallel programs, recorded via :func:`record_collectives`
  (static count from the optimized HLO, promoted from the old
  ``tools/collective_count.py``).

Env: ``LGBM_TPU_TELEMETRY`` = ``on`` (default) | ``off`` | ``json``
(``json`` additionally emits one structured JSON line to stderr when an
entry point calls :func:`emit`).  Read once at import (jit caches do
not key on env — same convention the env-read-at-trace rule enforces);
:func:`set_enabled` is the runtime override the overhead A/B uses.
"""

from __future__ import annotations

import bisect
import json
import re
import sys
import time
from os import environ as _environ
from typing import Dict, List, Optional

from ..analysis import lockcheck

# read once at import — see module docstring
TELEMETRY_MODE = _environ.get("LGBM_TPU_TELEMETRY", "on").strip().lower()

_RESERVOIR_CAP = 4096

# fixed latency buckets (seconds) for Prometheus-style histograms: the
# serving stage clocks span ~0.1 ms (pad on a warm bucket) to seconds
# (a cold dispatch); log-ish spacing keeps the tail resolvable without
# per-request allocation.  STABLE — these boundaries are part of the
# /metrics contract (docs/observability.md), change = new metric name.
DEFAULT_LATENCY_BOUNDS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class SpanStat:
    """Accumulated wall time of one named span (host-wall, see module
    docstring for the async-dispatch caveat)."""

    __slots__ = ("total_s", "count", "min_s", "max_s")

    def __init__(self) -> None:
        self.total_s = 0.0
        self.count = 0
        self.min_s = float("inf")
        self.max_s = 0.0

    def add(self, dt: float) -> None:
        self.total_s += dt
        self.count += 1
        if dt < self.min_s:
            self.min_s = dt
        if dt > self.max_s:
            self.max_s = dt

    def as_dict(self) -> dict:
        return {
            "total_s": round(self.total_s, 6),
            "count": self.count,
            "min_s": round(self.min_s, 6) if self.count else 0.0,
            "max_s": round(self.max_s, 6),
        }


class Reservoir:
    """Sliding window of the most recent ``cap`` samples with p50/p99.

    A ring buffer, not a probabilistic reservoir: per-tree times drift
    (lazy Mosaic compiles early, steady state later), and the question
    the manifest answers is "what does a tree cost NOW", so the window
    deliberately reports the most recent ``cap`` trees.  The total
    sample count is kept so a reader can see how much was windowed out.
    """

    __slots__ = ("cap", "_buf", "_n")

    def __init__(self, cap: int = _RESERVOIR_CAP) -> None:
        self.cap = cap
        self._buf: List[float] = []
        self._n = 0

    def add(self, v: float) -> None:
        if len(self._buf) < self.cap:
            self._buf.append(v)
        else:
            self._buf[self._n % self.cap] = v
        self._n += 1

    def __len__(self) -> int:
        return self._n

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile over the current window (0 if empty)."""
        if not self._buf:
            return 0.0
        s = sorted(self._buf)
        k = max(0, min(len(s) - 1, int(round(p / 100.0 * (len(s) - 1)))))
        return s[k]

    def clone(self) -> "Reservoir":
        """Cheap copy (one list copy) so percentile sorting can happen
        OUTSIDE the telemetry store lock — a /metrics scrape must not
        stall request-path writers for the duration of ~18 sorts."""
        c = Reservoir(self.cap)
        c._buf = list(self._buf)
        c._n = self._n
        return c

    def as_dict(self, include_samples: bool = False) -> dict:
        window = len(self._buf)
        mean = sum(self._buf) / window if window else 0.0
        out = {
            "count": self._n,
            "window": window,
            "mean_s": round(mean, 6),
            "p50_s": round(self.percentile(50), 6),
            "p99_s": round(self.percentile(99), 6),
            "max_s": round(max(self._buf), 6) if window else 0.0,
        }
        if include_samples:
            # the raw window, in insertion order: cross-rank merging
            # (obs/dist.py) concatenates windows and recomputes exact
            # quantiles — averaging per-rank percentiles would be wrong
            # for any skewed distribution
            start = self._n % self.cap if self._n > self.cap else 0
            ordered = self._buf[start:] + self._buf[:start]
            out["samples"] = [round(v, 6) for v in ordered]
        return out


class Histogram:
    """Fixed-bucket histogram (the Prometheus exposition shape).

    Complements :class:`Reservoir`: the reservoir answers "what do the
    most recent requests cost" (sliding window, exact quantiles); the
    histogram is cumulative over the process lifetime and exports as
    ``_bucket{le=...}/_sum/_count`` series a scraper can rate() and
    aggregate across replicas — which windowed quantiles cannot.
    ``observe`` is one bisect + three adds.
    """

    __slots__ = ("bounds", "counts", "total", "sum")

    def __init__(self, bounds=DEFAULT_LATENCY_BOUNDS) -> None:
        self.bounds = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(self.bounds) or not self.bounds:
            raise ValueError(f"histogram bounds must be sorted and "
                             f"non-empty, got {bounds!r}")
        self.counts = [0] * (len(self.bounds) + 1)  # last = +Inf bucket
        self.total = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.total += 1
        self.sum += v

    def as_dict(self) -> dict:
        return {"bounds": list(self.bounds), "counts": list(self.counts),
                "count": self.total, "sum": round(self.sum, 9)}


class _Span:
    """Context manager recording one timed region into a Telemetry."""

    __slots__ = ("_tel", "_name", "_t0")

    def __init__(self, tel: "Telemetry", name: str) -> None:
        self._tel = tel
        self._name = name

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._tel._record_span(self._name, time.perf_counter() - self._t0)


class _NullSpan:
    """Telemetry-off span: enter/exit do nothing at all."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


_NULL_SPAN = _NullSpan()


class Telemetry:
    """Process-wide telemetry store (counters, spans, reservoirs,
    histograms).

    Every mutation takes the one store lock.  This changed with the
    serving observability PR: the training loop is single-threaded (the
    GIL made torn counts a non-issue), but the serving tier increments
    from many request threads at once, where ``d[k] = d.get(k) + n``
    LOSES increments and a ``/v1/stats`` snapshot could see the rows
    counter ahead of the requests counter it rode in with.  An
    uncontended ``threading.Lock`` is tens of nanoseconds — re-proven
    below the noise floor by ``tools/telemetry_overhead.py`` — and in
    exchange :meth:`snapshot` is one consistent cut: everything it
    returns was simultaneously true.  Related adds that must move
    together go through :meth:`count_many` (one acquisition).
    """

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = enabled
        # RLock, not Lock: the preemption path runs flightrec.dump()
        # (which counts) from a SIGNAL HANDLER on the main thread — if
        # the signal interrupted a frame that already holds the store
        # lock, a non-reentrant lock would deadlock the "Ctrl-C twice"
        # abort.  Re-entry can at worst lose the interrupted frame's
        # single increment; a hang needs SIGKILL.
        self._lock = lockcheck.make_rlock("telemetry.store")
        self._counters: Dict[str, float] = {}
        self._spans: Dict[str, SpanStat] = {}
        self._reservoirs: Dict[str, Reservoir] = {}
        self._histograms: Dict[str, Histogram] = {}

    # ------------------------------------------------------------- record
    def span(self, name: str):
        """``with tel.span("bench.timed_loop"): ...`` — host-wall timer."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name)

    def _record_span(self, name: str, dt: float) -> None:
        with self._lock:
            st = self._spans.get(name)
            if st is None:
                st = self._spans.setdefault(name, SpanStat())
            st.add(dt)

    def count(self, name: str, n: float = 1) -> None:
        """Monotonic counter add (no-op when disabled)."""
        if self.enabled:
            with self._lock:
                self._counters[name] = self._counters.get(name, 0) + n

    def count_many(self, adds: Dict[str, float]) -> None:
        """Several counter adds under ONE lock acquisition — for pairs
        that must never be observed half-applied (``serving.requests``
        and ``serving.rows``: a snapshot between two separate adds
        would report traffic whose row count belongs to no request
        count)."""
        if not self.enabled:
            return
        with self._lock:
            for name, n in adds.items():
                self._counters[name] = self._counters.get(name, 0) + n

    def record_value(self, name: str, v: float) -> None:
        """Append one sample to the named reservoir (e.g. per-tree s)."""
        if not self.enabled:
            return
        with self._lock:
            r = self._reservoirs.get(name)
            if r is None:
                r = self._reservoirs.setdefault(name, Reservoir())
            r.add(v)

    def observe(self, name: str, v: float, bounds=None) -> None:
        """One sample into the named fixed-bucket histogram (the
        ``/metrics`` exposition shape; see :class:`Histogram` for why
        this exists next to the reservoirs).  ``bounds`` applies only
        on first touch of a name."""
        if not self.enabled:
            return
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms.setdefault(
                    name, Histogram(bounds or DEFAULT_LATENCY_BOUNDS))
            h.observe(v)

    def _sample_sinks(self, name: str):
        """Get-or-create the (reservoir, histogram) pair a latency
        series feeds.  Caller holds the store lock."""
        r = self._reservoirs.get(name)
        if r is None:
            r = self._reservoirs.setdefault(name, Reservoir())
        h = self._histograms.get(name)
        if h is None:
            h = self._histograms.setdefault(name, Histogram())
        return r, h

    def record_samples(self, samples: Dict[str, float]) -> None:
        """Several latency samples under ONE lock acquisition, each
        feeding its reservoir AND its histogram — the serving scatter
        path records five series per request (four stages + the
        end-to-end), and five-times-two separate acquisitions were the
        dominant tracing cost on the 1-core container (measured by
        ``tools/telemetry_overhead.py --serving``)."""
        if not self.enabled:
            return
        with self._lock:
            for name, v in samples.items():
                r, h = self._sample_sinks(name)
                r.add(v)
                h.observe(v)

    def record_sample_lists(self, samples: Dict[str, List[float]]) -> None:
        """Batch form of :meth:`record_samples`: one lock acquisition
        for a whole coalesced batch's worth of per-request samples —
        the serving dispatcher records once per BATCH, keeping the
        tracing cost on its critical path independent of how many
        requests coalesced."""
        if not self.enabled:
            return
        with self._lock:
            for name, vals in samples.items():
                r, h = self._sample_sinks(name)
                for v in vals:
                    r.add(v)
                    h.observe(v)

    def host_sync(self, n: int = 1) -> None:
        """Record a deliberate device->host materialization point."""
        self.count("host_syncs", n)

    # ------------------------------------------------------------ inspect
    def counter(self, name: str) -> float:
        return self._counters.get(name, 0)

    def reservoir(self, name: str) -> Optional[Reservoir]:
        return self._reservoirs.get(name)

    def span_stat(self, name: str) -> Optional[SpanStat]:
        return self._spans.get(name)

    def histogram(self, name: str) -> Optional[Histogram]:
        return self._histograms.get(name)

    def snapshot(self, include_compiles: bool = True,
                 include_samples: bool = False) -> dict:
        """ONE consistent cut of everything, as plain JSON-able dicts:
        the store lock is held across the whole copy and every writer
        takes the same lock, so no snapshot can observe one counter of
        a related pair updated and the other not (``/v1/stats`` and
        ``/metrics`` both read through here).

        ``backend_compiles`` is bridged in from the analysis subsystem's
        process-wide listener at snapshot time (importing jax only if
        the process already did — the listener installs on first use by
        whoever counts compiles, and a process that never imported jax
        has by definition compiled nothing).
        """
        with self._lock:
            counters = dict(self._counters)
            spans = {k: v.as_dict() for k, v in self._spans.items()}
            # clone, don't as_dict: percentile sorting over up-to-4096
            # samples per reservoir happens outside the lock, so a
            # scrape can't stall every request-path writer meanwhile
            res_clones = {k: v.clone() for k, v in self._reservoirs.items()}
            histograms = {k: v.as_dict() for k, v in self._histograms.items()}
        reservoirs = {k: v.as_dict(include_samples=include_samples)
                      for k, v in res_clones.items()}
        if include_compiles and "jax" in sys.modules:
            try:
                from lightgbm_tpu.analysis.recompile import (
                    backend_compile_count)

                counters["backend_compiles"] = backend_compile_count()
            except Exception:
                pass
        return {"counters": counters, "spans": spans,
                "reservoirs": reservoirs, "histograms": histograms}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._spans.clear()
            self._reservoirs.clear()
            self._histograms.clear()

    def emit(self, stream=None) -> None:
        """One JSON line of the full snapshot (``LGBM_TPU_TELEMETRY=json``
        consumers; also the ``verbose>=2`` structured tail)."""
        stream = sys.stderr if stream is None else stream
        print(json.dumps({"lgbm_tpu_telemetry": self.snapshot()},
                         sort_keys=True),
              file=stream, flush=True)


_TELEMETRY = Telemetry(enabled=TELEMETRY_MODE != "off")


def get_telemetry() -> Telemetry:
    """The process-wide singleton every entry point snapshots."""
    return _TELEMETRY


def set_enabled(flag: bool) -> None:
    """Runtime enable/disable (the overhead A/B measurement switch)."""
    _TELEMETRY.enabled = bool(flag)


def enabled() -> bool:
    return _TELEMETRY.enabled


# module-level conveniences bound to the singleton
def span(name: str):
    return _TELEMETRY.span(name)


def count(name: str, n: float = 1) -> None:
    _TELEMETRY.count(name, n)


def count_many(adds: Dict[str, float]) -> None:
    _TELEMETRY.count_many(adds)


def record_value(name: str, v: float) -> None:
    _TELEMETRY.record_value(name, v)


def observe(name: str, v: float, bounds=None) -> None:
    _TELEMETRY.observe(name, v, bounds=bounds)


def record_samples(samples: Dict[str, float]) -> None:
    _TELEMETRY.record_samples(samples)


def record_sample_lists(samples: Dict[str, List[float]]) -> None:
    _TELEMETRY.record_sample_lists(samples)


def host_sync(n: int = 1) -> None:
    _TELEMETRY.host_sync(n)


def emit_if_json(stream=None) -> None:
    """Emit the snapshot line iff LGBM_TPU_TELEMETRY=json (entry points
    call this unconditionally at the end of a run)."""
    if TELEMETRY_MODE == "json":
        _TELEMETRY.emit(stream)


# ------------------------------------------------------- collectives (HLO)
# Promoted from tools/collective_count.py: static collective count +
# payload bytes of a compiled program's optimized HLO.  The count is per
# compiled module; the while-body computation (executed num_leaves-1
# times per tree) is the per-split budget documented in
# parallel/data_parallel.py.

COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)\b"
)
_SHAPE_RE = re.compile(r"([a-z]+[0-9]+)\[([0-9,]*)\]")
_DT_BYTES = {"f32": 4, "f64": 8, "s32": 4, "u32": 4, "pred": 1, "bf16": 2,
             "s8": 1, "u8": 1, "f16": 2, "s64": 8, "u64": 8, "u16": 2,
             "s16": 2}


def _collective_bytes_of(line: str) -> int:
    """Sum ALL result-shape components: variadic (combined) collectives
    have tuple results like ``(f32[64,32], s32[4]) all-reduce(...)``."""
    lhs = line.split("=", 1)[-1]
    m_op = COLLECTIVE_RE.search(lhs)
    head = lhs[: m_op.start()] if m_op else lhs
    total = 0
    for dt, dims in _SHAPE_RE.findall(head):
        num = 1
        for d in dims.split(","):
            if d:
                num *= int(d)
        total += num * _DT_BYTES.get(dt, 4)
    return total


def collective_stats(hlo_text: str) -> dict:
    """Collective ops in an optimized-HLO dump, per computation.

    Returns ``{"total": N, "payload_bytes": B, "by_op": {...},
    "by_computation": {name: {"ops": {...}, "payload_bytes": B}}}``.
    ``-done`` halves of async pairs are not double-counted.
    """
    blocks: Dict[str, List[str]] = {}
    cur = None
    for line in hlo_text.splitlines():
        if line and not line.startswith(" ") and "{" in line:
            cur = line.split("{")[0].strip().split(" ")[0]
            blocks[cur] = []
        elif cur is not None:
            blocks[cur].append(line)
    by_comp: Dict[str, dict] = {}
    by_op: Dict[str, int] = {}
    total = 0
    payload = 0
    for name, lines in blocks.items():
        counts: Dict[str, int] = {}
        nbytes = 0
        for ln in lines:
            m = COLLECTIVE_RE.search(ln)
            if m and "=" in ln and "-done" not in ln.split("=", 1)[-1][:40]:
                counts[m.group(1)] = counts.get(m.group(1), 0) + 1
                nbytes += _collective_bytes_of(ln)
        if counts:
            by_comp[name] = {"ops": counts, "payload_bytes": nbytes}
            for op, c in counts.items():
                by_op[op] = by_op.get(op, 0) + c
            total += sum(counts.values())
            payload += nbytes
    return {"total": total, "payload_bytes": payload, "by_op": by_op,
            "by_computation": by_comp}


def record_collectives(tag: str, compiled) -> dict:
    """Count collectives in a compiled program (``jax.jit(f).lower(*a)
    .compile()``) and fold them into the telemetry counters
    (``collective_ops`` / ``collective_bytes``).  Returns the stats."""
    stats = collective_stats(compiled.as_text())
    adds = {
        "collective_ops": stats["total"],
        "collective_bytes": stats["payload_bytes"],
        f"collective_ops.{tag}": stats["total"],
    }
    # per-op-kind fold (obs/dist.py convention: the 3-collectives/split
    # contract is checkable per-op, not just as a total)
    for op, c in stats["by_op"].items():
        adds[f"collective_ops.op.{op}"] = \
            adds.get(f"collective_ops.op.{op}", 0) + c
    _TELEMETRY.count_many(adds)
    return stats
