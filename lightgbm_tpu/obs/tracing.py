"""Request tracing: trace ids + per-stage monotonic clocks for serving.

PR 13's only latency signal was one end-to-end ``serving.request_s``
reservoir — when a p99 moves, nothing says whether the time went to
queue wait, pad/copy, device dispatch, or scatter.  This module is the
carrier that fixes it: a :class:`TraceContext` is minted at the edge
(``MicroBatchQueue.submit`` or HTTP ingress — the ``X-LGBM-Trace-Id``
header is honored and echoed), rides the request through coalescing and
dispatch, and accumulates one duration per stage:

==============  =======================================================
stage           what it covers
==============  =======================================================
``queue_wait_s``  submit() → the dispatcher takes the batch
``pad_s``         host-side bucket pad/copy + device transfer handoff
``device_s``      jitted dispatch + device wait + result fetch
``scatter_s``     everything after the fetch: f64 transform, per-row
                  slicing, future resolution (measured as the residual
                  of real timestamps, so the four stages sum EXACTLY to
                  the end-to-end latency — the tier-1 pin)
==============  =======================================================

``pad_s``/``device_s`` are per-*batch* measurements shared by every
request the batch coalesced — that is the honest attribution: a
coalesced request really did pay the whole batch's pad and dispatch
wall, that being the price of riding along.  Each finished request
feeds every stage into its own labeled telemetry reservoir
(``serving.stage.<stage>``, p50/p99 in manifests and bench artifacts)
AND fixed-bucket histogram (the ``/metrics`` exposition).

Env: ``LGBM_TPU_TRACING`` = ``on`` (default) | ``off``, read once at
import (the repo's env-knob convention); :func:`set_enabled` is the
runtime switch the tracing-overhead A/B (``tools/telemetry_overhead.py
--serving``) flips.  Off means: no ids minted, no stage clocks read —
the ``PredictionResult`` then carries an empty trace id and no stages.

No jax import; nothing here touches a device array.
"""

from __future__ import annotations

import itertools
import re
import time
import uuid
from os import environ as _environ
from typing import Dict, Optional

from . import telemetry

# read once at import — see module docstring
TRACING_MODE = _environ.get("LGBM_TPU_TRACING", "on").strip().lower()

_ENABLED = TRACING_MODE != "off"

# trace ids are a random per-process prefix + a monotonic counter (GIL
# makes next() atomic): globally unique in practice, and ~10x cheaper
# than a uuid4 per request — minting is on the submit hot path and the
# difference was visible in the tracing-overhead A/B on one core
_ID_PREFIX = uuid.uuid4().hex[:16]
_ID_SEQ = itertools.count()

# the stage names, in pipeline order (the bench artifact + docs contract)
STAGES = ("queue_wait_s", "pad_s", "device_s", "scatter_s")

# reservoir/histogram prefix: serving.stage.queue_wait_s etc.
STAGE_METRIC_PREFIX = "serving.stage."

# inbound X-LGBM-Trace-Id values are caller-controlled: accept a sane
# charset/length, mint a fresh id otherwise (never 400 a predict over
# a decorative header).  fullmatch, not match-with-$: '$' would accept
# a trailing newline
_TRACE_ID_RE = re.compile(r"[A-Za-z0-9._\-]{1,128}")


def valid_trace_id(tid) -> bool:
    return isinstance(tid, str) and bool(_TRACE_ID_RE.fullmatch(tid))


def set_enabled(flag: bool) -> None:
    """Runtime tracing switch (the overhead A/B measurement hook)."""
    global _ENABLED
    _ENABLED = bool(flag)


def enabled() -> bool:
    return _ENABLED


def new_trace_id() -> str:
    return f"{_ID_PREFIX}{next(_ID_SEQ) & 0xFFFFFFFFFFFFFFFF:016x}"


class StageClock:
    """Mutable per-stage duration accumulator.  The engine receives one
    per dispatch (``clock=``) and adds its pad/device measurements;
    stage keys accumulate, so a row-chunked oversize request sums its
    chunks' stages."""

    __slots__ = ("stages",)

    def __init__(self) -> None:
        self.stages: Dict[str, float] = {}

    def add(self, stage: str, seconds: float) -> None:
        self.stages[stage] = self.stages.get(stage, 0.0) + seconds

    def get(self, stage: str) -> float:
        return self.stages.get(stage, 0.0)


class TraceContext(StageClock):
    """One request's identity + stage clock (see module docstring)."""

    __slots__ = ("trace_id", "t_origin")

    def __init__(self, trace_id: Optional[str] = None) -> None:
        super().__init__()
        self.trace_id = (trace_id if trace_id and valid_trace_id(trace_id)
                         else new_trace_id())
        self.t_origin = time.perf_counter()


def mint(trace_id: Optional[str] = None) -> Optional[TraceContext]:
    """A fresh TraceContext, or None when tracing is off (callers
    guard stage work on the context's existence, so off really costs
    nothing)."""
    if not _ENABLED:
        return None
    return TraceContext(trace_id)


def record_stages(trace: StageClock,
                  extra: Optional[Dict[str, float]] = None) -> None:
    """Feed one finished request's stages into the labeled telemetry
    reservoirs (manifest/bench p50-p99) and histograms (/metrics), in
    ONE store-lock acquisition.  ``extra`` rides along (the scatter
    path adds the end-to-end ``serving.request_s`` sample)."""
    samples = {STAGE_METRIC_PREFIX + k: v
               for k, v in trace.stages.items()}
    if extra:
        samples.update(extra)
    telemetry.record_samples(samples)
