"""Flight recorder: the last N structured events, dumped on the way down.

A preempted serving replica (PR 11's exit-75 path) or a NaN-poisoned
training run dies with nothing but whatever happened to be on stderr.
This module keeps a lock-cheap ring of the most recent events —
dispatches, hot-swaps, checkpoint writes, injected faults, guard trips,
signals — and, when something terminal happens, dumps the ring
atomically (``resilience.atomic``, with a ``.sha256`` sidecar) to
``<dir>/flightrec_r<rank>_<pid>.json``.  The dump's TAIL is the triggering
event: the writer records the trigger and then dumps, so a post-mortem
reads the file backwards from the cause.

Recording cost: one dict build + one ``deque.append`` — no lock on the
record path.  The ring is a ``collections.deque(maxlen=cap)``: append
and eviction are one atomic operation under the GIL, so concurrent
recorders can interleave (events are re-sorted by ``seq`` on read) but
can never grow the buffer past the cap or corrupt it — exactly the
capped-buffer discipline the jaxlint ``unbounded-event-buffer`` rule
exists to enforce on everyone else.  The dump lock only serializes
dumps (and the rare capacity changes) against each other.

Dump triggers (wired by this PR):

* cli training — SIGTERM/SIGINT preemption (after the checkpoint), the
  second-signal immediate abort, and a :class:`NonFiniteError` escape;
* serving — a dispatcher-thread crash (the "unhandled dispatch
  failure" that should never happen) and a refused hot-swap.

The dump directory: ``LGBM_TPU_FLIGHTREC_DIR`` (read at import) wins;
otherwise each entry point calls :func:`configure_dir` with a sensible
sibling (next to ``output_model`` for training, next to the served
model for ``task=serve``).  When neither is set, :func:`dump` is a
no-op returning ``None`` — observability never surprises a library
embedder with stray files.

Retrieval workflow and format: docs/observability.md.
"""

from __future__ import annotations

import collections
import itertools
import os
import time
from typing import Deque, Dict, List, Optional

from ..analysis import lockcheck

SCHEMA = "lightgbm-tpu/flightrec/v1"

DEFAULT_CAP = 256

# read once at import (repo convention for behavior knobs)
_ENV_DIR = os.environ.get("LGBM_TPU_FLIGHTREC_DIR", "")
try:
    _ENV_CAP = int(os.environ.get("LGBM_TPU_FLIGHTREC_CAP",
                                  str(DEFAULT_CAP)))
except ValueError:
    # a malformed knob must not make the whole package unimportable
    _ENV_CAP = DEFAULT_CAP

# the ring: append + oldest-eviction is ONE atomic deque operation, so
# concurrent recorders cannot grow it past the cap (see module docstring)
_EVENTS: Deque[dict] = collections.deque(maxlen=max(1, _ENV_CAP))
# seq via itertools.count: next() is atomic under the GIL, so ids stay
# unique and contiguous across threads
_SEQ = itertools.count()
_STATE: Dict[str, object] = {"dir": _ENV_DIR, "rank": None}
# RLock, not Lock: dump() runs from signal handlers (checkpoint's
# second-signal abort path), and a signal delivered while the main
# thread is mid-dump would re-enter a plain Lock and self-deadlock —
# the same hazard the telemetry store RLock exists for (jaxlint
# signal-unsafe-lock)
_DUMP_LOCK = lockcheck.make_rlock("flightrec.dump")


def set_rank(rank: Optional[int]) -> None:
    """Explicit rank override for the dump filename (tests/chaos
    simulate multi-rank worlds in one process).  ``None`` restores
    lazy auto-detection."""
    _STATE["rank"] = rank


def _resolve_rank() -> int:
    """The rank baked into the dump filename.  The explicit override
    wins; otherwise delegate to the ONE lazy resolution chain in
    obs/dist.py (jax-if-already-imported -> launcher env -> 0).
    Guarded: this can run in a signal handler on the way down, and a
    rank-resolution failure must never cost the post-mortem."""
    if _STATE.get("rank") is not None:
        return int(_STATE["rank"])  # type: ignore[arg-type]
    try:
        from .dist import process_index

        return process_index()
    except Exception:  # noqa: BLE001
        return 0


def record(kind: str, **fields) -> None:
    """Append one structured event to the ring.  ``kind`` is a short
    snake_case tag; ``fields`` must be JSON-able scalars/strings."""
    ev = {"seq": next(_SEQ), "t_mono": round(time.perf_counter(), 6),
          "unix": round(time.time(), 3), "kind": kind}
    if fields:
        ev.update(fields)
    _EVENTS.append(ev)


def events() -> List[dict]:
    """Chronological copy of the ring's current contents.  Concurrent
    recorders may append out of seq order (mint-then-append is two
    steps); sorting by seq restores the true timeline.  A concurrent
    append invalidates a live deque iterator (RuntimeError), so the
    copy retries — the record rate is per-batch/per-incident, so a
    clean window is always near (and losing the post-mortem to a torn
    copy would defeat the module)."""
    buf: List[dict] = []
    for _ in range(64):
        try:
            buf = list(_EVENTS)
            break
        except RuntimeError:  # deque mutated during iteration
            continue
    else:
        # pathological write storm: element-index reads tolerate
        # concurrent appends (a best-effort partial copy still beats
        # losing the post-mortem)
        for i in range(len(_EVENTS)):
            try:
                buf.append(_EVENTS[i])
            except IndexError:
                break
    return sorted(buf, key=lambda e: e["seq"])


def dropped() -> int:
    """Events that have aged out of the ring (seqs are contiguous, so
    total-recorded minus retained is exact up to a concurrent append)."""
    buf = events()
    if not buf:
        return 0
    return max(0, buf[-1]["seq"] + 1 - len(buf))


def configure_dir(fallback: str) -> str:
    """Entry-point wiring: the env override wins, else ``fallback``.
    Called per run (cli train / serve), so a long-lived test process
    follows each run's artifact directory."""
    d = _ENV_DIR or fallback
    _STATE["dir"] = d
    return d


def set_dump_dir(d: str) -> None:
    """Explicit override (chaos scenarios, tests)."""
    _STATE["dir"] = d


def dump_dir() -> str:
    return str(_STATE["dir"] or "")


def set_capacity(cap: int) -> None:
    """Resize the ring (tests).  Clears it and restarts the seq."""
    global _EVENTS, _SEQ
    if cap < 1:
        raise ValueError(f"flight recorder cap must be >= 1, got {cap}")
    with _DUMP_LOCK:
        _EVENTS = collections.deque(maxlen=int(cap))
        _SEQ = itertools.count()


def reset() -> None:
    global _SEQ
    with _DUMP_LOCK:
        _EVENTS.clear()
        _SEQ = itertools.count()


def dump_path(directory: Optional[str] = None) -> Optional[str]:
    """Rank-tagged dump location: ``flightrec_r<rank>_<pid>.json``.
    On a multi-rank run every rank dumps into the SAME directory
    (shared filesystem or a gathered scratch dir), so the filename must
    carry the rank — pids alone can collide across hosts, and a
    post-mortem that cannot say which rank's ring it reads is useless
    for desync/straggler attribution."""
    d = directory or dump_dir()
    if not d:
        return None
    return os.path.join(
        d, f"flightrec_r{_resolve_rank()}_{os.getpid()}.json")


def dump(reason: str = "", directory: Optional[str] = None
         ) -> Optional[str]:
    """Write the ring to ``<dir>/flightrec_r<rank>_<pid>.json``
    atomically with
    a checksum sidecar.  Returns the path, or None when no directory is
    configured.  NEVER raises — this runs on the way down (signal
    handlers, terminal excepts), and the dump failing must not mask the
    original failure."""
    path = dump_path(directory)
    if path is None:
        return None
    try:
        with _DUMP_LOCK:
            payload = {
                "schema": SCHEMA,
                "pid": os.getpid(),
                "rank": _resolve_rank(),
                "created_unix": round(time.time(), 3),
                "reason": reason,
                "dropped": dropped(),
                "events": events(),
            }
        from ..resilience.atomic import atomic_write_json

        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        atomic_write_json(path, payload, checksum=True)
        from . import telemetry

        telemetry.count("flightrec.dumps")
        return path
    except Exception as e:  # noqa: BLE001 — last-gasp writer, see docstring
        try:
            from ..log import Log

            Log.warning(f"flight-recorder dump to {path} failed: "
                        f"{type(e).__name__}: {e}")
        except Exception:  # noqa: BLE001
            pass
        return None
