"""Self-describing run manifests.

Every BENCH/`.bench/*.json` number becomes evidence instead of prose:
each bench entry point (``bench.py``, the ``cli.py`` train task,
``tools/northstar_run.py``) writes a ``RunManifest`` next to its result
artifact recording *what ran* (git sha, dirty flag, jax/backend/device,
config fingerprint, env knobs), *how it warmed up* (warm-up iteration
count, discarded warm trees, compile-stability), *what it counted*
(telemetry counters incl. backend compiles, collectives), and *where
the time went* (host-wall spans, phase breakdown, per-tree p50/p99).

The round-5 failure this kills: a 2x regression shipped because the
committed bench row said only "0.4442 s/tree" — nothing recorded that
the run carried lazy compiles, which commit it measured, or which phase
grew.  A manifest makes the next BENCH row diffable by
``tools/benchdiff.py`` instead of by archaeology.

Schema versioned as ``lightgbm-tpu/run-manifest/v1``; `validate`
pins the required keys so the round-trip is a tier-1 contract.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import platform as _platform
import subprocess
import sys
import time
from typing import Any, Dict, Optional

from .telemetry import get_telemetry

SCHEMA = "lightgbm-tpu/run-manifest/v1"

# env knobs worth recording: anything that changes what gets traced,
# compiled, or measured
_KNOB_PREFIXES = ("LGBM_TPU_", "BENCH_", "NS_", "JAX_PLATFORMS",
                  "XLA_FLAGS", "JAX_ENABLE_X64")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REQUIRED_KEYS = ("schema", "entry", "created_unix", "git", "runtime",
                 "config_fingerprint", "knobs", "warmup", "telemetry",
                 "phases", "per_tree", "result")


def _git_info() -> dict:
    """Best-effort git sha + dirty flag (a manifest from an exported
    tarball still validates — sha is then null)."""
    out = {"sha": None, "dirty": None}
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=_REPO_ROOT, timeout=10,
            capture_output=True, text=True)
        if sha.returncode == 0:
            out["sha"] = sha.stdout.strip()
        st = subprocess.run(
            ["git", "status", "--porcelain"], cwd=_REPO_ROOT, timeout=10,
            capture_output=True, text=True)
        if st.returncode == 0:
            out["dirty"] = bool(st.stdout.strip())
    except Exception:
        pass
    return out


def _runtime_info() -> dict:
    """jax / backend / device identity.  Lazy and guarded: collecting a
    manifest must never initialize a backend the run didn't already use
    (jax.devices() on a dead TPU tunnel HANGS — bench.py's probe
    lesson), so devices are read only when jax is already imported."""
    info: Dict[str, Any] = {
        "python": sys.version.split()[0],
        "platform": _platform.platform(),
    }
    if "jax" not in sys.modules:
        return info
    try:
        import jax

        info["jax"] = jax.__version__
        try:
            import jaxlib

            info["jaxlib"] = jaxlib.__version__
        except Exception:
            pass
        devs = jax.devices()
        info["backend"] = devs[0].platform
        info["device_kind"] = getattr(devs[0], "device_kind", None)
        info["device_count"] = len(devs)
    except Exception as e:
        info["jax_error"] = f"{type(e).__name__}: {str(e)[:120]}"
    return info


def _knobs() -> dict:
    return {k: v for k, v in sorted(os.environ.items())
            if k.startswith(_KNOB_PREFIXES)}


def config_fingerprint(config: Any) -> Optional[str]:
    """Stable sha256 over the run configuration (a Config object, a
    dict, or anything with ``__dict__``).  Two runs with the same
    fingerprint trained the same program shape — the precondition for a
    benchdiff comparison to be apples-to-apples."""
    if config is None:
        return None
    if dataclasses.is_dataclass(config) and not isinstance(config, type):
        d = dataclasses.asdict(config)
    elif isinstance(config, dict):
        d = config
    elif hasattr(config, "__dict__"):
        d = vars(config)
    else:
        d = {"repr": repr(config)}
    blob = json.dumps(
        {str(k): repr(v) for k, v in sorted(d.items(), key=lambda kv: str(kv[0]))},
        sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


@dataclasses.dataclass
class RunManifest:
    """One run's self-description; see module docstring for the fields'
    purpose.  ``telemetry`` is a full snapshot (counters/spans/
    reservoirs); ``phases`` is phase -> seconds; ``per_tree`` is the
    p50/p99 reservoir summary of the timed trees."""

    entry: str
    created_unix: float
    git: dict
    runtime: dict
    config_fingerprint: Optional[str]
    knobs: dict
    warmup: dict
    telemetry: dict
    phases: dict
    per_tree: dict
    result: dict
    extra: dict = dataclasses.field(default_factory=dict)
    # multi-rank runs (obs/dist.py): rank 0 writes the ONE manifest,
    # carrying every rank's identity + load-bearing numbers (device,
    # compiles, span seconds, collective wait/transfer).  Empty on
    # single-process runs; optional in v1 (validate does not require
    # it), so every existing manifest still loads.
    ranks: list = dataclasses.field(default_factory=list)
    # device-memory section beside phases{} (obs/memory.py:
    # manifest_memory_section()): hbm gauges, boundary watermarks,
    # owner-tagged census summary.  Optional in v1 like ``ranks``.
    memory: dict = dataclasses.field(default_factory=dict)
    schema: str = SCHEMA

    @classmethod
    def collect(cls, entry: str, config: Any = None,
                result: Optional[dict] = None,
                phases: Optional[dict] = None,
                warmup: Optional[dict] = None,
                per_tree_reservoir: str = "tree_s",
                extra: Optional[dict] = None,
                ranks: Optional[list] = None,
                memory: Optional[dict] = None) -> "RunManifest":
        """Gather everything the process knows right now.  ``entry`` is
        the entry point name ("bench.py", "cli.train", "northstar")."""
        tel = get_telemetry()
        snap = tel.snapshot()
        res = tel.reservoir(per_tree_reservoir)
        return cls(
            entry=entry,
            created_unix=round(time.time(), 3),
            git=_git_info(),
            runtime=_runtime_info(),
            config_fingerprint=config_fingerprint(config),
            knobs=_knobs(),
            warmup=dict(warmup or {}),
            telemetry=snap,
            phases=dict(phases or {}),
            per_tree=res.as_dict() if res is not None else {},
            result=dict(result or {}),
            extra=dict(extra or {}),
            ranks=list(ranks or []),
            memory=dict(memory or {}),
        )

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "RunManifest":
        validate(d)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    def write(self, path: str) -> str:
        # shared crash-safe writer (resilience/atomic.py): tmp + fsync +
        # rename — a crash mid-write must not leave a half manifest
        # shadowing a real result artifact
        from ..resilience.atomic import atomic_write_json

        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        return atomic_write_json(path, self.to_dict())

    @classmethod
    def load(cls, path: str) -> "RunManifest":
        with open(path) as fh:
            return cls.from_dict(json.load(fh))


def validate(d: dict) -> None:
    """Raise ValueError when a manifest dict is not v1-shaped."""
    missing = [k for k in REQUIRED_KEYS if k not in d]
    if missing:
        raise ValueError(f"manifest missing keys: {missing}")
    if d["schema"] != SCHEMA:
        raise ValueError(f"unknown manifest schema {d['schema']!r}")


def manifest_path(artifact_path: str) -> str:
    """Canonical manifest location for a result artifact:
    ``foo.json`` -> ``foo.manifest.json`` (sibling, self-pairing)."""
    base, ext = os.path.splitext(artifact_path)
    if ext == ".json":
        return base + ".manifest.json"
    return artifact_path + ".manifest.json"
