"""Prometheus text exposition of the telemetry snapshot.

``GET /metrics`` on the serving server renders through here; the same
function serves any embedder that wants to scrape a training process.
Pure string work over one consistent :meth:`Telemetry.snapshot` — no
jax import, no device touch, no extra locking (the snapshot is already
one cut).

Name scheme — STABLE: these names are the scrape-dashboard and
benchdiff-adjacent contract (docs/observability.md); renaming one is a
breaking change to be called out like a schema bump.

===============  =====================================================
telemetry kind   exported as
===============  =====================================================
counter ``x.y``  ``lgbm_x_y_total`` (TYPE counter)
span ``x``       ``lgbm_x_seconds_total`` + ``lgbm_x_calls_total``
reservoir ``x``  TYPE summary ``lgbm_x_window{quantile="0.5"|"0.99"}``
                 + ``lgbm_x_window_count`` — quantiles over the
                 SLIDING window (recent behavior), total count for
                 scale; suffixed ``_window`` so it can never collide
                 with the histogram series of the same telemetry name
histogram ``x``  TYPE histogram ``lgbm_x_bucket{le="..."}`` cumulative,
                 ``lgbm_x_sum``, ``lgbm_x_count`` — lifetime-cumulative
                 fixed buckets, the series a scraper can rate() and
                 aggregate across replicas
gauge            caller-provided (live values like queue depth that a
                 snapshot cannot know), TYPE gauge, name passed as-is
===============  =====================================================

Non-alphanumeric characters in telemetry names map to ``_``
(``serving.request_s`` -> ``lgbm_serving_request_s``).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple, Union

_SAN_RE = re.compile(r"[^a-zA-Z0-9_]")

GaugeValue = Union[float, int, Tuple[Union[float, int], str]]


def sanitize(name: str) -> str:
    """Telemetry name -> Prometheus metric-name stem (``lgbm_`` prefix,
    non-alphanumerics to underscores)."""
    san = _SAN_RE.sub("_", name.strip())
    if not san or not (san[0].isalpha() or san[0] == "_"):
        san = "_" + san
    return "lgbm_" + san


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    """Canonical sample value: integers without a trailing ``.0`` (the
    common case for counters), repr-round-trip floats otherwise."""
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _header(out: List[str], name: str, mtype: str, help_text: str) -> None:
    out.append(f"# HELP {name} {_escape_help(help_text)}")
    out.append(f"# TYPE {name} {mtype}")


def render_prometheus(snapshot: dict,
                      gauges: Optional[Dict[str, GaugeValue]] = None
                      ) -> str:
    """Render one telemetry snapshot (``Telemetry.snapshot()`` shape)
    as Prometheus text exposition format (version 0.0.4).  ``gauges``
    maps full metric names to ``value`` or ``(value, help)`` for live
    values the snapshot cannot carry (queue depth, swap age)."""
    out: List[str] = []

    for name, (value, help_text) in sorted(
            (k, v if isinstance(v, tuple) else (v, k))
            for k, v in (gauges or {}).items()):
        _header(out, name, "gauge", help_text)
        out.append(f"{name} {_fmt(value)}")

    for name, v in sorted((snapshot.get("counters") or {}).items()):
        metric = sanitize(name) + "_total"
        _header(out, metric, "counter", f"telemetry counter {name}")
        out.append(f"{metric} {_fmt(v)}")

    for name, st in sorted((snapshot.get("spans") or {}).items()):
        stem = sanitize(name)
        _header(out, stem + "_seconds_total", "counter",
                f"accumulated host-wall seconds of span {name}")
        out.append(f"{stem}_seconds_total {_fmt(st.get('total_s', 0.0))}")
        _header(out, stem + "_calls_total", "counter",
                f"completions of span {name}")
        out.append(f"{stem}_calls_total {_fmt(st.get('count', 0))}")

    for name, r in sorted((snapshot.get("reservoirs") or {}).items()):
        metric = sanitize(name) + "_window"
        _header(out, metric, "summary",
                f"sliding-window quantiles of reservoir {name} "
                f"(window={r.get('window', 0)})")
        out.append(f'{metric}{{quantile="0.5"}} '
                   f"{_fmt(r.get('p50_s', 0.0))}")
        out.append(f'{metric}{{quantile="0.99"}} '
                   f"{_fmt(r.get('p99_s', 0.0))}")
        out.append(f"{metric}_count {_fmt(r.get('count', 0))}")

    for name, h in sorted((snapshot.get("histograms") or {}).items()):
        metric = sanitize(name)
        _header(out, metric, "histogram",
                f"fixed-bucket histogram of {name} (seconds)")
        bounds = h.get("bounds") or []
        counts = h.get("counts") or []
        cum = 0
        for le, c in zip(bounds, counts):
            cum += int(c)
            out.append(f'{metric}_bucket{{le="{_fmt(le)}"}} {cum}')
        total = int(h.get("count", 0))
        out.append(f'{metric}_bucket{{le="+Inf"}} {total}')
        out.append(f"{metric}_sum {_fmt(h.get('sum', 0.0))}")
        out.append(f"{metric}_count {total}")

    return "\n".join(out) + "\n"


CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
