"""Phase-attributed device time for the grow loop.

Host timers cannot see inside the jitted ``fori_loop`` — by the time
``train_one_iter`` returns, the chip may not even have started, and
every split of every leaf runs inside one compiled program.  Attribution
therefore comes from two cooperating halves:

1. **Scope annotations at trace time** (:func:`phase_scope`): the hot
   ops (``ops/record.py``, ``ops/pallas_histogram.py``,
   ``ops/histogram.py``, ``ops/split.py``, ``ops/predict_matmul.py``,
   the post-grow update in ``models/gbdt.py``) wrap their lowered
   computations in ``jax.named_scope`` so every XLA op's metadata
   carries an ``lgbm.<phase>`` path that survives fusion into the
   profiler trace's event names/args.  ``jax.named_scope`` costs a name
   stack push at *trace* time and literally nothing at run time, so the
   always-on telemetry constraint holds.
2. **Trace bucketing at read time** (:func:`bucket_events`,
   :func:`phase_breakdown_from_trace`): parse a ``jax.profiler`` trace
   (chrome-trace JSON, the format ``jax.profiler.trace`` writes under
   ``<dir>/plugins/profile/<run>/*.trace.json.gz``) and bucket complete
   events into the four grow-loop phases — histogram / split-search /
   partition / leaf-update — plus predict, falling back to kernel-name
   patterns for ops that lost their scope path in fusion naming
   (promotes the ad-hoc breakdown logic of ``tools/tpu_breakdown.py``
   into the library).

Capture is opt-in (``with trace_phases(dir) as result: ...`` or the
``LGBM_TPU_TRACE=<dir>`` env consumed by bench.py): running the
profiler is NOT near-zero-overhead, so the always-on layer records only
scopes and counters, and a trace is taken when someone asks where the
device time went.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Dict, Iterable, List, Optional

import jax

# The four grow-loop phases (plus predict for the inference path and
# the unattributed remainder).  Keys are the manifest schema.
PHASES = ("histogram", "split-search", "partition", "leaf-update",
          "predict")

# named_scope path -> phase.  The split-step mega kernel fuses child
# histogram accumulation INTO the partition pass (ops/record.py); its
# device time is bucketed as partition because the row-routing work,
# not the binning math, dominated it (the round-5 one-hot profile —
# ~85% of device FLOPs; the prefix-sum routing default exists to close
# exactly that gap, and keeping the bucket stable lets benchdiff
# compare partition share across the routing change).
SCOPE_TO_PHASE: Dict[str, str] = {
    "lgbm.histogram": "histogram",
    "lgbm.split_search": "split-search",
    "lgbm.partition": "partition",
    "lgbm.split_step": "partition",
    "lgbm.leaf_update": "leaf-update",
    "lgbm.predict": "predict",
}

# kernel-name fallbacks, first match wins — for events whose fusion
# name kept the op stem but lost the scope path
_KERNEL_PATTERNS = (
    (re.compile(r"hist", re.I), "histogram"),
    (re.compile(r"split_step|place|compact|partition|route|write_window"
                r"|compress_half|lane_cumsum", re.I), "partition"),
    (re.compile(r"best_split|search|gain", re.I), "split-search"),
    (re.compile(r"post_grow|leaf_value|shrink", re.I), "leaf-update"),
    (re.compile(r"predict|ensemble|path_table|tree_hit", re.I), "predict"),
)


def phase_scope(phase: str):
    """Trace-time scope for a grow-loop phase: ops wrap their traced
    bodies in ``with phase_scope("histogram"): ...`` (or use it as a
    decorator under the ``jax.jit`` one) so XLA op metadata — and thus
    profiler event names — carries ``lgbm.<phase>``.  Zero run-time
    cost: it only pushes the tracing name stack.  Dashes normalize to
    underscores so scope names match :data:`SCOPE_TO_PHASE` keys."""
    return jax.named_scope("lgbm." + phase.replace("-", "_"))


def host_annotation(name: str):
    """Host-side profiler annotation (``jax.profiler.TraceAnnotation``)
    for eager regions — shows up as a TraceMe on the host track.  Used
    around host phases (binning, eval) when a trace is being captured;
    unlike :func:`phase_scope` it has a (tiny) run-time cost, so call
    sites keep it out of per-split paths."""
    return jax.profiler.TraceAnnotation(name)


def classify_event(name: str, long_name: str = "") -> Optional[str]:
    """Phase for one trace event, or None when unattributable."""
    hay = f"{name} {long_name}"
    for scope, phase in SCOPE_TO_PHASE.items():
        if scope in hay:
            return phase
    for pat, phase in _KERNEL_PATTERNS:
        if pat.search(hay):
            return phase
    return None


def _event_long_name(ev: dict) -> str:
    args = ev.get("args")
    if not isinstance(args, dict):
        return ""
    return " ".join(
        str(args.get(k, "")) for k in ("long_name", "tf_op", "hlo_op",
                                       "name", "hlo_module"))


def _is_xla_event(ev: dict) -> bool:
    """Does this event describe XLA/device work (vs a host Python
    TraceMe)?  XLA-emitted events carry op args; host TraceMes
    ('$builtins isinstance', 'TfrtCpuExecutable::Execute', ...) don't."""
    args = ev.get("args")
    if isinstance(args, dict) and any(
            k in args for k in ("hlo_op", "hlo_module", "tf_op",
                                "long_name")):
        return True
    return False


def bucket_events(events: Iterable[dict]) -> Dict[str, float]:
    """Bucket chrome-trace complete events into phase -> seconds.

    Only ``ph == "X"`` events with a duration participate.  Device
    tracks are detected from the ``process_name`` metadata (TPU/XLA/GPU
    device pids); when track metadata is absent (synthetic tests, CPU
    traces) every timed event is considered.  Unmatched XLA time is
    reported under ``"unattributed"`` so a breakdown can never silently
    claim full coverage; events that match no phase AND carry no XLA op
    args (host-side Python TraceMes) are dropped entirely.

    Backend caveat: op-level attribution needs a profiler that exports
    the HLO ``op_name`` metadata path into event args (the TPU plugin
    does).  The CPU tracer emits bare thunk names, so CPU traces bucket
    almost everything to ``unattributed`` — the scopes are still in the
    compiled HLO (pinned by tests), the CPU profiler just doesn't
    surface them.
    """
    events = list(events)
    device_pids = set()
    have_meta = False
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            have_meta = True
            pname = str((ev.get("args") or {}).get("name", ""))
            if re.search(r"TPU|XLA|/device|GPU", pname, re.I):
                device_pids.add(ev.get("pid"))
    out: Dict[str, float] = {}
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        if have_meta and device_pids and ev.get("pid") not in device_pids:
            continue
        sec = float(ev["dur"]) / 1e6  # chrome trace durations are us
        phase = classify_event(str(ev.get("name", "")),
                               _event_long_name(ev))
        if phase is None and not _is_xla_event(ev):
            continue
        key = phase if phase is not None else "unattributed"
        out[key] = out.get(key, 0.0) + sec
    return {k: round(v, 6) for k, v in out.items()}


def load_trace_events(trace_dir: str) -> List[dict]:
    """Trace events of the NEWEST capture under a ``jax.profiler.trace``
    output dir.  The profiler writes a fresh timestamped
    ``plugins/profile/<run>/`` per capture and never cleans old ones,
    so a reused trace dir holds several runs — summing across them
    would double phase seconds (and benchdiff would then flag phantom
    per-phase regressions).  Only files from the latest run directory
    (timestamped names sort lexicographically) are read."""
    paths = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                  recursive=True)
        + glob.glob(os.path.join(trace_dir, "**", "*.trace.json"),
                    recursive=True)
    )
    if paths:
        newest_run = max(os.path.dirname(p) for p in paths)
        paths = [p for p in paths if os.path.dirname(p) == newest_run]
    events: List[dict] = []
    for p in paths:
        opener = gzip.open if p.endswith(".gz") else open
        try:
            with opener(p, "rt", encoding="utf-8") as fh:
                data = json.load(fh)
        except Exception:
            continue
        evs = data.get("traceEvents") if isinstance(data, dict) else data
        if isinstance(evs, list):
            events.extend(e for e in evs if isinstance(e, dict))
    return events


def phase_breakdown_from_trace(trace_dir: str) -> Dict[str, float]:
    """Phase -> device seconds for a captured trace directory."""
    return bucket_events(load_trace_events(trace_dir))


class trace_phases:
    """Capture a profiler trace around a block and bucket it:

        with trace_phases("/tmp/lgbm_trace") as result:
            run_timed_loop()
        print(result.phases)   # {"histogram": ..., "partition": ...}

    Failure to start/stop the profiler (no TensorFlow profiler plugin,
    double-start) degrades to an empty breakdown rather than killing
    the run — a bench harness whose failure mode is "no number" is
    itself a defect (bench.py module docstring).
    """

    def __init__(self, trace_dir: str) -> None:
        self.trace_dir = trace_dir
        self.phases: Dict[str, float] = {}
        self._started = False

    def __enter__(self) -> "trace_phases":
        try:
            jax.profiler.start_trace(self.trace_dir)
            self._started = True
        except Exception:
            self._started = False
        return self

    def __exit__(self, *exc) -> None:
        if not self._started:
            return
        try:
            jax.profiler.stop_trace()
            self.phases = phase_breakdown_from_trace(self.trace_dir)
        except Exception:
            self.phases = {}
