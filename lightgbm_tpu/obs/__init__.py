"""Runtime observability: telemetry, phase-attributed device time,
self-describing run manifests.

The runtime half of ROADMAP item 1's "make perf un-regressable"
(jaxlint in ``analysis/`` is the static half):

* :mod:`~lightgbm_tpu.obs.telemetry` — always-on spans / counters /
  per-tree reservoirs (near-zero overhead; no jax import).
* :mod:`~lightgbm_tpu.obs.device_time` — ``phase_scope`` annotations on
  the hot ops + profiler-trace bucketing into histogram / split-search
  / partition / leaf-update (imports jax; loaded lazily so tools that
  only read manifests don't pay for it).
* :mod:`~lightgbm_tpu.obs.manifest` — ``RunManifest`` written next to
  every bench result artifact; diffed by ``tools/benchdiff.py``.
* :mod:`~lightgbm_tpu.obs.tracing` — per-request ``TraceContext``
  (trace id + stage clock) threaded through the serving tier; every
  served response carries a per-stage latency breakdown.
* :mod:`~lightgbm_tpu.obs.export` — Prometheus text exposition of the
  telemetry snapshot (``GET /metrics`` on the serving server).
* :mod:`~lightgbm_tpu.obs.flightrec` — lock-cheap last-N event ring,
  dumped atomically (checksum sidecar, rank-tagged filename) on
  preemption / guard trips / serving failures for post-mortem.
* :mod:`~lightgbm_tpu.obs.dist` — the cross-rank layer: rank-scoped
  snapshots, merge + skew attribution, host-side snapshot exchange,
  per-collective tracing (barrier-wait vs transfer), desync sentinels.
* :mod:`~lightgbm_tpu.obs.memory` — device-memory accounting: the
  shared ``memory_stats()`` reader, owner-tagged live-buffer census,
  host-boundary watermarks, ``lgbm_memory_*`` gauges, OOM post-mortems.
* :mod:`~lightgbm_tpu.obs.memmodel` — analytic HBM footprint model
  (expected live-set per phase from first principles); the planning
  artifact behind ``tools/hbm_budget.py``.

See docs/observability.md for the schemas and the reading guide.
"""

from __future__ import annotations

from . import (  # noqa: F401
    dist,
    export,
    flightrec,
    memmodel,
    memory,
    telemetry,
    tracing,
)
from .manifest import (  # noqa: F401
    RunManifest,
    config_fingerprint,
    manifest_path,
    validate,
)
from .telemetry import (  # noqa: F401
    Histogram,
    Reservoir,
    SpanStat,
    Telemetry,
    collective_stats,
    count,
    count_many,
    emit_if_json,
    enabled,
    get_telemetry,
    host_sync,
    observe,
    record_collectives,
    record_value,
    set_enabled,
    span,
)
from .tracing import TraceContext  # noqa: F401

_LAZY = ("phase_scope", "host_annotation", "bucket_events",
         "classify_event", "phase_breakdown_from_trace",
         "load_trace_events", "trace_phases", "PHASES", "SCOPE_TO_PHASE")


def __getattr__(name):
    # device_time imports jax; bridge it lazily so manifest/telemetry
    # consumers (benchdiff, lint tooling) stay jax-free
    if name in _LAZY or name == "device_time":
        from . import device_time

        if name == "device_time":
            return device_time
        return getattr(device_time, name)
    raise AttributeError(name)
