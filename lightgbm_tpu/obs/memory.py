"""Device-memory observability: the memory half of the obs stack.

``device_time.py``/``telemetry.py`` answer *where the time went*; this
module answers *where the bytes live*.  Four surfaces:

* ``hbm_stats()`` — the one shared reader over
  ``device.memory_stats()`` (bytes_in_use / peak / limit), normalized
  to ``hbm_*`` keys.  Backends without allocator stats (the CPU
  backend returns ``None``) degrade to ``hbm_stats_supported: false``
  with zeroed gauges instead of raising — tier-1 runs on CPU.
* ``live_buffer_census()`` — groups ``jax.live_arrays()`` by owner tag
  (dataset / scores / histograms / routing / serving) x dtype x shape.
  Owners self-register via ``register_owner``; the registry holds only
  weakrefs + getter callables, never the buffers themselves, so it can
  never *cause* the retention it is built to detect.
* host-side phase watermarks — ``phase_boundary(name)`` samples the
  allocator at the boundaries the host can see (binning / train / eval
  / serve / swap).  NOTE this is deliberately not ``phase_scope``: the
  trace-time phases (histogram / split-search / ...) live *inside* one
  jitted dispatch where the host cannot observe the allocator; their
  in-program peaks come from the static side instead
  (``analysis/hlo_audit.py`` memory budgets + ``obs/memmodel.py``).
* OOM post-mortems — ``classify_dispatch_error`` turns a
  RESOURCE_EXHAUSTED escaping a train/serve dispatch into a flight
  recorder dump (tail kind ``oom``) carrying the last census and the
  analytic model's prediction for the failing shape.

No jax import at module import time (jax is imported lazily inside
functions) so manifest/lint consumers stay jax-free, matching the rest
of ``obs/``.  See docs/memory.md for the gauge-name contract.
"""

from __future__ import annotations

import itertools
import weakref
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

from ..analysis import lockcheck

GAUGE_PREFIX = "lgbm_memory_"

# owner tags with a registered meaning (docs/memory.md); census rows
# from unregistered buffers fall under "other"
OWNER_TAGS = ("dataset", "scores", "histograms", "routing", "serving")

# host-visible sampling boundaries (NOT the trace-time PHASES — see
# module docstring)
BOUNDARIES = ("binning", "train", "eval", "serve", "swap")

# substrings that identify an out-of-device-memory failure in the
# message of a jax/XLA runtime error (XlaRuntimeError carries the grpc
# status name in-text; older paths say "Out of memory")
OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
               "OOM when allocating")

_lock = lockcheck.make_lock("memory.census")
_enabled = True

# token -> (tag, weakref-to-owner, getter).  getter(owner) returns a
# pytree / iterable of (possibly) jax arrays.
_owners: Dict[int, Tuple[str, "weakref.ref", Callable[[Any], Any]]] = {}
_owner_counter = itertools.count(1)

# phase -> {"last_bytes", "peak_bytes", "samples", "source"}
_watermarks: Dict[str, Dict[str, Any]] = {}
_last_census: Optional[dict] = None


def set_enabled(on: bool) -> None:
    """Runtime A/B switch for the sampling half (watermark sampling and
    census-on-boundary); used by tools/telemetry_overhead.py --memory.
    Explicit census / stats calls still work while disabled."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


# ---------------------------------------------------------------------------
# allocator stats (the shared reader northstar_run/bench adopt)

def device_memory_stats(device: Any = None) -> dict:
    """Raw ``memory_stats()`` for one device ({} when unsupported —
    the CPU backend returns None)."""
    try:
        import jax

        dev = device if device is not None else jax.local_devices()[0]
        return dict(dev.memory_stats() or {})
    except Exception:
        return {}


def hbm_stats(device: Any = None) -> dict:
    """Normalized device-memory gauges.  Keys are stable contract
    (docs/memory.md): ``hbm_bytes_in_use``, ``hbm_peak_bytes``,
    ``hbm_limit_bytes``, ``hbm_stats_supported``.  Never raises; a
    backend probe failure comes back as ``hbm_stats_error``."""
    try:
        import jax

        dev = device if device is not None else jax.local_devices()[0]
        ms = dev.memory_stats()
    except Exception as e:  # dead tunnel, uninitialized backend, ...
        return {"hbm_bytes_in_use": 0, "hbm_peak_bytes": 0,
                "hbm_limit_bytes": 0, "hbm_stats_supported": False,
                "hbm_stats_error": f"{type(e).__name__}: {str(e)[:120]}"}
    if not ms:
        return {"hbm_bytes_in_use": 0, "hbm_peak_bytes": 0,
                "hbm_limit_bytes": 0, "hbm_stats_supported": False}
    return {
        "hbm_bytes_in_use": int(ms.get("bytes_in_use", 0)),
        "hbm_peak_bytes": int(ms.get("peak_bytes_in_use", 0)),
        "hbm_limit_bytes": int(ms.get("bytes_limit", 0)),
        "hbm_stats_supported": True,
    }


# ---------------------------------------------------------------------------
# owner registry + live-buffer census

def register_owner(tag: str, owner: Any,
                   getter: Callable[[Any], Any]) -> int:
    """Register ``owner`` as holding device buffers under ``tag``.
    ``getter(owner)`` must return the buffers (a pytree or iterable);
    it is called at census time against the *live* owner.  Only a
    weakref to ``owner`` is kept — registration never extends a
    buffer's lifetime.  Returns a token for ``unregister_owner``."""
    token = next(_owner_counter)
    with _lock:
        _owners[token] = (str(tag), weakref.ref(owner), getter)
    return token


def unregister_owner(token: int) -> None:
    with _lock:
        _owners.pop(token, None)


def _iter_owner_arrays() -> Iterable[Tuple[str, Any]]:
    """(tag, array) pairs from live registered owners; drops dead
    weakrefs as it goes."""
    import jax

    with _lock:
        items = list(_owners.items())
    dead = []
    for token, (tag, ref, getter) in items:
        owner = ref()
        if owner is None:
            dead.append(token)
            continue
        try:
            leaves = jax.tree_util.tree_leaves(getter(owner))
        except Exception:
            continue
        for leaf in leaves:
            if isinstance(leaf, jax.Array):
                yield tag, leaf
    if dead:
        with _lock:
            for token in dead:
                _owners.pop(token, None)


def live_buffer_census(top: int = 16) -> dict:
    """Group every live device array by (owner tag, dtype, shape).

    Built on ``jax.live_arrays()`` so it sees *all* buffers, not just
    registered ones — unregistered buffers land under ``other``, which
    is exactly where a leak shows up.  O(live arrays) host walk; cheap
    at the scales this repo runs, and gated off the hot path (only at
    explicit call sites: /metrics scrape, manifest collection, OOM
    post-mortem, leak tests)."""
    global _last_census
    try:
        import jax
    except Exception:
        return {"total_bytes": 0, "buffers": 0, "by_owner": {},
                "groups": [], "supported": False}

    tag_of: Dict[int, str] = {}
    for tag, arr in _iter_owner_arrays():
        tag_of[id(arr)] = tag

    groups: Dict[Tuple[str, str, tuple], Dict[str, int]] = {}
    by_owner: Dict[str, Dict[str, int]] = {}
    total = 0
    count = 0
    for arr in jax.live_arrays():
        try:
            if arr.is_deleted():
                continue
            nbytes = int(arr.nbytes)
            key = (tag_of.get(id(arr), "other"), str(arr.dtype),
                   tuple(arr.shape))
        except Exception:
            continue
        total += nbytes
        count += 1
        g = groups.setdefault(key, {"bytes": 0, "count": 0})
        g["bytes"] += nbytes
        g["count"] += 1
        o = by_owner.setdefault(key[0], {"bytes": 0, "buffers": 0})
        o["bytes"] += nbytes
        o["buffers"] += 1

    rows = sorted(
        ({"owner": k[0], "dtype": k[1], "shape": list(k[2]),
          "count": v["count"], "bytes": v["bytes"]}
         for k, v in groups.items()),
        key=lambda r: (-r["bytes"], r["owner"], r["dtype"]))
    census = {
        "total_bytes": int(total),
        "buffers": int(count),
        "by_owner": {k: dict(v) for k, v in sorted(by_owner.items())},
        "groups": rows[:max(0, int(top))],
        "supported": True,
    }
    _last_census = census
    return census


def last_census() -> Optional[dict]:
    """Most recent census (post-mortems attach it when a fresh walk is
    impossible); None before the first census."""
    return _last_census


# ---------------------------------------------------------------------------
# host-side phase watermarks

def _live_bytes_fast() -> int:
    """Cheap total over live arrays — the CPU fallback signal when the
    allocator exposes no stats (keeps watermarks meaningful in tier-1)."""
    try:
        import jax

        return sum(int(a.nbytes) for a in jax.live_arrays()
                   if not a.is_deleted())
    except Exception:
        return 0


def phase_boundary(phase: str) -> None:
    """Sample device memory at a host-visible boundary (one of
    BOUNDARIES, though unknown names are accepted).  No-op while
    the layer is disabled."""
    if not _enabled:
        return
    st = hbm_stats()
    if st.get("hbm_stats_supported"):
        bytes_now = st["hbm_bytes_in_use"]
        peak_seen = st["hbm_peak_bytes"]
        source = "device"
    else:
        bytes_now = _live_bytes_fast()
        peak_seen = bytes_now
        source = "census"
    with _lock:
        w = _watermarks.setdefault(
            phase, {"last_bytes": 0, "peak_bytes": 0, "samples": 0,
                    "source": source})
        w["last_bytes"] = int(bytes_now)
        w["peak_bytes"] = max(int(w["peak_bytes"]), int(peak_seen),
                              int(bytes_now))
        w["samples"] += 1
        w["source"] = source


def watermarks() -> dict:
    with _lock:
        return {k: dict(v) for k, v in sorted(_watermarks.items())}


def reset_watermarks() -> None:
    with _lock:
        _watermarks.clear()


def peak_bytes() -> int:
    """Best available peak: allocator peak when supported, else the
    high-water mark over every boundary sample."""
    st = hbm_stats()
    if st.get("hbm_stats_supported"):
        return st["hbm_peak_bytes"]
    with _lock:
        return max((int(v["peak_bytes"]) for v in _watermarks.values()),
                   default=0)


# ---------------------------------------------------------------------------
# gauges / manifest section

def memory_gauges(census: Optional[dict] = None) -> dict:
    """Flat ``lgbm_memory_*`` gauge dict for
    :func:`obs.export.render_prometheus` (value or (value, help)
    entries).  Runs a fresh census unless one is passed in."""
    st = hbm_stats()
    c = census if census is not None else live_buffer_census()
    gauges: Dict[str, Any] = {
        GAUGE_PREFIX + "bytes_in_use": (
            st["hbm_bytes_in_use"],
            "Device allocator bytes currently in use"),
        GAUGE_PREFIX + "peak_bytes": (
            max(st["hbm_peak_bytes"], 0) or peak_bytes(),
            "Device allocator peak bytes (census high-water on CPU)"),
        GAUGE_PREFIX + "limit_bytes": (
            st["hbm_limit_bytes"], "Device allocator capacity"),
        GAUGE_PREFIX + "stats_supported": (
            1 if st.get("hbm_stats_supported") else 0,
            "1 when the backend exposes allocator stats"),
        GAUGE_PREFIX + "live_buffer_bytes": (
            c.get("total_bytes", 0),
            "Total bytes across jax.live_arrays()"),
        GAUGE_PREFIX + "live_buffers": (
            c.get("buffers", 0), "Number of live device arrays"),
    }
    for tag, row in (c.get("by_owner") or {}).items():
        gauges[GAUGE_PREFIX + "owner_bytes_" + str(tag)] = (
            row.get("bytes", 0),
            f"Live bytes owned by census tag '{tag}'")
    return gauges


def manifest_memory_section(census: Optional[dict] = None) -> dict:
    """The ``memory{}`` manifest section beside ``phases{}``: hbm
    gauges + boundary watermarks + a census summary."""
    c = census if census is not None else live_buffer_census()
    return {
        "hbm": hbm_stats(),
        "watermarks": watermarks(),
        "census": {
            "total_bytes": c.get("total_bytes", 0),
            "buffers": c.get("buffers", 0),
            "by_owner": c.get("by_owner", {}),
            "top": (c.get("groups") or [])[:8],
        },
    }


# ---------------------------------------------------------------------------
# OOM classification + post-mortem

def is_oom_error(exc: BaseException) -> bool:
    msg = f"{type(exc).__name__}: {exc}"
    return any(marker in msg for marker in OOM_MARKERS)


def oom_postmortem(exc: BaseException, where: str,
                   shape: Optional[dict] = None,
                   predict_params: Optional[dict] = None) -> dict:
    """Record + dump the post-mortem for an OOM at a dispatch boundary.

    Flight-recorder tail kind is ``oom`` and the event carries the last
    live-buffer census plus ``obs/memmodel``'s prediction for the
    failing shape (when the caller knows it) — so the dump answers both
    "what was resident" and "what did the model expect".  Never raises:
    a post-mortem that throws inside an OOM handler would mask the real
    failure."""
    from . import flightrec, telemetry

    try:
        census = live_buffer_census()
    except Exception:
        census = last_census() or {"total_bytes": 0, "buffers": 0,
                                   "by_owner": {}, "groups": []}
    predicted = None
    if predict_params:
        try:
            from . import memmodel

            predicted = memmodel.predict(**predict_params)
        except Exception:
            predicted = None
    event = {
        "where": where,
        "error": f"{type(exc).__name__}: {str(exc)[:400]}",
        "shape": dict(shape or {}),
        "hbm": hbm_stats(),
        "census": {
            "total_bytes": census.get("total_bytes", 0),
            "buffers": census.get("buffers", 0),
            "by_owner": census.get("by_owner", {}),
            "top": (census.get("groups") or [])[:8],
        },
        "predicted_peak_bytes": (
            predicted.get("peak_bytes") if predicted else None),
        "predicted_phases": (
            predicted.get("phases") if predicted else None),
    }
    try:
        telemetry.count("oom." + where.split(".")[0])
        flightrec.record("oom", **event)
        event["dump_path"] = flightrec.dump("oom")
    except Exception:
        event.setdefault("dump_path", None)
    return event


def classify_dispatch_error(exc: BaseException, where: str,
                            shape: Optional[dict] = None,
                            predict_params: Optional[dict] = None,
                            ) -> Optional[dict]:
    """Dispatch-boundary hook: post-mortem iff ``exc`` is an OOM.
    Returns the post-mortem event (or None); callers re-raise ``exc``
    either way."""
    if not is_oom_error(exc):
        return None
    return oom_postmortem(exc, where, shape=shape,
                          predict_params=predict_params)
