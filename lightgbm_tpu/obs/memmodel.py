"""Analytic HBM footprint model: expected live-set per phase from
first principles.

``predict(rows, features, ...)`` sums the allocations the training and
serving paths actually make (equations below mirror the real buffer
shapes in io/dataset.py, models/gbdt.py, learners/serial.py,
ops/record.py, serving/engine.py; docs/memory.md carries the same
table with derivations):

* binned dataset      ``F * n * bin_bytes``      (uint8, uint16 >256 bins)
* scores              ``K * n * 4``              (float32 raw scores)
* grad/hess           ``2 * K * n * gb``         (gb=8 under float64 hists)
* bagging mask        ``n * 4``
* histograms          ``L * F * B * 3 * hb``     (resident leaf-tier)
* routing scratch     order: ``n * 4``;
                      record: ``rec_height(F) * round_up(n, TILE) * 4``
                      (prefix); onehot ~2x for the compose buffer
* serving buckets     ``sum_b (b * F * 4 + b * K * 8)``

``n`` is rows/world (data-parallel shards the row dimension).  The
per-phase live sets compose these: the histogram/split-search phases
hold hists + grads, partition holds routing scratch instead, etc.
``peak_bytes`` is the max over phases — the number the 100M-row wall
(ROADMAP items 3/4) is planned against via tools/hbm_budget.py.

Validated in tier-1 against the measured live-buffer census
(obs/memory.py) at pinned shapes within TOLERANCE_PCT.  Pure python —
no jax, importable anywhere.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Tuple

SCHEMA = "lightgbm-tpu/memmodel/v1"

# documented census-vs-model tolerance (relative %, plus a small
# absolute floor for the tiny per-feature side arrays the model folds
# into its components): tier-1 pins model-vs-census within this band.
TOLERANCE_PCT = 20.0
TOLERANCE_ABS_BYTES = 8192

# record-mode routing layout constants (must mirror ops/record.py)
_REC_TILE = 512
_REC_STAT_ROWS = 5        # grad, hess, mask, row id, leaf id
_REC_HEIGHT_ALIGN = 8

PHASES = ("binning", "histogram", "split-search", "partition",
          "leaf-update", "predict")


def _round_up(x: int, m: int) -> int:
    return ((int(x) + m - 1) // m) * m


def _rec_height(features: int, bin_bytes: int) -> int:
    bins_per_word = 4 if bin_bytes == 1 else 2
    num_words = -(-int(features) // bins_per_word)
    return _round_up(num_words + _REC_STAT_ROWS, _REC_HEIGHT_ALIGN)


def predict(rows: int, features: int, bins: int = 255, leaves: int = 31,
            num_class: int = 1, world: int = 1, routing: str = "prefix",
            hist_prec: str = "float32",
            bucket_rows: Iterable[int] = (),
            forest_batch: int = 1) -> dict:
    """Expected per-chip live set, per phase, in bytes.

    ``routing`` is one of ``order`` (serial scatter learner),
    ``prefix`` / ``onehot`` (record-mode partition kernels).
    ``bucket_rows`` lists the serving shape-bucket capacities when the
    chip also serves.  All sizes are per data-parallel shard
    (``rows / world``).

    ``forest_batch`` is the number of INDEPENDENT models/folds trained
    through the batched forest dispatch (learners/forest.py) on the ONE
    shared binned matrix: per-model buffers (scores, bag masks,
    grad/hess) scale by B, and the dispatch-scoped buffers (histograms,
    routing) scale by all ``B * num_class`` lanes.  B=1 keeps the model
    describing the sequential grower exactly — the shape the tier-1
    model-vs-census pin measures."""
    rows = int(rows)
    features = int(features)
    bins = int(bins)
    leaves = int(leaves)
    num_class = max(1, int(num_class))
    world = max(1, int(world))
    forest_batch = max(1, int(forest_batch))
    n = -(-rows // world)

    bin_bytes = 1 if bins <= 256 else 2
    hist_bytes = 8 if str(hist_prec) in (
        "float64", "f64", "fp64", "double") else 4
    grad_bytes = hist_bytes  # float64 hists upcast the grad/hess pair

    dataset = features * n * bin_bytes
    scores = forest_batch * num_class * n * 4
    bag_mask = forest_batch * n * 4
    grad_hess = forest_batch * 2 * num_class * n * grad_bytes
    hists = leaves * features * bins * 3 * hist_bytes

    if forest_batch > 1:
        # batched forest dispatch: one histogram tier and one direct
        # row->leaf map per LANE (learners/forest.py _ForestState);
        # the record/order permutation machinery does not exist there
        lanes = forest_batch * num_class
        hists *= lanes
        routing_scratch = lanes * n * 4
    elif routing == "order":
        routing_scratch = n * 4
    else:
        rec = _rec_height(features, bin_bytes) * _round_up(
            max(n, 1), _REC_TILE) * 4
        routing_scratch = rec if routing == "prefix" else 2 * rec

    buckets = [int(b) for b in bucket_rows]
    serving = sum(b * features * 4 + b * num_class * 8 for b in buckets)

    raw_input = features * n * 4  # float32 source during quantization
    components: Dict[str, int] = {
        "raw_input": raw_input,
        "dataset": dataset,
        "scores": scores,
        "bag_mask": bag_mask,
        "grad_hess": grad_hess,
        "histograms": hists,
        "routing": routing_scratch,
        "serving": serving,
    }
    # what stays resident between dispatches (what a between-iteration
    # census sees): the binned matrix + score/bag buffers (+ serving
    # pads when bucket_rows given); raw_input lives only through binning
    resident = dataset + scores + bag_mask + serving

    phases: Dict[str, int] = {
        "binning": raw_input + dataset + scores + bag_mask,
        "histogram": resident + grad_hess + hists,
        "split-search": resident + grad_hess + hists,
        "partition": resident + grad_hess + routing_scratch,
        "leaf-update": resident + grad_hess,
        "predict": resident,
    }
    peak_phase = max(phases, key=lambda p: phases[p])
    return {
        "schema": SCHEMA,
        "params": {
            "rows": rows, "features": features, "bins": bins,
            "leaves": leaves, "num_class": num_class, "world": world,
            "routing": routing, "hist_prec": str(hist_prec),
            "bucket_rows": buckets, "rows_per_shard": n,
            "forest_batch": forest_batch,
        },
        "components": components,
        "resident_bytes": int(resident),
        "phases": {k: int(v) for k, v in phases.items()},
        "peak_bytes": int(phases[peak_phase]),
        "peak_phase": peak_phase,
    }


def limiting_component(pred: dict) -> Tuple[str, int]:
    """The largest single allocation in the peak phase — the first
    thing out-of-core work (ROADMAP item 3) must shard or stream."""
    comps = dict(pred["components"])
    phase = pred["peak_phase"]
    # components not live in the peak phase can't be the limiter
    live = {
        "binning": ("raw_input", "dataset", "scores", "bag_mask"),
        "histogram": ("dataset", "scores", "bag_mask", "grad_hess",
                      "histograms", "serving"),
        "split-search": ("dataset", "scores", "bag_mask", "grad_hess",
                         "histograms", "serving"),
        "partition": ("dataset", "scores", "bag_mask", "grad_hess",
                      "routing", "serving"),
        "leaf-update": ("dataset", "scores", "bag_mask", "grad_hess",
                        "serving"),
        "predict": ("dataset", "scores", "bag_mask", "serving"),
    }[phase]
    name = max(live, key=lambda c: comps.get(c, 0))
    return name, int(comps.get(name, 0))


def max_rows(capacity_bytes: int, **params: Any) -> int:
    """Largest row count whose predicted peak fits ``capacity_bytes``
    (binary search; 0 when even 1 row does not fit).  ``params`` are
    the non-``rows`` arguments of :func:`predict`."""
    capacity = int(capacity_bytes)
    if predict(rows=1, **params)["peak_bytes"] > capacity:
        return 0
    lo, hi = 1, 2
    while predict(rows=hi, **params)["peak_bytes"] <= capacity:
        lo, hi = hi, hi * 2
        if hi > 1 << 44:
            return lo
    while lo + 1 < hi:
        mid = (lo + hi) // 2
        if predict(rows=mid, **params)["peak_bytes"] <= capacity:
            lo = mid
        else:
            hi = mid
    return lo


def max_forest_batch(capacity_bytes: int, **params: Any) -> int:
    """Largest forest-batch lane count B whose predicted peak fits
    ``capacity_bytes`` at the given shape — the sizing input for
    picking B on chip (tools/hbm_budget.py --forest-batch).  ``params``
    are the non-``forest_batch`` arguments of :func:`predict` (``rows``
    included).  0 when even B=1 does not fit."""
    capacity = int(capacity_bytes)
    if predict(forest_batch=1, **params)["peak_bytes"] > capacity:
        return 0
    lo, hi = 1, 2
    while predict(forest_batch=hi, **params)["peak_bytes"] <= capacity:
        lo, hi = hi, hi * 2
        if hi > 1 << 30:
            return lo
    while lo + 1 < hi:
        mid = (lo + hi) // 2
        if predict(forest_batch=mid, **params)["peak_bytes"] <= capacity:
            lo = mid
        else:
            hi = mid
    return lo


def rows_curve(capacity_bytes: int, row_points: Iterable[int],
               **params: Any) -> dict:
    """The rows-vs-HBM planning artifact tools/hbm_budget.py prints:
    predicted peak at each row count, the capacity ceiling, and the
    allocation that hits the wall first."""
    points = []
    for r in row_points:
        pred = predict(rows=int(r), **params)
        points.append({
            "rows": int(r),
            "peak_bytes": pred["peak_bytes"],
            "peak_phase": pred["peak_phase"],
            "fits": pred["peak_bytes"] <= int(capacity_bytes),
        })
    cap_rows = max_rows(capacity_bytes, **params)
    at_wall = predict(rows=max(cap_rows, 1), **params)
    limiter, limiter_bytes = limiting_component(at_wall)
    return {
        "schema": SCHEMA,
        "capacity_bytes": int(capacity_bytes),
        "params": at_wall["params"],
        "points": points,
        "max_rows": cap_rows,
        "wall": {
            "peak_phase": at_wall["peak_phase"],
            "limiting_component": limiter,
            "limiting_bytes": limiter_bytes,
            "components": at_wall["components"],
        },
    }


def within_tolerance(model_bytes: int, measured_bytes: int,
                     pct: float = TOLERANCE_PCT,
                     abs_floor: int = TOLERANCE_ABS_BYTES) -> bool:
    """The documented agreement predicate tier-1 pins: |model -
    measured| <= max(pct% of measured, abs_floor)."""
    slack = max(abs(measured_bytes) * pct / 100.0, float(abs_floor))
    return abs(int(model_bytes) - int(measured_bytes)) <= slack
