"""Distributed-run observability: rank-scoped telemetry, cross-rank
merging with skew attribution, per-collective tracing, desync sentinels.

Everything the PR 7/PR 14 observability stack records is process-local:
on a multi-chip run that means eight telemetry stores, eight flight
recorders, and no way to say *which rank* was slow, *which collective*
dominated, or *where* two ranks silently diverged.  This module is the
cross-rank layer:

* **Rank snapshots** — :func:`rank_snapshot` stamps a full telemetry
  snapshot (reservoirs carrying their raw sample windows, so quantiles
  stay recomputable after a merge) with the rank's identity
  (``process_index``, device, pid, host).
* **Merging + skew** — :func:`merge_snapshots` sums counters, merges
  spans/reservoirs/histograms, and computes per-name cross-rank skew
  (max−min, max/mean, which rank) — the number that turns "the run was
  slow" into "rank 3 was slow".  :func:`attribute_stragglers` reads the
  barrier-wait series: the straggler is the rank that waited LEAST (it
  arrived last; everyone else's wait is time spent waiting for it).
* **Exchange** — :func:`exchange_snapshots`: every rank atomically
  writes ``rank_<i>.json`` into a shared directory; rank 0 polls with a
  deadline and merges.  Host-side files, not a device collective, so
  the 8-process CPU dryrun exercises the identical path a v5e-8 run
  will use (and a hung peer costs a timeout, not a wedged collective).
* **Per-collective tracing** — :func:`traced_collective` wraps a
  host-blocking collective site: an optional cheap barrier is timed
  separately (``*.wait_s`` — straggler time) from the payload op
  (``*.transfer_s``), op kind and payload bytes feed the existing
  ``collective_ops``/``collective_bytes`` counters per-op, and
  transient retries attribute to the site's label.
  :func:`record_collective_site` is the trace-time analog for
  collectives that live INSIDE a jitted program (``data_parallel.py``'s
  psum_scatter/all_gather sites): one counter per site per trace, so
  the 3-collectives/split contract is checkable per-op, not just as an
  HLO total.
* **Desync sentinels** — :class:`DesyncSentinel` piggybacks a cheap
  ``int32[3]`` fingerprint allgather on the per-iteration sync point;
  a mismatch raises :class:`DesyncError` NAMING the diverging rank and
  iteration (instead of bitwise divergence discovered post-hoc) and
  leaves a flight-recorder dump (tail = ``desync_detected``).

Env knobs (read once at import, repo convention):

* ``LGBM_TPU_DESYNC_CHECK`` — ``1`` (default): verify every iteration;
  ``N``: every N iterations; ``0``: off.
* ``LGBM_TPU_COLLECTIVE_TRACE`` — ``on`` (default) | ``off``: when off,
  traced_collective skips the barrier (no wait/transfer separation —
  one collective per site instead of two) and records transfer only.

No jax import at module import (the exchange/merge half must stay
importable from tools); rank identity is resolved lazily.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import sys
import time
import zlib
from os import environ as _environ
from typing import Callable, Dict, List, Optional, Sequence

from . import flightrec, telemetry

RANK_SCHEMA = "lightgbm-tpu/rank-snapshot/v1"
MERGED_SCHEMA = "lightgbm-tpu/merged-telemetry/v1"
MULTICHIP_SCHEMA = "lightgbm-tpu/multichip-bench/v1"

# read once at import — see module docstring
try:
    DESYNC_CHECK_EVERY = int(_environ.get("LGBM_TPU_DESYNC_CHECK", "1"))
except ValueError:
    DESYNC_CHECK_EVERY = 1
COLLECTIVE_TRACE = _environ.get(
    "LGBM_TPU_COLLECTIVE_TRACE", "on").strip().lower() != "off"


# ------------------------------------------------------------ rank identity
def process_index() -> int:
    """This process's rank.  Lazy: jax's distributed view when jax is
    already imported (never imports it), else the launcher env, else 0.
    """
    if "jax" in sys.modules:
        try:
            return int(sys.modules["jax"].process_index())
        except Exception:  # noqa: BLE001 — backend not initialized yet
            pass
    try:
        return int(_environ.get("LGBM_TPU_PROCESS_ID", "0") or 0)
    except ValueError:
        return 0


def process_count() -> int:
    """World size, resolved like :func:`process_index`."""
    if "jax" in sys.modules:
        try:
            return int(sys.modules["jax"].process_count())
        except Exception:  # noqa: BLE001
            pass
    try:
        return max(1, int(_environ.get("LGBM_TPU_NUM_PROCESSES", "1") or 1))
    except ValueError:
        return 1


def _device_info() -> dict:
    """Best-effort local device identity (never initializes a backend
    the process didn't already use — the manifest lesson)."""
    if "jax" not in sys.modules:
        return {}
    try:
        jax = sys.modules["jax"]
        devs = jax.local_devices()
        return {
            "backend": devs[0].platform,
            "kind": getattr(devs[0], "device_kind", None),
            "local_count": len(devs),
        }
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {str(e)[:80]}"}


# ------------------------------------------------------------ rank snapshot
def rank_snapshot(tel: Optional[telemetry.Telemetry] = None,
                  rank: Optional[int] = None,
                  world: Optional[int] = None,
                  extra: Optional[dict] = None) -> dict:
    """One rank's full telemetry snapshot, stamped with its identity.
    Reservoirs carry their raw sample windows (``include_samples``) so a
    merge can recompute exact window quantiles instead of averaging
    percentiles (which is wrong for any skewed distribution)."""
    tel = tel or telemetry.get_telemetry()
    snap = {
        "schema": RANK_SCHEMA,
        "process_index": process_index() if rank is None else int(rank),
        "process_count": process_count() if world is None else int(world),
        "pid": os.getpid(),
        "host": socket.gethostname(),
        "device": _device_info(),
        "created_unix": round(time.time(), 3),
        "telemetry": tel.snapshot(include_samples=True),
        "extra": dict(extra or {}),
    }
    # gang membership (resilience/gang.py): a supervised rank stamps its
    # slot/gang id so a recovery timeline is attributable — "slot 2's
    # third incarnation" reads straight off the merged manifest
    gang_dir = os.environ.get("LGBM_TPU_GANG_DIR", "")
    if gang_dir:
        snap["gang"] = {
            "gang_id": os.environ.get("LGBM_TPU_GANG_ID", "gang"),
            "slot": int(os.environ.get("LGBM_TPU_GANG_SLOT", "0") or 0),
            "barrier_every": int(
                os.environ.get("LGBM_TPU_GANG_BARRIER_EVERY", "0") or 0),
        }
    # Every rank snapshot carries its own device-memory high-water mark so
    # the merged artifact can show memory skew beside time skew.  The shared
    # reader degrades to the census high-water on backends without allocator
    # stats (CPU); an extra-provided value wins (test hooks).
    if "hbm_peak_bytes" not in snap["extra"]:
        try:
            from . import memory as obs_memory
            st = obs_memory.hbm_stats()
            snap["hbm_peak_bytes"] = int(
                st.get("hbm_peak_bytes") or obs_memory.peak_bytes())
        except Exception:  # noqa: BLE001 - memory evidence is best-effort
            snap["hbm_peak_bytes"] = 0
    else:
        snap["hbm_peak_bytes"] = int(snap["extra"]["hbm_peak_bytes"])
    return snap


def _skew(per_rank: Dict[int, float]) -> dict:
    """Cross-rank skew of one named series: max−min and max/mean plus
    WHICH rank sits at each extreme — the attribution half."""
    ranks = sorted(per_rank)
    vals = [per_rank[r] for r in ranks]
    vmax, vmin = max(vals), min(vals)
    mean = sum(vals) / len(vals)
    return {
        "per_rank": {str(r): round(per_rank[r], 6) for r in ranks},
        "mean_s": round(mean, 6),
        "max_s": round(vmax, 6),
        "min_s": round(vmin, 6),
        "max_minus_min_s": round(vmax - vmin, 6),
        "max_over_mean": round(vmax / mean, 4) if mean > 0 else 0.0,
        "max_rank": ranks[vals.index(vmax)],
        "min_rank": ranks[vals.index(vmin)],
        "reported": len(ranks),
    }


def merge_snapshots(snaps: Sequence[dict]) -> dict:
    """Merge per-rank snapshots (:func:`rank_snapshot` shape) into ONE
    cross-rank view.

    * counters: exact sums (plain sum in rank order — the tier-1
      contract is ``merged == sum(per-rank)`` to the bit);
    * spans: total_s/count summed, min/max over ranks, plus
      ``span_skew`` over per-rank total_s;
    * reservoirs: sample windows concatenated in rank order and the
      window quantiles recomputed exactly, plus ``reservoir_skew`` over
      per-rank window means;
    * histograms: bucket counts summed when bounds agree; a bounds
      mismatch is RECORDED (``histogram_merge_conflicts``), never
      silently resolved.
    """
    if not snaps:
        raise ValueError("merge_snapshots: no snapshots to merge")
    by_rank = sorted(snaps, key=lambda s: int(s.get("process_index", 0)))
    ranks = [int(s.get("process_index", 0)) for s in by_rank]
    if len(set(ranks)) != len(ranks):
        raise ValueError(f"merge_snapshots: duplicate ranks {ranks}")

    counters: Dict[str, float] = {}
    span_tot: Dict[str, dict] = {}
    span_per_rank: Dict[str, Dict[int, float]] = {}
    res_samples: Dict[str, List[float]] = {}
    res_count: Dict[str, int] = {}
    res_per_rank_mean: Dict[str, Dict[int, float]] = {}
    hists: Dict[str, dict] = {}
    hist_conflicts: List[str] = []

    for s in by_rank:
        r = int(s.get("process_index", 0))
        t = s.get("telemetry") or {}
        for k, v in (t.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + v
        for k, st in (t.get("spans") or {}).items():
            tot = span_tot.setdefault(
                k, {"total_s": 0.0, "count": 0,
                    "min_s": float("inf"), "max_s": 0.0})
            tot["total_s"] += float(st.get("total_s", 0.0))
            tot["count"] += int(st.get("count", 0))
            tot["min_s"] = min(tot["min_s"], float(st.get("min_s", 0.0)))
            tot["max_s"] = max(tot["max_s"], float(st.get("max_s", 0.0)))
            span_per_rank.setdefault(k, {})[r] = float(st.get("total_s", 0.0))
        for k, rd in (t.get("reservoirs") or {}).items():
            samples = [float(x) for x in (rd.get("samples") or [])]
            res_samples.setdefault(k, []).extend(samples)
            res_count[k] = res_count.get(k, 0) + int(rd.get("count", 0))
            res_per_rank_mean.setdefault(k, {})[r] = float(
                rd.get("mean_s", 0.0))
        for k, hd in (t.get("histograms") or {}).items():
            cur = hists.get(k)
            if cur is None:
                hists[k] = {"bounds": list(hd.get("bounds") or []),
                            "counts": [int(c) for c in
                                       (hd.get("counts") or [])],
                            "count": int(hd.get("count", 0)),
                            "sum": float(hd.get("sum", 0.0))}
            elif cur["bounds"] != list(hd.get("bounds") or []):
                if k not in hist_conflicts:
                    hist_conflicts.append(k)
            else:
                cur["counts"] = [a + int(b) for a, b in
                                 zip(cur["counts"], hd.get("counts") or [])]
                cur["count"] += int(hd.get("count", 0))
                cur["sum"] += float(hd.get("sum", 0.0))

    spans = {}
    for k, tot in span_tot.items():
        spans[k] = {
            "total_s": round(tot["total_s"], 6),
            "count": tot["count"],
            "min_s": round(tot["min_s"], 6)
            if tot["min_s"] != float("inf") else 0.0,
            "max_s": round(tot["max_s"], 6),
        }
    reservoirs = {}
    for k, samples in res_samples.items():
        window = len(samples)
        srt = sorted(samples)

        def _pct(p: float) -> float:
            if not srt:
                return 0.0
            i = max(0, min(len(srt) - 1,
                           int(round(p / 100.0 * (len(srt) - 1)))))
            return srt[i]

        reservoirs[k] = {
            "count": res_count.get(k, 0),
            "window": window,
            "mean_s": round(sum(samples) / window, 6) if window else 0.0,
            "p50_s": round(_pct(50), 6),
            "p99_s": round(_pct(99), 6),
            "max_s": round(srt[-1], 6) if srt else 0.0,
        }

    return {
        "schema": MERGED_SCHEMA,
        "world": len(by_rank),
        "ranks": ranks,
        "counters": counters,
        "spans": spans,
        "span_skew": {k: _skew(v) for k, v in span_per_rank.items()
                      if len(v) > 1},
        "reservoirs": reservoirs,
        "reservoir_skew": {k: _skew(v)
                           for k, v in res_per_rank_mean.items()
                           if len(v) > 1},
        "histograms": hists,
        "histogram_merge_conflicts": hist_conflicts,
    }


# straggler attribution reads these series: barrier wait per rank.  The
# rank that waited LEAST arrived LAST — everyone else's wait is the time
# they spent at the barrier waiting for it.
_WAIT_SUFFIX = ".wait_s"
# a skew below this floor is scheduling noise, not a straggler
STRAGGLER_FLOOR_S = 0.005


def attribute_stragglers(merged: dict,
                         floor_s: float = STRAGGLER_FLOOR_S) -> List[dict]:
    """Scan a merged snapshot's barrier-wait skews and name the
    straggling rank per collective site.  Returns
    ``[{site, straggler_rank, wait_skew_s, max_over_mean}]``, worst
    first; empty when no wait series shows skew above ``floor_s``."""
    out = []
    for name, sk in (merged.get("reservoir_skew") or {}).items():
        if not name.endswith(_WAIT_SUFFIX):
            continue
        if sk["max_minus_min_s"] < floor_s:
            continue
        site = name[len("collective."):-len(_WAIT_SUFFIX)] \
            if name.startswith("collective.") else name
        out.append({
            "site": site,
            "straggler_rank": sk["min_rank"],
            "wait_skew_s": sk["max_minus_min_s"],
            "max_over_mean": sk["max_over_mean"],
        })
    out.sort(key=lambda d: -d["wait_skew_s"])
    return out


# ---------------------------------------------------------------- exchange
def exchange_dir_for(artifact_path: str) -> str:
    """Canonical rank-snapshot exchange directory for a run artifact:
    the env override wins, else a ``<artifact>.rankobs`` sibling."""
    env = _environ.get("LGBM_TPU_RANK_OBS_DIR", "")
    if env:
        return env
    return os.path.abspath(artifact_path) + ".rankobs"


def _rank_file(directory: str, rank: int) -> str:
    return os.path.join(directory, f"rank_{rank}.json")


def write_rank_snapshot(directory: str,
                        snap: Optional[dict] = None) -> str:
    """Atomically publish this rank's snapshot into the exchange dir."""
    from ..resilience.atomic import atomic_write_json

    snap = snap or rank_snapshot()
    os.makedirs(directory, exist_ok=True)
    path = _rank_file(directory, int(snap["process_index"]))
    atomic_write_json(path, snap)
    return path


def gather_rank_snapshots(directory: str, world: int,
                          timeout_s: float = 120.0,
                          poll_s: float = 0.1) -> List[dict]:
    """Rank 0's half of the exchange: poll until all ``world`` files are
    present (atomic writes mean a present file is a complete file),
    then load them sorted by rank.  Raises ``TimeoutError`` naming the
    MISSING ranks — the closest thing a dead peer leaves to a name."""
    deadline = time.monotonic() + timeout_s
    want = {r: _rank_file(directory, r) for r in range(world)}
    while True:
        missing = [r for r, p in want.items() if not os.path.exists(p)]
        if not missing:
            break
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"rank-snapshot exchange: ranks {missing} never published "
                f"into {directory} within {timeout_s:.0f}s — those "
                "processes likely died; check their logs/flight recorders")
        time.sleep(poll_s)
    snaps = []
    for r in range(world):
        with open(want[r]) as fh:
            snaps.append(json.load(fh))
    return snaps


def exchange_snapshots(directory: str, timeout_s: float = 120.0,
                       extra: Optional[dict] = None) -> Optional[dict]:
    """End-of-run snapshot exchange: every rank publishes, rank 0
    gathers and merges.  Returns the merged snapshot on rank 0, None on
    other ranks.  Single-process worlds skip the file round-trip and
    merge the local snapshot directly (same output shape)."""
    world = process_count()
    rank = process_index()
    snap = rank_snapshot(extra=extra)
    if world <= 1:
        return merge_snapshots([snap])
    write_rank_snapshot(directory, snap)
    if rank != 0:
        return None
    return merge_snapshots(
        gather_rank_snapshots(directory, world, timeout_s=timeout_s))


def ranks_section(snaps: Sequence[dict]) -> List[dict]:
    """The manifest ``ranks[]`` entries: per-rank identity + the
    load-bearing numbers (compiles, span seconds, collective wait/
    transfer, counters) WITHOUT the raw sample windows — the manifest
    stays readable; the full snapshots stay in the exchange dir."""
    out = []
    for s in sorted(snaps, key=lambda s: int(s.get("process_index", 0))):
        t = s.get("telemetry") or {}
        res = {k: {kk: v[kk] for kk in ("count", "mean_s", "p50_s", "p99_s")
                   if kk in v}
               for k, v in (t.get("reservoirs") or {}).items()}
        row = {
            "process_index": int(s.get("process_index", 0)),
            "pid": s.get("pid"),
            "host": s.get("host"),
            "device": s.get("device") or {},
            "counters": dict(t.get("counters") or {}),
            "spans": dict(t.get("spans") or {}),
            "reservoirs": res,
        }
        hbm = s.get("hbm_peak_bytes",
                    (s.get("extra") or {}).get("hbm_peak_bytes"))
        if hbm is not None:
            row["hbm_peak_bytes"] = int(hbm)
        if s.get("gang"):
            row["gang"] = dict(s["gang"])
        out.append(row)
    return out


# ------------------------------------------------------ collective tracing
def record_collective_site(site: str, op: str, nbytes: int) -> None:
    """Trace-time census of an in-program collective site (called from
    INSIDE a traced body, so it counts once per retrace — pair it with
    the ``dp_grow_traces`` counter to normalize).  Makes the
    3-collectives/split contract checkable per-op: each site shows up
    as ``collective_site.<site>.<op>`` with its payload bytes."""
    telemetry.count_many({
        f"collective_site.{site}.{op}": 1,
        f"collective_site_bytes.{site}": int(nbytes),
    })


def traced_collective(fn: Callable, *, op: str, label: str,
                      payload_bytes: int = 0,
                      barrier_fn: Optional[Callable] = None,
                      deadline_s: float = 0.0,
                      retries: int = 2,
                      rank: Optional[int] = None,
                      tel: Optional[telemetry.Telemetry] = None):
    """Run a host-blocking collective with per-site tracing.

    Timing is split in two when ``barrier_fn`` is given (and the
    ``LGBM_TPU_COLLECTIVE_TRACE`` knob is on): the barrier's wall time
    is pure straggler wait (every rank must arrive before any passes),
    the remainder is the payload transfer.  Both feed labeled
    reservoirs (``collective.<label>.wait_s`` / ``.transfer_s``) — the
    series :func:`merge_snapshots` computes cross-rank skew over and
    :func:`attribute_stragglers` names the slow rank from.

    The call itself rides :func:`resilience.retry.guarded_collective`
    (chaos injection point, pre-dispatch transient retry attributed to
    ``label``, optional deadline).  ``rank`` overrides the fault
    injection's rank match (simulated worlds in tests/chaos)."""
    from ..resilience import faults
    from ..resilience.retry import call_with_deadline, guarded_collective

    tel = tel or telemetry.get_telemetry()
    faults.maybe_delay_collective(rank=rank)
    wait_s = 0.0
    t0 = time.perf_counter()
    if barrier_fn is not None and COLLECTIVE_TRACE:
        # the barrier is itself a collective: a dead peer would hang it
        # forever, so it runs under the SAME deadline as the payload —
        # tracing must never weaken the hang protection it instruments
        call_with_deadline(barrier_fn, deadline_s,
                           what=f"{label} barrier")
        wait_s = time.perf_counter() - t0
    t1 = time.perf_counter()
    out = guarded_collective(fn, deadline_s=deadline_s, label=label,
                             retries=retries)
    transfer_s = time.perf_counter() - t1
    tel.count_many({
        "collective_ops": 1,
        f"collective_ops.op.{op}": 1,
        "collective_bytes": int(payload_bytes),
        f"collective_bytes.op.{op}": int(payload_bytes),
    })
    tel.record_samples({
        f"collective.{label}.wait_s": wait_s,
        f"collective.{label}.transfer_s": transfer_s,
    })
    return out


# --------------------------------------------------------- desync sentinel
class DesyncError(RuntimeError):
    """Two ranks disagree on what iteration/model they are training.
    Raised the iteration the divergence is observed, NAMING the rank —
    the alternative is bitwise-divergent models discovered post-hoc."""


def state_fingerprint(step: int, config_fp: int, *payloads) -> int:
    """Cheap int31 fingerprint of the per-iteration state: the step,
    the structural-config crc, and any host bytes the caller wants
    covered (the grown tree's arrays — crc32 of a few KB per tree).
    Masked to int31 so the int32 collective transport is lossless."""
    h = zlib.crc32(f"{step}|{config_fp}".encode())
    for p in payloads:
        if p is None:
            continue
        if isinstance(p, (bytes, bytearray)):
            h = zlib.crc32(p, h)
        else:
            h = zlib.crc32(repr(p).encode(), h)
    return h & 0x7FFFFFFF


def config_crc(obj) -> int:
    """Structural-config half of the fingerprint (stable across ranks
    by construction — the config fingerprint multihost sync verified)."""
    try:
        blob = repr(sorted(vars(obj).items())) if hasattr(obj, "__dict__") \
            else repr(obj)
    except Exception:  # noqa: BLE001 — any stable repr will do
        blob = repr(obj)
    return zlib.crc32(blob.encode()) & 0x7FFFFFFF


class DesyncSentinel:
    """Cross-rank agreement check piggybacked on a per-iteration sync
    point.

    Each rank contributes ``[step, fingerprint, rank]`` (int32) to one
    small allgather; every rank then verifies all rows agree on (step,
    fingerprint).  A mismatch identifies the diverging rank(s) by
    majority (the minority rows are the divergents; on a tie the
    highest-rank minority is named) and raises :class:`DesyncError`
    within the iteration, after recording a flight-recorder event and
    dumping the ring (tail = ``desync_detected``).

    ``gather_fn(row) -> [world, 3]`` defaults to
    ``multihost_utils.process_allgather`` via :func:`traced_collective`
    (label ``desync_sentinel``); tests and chaos inject a fake gather
    to fabricate peer worlds in one process.
    """

    def __init__(self, world: Optional[int] = None,
                 rank: Optional[int] = None,
                 gather_fn: Optional[Callable] = None,
                 check_every: int = DESYNC_CHECK_EVERY,
                 deadline_s: float = 0.0) -> None:
        self.world = process_count() if world is None else int(world)
        self.rank = process_index() if rank is None else int(rank)
        self.check_every = int(check_every)
        self.deadline_s = deadline_s
        self._gather = gather_fn

    def local_row(self, step: int, fp: int):
        """This rank's sentinel row, with the ``desync_step`` chaos
        fault applied (a matching rank perturbs its fingerprint ONCE —
        the lab analog of a rank that silently took a different
        branch)."""
        import numpy as np

        from ..resilience import faults

        if faults.maybe_desync_step(rank=self.rank):
            fp = (fp + 1) & 0x7FFFFFFF
        return np.asarray([int(step) & 0x7FFFFFFF, int(fp), self.rank],
                          np.int32)

    def _default_gather(self, row):
        from jax.experimental import multihost_utils

        return traced_collective(
            lambda: multihost_utils.process_allgather(row),
            op="all-gather", label="desync_sentinel",
            payload_bytes=int(row.size) * 4 * self.world,
            barrier_fn=lambda: multihost_utils.sync_global_devices(
                "lgbm_desync_sentinel"),
            deadline_s=self.deadline_s)

    def should_check(self, step: int) -> bool:
        return (self.world > 1 and self.check_every > 0
                and step % self.check_every == 0)

    def verify(self, step: int, fp: int) -> None:
        """Exchange and compare.  No-op in single-rank worlds or on
        off-cadence steps."""
        if not self.should_check(step):
            return
        import numpy as np

        row = self.local_row(step, fp)
        gather = self._gather or self._default_gather
        g = np.asarray(gather(row)).reshape(-1, 3)
        telemetry.count("desync_checks")
        pairs = [(int(r[0]), int(r[1])) for r in g]
        if len(set(pairs)) <= 1:
            return
        # majority vote: the modal (step, fp) is the world's consensus;
        # every minority row is a divergent rank
        from collections import Counter

        consensus, _ = Counter(pairs).most_common(1)[0]
        divergent = sorted(int(g[i][2]) for i, p in enumerate(pairs)
                           if p != consensus)
        detail = {int(r[2]): {"step": int(r[0]), "fingerprint": int(r[1])}
                  for r in g}
        telemetry.count("desync_detected")
        flightrec.record("desync_detected", iteration=int(step),
                         divergent_ranks=divergent,
                         consensus_step=consensus[0],
                         consensus_fingerprint=consensus[1])
        flightrec.dump(reason="desync")
        raise DesyncError(
            f"cross-rank desync at iteration {int(step)}: rank(s) "
            f"{divergent} disagree with the {len(pairs) - len(divergent)}"
            f"-rank consensus (step={consensus[0]}, "
            f"fingerprint={consensus[1]}); per-rank view: {detail}. "
            "This world is no longer training one model — stop all "
            "ranks and resume from the last checkpoint.")


# ----------------------------------------------------- multichip artifact
def multichip_artifact(merged: dict, snaps: Sequence[dict],
                       result: Optional[dict] = None,
                       extra: Optional[dict] = None) -> dict:
    """The committable multi-chip evidence blob
    (``lightgbm-tpu/multichip-bench/v1``): merged telemetry + per-rank
    breakdown + skew + straggler attribution, benchdiff-comparable."""
    devices = {}
    for s in snaps:
        d = s.get("device") or {}
        if d.get("backend"):
            devices[d["backend"]] = devices.get(d["backend"], 0) \
                + int(d.get("local_count") or 1)
    return {
        "schema": MULTICHIP_SCHEMA,
        "world": merged.get("world"),
        "devices": devices,
        "result": dict(result or {}),
        "ranks": ranks_section(snaps),
        "merged": {k: merged[k] for k in
                   ("counters", "spans", "reservoirs", "histograms")
                   if k in merged},
        "skew": {"spans": merged.get("span_skew") or {},
                 "reservoirs": merged.get("reservoir_skew") or {}},
        "stragglers": attribute_stragglers(merged),
        "extra": dict(extra or {}),
        "created_unix": round(time.time(), 3),
    }


def _fmt_cell(v) -> str:
    if isinstance(v, float):
        return f"{v:.4f}"
    return str(v)


def render_rank_table(merged: dict, ranks: Sequence[dict],
                      counters: Sequence[str] = (
                          "backend_compiles", "dp_grow_traces",
                          "collective_ops", "desync_checks"),
                      span_prefixes: Sequence[str] = ("dist.grow",),
                      ) -> List[str]:
    """Human-readable per-rank table + skew tail (shared by
    ``tools/rank_report.py`` and the dryrun MULTICHIP tail)."""
    span_names = sorted(
        n for n in (merged.get("spans") or {})
        if any(n.startswith(p) for p in span_prefixes))
    wait_names = sorted(
        n for n in (merged.get("reservoirs") or {})
        if n.startswith("collective.") and n.endswith(".wait_s"))
    have_hbm = any((r.get("hbm_peak_bytes") or 0) > 0 for r in ranks)
    head = (["rank", "device"] + list(counters)
            + [f"{n} s" for n in span_names]
            + [f"{n[len('collective.'):-len('.wait_s')]} wait-mean s"
               for n in wait_names]
            + (["hbm_peak MiB"] if have_hbm else []))
    rows = [head]
    for r in ranks:
        dev = r.get("device") or {}
        cells = [str(r.get("process_index")),
                 f"{dev.get('backend', '?')}x{dev.get('local_count', '?')}"]
        cnt = r.get("counters") or {}
        cells += [_fmt_cell(cnt.get(c, 0)) for c in counters]
        sp = r.get("spans") or {}
        cells += [_fmt_cell((sp.get(n) or {}).get("total_s", 0.0))
                  for n in span_names]
        res = r.get("reservoirs") or {}
        cells += [_fmt_cell((res.get(n) or {}).get("mean_s", 0.0))
                  for n in wait_names]
        if have_hbm:
            cells.append(f"{(r.get('hbm_peak_bytes') or 0) / 2**20:.2f}")
        rows.append(cells)
    widths = [max(len(row[i]) for row in rows) for i in range(len(head))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
             for row in rows]
    for sk_name, sk in sorted((merged.get("span_skew") or {}).items()):
        if any(sk_name.startswith(p) for p in span_prefixes):
            lines.append(
                f"skew {sk_name}: max-min {sk['max_minus_min_s']:.4f}s "
                f"(max r{sk['max_rank']} / min r{sk['min_rank']}, "
                f"max/mean {sk['max_over_mean']:.2f})")
    for s in attribute_stragglers(merged):
        lines.append(
            f"straggler {s['site']}: rank {s['straggler_rank']} "
            f"(wait skew {s['wait_skew_s']:.4f}s, max/mean "
            f"{s['max_over_mean']:.2f})")
    hbm = {int(r.get("process_index", 0)): int(r.get("hbm_peak_bytes") or 0)
           for r in ranks if (r.get("hbm_peak_bytes") or 0) > 0}
    if len(hbm) >= 2:
        ordered = sorted(hbm)
        vals = [hbm[r] for r in ordered]
        vmax, vmin = max(vals), min(vals)
        pct = 100.0 * (vmax - vmin) / vmin if vmin > 0 else 0.0
        lines.append(
            f"memory skew hbm_peak_bytes: max-min "
            f"{(vmax - vmin) / 2**20:.2f} MiB (+{pct:.1f}%, "
            f"max r{ordered[vals.index(vmax)]} / "
            f"min r{ordered[vals.index(vmin)]})")
    return lines


def merged_manifest_extra(merged: dict) -> dict:
    """The slim merged-telemetry block a RunManifest carries under
    ``extra`` (skew + stragglers + merged counters; per-rank detail
    lives in ``ranks[]``)."""
    return {
        "merged_counters": dict(merged.get("counters") or {}),
        "span_skew": merged.get("span_skew") or {},
        "reservoir_skew": merged.get("reservoir_skew") or {},
        "stragglers": attribute_stragglers(merged),
        "world": merged.get("world"),
    }


def artifact_sha(path: str) -> Optional[str]:
    """sha256 of an artifact file (rank-report provenance lines)."""
    try:
        with open(path, "rb") as fh:
            return hashlib.sha256(fh.read()).hexdigest()[:16]
    except OSError:
        return None
