"""jaxlint stage 3: concurrency analysis of the threaded control plane.

Stages 1-2 audit trace-time and compiled-HLO hazards; this stage audits
the *threads*.  The serving fleet north-star (ROADMAP item 5) rests on
~10 multithreaded modules (`serving/queue.py`, `serving/engine.py`,
`obs/telemetry.py`, `obs/flightrec.py`, `obs/memory.py`,
`resilience/retry.py`, `native.py`) whose only race/deadlock defense
before this pass was code review.  The reference gets its thread
discipline from C++11 + OpenMP structure; the Python control plane gets
the equivalent from this analyzer plus the runtime sanitizer
(`analysis/lockcheck.py`, docs/jaxlint.md).

Scope model
-----------
A module is **threaded scope** when it lives under ``serving/``,
``obs/``, or ``resilience/``, or is ``native.py`` — the tier where
dispatcher threads, scrape handlers, and signal handlers interleave.
``device-sync-under-lock`` narrows to ``serving/``/``obs/`` (the
request path where a sync while holding a lock serializes the queue).
``signal-unsafe-lock`` is package-wide: it follows the call graph from
every registered signal handler, across modules.

Thread-entry inference: a function is thread-side when it is a
``threading.Thread(target=...)``, when it blocks in a
``Condition.wait`` loop (the consumer half of a producer/consumer
pair), or when it is registered as a signal handler in a
``resilience/`` module (CPython delivers signals as asynchronous
interleaves on the main thread — same shared-state discipline).

Known static limits (the runtime sanitizer covers the gap): calls
through singleton accessors (``get_telemetry().count(...)``), locks
passed as arguments, and in-place mutation of container attributes via
method calls (``self.buf.append(...)``) are not tracked.

Suppression: same pragmas as stages 1-2 —
``# jaxlint: disable=<rule>`` on the flagged line, or
``# jaxlint: disable-file=<rule>`` anywhere in the file.  Stage-3
suppressions must state the protecting invariant inline (see
docs/jaxlint.md): a suppression without the reason a race cannot
happen is a finding in itself.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .ast_rules import Finding, _dotted, _suppressions

# rule id -> one-line description (the CLI prints this table)
CONCURRENCY_RULES: Dict[str, str] = {
    "shared-state-unlocked": (
        "an instance/module attribute shared between thread-entry code "
        "(Thread targets, Condition.wait consumers, resilience/ signal "
        "handlers) and other callers is written without a common "
        "`with <lock>:` guard — a torn read/lost update under "
        "interleaving.  Guard both sides with the same lock, or "
        "suppress with the invariant that makes the race impossible "
        "written inline"
    ),
    "lock-order-cycle": (
        "the module's lock-acquisition graph (nested `with lock:` "
        "scopes plus calls made while a lock is held) contains a "
        "cycle: two threads taking the locks in opposite orders "
        "deadlock.  Impose one global order (acquire A before B "
        "everywhere) or collapse to a single lock"
    ),
    "device-sync-under-lock": (
        "a host sync/materialization (np.asarray/np.array, .item(), "
        ".tolist(), .block_until_ready(), jax.device_get) lexically "
        "inside a `with lock:` body in a serving/obs module: every "
        "other thread queues behind a device round-trip — the p99 "
        "hazard where one dispatch serializes the whole queue.  Move "
        "the sync outside the critical section (snapshot under the "
        "lock, materialize after)"
    ),
    "signal-unsafe-lock": (
        "a plain threading.Lock is acquired on a path reachable from a "
        "registered signal handler: a signal delivered while the main "
        "thread already holds the lock re-enters and self-deadlocks "
        "(the hazard obs/telemetry.py's store RLock exists for).  Use "
        "an RLock, or keep the handler path lock-free"
    ),
}

_THREADED_DIR_PARTS = ("serving", "obs", "resilience")
_THREADED_FILES = ("native.py",)
_SYNC_SCOPE_DIR_PARTS = ("serving", "obs")

# lock-constructor spellings -> lock kind; both the raw threading
# primitives and the analysis.lockcheck factories (the instrumented
# spellings the threaded modules adopt) classify identically
_LOCK_CTORS: Dict[str, str] = {
    "threading.Lock": "lock", "Lock": "lock",
    "threading.RLock": "rlock", "RLock": "rlock",
    "threading.Condition": "condition", "Condition": "condition",
    "lockcheck.make_lock": "lock", "make_lock": "lock",
    "lockcheck.make_rlock": "rlock", "make_rlock": "rlock",
    "lockcheck.make_condition": "condition", "make_condition": "condition",
}

_SYNC_CALLS = {
    "np.asarray", "np.array", "np.ascontiguousarray",
    "numpy.asarray", "numpy.array", "numpy.ascontiguousarray",
    "jax.device_get",
}
_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}

_THREAD_CTORS = ("threading.Thread", "Thread")
_PKG = "lightgbm_tpu"


def _is_threaded_scope(path: str) -> bool:
    parts = path.replace(os.sep, "/").split("/")
    if any(p in _THREADED_DIR_PARTS for p in parts[:-1]):
        return True
    return parts[-1] in _THREADED_FILES


def _is_sync_scope(path: str) -> bool:
    parts = path.replace(os.sep, "/").split("/")
    return any(p in _SYNC_SCOPE_DIR_PARTS for p in parts[:-1])


def _is_resilience(path: str) -> bool:
    parts = path.replace(os.sep, "/").split("/")
    return "resilience" in parts[:-1]


def _module_name(path: str) -> str:
    """Dotted package-relative module name ('obs.flightrec')."""
    parts = path.replace(os.sep, "/").split("/")
    if _PKG in parts:
        parts = parts[parts.index(_PKG) + 1:]
    name = "/".join(parts)
    if name.endswith(".py"):
        name = name[:-3]
    return name.replace("/", ".") or "<module>"


def _lock_kind(value: ast.AST) -> Optional[str]:
    if not isinstance(value, ast.Call):
        return None
    d = _dotted(value.func)
    return _LOCK_CTORS.get(d) if d else None


class _FnRecord:
    """Everything one function body contributes to the analysis."""

    __slots__ = ("key", "line", "accesses", "global_accesses",
                 "acquire_sites", "nest_edges", "calls", "sync_sites",
                 "wait_entry", "thread_targets", "signal_handlers")

    def __init__(self, key: Tuple[Optional[str], str], line: int) -> None:
        self.key = key
        self.line = line
        # (attr, is_write, line, guards) for self.<attr> accesses
        self.accesses: List[Tuple[str, bool, int, frozenset]] = []
        # (name, is_write, line, guards) for module-global accesses
        self.global_accesses: List[Tuple[str, bool, int, frozenset]] = []
        # (lock_id, kind, line) — every `with lock:` / lock.acquire()
        self.acquire_sites: List[Tuple[str, str, int]] = []
        # (held_lock_id, acquired_lock_id, line) from lexical nesting
        self.nest_edges: List[Tuple[str, str, int]] = []
        # (dotted_callee, line, guards)
        self.calls: List[Tuple[str, int, frozenset]] = []
        # (label, line, guards) — host-sync patterns
        self.sync_sites: List[Tuple[str, int, frozenset]] = []
        self.wait_entry = False
        # dotted Thread target= expressions seen in this body
        self.thread_targets: List[str] = []
        # dotted signal.signal handler expressions seen in this body
        self.signal_handlers: List[str] = []


class _ClassInfo:
    __slots__ = ("name", "methods", "locks")

    def __init__(self, name: str) -> None:
        self.name = name
        self.methods: Dict[str, ast.AST] = {}
        self.locks: Dict[str, str] = {}  # attr -> kind


class _ModuleInfo:
    __slots__ = ("name", "path", "source", "tree", "module_locks",
                 "module_globals", "classes", "functions", "records",
                 "import_map")

    def __init__(self, name: str, path: str, source: str,
                 tree: ast.Module) -> None:
        self.name = name
        self.path = path
        self.source = source
        self.tree = tree
        self.module_locks: Dict[str, str] = {}
        self.module_globals: Set[str] = set()
        self.classes: Dict[str, _ClassInfo] = {}
        # every def in the module (incl. nested), by bare name
        self.functions: Dict[str, ast.AST] = {}
        self.records: Dict[Tuple[Optional[str], str], _FnRecord] = {}
        self.import_map: Dict[str, str] = {}  # alias -> dotted module


class _BodyWalker(ast.NodeVisitor):
    """Walk one function body (or module top level) tracking the stack
    of lexically held locks; nested defs are recorded but not entered
    (each gets its own record)."""

    def __init__(self, mod: _ModuleInfo, cls: Optional[_ClassInfo],
                 rec: _FnRecord) -> None:
        self.mod = mod
        self.cls = cls
        self.rec = rec
        self.guards: List[str] = []

    # ------------------------------------------------------ lock naming
    def _resolve_lock(self, expr: ast.AST) -> Optional[Tuple[str, str]]:
        d = _dotted(expr)
        if not d:
            return None
        if d.startswith("self.") and self.cls is not None:
            attr = d[len("self."):]
            kind = self.cls.locks.get(attr)
            if kind:
                return f"{self.cls.name}.{attr}", kind
            return None
        kind = self.mod.module_locks.get(d)
        if kind:
            return d, kind
        return None

    def _guardset(self) -> frozenset:
        return frozenset(self.guards)

    # -------------------------------------------------------- structure
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass  # separate record; do not descend

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        pass  # nested classes: out of scope

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            resolved = self._resolve_lock(item.context_expr)
            if resolved is None:
                self.visit(item.context_expr)
                continue
            lock_id, kind = resolved
            line = item.context_expr.lineno
            self.rec.acquire_sites.append((lock_id, kind, line))
            if self.guards:
                self.rec.nest_edges.append((self.guards[-1], lock_id, line))
            self.guards.append(lock_id)
            pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.guards.pop()

    visit_AsyncWith = visit_With

    # ------------------------------------------------------ assignments
    def _record_target(self, tgt: ast.AST) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._record_target(e)
            return
        if isinstance(tgt, ast.Starred):
            self._record_target(tgt.value)
            return
        # peel subscripts: `self.d[k] = v` writes attribute d
        node = tgt
        while isinstance(node, ast.Subscript):
            node = node.value
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            self.rec.accesses.append(
                (node.attr, True, tgt.lineno, self._guardset()))
        elif (isinstance(node, ast.Name)
              and node.id in self.mod.module_globals):
            self.rec.global_accesses.append(
                (node.id, True, tgt.lineno, self._guardset()))
        if isinstance(tgt, ast.Subscript):
            self.visit(tgt.slice)

    def visit_Assign(self, node: ast.Assign) -> None:
        # record self.<attr> lock constructions for completeness (the
        # collector pre-pass already indexed them)
        for tgt in node.targets:
            self._record_target(tgt)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_target(node.target)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_target(node.target)
            self.visit(node.value)

    # ------------------------------------------------------------ reads
    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (isinstance(node.value, ast.Name) and node.value.id == "self"
                and isinstance(node.ctx, ast.Load)):
            self.rec.accesses.append(
                (node.attr, False, node.lineno, self._guardset()))
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if (isinstance(node.ctx, ast.Load)
                and node.id in self.mod.module_globals):
            self.rec.global_accesses.append(
                (node.id, False, node.lineno, self._guardset()))

    # ------------------------------------------------------------ calls
    def visit_Call(self, node: ast.Call) -> None:
        d = _dotted(node.func)
        guards = self._guardset()
        if d:
            self.rec.calls.append((d, node.lineno, guards))
            if d in _SYNC_CALLS and guards:
                self.rec.sync_sites.append((d, node.lineno, guards))
            if d in _THREAD_CTORS:
                for kw in node.keywords:
                    if kw.arg == "target":
                        t = _dotted(kw.value)
                        if t:
                            self.rec.thread_targets.append(t)
            if d == "signal.signal" and len(node.args) == 2:
                h = _dotted(node.args[1])
                if h:
                    self.rec.signal_handlers.append(h)
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr in _SYNC_ATTRS and guards:
                self.rec.sync_sites.append(
                    (f".{attr}()", node.lineno, guards))
            if attr in ("wait", "wait_for"):
                resolved = self._resolve_lock(node.func.value)
                if resolved is not None and resolved[1] == "condition":
                    self.rec.wait_entry = True
            if attr == "acquire":
                resolved = self._resolve_lock(node.func.value)
                if resolved is not None:
                    self.rec.acquire_sites.append(
                        (resolved[0], resolved[1], node.lineno))
        self.generic_visit(node)


# ------------------------------------------------------------- collection
def _collect_module(path: str, source: str,
                    tree: ast.Module) -> _ModuleInfo:
    mod = _ModuleInfo(_module_name(path), path, source, tree)

    # module-level names + locks
    for stmt in tree.body:
        targets: List[ast.AST] = []
        value: Optional[ast.AST] = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if value is None:
            continue
        kind = _lock_kind(value)
        for tgt in targets:
            if not isinstance(tgt, ast.Name):
                continue
            if kind:
                mod.module_locks[tgt.id] = kind
            else:
                mod.module_globals.add(tgt.id)

    # classes: methods + instance locks (self.<x> = Lock() anywhere)
    for stmt in tree.body:
        if not isinstance(stmt, ast.ClassDef):
            continue
        ci = _ClassInfo(stmt.name)
        for item in stmt.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[item.name] = item
        for item in ast.walk(stmt):
            if not isinstance(item, ast.Assign):
                continue
            kind = _lock_kind(item.value)
            if not kind:
                continue
            for tgt in item.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    ci.locks[tgt.attr] = kind
        mod.classes[stmt.name] = ci

    # every def in the module, by bare name (nested defs included so
    # Thread targets like retry.py's deadline worker resolve)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod.functions.setdefault(node.name, node)

    # walk bodies: methods (with class context), functions, module level
    walked: Set[int] = set()

    def walk_body(fn: ast.AST, key: Tuple[Optional[str], str],
                  cls: Optional[_ClassInfo]) -> None:
        rec = _FnRecord(key, getattr(fn, "lineno", 0))
        walker = _BodyWalker(mod, cls, rec)
        for stmt in fn.body:  # type: ignore[attr-defined]
            walker.visit(stmt)
        mod.records[key] = rec

    for cname, ci in mod.classes.items():
        for mname, fn in ci.methods.items():
            walked.add(id(fn))
            walk_body(fn, (cname, mname), ci)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if id(node) in walked:
                continue
            walked.add(id(node))
            walk_body(node, (None, node.name), None)

    # module top level (registrations like signal.signal at import)
    top = _FnRecord((None, "<module>"), 1)
    walker = _BodyWalker(mod, None, top)
    for stmt in tree.body:
        walker.visit(stmt)
    mod.records[(None, "<module>")] = top
    return mod


def _resolve_imports(mods: Dict[str, _ModuleInfo]) -> None:
    """alias -> package module, for cross-module call resolution."""
    for mod in mods.values():
        pkg_parts = mod.name.split(".")[:-1]
        for stmt in ast.walk(mod.tree):
            if isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    name = alias.name
                    if name.startswith(_PKG + "."):
                        name = name[len(_PKG) + 1:]
                    if name in mods:
                        mod.import_map[alias.asname
                                       or alias.name.split(".")[-1]] = name
            elif isinstance(stmt, ast.ImportFrom):
                if stmt.level == 0:
                    base = (stmt.module or "").split(".")
                    if base and base[0] == _PKG:
                        base = base[1:]
                    elif stmt.module not in (None, _PKG):
                        continue  # stdlib / third-party
                else:
                    keep = len(pkg_parts) - (stmt.level - 1)
                    if keep < 0:
                        continue
                    base = pkg_parts[:keep]
                    if stmt.module:
                        base = base + stmt.module.split(".")
                for alias in stmt.names:
                    cand = ".".join(base + [alias.name]).strip(".")
                    if cand in mods:
                        mod.import_map[alias.asname or alias.name] = cand


# -------------------------------------------------------- thread entries
def _resolve_local(mod: _ModuleInfo, dotted: str,
                   cls: Optional[str]) -> Optional[Tuple[Optional[str], str]]:
    """A dotted callee/target -> a record key in the SAME module."""
    if dotted.startswith("self.") and cls is not None:
        m = dotted[len("self."):]
        if "." not in m and m in mod.classes[cls].methods:
            return (cls, m)
        return None
    if "." not in dotted:
        if dotted in mod.functions:
            return (None, dotted)
    return None


def _class_thread_entries(mod: _ModuleInfo) -> Dict[str, Set[str]]:
    """class name -> method names that run on the thread side."""
    entries: Dict[str, Set[str]] = {c: set() for c in mod.classes}
    resilience = _is_resilience(mod.path)
    for key, rec in mod.records.items():
        cls = key[0]
        for tgt in rec.thread_targets:
            resolved = _resolve_local(mod, tgt, cls)
            if resolved and resolved[0] is not None:
                entries[resolved[0]].add(resolved[1])
        if resilience:
            for h in rec.signal_handlers:
                resolved = _resolve_local(mod, h, cls)
                if resolved and resolved[0] is not None:
                    entries[resolved[0]].add(resolved[1])
        if rec.wait_entry and cls is not None:
            entries[cls].add(key[1])
    return entries


def _module_fn_entries(mod: _ModuleInfo) -> Set[str]:
    """Module-level functions that run on the thread side."""
    entries: Set[str] = set()
    resilience = _is_resilience(mod.path)
    for key, rec in mod.records.items():
        for tgt in rec.thread_targets:
            resolved = _resolve_local(mod, tgt, key[0])
            if resolved and resolved[0] is None:
                entries.add(resolved[1])
        if resilience:
            for h in rec.signal_handlers:
                resolved = _resolve_local(mod, h, key[0])
                if resolved and resolved[0] is None:
                    entries.add(resolved[1])
        if rec.wait_entry and key[0] is None and key[1] != "<module>":
            entries.add(key[1])
    return entries


def _closure(seed: Set[str], edges: Dict[str, Set[str]]) -> Set[str]:
    out = set(seed)
    frontier = list(seed)
    while frontier:
        cur = frontier.pop()
        for nxt in edges.get(cur, ()):
            if nxt not in out:
                out.add(nxt)
                frontier.append(nxt)
    return out


# ------------------------------------------------- rule: shared state
def _rule_shared_state(mod: _ModuleInfo) -> List[Finding]:
    findings: List[Finding] = []
    class_entries = _class_thread_entries(mod)

    for cname, ci in mod.classes.items():
        seed = class_entries.get(cname) or set()
        if not seed:
            continue
        # intra-class call graph over self.<m>() calls
        edges: Dict[str, Set[str]] = {}
        for mname in ci.methods:
            rec = mod.records.get((cname, mname))
            if rec is None:
                continue
            outs: Set[str] = set()
            for d, _line, _g in rec.calls:
                r = _resolve_local(mod, d, cname)
                if r is not None and r[0] == cname:
                    outs.add(r[1])
            edges[mname] = outs
        thread_side = _closure(seed, edges)

        # attr -> [(is_thread_side, is_write, line, guards)]
        by_attr: Dict[str, List[Tuple[bool, bool, int, frozenset]]] = {}
        for mname in ci.methods:
            if mname == "__init__":
                continue  # construction happens-before every thread
            rec = mod.records.get((cname, mname))
            if rec is None:
                continue
            side = mname in thread_side
            for attr, is_write, line, guards in rec.accesses:
                if attr in ci.locks:
                    continue
                by_attr.setdefault(attr, []).append(
                    (side, is_write, line, guards))

        for attr in sorted(by_attr):
            acc = by_attr[attr]
            writes = [a for a in acc if a[1]]
            if not writes:
                continue
            sides = {a[0] for a in acc}
            if len(sides) < 2:
                continue  # not shared across the thread boundary
            common = frozenset.intersection(*[a[3] for a in writes])
            if common:
                continue
            bad = min((w for w in writes if not w[3]),
                      default=min(writes, key=lambda w: w[2]),
                      key=lambda w: w[2])
            entry_names = ", ".join(sorted(seed))
            findings.append(Finding(
                "shared-state-unlocked", mod.path, bad[2],
                f"'{cname}.{attr}' is written here and shared with "
                f"thread-entry code ({entry_names}) without a common "
                "`with <lock>:` guard on every write — guard both "
                "sides with one lock, or state the invariant inline "
                "and suppress"))

    # module-global half
    fn_entries = _module_fn_entries(mod)
    if fn_entries:
        edges = {}
        for key, rec in mod.records.items():
            if key[0] is not None:
                continue
            outs = set()
            for d, _line, _g in rec.calls:
                r = _resolve_local(mod, d, None)
                if r is not None and r[0] is None:
                    outs.add(r[1])
            edges[key[1]] = outs
        thread_side = _closure(fn_entries, edges)
        by_name: Dict[str, List[Tuple[bool, bool, int, frozenset]]] = {}
        for key, rec in mod.records.items():
            if key[0] is not None or key[1] == "<module>":
                continue
            side = key[1] in thread_side
            for name, is_write, line, guards in rec.global_accesses:
                by_name.setdefault(name, []).append(
                    (side, is_write, line, guards))
        for name in sorted(by_name):
            acc = by_name[name]
            writes = [a for a in acc if a[1]]
            if not writes or len({a[0] for a in acc}) < 2:
                continue
            common = frozenset.intersection(*[a[3] for a in writes])
            if common:
                continue
            bad = min((w for w in writes if not w[3]),
                      default=min(writes, key=lambda w: w[2]),
                      key=lambda w: w[2])
            findings.append(Finding(
                "shared-state-unlocked", mod.path, bad[2],
                f"module global '{name}' is written here and shared "
                f"with thread-entry code ({', '.join(sorted(fn_entries))}) "
                "without a common lock guard on every write"))
    return findings


# ------------------------------------------------- rule: lock order
def _rule_lock_order(mod: _ModuleInfo) -> List[Finding]:
    kinds: Dict[str, str] = dict(mod.module_locks)
    for cname, ci in mod.classes.items():
        for attr, kind in ci.locks.items():
            kinds[f"{cname}.{attr}"] = kind
    if len(kinds) == 0:
        return []

    # per-function may-acquire sets, closed over intra-module calls
    acq: Dict[Tuple[Optional[str], str], Set[str]] = {
        key: {a[0] for a in rec.acquire_sites}
        for key, rec in mod.records.items()}
    call_edges: Dict[Tuple[Optional[str], str],
                     Set[Tuple[Optional[str], str]]] = {}
    for key, rec in mod.records.items():
        outs = set()
        for d, _line, _g in rec.calls:
            r = _resolve_local(mod, d, key[0])
            if r is not None and r in mod.records:
                outs.add(r)
        call_edges[key] = outs
    changed = True
    while changed:
        changed = False
        for key, outs in call_edges.items():
            before = len(acq[key])
            for o in outs:
                acq[key] |= acq[o]
            changed = changed or len(acq[key]) != before

    # edges: lexical nesting + calls made while a lock is held
    edge_line: Dict[Tuple[str, str], int] = {}

    def add_edge(a: str, b: str, line: int) -> None:
        if a == b:
            return
        if (a, b) not in edge_line or line < edge_line[(a, b)]:
            edge_line[(a, b)] = line

    self_nest: Dict[str, int] = {}
    for key, rec in mod.records.items():
        for a, b, line in rec.nest_edges:
            if a == b and kinds.get(a) == "lock":
                if a not in self_nest or line < self_nest[a]:
                    self_nest[a] = line
            add_edge(a, b, line)
        for d, line, guards in rec.calls:
            if not guards:
                continue
            r = _resolve_local(mod, d, key[0])
            if r is None or r not in mod.records:
                continue
            for held in guards:
                for inner in acq[r]:
                    add_edge(held, inner, line)

    findings: List[Finding] = []
    for lock, line in sorted(self_nest.items()):
        findings.append(Finding(
            "lock-order-cycle", mod.path, line,
            f"non-reentrant lock '{lock}' is re-acquired while already "
            "held — guaranteed self-deadlock (use an RLock if "
            "re-entry is intended)"))

    # SCCs of the acquisition graph (iterative Tarjan)
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edge_line:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    for scc in sorted(sccs):
        members = set(scc)
        lines = [line for (a, b), line in edge_line.items()
                 if a in members and b in members]
        findings.append(Finding(
            "lock-order-cycle", mod.path, min(lines),
            "lock-acquisition cycle between "
            + " <-> ".join(f"'{name}'" for name in scc)
            + ": two threads taking them in opposite orders deadlock — "
            "impose one global acquisition order"))
    return findings


# ------------------------------------------- rule: sync under lock
def _rule_sync_under_lock(mod: _ModuleInfo) -> List[Finding]:
    findings: List[Finding] = []
    for rec in mod.records.values():
        for label, line, guards in rec.sync_sites:
            held = ", ".join(f"'{g}'" for g in sorted(guards))
            findings.append(Finding(
                "device-sync-under-lock", mod.path, line,
                f"{label} blocks on the device while holding {held}: "
                "every other thread queues behind the round-trip — "
                "move the materialization outside the critical section"))
    return findings


# ------------------------------------------ rule: signal-unsafe lock
def _rule_signal_unsafe(mods: Dict[str, _ModuleInfo]) -> List[Finding]:
    NodeKey = Tuple[str, Optional[str], str]  # (module, class, fn)

    def resolve(mod: _ModuleInfo, dotted: str,
                cls: Optional[str]) -> Optional[NodeKey]:
        local = _resolve_local(mod, dotted, cls)
        if local is not None:
            return (mod.name, local[0], local[1])
        parts = dotted.split(".")
        if len(parts) == 2 and parts[0] in mod.import_map:
            target = mods[mod.import_map[parts[0]]]
            if parts[1] in target.functions:
                return (target.name, None, parts[1])
            if parts[1] in target.classes:
                if "__init__" in target.classes[parts[1]].methods:
                    return (target.name, parts[1], "__init__")
        return None

    # seeds: every registered handler, package-wide
    seeds: List[Tuple[NodeKey, str]] = []
    for mod in mods.values():
        for key, rec in mod.records.items():
            for h in rec.signal_handlers:
                r = resolve(mod, h, key[0])
                if r is not None:
                    seeds.append((r, f"{mod.name}.{h}"))

    # BFS over the cross-module call graph, remembering one path
    origin: Dict[NodeKey, Tuple[str, Optional[NodeKey]]] = {}
    frontier: List[NodeKey] = []
    for node, label in seeds:
        if node not in origin:
            origin[node] = (label, None)
            frontier.append(node)
    while frontier:
        cur = frontier.pop()
        mod = mods[cur[0]]
        rec = mod.records.get((cur[1], cur[2]))
        if rec is None:
            continue
        label = origin[cur][0]
        for d, _line, _g in rec.calls:
            nxt = resolve(mod, d, cur[1])
            if nxt is not None and nxt not in origin:
                origin[nxt] = (label, cur)
                frontier.append(nxt)

    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for node in origin:
        mod = mods[node[0]]
        rec = mod.records.get((node[1], node[2]))
        if rec is None or node[2] == "<module>":
            continue
        for lock_id, kind, line in rec.acquire_sites:
            if kind != "lock":
                continue  # RLock re-entry is exactly the safe pattern
            if (mod.path, line) in seen:
                continue
            seen.add((mod.path, line))
            handler = origin[node][0]
            findings.append(Finding(
                "signal-unsafe-lock", mod.path, line,
                f"plain Lock '{lock_id}' is acquired on a path "
                f"reachable from signal handler {handler}: a signal "
                "delivered while the main thread holds it re-enters "
                "and self-deadlocks — use an RLock (the telemetry "
                "store precedent) or keep the handler path lock-free"))
    return findings


# ------------------------------------------------------------ entry points
def lint_concurrency_sources(sources: Dict[str, str],
                             rules: Optional[Iterable[str]] = None
                             ) -> List[Finding]:
    """Analyze a set of ``{path: source}`` modules as one package."""
    findings: List[Finding] = []
    mods: Dict[str, _ModuleInfo] = {}
    for path in sorted(sources):
        src = sources[path]
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            findings.append(
                Finding("syntax-error", path, e.lineno or 0, str(e.msg)))
            continue
        mi = _collect_module(path, src, tree)
        mods[mi.name] = mi
    _resolve_imports(mods)

    for name in sorted(mods):
        mi = mods[name]
        if _is_threaded_scope(mi.path):
            findings.extend(_rule_shared_state(mi))
            findings.extend(_rule_lock_order(mi))
        if _is_sync_scope(mi.path):
            findings.extend(_rule_sync_under_lock(mi))
    findings.extend(_rule_signal_unsafe(mods))

    active = set(rules) if rules is not None else set(CONCURRENCY_RULES)
    out: List[Finding] = []
    for f in findings:
        if f.rule == "syntax-error":
            out.append(f)
            continue
        if f.rule not in active:
            continue
        src = sources.get(f.path)
        file_sup, line_sup = _suppressions(src) if src else (set(), {})
        if f.rule in file_sup or f.rule in line_sup.get(f.line, ()):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def lint_concurrency_source(source: str,
                            path: str = "lightgbm_tpu/serving/mod.py",
                            rules: Optional[Iterable[str]] = None
                            ) -> List[Finding]:
    """Analyze one module in isolation (tests/fixtures)."""
    return lint_concurrency_sources({path: source}, rules=rules)


def lint_concurrency_paths(paths: Iterable[str],
                           rules: Optional[Iterable[str]] = None
                           ) -> List[Finding]:
    """Stage-3 lint over .py files (recursing into directories).

    The whole argument set is analyzed as ONE package, so
    ``signal-unsafe-lock`` follows handler paths across modules."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                if "__pycache__" in root:
                    continue
                for n in sorted(names):
                    if n.endswith(".py"):
                        files.append(os.path.join(root, n))
        elif p.endswith(".py"):
            files.append(p)
    sources: Dict[str, str] = {}
    for fp in sorted(files):
        with open(fp, encoding="utf-8") as fh:
            sources[fp] = fh.read()
    return lint_concurrency_sources(sources, rules=rules)
