"""jaxlint stage 1: AST rules over the package source.

Scope model
-----------
A function is **traced** when its body runs under ``jax.jit`` tracing:

* decorated with ``jax.jit`` / ``functools.partial(jax.jit, ...)``,
* wrapped at module level (``f = jax.jit(g)``) or lazily
  (``self._jfn = jax.jit(self.eval_jax)`` marks same-file methods named
  ``eval_jax``),
* lexically nested inside a traced function, or
* called (by simple name, including through ``functools.partial``)
  from a traced function in the same module — a fixpoint over the
  module-local call graph, so helpers like the tier-chain builders in
  ``learners/serial.py`` are correctly treated as trace-time code.

A function is **hot** when its module lives under ``learners/``,
``ops/``, ``parallel/``, or is ``models/gbdt.py`` / ``engine.py`` —
the per-iteration training path where a host sync inside a Python loop
drains the dispatch pipeline every tree (the class of regression the
round-3 lagged-stop work measured at ~0.3 s/tree over the TPU tunnel).

Suppression: append ``# jaxlint: disable=<rule>[,<rule>]`` to the
flagged line, or put ``# jaxlint: disable-file=<rule>`` on any line to
suppress a rule for the whole file.  Suppressions are for sites where
the flagged behavior is INTENTIONAL and documented (e.g. the f64
reference-parity accumulation in metrics.py) — not a way to mute real
findings.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

# ---------------------------------------------------------------- findings

@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class _Loc:
    """Synthetic location carrier for findings computed after the walk
    (only ``lineno`` is read by :meth:`_RuleWalker.flag`)."""

    __slots__ = ("lineno",)

    def __init__(self, lineno: int) -> None:
        self.lineno = lineno


# rule id -> one-line description (the CLI prints this table)
AST_RULES: Dict[str, str] = {
    "host-sync-in-jit": (
        "host transfer/materialization (np.asarray/np.array, .item(), "
        ".tolist(), jax.device_get, .block_until_ready()) inside a "
        "jit-traced function: executes at trace time on tracers (error "
        "or silent constant-folding) and defeats async dispatch"
    ),
    "python-loop-over-device-array": (
        "Python for-loop iterating a device array inside a jit-traced "
        "function: unrolls the trace per element and syncs per element "
        "when leaked to eager code"
    ),
    "env-read-at-trace": (
        "os.environ read inside a jit-traced function: the value is "
        "baked at trace time but the jit cache keys only on shapes/"
        "statics, so a mid-process env flip silently does not apply — "
        "read once at module import instead (ADVICE r3 convention)"
    ),
    "f64-literal-in-traced": (
        "explicit float64 dtype in jit-traced code: under default "
        "x64-disabled semantics this silently truncates to f32, and "
        "under enable_x64 it doubles histogram/score bandwidth — gate "
        "deliberate f64 paths behind a file-level suppression with the "
        "justification in a comment"
    ),
    "jit-cache-miss-risk": (
        "jax.jit of a lambda inside a function body, or any jax.jit "
        "call inside a loop: every evaluation builds a fresh callable "
        "with an empty jit cache, retracing and recompiling per call"
    ),
    "host-sync-in-loop": (
        "host materialization (float(f(...)), int(f(...)), np.asarray, "
        "np.array, .item(), .tolist()) inside a Python loop in a hot "
        "module: one device sync per iteration drains the dispatch "
        "pipeline (measured ~0.3 s/tree over the TPU tunnel at 1M rows)"
    ),
    "wallclock-without-sync": (
        "time.time()/perf_counter() stop timestamp around jax/jnp "
        "device computation with no block_until_ready/device_get/"
        "np.asarray sync before the stop: async dispatch returns "
        "before the device finishes, so the elapsed time measures "
        "dispatch, not compute (the mis-timing hazard behind every "
        "too-good-to-be-true bench number)"
    ),
    "raw-artifact-write": (
        "open(path, 'w'/'x') or json.dump(obj, open(...)) writes an "
        "artifact non-atomically: a preemption mid-write leaves half a "
        "file under the real name (a truncated model silently LOADS, "
        "with fewer trees).  Route result artifacts through "
        "resilience.atomic_write / atomic_write_json / atomic_writer "
        "(tmp + fsync + rename); append-mode logs are exempt"
    ),
    "device-buffer-retention": (
        "module-global or class-attribute assignment of a jax/jnp "
        "device value from runtime code in a hot/serving/obs module: "
        "the buffer is pinned in device memory for the process "
        "lifetime, invisible to owner-attributed census accounting "
        "(obs/memory.py) and to hot-swap reclamation.  Keep device "
        "buffers on instances registered via obs.memory.register_owner "
        "(docs/memory.md), or suppress with the justification inline"
    ),
    "unbounded-event-buffer": (
        "append/extend to a module-level list from function code in a "
        "hot/serving/obs module with no maxlen/ring discipline: a "
        "long-lived serving replica grows it without bound until the "
        "host OOMs (per-request event logs are the classic case).  Use "
        "collections.deque(maxlen=N) — append+evict is one atomic, "
        "capped operation (obs/flightrec.py's ring is the pattern)"
    ),
}

_HOT_DIR_PARTS = ("learners", "ops", "parallel")
_HOT_FILES = ("gbdt.py", "engine.py")
# unbounded-event-buffer scope: the hot modules PLUS the long-lived
# server/observability tiers, where an uncapped event list outlives
# every request that fed it
_EVENT_SCOPE_DIR_PARTS = ("serving", "obs")

_NP_NAMES = {"np", "numpy", "onp"}
# numpy calls that pull data to (or materialize on) the host; pure
# host-side allocation (zeros/ones/empty/arange/...) is NOT flagged
_NP_SYNC_FUNCS = {"asarray", "array", "ascontiguousarray"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# iteration wrappers that never yield a device array element-by-element
_SAFE_ITER_CALLS = {
    "range", "enumerate", "zip", "reversed", "sorted", "len", "list",
    "tuple", "dict", "set", "items", "keys", "values", "split",
    "splitlines", "product", "combinations", "chain",
}

_PRAGMA_LINE = re.compile(r"#\s*jaxlint:\s*disable=([\w,\-]+)")
_PRAGMA_FILE = re.compile(r"#\s*jaxlint:\s*disable-file=([\w,\-]+)")

# wallclock-without-sync machinery: wall-clock sources, device-compute
# roots, and the sync calls that make a stop timestamp honest
_TIME_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
               "perf_counter", "monotonic"}
_DEVICE_ROOTS = {"jax", "jnp"}
_SYNC_LEAVES = {"block_until_ready", "device_get", "item", "tolist"}


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jax_jit(node: ast.AST) -> bool:
    return _dotted(node) in ("jax.jit", "jit", "pjit", "jax.pjit")


def _is_partial_of_jit(call: ast.Call) -> bool:
    if _dotted(call.func) not in ("functools.partial", "partial"):
        return False
    return bool(call.args) and _is_jax_jit(call.args[0])


def _jit_decorated(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        if _is_jax_jit(dec):
            return True
        if isinstance(dec, ast.Call):
            if _is_jax_jit(dec.func) or _is_partial_of_jit(dec):
                return True
    return False


class _ModuleIndex(ast.NodeVisitor):
    """Collect module functions, jit roots, and the name-level call
    graph in one pass."""

    def __init__(self) -> None:
        self.functions: Dict[str, List[ast.AST]] = {}
        self.jit_roots: Set[str] = set()
        self.calls: Dict[str, Set[str]] = {}
        self._stack: List[str] = []

    def _add_fn(self, node: ast.AST) -> None:
        name = node.name  # type: ignore[attr-defined]
        self.functions.setdefault(name, []).append(node)
        if _jit_decorated(node):
            self.jit_roots.add(name)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._add_fn(node)
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Call(self, node: ast.Call) -> None:
        callee: Optional[str] = None
        func_name = _dotted(node.func)
        if _is_jax_jit(node.func) and node.args:
            # f = jax.jit(g) / self._jfn = jax.jit(self.eval_jax):
            # mark the wrapped function (by trailing name) as a root
            target = _dotted(node.args[0])
            if target is not None:
                self.jit_roots.add(target.split(".")[-1])
        if func_name is not None:
            if func_name in ("functools.partial", "partial") and node.args:
                inner = _dotted(node.args[0])
                if inner is not None:
                    callee = inner.split(".")[-1]
            else:
                callee = func_name.split(".")[-1]
        if callee and self._stack:
            self.calls.setdefault(self._stack[-1], set()).add(callee)
        self.generic_visit(node)


def _traced_functions(index: _ModuleIndex) -> Set[str]:
    """Fixpoint: jit roots + same-module functions they (transitively)
    call by name."""
    traced = set(index.jit_roots) & set(index.functions)
    changed = True
    while changed:
        changed = False
        for name in list(traced):
            for callee in index.calls.get(name, ()):
                if callee in index.functions and callee not in traced:
                    traced.add(callee)
                    changed = True
    return traced


class _RuleWalker(ast.NodeVisitor):
    """Walk one function body with (traced, hot, loop-depth) context."""

    def __init__(self, path: str, traced: bool, hot: bool,
                 findings: List[Finding],
                 jit_roots: Optional[Set[str]] = None,
                 module_lists: Optional[Set[str]] = None,
                 event_scope: bool = False,
                 module_classes: Optional[Set[str]] = None) -> None:
        self.path = path
        self.traced = traced
        self.hot = hot
        self.findings = findings
        self.loop_depth = 0
        self.jit_roots = jit_roots or set()
        # unbounded-event-buffer context: module-level bare-list names
        # (no maxlen discipline possible) + whether this module is a
        # hot/serving/obs scope the rule applies to
        self.module_lists = module_lists or set()
        self.event_scope = event_scope
        # device-buffer-retention context: module-level class names
        # (a ClassName.attr store is process-lifetime retention) and
        # names this function declared ``global``
        self.module_classes = module_classes or set()
        self._global_names: Set[str] = set()
        # wallclock-without-sync event streams (line-ordered within the
        # walked function; nested defs are walked separately)
        self._time_marks: Dict[str, List[int]] = {}
        self._device_lines: List[int] = []
        self._sync_lines: List[int] = []
        self._stops: List[Tuple[int, str]] = []

    def flag(self, rule: str, node: ast.AST, msg: str) -> None:
        self.findings.append(
            Finding(rule, self.path, getattr(node, "lineno", 0), msg))

    # nested defs are visited separately (lint_source's visit_scope)
    # with their own traced context — do not descend into them here
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_For(self, node: ast.For) -> None:
        if self.traced and not self._safe_iterable(node.iter):
            desc = _dotted(node.iter) or type(node.iter).__name__
            self.flag(
                "python-loop-over-device-array", node,
                f"for-loop iterates '{desc}' directly inside traced "
                "code; iterate range()/static containers or use "
                "lax.fori_loop/scan",
            )
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    def visit_While(self, node: ast.While) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    @staticmethod
    def _is_host_numpy_call(call: ast.Call) -> bool:
        """float(np.searchsorted(...))-style conversions of host-numpy
        results are host compute, not a device sync."""
        name = _dotted(call.func)
        return name is not None and name.split(".")[0] in _NP_NAMES

    @staticmethod
    def _safe_iterable(it: ast.AST) -> bool:
        if isinstance(it, (ast.List, ast.Tuple, ast.Dict, ast.Set,
                           ast.Constant, ast.GeneratorExp, ast.ListComp)):
            return True
        if isinstance(it, ast.Call):
            name = _dotted(it.func)
            if name is None:
                return False
            leaf = name.split(".")[-1]
            if leaf in _SAFE_ITER_CALLS:
                return True
            # sorted(x)/reversed(x)/zip(...) handled above by leaf name
            return False
        return False

    def visit_Subscript(self, node: ast.Subscript) -> None:
        self._check_environ(node, node.value)
        self.generic_visit(node)

    # -------------------------------------------- wallclock-without-sync
    @staticmethod
    def _is_time_call(node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and _dotted(node.func) in _TIME_CALLS)

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._is_time_call(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self._time_marks.setdefault(tgt.id, []).append(
                        node.lineno)
        self._check_buffer_retention(node, node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check_buffer_retention(node, [node.target], node.value)
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global) -> None:
        self._global_names.update(node.names)

    # ------------------------------------------ device-buffer-retention
    def _is_device_value(self, value: ast.AST) -> bool:
        """A jax/jnp-rooted call (or a call into one of this module's
        jit roots) — the expressions whose results live in device
        memory.  Host numpy and plain Python values are not flagged."""
        if not isinstance(value, ast.Call):
            return False
        if _is_jax_jit(value.func) or _is_partial_of_jit(value):
            # a cached jitted CALLABLE retains compiled code, not a
            # device buffer — the idiomatic module-level dispatch cache
            return False
        name = _dotted(value.func)
        if name is None:
            return False
        root, leaf = name.split(".")[0], name.split(".")[-1]
        return root in _DEVICE_ROOTS or leaf in self.jit_roots

    def _check_buffer_retention(self, node: ast.AST,
                                targets: List[ast.AST],
                                value: ast.AST) -> None:
        """device-buffer-retention: ``global NAME; NAME = jnp.f(...)``
        or ``ClassName.attr = jnp.f(...)`` from runtime code in an
        event-scope module parks a device buffer where no census owner
        can see it and no teardown frees it.  Instance attributes
        (``self.x = ...``) stay legal — they die with their owner."""
        if not self.event_scope or not self._is_device_value(value):
            return
        for tgt in targets:
            if isinstance(tgt, ast.Name) and tgt.id in self._global_names:
                self.flag(
                    "device-buffer-retention", node,
                    f"global '{tgt.id}' is bound to a device value from "
                    "runtime code: the buffer outlives every request and "
                    "is invisible to owner-attributed census accounting "
                    "— keep it on an instance registered via "
                    "obs.memory.register_owner (docs/memory.md)",
                )
            elif isinstance(tgt, ast.Attribute):
                root = tgt.value
                if (isinstance(root, ast.Name)
                        and root.id in self.module_classes):
                    self.flag(
                        "device-buffer-retention", node,
                        f"class attribute '{root.id}.{tgt.attr}' is bound "
                        "to a device value from runtime code: a "
                        "process-lifetime pin shared across instances, "
                        "invisible to census owner attribution — keep "
                        "device buffers on instances registered via "
                        "obs.memory.register_owner (docs/memory.md)",
                    )

    def visit_BinOp(self, node: ast.BinOp) -> None:
        # stop timestamp: `time.perf_counter() - t0` (t0 a recorded mark)
        if (isinstance(node.op, ast.Sub) and self._is_time_call(node.left)
                and isinstance(node.right, ast.Name)):
            self._stops.append((node.lineno, node.right.id))
        self.generic_visit(node)

    def _note_wallclock_call(self, node: ast.Call, name: Optional[str],
                             leaf: Optional[str]) -> None:
        """Record device-compute and sync events for the linear
        wallclock scan.  Device compute = a jax/jnp-rooted call (minus
        the sync API) or a call into one of this module's jit roots;
        sync = anything that blocks on device results."""
        line = getattr(node, "lineno", 0)
        if name is not None:
            root = name.split(".")[0]
            if leaf in _SYNC_LEAVES or (root in _NP_NAMES
                                        and leaf in _NP_SYNC_FUNCS):
                self._sync_lines.append(line)
                return
            if leaf in ("float", "int") and name == leaf:
                # float(x)/int(x) of a device scalar is a sync; of host
                # data it is harmless — treating it as a sync errs on
                # the quiet side for THIS rule (host-sync-in-loop owns
                # the opposite direction)
                self._sync_lines.append(line)
                return
            if name.startswith(("jax.profiler.", "jax.config.",
                                "jax.monitoring.")):
                return  # harness/profiler API, not device compute
            if root in _DEVICE_ROOTS or leaf in self.jit_roots:
                self._device_lines.append(line)
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in _SYNC_LEAVES:
            self._sync_lines.append(line)

    def finish(self) -> None:
        """Evaluate collected wallclock stop timestamps (called once
        after the whole function body is visited).  Traced code is
        exempt: a wall-clock read there is trace-time Python with its
        own failure mode (it would be constant-folded), not an async
        mis-timing."""
        if self.traced:
            return
        for stop_line, mark in self._stops:
            starts = [ln for ln in self._time_marks.get(mark, ())
                      if ln < stop_line]
            if not starts:
                continue
            start_line = max(starts)
            devs = [ln for ln in self._device_lines
                    if start_line < ln <= stop_line]
            syncs = [ln for ln in self._sync_lines
                     if start_line < ln <= stop_line]
            if devs and not syncs:
                self.flag(
                    "wallclock-without-sync",
                    _Loc(stop_line),
                    f"elapsed-time stop at line {stop_line} times device "
                    f"work dispatched at line(s) {devs} with no "
                    "block_until_ready()/device_get/np.asarray before "
                    "the stop: async dispatch makes this measure launch "
                    "cost, not compute",
                )

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self.traced and _dotted(node) in ("jnp.float64", "np.float64",
                                             "numpy.float64",
                                             "jax.numpy.float64"):
            self.flag(
                "f64-literal-in-traced", node,
                f"explicit {_dotted(node)} in traced code",
            )
        self.generic_visit(node)

    def _check_environ(self, node: ast.AST, value: ast.AST) -> None:
        if self.traced and _dotted(value) in ("os.environ", "environ"):
            self.flag(
                "env-read-at-trace", node,
                "os.environ read at trace time: hoist to a module-level "
                "read (jit caches do not key on env)",
            )

    # --------------------------------------------- raw-artifact-write
    @staticmethod
    def _write_mode_of(call: ast.Call) -> Optional[str]:
        """The constant mode string of an ``open()`` call when it is a
        WRITE mode ('w'/'x' family; 'a' append and 'r+' update are
        exempt — logs and in-place patching are not artifact writes)."""
        mode = None
        if len(call.args) >= 2:
            mode = call.args[1]
        for kw in call.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
            if mode.value and mode.value[0] in ("w", "x"):
                return mode.value
        return None

    def _check_raw_write(self, node: ast.Call, name: Optional[str]) -> None:
        if name == "open" and self._write_mode_of(node) is not None:
            self.flag(
                "raw-artifact-write", node,
                f"open(..., {self._write_mode_of(node)!r}) writes "
                "non-atomically: a crash mid-write leaves a truncated "
                "file under the real name — use resilience.atomic_write"
                "/atomic_writer (tmp + fsync + rename)",
            )
        elif name in ("json.dump",) and len(node.args) >= 2:
            f = node.args[1]
            if (isinstance(f, ast.Call) and _dotted(f.func) == "open"
                    and self._write_mode_of(f) is not None):
                self.flag(
                    "raw-artifact-write", node,
                    "json.dump(obj, open(..., 'w')) writes an artifact "
                    "non-atomically — use resilience.atomic_write_json",
                )

    def _check_event_buffer(self, node: ast.Call,
                            name: Optional[str]) -> None:
        """unbounded-event-buffer: ``MODLIST.append(...)`` / ``.extend``
        where MODLIST is a module-level bare list and this module is a
        hot/serving/obs scope.  Module-import-time appends never reach
        here (the walker only visits function bodies), so one-shot
        registry building at import stays legal."""
        if not self.event_scope or name is None:
            return
        parts = name.split(".")
        if (len(parts) == 2 and parts[1] in ("append", "extend")
                and parts[0] in self.module_lists):
            self.flag(
                "unbounded-event-buffer", node,
                f"{parts[0]}.{parts[1]}() grows the module-level list "
                f"'{parts[0]}' from request/runtime code with no "
                "maxlen/ring discipline — a long-lived server "
                "accumulates it forever; use collections.deque("
                "maxlen=N) (obs/flightrec.py's ring is the pattern)",
            )

    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        leaf = name.split(".")[-1] if name else None

        self._note_wallclock_call(node, name, leaf)
        self._check_raw_write(node, name)
        self._check_event_buffer(node, name)

        # env-read-at-trace: os.environ.get(...) / os.getenv(...)
        if self.traced and name in ("os.environ.get", "os.getenv",
                                    "environ.get", "getenv"):
            self.flag(
                "env-read-at-trace", node,
                "os.environ read at trace time: hoist to a module-level "
                "read (jit caches do not key on env)",
            )

        # host-sync-in-jit
        if self.traced:
            if (name is not None
                    and name.split(".")[0] in _NP_NAMES
                    and leaf in _NP_SYNC_FUNCS):
                self.flag(
                    "host-sync-in-jit", node,
                    f"{name}() materializes on host inside traced code "
                    "(use jnp, or move the host work outside the jit)",
                )
            elif name in ("jax.device_get", "device_get"):
                self.flag(
                    "host-sync-in-jit", node,
                    "jax.device_get inside traced code",
                )
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_METHODS):
                self.flag(
                    "host-sync-in-jit", node,
                    f".{node.func.attr}() forces a host sync inside "
                    "traced code",
                )

        # jit-cache-miss-risk
        if _is_jax_jit(node.func) and node.args:
            if isinstance(node.args[0], ast.Lambda):
                self.flag(
                    "jit-cache-miss-risk", node,
                    "jax.jit(lambda ...) builds a fresh callable (empty "
                    "jit cache) at every evaluation of this expression",
                )
            elif self.loop_depth > 0:
                self.flag(
                    "jit-cache-miss-risk", node,
                    "jax.jit called inside a loop: one retrace+compile "
                    "per iteration",
                )

        # host-sync-in-loop (hot, non-traced host code)
        if self.hot and not self.traced and self.loop_depth > 0:
            if (name is not None
                    and name.split(".")[0] in _NP_NAMES
                    and leaf in _NP_SYNC_FUNCS):
                self.flag(
                    "host-sync-in-loop", node,
                    f"{name}() inside a hot loop: one device->host "
                    "sync per iteration",
                )
            elif (leaf in ("float", "int") and name == leaf
                    and node.args
                    and isinstance(node.args[0], ast.Call)
                    and not self._is_host_numpy_call(node.args[0])):
                self.flag(
                    "host-sync-in-loop", node,
                    f"{leaf}(<call>) inside a hot loop materializes a "
                    "computed device value per iteration: batch the "
                    "fetches (one jax.device_get of all values) or park "
                    "the device scalar and materialize it lagged",
                )
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("item", "tolist")):
                self.flag(
                    "host-sync-in-loop", node,
                    f".{node.func.attr}() inside a hot loop: one device "
                    "sync per iteration",
                )

        self.generic_visit(node)


def _suppressions(source: str) -> Tuple[Set[str], Dict[int, Set[str]]]:
    file_rules: Set[str] = set()
    line_rules: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _PRAGMA_FILE.search(line)
        if m:
            file_rules.update(r.strip() for r in m.group(1).split(","))
            continue
        m = _PRAGMA_LINE.search(line)
        if m:
            line_rules.setdefault(i, set()).update(
                r.strip() for r in m.group(1).split(","))
    return file_rules, line_rules


def _is_hot(path: str) -> bool:
    norm = path.replace(os.sep, "/")
    parts = norm.split("/")
    if any(p in _HOT_DIR_PARTS for p in parts[:-1]):
        return True
    return parts[-1] in _HOT_FILES


def _is_event_scope(path: str) -> bool:
    """Where unbounded-event-buffer applies: the hot modules plus the
    long-lived serving/obs tiers."""
    if _is_hot(path):
        return True
    parts = path.replace(os.sep, "/").split("/")
    return any(p in _EVENT_SCOPE_DIR_PARTS for p in parts[:-1])


def _module_level_lists(tree: ast.Module) -> Set[str]:
    """Names bound to a bare ``[]`` / ``list()`` at module top level —
    the buffers with no possible maxlen discipline.  deque(maxlen=...)
    and any other construction are not collected."""
    names: Set[str] = set()
    for stmt in tree.body:
        targets: List[ast.AST] = []
        value: Optional[ast.AST] = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if value is None:
            continue
        is_bare_list = isinstance(value, ast.List) or (
            isinstance(value, ast.Call) and _dotted(value.func) == "list"
            and not value.args and not value.keywords)
        if not is_bare_list:
            continue
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                names.add(tgt.id)
    return names


def lint_source(source: str, path: str = "<string>",
                hot: Optional[bool] = None,
                rules: Optional[Iterable[str]] = None) -> List[Finding]:
    """Lint one module's source; returns surviving findings."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding("syntax-error", path, e.lineno or 0, str(e.msg))]
    index = _ModuleIndex()
    index.visit(tree)
    traced = _traced_functions(index)
    hot = _is_hot(path) if hot is None else hot
    module_lists = _module_level_lists(tree)
    event_scope = _is_event_scope(path)
    module_classes = {n.name for n in tree.body
                      if isinstance(n, ast.ClassDef)}

    findings: List[Finding] = []

    def walk_fn(fn: ast.AST, is_traced: bool) -> None:
        walker = _RuleWalker(path, is_traced, hot, findings,
                             jit_roots=index.jit_roots,
                             module_lists=module_lists,
                             event_scope=event_scope,
                             module_classes=module_classes)
        for stmt in fn.body:  # type: ignore[attr-defined]
            walker.visit(stmt)
        walker.finish()

    seen: Set[int] = set()

    def visit_scope(node: ast.AST, enclosing_traced: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(child) in seen:
                    continue
                seen.add(id(child))
                is_traced = enclosing_traced or child.name in traced
                walk_fn(child, is_traced)
                visit_scope(child, is_traced)
            else:
                visit_scope(child, enclosing_traced)

    visit_scope(tree, False)

    file_sup, line_sup = _suppressions(source)
    active = set(rules) if rules is not None else set(AST_RULES)
    out = []
    for f in findings:
        if f.rule not in active:
            continue
        if f.rule in file_sup or f.rule in line_sup.get(f.line, ()):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def lint_paths(paths: Iterable[str],
               rules: Optional[Iterable[str]] = None) -> List[Finding]:
    """Lint .py files (recursing into directories)."""
    findings: List[Finding] = []
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                if "__pycache__" in root:
                    continue
                for n in sorted(names):
                    if n.endswith(".py"):
                        files.append(os.path.join(root, n))
        elif p.endswith(".py"):
            files.append(p)
    for fp in sorted(files):
        with open(fp, encoding="utf-8") as fh:
            src = fh.read()
        findings.extend(lint_source(src, path=fp, rules=rules))
    return findings
