"""Process-wide backend-compile counter.

JAX emits a ``/jax/core/compile/backend_compile_duration`` monitoring
event once per actual backend compile (cache hits emit nothing —
verified on this jaxlib: two same-shape calls add zero events, a new
shape adds one).  Counting these events gives the recompile signal the
bench warm-up and the steady-loop tier-1 gate need: a timed loop is
only honest once an iteration adds no new compiles.

The listener registry in jax.monitoring has no targeted unregister, so
the listener installs once per process and stays; the counter is read
by delta (``CompileCounter.delta()`` snapshots).

Caveat: lazily-compiled Mosaic kernels inside an already-compiled XLA
program (the per-tier TPU kernels) compile in the TPU runtime and do
NOT emit this event — callers that warm real-chip loops should combine
the counter with an iteration-time stability check (bench.py does).
"""

from __future__ import annotations

import threading

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_installed = False
_count = 0


def _listener(event: str, duration: float, **kwargs) -> None:  # noqa: ARG001
    global _count
    if event == _COMPILE_EVENT:
        with _lock:
            _count += 1


def _install() -> None:
    global _installed
    with _lock:
        if _installed:
            return
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(_listener)
        # flag is set only AFTER successful registration: a failure
        # must surface on the next call too, not leave a permanently-
        # zero counter that makes every compile-stability gate pass
        # vacuously (registration never fires the listener, so holding
        # _lock across it cannot deadlock)
        _installed = True


class CompileCounter:
    """Snapshot view over the process-wide compile count."""

    def __init__(self) -> None:
        _install()
        self._mark = backend_compile_count()

    @property
    def count(self) -> int:
        """Total backend compiles this process has performed."""
        return backend_compile_count()

    def delta(self) -> int:
        """Compiles since construction or the last ``reset()``."""
        return backend_compile_count() - self._mark

    def reset(self) -> None:
        self._mark = backend_compile_count()


def backend_compile_count() -> int:
    _install()
    with _lock:
        return _count


def compile_counter() -> CompileCounter:
    """A fresh zeroed snapshot counter (installs the listener)."""
    return CompileCounter()
