"""jaxlint: JAX-aware static analysis + compiled-artifact audit.

Two stages, one failure class: perf regressions that are invisible at
unit-test level on this stack — silent full-record copies at cond
boundaries, dropped buffer donation, dtype promotion, host syncs inside
hot loops, and lazy recompiles polluting timed loops (the round-5
`learners/serial.py` rework shipped exactly such a regression
unmeasured; ROADMAP "Recent").

* Stage 1 (``ast_rules``): pure-AST lint over ``lightgbm_tpu/`` — no
  JAX import, runs in milliseconds.
* Stage 2 (``hlo_audit``): trace/lower/compile the hot entry points on
  CPU and assert committed budgets (``analysis/budgets.json``) on HLO
  op counts, donation aliasing, and the single-mention aliased record
  chain; ``recompile`` provides the process-wide backend-compile
  counter the bench warm-up and the steady-loop gate use.
* Stage 3 (``concurrency``): lock-discipline lint of the threaded
  serving/obs/resilience tier — shared-state guards, lock-order
  cycles, device syncs under locks, signal-handler lock safety; its
  runtime twin ``lockcheck`` is the env-gated (``LGBM_TPU_LOCKCHECK``)
  instrumented-lock sanitizer those modules create primitives through.

All stages are wired into tier-1 (tests/test_jaxlint.py,
tests/test_hlo_budgets.py, tests/test_concurrency_analysis.py) and the
standalone ``tools/jaxlint.py`` CLI.
"""

from . import lockcheck  # noqa: F401
from .ast_rules import (  # noqa: F401
    AST_RULES,
    Finding,
    lint_paths,
    lint_source,
)
from .concurrency import (  # noqa: F401
    CONCURRENCY_RULES,
    lint_concurrency_paths,
    lint_concurrency_source,
    lint_concurrency_sources,
)
from .hlo_audit import (  # noqa: F401
    ARTIFACT_RULES,
    audit_artifacts,
    budgets_path,
    check_budgets,
    hlo_op_counts,
    load_budgets,
    measure_entry_points,
)
from .recompile import CompileCounter, compile_counter  # noqa: F401
