"""Runtime lock sanitizer for the threaded serving/obs/resilience tier.

The static half (`analysis/concurrency.py`, jaxlint stage 3) reasons
about lexical `with lock:` structure; this module checks the dynamic
half — the actual interleavings — in the spirit of ThreadSanitizer's
lock-order analysis.  Threaded modules create their primitives through
the factories here:

    _lock = lockcheck.make_lock("memory.census")
    self._cond = lockcheck.make_condition("queue.cond")

With ``LGBM_TPU_LOCKCHECK`` unset (the default) the factories return
the plain ``threading`` primitives — zero wrappers, zero overhead, so
production serving pays nothing.  With ``LGBM_TPU_LOCKCHECK=1`` they
return instrumented proxies that record, per thread, the stack of held
locks and the acquisition call stack for each, and accumulate a
process-wide lock-order graph.  Two finding kinds:

``lock-order-inversion``
    acquiring B while holding A when some thread has already acquired
    A while holding B — the classic deadlock precondition, reported
    with BOTH lock names and BOTH acquisition stacks (this order's and
    the recorded reverse order's), so a post-mortem names the exact
    pair without reproducing the hang.

``sync-under-lock``
    a host sync/materialization executed while holding an instrumented
    lock.  The serving hot path calls ``lockcheck.note_host_sync(...)``
    just before each device wait; if the calling thread holds a lock
    at that point, every other thread is queued behind a device
    round-trip.

Findings are appended to an in-process list (``findings()``) and
mirrored to the flight recorder (``obs/flightrec.py``) as
``kind="lockcheck"`` events, so a deadlock post-mortem dump carries
them alongside the serving timeline.  ``stats()`` exposes per-lock
acquisition counts and max hold times for hold-time regressions.

The checker's own bookkeeping lock is a plain ``threading.Lock`` held
only for dict updates (never while calling user code or the flight
recorder) and is itself excluded from checking.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

_ENV_FLAG = "LGBM_TPU_LOCKCHECK"

_enabled = os.environ.get(_ENV_FLAG, "").strip().lower() in (
    "1", "true", "yes", "on")

# bookkeeping state -- guarded by _state_lock, which is deliberately a
# raw primitive (instrumenting the checker with itself would recurse)
_state_lock = threading.Lock()
_edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
_findings: List[Dict[str, Any]] = []
_stats: Dict[str, Dict[str, float]] = {}
_tls = threading.local()


def enabled() -> bool:
    """Whether the sanitizer is active (env knob or set_enabled)."""
    return _enabled


def set_enabled(flag: bool) -> None:
    """Toggle at runtime (tests).  Only locks created AFTER enabling
    are instrumented — module-level locks made at import keep whatever
    flavour the import-time knob selected."""
    global _enabled
    _enabled = bool(flag)


def reset() -> None:
    """Drop accumulated findings, edges, and stats (tests)."""
    with _state_lock:
        _edges.clear()
        del _findings[:]
        _stats.clear()


def findings() -> List[Dict[str, Any]]:
    with _state_lock:
        return [dict(f) for f in _findings]


def stats() -> Dict[str, Dict[str, float]]:
    with _state_lock:
        return {k: dict(v) for k, v in _stats.items()}


def lock_order_graph() -> Dict[Tuple[str, str], int]:
    """(held, acquired) -> times that edge was observed."""
    with _state_lock:
        return {k: int(v["count"]) for k, v in _edges.items()}


def _held_stack() -> List[Dict[str, Any]]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = []
        _tls.held = stack
    return stack


def _capture_stack(skip: int = 3) -> List[str]:
    """Short formatted stack, trimmed of checker frames."""
    frames = traceback.extract_stack(limit=skip + 12)[:-skip]
    return [f"{os.path.basename(fr.filename)}:{fr.lineno}:{fr.name}"
            for fr in frames[-8:]]


def _emit(finding: Dict[str, Any]) -> None:
    with _state_lock:
        _findings.append(finding)
    # mirror into the flight recorder so a post-mortem dump carries the
    # lock pair + stacks; lazy import keeps analysis/ jax- and obs-free
    # at import time, try/except keeps the sanitizer non-fatal
    try:
        from ..obs import flightrec
        flightrec.record("lockcheck", **finding)
    except Exception:
        pass


def _path_exists(src: str, dst: str) -> bool:
    """DFS over the recorded edge graph; caller holds _state_lock."""
    seen = {src}
    frontier = [src]
    while frontier:
        cur = frontier.pop()
        if cur == dst:
            return True
        for (a, b) in _edges:
            if a == cur and b not in seen:
                seen.add(b)
                frontier.append(b)
    return False


def _note_acquired(name: str, stack: List[str]) -> None:
    """Called after a top-level (depth 0 -> 1) acquisition succeeds."""
    held = _held_stack()
    thread = threading.current_thread().name
    inversion: Optional[Dict[str, Any]] = None
    with _state_lock:
        st = _stats.setdefault(name, {"acquisitions": 0, "max_hold_s": 0.0})
        st["acquisitions"] += 1
        if held:
            outer = held[-1]
            key = (outer["name"], name)
            rev = (name, outer["name"])
            # inversion: some thread has (or transitively had) the
            # reverse order on record and this edge would close a cycle
            if rev in _edges or _path_exists(name, outer["name"]):
                prior = _edges.get(rev)
                inversion = {
                    "finding": "lock-order-inversion",
                    "first_lock": outer["name"],
                    "second_lock": name,
                    "thread": thread,
                    "first_lock_stack": list(outer["stack"]),
                    "second_lock_stack": list(stack),
                    "reverse_thread": prior["thread"] if prior else "?",
                    "reverse_first_stack":
                        list(prior["outer_stack"]) if prior else [],
                    "reverse_second_stack":
                        list(prior["inner_stack"]) if prior else [],
                }
            e = _edges.setdefault(key, {
                "count": 0, "thread": thread,
                "outer_stack": list(outer["stack"]),
                "inner_stack": list(stack)})
            e["count"] += 1
    held.append({"name": name, "t0": time.perf_counter(), "stack": stack})
    if inversion is not None:
        _emit(inversion)


def _note_released(name: str) -> None:
    held = _held_stack()
    for i in range(len(held) - 1, -1, -1):
        if held[i]["name"] == name:
            entry = held.pop(i)
            hold_s = time.perf_counter() - entry["t0"]
            with _state_lock:
                st = _stats.setdefault(
                    name, {"acquisitions": 0, "max_hold_s": 0.0})
                if hold_s > st["max_hold_s"]:
                    st["max_hold_s"] = hold_s
            return


def note_host_sync(label: str) -> None:
    """Hot-path hook: call just before a host sync / device wait.

    No-op (one attribute load) when the sanitizer is off.  When on and
    the calling thread holds an instrumented lock, records a
    ``sync-under-lock`` finding with the held locks' acquisition
    stacks and the sync site."""
    if not _enabled:
        return
    held = _held_stack()
    if not held:
        return
    _emit({
        "finding": "sync-under-lock",
        "sync_site": label,
        "thread": threading.current_thread().name,
        "held_locks": [h["name"] for h in held],
        "held_stacks": {h["name"]: list(h["stack"]) for h in held},
        "sync_stack": _capture_stack(),
    })


class _InstrumentedLock:
    """Proxy over Lock/RLock recording order edges and hold times.

    Implements the full CPython Condition protocol (`_release_save`,
    `_acquire_restore`, `_is_owned`) so ``Condition(make_rlock(...))``
    keeps correct held-stack bookkeeping across ``wait()``."""

    __slots__ = ("_inner", "_name", "_reentrant", "_depth")

    def __init__(self, inner: Any, name: str, reentrant: bool) -> None:
        self._inner = inner
        self._name = name
        self._reentrant = reentrant
        self._depth = threading.local()

    @property
    def name(self) -> str:
        return self._name

    def _d(self) -> int:
        return getattr(self._depth, "v", 0)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        stack = _capture_stack()
        got = self._inner.acquire(blocking, timeout)
        if got:
            d = self._d()
            self._depth.v = d + 1
            if d == 0:
                _note_acquired(self._name, stack)
        return got

    def release(self) -> None:
        d = self._d()
        self._inner.release()
        if d > 0:
            self._depth.v = d - 1
            if d == 1:
                _note_released(self._name)

    def __enter__(self) -> "_InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # --- Condition integration -------------------------------------
    def _release_save(self) -> Any:
        d = self._d()
        self._depth.v = 0
        if d > 0:
            _note_released(self._name)
        if hasattr(self._inner, "_release_save"):
            return (d, self._inner._release_save())
        self._inner.release()
        return (d, None)

    def _acquire_restore(self, saved: Any) -> None:
        d, inner_saved = saved
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(inner_saved)
        else:
            self._inner.acquire()
        self._depth.v = d
        if d > 0:
            _note_acquired(self._name, _capture_stack())

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        return self._d() > 0


def make_lock(name: str) -> Any:
    """A mutex named for diagnostics; plain ``threading.Lock`` when the
    sanitizer is off."""
    if not _enabled:
        return threading.Lock()
    return _InstrumentedLock(threading.Lock(), name, reentrant=False)


def make_rlock(name: str) -> Any:
    """A reentrant mutex; plain ``threading.RLock`` when off."""
    if not _enabled:
        return threading.RLock()
    return _InstrumentedLock(threading.RLock(), name, reentrant=True)


def make_condition(name: str) -> threading.Condition:
    """A condition variable whose underlying (reentrant) lock is
    instrumented; plain ``threading.Condition`` when off."""
    if not _enabled:
        return threading.Condition()
    return threading.Condition(
        _InstrumentedLock(threading.RLock(), name, reentrant=True))
