"""jaxlint stage 2: compiled-artifact audit of the hot entry points.

Traces the serial grow loop, the mega split kernel (interpret mode on
CPU — the interpreter lowers the Pallas grid to real XLA HLO, so the
SURROUNDING program structure the budgets guard is the real thing),
the aliased placement kernel, and the matmul predictor, then checks:

* **hlo-op-budget** — compiled-HLO op counts (``copy``, ``transpose``,
  ``convert``, ``gather``, ``dynamic-update-slice``) against the
  committed budgets in ``analysis/budgets.json``.  The round-5 failure
  class — XLA copy-insertion cloning the full record/histogram buffer
  once per split inside the grow while-body — shows up as a step
  change in the ``copy`` count of these small-shape programs.
* **hlo-donation-dropped** — every donated entry point must compile
  with ``input_output_alias`` in the HLO module header and without a
  "donated buffers were not usable" warning.
* **record-chain-multi-use** — in the jaxprs of the hardware-config
  split step and placement, the donated record argument must be
  consumed by EXACTLY ONE equation: a second mention (a window slice,
  a go vector, a sibling view) is what forced copy-insertion to clone
  the record every split (~1 s/tree at 10M rows, round-5 measurement).
* **recompile-in-steady-loop** — re-running an already-warm callable
  over the same shapes must add zero backend compiles
  (``steady_loop_recompiles``; the tier-1 test drives the real grow
  loop through it).
* **hlo-memory-budget** — ``compiled.memory_analysis()`` bytes
  (temp/argument/output) against ``mem_*`` ceilings in the same
  budgets file: the static half of the memory-observability layer
  (obs/memory.py is the runtime half) — an XLA temp allocation that
  balloons at the pinned shape fails tier-1 before any chip time is
  spent.

Budgets are CPU-backend numbers at pinned small shapes; see
docs/jaxlint.md for the update workflow (never raise a budget to make
a red gate green without a bench row justifying the new count).
"""

from __future__ import annotations

import collections
import json
import os
import re
import warnings
from typing import Dict, List, Optional

from .ast_rules import Finding

ARTIFACT_RULES: Dict[str, str] = {
    "hlo-op-budget": (
        "compiled-HLO op count (copy/transpose/convert/gather/...) "
        "exceeds the committed budget in analysis/budgets.json"
    ),
    "hlo-donation-dropped": (
        "a donated entry point compiled without input_output_alias, or "
        "XLA warned that donated buffers were unusable"
    ),
    "record-chain-multi-use": (
        "the donated record argument is consumed by more than one "
        "jaxpr equation — copy-insertion will clone the full record "
        "per split (the round-5 ~1 s/tree regression class)"
    ),
    "recompile-in-steady-loop": (
        "an iteration of an already-warm loop triggered a backend "
        "compile — lazy recompiles pollute any timed loop"
    ),
    "hlo-memory-budget": (
        "compiled.memory_analysis() bytes (temp/argument/output) exceed "
        "the committed memory budget in analysis/budgets.json — a "
        "kernel change ballooned XLA's allocation at the pinned shape"
    ),
}

_HLO_OP = re.compile(r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*[^=]*?\s([\w\-]+)\(")
_ALIAS = re.compile(r"input_output_alias=\{\s*([^}]*\S)[^}]*\}")
_DONATION_WARNING = re.compile(r"donated", re.IGNORECASE)

# shapes for the audited programs: small enough to compile in seconds
# on CPU, big enough to exercise the multi-tier cond structure where
# the copy regressions live (n=2048 gives three hist/partition tiers)
_N, _F, _B, _L = 2048, 4, 16, 8


def budgets_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "budgets.json")


def load_budgets(path: Optional[str] = None) -> dict:
    with open(path or budgets_path(), encoding="utf-8") as fh:
        return json.load(fh)


def hlo_op_counts(hlo_text: str) -> Dict[str, int]:
    """Instruction-opcode histogram of an HLO module text."""
    counts: collections.Counter = collections.Counter()
    for line in hlo_text.splitlines():
        m = _HLO_OP.match(line)
        if m:
            counts[m.group(1)] += 1
    return dict(counts)


def _memory_analysis(compiled) -> dict:
    """``compiled.memory_analysis()`` normalized to plain ints (the
    static half of obs/memory.py's accounting).  {} when the backend
    does not expose it — the budget gate then treats the entry as
    unmeasurable rather than zero."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}
    out = {}
    for key in ("temp_size_in_bytes", "argument_size_in_bytes",
                "output_size_in_bytes", "generated_code_size_in_bytes",
                "alias_size_in_bytes"):
        val = getattr(ma, key, None)
        if val is not None:
            out[key.replace("_size_in_bytes", "_bytes")] = int(val)
    return out


def _compile_entry(lowered):
    """Compile a lowered computation, capturing donation warnings.
    Returns (op_counts, has_alias, warning_strings, memory_bytes)."""
    with warnings.catch_warnings(record=True) as wlog:
        warnings.simplefilter("always")
        compiled = lowered.compile()
    txt = compiled.as_text()
    has_alias = _ALIAS.search(txt) is not None
    donation_warnings = [
        str(w.message) for w in wlog
        if _DONATION_WARNING.search(str(w.message))
    ]
    return (hlo_op_counts(txt), has_alias, donation_warnings,
            _memory_analysis(compiled))


def _jaxpr_use_count(closed_jaxpr, invar_index: int) -> int:
    """How many equations consume the given top-level input variable."""
    var = closed_jaxpr.jaxpr.invars[invar_index]
    uses = 0
    for eqn in closed_jaxpr.jaxpr.eqns:
        if any(v is var for v in eqn.invars):
            uses += 1
    if any(v is var for v in closed_jaxpr.jaxpr.outvars):
        uses += 1
    return uses


# ------------------------------------------------------------ entry points

def _grow_inputs():
    import jax.numpy as jnp
    import numpy as np

    from ..learners.serial import TreeLearnerParams

    rng = np.random.RandomState(0)
    bins_T = jnp.asarray(
        rng.randint(0, _B, size=(_F, _N)).astype(np.uint8))
    grad = jnp.asarray(rng.randn(_N).astype(np.float32))
    hess = jnp.ones(_N, jnp.float32)
    bag = jnp.ones(_N, jnp.float32)
    fmask = jnp.ones(_F, bool)
    nbpf = jnp.full(_F, _B, jnp.int32)
    iscat = jnp.zeros(_F, bool)
    params = TreeLearnerParams(
        min_data_in_leaf=jnp.float32(1.0),
        min_sum_hessian_in_leaf=jnp.float32(1e-3),
        lambda_l1=jnp.float32(0.0),
        lambda_l2=jnp.float32(0.0),
        min_gain_to_split=jnp.float32(0.0),
        max_depth=jnp.int32(0),
    )
    return bins_T, grad, hess, bag, fmask, nbpf, iscat, params


def _measure_grow_tree_serial() -> dict:
    """The CPU serial grow loop (order-based partition, segment hists):
    the path every tier-1 test and the CPU bench fallback run."""
    from ..learners.serial import grow_tree

    args = _grow_inputs()
    lowered = grow_tree.lower(*args, num_bins=_B, max_leaves=_L)
    ops, has_alias, dwarn, mem = _compile_entry(lowered)
    return {"ops": ops, "donation": None, "donation_warnings": dwarn,
            "has_alias": has_alias, "memory": mem}


_FOREST_LANES = 4


def _measure_grow_forest_batched() -> dict:
    """The forest-batched grower (learners/forest.py, explicit batched
    loop): one traced program advancing _FOREST_LANES independent trees
    — the multiclass / cv-fold / train_many dispatch.  Audited at the
    same (n, F, bins, leaves) pin as grow_tree_serial so the two
    entries' op counts stay comparable lane-for-lane."""
    import jax.numpy as jnp

    from ..learners.forest import make_grow_forest, stack_learner_params

    bins_T, grad, hess, bag, fmask, nbpf, iscat, params = _grow_inputs()
    B = _FOREST_LANES
    gf = make_grow_forest(_B, _L, "batched")
    lowered = gf.lower(
        bins_T,
        jnp.broadcast_to(grad, (B, _N)),
        jnp.broadcast_to(hess, (B, _N)),
        jnp.broadcast_to(bag, (B, _N)),
        jnp.broadcast_to(fmask, (B, _F)),
        nbpf, iscat,
        stack_learner_params([params] * B))
    ops, has_alias, dwarn, mem = _compile_entry(lowered)
    return {"ops": ops, "donation": None, "donation_warnings": dwarn,
            "has_alias": has_alias, "memory": mem}


def _split_step_inputs():
    import jax.numpy as jnp
    import numpy as np

    from ..ops import record as rec_mod
    from ..ops.pallas_search import _pack_meta, _pack_scal

    T = rec_mod.TILE
    cap, n = T, T  # one-tile window; n_pad = 2 * TILE
    k = rec_mod.bins_per_word(jnp.uint8)
    rng = np.random.RandomState(0)
    bins_T = jnp.asarray(rng.randint(0, _B, size=(_F, n)).astype(np.uint8))
    grad = jnp.asarray(rng.randn(n).astype(np.float32))
    rec = rec_mod.build_record(
        bins_T, grad, jnp.ones(n, jnp.float32), jnp.ones(n, jnp.float32),
        2 * T)
    Fp = rec_mod.round_up(_F, 8)
    Bp = rec_mod.round_up(_B, 128)
    hists = jnp.zeros((2, Fp, 4, Bp), jnp.float32)
    scal_f = _pack_scal(
        jnp.float32(1.0), jnp.float32(0.0), jnp.float32(1.0),
        jnp.float32(n), jnp.float32(0.0), jnp.float32(1.0),
        jnp.float32(n), jnp.float32(1.0), jnp.float32(1e-3),
        jnp.float32(0.0), jnp.float32(0.0), jnp.float32(0.0))
    meta = _pack_meta(jnp.ones(_F, bool), jnp.full(_F, _B, jnp.int32),
                      jnp.zeros(_F, bool), Fp)
    scalars = dict(
        begin=jnp.int32(0), pcnt=jnp.int32(n),
        do_split=jnp.bool_(True), f=jnp.int32(1), thr=jnp.int32(3),
        is_cat=jnp.bool_(False), parent_slot=jnp.int32(0),
        new_slot=jnp.int32(1))
    return rec, hists, scal_f, meta, scalars, cap, k


def _measure_split_step_window() -> dict:
    """The mega split kernel, interpret mode: donation of the hists
    buffer plus the op budget of the surrounding XLA program."""
    from ..ops.record import split_step_window

    rec, hists, scal_f, meta, s, cap, k = _split_step_inputs()
    lowered = split_step_window.lower(
        hists, rec, s["begin"], s["pcnt"], s["do_split"], s["f"],
        s["thr"], s["is_cat"], s["parent_slot"], s["new_slot"],
        scal_f, meta, F=_F, cap=cap, k=k, interpret=True)
    ops, has_alias, dwarn, mem = _compile_entry(lowered)
    return {"ops": ops, "donation": has_alias and not dwarn,
            "donation_warnings": dwarn, "has_alias": has_alias,
            "memory": mem}


def _measure_split_step_record_chain() -> dict:
    """Jaxpr of the HARDWARE config (direct_read aliased path): the
    donated record must be consumed by exactly one equation."""
    import jax

    from ..ops.record import split_step_window

    rec, hists, scal_f, meta, s, cap, k = _split_step_inputs()

    def run(rec_, hists_):
        return split_step_window(
            hists_, rec_, s["begin"], s["pcnt"], s["do_split"], s["f"],
            s["thr"], s["is_cat"], s["parent_slot"], s["new_slot"],
            scal_f, meta, F=_F, cap=cap, k=k, return_comp=True,
            interpret=False)

    jaxpr = jax.make_jaxpr(run)(rec, hists)
    uses = _jaxpr_use_count(jaxpr, 0)
    return {"ops": {}, "donation": None, "donation_warnings": [],
            "record_uses": uses, "record_single_use": uses == 1}


def _measure_place_runs() -> dict:
    """The aliased placement: donation of the record (compiled,
    interpret fallback) AND single-mention in the hardware jaxpr."""
    import jax
    import jax.numpy as jnp

    from ..ops import record as rec_mod

    T = rec_mod.TILE
    rec, _hists, _scal_f, _meta, s, cap, k = _split_step_inputs()
    nt = cap // T
    W = rec.shape[0]
    comp = jnp.zeros((nt, W, 2 * T), jnp.int32)
    go = jnp.zeros(cap, jnp.int32)
    args = (comp, go, s["begin"], s["pcnt"], jnp.int32(cap // 2),
            s["do_split"], s["parent_slot"], s["new_slot"])
    kw = dict(cap=cap, leaf_row=rec_mod.num_words(_F, k) + 4)

    lowered = rec_mod.place_runs.lower(rec, *args, interpret=True, **kw)
    ops, has_alias, dwarn, mem = _compile_entry(lowered)

    def run_hw(rec_):
        return rec_mod.place_runs(rec_, *args, interpret=False, **kw)

    jaxpr = jax.make_jaxpr(run_hw)(rec)
    uses = _jaxpr_use_count(jaxpr, 0)
    return {"ops": ops, "donation": has_alias and not dwarn,
            "donation_warnings": dwarn, "has_alias": has_alias,
            "record_uses": uses, "record_single_use": uses == 1,
            "memory": mem}


def _measure_partition_window() -> dict:
    """The standalone partition compaction kernel (the record-mode
    hooks path), at its import-default routing — since PR 12 that is
    the prefix-sum network, so its copy/convert counts are gated from
    day one (a routing rework that reintroduces layout churn around
    the compaction shows up here before any bench run)."""
    import jax.numpy as jnp

    from ..ops import record as rec_mod

    rec, _hists, _scal_f, _meta, s, cap, k = _split_step_inputs()
    go = jnp.zeros(cap, jnp.int32)
    lowered = rec_mod.partition_window.lower(
        rec, go, s["begin"], s["pcnt"], s["do_split"], cap,
        jnp.int32(0), jnp.int32(1),
        leaf_row=rec_mod.num_words(_F, k) + 4, interpret=True)
    ops, has_alias, dwarn, mem = _compile_entry(lowered)
    return {"ops": ops, "donation": None, "donation_warnings": dwarn,
            "has_alias": has_alias, "routing": rec_mod.ROUTING,
            "memory": mem}


def _measure_predict_matmul() -> dict:
    """The matmul predictor: 'zero indexed access' is a budget —
    gather must stay 0."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models.tree import empty_tree, stack_trees
    from ..ops.predict_matmul import build_path_tables, ensemble_sum_matmul

    trees = [empty_tree(_L) for _ in range(2)]
    stacked = stack_trees(trees)
    stacked = jax.tree.map(
        lambda a: a.reshape((2, 1) + a.shape[1:]), stacked)
    tables = build_path_tables(stacked)
    X = jnp.asarray(np.random.RandomState(0)
                    .randn(64, _F).astype(np.float32))
    lowered = ensemble_sum_matmul.lower(tables, stacked, X)
    ops, has_alias, dwarn, mem = _compile_entry(lowered)
    ops.setdefault("gather", 0)
    return {"ops": ops, "donation": None, "donation_warnings": dwarn,
            "has_alias": has_alias, "memory": mem}


def _measure_post_grow_step() -> dict:
    """The per-tree score update: scores donation must hold (a dropped
    donation doubles score-buffer traffic every tree)."""
    import jax.numpy as jnp

    from ..models.gbdt import _post_grow_step
    from ..models.tree import empty_tree, pack_threshold_bounds

    tree = empty_tree(_L)
    scores = jnp.zeros((1, _N), jnp.float32)
    leaf_id = jnp.zeros(_N, jnp.int32)
    bounds_mat, real_feat = pack_threshold_bounds(
        [[0.5, 1.0] for _ in range(_F)], list(range(_F)))
    lowered = _post_grow_step.lower(
        tree, scores, jnp.int32(0), leaf_id, jnp.float32(0.1),
        bounds_mat, real_feat)
    ops, has_alias, dwarn, mem = _compile_entry(lowered)
    return {"ops": ops, "donation": has_alias and not dwarn,
            "donation_warnings": dwarn, "has_alias": has_alias,
            "memory": mem}


_ENTRY_MEASURERS = {
    "grow_tree_serial": _measure_grow_tree_serial,
    "grow_forest_batched": _measure_grow_forest_batched,
    "split_step_window": _measure_split_step_window,
    "split_step_record_chain": _measure_split_step_record_chain,
    "place_runs": _measure_place_runs,
    "partition_window": _measure_partition_window,
    "predict_matmul": _measure_predict_matmul,
    "post_grow_step": _measure_post_grow_step,
}


def measure_entry_points(names: Optional[List[str]] = None) -> dict:
    """Measure the audited entry points (CPU backend).  Returns
    {name: {"ops": {...}, "donation": bool|None, ...}}; a measurement
    that raises is recorded as {"error": str}."""
    out = {}
    for name, fn in _ENTRY_MEASURERS.items():
        if names is not None and name not in names:
            continue
        try:
            out[name] = fn()
        except Exception as e:  # surfaced as an audit finding downstream
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    return out


def check_budgets(measured: dict, budgets: dict,
                  require_all: bool = False) -> List[Finding]:
    """Compare measurements against the committed budgets; every
    violation (or missing/failed measurement) is a Finding.  With
    ``require_all`` a budget entry with NO measurement is itself a
    finding — a renamed measurer or typo'd entry key must not silently
    disable its gate (full audits set it; subset callers don't)."""
    findings: List[Finding] = []
    path = os.path.relpath(budgets_path(), os.getcwd())
    for name, entry in budgets.get("entries", {}).items():
        m = measured.get(name)
        if m is None:
            if require_all:
                findings.append(Finding(
                    "hlo-op-budget", path, 0,
                    f"{name}: budget entry has no measurement — "
                    "measurer renamed or entry key typo'd?"))
            continue  # caller restricted the audit to a subset
        if "error" in m:
            findings.append(Finding(
                "hlo-op-budget", path, 0,
                f"{name}: measurement failed: {m['error']}"))
            continue
        for key, limit in entry.items():
            if key == "donation":
                if limit and not m.get("donation"):
                    detail = ("; ".join(m.get("donation_warnings", []))
                              or "no input_output_alias in compiled HLO")
                    findings.append(Finding(
                        "hlo-donation-dropped", path, 0,
                        f"{name}: donation dropped ({detail})"))
            elif key == "record_single_use":
                if limit and not m.get("record_single_use"):
                    findings.append(Finding(
                        "record-chain-multi-use", path, 0,
                        f"{name}: donated record consumed by "
                        f"{m.get('record_uses')} equations (expected 1)"))
            elif key.startswith("_"):
                continue  # comment/metadata keys
            elif key.startswith("mem_"):
                # static memory budget: compiled.memory_analysis()
                # bytes (mem_temp_bytes -> memory["temp_bytes"], ...)
                mem = m.get("memory", {})
                if not mem:
                    findings.append(Finding(
                        "hlo-memory-budget", path, 0,
                        f"{name}: '{key}' budgeted but the backend "
                        "exposed no memory_analysis()"))
                    continue
                got = mem.get(key[len("mem_"):], 0)
                if got > limit:
                    findings.append(Finding(
                        "hlo-memory-budget", path, 0,
                        f"{name}: memory_analysis "
                        f"'{key[len('mem_'):]}' {got} bytes exceeds "
                        f"budget {limit}"))
            else:
                got = m.get("ops", {}).get(key, 0)
                if got > limit:
                    findings.append(Finding(
                        "hlo-op-budget", path, 0,
                        f"{name}: HLO '{key}' count {got} exceeds "
                        f"budget {limit}"))
    return findings


def audit_artifacts(budgets: Optional[dict] = None,
                    names: Optional[List[str]] = None):
    """Run the full stage-2 audit.  Returns (measured, findings)."""
    if budgets is None:
        budgets = load_budgets()
    measured = measure_entry_points(names)
    return measured, check_budgets(measured, budgets,
                                   require_all=names is None)


def steady_loop_recompiles(step_fn, iters: int = 3) -> int:
    """Run ``step_fn()`` ``iters`` times after it has already been
    called once (warm), returning how many backend compiles the warm
    iterations triggered.  0 is the only acceptable answer for a
    shape-stable loop (the recompile-in-steady-loop rule)."""
    from .recompile import compile_counter

    step_fn()  # warm: compiles happen here
    cc = compile_counter()
    for _ in range(iters):
        step_fn()
    return cc.delta()
