"""Text data parsers: CSV / TSV / LibSVM with format auto-detection.

Mirrors the reference's behavior (src/io/parser.cpp): the format is sniffed
from delimiter statistics of the first non-empty lines (parser.cpp:72-144);
LibSVM is detected by ``idx:value`` pairs.  Parsing itself is vectorized via
numpy/pandas rather than the reference's char-by-char Atof loops.
"""

from __future__ import annotations

import io
from typing import List, Optional, Tuple

import numpy as np

from ..log import Log
from ..obs import telemetry


class ParseError(ValueError):
    """Malformed input under strict_data=true.  The message names the
    file and the first offending content so the operator can fix the
    data instead of spelunking a pandas traceback."""


def detect_format(sample_lines: List[str]) -> str:
    """Return one of 'csv', 'tsv', 'libsvm' (parser.cpp:72-144)."""
    for line in sample_lines:
        line = line.strip()
        if not line:
            continue
        tokens = line.replace("\t", " ").replace(",", " ").split()
        colon_tokens = [t for t in tokens[1:] if ":" in t]
        if colon_tokens and all(":" in t for t in tokens[1:]):
            return "libsvm"
        if "\t" in line:
            return "tsv"
        if "," in line:
            return "csv"
        return "tsv"  # space-separated treated as tsv-style whitespace
    return "csv"


def detect_file_format(path: str, has_header: bool = False) -> str:
    """Sniff a file's format from its first data lines — the one public
    entry point for the head-slicing convention shared by the loaders,
    the chunked reader, and the CLI predictor."""
    head = _read_head(path, 3 if has_header else 2)
    return detect_format(head[1:] if has_header else head)


def _read_head(path: str, n: int = 2) -> List[str]:
    lines = []
    with open(path, "r") as fh:
        for _ in range(n):
            line = fh.readline()
            if not line:
                break
            lines.append(line)
    return lines


def parse_file(
    path: str,
    has_header: bool = False,
    fmt: Optional[str] = None,
    strict: bool = False,
) -> Tuple[np.ndarray, Optional[List[str]]]:
    """Parse a data file into a dense float64 row-matrix.

    Returns (matrix including the label column if present, header names or
    None).  Column-role resolution (which column is the label etc.) is the
    caller's job, mirroring DatasetLoader (dataset_loader.cpp:23-160).

    Malformed rows (unparseable tokens, wrong field counts) are a
    counted, logged skip (telemetry counter ``bad_rows``) on the default
    lenient path; ``strict=True`` (Config.strict_data) raises
    :class:`ParseError` instead — never an unhandled exception from deep
    inside pandas.
    """
    head = _read_head(path, 2 if not has_header else 3)
    if fmt is None:
        fmt = detect_format(head[1:] if has_header else head)

    names = None
    if has_header and head:
        sep = "," if fmt == "csv" else None
        names = [s.strip() for s in head[0].strip().split(sep)]

    # native fast path (src/native/lgbm_native.cpp; OpenMP row-parallel)
    from .. import native

    try:
        mat = native.parse_file(path, fmt, skip_header=has_header)
    except Exception:
        mat = None  # malformed input: fall through to the guarded paths
    if mat is not None:
        return mat, names if has_header else None

    if fmt == "libsvm":
        with open(path, "r") as fh:
            if has_header:
                fh.readline()
            return _parse_libsvm(fh, strict=strict, source=path), None

    import pandas as pd

    try:
        df = pd.read_csv(path, **_read_csv_kwargs(head, fmt, has_header))
    except (ValueError, pd.errors.ParserError) as e:
        if strict:
            raise ParseError(
                f"{path}: malformed rows (strict_data=true): "
                f"{type(e).__name__}: {str(e)[:200]}") from e
        df = _lenient_read(path, head, fmt, has_header, pd)
    names = [str(c) for c in df.columns] if has_header else None
    return df.to_numpy(dtype=np.float64), names


def _lenient_read(path: str, head: List[str], fmt: str, has_header: bool,
                  pd):
    """Degraded re-parse after the strict fast path failed: rows with
    wrong field counts or unparseable tokens become a counted, logged
    skip instead of an exception."""
    kwargs = _read_csv_kwargs(head, fmt, has_header)
    kwargs.pop("dtype")
    bad = {"n": 0}

    def on_bad(fields):  # wrong field count: drop the row, count it
        bad["n"] += 1
        return None

    df = pd.read_csv(path, engine="python", on_bad_lines=on_bad, **{
        k: v for k, v in kwargs.items() if k != "engine"})
    num = df.apply(pd.to_numeric, errors="coerce")
    # a cell that held a real (non-NA) token but failed numeric
    # conversion marks its row malformed; NA tokens already became NaN
    # in df and stay missing-value semantics, not errors
    cell_bad = num.isna() & df.notna()
    row_bad = cell_bad.any(axis=1)
    bad["n"] += int(row_bad.sum())
    if bad["n"]:
        telemetry.count("bad_rows", bad["n"])
        Log.warning(
            f"{path}: skipped {bad['n']} malformed row(s) "
            "(strict_data=false; set strict_data=true to raise instead)")
    return num[~row_bad].astype(np.float64)


def _read_csv_kwargs(head: List[str], fmt: str, has_header: bool) -> dict:
    """One source of truth for the pandas parse configuration, shared by
    the one-shot and the chunked (two-round) loaders so both produce the
    same matrix for the same file.  True tab-separated files keep pandas'
    fast C engine; arbitrary whitespace needs the python engine's regex
    separator."""
    probe = head[-1] if head else ""
    if fmt == "csv":
        sep, engine = ",", "c"
    elif "\t" in probe:
        sep, engine = "\t", "c"
    else:
        sep, engine = r"\s+", "python"
    return dict(
        sep=sep,
        header=0 if has_header else None,
        engine=engine,
        dtype=np.float64,
        na_values=["", "NA", "nan", "NaN"],
    )


def _parse_libsvm(lines, strict: bool = False,
                  source: str = "<lines>") -> np.ndarray:
    """LibSVM ``label idx:val ...`` lines -> dense matrix (column 0 = label).

    ``lines`` is any iterable of strings (an open file, a list, ...).
    Malformed lines: counted, logged skip (``bad_rows``), or
    :class:`ParseError` under ``strict``."""
    labels: List[float] = []
    rows: List[Tuple[np.ndarray, np.ndarray]] = []
    max_idx = -1
    n_bad = 0
    for lineno, line in enumerate(lines, start=1):
        parts = line.split()
        if not parts:
            continue
        try:
            label = float(parts[0])
            if len(parts) > 1:
                kv = np.array([p.split(":") for p in parts[1:]])
                idx = kv[:, 0].astype(np.int64)
                val = kv[:, 1].astype(np.float64)
            else:
                idx = np.empty(0, dtype=np.int64)
                val = np.empty(0, dtype=np.float64)
        except (ValueError, IndexError) as e:
            if strict:
                raise ParseError(
                    f"{source}: malformed libsvm line {lineno} "
                    f"({line.strip()[:80]!r}) (strict_data=true)") from e
            n_bad += 1
            continue
        labels.append(label)
        if len(idx):
            max_idx = max(max_idx, int(idx.max()))
        rows.append((idx, val))
    if n_bad:
        telemetry.count("bad_rows", n_bad)
        Log.warning(
            f"{source}: skipped {n_bad} malformed libsvm line(s) "
            "(strict_data=false; set strict_data=true to raise instead)")
    n, f = len(labels), max_idx + 1
    out = np.zeros((n, f + 1), dtype=np.float64)
    out[:, 0] = labels
    for i, (idx, val) in enumerate(rows):
        out[i, idx + 1] = val
    return out


def count_data_rows(path: str, has_header: bool = False) -> int:
    """Count non-blank data lines by streaming 1MB blocks (TextReader-
    style, include/LightGBM/utils/text_reader.h:144-288) — no parsing,
    no whole-file buffer.  Blank lines are excluded to match pandas'
    skip_blank_lines behavior in the chunked parser."""
    n = 0
    carry = b""
    with open(path, "rb") as fh:
        while True:
            block = fh.read(1 << 20)
            if not block:
                break
            lines = (carry + block).split(b"\n")
            carry = lines[-1]
            n += sum(1 for ln in lines[:-1] if ln.strip())
    if carry.strip():
        n += 1  # unterminated final line
    return n - (1 if has_header else 0)


def parse_file_chunks(
    path: str,
    has_header: bool = False,
    fmt: Optional[str] = None,
    chunk_rows: int = 200_000,
):
    """Yield dense float64 row-matrix chunks of a CSV/TSV file.

    The streamed half of two-round loading (dataset_loader.cpp:181-209):
    peak memory is one chunk, not the file.  LibSVM streams through the
    sparse CSR path instead (io/sparse.py).
    """
    head = _read_head(path, 2 if not has_header else 3)
    if fmt is None:
        fmt = detect_format(head[1:] if has_header else head)
    if fmt == "libsvm":
        raise ValueError("libsvm streams via the sparse CSR path")

    # native OpenMP chunk reader (src/native/lgbm_native.cpp); pandas
    # fallback keeps identical NA/short-line semantics
    from .. import native

    native_gen = native.parse_file_chunks(path, fmt, has_header, chunk_rows)
    if native_gen is not None:
        yield from native_gen
        return
    import pandas as pd

    reader = pd.read_csv(
        path, chunksize=chunk_rows, **_read_csv_kwargs(head, fmt, has_header)
    )
    for df in reader:
        yield df.to_numpy(dtype=np.float64)


def parse_lines(lines: List[str], fmt: Optional[str] = None) -> np.ndarray:
    """Parse in-memory text lines (used by the Predictor file path).
    Strict: prediction outputs are joined to inputs by row number, so a
    skipped malformed line would misattribute every later prediction."""
    if fmt is None:
        fmt = detect_format(lines[:2])
    if fmt == "libsvm":
        return _parse_libsvm(lines, strict=True)
    import pandas as pd

    buf = io.StringIO("".join(l if l.endswith("\n") else l + "\n" for l in lines))
    sep = "," if fmt == "csv" else r"\s+"
    df = pd.read_csv(buf, sep=sep, header=None, engine="python", dtype=np.float64)
    return df.to_numpy(dtype=np.float64)
