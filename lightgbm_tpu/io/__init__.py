from .binner import BinMapper, find_bin_mappers, NUMERICAL, CATEGORICAL
from .metadata import Metadata
from .dataset import BinnedDataset
from .parser import parse_file, detect_format

__all__ = [
    "BinMapper",
    "find_bin_mappers",
    "NUMERICAL",
    "CATEGORICAL",
    "Metadata",
    "BinnedDataset",
    "parse_file",
    "detect_format",
]
