"""Binned dataset: the device-friendly column store.

TPU-native redesign of the reference ``Dataset``/``DatasetLoader``
(include/LightGBM/dataset.h:279-411, src/io/dataset_loader.cpp): instead of
per-feature Bin objects (dense u8/u16/u32 + sparse delta encodings), the
whole dataset is a single dense binned matrix ``X_bin: uint8[n, F]`` (u16
when any feature has >256 bins) laid out row-major in host memory and moved
to TPU HBM once.  Trivial (single-bin) features are dropped and tracked via
``used_feature_map`` exactly like the reference (dataset.h:286-307).

Loading pipeline (mirrors DatasetLoader::LoadFromFile, dataset_loader.cpp:162):
parse text -> resolve column roles -> sample rows (bin_construct_sample_cnt)
-> find per-feature BinMappers -> encode all rows to bins.  Valid sets are
encoded with the *train* set's mappers (LoadFromFileAlignWithOtherDataset,
dataset_loader.cpp:223-264).  A binary cache (npz) skips parse+binning
(SaveBinaryFile/LoadFromBinFile, dataset.cpp:131-168).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config
from ..log import Log
from ..obs import telemetry
from .binner import BinMapper, CATEGORICAL, NUMERICAL, find_bin_mappers
from .metadata import Metadata
from .parser import ParseError, parse_file

BINARY_MAGIC = "lightgbm_tpu_binned_dataset_v1"


def _finite_label_mask(label_col: np.ndarray, config: Config, path: str,
                       has_side_rows: bool = False) -> Optional[np.ndarray]:
    """Input hardening: rows with non-finite labels are a counted,
    logged skip (telemetry ``bad_rows``) — a single NaN label would
    otherwise poison every gradient of the run.  Returns the keep mask,
    or None when all labels are finite.  ``strict_data=true`` raises;
    so does the presence of row-aligned side files (weights/query/
    init_score), where silently renumbering rows would desynchronize
    them."""
    bad = ~np.isfinite(np.asarray(label_col, np.float64))
    n_bad = int(bad.sum())
    if n_bad == 0:
        return None
    msg = (f"{path}: {n_bad} row(s) with non-finite labels "
           f"(first at data row {int(np.argmax(bad))})")
    if config.strict_data:
        raise ParseError(msg + " (strict_data=true)")
    if has_side_rows:
        raise ParseError(
            msg + " — cannot skip rows: row-aligned side files "
            "(.weight/.query/.init) would desynchronize. Clean the data "
            "or regenerate the side files.")
    telemetry.count("bad_rows", n_bad)
    Log.warning(msg + "; skipping them (strict_data=false)")
    return ~bad


def _encode_bins(
    X: np.ndarray,
    used_map: np.ndarray,
    mappers: List[BinMapper],
    X_bin: np.ndarray,
) -> None:
    """Fill ``X_bin[:, inner] = mappers[inner].value_to_bin(X[:, orig])``
    for every used column — the Feature::PushData loop
    (dataset_loader.cpp:761, feature.h:79-85).  Numerical features go
    through the native OpenMP batch encoder when available."""
    from .. import native

    num_orig: List[int] = []
    num_inner: List[int] = []
    num_bounds: List[np.ndarray] = []
    rest: List[Tuple[int, int]] = []
    for orig, inner in enumerate(used_map):
        if inner < 0:
            continue
        m = mappers[inner]
        if m.bin_type == NUMERICAL:
            num_orig.append(orig)
            num_inner.append(int(inner))
            num_bounds.append(np.asarray(m.bin_upper_bound, np.float64))
        else:
            rest.append((orig, int(inner)))

    if num_orig:
        inner_arr = np.asarray(num_inner)
        direct = (
            X_bin.flags.c_contiguous
            and len(num_orig) == X_bin.shape[1]
            and np.array_equal(inner_arr, np.arange(X_bin.shape[1]))
        )
        out = X_bin if direct else np.empty(
            (X.shape[0], len(num_orig)), X_bin.dtype
        )
        if native.value_to_bin_numerical(
            np.ascontiguousarray(X, np.float64),
            np.asarray(num_orig, np.int64),
            num_bounds,
            out,
        ):
            if not direct:
                X_bin[:, inner_arr] = out
        else:  # pure-python fallback
            rest = list(zip(num_orig, num_inner)) + rest

    for orig, inner in rest:
        X_bin[:, inner] = mappers[inner].value_to_bin(X[:, orig])


def _sample_row_indices(n: int, config: Config) -> np.ndarray:
    """The shared-seed bin-construction sample draw (config.h:108 default
    50k rows).  ONE implementation on purpose: streaming, distributed,
    sparse, and in-memory loading must all draw the identical rows for
    their bin mappers (and therefore trees) to be bit-identical."""
    cnt = min(n, int(config.bin_construct_sample_cnt))
    rng = np.random.RandomState(config.data_random_seed)
    if cnt >= n:
        return np.arange(n)
    return np.sort(rng.choice(n, size=cnt, replace=False))


def _resolve_roles(config: Config, names: Optional[List[str]]):
    """Column-role resolution shared by the one-shot and streaming
    loaders (dataset_loader.cpp:23-160): returns (label_col, ignore set,
    categorical cols, weight_col, group_col) in raw column space, with
    weight/group added to the ignore set."""
    label_col = _resolve_column(config.label_column, names)
    if label_col is None:
        label_col = 0
    ignore = set(_resolve_column_list(config.ignore_column, names, label_col))
    cats = _resolve_column_list(config.categorical_column, names, label_col)
    weight_col = _resolve_column(config.weight_column, names, label_col)
    group_col = _resolve_column(config.group_column, names, label_col)
    if weight_col is not None:
        ignore.add(weight_col)
    if group_col is not None:
        ignore.add(group_col)
    return label_col, ignore, cats, weight_col, group_col



def _merge_api_categoricals(cat_inner, categorical_features, num_features):
    """Union API-level (FEATURE-space) categorical declarations into the
    config-derived list, validating range — a typo'd index must not be a
    silent no-op."""
    if not categorical_features:
        return cat_inner
    bad = [c for c in categorical_features if not 0 <= int(c) < num_features]
    if bad:
        raise ValueError(
            f"categorical_feature indices out of range: {bad} "
            f"(num_features={num_features})"
        )
    return sorted(set(cat_inner) | {int(c) for c in categorical_features})


def _resolve_column(spec: str, names: Optional[List[str]],
                    label_col: Optional[int] = None) -> Optional[int]:
    """Resolve 'name:foo' or integer-string column spec to a RAW column
    index (dataset_loader.cpp:23-160).

    Numeric side-column specs (weight/group/ignore/categorical) are
    FEATURE-space in the reference — its parser strips the label before
    assigning indices (parser.hpp:28-33, ``bias = -1``), and name lookups
    go through a label-removed name2idx (dataset_loader.cpp:62-67).  Pass
    ``label_col`` to convert such a spec to raw space; the label spec
    itself resolves raw (``label_col=None``)."""
    if spec is None or spec == "":
        return None
    if spec.startswith("name:"):
        if names is None:
            raise ValueError("column given by name but data has no header")
        return names.index(spec[5:])
    v = int(spec)
    if label_col is not None and v >= label_col:
        v += 1
    return v


def _resolve_column_list(spec: str, names: Optional[List[str]],
                         label_col: Optional[int] = None) -> List[int]:
    """List form of :func:`_resolve_column` (same feature-space
    semantics for numeric entries when ``label_col`` is given)."""
    if not spec:
        return []
    if spec.startswith("name:"):
        if names is None:
            raise ValueError("columns given by name but data has no header")
        return [names.index(s) for s in spec[5:].split(",")]
    out = [int(s) for s in spec.replace(",", " ").split()]
    if label_col is not None:
        out = [v if v < label_col else v + 1 for v in out]
    return out


class BinnedDataset:
    """Columns binned to integers + metadata; ready for device transfer."""

    def __init__(
        self,
        X_bin,
        bin_mappers: List[BinMapper],
        used_feature_map: np.ndarray,
        num_total_features: int,
        metadata: Metadata,
        feature_names: Optional[List[str]] = None,
    ):
        assert len(X_bin.shape) == 2 and X_bin.shape[1] == len(bin_mappers)
        # [n, F_used] uint8/uint16 ndarray, or a SparseBins CSR structure
        # (io/sparse.py) for high-sparsity data — the SparseBin analog
        # (src/io/sparse_bin.hpp), kept when density < 0.2 mirroring the
        # reference's sparse_rate >= 0.8 threshold (bin.cpp:291-302)
        self.X_bin = X_bin
        self.bin_mappers = bin_mappers  # per *used* feature
        # used_feature_map[orig_col] = inner feature idx or -1 (dataset.h:286)
        self.used_feature_map = used_feature_map
        self.num_total_features = int(num_total_features)
        self.metadata = metadata
        self.feature_names = feature_names or [
            f"Column_{i}" for i in range(num_total_features)
        ]

    # ---------------------------------------------------------------- props
    @property
    def is_sparse(self) -> bool:
        return not isinstance(self.X_bin, np.ndarray)

    def dense_bins(self) -> np.ndarray:
        """The dense [n, F_used] binned matrix — materialized on demand
        for sparse storage (binned u8 is 8-64x smaller than the raw f64
        the round-1 path densified, and trivial columns are already
        dropped, so this is the TPU-transfer layout, not a memory bomb)."""
        return self.X_bin.toarray() if self.is_sparse else self.X_bin

    def dense_bins_T_device(self):
        """The feature-major [F, n] binned matrix ON DEVICE, cached on
        the dataset so every booster sharing this dataset — cv() folds,
        train_many() models — shares ONE device copy instead of
        uploading num_models duplicates (the forest-batching HBM
        contract, docs/forest_batching.md)."""
        cached = getattr(self, "_bins_T_device", None)
        if cached is None:
            import jax.numpy as jnp

            cached = jnp.asarray(np.ascontiguousarray(self.dense_bins().T))
            self._bins_T_device = cached
        return cached

    @property
    def num_data(self) -> int:
        return self.X_bin.shape[0]

    @property
    def num_features(self) -> int:
        return self.X_bin.shape[1]

    @property
    def num_bins_per_feature(self) -> np.ndarray:
        return np.array([m.num_bin for m in self.bin_mappers], dtype=np.int32)

    @property
    def max_num_bin(self) -> int:
        return int(self.num_bins_per_feature.max()) if self.num_features else 1

    @property
    def is_categorical(self) -> np.ndarray:
        return np.array(
            [m.bin_type == CATEGORICAL for m in self.bin_mappers], dtype=bool
        )

    def inner_to_real_feature(self, inner: int) -> int:
        """Inner feature index -> original column index."""
        return int(np.nonzero(self.used_feature_map == inner)[0][0])

    @property
    def real_feature_indices(self) -> np.ndarray:
        out = np.full(self.num_features, -1, dtype=np.int64)
        for orig, inner in enumerate(self.used_feature_map):
            if inner >= 0:
                out[inner] = orig
        return out

    # ------------------------------------------------------------ construct
    @staticmethod
    def from_matrix(
        X: np.ndarray,
        metadata: Metadata,
        config: Optional[Config] = None,
        categorical_features: Sequence[int] = (),
        feature_names: Optional[List[str]] = None,
        mappers_all: Optional[List[BinMapper]] = None,
    ) -> "BinnedDataset":
        """Bin a dense feature matrix.  ``mappers_all`` (one BinMapper per
        column, trivial ones dropped here) skips bin finding — used by the
        distributed loader where mappers must be rank-consistent."""
        config = config or Config()
        X = np.ascontiguousarray(X, dtype=np.float64)
        n, f_total = X.shape
        if mappers_all is None:
            sample_idx = _sample_row_indices(n, config)
            mappers_all = find_bin_mappers(
                X[sample_idx],
                total_sample_cnt=len(sample_idx),
                max_bin=config.max_bin,
                categorical_features=categorical_features,
            )
        if len(mappers_all) != f_total:
            raise ValueError(
                f"mappers_all covers {len(mappers_all)} columns, data has {f_total}"
            )
        used_map = np.full(f_total, -1, dtype=np.int64)
        used_mappers: List[BinMapper] = []
        for j, m in enumerate(mappers_all):
            if not m.is_trivial:
                used_map[j] = len(used_mappers)
                used_mappers.append(m)

        max_nb = max((m.num_bin for m in used_mappers), default=1)
        if max_nb > 65536:
            # the reference's u32 dense-bin specialization
            # (src/io/bin.cpp:304-322) is deliberately not carried: the
            # packed training record stores bins 2-per-i32 at u16 width
            # and no realistic config exceeds 65536 bins per feature —
            # fail loudly instead of silently wrapping the u16 cast
            raise ValueError(
                f"a feature produced {max_nb} bins; this build supports "
                f"max 65536 bins per feature (uint16 storage) — lower "
                f"max_bin or bin_construct_sample_cnt")
        dtype = np.uint8 if max_nb <= 256 else np.uint16
        X_bin = np.empty((n, len(used_mappers)), dtype=dtype)
        _encode_bins(X, used_map, used_mappers, X_bin)
        return BinnedDataset(
            X_bin, used_mappers, used_map, f_total, metadata, feature_names
        )

    @staticmethod
    def from_csr(
        indptr: np.ndarray,
        indices: np.ndarray,
        values: np.ndarray,
        num_cols: int,
        metadata: Metadata,
        config: Optional[Config] = None,
        categorical_features: Sequence[int] = (),
        feature_names: Optional[List[str]] = None,
        mappers_all: Optional[List[BinMapper]] = None,
        keep_sparse: Optional[bool] = None,
    ) -> "BinnedDataset":
        """Bin a CSR matrix in O(nnz) memory — no dense f64 ever exists.

        Mirrors the reference's sparse push path (Feature::PushData on
        ``(col, value)`` pairs, feature.h:79-85 + sparse_bin.hpp): bin
        mappers are found from a sampled row subset with elided zeros
        counted (bin.cpp:48-85), then every stored entry is bin-encoded
        in place.  Storage stays CSR when density < 0.2 (``keep_sparse``
        overrides), else the dense u8 matrix is built.
        """
        from .sparse import encode_csr_bins, find_bin_mappers_csr

        config = config or Config()
        n = len(indptr) - 1
        if mappers_all is None:
            sample_idx = _sample_row_indices(n, config)
            mappers_all = find_bin_mappers_csr(
                indptr, indices, values, num_cols, sample_idx,
                max_bin=config.max_bin,
                categorical_features=categorical_features,
            )
        used_map = np.full(num_cols, -1, dtype=np.int64)
        used_mappers: List[BinMapper] = []
        for j, m in enumerate(mappers_all):
            if not m.is_trivial:
                used_map[j] = len(used_mappers)
                used_mappers.append(m)
        sb = encode_csr_bins(indptr, indices, values, used_map, used_mappers)
        f_used = max(len(used_mappers), 1)
        density = sb.nnz / float(max(n, 1) * f_used)
        if keep_sparse is None:
            # is_enable_sparse=false forces dense storage (config.h:104)
            keep_sparse = config.is_enable_sparse and density < 0.2
        X_bin = sb if keep_sparse else sb.toarray()
        return BinnedDataset(
            X_bin, used_mappers, used_map, num_cols, metadata, feature_names
        )

    def align_with(
        self, X: np.ndarray, metadata: Metadata
    ) -> "BinnedDataset":
        """Bin another raw matrix with THIS dataset's mappers (valid set
        alignment, dataset_loader.cpp:223-264)."""
        X = np.ascontiguousarray(X, dtype=np.float64)
        n, f_total = X.shape
        if f_total < self.num_total_features:
            pad = np.zeros((n, self.num_total_features - f_total), dtype=np.float64)
            X = np.hstack([X, pad])
        X_bin = np.empty((n, self.num_features), dtype=self.X_bin.dtype)
        _encode_bins(X, self.used_feature_map, self.bin_mappers, X_bin)
        return BinnedDataset(
            X_bin,
            self.bin_mappers,
            self.used_feature_map,
            self.num_total_features,
            metadata,
            self.feature_names,
        )

    def align_with_csr(
        self,
        indptr: np.ndarray,
        indices: np.ndarray,
        values: np.ndarray,
        metadata: Metadata,
        keep_sparse: Optional[bool] = None,
    ) -> "BinnedDataset":
        """Sparse counterpart of ``align_with``: bin CSR rows with THIS
        dataset's mappers in O(nnz)."""
        from .sparse import encode_csr_bins

        # entries in columns this dataset never saw map to no used feature
        in_range = indices < len(self.used_feature_map)
        if not in_range.all():
            n = len(indptr) - 1
            rows = np.repeat(np.arange(n, dtype=np.int64), np.diff(indptr))
            rows, indices, values = rows[in_range], indices[in_range], values[in_range]
            row_lens = np.bincount(rows, minlength=n)
            indptr = np.concatenate([[0], np.cumsum(row_lens, dtype=np.int64)])
        sb = encode_csr_bins(
            indptr, indices, values, self.used_feature_map, self.bin_mappers
        )
        if keep_sparse is None:
            keep_sparse = self.is_sparse
        return BinnedDataset(
            sb if keep_sparse else sb.toarray(),
            self.bin_mappers,
            self.used_feature_map,
            self.num_total_features,
            metadata,
            self.feature_names,
        )

    @staticmethod
    def from_file(
        path: str,
        config: Optional[Config] = None,
        reference: Optional["BinnedDataset"] = None,
        rank: Optional[int] = None,
        categorical_features: Optional[Sequence[int]] = None,
    ) -> "BinnedDataset":
        """Load + bin a text data file (or its binary cache).

        With ``config.num_machines > 1`` and ``is_pre_partition=false``,
        every rank reads the file and keeps only its shared-seed random
        row partition — query-granular for ranked data
        (dataset_loader.cpp:500-605).  ``rank`` defaults to
        ``jax.process_index()``."""
        config = config or Config()
        bin_path = path + ".bin"
        if (
            config.enable_load_from_binary_file
            and os.path.exists(bin_path)
            and reference is None
            and config.num_machines <= 1
            and not categorical_features
            # a cached binary records nothing about API-level categorical
            # declarations; honoring the declaration wins over the cache
        ):
            try:
                ds = BinnedDataset.load_binary(bin_path)
                if ds.is_sparse and not config.is_enable_sparse:
                    # the cache was written sparse; honor the flag anyway
                    ds.X_bin = ds.dense_bins()
                return ds
            except Exception:
                pass
        from .parser import detect_file_format

        fmt = detect_file_format(path, config.has_header)
        if fmt == "libsvm" and not config.weight_column and not config.group_column:
            return BinnedDataset._from_libsvm_sparse(
                path, config, reference=reference, rank=rank,
                categorical_features=categorical_features,
            )
        single_machine = config.num_machines <= 1 or config.is_pre_partition
        # auto-stream only for files too big to comfortably hold as f64
        # (the flag is the explicit opt-in; dense LibSVM with weight/
        # group columns keeps the one-shot parser)
        want_stream = config.use_two_round_loading or (
            os.path.getsize(path) > (4 << 30)
        )
        if want_stream and single_machine and fmt != "libsvm":
            try:
                return BinnedDataset._from_file_streaming(
                    path, config, fmt, reference=reference,
                    categorical_features=categorical_features,
                )
            except ParseError:
                raise  # already classified (strict mode / label guard)
            except ValueError as e:
                # malformed rows mid-stream: the chunked fast reader
                # cannot skip-and-continue (dropped rows would desync
                # the counted preallocation), so degrade to the one-shot
                # lenient path below — counted bad_rows skip semantics,
                # at the cost of whole-file memory for an already-
                # degraded input.  strict_data raises instead.
                if config.strict_data:
                    raise ParseError(
                        f"{path}: malformed rows in streaming load "
                        f"(strict_data=true): {type(e).__name__}: "
                        f"{str(e)[:200]}") from e
                Log.warning(
                    f"{path}: streaming parse failed "
                    f"({type(e).__name__}: {str(e)[:120]}); falling "
                    "back to one-shot lenient load (malformed rows "
                    "will be counted and skipped)")
        raw, names = parse_file(path, has_header=config.has_header, fmt=fmt,
                                strict=config.strict_data)
        side = Metadata.load_side_files(path)

        # ---- resolve column roles on the FULL file (dataset_loader.cpp:23-160)
        label_col, ignore, cats, weight_col, group_col = _resolve_roles(
            config, names
        )
        keep = _finite_label_mask(
            raw[:, label_col], config, path,
            has_side_rows=any(side.get(k) is not None for k in
                              ("weights", "query_boundaries", "init_score")))
        if keep is not None:
            raw = raw[keep]
        n = raw.shape[0]
        label = raw[:, label_col].astype(np.float32)
        weights = side.get("weights")
        if weight_col is not None:
            weights = raw[:, weight_col].astype(np.float32)
        qb = side.get("query_boundaries")
        if group_col is not None:
            gid = raw[:, group_col].astype(np.int64)
            # contiguous group ids -> boundaries
            change = np.nonzero(np.diff(gid))[0] + 1
            qb = np.concatenate([[0], change, [n]])

        feat_cols = [
            j for j in range(raw.shape[1]) if j != label_col and j not in ignore
        ]
        X = raw[:, feat_cols]
        fnames = (
            [names[j] for j in feat_cols]
            if names is not None
            else [f"Column_{j}" for j in range(len(feat_cols))]
        )
        cat_inner = _merge_api_categoricals(
            [feat_cols.index(c) for c in cats if c in feat_cols],
            categorical_features, len(feat_cols),
        )
        meta = Metadata(
            label=label,
            weights=weights,
            query_boundaries=qb,
            init_score=side.get("init_score"),
        )

        distributed = config.num_machines > 1 and not config.is_pre_partition
        mappers_all = None
        if distributed:
            from .distributed import (
                distributed_find_bin_mappers,
                partition_rows,
            )
            import jax

            if rank is None:
                rank = jax.process_index()
            # query-granular partition uses the FULL metadata's boundaries
            # (side file OR group_column, dataset_loader.cpp:560-605)
            keep = partition_rows(
                n, rank, config.num_machines,
                seed=config.data_random_seed,
                query_boundaries=meta.query_boundaries,
            )
            # Bin mappers must be rank-consistent.  Since is_pre_partition=
            # false means every rank parsed the FULL file, the shared-seed
            # sample over the full data gives identical mappers everywhere
            # with zero communication; with multiple attached processes the
            # feature-sharded finder + mapper allgather is used instead
            # (dataset_loader.cpp:692-755).
            sample_idx = _sample_row_indices(n, config)
            if jax.process_count() > 1:
                mappers_all = distributed_find_bin_mappers(
                    X[sample_idx], rank, config.num_machines,
                    max_bin=config.max_bin, categorical_features=cat_inner,
                    total_sample_cnt=len(sample_idx),
                )
            else:
                mappers_all = find_bin_mappers(
                    X[sample_idx], total_sample_cnt=len(sample_idx),
                    max_bin=config.max_bin, categorical_features=cat_inner,
                )
            X = X[keep]
            meta = meta.subset(keep)

        if reference is not None:
            return reference.align_with(X, meta)
        ds = BinnedDataset.from_matrix(
            X, meta, config, categorical_features=cat_inner,
            feature_names=fnames, mappers_all=mappers_all,
        )
        # the binary cache holds FULL-file contents only — a partitioned
        # rank subset must never poison the shared cache path
        if config.is_save_binary_file and not distributed:
            ds.save_binary(bin_path)
        return ds

    @staticmethod
    def _from_file_streaming(
        path: str,
        config: Config,
        fmt: str,
        reference: Optional["BinnedDataset"] = None,
        chunk_rows: int = 200_000,
        categorical_features: Optional[Sequence[int]] = None,
    ) -> "BinnedDataset":
        """Two-round loading (use_two_round_loading, dataset_loader.cpp:
        181-209): round one streams chunks to pull the bin-construction
        sample, round two streams again encoding each chunk straight into
        the preallocated binned matrix.  Peak RSS is the binned matrix
        plus one text chunk — never the whole file as float64.

        The sampled row indices reuse the in-memory path's shared-seed
        draw over the counted row total, so bin mappers (and therefore
        trees) are bit-identical to non-streaming loading.
        """
        from .parser import (
            _read_head,
            count_data_rows,
            parse_file_chunks,
        )

        names: Optional[List[str]] = None
        if config.has_header:
            head = _read_head(path, 1)
            sep = "," if fmt == "csv" else None
            names = [s.strip() for s in head[0].strip().split(sep)]
        side = Metadata.load_side_files(path)
        n = count_data_rows(path, config.has_header)

        label_col, ignore, cats, weight_col, group_col = _resolve_roles(
            config, names
        )

        feat_cols: Optional[List[int]] = None
        mappers_all = None
        if reference is None:
            # ---- round 1: stream chunks, keep only the sampled rows
            sample_idx = _sample_row_indices(n, config)
            offset = 0
            buf: List[np.ndarray] = []
            for chunk in parse_file_chunks(path, config.has_header, fmt, chunk_rows):
                if feat_cols is None:
                    feat_cols = [
                        j for j in range(chunk.shape[1])
                        if j != label_col and j not in ignore
                    ]
                lo = np.searchsorted(sample_idx, offset)
                hi = np.searchsorted(sample_idx, offset + len(chunk))
                if hi > lo:
                    buf.append(chunk[sample_idx[lo:hi] - offset][:, feat_cols])
                offset += len(chunk)
            sample_raw = np.vstack(buf)
            cat_inner = _merge_api_categoricals(
                [feat_cols.index(c) for c in cats if c in feat_cols],
                categorical_features, len(feat_cols),
            )
            mappers_all = find_bin_mappers(
                sample_raw,
                total_sample_cnt=len(sample_idx),
                max_bin=config.max_bin,
                categorical_features=cat_inner,
            )
            used_map = np.full(len(feat_cols), -1, dtype=np.int64)
            used_mappers: List[BinMapper] = []
            for j, m in enumerate(mappers_all):
                if not m.is_trivial:
                    used_map[j] = len(used_mappers)
                    used_mappers.append(m)
        else:
            used_map = reference.used_feature_map
            used_mappers = reference.bin_mappers

        # ---- round 2: stream again, encoding chunks into the binned matrix
        dtype = (
            np.uint8
            if max((m.num_bin for m in used_mappers), default=1) <= 256
            else np.uint16
        )
        X_bin = np.empty((n, len(used_mappers)), dtype=dtype)
        label = np.empty(n, np.float32)
        weights = np.empty(n, np.float32) if weight_col is not None else None
        gid = np.empty(n, np.int64) if group_col is not None else None
        offset = 0
        for chunk in parse_file_chunks(path, config.has_header, fmt, chunk_rows):
            if feat_cols is None:
                feat_cols = [
                    j for j in range(chunk.shape[1])
                    if j != label_col and j not in ignore
                ]
            m_rows = len(chunk)
            X = chunk[:, feat_cols]
            if reference is not None and X.shape[1] < len(used_map):
                X = np.hstack(
                    [X, np.zeros((m_rows, len(used_map) - X.shape[1]))]
                )
            _encode_bins(X, used_map, used_mappers, X_bin[offset:offset + m_rows])
            label[offset:offset + m_rows] = chunk[:, label_col]
            if weights is not None:
                weights[offset:offset + m_rows] = chunk[:, weight_col]
            if gid is not None:
                gid[offset:offset + m_rows] = chunk[:, group_col]
            offset += m_rows

        keep = _finite_label_mask(
            label, config, path,
            has_side_rows=any(side.get(k) is not None for k in
                              ("weights", "query_boundaries", "init_score")))
        if keep is not None:
            X_bin, label = X_bin[keep], label[keep]
            weights = weights[keep] if weights is not None else None
            gid = gid[keep] if gid is not None else None
            n = int(keep.sum())

        qb = side.get("query_boundaries")
        if gid is not None:
            change = np.nonzero(np.diff(gid))[0] + 1
            qb = np.concatenate([[0], change, [n]])
        meta = Metadata(
            label=label,
            weights=side.get("weights") if weights is None else weights,
            query_boundaries=qb,
            init_score=side.get("init_score"),
        )
        fnames = (
            [names[j] for j in feat_cols]
            if names is not None
            else None
        )
        if reference is not None:
            return BinnedDataset(
                X_bin,
                reference.bin_mappers,
                reference.used_feature_map,
                reference.num_total_features,
                meta,
                reference.feature_names,
            )
        ds = BinnedDataset(
            X_bin, used_mappers, used_map, len(feat_cols), meta, fnames
        )
        if config.is_save_binary_file:
            ds.save_binary(path + ".bin")
        return ds

    @staticmethod
    def _from_libsvm_sparse(
        path: str,
        config: Config,
        reference: Optional["BinnedDataset"] = None,
        rank: Optional[int] = None,
        categorical_features: Optional[Sequence[int]] = None,
    ) -> "BinnedDataset":
        """LibSVM ingest in O(nnz) memory — streamed CSR parse, sparse
        bin finding with elided zeros, in-place bin encoding.  Replaces
        the round-1 dense-f64 materialization (a news20-scale memory
        bomb; reference handles this via SparseBin, sparse_bin.hpp).

        Column-space note: ``ignore_column``/``categorical_column``
        numeric specs are FEATURE indices (the reference's parsers emit
        label-removed indices, parser.hpp:28-33; LibSVM token indices ARE
        feature indices), so they apply to the CSR columns directly.
        """
        from .sparse import _ranges_concat, parse_libsvm_csr

        label, indptr, indices, values, num_cols = parse_libsvm_csr(
            path, has_header=config.has_header
        )
        side = Metadata.load_side_files(path)
        keep = _finite_label_mask(
            label, config, path,
            has_side_rows=any(side.get(k) is not None for k in
                              ("weights", "query_boundaries", "init_score")))
        if keep is not None:
            nz_keep = np.repeat(keep, np.diff(indptr))
            indices, values = indices[nz_keep], values[nz_keep]
            label = label[keep]
            row_lens = np.diff(indptr)[keep]
            indptr = np.concatenate([[0], np.cumsum(row_lens,
                                                    dtype=np.int64)])
        n = len(label)

        ignore = set(_resolve_column_list(config.ignore_column, None))
        if ignore:
            keep = ~np.isin(indices, np.asarray(sorted(ignore)))
            rows = np.repeat(np.arange(n, dtype=np.int64), np.diff(indptr))
            rows, indices, values = rows[keep], indices[keep], values[keep]
            row_lens = np.bincount(rows, minlength=n)
            indptr = np.concatenate([[0], np.cumsum(row_lens, dtype=np.int64)])
        cats = _merge_api_categoricals(
            _resolve_column_list(config.categorical_column, None),
            categorical_features, num_cols,
        )
        meta = Metadata(
            label=label,
            weights=side.get("weights"),
            query_boundaries=side.get("query_boundaries"),
            init_score=side.get("init_score"),
        )

        distributed = config.num_machines > 1 and not config.is_pre_partition
        mappers_all = None
        if distributed:
            from .distributed import partition_rows
            from .sparse import find_bin_mappers_csr
            import jax

            if rank is None:
                rank = jax.process_index()
            keep_rows = partition_rows(
                n, rank, config.num_machines,
                seed=config.data_random_seed,
                query_boundaries=meta.query_boundaries,
            )
            # shared-seed sample over the FULL file gives every rank
            # identical mappers with zero communication (every rank
            # parsed the whole file when is_pre_partition=false)
            sample_idx = _sample_row_indices(n, config)
            mappers_all = find_bin_mappers_csr(
                indptr, indices, values, num_cols, sample_idx,
                max_bin=config.max_bin, categorical_features=cats,
            )
            keep_rows = np.asarray(keep_rows)
            starts = indptr[keep_rows]
            lens = indptr[keep_rows + 1] - starts
            take = _ranges_concat(starts, lens)
            indices, values = indices[take], values[take]
            indptr = np.concatenate([[0], np.cumsum(lens, dtype=np.int64)])
            meta = meta.subset(keep_rows)

        if reference is not None:
            return reference.align_with_csr(indptr, indices, values, meta)
        ds = BinnedDataset.from_csr(
            indptr, indices, values, num_cols, meta, config,
            categorical_features=cats, mappers_all=mappers_all,
        )
        if config.is_save_binary_file and not distributed:
            ds.save_binary(path + ".bin")
        return ds

    # ---------------------------------------------------------- binary cache
    def save_binary(self, path: str) -> None:
        import json

        tmp = path + ".tmp.npz"
        sparse_fields = {}
        if self.is_sparse:
            sparse_fields = dict(
                sp_indptr=self.X_bin.indptr,
                sp_col=self.X_bin.col,
                sp_bin=self.X_bin.bin,
                sp_default=self.X_bin.default_bins,
                sp_shape=np.asarray(self.X_bin.shape, dtype=np.int64),
            )
        np.savez_compressed(
            tmp,
            magic=BINARY_MAGIC,
            X_bin=np.empty((0, 0), np.uint8) if self.is_sparse else self.X_bin,
            **sparse_fields,
            used_feature_map=self.used_feature_map,
            num_total_features=self.num_total_features,
            mappers=json.dumps([m.to_dict() for m in self.bin_mappers]),
            feature_names=json.dumps(self.feature_names),
            label=self.metadata.label if self.metadata.label is not None else np.empty(0),
            weights=self.metadata.weights
            if self.metadata.weights is not None
            else np.empty(0),
            query_boundaries=self.metadata.query_boundaries
            if self.metadata.query_boundaries is not None
            else np.empty(0, dtype=np.int64),
            init_score=self.metadata.init_score
            if self.metadata.init_score is not None
            else np.empty(0),
        )
        # numpy appends .npz to names without it; move atomically onto the
        # requested name so a re-save never leaves a stale cache behind
        os.replace(tmp if os.path.exists(tmp) else tmp + ".npz", path)

    @staticmethod
    def load_binary(path: str) -> "BinnedDataset":
        import json

        with np.load(path, allow_pickle=False) as z:
            if str(z["magic"]) != BINARY_MAGIC:
                raise ValueError("not a lightgbm_tpu binary dataset file")
            mappers = [BinMapper.from_dict(d) for d in json.loads(str(z["mappers"]))]
            meta = Metadata(
                label=z["label"] if z["label"].size else None,
                weights=z["weights"] if z["weights"].size else None,
                query_boundaries=z["query_boundaries"]
                if z["query_boundaries"].size
                else None,
                init_score=z["init_score"] if z["init_score"].size else None,
            )
            if "sp_indptr" in z:
                from .sparse import SparseBins

                storage = SparseBins(
                    z["sp_indptr"], z["sp_col"], z["sp_bin"],
                    z["sp_default"], tuple(z["sp_shape"]),
                )
            else:
                storage = z["X_bin"]
            return BinnedDataset(
                storage,
                mappers,
                z["used_feature_map"],
                int(z["num_total_features"]),
                meta,
                json.loads(str(z["feature_names"])),
            )

    # -------------------------------------------------------------- numerics
    def subset(self, indices: np.ndarray) -> "BinnedDataset":
        """Row subset sharing bin mappers (Dataset::Subset, dataset.cpp:59)."""
        indices = np.asarray(indices)
        return BinnedDataset(
            self.X_bin.rows(indices) if self.is_sparse else self.X_bin[indices],
            self.bin_mappers,
            self.used_feature_map,
            self.num_total_features,
            self.metadata.subset(indices),
            self.feature_names,
        )

    def check_align(self, other: "BinnedDataset") -> bool:
        """Valid-data bin compatibility (Dataset::CheckAlign,
        dataset.h:290-306)."""
        if other.num_features != self.num_features:
            return False
        return all(
            a.num_bin == b.num_bin for a, b in zip(self.bin_mappers, other.bin_mappers)
        )

    def bin_thresholds_real(self) -> List[np.ndarray]:
        """Per-feature real-valued threshold for each bin (used when writing
        tree thresholds in raw-value space, tree.cpp:70)."""
        return [m.bin_upper_bound if m.bin_type == NUMERICAL else np.asarray(m.bin_to_category, dtype=np.float64) for m in self.bin_mappers]
