"""Dataset metadata: labels, weights, query boundaries, init scores.

Mirrors the reference ``Metadata`` (include/LightGBM/dataset.h:36-247,
src/io/metadata.cpp): side files ``<data>.weight``, ``<data>.query``,
``<data>.init`` are auto-loaded next to the data file
(metadata.cpp:380-476); query sizes are converted to cumulative
boundaries; query weights are means of member weights.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np


class Metadata:
    def __init__(
        self,
        label: Optional[np.ndarray] = None,
        weights: Optional[np.ndarray] = None,
        query_boundaries: Optional[np.ndarray] = None,
        init_score: Optional[np.ndarray] = None,
    ):
        self.label = None if label is None else np.asarray(label, dtype=np.float32)
        self.weights = None if weights is None else np.asarray(weights, dtype=np.float32)
        self.query_boundaries = (
            None if query_boundaries is None else np.asarray(query_boundaries, dtype=np.int64)
        )
        self.init_score = (
            None if init_score is None else np.asarray(init_score, dtype=np.float64)
        )
        self.query_weights: Optional[np.ndarray] = None
        self._finish()

    # ------------------------------------------------------------------
    def _finish(self) -> None:
        if self.query_boundaries is not None and self.weights is not None:
            qb = self.query_boundaries
            # per-query weight = mean of member weights (metadata.cpp:95-105)
            sums = np.add.reduceat(self.weights, qb[:-1])
            self.query_weights = (sums / np.maximum(np.diff(qb), 1)).astype(np.float32)

    @property
    def num_data(self) -> int:
        return 0 if self.label is None else len(self.label)

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1

    def set_field(self, name: str, data) -> None:
        if data is not None:
            data = np.asarray(data)
        if name == "label":
            self.label = None if data is None else data.astype(np.float32)
        elif name == "weight":
            self.weights = None if data is None else data.astype(np.float32)
        elif name == "init_score":
            self.init_score = None if data is None else data.astype(np.float64)
        elif name == "group" or name == "query":
            if data is None:
                self.query_boundaries = None
            else:
                data = data.astype(np.int64)
                if len(data) and data[0] == 0 and np.all(np.diff(data) >= 0):
                    # already boundaries
                    self.query_boundaries = data
                else:  # group sizes -> boundaries (metadata.cpp:437-453)
                    self.query_boundaries = np.concatenate(
                        [[0], np.cumsum(data)]
                    ).astype(np.int64)
        else:
            raise ValueError(f"Unknown field {name!r}")
        self._finish()

    def get_field(self, name: str):
        if name == "label":
            return self.label
        if name == "weight":
            return self.weights
        if name == "init_score":
            return self.init_score
        if name in ("group", "query"):
            # group SIZES, matching what callers set and what custom
            # objectives expect; boundaries stay internal
            if self.query_boundaries is None:
                return None
            return np.diff(self.query_boundaries)
        raise ValueError(f"Unknown field {name!r}")

    def subset(self, indices: np.ndarray) -> "Metadata":
        """Row subset (used by bagging-by-subset and Dataset.Subset).

        Query boundaries are remapped to the selected rows, dropping
        now-empty queries (reference Metadata::Init(fullset, used_indices),
        metadata.cpp:48-110)."""
        indices = np.asarray(indices)
        lab = None if self.label is None else self.label[indices]
        w = None if self.weights is None else self.weights[indices]
        ini = None
        if self.init_score is not None:
            ncls = len(self.init_score) // max(self.num_data, 1)
            ini = (
                self.init_score.reshape(ncls, -1)[:, indices].reshape(-1)
                if ncls > 1
                else self.init_score[indices]
            )
        qb = None
        if self.query_boundaries is not None:
            # per-row query id, then boundary rebuild over the kept rows
            qid = np.searchsorted(self.query_boundaries, indices, side="right") - 1
            if len(qid) and np.any(np.diff(qid) < 0):
                raise ValueError("subset indices must be sorted for query data")
            per_query = np.bincount(qid, minlength=self.num_queries)
            sizes = per_query[per_query > 0]
            qb = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
        return Metadata(lab, w, qb, ini)

    # ------------------------------------------------------------- side files
    @staticmethod
    def load_side_files(data_path: str) -> dict:
        """Auto-load <data>.weight/.query/.init if present
        (metadata.cpp:380-476)."""
        out = {}
        wpath = data_path + ".weight"
        if os.path.exists(wpath):
            out["weights"] = np.loadtxt(wpath, dtype=np.float32).reshape(-1)
        qpath = data_path + ".query"
        if os.path.exists(qpath):
            sizes = np.loadtxt(qpath, dtype=np.int64).reshape(-1)
            out["query_boundaries"] = np.concatenate([[0], np.cumsum(sizes)])
        ipath = data_path + ".init"
        if os.path.exists(ipath):
            out["init_score"] = np.loadtxt(ipath, dtype=np.float64).reshape(-1)
        return out
