"""Sparse ingest: CSR/LibSVM -> binned storage without densification.

TPU-native replacement for the reference's sparse input path
(src/io/sparse_bin.hpp:153-181 delta-encoded per-feature bins,
src/io/parser.cpp LibSVM ``idx:value`` pairs).  The reference keeps
*storage* sparse per feature when sparse_rate >= 0.8 (bin.cpp:291-302);
here the whole dataset keeps ONE binned CSR structure (row pointers +
column + bin per stored entry) and rows absent from a column implicitly
sit in that column's *default bin* (the bin of raw 0.0, bin.h:150-160).

Invariants:
* loading a LibSVM/CSR input is O(nnz) memory end-to-end — no dense
  float64 matrix is ever materialized (the round-1 path called
  ``.toarray()``, a memory bomb at news20 scale);
* the binned result is bit-identical to the dense path on the same data
  (the parity tests pin this), because bin *finding* already models
  elided zeros via ``total_sample_cnt`` (io/binner.py, bin.cpp:48-85);
* dense compute stays the TPU layout: ``SparseBins.toarray()`` produces
  the usual uint8 ``[n, F_used]`` matrix on demand (binned u8 is 8-64x
  smaller than raw f64, so post-binning densification of *used* features
  is cheap; 1M mostly-trivial columns collapse to the few thousand
  non-trivial ones first).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from .binner import BinMapper, CATEGORICAL, NUMERICAL


class SparseBins:
    """Binned CSR storage: entry k of row i (``indptr[i] <= k < indptr[i+1]``)
    says "inner feature ``col[k]`` has bin ``bin[k]``"; every (row, feature)
    pair not stored holds ``default_bins[feature]``.
    """

    __slots__ = ("indptr", "col", "bin", "default_bins", "shape", "dtype")

    def __init__(self, indptr, col, bins, default_bins, shape):
        self.indptr = np.asarray(indptr, dtype=np.int64)
        self.col = np.asarray(col, dtype=np.int32)
        self.bin = bins
        self.default_bins = np.asarray(default_bins)
        self.shape = tuple(shape)
        self.dtype = bins.dtype

    @property
    def nnz(self) -> int:
        return len(self.col)

    @property
    def nbytes(self) -> int:
        return self.indptr.nbytes + self.col.nbytes + self.bin.nbytes

    def toarray(self) -> np.ndarray:
        """Dense ``[n, F_used]`` binned matrix (default bins filled in)."""
        n, f = self.shape
        out = np.empty((n, f), dtype=self.dtype)
        out[:] = self.default_bins.astype(self.dtype)[None, :]
        rows = np.repeat(
            np.arange(n, dtype=np.int64), np.diff(self.indptr)
        )
        out[rows, self.col] = self.bin
        return out

    def rows(self, indices: np.ndarray) -> "SparseBins":
        """Row subset (Dataset::Subset) in O(nnz of the subset)."""
        indices = np.asarray(indices, dtype=np.int64)
        starts = self.indptr[indices]
        lens = self.indptr[indices + 1] - starts
        new_indptr = np.concatenate([[0], np.cumsum(lens)])
        take = _ranges_concat(starts, lens)
        return SparseBins(
            new_indptr, self.col[take], self.bin[take],
            self.default_bins, (len(indices), self.shape[1]),
        )


def _ranges_concat(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Concatenate index ranges [starts[i], starts[i]+lens[i]) vectorized:
    a cumsum over an array of ones with a corrective jump planted at each
    range boundary."""
    lens = np.asarray(lens, dtype=np.int64)
    total = int(lens.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64)
    nonempty = lens > 0
    st = np.asarray(starts, dtype=np.int64)[nonempty]
    ln = lens[nonempty]
    out = np.ones(total, dtype=np.int64)
    out[0] = st[0]
    pos = np.cumsum(ln)[:-1]  # positions where each later range begins
    prev_end = st[:-1] + ln[:-1]
    out[pos] = st[1:] - prev_end + 1
    return np.cumsum(out)


def parse_libsvm_csr(
    path_or_lines, has_header: bool = False, chunk_lines: int = 200_000
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, int]:
    """Stream-parse LibSVM ``label idx:val ...`` text into CSR arrays.

    Returns ``(labels f32[n], indptr int64[n+1], indices int32[nnz],
    values f64[nnz], num_cols)``.  Peak memory is O(nnz) plus one
    ``chunk_lines``-line text buffer (the reference streams 1MB blocks,
    utils/text_reader.h:144-288).
    """
    own = isinstance(path_or_lines, str)
    fh = open(path_or_lines) if own else iter(path_or_lines)
    labels: List[np.ndarray] = []
    idx_parts: List[np.ndarray] = []
    val_parts: List[np.ndarray] = []
    row_lens: List[np.ndarray] = []
    try:
        if own and has_header:
            fh.readline()
        first = not own and has_header
        while True:
            lines = []
            for line in fh:
                if first:
                    first = False
                    continue
                if line.strip():
                    lines.append(line)
                if len(lines) >= chunk_lines:
                    break
            if not lines:
                break
            lab, ind, val, rl = _parse_libsvm_chunk(lines)
            labels.append(lab)
            idx_parts.append(ind)
            val_parts.append(val)
            row_lens.append(rl)
    finally:
        if own:
            fh.close()
    if not labels:
        return (
            np.empty(0, np.float32),
            np.zeros(1, np.int64),
            np.empty(0, np.int32),
            np.empty(0, np.float64),
            0,
        )
    lab = np.concatenate(labels)
    ind = np.concatenate(idx_parts)
    val = np.concatenate(val_parts)
    rl = np.concatenate(row_lens)
    indptr = np.concatenate([[0], np.cumsum(rl, dtype=np.int64)])
    num_cols = int(ind.max()) + 1 if len(ind) else 0
    return lab.astype(np.float32), indptr, ind.astype(np.int32), val, num_cols


def _parse_libsvm_chunk(lines: List[str]):
    """Vectorized LibSVM token parse of a batch of lines."""
    toks = np.asarray(" ".join(s.strip() for s in lines).split())
    is_pair = np.char.find(toks, ":") >= 0
    labels = toks[~is_pair].astype(np.float64)
    # rows are delimited by the label tokens; entries between two labels
    # belong to the earlier row
    row_of_tok = np.cumsum(~is_pair) - 1
    pair_toks = toks[is_pair]
    if len(pair_toks):
        kv = np.char.partition(pair_toks, ":")
        ind = kv[:, 0].astype(np.int64)
        val = kv[:, 2].astype(np.float64)
    else:
        ind = np.empty(0, np.int64)
        val = np.empty(0, np.float64)
    row_lens = np.bincount(row_of_tok[is_pair], minlength=len(labels))
    return labels, ind, val, row_lens.astype(np.int64)


def find_bin_mappers_csr(
    indptr: np.ndarray,
    indices: np.ndarray,
    values: np.ndarray,
    num_cols: int,
    sample_idx: np.ndarray,
    max_bin: int = 256,
    categorical_features: Sequence[int] = (),
) -> List[BinMapper]:
    """Per-column BinMappers from a sampled row subset of a CSR matrix.

    Elided zeros are modeled exactly like the reference's sparse
    bin-finding (bin.cpp:48-85): each column's sample is its nonzero
    values among the sampled rows, with ``total_sample_cnt`` equal to the
    number of sampled rows.
    """
    sample_idx = np.asarray(sample_idx, dtype=np.int64)
    starts = indptr[sample_idx]
    lens = indptr[sample_idx + 1] - starts
    take = _ranges_concat(starts, lens)
    cols_s = indices[take]
    vals_s = values[take]
    order = np.argsort(cols_s, kind="stable")
    cols_s, vals_s = cols_s[order], vals_s[order]
    cats = set(int(c) for c in categorical_features)
    n_sample = len(sample_idx)
    # columns with no sampled nonzero are all-zero -> one shared trivial
    # mapper; only columns actually present get a real find() (this is
    # what keeps 1M-column data O(nnz), not O(num_cols x find))
    trivial = BinMapper.find(np.empty(0), n_sample, max_bin, NUMERICAL)
    mappers: List[BinMapper] = [trivial] * num_cols
    present, first = np.unique(cols_s, return_index=True)
    bounds = np.append(first, len(cols_s))
    for k, j in enumerate(present):
        bt = CATEGORICAL if int(j) in cats else NUMERICAL
        mappers[int(j)] = BinMapper.find(
            vals_s[bounds[k]:bounds[k + 1]], n_sample, max_bin, bt
        )
    return mappers


def encode_csr_bins(
    indptr: np.ndarray,
    indices: np.ndarray,
    values: np.ndarray,
    used_map: np.ndarray,
    mappers: List[BinMapper],
) -> SparseBins:
    """Bin-encode CSR entries in place: O(nnz), never densifies.

    Entries in trivial (dropped) columns vanish; remaining columns are
    renumbered to inner feature indices (used_feature_map semantics,
    dataset.h:286-307).
    """
    n = len(indptr) - 1
    inner_of = np.asarray(used_map, dtype=np.int64)
    keep = inner_of[indices] >= 0
    rows_all = np.repeat(np.arange(n, dtype=np.int64), np.diff(indptr))
    rows = rows_all[keep]
    cols = inner_of[indices[keep]].astype(np.int32)
    vals = values[keep]

    f_used = len(mappers)
    dtype = np.uint8 if max(
        (m.num_bin for m in mappers), default=1
    ) <= 256 else np.uint16
    bins = np.empty(len(vals), dtype=dtype)
    # group entries by column once, encode per column vectorized
    order = np.argsort(cols, kind="stable")
    cols_sorted = cols[order]
    bounds = np.searchsorted(cols_sorted, np.arange(f_used + 1))
    for j in range(f_used):
        sl = order[bounds[j]:bounds[j + 1]]
        if len(sl):
            bins[sl] = mappers[j].value_to_bin(vals[sl]).astype(dtype)

    row_lens = np.bincount(rows, minlength=n)
    new_indptr = np.concatenate([[0], np.cumsum(row_lens, dtype=np.int64)])
    # entries are already in row-major order (rows ascending, original
    # column order within a row)
    default_bins = np.asarray([m.default_bin for m in mappers], dtype=dtype)
    return SparseBins(new_indptr, cols, bins, default_bins, (n, f_used))
