"""Distributed data loading: per-rank row partition + parallel bin finding.

Mirrors the reference's distributed loader (src/io/dataset_loader.cpp):

* **Row partition at load** (dataset_loader.cpp:500-605, is_pre_partition
  = false): every rank reads the same file and keeps the rows a shared-
  seed RNG assigns to it — query-granular for ranking data so no query is
  split across ranks.
* **Parallel bin finding** (dataset_loader.cpp:692-755): features are
  sharded across ranks, each rank fits BinMappers for its shard from its
  LOCAL sample, and the mappers are allgathered so every rank ends with
  the full set.  The reference moves serialized BinMapper buffers through
  its Bruck allgather (network.cpp:99-131); here the payload is the same
  idea (BinMapper.to_dict JSON) moved by a pluggable gather function —
  `jax.experimental.multihost_utils.process_allgather` in a real
  multi-host run, identity in tests.

These are host-side (numpy) by design: binning happens once at ingest,
the TPU only ever sees the binned matrix.
"""

from __future__ import annotations

import json
from typing import Callable, List, Optional, Sequence

import numpy as np

from .binner import BinMapper, find_bin_mappers

GatherFn = Callable[[str], List[str]]


# --------------------------------------------------------------- partition
def partition_rows(
    num_rows: int,
    rank: int,
    num_machines: int,
    seed: int,
    query_boundaries: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Row indices this rank keeps (dataset_loader.cpp:500-605).

    Every rank runs the same RNG stream, so the rank assignment is
    consistent without communication.  With ``query_boundaries`` the
    assignment is per-query (query-granular partition for lambdarank,
    dataset_loader.cpp:560-605)."""
    rng = np.random.RandomState(seed)
    if query_boundaries is not None:
        qb = np.asarray(query_boundaries)
        nq = len(qb) - 1
        owner = rng.randint(0, num_machines, size=nq)
        keep_q = np.nonzero(owner == rank)[0]
        return np.concatenate(
            [np.arange(qb[q], qb[q + 1]) for q in keep_q]
        ).astype(np.int64) if len(keep_q) else np.empty(0, np.int64)
    owner = rng.randint(0, num_machines, size=num_rows)
    return np.nonzero(owner == rank)[0].astype(np.int64)


# ------------------------------------------------------------- bin finding
def shard_features(num_features: int, num_machines: int) -> List[np.ndarray]:
    """Contiguous feature shards, one per rank (the reference balances by
    bin count after a first pass, dataset_loader.cpp:697-716; contiguous
    even split is the same comm volume and simpler)."""
    bounds = np.linspace(0, num_features, num_machines + 1).astype(np.int64)
    return [np.arange(bounds[r], bounds[r + 1]) for r in range(num_machines)]


def _identity_gather(payload: str) -> List[str]:
    return [payload]


def _jax_process_gather(payload: str) -> List[str]:
    """Allgather JSON payloads across jax processes (multi-host)."""
    import jax
    from jax.experimental import multihost_utils

    data = np.frombuffer(payload.encode(), dtype=np.uint8)
    # pad to the max length across processes
    n = np.asarray([len(data)], np.int32)
    all_n = multihost_utils.process_allgather(n).reshape(-1)
    maxlen = int(all_n.max())
    padded = np.zeros(maxlen, np.uint8)
    padded[: len(data)] = data
    gathered = multihost_utils.process_allgather(padded)
    return [
        bytes(gathered[r][: int(all_n[r])]).decode()
        for r in range(gathered.shape[0])
    ]


def distributed_find_bin_mappers(
    sample_local: np.ndarray,
    rank: int,
    num_machines: int,
    max_bin: int = 256,
    categorical_features: Sequence[int] = (),
    total_sample_cnt: Optional[int] = None,
    gather_fn: Optional[GatherFn] = None,
) -> List[BinMapper]:
    """Feature-sharded bin finding + mapper allgather
    (dataset_loader.cpp:692-755).

    Each rank fits mappers only for its feature shard (from its local
    sample) and broadcasts them; the returned list covers ALL features on
    every rank.  ``gather_fn(payload) -> [payload_rank0, ...]`` abstracts
    the transport; the default uses jax multihost allgather when more
    than one process is attached, else runs single-rank."""
    F = sample_local.shape[1]
    shards = shard_features(F, num_machines)
    mine = shards[rank]
    cats = set(int(c) for c in categorical_features)

    local = find_bin_mappers(
        sample_local[:, mine] if len(mine) else sample_local[:, :0],
        total_sample_cnt=total_sample_cnt or len(sample_local),
        max_bin=max_bin,
        categorical_features=[i for i, j in enumerate(mine) if int(j) in cats],
    )
    payload = json.dumps(
        {"rank": rank, "mappers": [m.to_dict() for m in local]}
    )

    if gather_fn is None:
        import jax

        gather_fn = (
            _jax_process_gather if jax.process_count() > 1 else _identity_gather
        )
    gathered = [json.loads(s) for s in gather_fn(payload)]
    if len(gathered) == 1 and num_machines == 1:
        return local

    by_rank = {g["rank"]: g["mappers"] for g in gathered}
    if len(by_rank) != num_machines:
        raise RuntimeError(
            f"distributed bin finding expected {num_machines} payloads, "
            f"got ranks {sorted(by_rank)}"
        )
    out: List[Optional[BinMapper]] = [None] * F
    for r in range(num_machines):
        for i, j in enumerate(shards[r]):
            out[int(j)] = BinMapper.from_dict(by_rank[r][i])
    return out  # type: ignore[return-value]
