"""Per-feature value->bin discretization (BinMapper).

Re-expresses the reference bin-finding semantics (src/io/bin.cpp:44-196) in
vectorized numpy:

* numerical features: if the number of distinct sampled values fits in
  ``max_bin``, each distinct value gets its own bin with upper bounds at
  midpoints (bin.cpp:90-99); otherwise greedy equal-frequency binning where
  values whose sample count exceeds the running mean bin size are forced
  into their own bin (bin.cpp:100-153).
* categorical features: categories sorted by descending count, top
  ``max_bin`` kept, the rest mapped to the most frequent bin's... dropped
  to bin of their own absence (reference maps unseen to bin 0 at data-push
  time; bin.cpp:155-186).

Zero values that were elided from the sample (sparse collection) are
re-inserted with their count, as the reference does (bin.cpp:48-85).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

NUMERICAL = 0
CATEGORICAL = 1


class BinMapper:
    """Maps raw feature values to integer bins.

    Attributes
    ----------
    bin_type: NUMERICAL or CATEGORICAL
    num_bin: number of bins actually used (<= max_bin)
    bin_upper_bound: float64[num_bin] upper bound per bin (numerical);
        last entry is +inf (bin.cpp:99,152)
    bin_to_category / category_to_bin: categorical mappings (bin.cpp:173-180)
    is_trivial: single-bin feature, dropped from training (bin.cpp:188-193)
    """

    __slots__ = (
        "bin_type",
        "num_bin",
        "bin_upper_bound",
        "bin_to_category",
        "category_to_bin",
        "is_trivial",
        "sparse_rate",
    )

    def __init__(self):
        self.bin_type = NUMERICAL
        self.num_bin = 1
        self.bin_upper_bound = np.array([np.inf])
        self.bin_to_category: List[int] = []
        self.category_to_bin: Dict[int, int] = {}
        self.is_trivial = True
        self.sparse_rate = 0.0

    # ------------------------------------------------------------------ find
    @staticmethod
    def find(
        sample_values: np.ndarray,
        total_sample_cnt: Optional[int] = None,
        max_bin: int = 256,
        bin_type: int = NUMERICAL,
    ) -> "BinMapper":
        """Learn the discretization from sampled values.

        ``total_sample_cnt`` may exceed ``len(sample_values)``; the gap is
        treated as elided zeros (bin.cpp:48).  NaNs are treated as zeros
        (the reference parser never produces NaN; we are more lenient).
        """
        m = BinMapper()
        m.bin_type = bin_type
        vals = np.asarray(sample_values, dtype=np.float64)
        n_inf = int(np.isinf(vals).sum())
        if n_inf:
            # input hardening: an inf sample would put an inf midpoint
            # into bin_upper_bound and poison every threshold after it.
            # Treat inf like NaN (excluded from bin finding; at encode
            # time it lands in the last/first bin via the clip), counted
            # so a fleet dashboard sees the degradation
            from ..obs import telemetry

            telemetry.count("nonfinite_feature_values", n_inf)
        vals = vals[np.isfinite(vals)]
        if total_sample_cnt is None:
            total_sample_cnt = len(vals)
        zero_cnt = int(total_sample_cnt - len(vals))

        # distinct values + counts, with elided zeros folded in
        if len(vals):
            distinct, counts = np.unique(vals, return_counts=True)
        else:
            distinct, counts = np.array([], dtype=np.float64), np.array([], dtype=np.int64)
        if zero_cnt > 0:
            zi = np.searchsorted(distinct, 0.0)
            if zi < len(distinct) and distinct[zi] == 0.0:
                counts = counts.copy()
                counts[zi] += zero_cnt
            else:
                distinct = np.insert(distinct, zi, 0.0)
                counts = np.insert(counts, zi, zero_cnt)
        counts = counts.astype(np.int64)
        sample_size = int(total_sample_cnt)
        num_values = len(distinct)

        if num_values == 0:
            m.num_bin = 1
            m.bin_upper_bound = np.array([np.inf])
            m.is_trivial = True
            return m

        if bin_type == NUMERICAL:
            if num_values <= max_bin:
                # one bin per distinct value; midpoint upper bounds
                m.num_bin = num_values
                ub = np.empty(num_values, dtype=np.float64)
                ub[:-1] = (distinct[:-1] + distinct[1:]) / 2.0
                ub[-1] = np.inf
                m.bin_upper_bound = ub
                cnt_in_bin0 = int(counts[0])
            else:
                ub, cnt_in_bin0 = _greedy_equal_freq(
                    distinct, counts, sample_size, max_bin
                )
                m.bin_upper_bound = ub
                m.num_bin = len(ub)
        else:
            ivals = distinct.astype(np.int64)
            # merge duplicate ints (floats truncating to same int)
            idistinct, inv = np.unique(ivals, return_inverse=True)
            icounts = np.zeros(len(idistinct), dtype=np.int64)
            np.add.at(icounts, inv, counts)
            # sort by count descending, stable on category id for determinism
            order = np.lexsort((idistinct, -icounts))
            idistinct, icounts = idistinct[order], icounts[order]
            m.num_bin = min(max_bin, len(idistinct))
            kept = idistinct[: m.num_bin]
            m.bin_to_category = [int(c) for c in kept]
            m.category_to_bin = {int(c): i for i, c in enumerate(kept)}
            used_cnt = int(icounts[: m.num_bin].sum())
            cnt_in_bin0 = sample_size - used_cnt + int(icounts[0])

        m.is_trivial = m.num_bin <= 1
        m.sparse_rate = cnt_in_bin0 / max(sample_size, 1)
        return m

    # --------------------------------------------------------------- mapping
    def value_to_bin(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value->bin (reference bin.h:353-375)."""
        values = np.asarray(values, dtype=np.float64)
        if self.bin_type == NUMERICAL:
            # NaN (missing) behaves like 0.0, matching how find() counts it
            values = np.where(np.isnan(values), 0.0, values)
            # bin b holds values <= bin_upper_bound[b]; searchsorted left on
            # upper bounds gives the first bound >= value.
            bins = np.searchsorted(self.bin_upper_bound, values, side="left")
            return np.clip(bins, 0, self.num_bin - 1).astype(np.int32)
        ivals = np.nan_to_num(values, nan=0.0).astype(np.int64)
        out = np.zeros(len(ivals), dtype=np.int32)
        # unseen categories -> bin 0 (reference SparseCategoricalBin pushes
        # only known categories; dense unknown falls to default bin 0)
        if self.category_to_bin:
            cats = np.array(self.bin_to_category, dtype=np.int64)
            sorter = np.argsort(cats)
            pos = np.searchsorted(cats[sorter], ivals)
            pos = np.clip(pos, 0, len(cats) - 1)
            hit = cats[sorter][pos] == ivals
            out = np.where(hit, sorter[pos], 0).astype(np.int32)
        return out

    def bin_to_value(self, bins: np.ndarray) -> np.ndarray:
        """Representative real value per bin, for model text output the
        reference stores the *upper bound* as the threshold (tree.cpp:70)."""
        bins = np.asarray(bins, dtype=np.int64)
        if self.bin_type == NUMERICAL:
            return self.bin_upper_bound[np.clip(bins, 0, self.num_bin - 1)]
        arr = np.array(self.bin_to_category, dtype=np.float64)
        return arr[np.clip(bins, 0, self.num_bin - 1)]

    @property
    def default_bin(self) -> int:
        """Bin of the value 0.0 (bin.h:150-160), the implicit bin for
        sparse/elided entries."""
        if self.bin_type == NUMERICAL:
            return int(self.value_to_bin(np.array([0.0]))[0])
        return int(self.category_to_bin.get(0, 0))

    # --------------------------------------------------------- serialization
    def to_dict(self) -> dict:
        return {
            "bin_type": int(self.bin_type),
            "num_bin": int(self.num_bin),
            "bin_upper_bound": [float(x) for x in np.asarray(self.bin_upper_bound)],
            "bin_to_category": list(self.bin_to_category),
            "is_trivial": bool(self.is_trivial),
            "sparse_rate": float(self.sparse_rate),
        }

    @staticmethod
    def from_dict(d: dict) -> "BinMapper":
        m = BinMapper()
        m.bin_type = int(d["bin_type"])
        m.num_bin = int(d["num_bin"])
        m.bin_upper_bound = np.asarray(d["bin_upper_bound"], dtype=np.float64)
        m.bin_to_category = [int(c) for c in d.get("bin_to_category", [])]
        m.category_to_bin = {c: i for i, c in enumerate(m.bin_to_category)}
        m.is_trivial = bool(d["is_trivial"])
        m.sparse_rate = float(d.get("sparse_rate", 0.0))
        return m


def _greedy_equal_freq(
    distinct: np.ndarray, counts: np.ndarray, sample_size: int, max_bin: int
):
    """Greedy equal-frequency binning with big-count isolation
    (bin.cpp:100-153) — closure-jumping implementation.

    Semantics of the reference's value-by-value loop (kept verbatim as
    ``_greedy_equal_freq_spec`` and pinned equivalent by
    tests/test_binner.py): values with count >= mean bin size get their
    own bin; remaining values pack left-to-right until the running mean
    bin size is reached, with a half-mean early closure just before a
    big value.  Instead of visiting every distinct value, each bin
    closure is found directly — the mean-size criterion by a
    ``searchsorted`` on the count prefix sums, the big-value criteria
    from the precomputed big positions — so the Python loop runs
    O(max_bin) times, not O(num_distinct): ~100x faster on 50k-distinct
    features.  Returns (bin_upper_bound, cnt_in_bin0).
    """
    num_values = len(distinct)
    mean_bin_size = sample_size / float(max_bin)
    is_big = counts >= mean_bin_size
    rest_bin_cnt = max_bin - int(is_big.sum())
    rest_sample_cnt = int(sample_size - counts[is_big].sum())
    mean_bin_size = rest_sample_cnt / float(max(rest_bin_cnt, 1))

    P = np.cumsum(counts, dtype=np.int64)  # inclusive prefix sums
    Ps = np.cumsum(np.where(is_big, 0, counts), dtype=np.int64)  # small-only
    big_pos = np.flatnonzero(is_big)

    upper_idx: List[int] = []  # closure index per bin
    cnt_in_bin0 = 0
    i0 = 0  # first value of the open bin
    bi = 0  # next big position pointer
    while i0 < num_values - 1:
        base = P[i0 - 1] if i0 > 0 else 0
        # candidate 1: a big value at or after i0 closes its bin at itself
        while bi < len(big_pos) and big_pos[bi] < i0:
            bi += 1
        j_big = big_pos[bi] if bi < len(big_pos) else num_values
        # candidate 2: accumulated count reaches the running mean.  The
        # spec checks AFTER consuming a value, so a closure is never
        # before i0 even when the running mean hits zero (all-big tails)
        j_mean = max(i0, int(np.searchsorted(P, base + mean_bin_size, side="left")))
        # candidate 3: the value before a big value, once >= half-mean —
        # only worth probing when a big value is ahead AND could close
        # earlier than the mean criterion
        j_pre_big = num_values
        if j_big - 1 < j_mean:
            half = max(1.0, mean_bin_size * 0.5)
            j_half = max(i0, int(np.searchsorted(P, base + half, side="left")))
            if j_big - 1 >= j_half:
                j_pre_big = j_big - 1
        j = min(j_big, j_mean, j_pre_big)
        if j >= num_values - 1:
            break  # loop ends before the last value (it joins the open bin)
        upper_idx.append(j)
        if len(upper_idx) == 1:
            cnt_in_bin0 = int(P[j] - base)
        if len(upper_idx) >= max_bin - 1:
            break
        if not is_big[j]:
            # the running mean updates ONLY on small-value closures
            # (bin.cpp:141-144); remaining small mass counts down from the
            # spec's seed (sample_size - big mass), which may exceed
            # counts.sum() when the caller folds elided rows elsewhere
            rest_bin_cnt -= 1
            mean_bin_size = float(rest_sample_cnt - Ps[j]) / float(
                max(rest_bin_cnt, 1)
            )
        i0 = j + 1

    bin_cnt = len(upper_idx) + 1
    ub = np.empty(bin_cnt, dtype=np.float64)
    for b, j in enumerate(upper_idx):
        ub[b] = (float(distinct[j]) + float(distinct[j + 1])) / 2.0
    ub[bin_cnt - 1] = np.inf
    return ub, cnt_in_bin0


def _greedy_equal_freq_spec(
    distinct: np.ndarray, counts: np.ndarray, sample_size: int, max_bin: int
):
    """The reference's value-by-value greedy loop (bin.cpp:100-153),
    kept as the executable specification for _greedy_equal_freq."""
    num_values = len(distinct)
    mean_bin_size = sample_size / float(max_bin)
    is_big = counts >= mean_bin_size
    rest_bin_cnt = max_bin - int(is_big.sum())
    rest_sample_cnt = int(sample_size - counts[is_big].sum())
    mean_bin_size = rest_sample_cnt / float(max(rest_bin_cnt, 1))

    upper_bounds: List[float] = []
    lower_bounds: List[float] = [float(distinct[0])]
    cnt_in_bin0 = 0
    cur_cnt_inbin = 0
    bin_cnt = 0
    for i in range(num_values - 1):
        if not is_big[i]:
            rest_sample_cnt -= int(counts[i])
        cur_cnt_inbin += int(counts[i])
        # close the current bin? (bin.cpp:127-128)
        if (
            is_big[i]
            or cur_cnt_inbin >= mean_bin_size
            or (is_big[i + 1] and cur_cnt_inbin >= max(1.0, mean_bin_size * 0.5))
        ):
            upper_bounds.append(float(distinct[i]))
            if bin_cnt == 0:
                cnt_in_bin0 = cur_cnt_inbin
            bin_cnt += 1
            lower_bounds.append(float(distinct[i + 1]))
            if bin_cnt >= max_bin - 1:
                break
            cur_cnt_inbin = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / float(max(rest_bin_cnt, 1))
    bin_cnt += 1
    ub = np.empty(bin_cnt, dtype=np.float64)
    for i in range(bin_cnt - 1):
        ub[i] = (upper_bounds[i] + lower_bounds[i + 1]) / 2.0
    ub[bin_cnt - 1] = np.inf
    return ub, cnt_in_bin0


def find_bin_mappers(
    sample: np.ndarray,
    total_sample_cnt: Optional[int] = None,
    max_bin: int = 256,
    categorical_features: Sequence[int] = (),
) -> List[BinMapper]:
    """Find a BinMapper per column of a sampled row-matrix ``sample``."""
    cats = set(int(c) for c in categorical_features)
    mappers = []
    n = sample.shape[0] if total_sample_cnt is None else total_sample_cnt
    for j in range(sample.shape[1]):
        bt = CATEGORICAL if j in cats else NUMERICAL
        mappers.append(BinMapper.find(sample[:, j], n, max_bin, bt))
    return mappers
