"""Logging (reference include/LightGBM/utils/log.h:26-98).

Four levels with a process-wide threshold mapped from the ``verbose``
config (config.cpp verbosity mapping): verbose<=0 -> Warning+,
verbose==1 -> Info+, verbose>=2 -> Debug+.  ``Log.fatal`` raises
:class:`LightGBMError` like the reference's throwing Log::Fatal
(log.h:65-78, caught in main.cpp:9-22).
"""

from __future__ import annotations

import sys

DEBUG, INFO, WARNING, FATAL = 0, 1, 2, 3


class Log:
    _level = INFO

    @classmethod
    def reset_log_level(cls, verbose: int) -> None:
        cls._level = WARNING if verbose <= 0 else (INFO if verbose == 1 else DEBUG)

    @classmethod
    def debug(cls, msg: str) -> None:
        if cls._level <= DEBUG:
            print(f"[LightGBM] [Debug] {msg}", flush=True)

    @classmethod
    def info(cls, msg: str) -> None:
        if cls._level <= INFO:
            print(f"[LightGBM] [Info] {msg}", flush=True)

    @classmethod
    def warning(cls, msg: str) -> None:
        print(f"[LightGBM] [Warning] {msg}", file=sys.stderr, flush=True)

    @classmethod
    def fatal(cls, msg: str) -> None:
        from .basic import LightGBMError

        print(f"[LightGBM] [Fatal] {msg}", file=sys.stderr, flush=True)
        raise LightGBMError(msg)
