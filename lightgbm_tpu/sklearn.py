"""Scikit-learn API wrappers.

Mirrors the reference python-package/lightgbm/sklearn.py: ``LGBMModel``
base (sklearn.py:134-460) with fobj/feval adapters converting sklearn
``(y_true, y_pred)`` signatures to the internal ``(preds, dataset)``
protocol (sklearn.py:28-133), plus ``LGBMRegressor`` / ``LGBMClassifier``
(label encoding, predict_proba) / ``LGBMRanker`` (sklearn.py:461-642).
Works with sklearn's clone/GridSearchCV since get_params/set_params follow
the estimator contract.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset, LightGBMError
from .engine import train as _train

# soft sklearn dependency (reference sklearn.py:13-25): inherit the real
# base classes when available so clone/GridSearchCV/tags work
try:
    from sklearn.base import (
        BaseEstimator as _SKLBase,
        ClassifierMixin as _SKLClassifierMixin,
        RegressorMixin as _SKLRegressorMixin,
    )
except ImportError:  # pragma: no cover
    _SKLBase = object

    class _SKLClassifierMixin:  # type: ignore[no-redef]
        pass

    class _SKLRegressorMixin:  # type: ignore[no-redef]
        pass


class _ObjectiveFunctionWrapper:
    """sklearn fobj(y_true, y_pred [, weight|group]) -> internal
    fobj(preds, dataset) (sklearn.py:28-87)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            grad, hess = self.func(labels, preds)
        elif argc == 3:
            grad, hess = self.func(labels, preds, dataset.get_field("group"))
        else:
            raise TypeError(f"Self-defined objective should have 2 or 3 arguments, got {argc}")
        weight = dataset.get_weight()
        if weight is not None:
            grad = np.asarray(grad) * weight
            hess = np.asarray(hess) * weight
        return grad, hess


class _EvalFunctionWrapper:
    """sklearn feval(y_true, y_pred [, weight [, group]]) -> internal
    feval(preds, dataset) (sklearn.py:90-133)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            return self.func(labels, preds)
        if argc == 3:
            return self.func(labels, preds, dataset.get_weight())
        if argc == 4:
            return self.func(
                labels, preds, dataset.get_weight(), dataset.get_field("group")
            )
        raise TypeError(f"Self-defined eval function should have 2 to 4 arguments, got {argc}")


class LGBMModel(_SKLBase):
    """Base estimator (sklearn.py:134-460)."""

    def __init__(
        self,
        boosting_type: str = "gbdt",
        num_leaves: int = 31,
        max_depth: int = -1,
        learning_rate: float = 0.1,
        n_estimators: int = 10,
        max_bin: int = 255,
        subsample_for_bin: int = 50000,
        objective: str = "regression",
        min_split_gain: float = 0.0,
        min_child_weight: float = 5.0,
        min_child_samples: int = 10,
        subsample: float = 1.0,
        subsample_freq: int = 1,
        colsample_bytree: float = 1.0,
        reg_alpha: float = 0.0,
        reg_lambda: float = 0.0,
        scale_pos_weight: float = 1.0,
        is_unbalance: bool = False,
        seed: int = 0,
        nthread: int = -1,
        silent: bool = True,
        sigmoid: float = 1.0,
        drop_rate: float = 0.1,
        max_drop: int = 50,
        skip_drop: float = 0.5,
        uniform_drop: bool = False,
        xgboost_dart_mode: bool = False,
    ):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.max_bin = max_bin
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.scale_pos_weight = scale_pos_weight
        self.is_unbalance = is_unbalance
        self.seed = seed
        self.nthread = nthread
        self.silent = silent
        self.sigmoid = sigmoid
        self.drop_rate = drop_rate
        self.max_drop = max_drop
        self.skip_drop = skip_drop
        self.uniform_drop = uniform_drop
        self.xgboost_dart_mode = xgboost_dart_mode
        self._Booster: Optional[Booster] = None
        self.best_iteration = -1
        self.evals_result_: Dict = {}

    # --------------------------------------------------- sklearn estimator
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        import inspect

        # subclasses declare (objective=..., **kwargs); enumerate the base
        # class's explicit parameter list instead
        sig = inspect.signature(LGBMModel.__init__)
        return {
            name: getattr(self, name)
            for name, p in sig.parameters.items()
            if name != "self" and p.kind is not inspect.Parameter.VAR_KEYWORD
        }

    def set_params(self, **params) -> "LGBMModel":
        for k, v in params.items():
            setattr(self, k, v)
        return self

    def _to_inner_params(self) -> Dict[str, Any]:
        """Map sklearn names to framework params (sklearn.py:257-292)."""
        p = {
            "boosting_type": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "max_bin": self.max_bin,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "objective": self.objective if not callable(self.objective) else "none",
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "scale_pos_weight": self.scale_pos_weight,
            "is_unbalance": self.is_unbalance,
            "seed": self.seed,
            "sigmoid": self.sigmoid,
            "verbose": 0 if self.silent else 1,
        }
        if self.boosting_type == "dart":
            p.update(
                drop_rate=self.drop_rate, max_drop=self.max_drop,
                skip_drop=self.skip_drop, uniform_drop=self.uniform_drop,
                xgboost_dart_mode=self.xgboost_dart_mode,
            )
        return p

    def fit(
        self,
        X,
        y,
        sample_weight=None,
        init_score=None,
        group=None,
        eval_set=None,
        eval_sample_weight=None,
        eval_init_score=None,
        eval_group=None,
        eval_metric=None,
        early_stopping_rounds=None,
        verbose: bool = False,
        feature_name=None,
        categorical_feature=None,
        callbacks=None,
        _extra_params=None,
    ) -> "LGBMModel":
        params = self._to_inner_params()
        if _extra_params:
            params.update(_extra_params)
        if eval_metric is not None and not callable(eval_metric):
            params["metric"] = eval_metric
        fobj = _ObjectiveFunctionWrapper(self.objective) if callable(self.objective) else None
        feval = _EvalFunctionWrapper(eval_metric) if callable(eval_metric) else None

        train_set = Dataset(
            X, label=y, weight=sample_weight, group=group, init_score=init_score,
            params=params, feature_name=feature_name,
            categorical_feature=categorical_feature,
        )
        valid_sets = []
        valid_names = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vw = eval_sample_weight[i] if eval_sample_weight else None
                vg = eval_group[i] if eval_group else None
                vi = eval_init_score[i] if eval_init_score else None
                valid_sets.append(train_set.create_valid(
                    vx, label=vy, weight=vw, group=vg, init_score=vi))
                valid_names.append(f"valid_{i}")

        self.evals_result_ = {}
        self._Booster = _train(
            params,
            train_set,
            num_boost_round=self.n_estimators,
            valid_sets=valid_sets,
            valid_names=valid_names,
            fobj=fobj,
            feval=feval,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=self.evals_result_,
            verbose_eval=verbose,
            callbacks=callbacks,
        )
        self.best_iteration = self._Booster.best_iteration
        return self

    def predict(self, X, raw_score: bool = False, num_iteration: int = -1):
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted, call fit before predict")
        return self._Booster.predict(X, raw_score=raw_score, num_iteration=num_iteration)

    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise LightGBMError("No booster found, call fit first")
        return self._Booster

    @property
    def feature_importances_(self) -> np.ndarray:
        return self.booster_.feature_importance()

    @property
    def feature_importance_(self) -> np.ndarray:
        """Normalized split-count importances (reference
        sklearn.py:448-451)."""
        arr = self.booster_.feature_importance().astype(np.float32)
        total = arr.sum()
        return arr / total if total else arr

    def booster(self) -> Booster:
        """Deprecated accessor kept for reference compatibility
        (sklearn.py:454-456); use the ``booster_`` attribute."""
        import warnings

        warnings.warn("Use attribute booster_ instead.", DeprecationWarning)
        return self.booster_

    def feature_importance(self) -> np.ndarray:
        """Deprecated accessor kept for reference compatibility
        (sklearn.py:458-460); use ``feature_importance_``."""
        import warnings

        warnings.warn(
            "Use attribute feature_importance_ instead.", DeprecationWarning
        )
        return self.feature_importance_

    def apply(self, X, num_iteration: int = -1):
        """Per-row leaf indices (sklearn.py predict with pred_leaf)."""
        return self.booster_.predict(X, pred_leaf=True, num_iteration=num_iteration)


class LGBMRegressor(_SKLRegressorMixin, LGBMModel):
    def __init__(self, objective: str = "regression", **kwargs):
        super().__init__(objective=objective, **kwargs)

    def fit(self, X, y, **kwargs):  # noqa: D102
        return super().fit(X, y, **kwargs)


class LGBMClassifier(_SKLClassifierMixin, LGBMModel):
    def __init__(self, objective: str = "binary", **kwargs):
        super().__init__(objective=objective, **kwargs)

    def fit(self, X, y, **kwargs):
        y = np.asarray(y)
        self.classes_, y_enc = np.unique(y, return_inverse=True)
        self.n_classes_ = len(self.classes_)
        extra = {}
        if self.n_classes_ > 2 and not callable(self.objective):
            # leave self.objective untouched (sklearn params are immutable
            # across fits); route the override through fit-time params
            extra = {"objective": "multiclass", "num_class": self.n_classes_}
        # eval_set labels must go through the same encoding as y
        eval_set = kwargs.get("eval_set")
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            encoded = []
            for vx, vy in eval_set:
                vy = np.asarray(vy)
                vy_enc = np.searchsorted(self.classes_, vy)
                in_range = vy_enc < len(self.classes_)
                if not (np.all(in_range) and np.all(self.classes_[np.where(in_range, vy_enc, 0)] == vy)):
                    raise LightGBMError(
                        "eval_set contains labels unseen in training data"
                    )
                encoded.append((vx, vy_enc.astype(np.float64)))
            kwargs["eval_set"] = encoded
        super().fit(X, y_enc.astype(np.float64), _extra_params=extra, **kwargs)
        return self

    def predict(self, X, raw_score: bool = False, num_iteration: int = -1):
        if raw_score:
            return super().predict(X, raw_score=True, num_iteration=num_iteration)
        proba = self.predict_proba(X, num_iteration=num_iteration)
        return self.classes_[np.argmax(proba, axis=1)]

    def predict_proba(self, X, num_iteration: int = -1) -> np.ndarray:
        out = super().predict(X, num_iteration=num_iteration)
        if out.ndim == 1:  # binary: prob of positive class
            return np.column_stack([1.0 - out, out])
        return out


class LGBMRanker(LGBMModel):
    def __init__(self, objective: str = "lambdarank", **kwargs):
        super().__init__(objective=objective, **kwargs)

    def fit(self, X, y, group=None, **kwargs):
        if group is None:
            raise LightGBMError("Should set group for ranking task")
        if "eval_set" in kwargs and kwargs["eval_set"] is not None:
            if kwargs.get("eval_group") is None:
                raise LightGBMError("Eval_group cannot be None when eval_set is not None")
        return super().fit(X, y, group=group, **kwargs)
