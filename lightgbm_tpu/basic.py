"""User-facing ``Dataset`` and ``Booster``.

Mirrors the reference python package's basic.py (python-package/lightgbm/
basic.py:930 ``Dataset``, basic.py:1276 ``Booster``) — same lazy-construction
semantics, same method surface — but with no FFI: the "C API layer" the
reference reaches through ctypes (src/c_api.cpp) is here the in-process
TPU framework itself (BinnedDataset + GBDT/DART on JAX).
"""

from __future__ import annotations

import copy
import json
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from .config import Config, key_alias_transform
from .io.dataset import BinnedDataset
from .io.metadata import Metadata
from .metrics import Metric, create_metrics
from .models.dart import create_boosting
from .models.gbdt import GBDT
from .objectives import create_objective


class LightGBMError(Exception):
    """Error raised by the framework (reference basic.py:45)."""


def _to_2d_float(data) -> np.ndarray:
    arr = np.asarray(data, dtype=np.float64)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    if arr.ndim != 2:
        raise LightGBMError("data must be 2 dimensional")
    return arr


def _densify(data) -> np.ndarray:
    """Accept numpy / pandas / scipy-sparse row data (basic.py:472-927)."""
    if hasattr(data, "toarray"):  # scipy CSR/CSC
        return _to_2d_float(data.toarray())
    if hasattr(data, "values") and not isinstance(data, np.ndarray):  # pandas
        return _to_2d_float(np.asarray(data.values, dtype=np.float64))
    return _to_2d_float(data)


class Dataset:
    """Dataset for training/validation.

    Like the reference ``Dataset`` (basic.py:930-1274): parameters
    (max_bin, categorical_feature, reference, ...) are collected eagerly
    but binning happens lazily on first use, so a validation set can be
    aligned to its training set's bin mappers.
    """

    def __init__(
        self,
        data,
        label=None,
        max_bin: int = 256,
        reference: Optional["Dataset"] = None,
        weight=None,
        group=None,
        init_score=None,
        feature_name: Optional[List[str]] = None,
        categorical_feature: Optional[Sequence[int]] = None,
        params: Optional[Dict[str, Any]] = None,
        free_raw_data: bool = False,
    ):
        self.data = data
        self.label = label
        self.max_bin = int(max_bin)
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = list(categorical_feature or [])
        self.params = dict(params or {})
        self.free_raw_data = free_raw_data
        self._inner: Optional[BinnedDataset] = None

    # ------------------------------------------------------------ construct
    def construct(self) -> BinnedDataset:
        """Build the binned dataset lazily (basic.py:1014-1036)."""
        if self._inner is not None:
            return self._inner
        params = key_alias_transform(dict(self.params))
        params.setdefault("max_bin", self.max_bin)
        cfg = Config.from_dict(params)
        cats = self.categorical_feature
        if any(isinstance(c, str) for c in cats):
            # column-name entries resolve against feature_name
            # (reference basic.py categorical_feature by str)
            if not self.feature_name:
                raise LightGBMError(
                    "categorical_feature given by name requires feature_name"
                )
            try:
                cats = [
                    c if not isinstance(c, str) else self.feature_name.index(c)
                    for c in cats
                ]
            except ValueError as e:
                raise LightGBMError(
                    f"categorical_feature name not in feature_name: {e}"
                ) from None
        meta_kwargs = dict(
            label=None if self.label is None else np.asarray(self.label),
            weights=self.weight,
            init_score=self.init_score,
        )
        meta = Metadata(**meta_kwargs)
        if self.group is not None:
            meta.set_field("group", np.asarray(self.group))

        ref_inner = self.reference.construct() if self.reference is not None else None
        if isinstance(self.data, str):
            self._inner = BinnedDataset.from_file(
                self.data, config=cfg, reference=ref_inner,
                categorical_features=cats or None,
            )
            if meta.label is not None:
                self._inner.metadata.set_field("label", meta.label)
            for field in ("weight", "init_score"):
                v = meta.get_field(field)
                if v is not None:
                    self._inner.metadata.set_field(field, v)
            if meta.query_boundaries is not None:
                self._inner.metadata.query_boundaries = meta.query_boundaries
                self._inner.metadata._finish()
        elif hasattr(self.data, "tocsr"):  # scipy sparse: O(nnz) ingest,
            # never densified to f64 (reference SparseBin path,
            # sparse_bin.hpp; round 1 called .toarray() here)
            if meta.label is None:
                raise LightGBMError("label should not be None for training data")
            csr = self.data.tocsr()
            indptr = np.asarray(csr.indptr, dtype=np.int64)
            indices = np.asarray(csr.indices, dtype=np.int64)
            values = np.asarray(csr.data, dtype=np.float64)
            if ref_inner is not None:
                self._inner = ref_inner.align_with_csr(
                    indptr, indices, values, meta
                )
            else:
                self._inner = BinnedDataset.from_csr(
                    indptr, indices, values, csr.shape[1], meta, config=cfg,
                    categorical_features=cats,
                    feature_names=self.feature_name,
                )
        else:
            X = _densify(self.data)
            if meta.label is None:
                raise LightGBMError("label should not be None for training data")
            if ref_inner is not None:
                self._inner = ref_inner.align_with(X, meta)
            else:
                self._inner = BinnedDataset.from_matrix(
                    X,
                    meta,
                    config=cfg,
                    categorical_features=cats,
                    feature_names=self.feature_name,
                )
        if self.free_raw_data:
            self.data = None
        return self._inner

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        """A validation set aligned to this dataset (basic.py:1074-1097)."""
        return Dataset(
            data, label=label, reference=self, weight=weight, group=group,
            init_score=init_score, params=params or self.params,
        )

    def subset(self, used_indices, params=None) -> "Dataset":
        """Row subset sharing this dataset's bin mappers (basic.py:1099)."""
        inner = self.construct().subset(np.asarray(used_indices))
        out = Dataset.__new__(Dataset)
        out.__dict__.update(
            data=None, label=None, max_bin=self.max_bin, reference=self,
            weight=None, group=None, init_score=None, feature_name=self.feature_name,
            categorical_feature=self.categorical_feature,
            params=dict(params or self.params), free_raw_data=True, _inner=inner,
        )
        return out

    def save_binary(self, filename: str) -> None:
        self.construct().save_binary(filename)

    # -------------------------------------------------------------- fields
    def set_field(self, field_name: str, data) -> None:
        if self._inner is not None:
            self._inner.metadata.set_field(field_name, data)
        if field_name == "label":
            self.label = data
        elif field_name == "weight":
            self.weight = data
        elif field_name in ("group", "query"):
            self.group = data
        elif field_name == "init_score":
            self.init_score = data

    def get_field(self, field_name: str):
        if self._inner is not None:
            return self._inner.metadata.get_field(field_name)
        return {
            "label": self.label, "weight": self.weight,
            "group": self.group, "query": self.group,
            "init_score": self.init_score,
        }.get(field_name)

    set_label = lambda self, label: self.set_field("label", label)
    set_weight = lambda self, weight: self.set_field("weight", weight)
    set_group = lambda self, group: self.set_field("group", group)
    set_init_score = lambda self, s: self.set_field("init_score", s)

    def get_group(self):
        """Per-query group sizes (reference basic.py get_group =
        get_field('group'))."""
        g = self.get_field("group")
        return None if g is None else np.asarray(g)

    def _reset_or_refuse(self, what: str) -> None:
        """Binning-input mutation after construction: rebin lazily when
        the raw data is still held (reference basic.py drops its inner
        dataset), refuse only once the raw data was freed."""
        if self._inner is None:
            return
        if self.data is not None:
            self._inner = None
        else:
            raise LightGBMError(
                f"cannot change {what} after construction once raw data "
                "was freed; create a new Dataset"
            )

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        """Declare categorical columns by index or name, or 'auto'
        (reference basic.py:1135-1147)."""
        if isinstance(categorical_feature, str):
            if categorical_feature != "auto":
                raise LightGBMError(
                    "categorical_feature must be a list of int/str or 'auto'"
                )
            cats = []
        else:
            cats = list(categorical_feature or [])
        if cats != self.categorical_feature:
            self._reset_or_refuse("categorical_feature")
        self.categorical_feature = cats
        return self

    def set_feature_name(self, feature_name) -> "Dataset":
        """Column names (reference basic.py set_feature_name)."""
        names = list(feature_name) if feature_name is not None else None
        if names is not None:
            expected = None
            if self._inner is not None:
                expected = self._inner.num_total_features
            elif hasattr(self.data, "shape") and len(
                getattr(self.data, "shape", ())
            ) == 2:
                expected = self.data.shape[1]
            if expected is not None and len(names) != expected:
                raise LightGBMError(
                    f"expected {expected} feature names, got {len(names)}"
                )
            if self._inner is not None:
                self._inner.feature_names = names
        self.feature_name = names
        return self

    def set_reference(self, reference: "Dataset") -> "Dataset":
        """Align this dataset's binning to another dataset's bin mappers
        (reference basic.py set_reference)."""
        if reference is not self.reference:
            self._reset_or_refuse("reference")
        self.reference = reference
        return self

    def get_label(self):
        return self.get_field("label")

    def get_weight(self):
        return self.get_field("weight")

    def get_init_score(self):
        return self.get_field("init_score")

    def num_data(self) -> int:
        return self.construct().num_data

    def num_feature(self) -> int:
        return self.construct().num_total_features


class Booster:
    """The boosting model (reference basic.py:1276-1819).

    Construct with either ``train_set`` (training mode), ``model_file``
    (prediction mode), or ``model_str``.
    """

    def __init__(
        self,
        params: Optional[Dict[str, Any]] = None,
        train_set: Optional[Dataset] = None,
        model_file: Optional[str] = None,
        model_str: Optional[str] = None,
    ):
        self.params = dict(params or {})
        self.best_iteration = -1
        self._train_dataset: Optional[Dataset] = None
        self.name_valid_sets: List[str] = []
        self.train_data_name = "training"
        self._attr: Dict[str, str] = {}
        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise LightGBMError("Training data should be Dataset instance")
            cfg = Config.from_dict(self.params)
            inner_train = train_set.construct()
            objective = None
            if cfg.objective != "none":
                objective = create_objective(cfg, inner_train.metadata, inner_train.num_data)
            self._gbdt = create_boosting(cfg, inner_train, objective)
            self.config = cfg
            self._train_dataset = train_set
            if cfg.input_model:
                init = Booster(model_file=cfg.input_model)
                self._gbdt.merge_from(init._gbdt, prepend=True)
        elif model_file is not None:
            with open(model_file, "r") as fh:
                model_str = fh.read()
            self._init_from_string(model_str)
        elif model_str is not None:
            self._init_from_string(model_str)
        else:
            raise LightGBMError(
                "Booster needs at least one of train_set, model_file, model_str"
            )

    def _init_from_string(self, model_str: str) -> None:
        cfg = Config.from_dict(self.params)
        first = model_str.lstrip().splitlines()[0].strip()
        # model-file type sniffing (boosting.cpp:7-16)
        if first == "dart":
            from .models.dart import DART

            self._gbdt = DART(cfg)
        else:
            self._gbdt = GBDT(cfg)
        self._gbdt.load_model_from_string(model_str)
        self.config = cfg

    # ----------------------------------------------------------- attributes
    def attr(self, key: str) -> Optional[str]:
        """Get a string attribute (reference basic.py attr)."""
        return self._attr.get(key)

    def set_attr(self, **kwargs) -> "Booster":
        """Set string attributes; None deletes (reference basic.py
        set_attr)."""
        for key, value in kwargs.items():
            if value is None:
                self._attr.pop(key, None)
            else:
                if not isinstance(value, str):
                    # ValueError for reference exception compatibility
                    # (reference basic.py set_attr)
                    raise ValueError("Set attr only accepts strings")
                self._attr[key] = value
        return self

    def set_train_data_name(self, name: str) -> "Booster":
        """Name used for the training set in eval output (reference
        basic.py set_train_data_name)."""
        self.train_data_name = name
        return self

    # ------------------------------------------------------------- training
    def add_valid(self, data: Dataset, name: str) -> None:
        """basic.py:1388 / LGBM_BoosterAddValidData."""
        if not isinstance(data, Dataset):
            raise LightGBMError("Validation data should be Dataset instance")
        self._gbdt.add_valid_dataset(data.construct(), name)
        self.name_valid_sets.append(name)

    def finish_lagged_stop(self) -> None:
        """Drain the lagged stop check after the last update() call
        (no-op unless LGBM_TPU_STOP_LAG is set) — see GBDT."""
        self._gbdt.finish_lagged_stop()

    def update(self, train_set: Optional[Dataset] = None, fobj: Optional[Callable] = None) -> bool:
        """One boosting iteration; returns True if no further training is
        possible (basic.py:1431-1501)."""
        if train_set is not None and train_set is not self._train_dataset:
            self._reset_train_data(train_set)
        if fobj is None:
            return self._gbdt.train_one_iter()
        grad, hess = fobj(self.__inner_predict_flat(0), self._train_dataset)
        grad = np.asarray(grad, np.float32)
        hess = np.asarray(hess, np.float32)
        n = self._gbdt.num_data * self._gbdt.num_class
        if len(grad) != n or len(hess) != n:
            raise LightGBMError(
                f"Lengths of gradient({len(grad)}) and hessian({len(hess)}) "
                f"don't match training rows x classes ({n})"
            )
        return self._gbdt.train_one_iter(grad, hess)

    def _reset_train_data(self, train_set: Dataset) -> None:
        """LGBM_BoosterResetTrainingData semantics, shared by update()'s
        train_set branch and the C API shim."""
        inner = train_set.construct()
        obj = create_objective(self.config, inner.metadata, inner.num_data) \
            if self.config.objective != "none" else None
        self._gbdt.reset_training_data(inner, obj)
        self._train_dataset = train_set

    def rollback_one_iter(self) -> None:
        self._gbdt.rollback_one_iter()

    def reset_parameter(self, params: Dict[str, Any]) -> None:
        """Subset of parameters resettable mid-training (learning_rate et al;
        reference LGBM_BoosterResetParameter path)."""
        params = key_alias_transform(dict(params))
        for k, v in params.items():
            if hasattr(self.config, k):
                setattr(self.config, k, type(getattr(self.config, k))(v))
        if "learning_rate" in params:
            self._gbdt.learning_rate = float(params["learning_rate"])
        self.params.update(params)

    # ----------------------------------------------------------------- eval
    def __inner_predict_flat(self, data_idx: int) -> np.ndarray:
        s = self._gbdt.predict_at(data_idx)  # [K, n]
        return s.reshape(-1)  # class-major flatten, matching the reference

    def eval(self, data: Union[int, Dataset], name: str, feval=None):
        """Evaluate on train (0) / added valid sets; returns the reference's
        (data_name, eval_name, result, is_higher_better) tuples."""
        if isinstance(data, int):
            data_idx = data
        else:
            if data is self._train_dataset:
                data_idx = 0
            else:
                inner = data.construct()
                data_idx = 1 + next(
                    i for i, vs in enumerate(self._gbdt.valid_sets) if vs is inner
                )
        return self.__eval_at(data_idx, name, feval)

    def eval_train(self, feval=None):
        return self.__eval_at(0, self.train_data_name, feval)

    def eval_valid(self, feval=None):
        out = []
        for i, name in enumerate(self.name_valid_sets):
            out.extend(self.__eval_at(i + 1, name, feval))
        return out

    def __eval_at(self, data_idx: int, name: str, feval=None):
        gb = self._gbdt
        metrics = gb.train_metrics if data_idx == 0 else gb.valid_metrics[data_idx - 1]
        scores = gb.predict_at(data_idx)
        s = scores if gb.num_class > 1 else scores[0]
        out = []
        for m in metrics:
            if hasattr(m, "eval_multi"):
                for k, v in zip(m.eval_at, m.eval_multi(s)):
                    out.append((name, f"{m.name}@{k}", v, m.bigger_is_better))
            else:
                out.append((name, m.name, m.eval(s), m.bigger_is_better))
        if feval is not None:
            ds = self._train_dataset if data_idx == 0 else _DatasetView(
                gb.valid_sets[data_idx - 1]
            )
            ret = feval(scores.reshape(-1), ds)
            if ret is not None:
                if isinstance(ret, list):
                    for n_, v_, b_ in ret:
                        out.append((name, n_, v_, b_))
                else:
                    n_, v_, b_ = ret
                    out.append((name, n_, v_, b_))
        return out

    # -------------------------------------------------------------- predict
    def predict(
        self,
        data,
        num_iteration: int = -1,
        raw_score: bool = False,
        pred_leaf: bool = False,
        data_has_header: bool = False,
        is_reshape: bool = True,
    ):
        """Prediction on raw (unbinned) features; ``data`` may be a matrix
        or a text file path (basic.py:259-448 semantics)."""
        if self.best_iteration > 0 and num_iteration <= 0:
            num_iteration = self.best_iteration
        if isinstance(data, str):
            from .io.parser import parse_file

            # STRICT on the prediction path regardless of any training
            # config: lenient parsing skips rows, and a skipped row
            # silently shifts every later prediction onto the wrong
            # input line — raising (the pre-hardening behavior) is the
            # only row-alignment-safe response here
            raw, _ = parse_file(data, has_header=data_has_header,
                                strict=True)
            label_idx = self._gbdt.label_idx
            if raw.shape[1] > self._gbdt.max_feature_idx + 1:
                data = np.delete(raw, label_idx, axis=1)
            else:
                data = raw
        if hasattr(data, "tocsr"):
            # sparse inputs: densify per row-chunk so peak memory is one
            # chunk, not the whole matrix (the reference predicts CSR
            # natively, c_api.cpp PredictForCSR; trees only read the
            # split features of each row anyway).  The chunk row count
            # scales with the width so the dense chunk stays ~256MB
            # whatever the feature count.
            n_rows, n_cols = data.shape
            chunk_rows = max(1, (32 << 20) // max(1, n_cols))  # 32M f64 elems
            if n_rows > chunk_rows:
                csr = data.tocsr()
                chunks = [
                    self.predict(
                        csr[i : i + chunk_rows].toarray(),
                        num_iteration=num_iteration, raw_score=raw_score,
                        pred_leaf=pred_leaf, is_reshape=is_reshape,
                    )
                    for i in range(0, n_rows, chunk_rows)
                ]
                return np.concatenate(chunks, axis=0)
        X = _densify(data)
        if pred_leaf:
            return self._gbdt.predict_leaf_index(X, num_iteration)
        if raw_score:
            return self._gbdt.predict_raw_score(X, num_iteration)
        return self._gbdt.predict(X, num_iteration)

    # ----------------------------------------------------------------- save
    def save_model(self, filename: str, num_iteration: int = -1) -> None:
        if num_iteration <= 0:
            num_iteration = self.best_iteration
        self._gbdt.save_model_to_file(filename, num_iteration)

    def model_to_string(self, num_iteration: int = -1) -> str:
        if num_iteration <= 0:
            num_iteration = self.best_iteration
        return self._gbdt.save_model_to_string(num_iteration)

    def dump_model(self, num_iteration: int = -1) -> Dict[str, Any]:
        """JSON-style dict dump (gbdt.cpp:438-477)."""
        if num_iteration <= 0:
            num_iteration = self.best_iteration
        return self._gbdt.dump_model(num_iteration)

    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        imp = self._gbdt.feature_importance_array(importance_type)
        return imp

    def feature_name(self) -> List[str]:
        return list(self._gbdt.feature_names)

    @property
    def current_iteration(self) -> int:
        return self._gbdt.current_iteration

    def num_trees(self) -> int:
        return self._gbdt.num_trees

    # --------------------------------------------------------------- pickle
    def __getstate__(self):
        """Pickle via model-string round trip (basic.py:1360)."""
        state = {
            "params": self.params,
            "best_iteration": self.best_iteration,
            "model_str": self._gbdt.save_model_to_string(-1),
            "attr": dict(self._attr),
            "train_data_name": self.train_data_name,
        }
        return state

    def __setstate__(self, state):
        self.params = state["params"]
        self.best_iteration = state["best_iteration"]
        self._train_dataset = None
        self.name_valid_sets = []
        self.train_data_name = state.get("train_data_name", "training")
        self._attr = dict(state.get("attr", {}))
        self._init_from_string(state["model_str"])

    def __copy__(self):
        return self.__deepcopy__(None)

    def __deepcopy__(self, memo):
        out = Booster(model_str=self._gbdt.save_model_to_string(-1),
                      params=copy.deepcopy(self.params))
        out.best_iteration = self.best_iteration
        out._attr = dict(self._attr)
        out.train_data_name = self.train_data_name
        return out


class _DatasetView:
    """Minimal Dataset-like wrapper handed to custom fobj/feval for valid
    sets (exposes get_label/get_weight/get_field like the reference)."""

    def __init__(self, inner: BinnedDataset):
        self._inner = inner

    def get_label(self):
        return self._inner.metadata.label

    def get_weight(self):
        return self._inner.metadata.weights

    def get_field(self, name):
        return self._inner.metadata.get_field(name)

    def num_data(self):
        return self._inner.num_data
